#!/usr/bin/env python
"""Leakage detection through IQ-level 3-class readout (round 5).

A |2> level is the transmon's classic silent failure: it reads out
near |1> and a 2-state discriminator cannot see it.  This demo runs
the full chain the framework ships for it:

1. A pi-pulse train leaks the qubit with a known closed-form
   probability (CPTP trajectory unraveling, sim/device.py).
2. Readout windows are synthesized and demodulated with |2> given its
   OWN channel response (`ReadoutPhysics.g2` — the IQ-level element
   contract, reference: python/distproc/asmparse.py:46-63), and a
   nearest-centroid 3-class discriminator (`classify3`) recovers the
   state per shot.
3. REPEATED readout separates |1> from |2>: a leaked core classifies
   2 on every read (the |2> response is persistent), a |1> survivor
   classifies 1 — the standard leakage-detection experiment,
   physics-closed.
4. Seepage (`seep_per_pulse`) returns leaked cores to service and the
   detection rate tracks it.

    JAX_PLATFORMS=cpu python examples/leakage_detection.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.models.coupling import couplings_from_qchip
from distributed_processor_tpu.models.default_qchip import make_default_qchip
from distributed_processor_tpu.sim.device import DeviceModel
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)

PI_PULSE = {'name': 'pulse', 'dest': 'Q0.qdrv', 'freq': 4.2e9,
            'phase': 0.0, 'amp': 0.96, 'twidth': 24e-9,
            'env': {'env_func': 'square', 'paradict': {}}}
KW = dict(max_steps=4000, max_pulses=64, max_meas=4)


def run(prog, shots, key, dev_kw, **model_kw):
    sim = Simulator(n_qubits=2)
    mp = sim.compile(prog)
    model = ReadoutPhysics(
        p1_init=0.0, device=DeviceModel(
            'statevec', couplings=couplings_from_qchip(
                mp, make_default_qchip(2)), **dev_kw), **model_kw)
    out = run_physics_batch(mp, model, key, shots, **KW)
    assert not np.any(np.asarray(out['err']))
    return out


def main():
    shots, p_leak = 2048, 0.25
    prog = [dict(PI_PULSE), dict(PI_PULSE),     # X360: leaks or returns
            {'name': 'read', 'qubit': ['Q0']},
            {'name': 'read', 'qubit': ['Q0']}]  # repeated readout

    # -- 3-class IQ discrimination: |2> has its own response ----------
    out = run(prog, shots, 11, dict(leak_per_pulse=p_leak),
              sigma=0.02, g2=-0.9 - 0.4j, classify3=True)
    leaked = np.asarray(out['leaked'])[:, 0]
    cls = np.asarray(out['meas_class'])[:, 0, :2]
    want = 1.0 - (1.0 - p_leak)                 # one exposed pi pulse
    print(f'leaked fraction      {leaked.mean():.3f} '
          f'(closed form {want:.3f})')
    both2 = (cls == 2).all(axis=1)
    print(f'classified |2> twice {both2.mean():.3f} — detection vs '
          f'truth agree on {np.mean(both2 == leaked):.4f} of shots')

    # -- a 2-class discriminator CANNOT see it ------------------------
    out = run(prog, shots, 11, dict(leak_per_pulse=p_leak),
              sigma=0.02, g2=-0.6 + 0.8j)       # g2 at g1: reads as 1
    bits = np.asarray(out['meas_bits'])[:, 0, :2]
    print(f'2-class reader: leaked shots read {bits[leaked].mean():.3f} '
          f'(indistinguishable from |1>)')

    # -- seepage returns cores to service -----------------------------
    for seep in (0.0, 0.3, 0.6):
        out = run([dict(PI_PULSE)] * 3 + [{'name': 'read', 'qubit': ['Q0']}],
                  shots, 7, dict(leak_per_pulse=1.0, seep_per_pulse=seep),
                  sigma=0.0)
        still = np.asarray(out['leaked'])[:, 0].mean()
        print(f'seep={seep:.1f}: still leaked after 2 recovery chances '
              f'{still:.3f} (closed form {(1 - seep) ** 2:.3f})')


if __name__ == '__main__':
    main()
