#!/usr/bin/env python
"""Register-parameterized 2D amplitude x frequency sweep, one compile.

The reference re-runs or re-compiles per sweep point host-side; here
the swept pulse reads its amplitude and frequency from processor
registers, the full grid is the initial-register batch, and the whole
sweep shards over the device mesh — one compile, one jit.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/param_sweep_grid.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor JAX_PLATFORMS even where site config pre-selects a backend
if os.environ.get('JAX_PLATFORMS'):
    import jax
    jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])

import numpy as np

from distributed_processor_tpu.parallel import (
    swept_pulse_machine_program, grid_init_regs, sweep_cfg, make_mesh,
    sharded_simulate)

N_CORES = 2


def main():
    mp = swept_pulse_machine_program(N_CORES, n_pulses=2)
    amps = [0x2000, 0x4000, 0x8000, 0xffff]
    freqs = [0, 1]
    regs = grid_init_regs(amps, freqs, N_CORES)      # [8 points, cores, 16]
    cfg = sweep_cfg(mp, n_pulses_per_core=3)
    bits = np.zeros((len(regs), N_CORES, cfg.max_meas), int)

    import jax
    mesh = make_mesh(n_dp=min(8, len(jax.devices())))
    out = sharded_simulate(mp, bits, mesh, init_regs=regs, cfg=cfg)

    amp_played = np.asarray(out['rec_amp'])[:, 0, 0]    # core 0, pulse 0
    freq_played = np.asarray(out['rec_freq'])[:, 0, 0]
    print(f'{"point":>6} {"amp reg":>8} {"amp word":>9} {"freq addr":>9}')
    for p in range(len(regs)):
        print(f'{p:6d} {regs[p, 0, 0]:#8x} {amp_played[p]:#9x} '
              f'{freq_played[p]:9d}')
    assert np.array_equal(amp_played, regs[:, 0, 0])
    assert np.array_equal(freq_played, regs[:, 0, 1])
    print('grid played back exactly: one compile, '
          f'{len(regs)} sweep points over {mesh.shape} mesh')


if __name__ == '__main__':
    main()
