#!/usr/bin/env python
"""Physics-closed active reset: reset fidelity vs ADC noise.

Compiles the measurement-conditioned reset idiom (read -> branch on the
demodulated bit -> conditional X flip), executes it with the readout
loop closed by the DSP chain (nothing injected), and reports how the
end-of-sequence ground-state fraction degrades as ADC noise approaches
the discrimination boundary.

Runs anywhere (CPU mesh included):

    JAX_PLATFORMS=cpu python examples/active_reset_fidelity.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor JAX_PLATFORMS even where site config pre-selects a backend
if os.environ.get('JAX_PLATFORMS'):
    import jax
    jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])

import numpy as np

from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.models import active_reset, make_default_qchip
from distributed_processor_tpu.sim.interpreter import InterpreterConfig
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)

SHOTS = int(os.environ.get('SHOTS', 512))
N_QUBITS = 2


def main():
    qchip = make_default_qchip(N_QUBITS)
    qubits = [f'Q{i}' for i in range(N_QUBITS)]
    mp = compile_to_machine(active_reset(qubits) +
                            [{'name': 'read', 'qubit': [q]} for q in qubits],
                            qchip, n_qubits=N_QUBITS)
    cfg = InterpreterConfig(max_steps=4 * mp.n_instr + 64, max_pulses=16,
                            max_meas=2, max_resets=1)

    print(f'{SHOTS} shots x {N_QUBITS} qubits, thermal P(|1>)=0.5')
    print(f'{"sigma":>8} {"reset err":>10} {"readout err (est)":>18}')
    for sigma in (0.5, 20.0, 40.0, 60.0):
        model = ReadoutPhysics(sigma=sigma, p1_init=0.5)
        out = run_physics_batch(mp, model, 0, SHOTS, cfg=cfg)
        assert not bool(np.asarray(out['incomplete']))
        # final read (slot 1) measures the post-reset state
        final = np.asarray(out['meas_bits'])[:, :, 1]
        # the device ends excited iff the *reset* failed (bad bit 0);
        # the final read then adds its own assignment error on top
        state = np.asarray(out['qturns']) % 4 // 2
        print(f'{sigma:8.1f} {state.mean():10.4f} '
              f'{np.abs(final - state).mean():18.4f}')


if __name__ == '__main__':
    main()
