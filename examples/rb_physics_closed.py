#!/usr/bin/env python
"""Randomized benchmarking, physics-closed end to end.

The full product loop in one script: random virtual-Z Clifford
sequences (models/rb.py) compile through the 12-pass pipeline, execute
on the batched interpreter with the SU(2) Bloch device co-state
(sim/device.py — per-pulse depolarization injected), every readout
window is synthesized + demodulated + discriminated in-sim
(sigma-noisy, so assignment errors are part of the measured survival),
and `analysis.fit_rb` recovers the injected error per Clifford from
the sampled bits.

Expected: alpha ~= (1 - p_depol)^2 (two physical pulses per Clifford),
with SPAM (readout infidelity + thermal init) absorbed in A/B as in a
real lab fit.

    JAX_PLATFORMS=cpu python examples/rb_physics_closed.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor JAX_PLATFORMS even where site config pre-selects a backend
if os.environ.get('JAX_PLATFORMS'):
    import jax
    jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])

import numpy as np

from distributed_processor_tpu.analysis import fit_rb
from distributed_processor_tpu.models.rb import (rb_sequence,
                                                 clifford_instructions)
from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.sim.device import DeviceModel
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)

SHOTS = int(os.environ.get('SHOTS', 512))
DEPTHS = (2, 4, 8, 16, 32, 48, 64)
SEQS_PER_DEPTH = int(os.environ.get('SEQS', 2))
P_DEPOL = 0.01
SIGMA = 2.0            # visible readout infidelity -> realistic SPAM


def main():
    sim = Simulator(n_qubits=1)
    model = ReadoutPhysics(
        sigma=SIGMA, p1_init=0.01,
        device=DeviceModel('bloch', depol_per_pulse=P_DEPOL))
    rng = np.random.default_rng(11)
    print(f'{SHOTS} shots/point, {SEQS_PER_DEPTH} sequences/depth, '
          f'p_depol={P_DEPOL}, sigma={SIGMA}')
    survival = []
    for depth in DEPTHS:
        acc = []
        for _ in range(SEQS_PER_DEPTH):
            prog = []
            for ci in rb_sequence(rng, depth):
                prog += clifford_instructions('Q0', ci)
            prog.append({'name': 'read', 'qubit': ['Q0']})
            mp = sim.compile(prog)
            out = run_physics_batch(
                mp, model, int(rng.integers(1 << 30)), SHOTS,
                max_steps=mp.n_instr * 2 + 64, max_pulses=256, max_meas=2)
            assert not bool(out['incomplete'])
            bits = np.asarray(out['meas_bits'])[:, 0, 0]
            acc.append(1.0 - bits.mean())          # P(measured |0>)
        survival.append(float(np.mean(acc)))
        print(f'  depth {depth:>3}: survival {survival[-1]:.4f}')
    alpha, epc, (A, p, B) = fit_rb(np.array(DEPTHS), np.array(survival))
    print(f'\nfit: alpha={alpha:.4f} (expected ~{(1-P_DEPOL)**2:.4f}), '
          f'error/Clifford={epc:.4f}, A={A:.3f}, B={B:.3f}')


if __name__ == '__main__':
    main()
