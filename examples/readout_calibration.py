#!/usr/bin/env python
"""Readout calibration: centroid fitting + assignment fidelity.

Runs prepared-|0> and prepared-|1> batches through the IQ readout
model, fits per-channel centroids, and prints the assignment matrix —
the calibration loop the reference delegates to external tooling.

    JAX_PLATFORMS=cpu python examples/readout_calibration.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor JAX_PLATFORMS even where site config pre-selects a backend
if os.environ.get('JAX_PLATFORMS'):
    import jax
    jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])

import numpy as np
import jax

from distributed_processor_tpu.models.readout import IQReadoutModel
from distributed_processor_tpu.models.calibration import (
    fit_centroids, assignment_matrix, readout_fidelity)

SHOTS = int(os.environ.get('SHOTS', 2048))
N_CH = 4


def main():
    model = IQReadoutModel(
        centers0=np.full(N_CH, 1.0 + 0.0j),
        centers1=np.full(N_CH, -0.6 + 0.8j), sigma=0.55)
    k0, k1 = jax.random.split(jax.random.PRNGKey(0))
    iq0 = model.sample_iq(k0, np.zeros((SHOTS, N_CH), int))
    iq1 = model.sample_iq(k1, np.ones((SHOTS, N_CH), int))

    c0, c1 = fit_centroids(iq0, iq1)
    print('fitted |0> centroids:', np.asarray(c0).round(3)[:2], '...')
    print('fitted |1> centroids:', np.asarray(c1).round(3)[:2], '...')
    mat = np.asarray(assignment_matrix(iq0, iq1, c0, c1))
    fid = np.asarray(readout_fidelity(iq0, iq1, c0, c1))
    for ch in range(N_CH):
        print(f'ch {ch}: P(0|0)={mat[ch, 0, 0]:.3f} '
              f'P(1|1)={mat[ch, 1, 1]:.3f} fidelity={fid[ch]:.3f}')


if __name__ == '__main__':
    main()
