#!/usr/bin/env python
"""Register-swept Rabi, physics-closed: one compile, amplitude as data.

Declares an amp-typed program variable, references it from the drive
pulse, and preloads it per shot with ``make_init_regs`` — the
simulator-side analog of the reference host writing parameter registers
over the FPGA bus. Every amplitude point executes in one batched run
with the measurement loop closed by the DSP chain; the classical device
model turns the sweep into a quantized Rabi staircase
(``state = (round(amp / x90_amp) >> 1) & 1``).

Runs anywhere (CPU mesh included):

    JAX_PLATFORMS=cpu python examples/rabi_register_sweep.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get('JAX_PLATFORMS'):
    import jax
    jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])

import numpy as np

from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.decoder import make_init_regs
from distributed_processor_tpu.models import make_default_qchip
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)

N_POINTS = 32


def main():
    qchip = make_default_qchip(1)
    program = [
        {'name': 'declare', 'var': 'drive_amp', 'dtype': 'amp',
         'scope': ['Q0']},
        {'name': 'pulse', 'freq': 'Q0.freq', 'phase': 0.0,
         'amp': 'drive_amp',
         'env': {'env_func': 'cos_edge_square',
                 'paradict': {'ramp_fraction': 0.25}},
         'twidth': 32e-9, 'dest': 'Q0.qdrv'},
        {'name': 'read', 'qubit': ['Q0']},
    ]
    mp = compile_to_machine(program, qchip, n_qubits=1)
    print(f'compiled once: {mp.n_instr} instructions, '
          f'variable map {mp.reg_maps[0]}')

    amps = np.linspace(0.0, 1.0, N_POINTS)
    regs = make_init_regs(mp, {'drive_amp': amps}, n_shots=N_POINTS)
    model = ReadoutPhysics(sigma=0.01, p1_init=0.0)
    out = run_physics_batch(mp, model, 0, N_POINTS,
                            init_states=np.zeros((N_POINTS, 1), np.int32),
                            init_regs=regs, max_steps=mp.n_instr * 4 + 64,
                            max_pulses=8, max_meas=2)
    assert not bool(out['incomplete'])
    bits = np.asarray(out['meas_bits'])[:, 0, 0]

    print(f'{"amp":>6} {"measured":>9}')
    for a, b in zip(amps, bits):
        bar = '#' * int(b * 20)
        print(f'{a:6.3f} {b:9d}  {bar}')


if __name__ == '__main__':
    main()
