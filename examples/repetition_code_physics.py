#!/usr/bin/env python
"""Physics-closed repetition code: syndrome LUT correction vs ADC noise.

A distance-3 repetition code round on the LUT measurement fabric
(reference: hdl/fproc_lut.sv + meas_lut.sv): every data core measures,
the demodulated bits form the syndrome address, and each core branches
on its own majority-vote correction bit — readout, distribution, and
correction all inside one jitted XLA computation, nothing injected.
Reports the logical error rate (fraction of shots whose corrected
codeword disagrees with the initial majority) as ADC noise rises.

Runs anywhere (CPU mesh included):

    JAX_PLATFORMS=cpu python examples/repetition_code_physics.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get('JAX_PLATFORMS'):
    import jax
    jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])

import numpy as np

from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.models.repetition import (
    repetition_round_program, repetition_physics_kwargs)
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)

N_DATA = 3
SHOTS = 512


def main():
    sim = Simulator(n_qubits=N_DATA)
    mp = sim.compile(repetition_round_program(N_DATA))
    kw = dict(max_steps=mp.n_instr * 6 + 64, record_pulses=False,
              **repetition_physics_kwargs(N_DATA))

    print(f'distance-{N_DATA} repetition round, {SHOTS} shots, '
          f'single-bit-flip initial states')
    print(f'{"sigma":>8} {"readout_err":>12} {"logical_err":>12}')
    rng = np.random.default_rng(0)
    # one flipped data bit per shot: correctable by majority vote
    init = np.zeros((SHOTS, N_DATA), np.int32)
    init[np.arange(SHOTS), rng.integers(0, N_DATA, SHOTS)] = 1
    for sigma in (0.01, 20.0, 40.0, 60.0, 80.0):
        model = ReadoutPhysics(sigma=sigma)
        out = run_physics_batch(mp, model, 7, SHOTS, init_states=init, **kw)
        assert not bool(out['incomplete'])
        bits = np.asarray(out['meas_bits'])[:, :, 0]
        readout_err = float((bits != init).mean())
        final = np.asarray(out['qturns']) % 4 // 2
        logical_err = float((final != 0).any(axis=1).mean())
        print(f'{sigma:8.2f} {readout_err:12.4f} {logical_err:12.4f}')


if __name__ == '__main__':
    main()
