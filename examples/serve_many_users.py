#!/usr/bin/env python
"""Many tenants, one chip: the continuous-batching execution service.

Eight simulated users each compile their own random RB sequence and
submit it from their own thread — the single-tenant QubiC calling
convention, except nobody owns the hardware: the service coalesces
whatever arrives within the batching window into shape-bucketed
multi-program dispatches (one warm jit for the whole fleet) and every
user gets exactly the stats a solo run would have produced
(docs/SERVING.md). One user asks for strict fault mode and a deadline,
to show per-request policy riding a shared batch.

Runs anywhere (CPU mesh included):

    JAX_PLATFORMS=cpu python examples/serve_many_users.py
"""

import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get('JAX_PLATFORMS'):
    import jax
    jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])

import numpy as np

from distributed_processor_tpu import isa
from distributed_processor_tpu.models import (active_reset,
                                              make_default_qchip,
                                              rb_ensemble)
from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.serve import ExecutionService
from distributed_processor_tpu.sim.interpreter import InterpreterConfig

N_USERS = 8
SHOTS = 64


def main():
    qubits = ['Q0', 'Q1']
    qchip = make_default_qchip(2)
    programs = [compile_to_machine(active_reset(qubits) + prog, qchip,
                                   n_qubits=2)
                for prog in rb_ensemble(qubits, 2, N_USERS, seed=42)]
    bucket = max(isa.shape_bucket(mp.n_instr) for mp in programs)
    cfg = InterpreterConfig(max_steps=2 * bucket + 64,
                            max_pulses=bucket + 2, max_meas=2,
                            max_resets=2, record_pulses=False)
    rng = np.random.default_rng(7)
    outputs = [None] * N_USERS

    with ExecutionService(cfg, max_batch_programs=N_USERS,
                          max_wait_ms=20.0) as svc:

        def user(uid):
            bits = rng.integers(0, 2, (SHOTS, programs[uid].n_cores, 2)) \
                .astype(np.int32)
            handle = svc.submit(
                programs[uid], bits,
                # user 0 wants hard guarantees; everyone else defaults
                fault_mode='strict' if uid == 0 else None,
                deadline_ms=10_000.0 if uid == 0 else None)
            outputs[uid] = handle.result(timeout=120)

        threads = [threading.Thread(target=user, args=(u,))
                   for u in range(N_USERS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = svc.stats()

    for uid, out in enumerate(outputs):
        assert out is not None and not bool(np.asarray(out['incomplete']))
        print(f'user {uid}: {SHOTS} shots, steps={int(out["steps"])}, '
              f'measurements/shot/core='
              f'{float(np.asarray(out["n_meas"]).mean()):.2f}')
    print(f'\n{N_USERS} users -> {stats["dispatches"]} device '
          f'dispatch(es), {stats["coalesce_efficiency"]:.1f} programs '
          f'per dispatch, p99 latency {stats["latency_p99_ms"]:.1f} ms')
    assert stats['completed'] == N_USERS


if __name__ == '__main__':
    main()
