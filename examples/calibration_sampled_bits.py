#!/usr/bin/env python
"""Per-core device calibration from sampled bits on a device mesh.

The workflow a hardware calibration performs, end to end in-sim: Ramsey
and T1 sweeps compile once per delay point, execute physics-closed on
the dp-sharded sweep driver (every batch sharded over the mesh, only
psum-reduced statistics reaching the host), with SAMPLED BITS through a
noisy readout channel (finite sigma -> a few percent assignment error)
— and the fitters recover each core's injected detuning and T1.  No
``meas_p1`` expectation shortcut anywhere.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/calibration_sampled_bits.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault('XLA_FLAGS', '--xla_force_host_platform_device_count=8')
if os.environ.get('JAX_PLATFORMS'):
    import jax
    jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])

import numpy as np

from distributed_processor_tpu.analysis import fit_ramsey, fit_t1
from distributed_processor_tpu.models.experiments import (ramsey_program,
                                                          t1_program)
from distributed_processor_tpu.parallel import run_physics_sweep, make_mesh
from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.sim.device import DeviceModel
from distributed_processor_tpu.sim.physics import ReadoutPhysics

KW = dict(max_steps=2000, max_pulses=32, max_meas=2)
SHOTS, BATCH = 8192, 4096
# fold every point's batches into ONE device dispatch (statistics are
# bit-identical to the per-batch loop — parallel/driver.py span=)
SPAN = SHOTS // BATCH


def sweep(sim, progs, model, mesh, key0):
    curves = []
    for i, prog in enumerate(progs):
        mp = sim.compile(prog)
        out = run_physics_sweep(mp, model, SHOTS, BATCH, key=key0 + i,
                                mesh=mesh, span=SPAN, **KW)
        assert out['err_shots'] == 0
        curves.append(out['meas1_rate'])
    return np.stack(curves)


def main():
    mesh = make_mesh(n_dp=8)
    sim = Simulator(n_qubits=2)
    det_true = (0.5e6, 0.8e6)
    t1_true = (12e-6, 25e-6)
    print(f'mesh: {mesh.shape}; {SHOTS} shots/point, sigma=15 readout')

    model = ReadoutPhysics(sigma=15.0, p1_init=0.0, device=DeviceModel(
        'bloch', detuning_hz=det_true, t2_s=40e-6))
    delays = np.linspace(0.1e-6, 6.1e-6, 16)
    progs = [ramsey_program('Q0', float(d)) + ramsey_program('Q1', float(d))
             for d in delays]
    curves = sweep(sim, progs, model, mesh, 100)
    for c in range(2):
        f, t2s, _ = fit_ramsey(delays, curves[:, c])
        print(f'  Q{c}: detuning {f/1e6:.4f} MHz '
              f'(injected {det_true[c]/1e6:.4f})')

    model = ReadoutPhysics(sigma=15.0, p1_init=0.0, device=DeviceModel(
        'bloch', t1_s=t1_true))
    delays = np.linspace(0.5e-6, 45e-6, 12)
    progs = [t1_program('Q0', float(d)) + t1_program('Q1', float(d))
             for d in delays]
    curves = sweep(sim, progs, model, mesh, 300)
    for c in range(2):
        t1, _ = fit_t1(delays, curves[:, c])
        print(f'  Q{c}: T1 {t1*1e6:.2f} us (injected {t1_true[c]*1e6:.2f})')


if __name__ == '__main__':
    main()
