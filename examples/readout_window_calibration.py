#!/usr/bin/env python
"""Readout-window calibration against the resonator ring-up transient.

With `ReadoutPhysics.ring_tau > 0` the state-dependent transmission
builds up as `1 - exp(-(s+1)/ring_tau)` over the window, so early
samples carry less discrimination information than their energy
suggests.  This example runs the physics-closed loop at a sweep of
integration-window lengths and prints the assignment-fidelity curve —
the measurement a lab runs to pick its readout window — in the
per-sample mode (which simulates the transient) next to the analytic
flat-response shortcut (which is optimistic at short windows: the gap
IS the modeling power that justifies the per-sample path).

    JAX_PLATFORMS=cpu python examples/readout_window_calibration.py
"""

import os
import sys
import warnings

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor JAX_PLATFORMS even where site config pre-selects a backend
if os.environ.get('JAX_PLATFORMS'):
    import jax
    jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])

import numpy as np

from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)

SHOTS = int(os.environ.get('SHOTS', 2048))
RING_TAU = 256.0      # DAC samples; resonator linewidth proxy
SIGMA = 4.0
WINDOWS = (64, 128, 256, 512, 1024, 2048)


def fidelity(mp, window, mode):
    model = ReadoutPhysics(sigma=SIGMA, ring_tau=RING_TAU,
                           window_samples=window, resolve_mode=mode)
    init = (np.arange(SHOTS) % 2).astype(np.int32).reshape(SHOTS, 1)
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')   # analytic+ring warns by design
        out = run_physics_batch(mp, model, 11, SHOTS, init_states=init,
                                max_steps=200, max_pulses=16, max_meas=4)
    bits = np.asarray(out['meas_bits'])[:, 0, 0]
    return float(np.mean(bits == init[:, 0]))


def main():
    sim = Simulator(n_qubits=1)
    mp = sim.compile([{'name': 'read', 'qubit': ['Q0']}])
    print(f'ring_tau = {RING_TAU:.0f} samples, sigma = {SIGMA}, '
          f'{SHOTS} shots')
    print(f'{"window":>8} {"F (per-sample)":>15} {"F (flat analytic)":>18}')
    best = None
    for w in WINDOWS:
        f_ps = fidelity(mp, w, 'persample')
        f_an = fidelity(mp, w, 'analytic')
        print(f'{w:>8} {f_ps:>15.4f} {f_an:>18.4f}')
        if best is None or f_ps > best[1]:
            best = (w, f_ps)
    print(f'\nshortest window at peak per-sample fidelity: {best[0]} '
          f'samples (F = {best[1]:.4f})')
    print('the flat-response shortcut overestimates fidelity at short '
          'windows — the transient is what the per-sample path models')


if __name__ == '__main__':
    main()
