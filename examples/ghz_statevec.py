#!/usr/bin/env python
"""GHZ preparation with real entanglement, physics-closed end to end.

The flagship statevec demo: a gate-level GHZ program (H + CNOT chain)
compiles through the 12-pass pipeline, the echoed-CR CNOT calibrations
execute as EXACT entanglers on the per-shot state vector
(sim/device.py 'statevec'), every readout window is synthesized +
demodulated + discriminated in-sim, and the sampled bits carry the
entanglement: noiseless shots agree across the whole chain, bit for
bit, and the X-basis parity witnesses the coherence a classical
mixture cannot produce.  A second pass turns on trajectory noise
(T1, 2q depolarization, ADC sigma) and watches the parity degrade.

    JAX_PLATFORMS=cpu python examples/ghz_statevec.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get('JAX_PLATFORMS'):
    import jax
    jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])

import numpy as np

from distributed_processor_tpu.models import ghz_program
from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.sim.device import DeviceModel
from distributed_processor_tpu.sim.physics import ReadoutPhysics

# full system size: 8 qubits = a [shots, 256] state vector per shot,
# the scale the reference ecosystem calibrates 2q gates at (round 5;
# N=4 runs in a few seconds if you want a quicker demo)
N, SHOTS = 8, 1024


def main():
    qubits = [f'Q{i}' for i in range(N)]
    sim = Simulator(n_qubits=N)
    prog = ghz_program(qubits)

    # noiseless: exact GHZ through the closed loop (couplings derived
    # automatically from the program + gate library by Simulator.run)
    model = ReadoutPhysics(sigma=0.0, p1_init=0.0,
                           device=DeviceModel('statevec'))
    out = sim.run(prog, shots=SHOTS, physics=model, max_meas=4)
    bits = np.asarray(out['meas_bits'])[:, :, 0]
    agree = np.all(bits == bits[:, :1], axis=1).mean()
    print(f'{N}-qubit GHZ, {SHOTS} shots, noiseless:')
    print(f'  all-{N}-bits-agree fraction: {agree:.4f}  '
          f'(mean bit {bits[:, 0].mean():.3f})')
    assert agree == 1.0

    # the coherence witness: measure every qubit in the X basis (Y90
    # before each read).  The GHZ superposition gives a DETERMINISTIC
    # N-fold X parity; a classical |0..0>/|1..1> mixture would give
    # mean parity 0 — Z-agreement alone cannot tell them apart.
    xprog = list(prog[:-N])                 # prep + CNOTs + barrier
    for q in qubits:
        xprog += [{'name': 'virtual_z', 'qubit': [q],
                   'phase': np.pi / 2},
                  {'name': 'X90', 'qubit': [q]},
                  {'name': 'virtual_z', 'qubit': [q],
                   'phase': -np.pi / 2}]
    xprog += [{'name': 'read', 'qubit': [q]} for q in qubits]
    out = sim.run(xprog, shots=SHOTS, physics=model, max_meas=4)
    xbits = np.asarray(out['meas_bits'])[:, :, 0]
    parity = np.prod(1 - 2 * xbits, axis=1)
    print(f'  X-basis {N}-fold parity: {parity.mean():+.4f}  '
          f'(deterministic — a classical mixture would give ~0)')
    assert abs(parity.mean()) == 1.0

    # with noise: T1, 2q depol on the CR pulses, finite readout sigma
    noisy = ReadoutPhysics(sigma=10.0, p1_init=0.02, device=DeviceModel(
        'statevec', t1_s=60e-6, depol2_per_pulse=0.01))
    out = sim.run(prog, shots=SHOTS, physics=noisy, max_meas=4)
    bits = np.asarray(out['meas_bits'])[:, :, 0]
    agree = np.all(bits == bits[:, :1], axis=1).mean()
    print(f'with T1=60us, depol2=1%/CR, sigma=10 readout:')
    print(f'  all-{N}-bits-agree fraction: {agree:.4f}  '
          f'(decoherence + assignment errors, as on hardware)')
    assert 0.5 < agree < 1.0


if __name__ == '__main__':
    main()
