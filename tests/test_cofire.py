"""Equal-time co-fire ordering lint (statevec device).

When cross-core pulses land on the same trigger time, the statevec
engine applies a fixed stage order (1q rotations -> couplings ->
measurements).  For non-commuting operator pairs that is a
simulator-chosen ordering with no hardware analog (the FPGA issues
per-core sequentially — reference: hdl/ctrl.v one instruction at a
time — and genuine RF overlap is not a sequenced product either), so
the engine flags it (``ERR_COFIRE_ORDER``) instead of silently picking
an outcome.  Commuting overlaps stay clean: 1q||1q on distinct cores,
Z legs against Z measurement, zz||zz (both diagonal).
"""

import numpy as np

from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import machine_program_from_cmds
from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.models.coupling import couplings_from_qchip
from distributed_processor_tpu.models.default_qchip import make_default_qchip
from distributed_processor_tpu.sim.device import DeviceModel
from distributed_processor_tpu.sim.interpreter import ERR_COFIRE_ORDER
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)


def _run_pair(c0_t, c1_t, kind='zx', c1_meas=False, c1_phase=40000):
    """Two cores, one coupling (0 -> 1): core 0 fires a coupling pulse
    at ``c0_t``; core 1 fires a 1q drive (or measurement) at ``c1_t``
    (``c1_phase`` defaults to a DIFFERENT equatorial axis than the
    coupling's — same-axis zx overlaps commute and stay clean)."""
    c1_cfg = 2 if c1_meas else 0
    mp = machine_program_from_cmds([
        [isa.pulse_cmd(cmd_time=c0_t, cfg_word=0, env_word=4096,
                       amp_word=20000, phase_word=0),
         isa.done_cmd()],
        [isa.pulse_cmd(cmd_time=c1_t, cfg_word=c1_cfg,
                       env_word=(8 << 12) if c1_meas else 4096,
                       amp_word=30000, phase_word=c1_phase),
         isa.done_cmd()],
    ])
    if c1_meas:
        for t in mp.tables:
            t.envs[2] = np.ones(32, complex)
            t.freqs[2] = {'freq': np.array([0.0]),
                          'iq15': np.zeros((1, 15))}
    model = ReadoutPhysics(sigma=0.0, device=DeviceModel(
        'statevec', couplings=((0, 0, 1, kind),)))
    out = run_physics_batch(mp, model, 0, 4, max_steps=256)
    assert not bool(out['incomplete'])
    return np.asarray(out['err'])


def test_zx_collides_with_target_drive():
    err = _run_pair(100, 100, kind='zx')
    assert np.all(err[:, 0] & ERR_COFIRE_ORDER)


def test_zx_same_axis_target_drive_commutes():
    """A same-axis (phase word equal mod half-turn) 1q drive on the zx
    target commutes with the coupling's X leg: clean."""
    for ph in (0, 1 << 16):          # phi and phi + pi: same generator
        err = _run_pair(100, 100, kind='zx', c1_phase=ph)
        assert not np.any(err & ERR_COFIRE_ORDER), ph


def test_zz_collides_with_target_drive():
    err = _run_pair(100, 100, kind='zz')
    assert np.all(err[:, 0] & ERR_COFIRE_ORDER)


def test_separated_triggers_are_clean():
    """The event gate serializes unequal triggers: no co-fire, no flag."""
    for kind in ('zx', 'zz'):
        assert not np.any(_run_pair(100, 200, kind=kind))
        assert not np.any(_run_pair(200, 100, kind=kind))


def test_zx_collides_with_target_measurement():
    """The zx target leg is X: non-commuting with the Z measurement."""
    err = _run_pair(100, 100, kind='zx', c1_meas=True)
    assert np.all(err[:, 0] & ERR_COFIRE_ORDER)


def test_zz_commutes_with_measurement():
    """zz is diagonal: a same-time Z measurement commutes — clean."""
    err = _run_pair(100, 100, kind='zz', c1_meas=True)
    assert not np.any(err & ERR_COFIRE_ORDER)


def test_shared_target_zx_pair_axis_dependent():
    """Two CR tones converging on one target: same drive axis commutes
    (clean), different axes do not (flagged on the first coupling's
    control core)."""
    def run(ph1):
        mp = machine_program_from_cmds([
            [isa.pulse_cmd(cmd_time=100, cfg_word=0, env_word=4096,
                           amp_word=20000, phase_word=0),
             isa.done_cmd()],
            [isa.pulse_cmd(cmd_time=100, cfg_word=0, env_word=4096,
                           amp_word=20000, phase_word=ph1),
             isa.done_cmd()],
            [isa.done_cmd()],
        ])
        model = ReadoutPhysics(sigma=0.0, device=DeviceModel(
            'statevec', couplings=((0, 0, 2, 'zx'), (1, 0, 2, 'zx'))))
        out = run_physics_batch(mp, model, 0, 4, max_steps=256)
        assert not bool(out['incomplete'])
        return np.asarray(out['err'])

    assert not np.any(run(0) & ERR_COFIRE_ORDER)        # same axis
    err = run(40000)                                    # different axis
    assert np.all(err[:, 0] & ERR_COFIRE_ORDER)


def test_compiled_cz_x90_collision_and_barrier_fix():
    """Compiled path: a user program playing CZ(Q0,Q1) and X90 on Q1 in
    the same schedule layer collides (flagged); the barrier-separated
    variant is clean — the lint tells the user exactly which fix the
    stack's scheduling model expects (the fence every calibrated 2q
    gate and rb2q already carry)."""
    sim = Simulator(n_qubits=2)
    qchip = make_default_qchip(2)
    reads = [{'name': 'read', 'qubit': ['Q0']},
             {'name': 'read', 'qubit': ['Q1']}]

    def run(prog):
        mp = sim.compile(prog)
        model = ReadoutPhysics(sigma=0.0, device=DeviceModel(
            'statevec', couplings=couplings_from_qchip(mp, qchip)))
        out = run_physics_batch(mp, model, 0, 4, max_steps=4000,
                                max_pulses=64, max_meas=4)
        assert not bool(out['incomplete'])
        return np.asarray(out['err'])

    err = run([{'name': 'CZ', 'qubit': ['Q0', 'Q1']},
               {'name': 'X90', 'qubit': ['Q1']}] + reads)
    assert np.all(err[:, 0] & ERR_COFIRE_ORDER), \
        'unfenced CZ || X90 must be flagged'
    err = run([{'name': 'CZ', 'qubit': ['Q0', 'Q1']},
               {'name': 'barrier', 'qubit': ['Q0', 'Q1']},
               {'name': 'X90', 'qubit': ['Q1']}] + reads)
    assert not np.any(err), 'barrier-separated variant must be clean'


def test_brickwork_cz_layers_are_clean():
    """Parallel CZs on disjoint pairs co-fire zz||zz (both diagonal):
    the bench's entangling workload shape must stay clean."""
    sim = Simulator(n_qubits=4)
    qchip = make_default_qchip(4)
    qubits = ['Q0', 'Q1', 'Q2', 'Q3']
    prog = [{'name': 'barrier', 'qubit': qubits},
            {'name': 'CZ', 'qubit': ['Q0', 'Q1']},
            {'name': 'CZ', 'qubit': ['Q2', 'Q3']},
            {'name': 'barrier', 'qubit': qubits}] \
        + [{'name': 'read', 'qubit': [q]} for q in qubits]
    mp = sim.compile(prog)
    model = ReadoutPhysics(sigma=0.0, device=DeviceModel(
        'statevec', couplings=couplings_from_qchip(mp, qchip)))
    out = run_physics_batch(mp, model, 0, 4, max_steps=8000,
                            max_pulses=64, max_meas=4)
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))
