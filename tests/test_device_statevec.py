"""Entangling statevec device co-state (sim/device.py, device='statevec').

Round-3 review's top item: two-qubit physics is real, not per-core
independent.  The statevec model holds one 2^n_cores state vector per
shot, identifies entangling pulses by (core, frequency-word) coupling
entries, and the default qchip's CNOT/CZ calibrations compose EXACTLY
to CNOT/CZ under its interaction semantics — so GHZ correlations, CZ
conditional phases, and two-qubit error channels all survive end-to-end
through the physics-closed compiled path (readout synthesis + demod +
discrimination included).

Matches the two-qubit calibrations the reference ecosystem treats as
first-class (reference: python/test/qubitcfg.json:1152 Q5Q4CNOT) but
executes as real entanglers rather than relying on hardware.
"""

import numpy as np
import pytest

from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.models.coupling import couplings_from_qchip
from distributed_processor_tpu.models.default_qchip import make_default_qchip
from distributed_processor_tpu.models.experiments import ghz_program, \
    ramsey_program
from distributed_processor_tpu.sim.device import DeviceModel
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)

KW = dict(max_steps=4000, max_pulses=128, max_meas=4)


@pytest.fixture(scope='module')
def sim2():
    return Simulator(n_qubits=2)


@pytest.fixture(scope='module')
def qchip2():
    return make_default_qchip(2)


def _run(sim, qchip, prog, shots=1, key=0, init=None, dev_kw=None,
         model_kw=None, **kw):
    mp = sim.compile(prog)
    cps = couplings_from_qchip(mp, qchip)
    model = ReadoutPhysics(
        sigma=0.0, device=DeviceModel('statevec', couplings=cps,
                                      **(dev_kw or {})), **(model_kw or {}))
    if init is None:
        init = np.zeros((shots, mp.n_cores), np.int32)
    out = run_physics_batch(mp, model, key, shots, init_states=init,
                            **{**KW, **kw})
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))
    return out


def _h(q):
    """The H-like prep block (vz pi/2, X90, vz pi/2): operationally an
    involution (the second application's folded frame inverts it)."""
    return [{'name': 'virtual_z', 'qubit': [q], 'phase': np.pi / 2},
            {'name': 'X90', 'qubit': [q]},
            {'name': 'virtual_z', 'qubit': [q], 'phase': np.pi / 2}]


def _reads(qubits):
    return [{'name': 'read', 'qubit': [q]} for q in qubits]


def test_cnot_truth_table(sim2, qchip2):
    """The compiled echoed-CR CNOT calibration acts as exact CNOT on
    basis states through the full closed loop."""
    prog = [{'name': 'CNOT', 'qubit': ['Q0', 'Q1']},
            {'name': 'barrier', 'qubit': ['Q0', 'Q1']}] + _reads(['Q0', 'Q1'])
    for b0, b1 in ((0, 0), (0, 1), (1, 0), (1, 1)):
        out = _run(sim2, qchip2, prog, init=np.array([[b0, b1]], np.int32))
        bits = np.asarray(out['meas_bits'])[0, :, 0]
        assert (bits[0], bits[1]) == (b0, b1 ^ b0)


def test_bell_parity_and_coherence(sim2, qchip2):
    """H + CNOT prepares a Bell state: ZZ parity of the sampled bits is
    exactly +1 on every shot, marginals are ~1/2, and measuring in the
    X basis (Y90 rotations pre-read) gives deterministic parity -1 —
    the coherence witness a classical mixture cannot produce."""
    base = _h('Q0') + [
        {'name': 'barrier', 'qubit': ['Q0', 'Q1']},
        {'name': 'CNOT', 'qubit': ['Q0', 'Q1']},
        {'name': 'barrier', 'qubit': ['Q0', 'Q1']}]
    y90s = []
    for q in ('Q0', 'Q1'):
        y90s += [{'name': 'virtual_z', 'qubit': [q], 'phase': np.pi / 2},
                 {'name': 'X90', 'qubit': [q]},
                 {'name': 'virtual_z', 'qubit': [q], 'phase': -np.pi / 2}]
    for basis, want in (('zz', 1), ('xx', -1)):
        prog = base + (y90s if basis == 'xx' else []) + _reads(['Q0', 'Q1'])
        out = _run(sim2, qchip2, prog, shots=256, key=3)
        bits = np.asarray(out['meas_bits'])[:, :, 0]
        parity = (1 - 2 * bits[:, 0]) * (1 - 2 * bits[:, 1])
        assert np.all(parity == want), f'{basis} parity not deterministic'
        assert 0.35 < bits[:, 0].mean() < 0.65


def test_ghz_chain_parity():
    """Round-3 'done' criterion: a noiseless physics-closed GHZ run
    shows ZZ-parity correlation 1 across cores — every shot's bits
    agree across the whole 4-qubit chain, with ~50/50 marginals."""
    sim = Simulator(n_qubits=4)
    qchip = make_default_qchip(4)
    out = _run(sim, qchip, ghz_program(['Q0', 'Q1', 'Q2', 'Q3']),
               shots=512, key=2, max_pulses=256, max_steps=8000)
    bits = np.asarray(out['meas_bits'])[:, :, 0]
    assert np.all(bits == bits[:, :1]), 'GHZ bits must agree across cores'
    assert 0.4 < bits[:, 0].mean() < 0.6
    # adjacent-pair ZZ parity correlation, the criterion as stated
    for a in range(3):
        zz = (1 - 2 * bits[:, a]) * (1 - 2 * bits[:, a + 1])
        assert zz.mean() == 1.0


def test_cz_conditional_phase(sim2, qchip2):
    """CZ sandwiched in target-frame H blocks acts as CNOT (H Z H = X):
    the conditional phase is real, not a classical no-op."""
    for b0 in (0, 1):
        prog = ([{'name': 'X90', 'qubit': ['Q0']},
                 {'name': 'X90', 'qubit': ['Q0']}] if b0 else []) \
            + _h('Q1') + [
                {'name': 'barrier', 'qubit': ['Q0', 'Q1']},
                {'name': 'CZ', 'qubit': ['Q0', 'Q1']},
                {'name': 'barrier', 'qubit': ['Q0', 'Q1']}] \
            + _h('Q1') + _reads(['Q0', 'Q1'])
        out = _run(sim2, qchip2, prog, shots=32, key=1)
        bits = np.asarray(out['meas_bits'])[:, :, 0]
        assert np.all(bits[:, 0] == b0)
        assert np.all(bits[:, 1] == b0), \
            f'CZ conditional phase missing for control={b0}'


def test_matches_bloch_for_product_states(sim2, qchip2):
    """On a 1q unitary program (Ramsey with detuning) the statevec
    meas_p1 equals the bloch model's exactly — statevec strictly
    extends the single-qubit physics."""
    from distributed_processor_tpu.sim.physics import ReadoutPhysics as RP
    prog = ramsey_program('Q0', 2.5e-6) + []
    mp = sim2.compile(prog)
    out_sv = _run(sim2, qchip2, prog, dev_kw=dict(detuning_hz=0.37e6))
    model_b = RP(sigma=0.0, device=DeviceModel('bloch', detuning_hz=0.37e6))
    out_b = run_physics_batch(
        mp, model_b, 0, 1,
        init_states=np.zeros((1, mp.n_cores), np.int32), **KW)
    np.testing.assert_allclose(np.asarray(out_sv['meas_p1'])[0, 0, 0],
                               np.asarray(out_b['meas_p1'])[0, 0, 0],
                               atol=2e-5)


def test_t1_trajectory_ensemble(sim2, qchip2):
    """Quantum-jump T1 unraveling: the shot ensemble reproduces the
    exponential the bloch model applies deterministically."""
    from distributed_processor_tpu.models.experiments import t1_program
    t1, delay, shots = 20e-6, 15e-6, 3000
    out = _run(sim2, qchip2, t1_program('Q0', delay), shots=shots, key=7,
               dev_kw=dict(t1_s=t1))
    p1 = np.asarray(out['meas_bits'])[:, 0, 0].mean()
    want = np.exp(-delay / t1)
    se = np.sqrt(want * (1 - want) / shots)
    assert abs(p1 - want) < 4 * se, (p1, want)


def test_depol2_targets_only_couplings(sim2, qchip2):
    """1q-only sequences are untouched by depol2 (and vice versa the 2q
    channel fires on coupling pulses): X90 x 4 returns to |0> exactly
    even with a large depol2 injected."""
    prog = [{'name': 'X90', 'qubit': ['Q0']} for _ in range(4)] \
        + _reads(['Q0'])
    out = _run(sim2, qchip2, prog, shots=64, key=5,
               dev_kw=dict(depol2_per_pulse=0.5))
    assert not np.any(np.asarray(out['meas_bits'])[:, 0, 0])


def test_determinism(sim2, qchip2):
    """Same key -> identical sampled bits (trajectory draws are
    counter-based per (shot, core, step))."""
    prog = _h('Q0') + [
        {'name': 'barrier', 'qubit': ['Q0', 'Q1']},
        {'name': 'CNOT', 'qubit': ['Q0', 'Q1']},
        {'name': 'barrier', 'qubit': ['Q0', 'Q1']}] + _reads(['Q0', 'Q1'])
    kw = dict(shots=64, key=11, dev_kw=dict(depol_per_pulse=0.05,
                                            depol2_per_pulse=0.05))
    a = _run(sim2, qchip2, prog, **kw)
    b = _run(sim2, qchip2, prog, **kw)
    np.testing.assert_array_equal(np.asarray(a['meas_bits']),
                                  np.asarray(b['meas_bits']))


def test_statevec_needs_physics_path(sim2):
    """The injected-bits simulate path has no state vector to evolve —
    it must refuse, like bloch."""
    from distributed_processor_tpu.sim.interpreter import (simulate,
                                                           InterpreterConfig)
    mp = sim2.compile([{'name': 'X90', 'qubit': ['Q0']}] + _reads(['Q0']))
    with pytest.raises(ValueError, match='statevec'):
        simulate(mp, cfg=InterpreterConfig(physics=True, device='statevec',
                                           x90_amp=31457))


def test_statevec_core_cap():
    """n_cores > 12 would allocate 2^C amplitudes per shot: refuse."""
    from distributed_processor_tpu import isa
    from distributed_processor_tpu.decoder import machine_program_from_cmds
    from distributed_processor_tpu.sim.device import STATEVEC_MAX_CORES
    wide = machine_program_from_cmds(
        [[isa.pulse_cmd(cmd_time=10), isa.done_cmd()]]
        * (STATEVEC_MAX_CORES + 1))
    model = ReadoutPhysics(device=DeviceModel('statevec'))
    with pytest.raises(ValueError, match='exceeds the cap'):
        run_physics_batch(wide, model, 0, 1)


def test_event_gate_sync_no_deadlock():
    """Regression: the discrete-event gate must not deadlock against a
    SYNC-stalled core.  Core 0 fires a pulse scheduled past core 1's
    frozen clock, then both sync; with a naive frontier (the stalled
    core's local time) core 0 waits on core 1 and core 1 waits at the
    barrier — forever.  The sync-stalled core's frontier must instead
    be the release lower bound (max over participants' frontiers)."""
    from distributed_processor_tpu import isa
    from distributed_processor_tpu.decoder import machine_program_from_cmds
    mp = machine_program_from_cmds([
        [isa.pulse_cmd(cmd_time=500, cfg_word=0), isa.sync(0),
         isa.done_cmd()],
        [isa.sync(0), isa.pulse_cmd(cmd_time=20, cfg_word=0),
         isa.done_cmd()],
    ])
    model = ReadoutPhysics(sigma=0.0, device=DeviceModel(
        'statevec', couplings=((0, 0, 1, 'zx'),)))
    out = run_physics_batch(mp, model, 0, 4, max_steps=256)
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))


def test_event_gate_fproc_no_deadlock():
    """Regression: a reader stalled on its neighbour's *unfired*
    measurement must not freeze the gate either — the producer's
    readout pulse (scheduled past the reader's frozen clock) has to be
    allowed to fire.  The reader inherits the producer's frontier."""
    from distributed_processor_tpu import isa
    from distributed_processor_tpu.decoder import machine_program_from_cmds
    mp = machine_program_from_cmds([
        # core 0: read core 1's measurement (fresh), then flip, done
        [isa.alu_cmd('jump_fproc', 'i', 1, 'eq', jump_cmd_ptr=2,
                     func_id=1),
         isa.jump_i(3),
         isa.pulse_cmd(cmd_time=900, cfg_word=0, env_word=(2 << 12)),
         isa.done_cmd()],
        # core 1: measurement pulse late enough to be past core 0's clock
        [isa.pulse_cmd(cmd_time=400, cfg_word=2, env_word=(2 << 12)),
         isa.done_cmd()],
    ])
    model = ReadoutPhysics(sigma=0.0, device=DeviceModel(
        'statevec', couplings=((0, 0, 1, 'zx'),)))
    out = run_physics_batch(mp, model, 0, 4, fabric='fresh', max_steps=256)
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))


def test_event_gate_sticky_serves_final_snapshot():
    """Regression (review round 4): under the gate, a sticky read whose
    producer sits at a far-future pending trigger must be SERVED (the
    latched snapshot is final — any future measurement lands at
    frontier + latency, past the request), not deadlocked, and other
    cores' time-later pulses must NOT be admitted ahead of the reader's
    earlier ones.  Core 1 reads core 0's bit at ~117 and branches into
    a pulse at 130 while core 0 still holds a pending trigger at 1000;
    correct outcome: the read serves bit 1, the branch pulse fires, and
    the run completes with no error."""
    from distributed_processor_tpu import isa
    from distributed_processor_tpu.decoder import machine_program_from_cmds
    mp = machine_program_from_cmds([
        # producer: measurement at 10 (avail 74+), then a far pulse
        [isa.pulse_cmd(cmd_time=10, cfg_word=2, env_word=(8 << 12),
                       amp_word=30000),
         isa.pulse_cmd(cmd_time=1000, cfg_word=0, env_word=4096),
         isa.done_cmd()],
        # reader: idle to 114, sticky-read producer, guarded pulse
        [isa.idle(114),
         isa.alu_cmd('jump_fproc', 'i', 1, 'eq', jump_cmd_ptr=3,
                     func_id=0),
         isa.jump_i(4),
         isa.pulse_cmd(cmd_time=130, cfg_word=0, env_word=4096),
         isa.done_cmd()],
    ])
    # hand-built programs carry empty envelope tables: give the
    # measurement element a real window so the resolver has energy
    for t in mp.tables:
        t.envs[2] = np.ones(32, complex)
        t.freqs[2] = {'freq': np.array([0.0]), 'iq15': np.zeros((1, 15))}
    model = ReadoutPhysics(sigma=0.0, p1_init=1.0, device=DeviceModel(
        'statevec', couplings=((0, 0, 1, 'zx'),)))
    out = run_physics_batch(mp, model, 0, 4, fabric='sticky',
                            init_states=np.ones((4, 2), np.int32),
                            max_steps=512)
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))
    # the guarded pulse fired: the sticky read served bit 1
    assert np.all(np.asarray(out['n_pulses'])[:, 1] == 1)


def test_event_gate_chain_no_deadlock():
    """Regression (review round 4): frontier bounds must propagate
    through multi-link stall chains.  Core 0 fproc-reads core 1's
    unfired measurement; core 1 waits at a sync barrier with core 2;
    core 2 holds the only pending pulse trigger — with one-level
    inheritance core 2's pulse stalls on core 0's frozen clock forever.
    The fixpoint raises core 0's bound through core 1's sync bound to
    core 2's trigger, so the pulse fires and everything completes."""
    from distributed_processor_tpu import isa
    from distributed_processor_tpu.decoder import machine_program_from_cmds
    mp = machine_program_from_cmds([
        [isa.alu_cmd('jump_fproc', 'i', 1, 'eq', jump_cmd_ptr=2,
                     func_id=1),
         isa.jump_i(2),
         isa.done_cmd()],
        [isa.sync(0), isa.pulse_cmd(cmd_time=5, cfg_word=2, env_word=0),
         isa.done_cmd()],
        [isa.pulse_cmd(cmd_time=100, cfg_word=0, env_word=4096),
         isa.sync(0), isa.done_cmd()],
    ])
    model = ReadoutPhysics(sigma=0.0, device=DeviceModel(
        'statevec', couplings=((0, 0, 1, 'zx'),)))
    out = run_physics_batch(mp, model, 0, 4, fabric='fresh',
                            max_steps=512)
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))


def test_coupling_validation():
    with pytest.raises(ValueError, match='coupling'):
        DeviceModel('statevec', couplings=((0, 0, 0, 'zx'),))
    with pytest.raises(ValueError, match='zx'):
        DeviceModel('statevec', couplings=((0, 0, 1, 'bad'),))
