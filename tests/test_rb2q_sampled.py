"""Two-qubit RB through the NOISY readout channel at realistic scale
(round-4 review weak #5: every 2q RB test ran sigma=0).

The exact-closed-form 2q recoveries (tests/test_rb2q.py) re-run here
the way a hardware calibration would: finite sigma (a few percent
assignment error), thousands of sampled shots per point, every point
executed by the dp-sharded sweep driver over the 8-device CPU mesh —
the calibration workflow the reference ecosystem runs on hardware
(reference: python/distproc/hwconfig.py:69-98).

Symmetric per-qubit assignment error leaves the depolarizing-RB
asymptote at exactly 1/4 (the fully-mixed state reads uniformly
through any symmetric channel: A' = [(1-e)+e]^2/4 = 1/4) and only
rescales the decay amplitude — so the count-exact two-depth estimators
of test_rb2q.py stay unbiased and only their CI widens.
"""

import numpy as np
import pytest

from distributed_processor_tpu.models.coupling import couplings_from_qchip
from distributed_processor_tpu.models.default_qchip import make_default_qchip
from distributed_processor_tpu.models.rb2q import (depol2_survival,
                                                   rb2q_interleaved_program,
                                                   rb2q_program)
from distributed_processor_tpu.parallel import make_mesh, run_physics_sweep
from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.sim.device import DeviceModel
from distributed_processor_tpu.sim.physics import ReadoutPhysics

KW = dict(max_steps=8000, max_pulses=192, max_meas=4)
SHOTS, BATCH = 4096, 4096           # dp=8 -> 512 per shard per batch
SIGMA = 15.0                        # a few % assignment error


@pytest.fixture(scope='module')
def setup():
    return Simulator(n_qubits=2), make_default_qchip(2), make_mesh(n_dp=8)


def _survival(setup, prog, key, p2):
    """Joint P(00) through the sharded driver with the noisy channel."""
    sim, qchip, mesh = setup
    mp = sim.compile(prog)
    model = ReadoutPhysics(
        sigma=SIGMA, p1_init=0.0,
        device=DeviceModel('statevec',
                           couplings=couplings_from_qchip(mp, qchip),
                           depol2_per_pulse=p2))
    out = run_physics_sweep(mp, model, SHOTS, BATCH, key=key, mesh=mesh,
                            **KW)
    assert out['err_shots'] == 0 and out['incomplete_batches'] == 0
    return out['survival00_rate']


def test_assignment_error_is_really_there(setup):
    """The channel is genuinely lossy at this sigma: |00> readout
    misassigns a few percent of shots."""
    prog = [{'name': 'read', 'qubit': ['Q0']},
            {'name': 'read', 'qubit': ['Q1']}]
    s00 = _survival(setup, prog, 3, p2=0.0)
    assert 0.70 < s00 < 0.99, s00
    assert s00 < 0.995                     # not a noise-free channel


def test_depol2_recovered_through_noisy_channel(setup):
    """Injected 2q depolarization recovered from sampled survival
    through the noisy discriminator on the mesh: the two-depth alpha
    estimate inverts to the injected p2 (asymptote stays exactly 1/4
    under the symmetric channel; amplitude rescaling cancels in the
    ratio)."""
    p2 = 0.04
    points = []
    for depth, seed in ((2, 1), (6, 2)):
        prog, info = rb2q_program('Q0', 'Q1', depth, seed=seed)
        surv = _survival(setup, prog, seed, p2)
        points.append((info['n_cz'], surv))
        # the raw curve also tracks the closed form up to the readout
        # contrast factor: bound it loosely
        pred = depol2_survival(p2, info['n_cz'])
        assert abs(surv - pred) < 0.10, (depth, surv, pred)
    (n1, s1), (n2, s2) = points
    assert n2 > n1
    alpha = ((s2 - 0.25) / (s1 - 0.25)) ** (1.0 / (n2 - n1))
    p2_hat = 15.0 * (1.0 - alpha) / 16.0
    np.testing.assert_allclose(p2_hat, p2, rtol=0.35)


def test_interleaved_cz_error_through_noisy_channel(setup):
    """Interleaved-CZ isolation at realistic scale: reference and
    interleaved survivals sampled through the noisy channel, the
    count-exact alpha ratio inverts to the per-CZ depolarization within
    CI of the injection."""
    p2 = 0.04
    ref, intl = {}, {}
    for depth, seed in ((2, 21), (6, 22)):
        prog_r, info_r = rb2q_program('Q0', 'Q1', depth, seed=seed)
        ref[depth] = (info_r['n_cz'],
                      _survival(setup, prog_r, seed, p2))
        prog_i, info_i = rb2q_interleaved_program('Q0', 'Q1', depth,
                                                  seed=seed)
        intl[depth] = (info_i['n_cz'],
                       _survival(setup, prog_i, seed + 50, p2))
    d1, d2 = 2, 6
    steps = d2 - d1
    a_ref = ((ref[d2][1] - 0.25) / (ref[d1][1] - 0.25)) ** (1 / steps)
    a_int = ((intl[d2][1] - 0.25) / (intl[d1][1] - 0.25)) ** (1 / steps)
    extra = (intl[d2][0] - intl[d1][0]) - (ref[d2][0] - ref[d1][0])
    assert extra >= 1, (ref, intl)
    alpha_cz = (a_int / a_ref) ** (steps / extra)
    p2_hat = 15.0 * (1.0 - alpha_cz) / 16.0
    np.testing.assert_allclose(p2_hat, p2, rtol=0.5)
