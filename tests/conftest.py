"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh (the driver separately
dry-runs the multi-chip path); set platform flags before jax ever imports.

``DPROC_TPU_TESTS=1`` keeps the real accelerator platform instead, for
the ``tpu``-marked kernel-parity tests on the bench host:

    DPROC_TPU_TESTS=1 pytest tests/ -m tpu
"""

import os
import sys

_USE_REAL_PLATFORM = os.environ.get('DPROC_TPU_TESTS') == '1'

if not _USE_REAL_PLATFORM:
    os.environ['JAX_PLATFORMS'] = 'cpu'
    flags = os.environ.get('XLA_FLAGS', '')
    if '--xla_force_host_platform_device_count' not in flags:
        os.environ['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count=8').strip()

# the environment's sitecustomize imports jax at interpreter start (with
# JAX_PLATFORMS=axon already in the env), so the env var alone is locked
# in; override through the config API before any backend initialises.
import jax
if not _USE_REAL_PLATFORM:
    jax.config.update('jax_platforms', 'cpu')

# share bench.py's persistent compilation cache (.jax_cache/, gitignored):
# the tier-1 suite is compile-dominated on small CPU hosts, and the
# module-boundary jax.clear_caches() below turns every re-run into a full
# recompile without it.  Warm-cache reruns cut suite wall-clock severalfold;
# DPROC_TEST_NO_CACHE=1 restores cold compiles (e.g. to time the compiler).
if not os.environ.get('DPROC_TEST_NO_CACHE'):
    _cache_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        '.jax_cache')
    os.makedirs(_cache_dir, exist_ok=True)
    jax.config.update('jax_compilation_cache_dir', _cache_dir)
    jax.config.update('jax_persistent_cache_min_compile_time_secs', 0.5)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pytest

REFERENCE_ROOT = os.environ.get('DPROC_REFERENCE_ROOT', '/root/reference')


@pytest.fixture(autouse=True)
def _serve_thread_leak_probe():
    """Print the junit-gated marker when a test leaks any execution-
    service thread — dispatcher, supervisor, canary probe, or a
    compile-front-door worker (``dproc-serve-compile-*``, the
    ``submit_source`` pool), i.e. the whole ``dproc-serve`` prefix
    family (tools/check_junit.py fails CI on it).

    A leaked dispatcher outlives its test, keeps a jit cache reference
    alive, and can dispatch into a torn-down fixture; a leaked
    supervisor keeps respawning them; a leaked compile worker can
    finish a compile after teardown and submit into a dead service —
    the serving analog of the fault-leak gate: tests must shut their
    services down (ExecutionService is a context manager, and
    ``shutdown`` joins the compile pool in both drain modes)."""
    import threading
    # every service-owned thread family; new pools must register here
    _SERVE_PREFIXES = ('dproc-serve', 'dproc-serve-compile')
    yield
    leaked = sorted(t.name for t in threading.enumerate()
                    if t.name.startswith(_SERVE_PREFIXES)
                    and t.is_alive())
    if leaked:
        print(f'SERVICE THREAD LEAK: {leaked}')


@pytest.fixture(autouse=True)
def _profiling_counter_isolation():
    """Snapshot/restore the process-wide metrics registry around every
    test: counters, gauges and histograms a test bumps (serve.* /
    compilecache.* / interpreter trace counters all live there now —
    utils/profiling.py fronts obs/metrics.py) never leak into another
    test's assertions, and tests may assert exact counter deltas
    without caring what ran before them."""
    from distributed_processor_tpu.utils import profiling
    snap = profiling.registry_snapshot()
    yield
    profiling.registry_restore(snap)


@pytest.fixture(autouse=True, scope='module')
def _clear_jax_caches_between_modules():
    """Free compiled executables between test FILES.

    The full suite compiles hundreds of XLA modules into one process;
    past ~4-500 of them XLA's CPU compile has been observed segfaulting
    non-deterministically on whichever large module comes late (the
    same modules compile cleanly in a fresh process).  Dropping the
    executable caches at file boundaries keeps the per-process compiler
    footprint bounded; within-file sharing (where almost all reuse
    lives) is untouched.  The interpreter's AOT warmup cache
    (sim/interpreter.py ``_AOT_CACHE``) holds Compiled executables
    outside jax's own tables, so it drops here too."""
    yield
    jax.clear_caches()
    from distributed_processor_tpu.sim.interpreter import clear_aot_cache
    clear_aot_cache()


def pytest_collection_modifyitems(config, items):
    """Everything touching the reference checkout is an *optional* oracle
    comparison (marked ``reference_oracle``, auto-skipped when absent);
    the committed tests/goldens/ files pin the compiler in bare
    checkouts (tests/test_goldens_self.py)."""
    for item in items:
        if 'reference_root' in getattr(item, 'fixturenames', ()):
            item.add_marker(pytest.mark.reference_oracle)


@pytest.fixture(scope='session')
def reference_root():
    if not os.path.isdir(REFERENCE_ROOT):
        pytest.skip('reference checkout not available')
    return REFERENCE_ROOT


@pytest.fixture(scope='session')
def qchipcfg_path(reference_root):
    return os.path.join(reference_root, 'python/test/qubitcfg.json')


@pytest.fixture(scope='session')
def channelcfg_path(reference_root):
    return os.path.join(reference_root, 'python/test/channel_config.json')


def assert_close_tree(actual, expected, path='$'):
    """Recursively compare nested dict/list/tuple structures; numeric leaves
    compare with np.isclose (golden files print full float repr)."""
    if isinstance(expected, dict):
        assert isinstance(actual, dict), f'{path}: {type(actual)} != dict'
        assert set(actual.keys()) == set(expected.keys()), \
            f'{path}: keys {sorted(map(str, actual.keys()))} != {sorted(map(str, expected.keys()))}'
        for k in expected:
            assert_close_tree(actual[k], expected[k], f'{path}.{k}')
    elif isinstance(expected, (list, tuple)):
        assert isinstance(actual, (list, tuple)), f'{path}: {type(actual)} != list'
        assert len(actual) == len(expected), \
            f'{path}: length {len(actual)} != {len(expected)}\n{actual}\n{expected}'
        for i, (a, e) in enumerate(zip(actual, expected)):
            assert_close_tree(a, e, f'{path}[{i}]')
    elif isinstance(expected, bool) or expected is None:
        assert actual == expected, f'{path}: {actual} != {expected}'
    elif isinstance(expected, (int, float, np.integer, np.floating)):
        assert np.isclose(actual, expected, rtol=1e-12, atol=0), \
            f'{path}: {actual} != {expected}'
    else:
        assert actual == expected, f'{path}: {actual!r} != {expected!r}'
