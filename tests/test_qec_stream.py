"""Streaming multi-round QEC (docs/PERF.md "Streaming QEC",
docs/SERVING.md "Streaming sessions").

The contract, pinned here:

* **Rounds-scan bit-identity**: R rounds in ONE
  ``simulate_rounds`` dispatch equal R sequential ``simulate_batch``
  dispatches per stat, on every engine rung the scan composes with
  (generic / straightline / block / pallas-interpret) — the
  amortization the ``qec_streaming`` bench row measures is free of
  semantic drift by construction.
* **Decoder correctness**: the pure-``jnp`` in-loop decoders
  (``'majority'`` LUT-walk, ``'matching'`` union-find-lite chain
  matching) are fuzzed against brute-force NumPy oracles that share
  no structure with them — exhaustive min-weight search and the
  literal ``majority_lut`` table — on >= 200 seeded cases with zero
  disagreements, and are engine-invariant through the scan.
* **Streaming sessions**: chunks ride the ordinary request lifecycle
  (deadlines honored at scan-chunk boundaries, retry under the
  attempt-token machinery, TTL expiry), results arrive in submission
  order as incremental frames, and a chaos kill of the dispatch path
  retries the chunk with no lost or duplicated round results.

This module is listed in tools/check_junit.py NO_SKIP_MODULES: it
runs on pure CPU with injected measurement planes and has no
legitimate skip condition.
"""

import time
from dataclasses import replace

import numpy as np
import pytest

import jax

from distributed_processor_tpu.models.qec import (
    chain_lut, qec_config, qec_multiround_machine_program,
    qec_round_machine_program, repetition_decode_spec,
    surface_cycle_config, surface_cycle_machine_program,
    surface_decode_spec)
from distributed_processor_tpu.ops.decode import (
    DecodeSpec, as_decode_spec, bit_majority_correction, chain_matching,
    chain_matching_np, decode_history, majority_correction_np,
    majority_vote)
from distributed_processor_tpu.serve import (ChaosMonkey, ChaosPlan,
                                             DeadlineError,
                                             ExecutionService,
                                             RetryPolicy, StreamKey)
from distributed_processor_tpu.serve.service import _normalize_stream_cfg
from distributed_processor_tpu.sim.interpreter import (InterpreterConfig,
                                                       simulate_batch,
                                                       simulate_rounds)

pytestmark = pytest.mark.qec


def _rep(n_data=3, **cfg_kw):
    """Repetition-code streaming workload: the single-round unit
    program the scan repeats, its LUT-fabric config, and the
    majority decode spec."""
    mp = qec_round_machine_program(n_data)
    cfg = qec_config(n_data, record_pulses=False, **cfg_kw)
    return mp, cfg, repetition_decode_spec(n_data)


def _planes(rng, rounds, shots, mp, cfg):
    return rng.integers(0, 2, (rounds, shots, mp.n_cores, cfg.max_meas),
                        dtype=np.int32)


def _assert_same(got, want, label='', ignore=()):
    """Bit-identity per stat; ``ignore`` drops engine bookkeeping
    ('steps' is the dispatch loop's own counter and legitimately
    differs across engine rungs — same carve-out as test_ici_fabric)."""
    assert set(got) - set(ignore) == set(want) - set(ignore), \
        f'{label}: keys {set(got) ^ set(want)} diverged'
    for k in sorted(set(want) - set(ignore)):
        np.testing.assert_array_equal(
            np.asarray(got[k]), np.asarray(want[k]),
            err_msg=f'{label}: stat {k!r} diverged')


def _stack_rounds(per_round):
    """R per-round simulate_batch pytrees -> one pytree with a leading
    round axis per leaf (the shape simulate_rounds returns)."""
    return {k: np.stack([np.asarray(r[k]) for r in per_round])
            for k in per_round[0]}


# ---------------------------------------------------------------------------
# decoder fuzz vs the brute-force oracles (>= 200 seeded cases total)
# ---------------------------------------------------------------------------

def test_majority_decoder_fuzz_vs_lut_oracle():
    """120 seeded histories, K in 1..5, R in 1..6: the jnp majority
    decoder must agree with the literal ``majority_lut`` table walk on
    every case, and the round-majority with the strict-majority
    convention (ties -> 0)."""
    rng = np.random.default_rng(0xC0DE)
    cases = 0
    for _ in range(120):
        k = int(rng.integers(1, 6))
        r = int(rng.integers(1, 7))
        hist = rng.integers(0, 2, (r, k), dtype=np.int32)
        voted = np.asarray(majority_vote(hist))
        want_vote = (2 * hist.sum(axis=0) > r).astype(np.int32)
        np.testing.assert_array_equal(voted, want_vote)
        got = np.asarray(decode_history(hist, 'majority'))
        np.testing.assert_array_equal(
            got, np.asarray(bit_majority_correction(voted)))
        want = majority_correction_np(want_vote)
        np.testing.assert_array_equal(
            got, want, err_msg=f'case {cases}: hist={hist.tolist()}')
        cases += 1
    assert cases == 120


def test_matching_decoder_fuzz_vs_bruteforce_oracle():
    """120 seeded syndrome histories, A (ancillas) in 1..5, R in 1..6:
    the closed-form chain matching must reproduce the exhaustive
    min-weight search — syndrome-consistency, weight, AND the
    tie-break anchor (qubit 0 clear) — on every case."""
    rng = np.random.default_rng(0xDEC0DE)
    cases = 0
    for _ in range(120):
        a = int(rng.integers(1, 6))
        r = int(rng.integers(1, 7))
        hist = rng.integers(0, 2, (r, a), dtype=np.int32)
        synd = (2 * hist.sum(axis=0) > r).astype(np.int32)
        got = np.asarray(decode_history(hist, 'matching'))
        np.testing.assert_array_equal(
            got, np.asarray(chain_matching(synd)))
        # the decoded pattern must actually satisfy the syndrome
        np.testing.assert_array_equal(got[:-1] ^ got[1:], synd)
        want = chain_matching_np(synd)
        np.testing.assert_array_equal(
            got, want, err_msg=f'case {cases}: synd={synd.tolist()}')
        cases += 1
    assert cases == 120


def test_decode_history_batched_matches_per_case():
    """The decoders are shape-polymorphic over leading batch axes: a
    stacked [B, R, K] decode equals B independent [R, K] decodes (the
    form the in-loop decode uses under the scan)."""
    rng = np.random.default_rng(11)
    for scheme in ('majority', 'matching'):
        hists = rng.integers(0, 2, (16, 5, 3), dtype=np.int32)
        batched = np.asarray(decode_history(hists, scheme))
        for b in range(hists.shape[0]):
            np.testing.assert_array_equal(
                batched[b], np.asarray(decode_history(hists[b], scheme)),
                err_msg=f'{scheme}: row {b}')


def test_decode_spec_validation():
    with pytest.raises(ValueError, match='scheme'):
        DecodeSpec('bogus', (0,))
    with pytest.raises(ValueError, match='cores'):
        DecodeSpec('majority', ())
    with pytest.raises(ValueError):
        as_decode_spec(None)
    with pytest.raises(ValueError):
        decode_history(np.zeros((2, 3), np.int32), 'bogus')
    # tuple / dict / passthrough coercions agree
    spec = DecodeSpec('matching', (3, 4), 0)
    assert as_decode_spec(spec) is spec
    assert as_decode_spec(('matching', (3, 4), 0)) == spec
    assert as_decode_spec(
        {'scheme': 'matching', 'cores': (3, 4)}) == spec


# ---------------------------------------------------------------------------
# rounds scan: bit-identity vs sequential dispatches, per engine
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('engine', ['generic', 'straightline', 'block',
                                    'pallas'])
def test_rounds_scan_bit_identical_to_sequential(engine):
    """R rounds in ONE scan dispatch == R sequential simulate_batch
    dispatches, per stat, on every engine rung the scan composes with
    (the fast engines ride the PR 17 timestamped fabric).  This is the
    bit-identity gate the qec_streaming bench row re-checks before
    timing."""
    mp, cfg, _ = _rep(3)
    kw = {'engine': engine}
    if engine == 'pallas':
        kw['pallas_interpret'] = True
    cfg = replace(cfg, **kw)
    rng = np.random.default_rng(5)
    mb = _planes(rng, 4, 5, mp, cfg)
    scan = simulate_rounds(mp, mb, cfg=cfg)
    seq = _stack_rounds([simulate_batch(mp, mb[r], cfg=cfg)
                         for r in range(mb.shape[0])])
    _assert_same(scan, seq, f'engine={engine}')


def test_rounds_scan_decode_engine_invariant():
    """The in-loop decode rides the same scan on every engine: full
    pytrees (syndrome_hist and decoded included) are equal across
    generic/block, the history is exactly the injected planes at the
    decode cores/slot, and the decoded correction equals the host-side
    decode of that history."""
    mp, cfg, dec = _rep(3)
    rng = np.random.default_rng(6)
    mb = _planes(rng, 5, 4, mp, cfg)
    outs = {eng: jax.tree.map(
                np.asarray,
                simulate_rounds(mp, mb, cfg=replace(cfg, engine=eng),
                                decode=dec))
            for eng in ('generic', 'block')}
    _assert_same(outs['block'], outs['generic'], 'block vs generic',
                 ignore=('steps',))
    hist = outs['generic']['syndrome_hist']
    np.testing.assert_array_equal(
        hist, np.transpose(mb[:, :, list(dec.cores), dec.slot],
                           (1, 0, 2)))
    np.testing.assert_array_equal(
        outs['generic']['decoded'],
        np.asarray(decode_history(hist, dec.scheme)))


def test_multiround_emitter_clean_and_engine_invariant():
    """The R-round unrolled emitter (one instruction stream, chain of
    R CFG diamonds) runs clean on generic AND the content-keyed block
    engine, bit-identically — the dispatch-granularity invariance of
    the timestamped LUT fabric carries over to the unrolled form."""
    rounds, n_data = 3, 3
    mp = qec_multiround_machine_program(n_data, rounds=rounds)
    cfg = qec_config(n_data, rounds=rounds, record_pulses=False)
    rng = np.random.default_rng(7)
    bits = rng.integers(0, 2, (6, n_data, cfg.max_meas), dtype=np.int32)
    outs = {eng: jax.tree.map(
                np.asarray,
                simulate_batch(mp, bits, cfg=replace(cfg, engine=eng)))
            for eng in ('generic', 'block')}
    _assert_same(outs['block'], outs['generic'], 'block vs generic',
                 ignore=('steps',))
    assert not np.any(outs['generic']['fault'])
    assert not np.any(outs['generic']['incomplete'])


def test_surface_cycle_chain_lut_decode():
    """Distance-3 surface-code-cycle-shaped rounds: the fabric LUT is
    the exact min-weight chain matching (built by the brute-force
    oracle), the scan's syndrome history reads the ancilla cores, and
    the in-loop 'matching' decode agrees with the LUT entry at the
    round-majority syndrome address."""
    d = 3
    assert chain_lut(d) == (0, 1, 4, 2)
    mp = surface_cycle_machine_program(d)
    assert mp.n_cores == 2 * d - 1
    cfg = surface_cycle_config(d, record_pulses=False)
    dec = surface_decode_spec(d)
    rng = np.random.default_rng(8)
    rounds, shots = 4, 6
    mb = _planes(rng, rounds, shots, mp, cfg)
    out = jax.tree.map(np.asarray,
                       simulate_rounds(mp, mb, cfg=cfg, decode=dec))
    assert out['syndrome_hist'].shape == (shots, rounds, d - 1)
    assert out['decoded'].shape == (shots, d)
    assert not np.any(out['fault'])
    voted = np.asarray(majority_vote(out['syndrome_hist']))
    lut = chain_lut(d)
    for b in range(shots):
        addr = int(sum(int(v) << i for i, v in enumerate(voted[b])))
        want = np.array([(lut[addr] >> i) & 1 for i in range(d)],
                        np.int32)
        np.testing.assert_array_equal(out['decoded'][b], want,
                                      err_msg=f'shot {b}')


def test_rounds_entry_rejections():
    """Typed rejections on both sides of the streaming boundary: the
    single-round entry points refuse a streaming cfg, and the rounds
    entry refuses malformed planes, contradictory round counts, the
    physics-closed fused engine, and out-of-range decode specs."""
    mp, cfg, dec = _rep(3)
    rng = np.random.default_rng(9)
    mb = _planes(rng, 2, 3, mp, cfg)
    with pytest.raises(ValueError, match='single-round'):
        simulate_batch(mp, mb[0], cfg=replace(cfg, rounds=4))
    with pytest.raises(ValueError, match='rounds, n_shots'):
        simulate_rounds(mp, mb[0], cfg=cfg)
    with pytest.raises(ValueError, match='contradicts'):
        simulate_rounds(mp, mb, cfg=replace(cfg, rounds=3))
    with pytest.raises(ValueError, match='fused'):
        simulate_rounds(mp, mb, cfg=replace(cfg, engine='fused'))
    with pytest.raises(ValueError, match='out of range'):
        simulate_rounds(mp, mb, cfg=cfg,
                        decode=DecodeSpec('majority', (0, 99)))
    with pytest.raises(ValueError, match='slot'):
        simulate_rounds(mp, mb, cfg=cfg,
                        decode=DecodeSpec('majority', (0,),
                                          slot=cfg.max_meas))


def test_normalize_stream_cfg_policy():
    """The stream normalizer differs from the coalescing one on
    purpose: the engine selector SURVIVES (each chunk is one session's
    scan, content-keyed rungs are eligible), while fused / op_hist /
    cores_axis reject typed, record_pulses is forced off, and the
    routing cfg pins rounds=1 so chunk lengths never fragment the
    session key."""
    base = InterpreterConfig(max_steps=80, max_pulses=10, max_meas=2)
    with pytest.raises(ValueError, match='fused'):
        _normalize_stream_cfg(replace(base, engine='fused'), 8)
    with pytest.raises(ValueError, match='op_hist'):
        _normalize_stream_cfg(replace(base, opcode_histogram=True), 8)
    with pytest.raises(ValueError, match='cores_axis'):
        _normalize_stream_cfg(replace(base, cores_axis='cores'), 8)
    with pytest.raises(ValueError, match='fault_mode'):
        _normalize_stream_cfg(replace(base, fault_mode='bogus'), 8)
    norm, strict = _normalize_stream_cfg(
        replace(base, engine='block', record_pulses=True, rounds=8,
                fault_mode='strict'), 8)
    assert norm.engine == 'block'
    assert not norm.record_pulses
    assert norm.rounds == 1
    assert norm.fault_mode == 'count' and strict
    key = StreamKey(sid=3, n_cores=2, n_instr_bucket=8, cfg=norm)
    assert key.label() == 'stream3c2i8'


# ---------------------------------------------------------------------------
# streaming sessions over the execution service
# ---------------------------------------------------------------------------

@pytest.mark.serve
def test_stream_session_end_to_end():
    """Open a session, stream 3 chunks of differing round counts:
    results arrive in submission order as incremental frames, each
    bit-identical to its solo simulate_rounds scan; close() drains,
    returns the full-history decode over the concatenated syndrome,
    and deregisters (further submits reject typed).  The frozen
    streaming stats block tracks rounds and session counts."""
    mp, cfg, dec = _rep(3)
    rng = np.random.default_rng(21)
    chunks = [_planes(rng, r, 4, mp, cfg) for r in (2, 3, 4)]
    with ExecutionService(max_wait_ms=2.0) as svc:
        sess = svc.open_stream(mp, cfg=cfg, decode=dec)
        for mb in chunks:
            sess.submit_rounds(mb)
        results = list(sess.results(timeout=300.0))
        assert len(results) == len(chunks)
        for i, (mb, got) in enumerate(zip(chunks, results)):
            want = jax.tree.map(
                np.asarray, simulate_rounds(mp, mb, cfg=cfg, decode=dec))
            _assert_same(got, want, f'chunk {i}')
        summary = sess.close(timeout=60.0)
        assert summary['chunks'] == 3
        assert summary['rounds'] == 9
        assert summary['failed_chunks'] == 0
        # full-history decode == one decode over every chunk's history
        hist = np.concatenate(
            [np.asarray(r['syndrome_hist']) for r in results], axis=1)
        np.testing.assert_array_equal(summary['syndrome_hist'], hist)
        np.testing.assert_array_equal(
            summary['decoded'],
            np.asarray(decode_history(hist, dec.scheme)))
        st = svc.stats()['streaming']
        assert st['open_sessions'] == 0
        assert st['sessions_opened'] == 1
        assert st['rounds_submitted'] == 9
        assert st['rounds_served'] == 9
        assert st['round_deadline_misses'] == 0
        # closed session rejects: the session object and the service
        with pytest.raises(RuntimeError, match='closed'):
            sess.submit_rounds(chunks[0])
        with pytest.raises(RuntimeError, match='closed'):
            sess.close()
        with pytest.raises(ValueError, match='not open'):
            svc.submit_rounds(mp, chunks[0], cfg=cfg, stream=sess.sid)
        assert svc.close_stream(sess.sid) is False   # idempotent


@pytest.mark.serve
def test_submit_rounds_detached_and_rejections():
    """A detached chunk (no session) serves under its own fresh sid
    and never appears in open_sessions; malformed submissions reject
    before enqueue."""
    mp, cfg, dec = _rep(3)
    rng = np.random.default_rng(22)
    mb = _planes(rng, 3, 4, mp, cfg)
    with ExecutionService(max_wait_ms=2.0) as svc:
        got = svc.submit_rounds(mp, mb, cfg=cfg,
                                decode=dec).result(timeout=300.0)
        want = jax.tree.map(
            np.asarray, simulate_rounds(mp, mb, cfg=cfg, decode=dec))
        _assert_same(got, want, 'detached chunk')
        assert svc.stats()['streaming']['open_sessions'] == 0
        with pytest.raises(ValueError, match='rounds, n_shots'):
            svc.submit_rounds(mp, mb[0], cfg=cfg)
        with pytest.raises(ValueError, match='not both'):
            svc.submit_rounds(mp, mb, cfg=cfg, deadline_ms=50.0,
                              round_deadline_ms=10.0)
        with pytest.raises(ValueError, match='out of range'):
            svc.submit_rounds(mp, mb, cfg=cfg,
                              decode=DecodeSpec('majority', (99,)))
        with pytest.raises(ValueError, match='not open'):
            svc.submit_rounds(mp, mb, cfg=cfg, stream=424242)


@pytest.mark.serve
def test_stream_round_deadline_miss_counts_every_round():
    """Per-round deadlines are honored at scan-chunk boundaries: a
    chunk expiring in queue raises DeadlineError and counts EVERY
    round it carried as a miss; the session summary reports the failed
    chunk without losing the session."""
    mp, cfg, dec = _rep(3)
    rng = np.random.default_rng(23)
    mb = _planes(rng, 4, 3, mp, cfg)
    # a huge batching window keeps the chunk queued until its
    # (rounds x round_deadline_ms) deadline expires un-dispatched
    with ExecutionService(max_batch_programs=64,
                          max_wait_ms=60_000.0) as svc:
        sess = svc.open_stream(mp, cfg=cfg, decode=dec,
                               round_deadline_ms=15.0)
        h = sess.submit_rounds(mb)
        with pytest.raises(DeadlineError):
            h.result(timeout=60.0)
        summary = sess.close(timeout=60.0)
        assert summary['failed_chunks'] == 1
        assert isinstance(summary['errors'][0], DeadlineError)
        st = svc.stats()['streaming']
        assert st['round_deadline_misses'] == mb.shape[0]
        assert st['rounds_served'] == 0


@pytest.mark.serve
def test_stream_session_ttl_expiry():
    """An idle session past session_ttl_s is swept: sessions_expired
    advances, a session_expired flight event records the sid, and a
    late submit rejects typed — an abandoned stream cannot pin its
    home executor forever."""
    mp, cfg, dec = _rep(3)
    rng = np.random.default_rng(24)
    with ExecutionService(max_wait_ms=2.0, supervise_interval_ms=10.0,
                          session_ttl_s=0.05) as svc:
        sess = svc.open_stream(mp, cfg=cfg, decode=dec)
        deadline = time.monotonic() + 30.0
        while svc.stats()['streaming']['sessions_expired'] < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        st = svc.stats()['streaming']
        assert st['sessions_expired'] == 1
        assert st['open_sessions'] == 0
        events = svc.flight_recorder.events(kind='session_expired')
        assert events and events[-1]['sid'] == sess.sid
        with pytest.raises(ValueError, match='not open'):
            sess.submit_rounds(_planes(rng, 2, 3, mp, cfg))


@pytest.mark.serve
@pytest.mark.chaos
def test_stream_chunk_survives_chaos_crashes():
    """Two scripted crashes under the ONLY executor while a chunk is
    in flight: the attempt-token retry machinery re-dispatches the
    whole scan and the session sees exactly one result, bit-identical
    — no lost or duplicated round results under a killed dispatch."""
    mp, cfg, dec = _rep(3)
    rng = np.random.default_rng(25)
    chunks = [_planes(rng, r, 3, mp, cfg) for r in (3, 2)]
    plan = ChaosPlan(seed=0, script=('crash', 'crash'))
    with ExecutionService(max_wait_ms=2.0, max_queue=1024,
                          retry_policy=RetryPolicy(max_attempts=6,
                                                   backoff_s=0.005),
                          breaker_threshold=2, breaker_cooldown_ms=60.0,
                          supervise_interval_ms=10.0) as svc:
        sess = svc.open_stream(mp, cfg=cfg, decode=dec)
        with ChaosMonkey(svc, plan) as monkey:
            h = sess.submit_rounds(chunks[0])
            got = h.result(timeout=300.0)
        assert monkey.script_exhausted()
        assert h.retries == 2
        want = jax.tree.map(
            np.asarray,
            simulate_rounds(mp, chunks[0], cfg=cfg, decode=dec))
        _assert_same(got, want, 'healed chunk')
        # the session is still live on the healed service: a clean
        # chunk serves and the summary counts exactly the submitted
        # rounds (nothing double-completed through the stale attempt)
        sess.submit_rounds(chunks[1])
        summary = sess.close(timeout=300.0)
        assert summary['failed_chunks'] == 0
        assert summary['rounds'] == 5
        assert summary['decoded'].shape == (3, 3)
        assert svc.stats()['streaming']['rounds_served'] == 5


# ---------------------------------------------------------------------------
# fleet: sticky sessions surviving replica loss (slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.fleet
@pytest.mark.serve
def test_fleet_stream_survives_home_replica_kill():
    """The acceptance chaos drill at fleet scope: open a stream over
    replica PROCESSES, SIGKILL the session's home replica mid-stream,
    and every chunk — before and after the kill — completes
    bit-identically with no lost or duplicated round results; the
    session closes with a clean summary."""
    from distributed_processor_tpu.serve.fleet import Fleet
    mp, cfg, dec = _rep(3)
    rng = np.random.default_rng(26)
    chunks = [_planes(rng, 2, 4, mp, cfg) for _ in range(5)]
    refs = [jax.tree.map(np.asarray,
                         simulate_rounds(mp, mb, cfg=cfg, decode=dec))
            for mb in chunks]
    with Fleet(2,
               service={'max_batch_programs': 4, 'max_wait_ms': 5.0,
                        'max_queue': 256},
               env={'XLA_FLAGS':
                    '--xla_force_host_platform_device_count=1'},
               router_kwargs={'retry_policy':
                              RetryPolicy(max_attempts=10,
                                          backoff_s=0.05,
                                          max_backoff_s=1.0)}) as f:
        sess = f.open_stream(mp, cfg=cfg, decode=dec)
        for mb in chunks[:3]:
            sess.submit_rounds(mb)
        for i, got in zip(range(3), sess.results(timeout=600.0)):
            _assert_same(got, refs[i], f'chunk {i} pre-kill')
        # the whole session is pinned to one home replica; kill it
        home_rid = f.router._home.get(('stream', sess.sid))
        assert home_rid is not None, 'stream never homed'
        f.kill(f.replica_ids().index(home_rid))
        for mb in chunks[3:]:
            sess.submit_rounds(mb)
        for i, got in zip(range(3, 5), sess.results(timeout=600.0)):
            _assert_same(got, refs[i], f'chunk {i} post-kill')
        summary = sess.close(timeout=600.0)
        assert summary['failed_chunks'] == 0
        assert summary['chunks'] == 5 and summary['rounds'] == 10
        np.testing.assert_array_equal(
            summary['decoded'],
            np.asarray(decode_history(summary['syndrome_hist'],
                                      dec.scheme)))
        st = f.router.stats()
        assert st['streaming']['rounds_submitted'] == 10
        assert st['streaming']['open_sessions'] == 0
