"""Real multi-process multihost execution (VERDICT round-1 item 7).

Launches 2 OS processes, each a separate JAX controller with 4 virtual
CPU devices, wired by ``jax.distributed.initialize`` over a localhost
coordinator — the same multi-controller model that spans hosts over DCN
on a TPU pod.  Asserts that ``make_global_mesh`` / ``host_local_batch``
/ ``global_shot_array`` / ``sweep_stats`` produce statistics identical
to a single-process run of the same shots.

The reference has no multi-host analog (its fabric is on-chip wiring);
this pins the capability the TPU build adds.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, 'multihost_worker.py')


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


@pytest.mark.multihost
def test_two_process_sweep_stats_matches_single():
    port = _free_port()
    env = dict(os.environ)
    env.pop('JAX_PLATFORMS', None)            # workers set their own
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), '2', str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            cwd=HERE, text=True)
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, f'worker failed:\n{err[-3000:]}'
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        # a failed/timed-out worker must not orphan its peer (which
        # would sit blocked on the coordinator holding the port)
        for q in procs:
            if q.poll() is None:
                q.kill()

    # topology: 2 controllers x 4 local = 8 global devices, disjoint
    # host-local shot shards covering all 16 shots
    for o in outs:
        assert o['info']['process_count'] == 2
        assert o['info']['global_devices'] == 8
        assert o['local_shots'] == 8
    assert sorted(o['offset'] for o in outs) == [0, 8]

    # both controllers computed identical (psum-replicated) statistics
    assert outs[0]['mean_pulses'] == outs[1]['mean_pulses']
    assert outs[0]['mean_qclk'] == outs[1]['mean_qclk']
    assert outs[0]['err_rate'] == outs[1]['err_rate'] == 0.0

    # physics-closed stats agree across controllers too (epoch loops ran
    # on each host's local devices; only the final psum crossed DCN)
    assert outs[0]['phys_mean_pulses'] == outs[1]['phys_mean_pulses']
    assert outs[0]['phys_meas1_rate'] == outs[1]['phys_meas1_rate']
    assert outs[0]['phys_err_rate'] == outs[1]['phys_err_rate'] == 0.0
    # p1_init=1, sigma=0.01: every shot measured 1 and took the reset
    # branch (4 pulses) — the physics loop really closed on both hosts
    np.testing.assert_allclose(outs[0]['phys_meas1_rate'], 1.0)
    np.testing.assert_allclose(outs[0]['phys_mean_pulses'], 4.0)

    # ... equal to the single-process run of the same global batch
    from distributed_processor_tpu.parallel import (sweep_stats, make_mesh,
                                                    sharded_physics_stats)
    from distributed_processor_tpu.pipeline import compile_to_machine
    from distributed_processor_tpu.models import (active_reset,
                                                  make_default_qchip)
    from distributed_processor_tpu.sim.interpreter import InterpreterConfig
    from distributed_processor_tpu.sim.physics import ReadoutPhysics
    mp = compile_to_machine(active_reset(['Q0']), make_default_qchip(2),
                            n_qubits=1)
    cfg = InterpreterConfig(max_steps=mp.n_instr + 8, max_pulses=8,
                            max_meas=2, max_resets=1)
    rng = np.random.default_rng(7)            # worker's stream
    bits = rng.integers(0, 2, size=(16, mp.n_cores, cfg.max_meas))
    mesh = make_mesh(n_dp=8)
    stats = sweep_stats(mp, bits, mesh, cfg=cfg)
    np.testing.assert_allclose(np.asarray(stats['mean_pulses']),
                               outs[0]['mean_pulses'])
    np.testing.assert_allclose(np.asarray(stats['mean_qclk']),
                               outs[0]['mean_qclk'])
    # same dp-axis extent (8) => identical per-shard fold_in keys, so
    # the single-process physics stats match the 2-controller run
    pstats = sharded_physics_stats(
        mp, ReadoutPhysics(sigma=0.01, p1_init=1.0), 3, 16, mesh,
        max_steps=mp.n_instr * 4 + 64, max_pulses=8, max_meas=2)
    np.testing.assert_allclose(np.asarray(pstats['mean_pulses']),
                               outs[0]['phys_mean_pulses'])
    np.testing.assert_allclose(np.asarray(pstats['meas1_rate']),
                               outs[0]['phys_meas1_rate'])
    np.testing.assert_allclose(float(pstats['err_rate']),
                               outs[0]['phys_err_rate'])
