"""CLI, result-checkpointing, and assembler API-equivalence tests."""

import json

import numpy as np
import pytest

import distributed_processor_tpu as dp
from distributed_processor_tpu.cli import main as cli_main
from distributed_processor_tpu.utils.results import (
    save_results, load_results, SweepAccumulator)
from distributed_processor_tpu import isa


def test_cli_run_and_compile(tmp_path, capsys):
    prog_path = tmp_path / 'prog.json'
    prog_path.write_text(json.dumps(
        [{'name': 'X90', 'qubit': ['Q0']},
         {'name': 'read', 'qubit': ['Q0']}]))
    cli_main(['--qubits', '1', 'run', str(prog_path), '--shots', '4'])
    out = json.loads(capsys.readouterr().out)
    assert out['shots'] == 4 and out['error_shots'] == 0
    assert out['mean_pulses_per_core'] == [3.0]

    cli_main(['--qubits', '1', 'compile', str(prog_path), '-o',
              str(tmp_path / 'out.json')])
    saved = json.loads((tmp_path / 'out.json').read_text())
    assert 'program' in saved


def test_cli_qasm_trace(tmp_path, capsys):
    qasm = tmp_path / 'p.qasm'
    qasm.write_text('qubit[1] q; sx q[0];')
    cli_main(['--qubits', '1', 'trace', str(qasm)])
    out = capsys.readouterr().out
    assert 'core 0' in out and 'pc=' in out


def test_cli_disasm_full_operands(tmp_path, capsys):
    """disasm prints every operand field (amp/phase/env/time...), the
    analog of the reference's asmparse.cmdparse dump — not just opcode
    names (round-1 review item)."""
    prog_path = tmp_path / 'prog.json'
    prog_path.write_text(json.dumps(
        [{'name': 'X90', 'qubit': ['Q0']},
         {'name': 'read', 'qubit': ['Q0']}]))
    cli_main(['--qubits', '1', 'disasm', str(prog_path)])
    out = capsys.readouterr().out
    assert 'pulse_write_trig' in out
    for field in ('amp=', 'phase=', 'freq=', 'cfg=', 'cmd_time=',
                  'env_start=', 'env_length='):
        assert field in out, f'missing {field} in disasm output:\n{out}'


def test_cli_envdump_freqdump(tmp_path, capsys):
    prog_path = tmp_path / 'prog.json'
    prog_path.write_text(json.dumps(
        [{'name': 'X90', 'qubit': ['Q0']},
         {'name': 'read', 'qubit': ['Q0']}]))
    cli_main(['--qubits', '1', 'envdump', str(prog_path)])
    out = capsys.readouterr().out
    assert 'elem 0' in out and 'j' in out      # complex samples printed
    cli_main(['--qubits', '1', 'freqdump', str(prog_path)])
    out = capsys.readouterr().out
    assert 'freq 4.2' in out                   # Q0 drive frequency
    assert 'fsamp 8.0' in out                  # 16 spc @ 500 MHz


def test_results_roundtrip(tmp_path):
    path = str(tmp_path / 'res.npz')
    save_results(path, {'counts': np.arange(8), '_private': 1},
                 meta={'shots': 100})
    arrays, meta = load_results(path)
    np.testing.assert_array_equal(arrays['counts'], np.arange(8))
    assert '_private' not in arrays
    assert meta == {'shots': 100}


def test_sweep_accumulator_resume(tmp_path):
    path = str(tmp_path / 'acc.npz')
    acc = SweepAccumulator(path, checkpoint_every=2)
    for _ in range(4):
        acc.add({'ones': np.ones(3)})
    resumed = SweepAccumulator.resume(path)
    assert resumed.n_batches == 4
    np.testing.assert_array_equal(resumed.state['ones'], 4 * np.ones(3))
    resumed.add({'ones': np.ones(3)})
    assert resumed.n_batches == 5


def test_assembler_programmatic_equals_from_list(channelcfg_path):
    """Programmatic SingleCoreAssembler API vs from_list must produce
    identical buffers (the reference proves the same equivalence,
    python/test/test_assembler.py:44-65)."""
    from distributed_processor_tpu.elements import TPUElementConfig
    elem_cfgs = [TPUElementConfig(16, 1), TPUElementConfig(16, 16),
                 TPUElementConfig(4, 4)]

    cmd_list = [
        {'op': 'phase_reset'},
        {'op': 'declare_reg', 'name': 'n', 'dtype': 'int'},
        {'op': 'reg_write', 'name': 'n', 'value': 3},
        {'op': 'pulse', 'freq': 100e6, 'phase': 0.5, 'amp': 0.7,
         'env': np.ones(32, complex) * 0.5, 'start_time': 10, 'elem_ind': 0},
        {'op': 'jump_label', 'dest_label': 'loop'},
        {'op': 'reg_alu', 'in0': -1, 'alu_op': 'add', 'in1_reg': 'n',
         'out_reg': 'n'},
        {'op': 'jump_cond', 'in0': 1, 'alu_op': 'le', 'in1_reg': 'n',
         'jump_label': 'loop'},
        {'op': 'done_stb'},
    ]
    a1 = dp.SingleCoreAssembler(elem_cfgs)
    a1.from_list(cmd_list)

    a2 = dp.SingleCoreAssembler(elem_cfgs)
    a2.add_phase_reset()
    a2.declare_reg('n', dtype='int')
    a2.add_reg_write('n', 3)
    a2.add_pulse(freq=100e6, phase=0.5, amp=0.7,
                 env=np.ones(32, complex) * 0.5, start_time=10, elem_ind=0)
    a2.add_reg_alu(-1, 'add', 'n', 'n', label='loop')
    a2.add_jump_cond(1, 'le', 'n', 'loop')
    a2.add_done_stb()

    c1, e1, f1 = a1.get_compiled_program()
    c2, e2, f2 = a2.get_compiled_program()
    assert c1 == c2
    for x, y in zip(e1, e2):
        np.testing.assert_array_equal(x, y)
    for x, y in zip(f1, f2):
        np.testing.assert_array_equal(x, y)


def test_assembler_label_aliases_through_declares():
    """Review regression: consecutive labels separated only by
    declarations all bind to the next instruction address."""
    from distributed_processor_tpu.assembler import SingleCoreAssembler
    from distributed_processor_tpu.elements import TPUElementConfig

    elems = [TPUElementConfig(samples_per_clk=16),
             TPUElementConfig(samples_per_clk=16),
             TPUElementConfig(samples_per_clk=4)]
    asm = SingleCoreAssembler(elems)
    asm.from_list([
        {'op': 'jump_label', 'dest_label': 'L1'},
        {'op': 'declare_reg', 'name': 'r0'},
        {'op': 'jump_label', 'dest_label': 'L2'},
        {'op': 'reg_alu', 'in0': 1, 'alu_op': 'id0', 'in1_reg': 'r0',
         'out_reg': 'r0'},
        {'op': 'jump_i', 'jump_label': 'L1'},
        {'op': 'jump_i', 'jump_label': 'L2'},
        {'op': 'done_stb'},
    ])
    cmd_buf, _, _ = asm.get_compiled_program()
    dis = isa.disassemble(cmd_buf)
    assert dis[1]['op'] == 'jump_i' and dis[1]['jump_addr'] == 0
    assert dis[2]['op'] == 'jump_i' and dis[2]['jump_addr'] == 0


def test_pulse_split_label_binds_first_instruction():
    """Review regression: a label on a multi-register-parameter pulse
    must address the first instruction of the split group, so loop
    back-edges re-execute the parameter writes."""
    from distributed_processor_tpu.assembler import SingleCoreAssembler
    from distributed_processor_tpu.elements import TPUElementConfig

    elems = [TPUElementConfig(samples_per_clk=16)]
    asm = SingleCoreAssembler(elems)
    asm.declare_reg('rf', dtype='int')
    asm.declare_reg('ra', dtype=('amp', 0))
    asm.add_pulse(freq='rf', phase=0.0, amp='ra', start_time=10,
                  env=np.ones(32, complex) * 0.5, elem_ind=0,
                  label='L')
    asm.add_done_stb()
    assert len(asm._program) == 3            # write-only + main + done
    assert asm._get_cmd_labelmap()['L'] == 0


def test_vcd_export(tmp_path, capsys):
    """`dproc-tpu trace --vcd` writes a parseable VCD: correct header,
    per-core scopes, pc transitions at trace times, cstrobe + pulse
    words at the recorded trigger times."""
    prog_path = tmp_path / 'prog.json'
    prog_path.write_text(json.dumps(
        [{'name': 'X90', 'qubit': ['Q0']},
         {'name': 'read', 'qubit': ['Q0']}]))
    vcd_path = tmp_path / 'trace.vcd'
    cli_main(['--qubits', '1', 'trace', str(prog_path),
              '--vcd', str(vcd_path)])
    assert 'wrote' in capsys.readouterr().out
    text = vcd_path.read_text()
    assert '$timescale 1 ps $end' in text
    assert '$scope module core0 $end' in text
    assert '$scope module elem0 $end' in text   # per-element pulse_iface
    for name in ('pc', 'qclk', 'cstrobe', 'amp', 'phase', 'freq', 'env'):
        assert f' {name} ' in text or f' {name}\n' in text

    # cross-check against the run itself: every recorded pulse trigger
    # time appears as a timestamped cstrobe rise
    from distributed_processor_tpu.simulator import Simulator
    sim = Simulator(n_qubits=1)
    mp = sim.compile(json.loads(prog_path.read_text()))
    from distributed_processor_tpu.sim import simulate
    out = simulate(mp, cfg=sim.interpreter_config(mp, trace=True))
    n = int(np.asarray(out['n_pulses'])[0])
    assert n == 3                      # X90 + rdrv + rdlo
    times = set()
    cur = None
    for line in text.splitlines():
        if line.startswith('#'):
            cur = int(line[1:])
        elif cur is not None and line.startswith('1'):
            times.add(cur)             # a 1-bit rise (cstrobe or done)
    for p in range(n):
        assert int(np.asarray(out['rec_gtime'])[0, p]) * 2000 in times


def test_vcd_requires_trace_and_records(tmp_path):
    from distributed_processor_tpu.utils.vcd import write_vcd
    from distributed_processor_tpu.simulator import Simulator
    sim = Simulator(n_qubits=1)
    out = sim.run([{'name': 'X90', 'qubit': ['Q0']}])
    with pytest.raises(ValueError, match='trace'):
        write_vcd(str(tmp_path / 'x.vcd'), out)


def test_cli_run_physics(tmp_path, capsys):
    """`dproc-tpu run --physics` closes the loop from the command line."""
    prog_path = tmp_path / 'prog.json'
    prog_path.write_text(json.dumps(
        [{'name': 'read', 'qubit': ['Q0']},
         {'name': 'branch_fproc', 'alu_cond': 'eq', 'cond_lhs': 1,
          'func_id': 'Q0.meas', 'scope': ['Q0'],
          'true': [{'name': 'X90', 'qubit': ['Q0']},
                   {'name': 'X90', 'qubit': ['Q0']}],
          'false': []}]))
    cli_main(['--qubits', '1', 'run', str(prog_path), '--shots', '16',
              '--physics', '--sigma', '0.01', '--p1-init', '1.0'])
    out = json.loads(capsys.readouterr().out)
    assert out['error_shots'] == 0
    assert out['meas1_rate_per_core'] == [1.0]   # all start excited
    assert out['mean_pulses_per_core'] == [4.0]  # reset branch everywhere
    assert out['epochs'] >= 1


def test_vcd_qclk_exact_across_sync(tmp_path):
    """ADVICE r2: qclk is dumped from the per-step offset trace, so a
    sync's qclk reset takes effect AT its step instead of ramping
    retroactively — early-timestamp qclk values equal the global time
    (offset 0) even though the run ends with a nonzero offset."""
    from distributed_processor_tpu.simulator import Simulator
    from distributed_processor_tpu.sim import simulate
    from distributed_processor_tpu.models.experiments import \
        loop_shots_program
    from distributed_processor_tpu.utils.vcd import write_vcd

    sim = Simulator(n_qubits=1)
    mp = sim.compile(loop_shots_program(
        [{'name': 'X90', 'qubit': ['Q0']}], 2, ['Q0']))
    out = simulate(mp, cfg=sim.interpreter_config(mp, trace=True))
    final_off = int(np.asarray(out['time'])[0]) \
        - int(np.asarray(out['qclk'])[0])
    assert final_off > 0        # the loop's qclk rewind moved the origin
    path = tmp_path / 't.vcd'
    write_vcd(str(path), out)
    text = path.read_text()
    assert ' qclk ' in text or ' qclk\n' in text      # exact, not approx
    assert 'qclk_approx' not in text
    # collect (time_ps, qclk) events for core 0
    ident = None
    for line in text.splitlines():
        if '$var' in line and ' qclk ' in line:
            ident = line.split()[3]
            break
    events, cur = [], None
    for line in text.splitlines():
        if line.startswith('#'):
            cur = int(line[1:])
        elif ident and line.startswith('b') and line.endswith(' ' + ident):
            events.append((cur, int(line.split()[0][1:], 2)))
    pre_sync = [(t, q) for t, q in events if t is not None
                and q == t // 2000 and t // 2000 < final_off]
    assert pre_sync                    # early steps dump qclk == time
    # and a legacy trace (no trace_off) is honestly renamed
    legacy = {k: v for k, v in out.items() if k != 'trace_off'}
    write_vcd(str(path), legacy)
    assert 'qclk_approx' in path.read_text()


def test_sweep_accumulator_legacy_and_field_diff(tmp_path):
    """ADVICE r2: a checkpoint without identity resumes with a warning
    (legacy), and a mismatched fingerprint names the differing fields
    instead of dumping two repr strings."""
    import warnings
    path = str(tmp_path / 'c.npz')
    legacy = SweepAccumulator(path, checkpoint_every=1)   # no meta stored
    legacy.add({'ones': np.ones(2)})
    with pytest.warns(UserWarning, match='no identity'):
        SweepAccumulator.resume(path, meta={'fingerprint_version': 2,
                                            'batch': 16})
    meta = {'fingerprint_version': 2, 'batch': 16, 'key': [0, 5]}
    acc = SweepAccumulator(path, checkpoint_every=1, meta=meta)
    acc.add({'ones': np.ones(2)})
    with pytest.raises(ValueError, match="'batch'"):
        SweepAccumulator.resume(path, meta=dict(meta, batch=32))
    # version skew alone: warn, but version-stable fields still compare
    with pytest.warns(UserWarning, match='fingerprint version'):
        SweepAccumulator.resume(path, meta=dict(meta,
                                                fingerprint_version=3))
    with pytest.warns(UserWarning, match='fingerprint version'):
        with pytest.raises(ValueError, match="'batch'"):
            SweepAccumulator.resume(
                path, meta=dict(meta, fingerprint_version=3, batch=64))
    # a format-changed field (str in old version, dict now) is skipped
    # on version skew instead of spuriously failing
    acc2 = SweepAccumulator(str(tmp_path / 'c2.npz'), checkpoint_every=1,
                            meta=dict(meta, model='ReadoutPhysics(...)'))
    acc2.add({'ones': np.ones(2)})
    with pytest.warns(UserWarning, match="model"):
        SweepAccumulator.resume(
            str(tmp_path / 'c2.npz'),
            meta=dict(meta, fingerprint_version=3, model={'sigma': 0.1}))


def test_sweep_fingerprint_array_model_fields(tmp_path):
    """ADVICE-fix follow-up: per-core array g0/g1 (a documented model
    form) must fingerprint and checkpoint cleanly."""
    import json as _json
    from distributed_processor_tpu.parallel.driver import _jsonable
    from distributed_processor_tpu.sim.physics import ReadoutPhysics
    m = ReadoutPhysics(g0=np.array([1 + 0j, 0.5 + 0.5j]),
                       g1=np.array([-0.6 + 0.8j, -1 + 0j]))
    enc = _jsonable(m)
    _json.dumps(enc)                   # round-trippable
    assert enc['g0'] == [[1.0, 0.0], [0.5, 0.5]]


def test_cli_run_physics_bloch(tmp_path, capsys):
    """`run --physics --device bloch` drives the SU(2) co-state from
    the command line: an X90-then-read program measures ~50/50."""
    prog_path = tmp_path / 'p.json'
    prog_path.write_text(json.dumps(
        [{'name': 'X90', 'qubit': ['Q0']},
         {'name': 'read', 'qubit': ['Q0']}]))
    cli_main(['--qubits', '1', 'run', str(prog_path), '--physics',
              '--device', 'bloch', '--shots', '256', '--sigma', '0.01',
              '--p1-init', '0.0'])
    out = json.loads(capsys.readouterr().out)
    assert out['error_shots'] == 0
    assert 0.3 < out['meas1_rate_per_core'][0] < 0.7


def test_cli_statevec_bell(tmp_path, capsys):
    """--device statevec: the coupling map auto-derives from the
    program + gate library, and a Bell program's sampled bits come out
    perfectly correlated (identical per-core marginals at sigma=0)."""
    import json
    prog = [{'name': 'virtual_z', 'qubit': ['Q0'],
             'phase': 1.5707963267948966},
            {'name': 'X90', 'qubit': ['Q0']},
            {'name': 'virtual_z', 'qubit': ['Q0'],
             'phase': 1.5707963267948966},
            {'name': 'CNOT', 'qubit': ['Q0', 'Q1']},
            {'name': 'barrier', 'qubit': ['Q0', 'Q1']},
            {'name': 'read', 'qubit': ['Q0']},
            {'name': 'read', 'qubit': ['Q1']}]
    p = tmp_path / 'bell.json'
    p.write_text(json.dumps(prog))
    cli_main(['--qubits', '2', 'run', str(p), '--shots', '64',
              '--physics', '--sigma', '0', '--p1-init', '0',
              '--device', 'statevec'])
    out = json.loads(capsys.readouterr().out)
    assert out['error_shots'] == 0
    r0, r1 = out['meas1_rate_per_core']
    assert abs(r0 - r1) < 1e-9          # Bell: bit-for-bit correlated
    assert 0.2 < r0 < 0.8


def test_cli_statevec_flag_validation(tmp_path):
    import json
    import pytest
    p = tmp_path / 'x.json'
    p.write_text(json.dumps([{'name': 'X90', 'qubit': ['Q0']},
                             {'name': 'read', 'qubit': ['Q0']}]))
    with pytest.raises(SystemExit, match='statevec'):
        cli_main(['--qubits', '1', 'run', str(p), '--physics',
                  '--device', 'bloch', '--depol2', '0.1'])


def test_cli_statevec_leak(tmp_path, capsys):
    """--leak through the CLI: a pi pulse (P(|1>)=1 after it) at
    leak=1.0 leaves every shot leaked, reading --leak-bit."""
    prog = [{'name': 'pulse', 'dest': 'Q0.qdrv', 'freq': 4.2e9,
             'phase': 0.0, 'amp': 0.96, 'twidth': 24e-9,
             'env': {'env_func': 'square', 'paradict': {}}},
            {'name': 'read', 'qubit': ['Q0']}]
    p = tmp_path / 'leak.json'
    p.write_text(json.dumps(prog))
    for bit in (1, 0):
        cli_main(['--qubits', '1', 'run', str(p), '--shots', '16',
                  '--physics', '--sigma', '0', '--p1-init', '0',
                  '--device', 'statevec', '--leak', '1.0',
                  '--leak-bit', str(bit)])
        out = json.loads(capsys.readouterr().out)
        assert out['meas1_rate_per_core'] == [float(bit)]
        assert out['leaked_rate_per_core'] == [1.0]
    with pytest.raises(SystemExit, match='statevec'):
        cli_main(['--qubits', '1', 'run', str(p), '--physics',
                  '--device', 'bloch', '--leak', '0.1'])
