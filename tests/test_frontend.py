"""OpenQASM 3 frontend tests: parser, gate mapping, translation, and
QASM -> compile -> simulate end-to-end."""

import numpy as np
import pytest

from distributed_processor_tpu.frontend import (qasm_to_program,
                                                DefaultGateMap)
from distributed_processor_tpu.frontend.qasm_parser import (parse_qasm,
                                                            QASMSyntaxError)
from distributed_processor_tpu.models import make_default_qchip
from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.sim import simulate


def test_parser_basics():
    stmts = parse_qasm('''
        OPENQASM 3;
        include "stdgates.inc";
        qubit[2] q;
        bit[2] c;
        h q[0];
        cx q[0], q[1];
        rz(pi/2) q[1];
        c[0] = measure q[0];
        // a comment
        reset q[1];
    ''')
    kinds = [type(s).__name__ for s in stmts]
    assert kinds == ['Decl', 'Decl', 'GateCall', 'GateCall', 'GateCall',
                     'Measure', 'Reset']


def test_parser_rejects_garbage():
    with pytest.raises(QASMSyntaxError):
        parse_qasm('qubit[2 q;')


def test_gate_map_decompositions():
    gm = DefaultGateMap()
    h = gm.get_qubic_gateinstr('h', ['Q0'], [])
    assert [i['name'] for i in h] == ['virtual_z', 'X90', 'virtual_z']
    x = gm.get_qubic_gateinstr('x', ['Q0'], [])
    assert [i['name'] for i in x] == ['X90', 'X90']
    rz = gm.get_qubic_gateinstr('rz', ['Q0'], [np.pi / 4])
    assert rz == [{'name': 'virtual_z', 'qubit': ['Q0'],
                   'phase': np.pi / 4}]
    cx = gm.get_qubic_gateinstr('cx', ['Q0', 'Q1'], [])
    assert cx == [{'name': 'CNOT', 'qubit': ['Q0', 'Q1']}]


def test_gate_map_unitaries():
    """Euler decompositions must reproduce the gate unitaries."""
    gm = DefaultGateMap()
    X90 = np.array([[1, -1j], [-1j, 1]]) / np.sqrt(2)

    def u_of(instrs):
        u = np.eye(2)
        for i in instrs:
            if i['name'] == 'X90':
                u = X90 @ u
            else:
                p = i['phase']
                u = np.diag([np.exp(-1j * p / 2), np.exp(1j * p / 2)]) @ u
        return u

    def proj_eq(a, b):
        return abs(abs(np.trace(a.conj().T @ b)) - 2) < 1e-9

    H = np.array([[1, 1], [1, -1]]) / np.sqrt(2)
    Y = np.array([[0, -1j], [1j, 0]])
    assert proj_eq(u_of(gm.get_qubic_gateinstr('h', ['Q0'], [])), H)
    assert proj_eq(u_of(gm.get_qubic_gateinstr('y', ['Q0'], [])), Y)
    theta = 1.23
    RX = np.array([[np.cos(theta / 2), -1j * np.sin(theta / 2)],
                   [-1j * np.sin(theta / 2), np.cos(theta / 2)]])
    assert proj_eq(u_of(gm.get_qubic_gateinstr('rx', ['Q0'], [theta])), RX)
    RY = np.array([[np.cos(theta / 2), -np.sin(theta / 2)],
                   [np.sin(theta / 2), np.cos(theta / 2)]])
    assert proj_eq(u_of(gm.get_qubic_gateinstr('ry', ['Q0'], [theta])), RY)


def test_reset_expands_to_active_reset():
    prog = qasm_to_program('qubit[1] q; reset q[0];')
    assert prog[0] == {'name': 'read', 'qubit': ['Q0']}
    assert prog[1]['name'] == 'branch_fproc'
    assert prog[1]['func_id'] == 'Q0.meas'
    assert [i['name'] for i in prog[1]['true']] == ['X90', 'X90']


def test_measure_feeds_branch():
    prog = qasm_to_program('''
        qubit[2] q;
        bit[1] c;
        c[0] = measure q[0];
        if (c[0] == 1) { x q[1]; }
    ''')
    assert prog[0] == {'name': 'read', 'qubit': ['Q0']}
    br = prog[1]
    assert br['name'] == 'branch_fproc' and br['func_id'] == 'Q0.meas'
    assert [i['name'] for i in br['true']] == ['X90', 'X90']
    assert br['false'] == []


def test_classical_arithmetic():
    prog = qasm_to_program('''
        qubit[1] q;
        int[32] a = 3;
        int[32] b;
        b = a + 2;
        if (b >= 5) { x q[0]; }
    ''')
    names = [i['name'] for i in prog]
    assert 'declare' in names and 'set_var' in names and 'alu' in names
    alu = next(i for i in prog if i['name'] == 'alu')
    assert alu['op'] == 'add' and alu['out'] == 'b'
    assert prog[-1]['name'] == 'branch_var'
    assert prog[-1]['cond_rhs'] == 'b'


def test_qasm_end_to_end_simulation():
    src = '''
        OPENQASM 3;
        qubit[2] q;
        bit[2] c;
        h q[0];
        cx q[0], q[1];
        barrier q[0], q[1];
        c[0] = measure q[0];
        c[1] = measure q[1];
        if (c[0] == 1) { x q[0]; }
    '''
    program = qasm_to_program(src)
    qchip = make_default_qchip(2)
    mp = compile_to_machine(program, qchip, n_qubits=2)
    out0 = simulate(mp, meas_bits=np.zeros((2, 4), int))
    out1 = simulate(mp, meas_bits=np.ones((2, 4), int))
    assert np.all(np.asarray(out0['err']) == 0)
    assert np.all(np.asarray(out1['err']) == 0)
    # measured-1 branch adds the two X90 flip pulses on core 0
    assert int(out1['n_pulses'][0]) == int(out0['n_pulses'][0]) + 2


def test_for_loop_lowers_to_hardware_loop():
    prog = qasm_to_program('''
        qubit[1] q;
        for uint i in [0:9] { sx q[0]; }
    ''')
    loop = next(i for i in prog if i['name'] == 'loop')
    assert loop['cond_lhs'] == 9 and loop['alu_cond'] == 'ge'
    assert loop['cond_rhs'] == 'i'
    incr = loop['body'][-1]
    assert incr == {'name': 'alu', 'op': 'add', 'lhs': 1, 'rhs': 'i',
                    'out': 'i'}
    # executes exactly 10 iterations on device
    import numpy as np
    from distributed_processor_tpu.simulator import Simulator
    sim = Simulator(n_qubits=1)
    out = sim.run(sim.compile(prog), shots=1, max_meas=1)
    assert not bool(out['incomplete'])
    assert np.all(np.asarray(out['err']) == 0)
    assert int(np.asarray(out['n_pulses'])[0]) == 10


def test_for_loop_step_and_empty_range():
    prog = qasm_to_program('''
        qubit[1] q;
        for int i in [10:-2:0] { sx q[0]; }
    ''')
    loop = next(i for i in prog if i['name'] == 'loop')
    # descending inclusive range [10:-2:0]: continue while var > 0,
    # i.e. -1 < var with the hardware's strict le (alu.v:25-27)
    assert loop['cond_lhs'] == -1 and loop['alu_cond'] == 'le'
    import pytest
    with pytest.raises(Exception, match='step must be nonzero'):
        qasm_to_program('qubit[1] q; for uint i in [0:0:5] { sx q[0]; }')


def test_while_loop_guard_and_body():
    prog = qasm_to_program('''
        qubit[1] q;
        int[32] n = 0;
        while (n < 3) { sx q[0]; n = n + 1; }
    ''')
    guard = prog[-1]
    assert guard['name'] == 'branch_var'
    assert guard['cond_lhs'] == 2 and guard['alu_cond'] == 'ge'
    assert guard['false'] == []
    loop = guard['true'][0]
    assert loop['name'] == 'loop' and loop['cond_rhs'] == 'n'
    # while (n < 3) with n starting at 3: body never runs
    import numpy as np
    from distributed_processor_tpu.simulator import Simulator
    sim = Simulator(n_qubits=1)
    prog0 = qasm_to_program('''
        qubit[1] q;
        int[32] n = 3;
        while (n < 3) { sx q[0]; n = n + 1; }
    ''')
    out = sim.run(sim.compile(prog0), shots=1, max_meas=1)
    assert int(np.asarray(out['n_pulses'])[0]) == 0
    # and starting at 0: exactly 3 iterations
    out = sim.run(sim.compile(prog), shots=1, max_meas=1)
    assert int(np.asarray(out['n_pulses'])[0]) == 3


def test_delay_statement():
    prog = qasm_to_program('''
        qubit[2] q;
        sx q[0];
        delay[500ns] q[0];
        sx q[0];
    ''')
    d = next(i for i in prog if i['name'] == 'delay')
    assert abs(d['t'] - 5e-7) < 1e-15 and d['qubit'] == ['Q0']
    # the delay shows up as a gap in scheduled pulse times
    import numpy as np
    from distributed_processor_tpu.simulator import Simulator
    sim = Simulator(n_qubits=2)
    out = sim.run(sim.compile(prog), shots=1, max_meas=1)
    gt = np.asarray(out['rec_gtime'])[0]
    # 500 ns = 250 clks at 2 ns/clk
    assert gt[1] - gt[0] >= 250


def test_for_loop_var_reuse_and_single_element_ranges():
    """Review regressions: sequential loops reusing a variable compile;
    single-element negative-step ranges are valid."""
    import numpy as np
    from distributed_processor_tpu.simulator import Simulator
    prog = qasm_to_program('''
        qubit[1] q;
        for uint i in [0:1] { sx q[0]; }
        for uint i in [0:2] { sx q[0]; }
        for int j in [3:-1:3] { sx q[0]; }
    ''')
    sim = Simulator(n_qubits=1)
    out = sim.run(sim.compile(prog), shots=1, max_meas=1)
    assert int(np.asarray(out['n_pulses'])[0]) == 2 + 3 + 1


def test_whole_register_delay_and_barrier():
    """`delay[...] q;` / `barrier q;` touch every qubit of the register,
    not just element 0 (review regression)."""
    prog = qasm_to_program('''
        qubit[2] q;
        barrier q;
        delay[100ns] q;
    ''')
    b = next(i for i in prog if i['name'] == 'barrier')
    d = next(i for i in prog if i['name'] == 'delay')
    assert b['qubit'] == ['Q0', 'Q1']
    assert d['qubit'] == ['Q0', 'Q1']


def test_nested_loop_var_shadowing():
    """QASM3 loop variables are loop-scoped: nested loops sharing a name
    must iterate independently (review regression)."""
    import numpy as np
    from distributed_processor_tpu.simulator import Simulator
    prog = qasm_to_program('''
        qubit[1] q;
        for uint i in [0:1] { for uint i in [0:1] { sx q[0]; } }
    ''')
    sim = Simulator(n_qubits=1)
    out = sim.run(sim.compile(prog), shots=1, max_meas=1)
    assert int(np.asarray(out['n_pulses'])[0]) == 4    # 2 outer x 2 inner
    # shadowing must not clobber an outer user variable
    prog2 = qasm_to_program('''
        qubit[1] q;
        int[32] n = 7;
        for uint n in [0:2] { sx q[0]; }
        if (n == 7) { sx q[0]; }
    ''')
    out2 = sim.run(sim.compile(prog2), shots=1, max_meas=1)
    assert int(np.asarray(out2['n_pulses'])[0]) == 3 + 1


def test_zero_trip_range_is_noop():
    prog = qasm_to_program('qubit[1] q; for uint i in [5:1] { sx q[0]; } sx q[0];')
    names = [i['name'] for i in prog]
    assert 'loop' not in names and names[-1] == 'X90'


def test_parser_rejects_bad_loop_syntax():
    import pytest
    with pytest.raises(QASMSyntaxError):
        parse_qasm('qubit[1] q; for uint 5 in [0:1] { sx q[0]; }')
    with pytest.raises(QASMSyntaxError, match='unsupported while'):
        parse_qasm('qubit[1] q; while (1 != 2) { sx q[0]; }')


def test_sequential_whiles_and_branchy_fors():
    """Review regression: sibling bodies flattened in separate recursive
    calls must not collide on generated jump labels."""
    import numpy as np
    from distributed_processor_tpu.simulator import Simulator
    sim = Simulator(n_qubits=1)
    prog = qasm_to_program('''
        qubit[1] q;
        int[32] n = 0;
        while (n < 2) { sx q[0]; n = n + 1; }
        int[32] m = 0;
        while (m < 3) { sx q[0]; m = m + 1; }
    ''')
    out = sim.run(sim.compile(prog), shots=1, max_meas=1)
    assert int(np.asarray(out['n_pulses'])[0]) == 2 + 3
    # two for-loops each containing an if: the bodies' branch labels
    # collided before the fix
    prog2 = qasm_to_program('''
        qubit[1] q;
        int[32] a = 1;
        for uint i in [0:1] { if (a == 1) { sx q[0]; } }
        for uint j in [0:2] { if (a == 1) { sx q[0]; } }
    ''')
    out2 = sim.run(sim.compile(prog2), shots=1, max_meas=1)
    assert int(np.asarray(out2['n_pulses'])[0]) == 2 + 3


def test_many_sequential_loops_share_one_register():
    """Review regression: 20 sequential loops reusing one name must not
    exhaust the 16-register file."""
    import numpy as np
    from distributed_processor_tpu.simulator import Simulator
    body = 'for uint i in [0:1] { sx q[0]; }\n' * 20
    prog = qasm_to_program('qubit[1] q;\n' + body)
    sim = Simulator(n_qubits=1)
    out = sim.run(sim.compile(prog), shots=1, max_meas=1)
    assert int(np.asarray(out['n_pulses'])[0]) == 40


def test_nested_sibling_loops_share_registers():
    """Review regression: same-name sibling loops nested under a
    shadowing loop reuse one minted register."""
    import numpy as np
    from distributed_processor_tpu.simulator import Simulator
    inner = 'for uint i in [0:1] { sx q[0]; }\n' * 18
    prog = qasm_to_program('qubit[1] q;\nfor uint i in [0:0] {\n'
                           + inner + '}')
    sim = Simulator(n_qubits=1)
    out = sim.run(sim.compile(prog), shots=1, max_meas=1)
    assert int(np.asarray(out['n_pulses'])[0]) == 36   # 18 inner x 2


def test_if_negative_constant_folds():
    """Negative literals parse as BinOp(0-n); the branch lowering must
    constant-fold them rather than materializing a register and then
    rejecting the <=/> fold (round-3 review finding)."""
    prog = qasm_to_program('''
        qubit[1] q;
        int[32] x = 1;
        if (x >= -5) { sx q[0]; }
    ''')
    br = next(i for i in prog if i['name'] == 'branch_var')
    # normalized to "-5 <= x" then folded strict: -6 < x
    assert br['cond_lhs'] == -6 and br['alu_cond'] == 'le'
    from distributed_processor_tpu.simulator import Simulator
    sim = Simulator(n_qubits=1)
    out = sim.run(sim.compile(prog), shots=1, max_meas=1)
    assert int(np.asarray(out['n_pulses'])[0]) == 1   # 1 >= -5: taken


def test_if_var_vs_var_le():
    """var-vs-var <= lowers by swapping operands with the flipped
    strict complement: a <= y == y >= a."""
    prog = qasm_to_program('''
        qubit[1] q;
        int[32] a = 2;
        int[32] y = 2;
        if (a <= y) { sx q[0]; }
    ''')
    br = next(i for i in prog if i['name'] == 'branch_var')
    assert br['alu_cond'] == 'ge' and br['cond_lhs'] == 'y' \
        and br['cond_rhs'] == 'a'
    from distributed_processor_tpu.simulator import Simulator
    sim = Simulator(n_qubits=1)
    out = sim.run(sim.compile(prog), shots=1, max_meas=1)
    assert int(np.asarray(out['n_pulses'])[0]) == 1   # 2 <= 2: taken


def test_int32_min_folds_raise_clearly():
    from distributed_processor_tpu.frontend.visitor import \
        QASMTranslationError
    with pytest.raises(QASMTranslationError, match='INT32_MIN'):
        qasm_to_program('qubit[1] q; int[32] n = 0; '
                        'while (n >= -2147483648) { sx q[0]; }')
    with pytest.raises(QASMTranslationError, match='INT32_MIN'):
        qasm_to_program('qubit[1] q; '
                        'for int i in [5:-1:-2147483648] { sx q[0]; }')
