"""OpenQASM 3 frontend tests: parser, gate mapping, translation, and
QASM -> compile -> simulate end-to-end."""

import numpy as np
import pytest

from distributed_processor_tpu.frontend import (qasm_to_program,
                                                DefaultGateMap)
from distributed_processor_tpu.frontend.qasm_parser import (parse_qasm,
                                                            QASMSyntaxError)
from distributed_processor_tpu.models import make_default_qchip
from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.sim import simulate


def test_parser_basics():
    stmts = parse_qasm('''
        OPENQASM 3;
        include "stdgates.inc";
        qubit[2] q;
        bit[2] c;
        h q[0];
        cx q[0], q[1];
        rz(pi/2) q[1];
        c[0] = measure q[0];
        // a comment
        reset q[1];
    ''')
    kinds = [type(s).__name__ for s in stmts]
    assert kinds == ['Decl', 'Decl', 'GateCall', 'GateCall', 'GateCall',
                     'Measure', 'Reset']


def test_parser_rejects_garbage():
    with pytest.raises(QASMSyntaxError):
        parse_qasm('qubit[2 q;')


def test_gate_map_decompositions():
    gm = DefaultGateMap()
    h = gm.get_qubic_gateinstr('h', ['Q0'], [])
    assert [i['name'] for i in h] == ['virtual_z', 'X90', 'virtual_z']
    x = gm.get_qubic_gateinstr('x', ['Q0'], [])
    assert [i['name'] for i in x] == ['X90', 'X90']
    rz = gm.get_qubic_gateinstr('rz', ['Q0'], [np.pi / 4])
    assert rz == [{'name': 'virtual_z', 'qubit': ['Q0'],
                   'phase': np.pi / 4}]
    cx = gm.get_qubic_gateinstr('cx', ['Q0', 'Q1'], [])
    assert cx == [{'name': 'CNOT', 'qubit': ['Q0', 'Q1']}]


def test_gate_map_unitaries():
    """Euler decompositions must reproduce the gate unitaries."""
    gm = DefaultGateMap()
    X90 = np.array([[1, -1j], [-1j, 1]]) / np.sqrt(2)

    def u_of(instrs):
        u = np.eye(2)
        for i in instrs:
            if i['name'] == 'X90':
                u = X90 @ u
            else:
                p = i['phase']
                u = np.diag([np.exp(-1j * p / 2), np.exp(1j * p / 2)]) @ u
        return u

    def proj_eq(a, b):
        return abs(abs(np.trace(a.conj().T @ b)) - 2) < 1e-9

    H = np.array([[1, 1], [1, -1]]) / np.sqrt(2)
    Y = np.array([[0, -1j], [1j, 0]])
    assert proj_eq(u_of(gm.get_qubic_gateinstr('h', ['Q0'], [])), H)
    assert proj_eq(u_of(gm.get_qubic_gateinstr('y', ['Q0'], [])), Y)
    theta = 1.23
    RX = np.array([[np.cos(theta / 2), -1j * np.sin(theta / 2)],
                   [-1j * np.sin(theta / 2), np.cos(theta / 2)]])
    assert proj_eq(u_of(gm.get_qubic_gateinstr('rx', ['Q0'], [theta])), RX)
    RY = np.array([[np.cos(theta / 2), -np.sin(theta / 2)],
                   [np.sin(theta / 2), np.cos(theta / 2)]])
    assert proj_eq(u_of(gm.get_qubic_gateinstr('ry', ['Q0'], [theta])), RY)


def test_reset_expands_to_active_reset():
    prog = qasm_to_program('qubit[1] q; reset q[0];')
    assert prog[0] == {'name': 'read', 'qubit': ['Q0']}
    assert prog[1]['name'] == 'branch_fproc'
    assert prog[1]['func_id'] == 'Q0.meas'
    assert [i['name'] for i in prog[1]['true']] == ['X90', 'X90']


def test_measure_feeds_branch():
    prog = qasm_to_program('''
        qubit[2] q;
        bit[1] c;
        c[0] = measure q[0];
        if (c[0] == 1) { x q[1]; }
    ''')
    assert prog[0] == {'name': 'read', 'qubit': ['Q0']}
    br = prog[1]
    assert br['name'] == 'branch_fproc' and br['func_id'] == 'Q0.meas'
    assert [i['name'] for i in br['true']] == ['X90', 'X90']
    assert br['false'] == []


def test_classical_arithmetic():
    prog = qasm_to_program('''
        qubit[1] q;
        int[32] a = 3;
        int[32] b;
        b = a + 2;
        if (b >= 5) { x q[0]; }
    ''')
    names = [i['name'] for i in prog]
    assert 'declare' in names and 'set_var' in names and 'alu' in names
    alu = next(i for i in prog if i['name'] == 'alu')
    assert alu['op'] == 'add' and alu['out'] == 'b'
    assert prog[-1]['name'] == 'branch_var'
    assert prog[-1]['cond_rhs'] == 'b'


def test_qasm_end_to_end_simulation():
    src = '''
        OPENQASM 3;
        qubit[2] q;
        bit[2] c;
        h q[0];
        cx q[0], q[1];
        barrier q[0], q[1];
        c[0] = measure q[0];
        c[1] = measure q[1];
        if (c[0] == 1) { x q[0]; }
    '''
    program = qasm_to_program(src)
    qchip = make_default_qchip(2)
    mp = compile_to_machine(program, qchip, n_qubits=2)
    out0 = simulate(mp, meas_bits=np.zeros((2, 4), int))
    out1 = simulate(mp, meas_bits=np.ones((2, 4), int))
    assert np.all(np.asarray(out0['err']) == 0)
    assert np.all(np.asarray(out1['err']) == 0)
    # measured-1 branch adds the two X90 flip pulses on core 0
    assert int(out1['n_pulses'][0]) == int(out0['n_pulses'][0]) + 2
