"""Worker process for the real 2-process multihost test (not collected
by pytest — launched by tests/test_multihost.py).

Each worker is one JAX controller: 4 virtual CPU devices, wired to its
peers via ``jax.distributed.initialize``, computing global sweep
statistics over a mesh spanning both processes.  Prints one JSON line.
"""

import json
import os
import sys

PID = int(sys.argv[1])
NPROC = int(sys.argv[2])
PORT = sys.argv[3]
LOCAL_DEVICES = 4

os.environ['JAX_PLATFORMS'] = 'cpu'
flags = [f for f in os.environ.get('XLA_FLAGS', '').split()
         if not f.startswith('--xla_force_host_platform_device_count')]
flags.append(f'--xla_force_host_platform_device_count={LOCAL_DEVICES}')
os.environ['XLA_FLAGS'] = ' '.join(flags)

import jax
jax.config.update('jax_platforms', 'cpu')

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
from distributed_processor_tpu.parallel.multihost import (
    initialize_multihost, make_global_mesh, host_local_batch,
    host_local_mesh, dp_row_offset, cross_host_sum)
from distributed_processor_tpu.parallel import (
    sweep_stat_sums, sharded_physics_stat_sums)
from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.models import active_reset, make_default_qchip
from distributed_processor_tpu.sim.interpreter import InterpreterConfig


def main():
    info = initialize_multihost(f'127.0.0.1:{PORT}', NPROC, PID)
    assert info['process_count'] == NPROC, info
    assert info['global_devices'] == NPROC * LOCAL_DEVICES, info

    mp = compile_to_machine(active_reset(['Q0']), make_default_qchip(2),
                            n_qubits=1)
    cfg = InterpreterConfig(max_steps=mp.n_instr + 8, max_pulses=8,
                            max_meas=2, max_resets=1)
    shots = 16
    rng = np.random.default_rng(7)            # same stream on every host
    bits = rng.integers(0, 2, size=(shots, mp.n_cores, cfg.max_meas))

    # the GLOBAL mesh carries topology (which dp rows are ours); the
    # COMPUTE runs on a host-local mesh — the CPU backend refuses
    # multiprocess jit computations, and a TPU pod would simply use
    # sweep_stats on the global mesh instead.  Exact integer partial
    # sums cross DCN through the coordination-service KV store in
    # deterministic process order, so both controllers (and the
    # single-process reference) agree bit-for-bit.
    mesh = make_global_mesh()
    local_shots, offset = host_local_batch(mesh, shots)
    lmesh = host_local_mesh()
    sums = cross_host_sum('sweep', sweep_stat_sums(
        mp, bits[offset:offset + local_shots], lmesh, cfg=cfg))
    stats = dict(mean_pulses=sums['pulse_sum'] / shots,
                 err_rate=sums['err_shots'] / shots,
                 mean_qclk=sums['qclk_sum'] / shots)

    # physics-closed execution across both controllers: every dp shard
    # runs its own epoch loop (synthesis -> demod -> branch resolution)
    # on local devices; dp_offset places this host's shards on the
    # global dp grid so per-shard noise keys match the single-process
    # dp=8 run, and only the final integer sum crosses DCN
    from distributed_processor_tpu.sim.physics import ReadoutPhysics
    psums = cross_host_sum('physics', sharded_physics_stat_sums(
        mp, ReadoutPhysics(sigma=0.01, p1_init=1.0), 3, local_shots,
        lmesh, dp_offset=dp_row_offset(mesh),
        max_steps=mp.n_instr * 4 + 64, max_pulses=8, max_meas=2))
    pstats = dict(mean_pulses=psums['pulse_sum'] / shots,
                  err_rate=psums['err_shots'] / shots,
                  meas1_rate=psums['meas1_sum'] / shots)

    print(json.dumps({
        'pid': PID,
        'info': info,
        'local_shots': local_shots,
        'offset': offset,
        'mean_pulses': np.asarray(stats['mean_pulses']).tolist(),
        'err_rate': float(stats['err_rate']),
        'mean_qclk': np.asarray(stats['mean_qclk']).tolist(),
        'phys_mean_pulses': np.asarray(pstats['mean_pulses']).tolist(),
        'phys_err_rate': float(pstats['err_rate']),
        'phys_meas1_rate': np.asarray(pstats['meas1_rate']).tolist(),
    }))


if __name__ == '__main__':
    main()
