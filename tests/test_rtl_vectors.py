"""RTL-derived timing vectors: both engines vs. hand-transcribed data.

Round-2 review, missing #1 (the shared-fate oracle risk): the JAX
interpreter and the scalar oracle are reimplementations by the same
author, so a shared misunderstanding would pass every engine-vs-oracle
test.  ``tests/goldens/rtl_timing_vectors.json`` transcribes the
reference cocotb testbench's *expected observables* (pulse strobe
positions, ALU results per hdl/alu.v including signed-comparison
boundary and overflow cases, branch targets, fproc availability times,
qclk arithmetic, sync release, idle holds) as DATA with per-case
provenance — this test runs BOTH engines against that data
independently, so a divergence in either engine alone is caught.

Transcribing the vectors caught a real one: both engines implemented
``le`` as ``<=`` while alu.v:25-27 computes strict signed ``<``
(``sub[31] ^ sub_oflow``) — fixed in round 3 and pinned here by the
``alu_table`` boundary rows and the ``jump_cond_*_boundary`` cases.
"""

import json
import os

import numpy as np
import pytest

from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import machine_program_from_cmds
from distributed_processor_tpu.sim import simulate, run_oracle

_PATH = os.path.join(os.path.dirname(__file__), 'goldens',
                     'rtl_timing_vectors.json')
with open(_PATH) as f:
    _VECTORS = json.load(f)

CSTROBE_DELAY = _VECTORS['cstrobe_delay']


def _fabric_kwargs(case) -> dict:
    kw = {}
    if 'fabric' in case:
        kw['fabric'] = case['fabric']
    if 'lut_mask' in case:
        kw['lut_mask'] = tuple(case['lut_mask'])
        kw['lut_table'] = tuple(case['lut_table'])
    return kw


def _build(case):
    cores = [[getattr(isa, ins['fn'])(**ins['kw']) for ins in core]
             for core in case['cores']]
    return machine_program_from_cmds(cores)


def _check_pulses(exp, pulses_per_core, label):
    """Shared pulse assertions from engine-neutral pulse dicts."""
    for c, want_n in enumerate(exp.get('n_pulses', [])):
        got = pulses_per_core[c]
        assert len(got) == want_n, (label, c, len(got), want_n)
    for field in ('qtime', 'gtime', 'freq', 'phase', 'amp', 'env'):
        for c, wants in enumerate(exp.get(field, [])):
            for p, want in enumerate(wants):
                assert pulses_per_core[c][p][field] == want, \
                    (label, field, c, p, pulses_per_core[c][p][field], want)
    # the RTL observation: cstrobe appears at qclk == qtime + the
    # documented 2-cycle strobe pipeline (cocotb test_proc.py:123)
    for c, strobes in enumerate(exp.get('strobe_qclk', [])):
        for p, strobe in enumerate(strobes):
            assert pulses_per_core[c][p]['qtime'] == strobe - CSTROBE_DELAY, \
                (label, 'strobe', c, p)


def _check_scalars(exp, out, label):
    for key in ('time', 'qclk'):
        for c, want in enumerate(exp.get(key, [])):
            assert int(np.asarray(out[key])[c]) == want, \
                (label, key, c, int(np.asarray(out[key])[c]), want)
    for c, want in enumerate(exp.get('done', [])):
        assert bool(np.asarray(out['done'])[c]) == want, (label, 'done', c)
    for c, want in enumerate(exp.get('err', [])):
        got = out['err'][c]
        got = len(got) if isinstance(got, list) else int(np.asarray(got))
        assert got == want, (label, 'err', c, got, want)
    for c, regs in enumerate(exp.get('regs', [])):
        for idx, want in regs.items():
            got = int(np.asarray(out['regs'])[c, int(idx)])
            assert got == want, (label, 'reg', c, idx, got, want)


@pytest.mark.parametrize('case', _VECTORS['cases'],
                         ids=[c['name'] for c in _VECTORS['cases']])
def test_jax_engine_matches_rtl_vectors(case):
    mp = _build(case)
    exp = case['expected']
    kw = _fabric_kwargs(case)
    meas = np.asarray(case['meas_bits'], np.int32) \
        if case.get('meas_bits') is not None else None
    out = simulate(mp, meas_bits=meas, max_meas=4, **kw)
    pulses = []
    for c in range(mp.n_cores):
        n = int(np.asarray(out['n_pulses'])[c])
        pulses.append([
            {f: int(np.asarray(out['rec_' + f])[c, p])
             for f in ('qtime', 'gtime', 'freq', 'phase', 'amp', 'env')}
            for p in range(n)])
    _check_pulses(exp, pulses, 'jax:' + case['name'])
    _check_scalars(exp, out, 'jax:' + case['name'])
    for c, wants in enumerate(exp.get('meas_avail', [])):
        got = [int(t) for t in np.asarray(out['meas_avail'])[c]
               if t != np.iinfo(np.int32).max]
        assert got == wants, ('jax', 'meas_avail', c, got, wants)
    for c, want in enumerate(exp.get('n_resets', [])):
        assert int(np.asarray(out['n_resets'])[c]) == want
    for c, wants in enumerate(exp.get('rst_time', [])):
        got = [int(t) for t in
               np.asarray(out['rst_time'])[c][:len(wants)]]
        assert got == wants, ('jax', 'rst_time', c)


@pytest.mark.parametrize('case', _VECTORS['cases'],
                         ids=[c['name'] for c in _VECTORS['cases']])
def test_oracle_matches_rtl_vectors(case):
    mp = _build(case)
    exp = case['expected']
    kw = _fabric_kwargs(case)
    meas = np.asarray(case['meas_bits']) \
        if case.get('meas_bits') is not None else None
    out = run_oracle(mp, meas_bits=meas, **kw)
    pulses = [[{f: int(p[f]) for f in
                ('qtime', 'gtime', 'freq', 'phase', 'amp', 'env')}
               for p in core] for core in out['pulses']]
    _check_pulses(exp, pulses, 'oracle:' + case['name'])
    _check_scalars(exp, out, 'oracle:' + case['name'])
    for c, wants in enumerate(exp.get('meas_avail', [])):
        assert [int(t) for t in out['meas_avail'][c]] == wants
    for c, want in enumerate(exp.get('n_resets', [])):
        assert len(out['resets'][c]) == want
    for c, wants in enumerate(exp.get('rst_time', [])):
        assert [int(t) for t in out['resets'][c][:len(wants)]] == wants
