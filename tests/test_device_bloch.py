"""SU(2) Bloch device co-state (sim/device.py, device='bloch').

The round-2 review's top item: with the Bloch model, the experiment
programs the repo ships (models/experiments, models/rb) are physically
meaningful *end-to-end through the closed loop* — drive phase words set
rotation axes (so virtual-z matters), scheduled delays dephase and
decay the qubit, measurement projects, and the fitters (analysis.py)
recover the injected device parameters from physics-closed sweeps.

Expectation-value tests read ``meas_p1`` (the pre-projection P(1)
recorded per measurement slot) with one shot and sigma=0 — exact and
fast; the sampled-bit path gets its own statistical test.
"""

import numpy as np
import pytest

from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.analysis import fit_ramsey, fit_rb, fit_t1, \
    fit_exp_decay
from distributed_processor_tpu.models.experiments import (
    active_reset, rabi_program, ramsey_program, t1_program, t2_echo_program)
from distributed_processor_tpu.models.rb import (clifford_table, rb_sequence,
                                                 clifford_instructions)
from distributed_processor_tpu.sim.device import DeviceModel
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)

KW = dict(max_steps=2000, max_pulses=128, max_meas=4)


@pytest.fixture(scope='module')
def sim1():
    return Simulator(n_qubits=1)


def _p1(sim, prog, model, shots=1, key=0, init=None, **kw):
    mp = sim.compile(prog)
    if init is None:
        init = np.zeros((shots, mp.n_cores), np.int32)
    out = run_physics_batch(mp, model, key, shots, init_states=init,
                            **KW, **kw)
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))
    return out


def test_rabi_amplitude_curve(sim1):
    """P(1) = sin^2(theta/2), theta = (pi/2) * amp / x90_amp — the
    continuous rotation the parity counter rounded away."""
    model = ReadoutPhysics(sigma=0.0, device=DeviceModel('bloch'))
    for amp in (0.0, 0.12, 0.24, 0.48, 0.72, 0.96):
        out = _p1(sim1, rabi_program('Q0', amp), model)
        theta = np.pi / 2 * amp / 0.48       # default qchip X90 amp 0.48
        np.testing.assert_allclose(np.asarray(out['meas_p1'])[0, 0, 0],
                                   np.sin(theta / 2) ** 2, atol=1e-5)


def test_clifford_sequences_match_unitaries(sim1):
    """Random virtual-Z Clifford sequences through the closed loop give
    P(1) = |<1|U|0>|^2 from the models/rb.py group table — this pins the
    phase-word/axis convention against the compiler's ResolveVirtualZ
    folding (a sign error here shifts fringes and breaks the table)."""
    triples, unis = clifford_table()
    rng = np.random.default_rng(0)
    model = ReadoutPhysics(sigma=0.0, device=DeviceModel('bloch'))
    for _ in range(5):
        seq = [int(rng.integers(24)) for _ in range(5)]
        prog, net = [], np.eye(2)
        for i in seq:
            prog += clifford_instructions('Q0', i)
            net = unis[i] @ net
        prog.append({'name': 'read', 'qubit': ['Q0']})
        out = _p1(sim1, prog, model)
        np.testing.assert_allclose(np.asarray(out['meas_p1'])[0, 0, 0],
                                   abs(net[1, 0]) ** 2, atol=1e-5)


def test_t1_decay_recovered(sim1):
    """Excited-state decay over scheduled delays: fit_t1 recovers the
    model's T1 from a physics-closed sweep."""
    model = ReadoutPhysics(sigma=0.0,
                           device=DeviceModel('bloch', t1_s=20e-6))
    delays = np.linspace(0.5e-6, 60e-6, 8)
    p1s = [float(np.asarray(
        _p1(sim1, t1_program('Q0', float(d)), model)['meas_p1'])[0, 0, 0])
        for d in delays]
    assert p1s[0] > 0.9 and p1s[-1] < 0.1     # it decays
    t1, _ = fit_t1(delays, np.asarray(p1s))
    np.testing.assert_allclose(t1, 20e-6, rtol=0.02)


def test_ramsey_fringes_at_programmed_detuning(sim1):
    """The review's 'done' criterion: a physics-closed Ramsey sweep
    shows fringes at the programmed detuning and fit_ramsey recovers
    it (plus T2*)."""
    model = ReadoutPhysics(
        sigma=0.0, device=DeviceModel('bloch', detuning_hz=0.7e6,
                                      t2_s=15e-6))
    delays = np.linspace(0, 8e-6, 33)
    p1s = [float(np.asarray(
        _p1(sim1, ramsey_program('Q0', float(d)), model)['meas_p1'])[0, 0, 0])
        for d in delays]
    assert max(p1s) > 0.9 and min(p1s) < 0.1  # full-contrast fringes
    f, t2s, _ = fit_ramsey(delays, np.asarray(p1s))
    np.testing.assert_allclose(f, 0.7e6, rtol=0.01)
    np.testing.assert_allclose(t2s, 15e-6, rtol=0.05)


def test_t2_echo_cancels_detuning(sim1):
    """Hahn echo refocuses static detuning: no fringes, pure exp(-t/T2)
    contrast decay — distinguishable from the Ramsey case above."""
    model = ReadoutPhysics(
        sigma=0.0, device=DeviceModel('bloch', detuning_hz=0.7e6,
                                      t2_s=10e-6))
    delays = np.linspace(0.2e-6, 30e-6, 8)
    p1s = np.asarray([float(np.asarray(
        _p1(sim1, t2_echo_program('Q0', float(d)), model)['meas_p1'])
        [0, 0, 0]) for d in delays])
    # X90-X180-X90 = identity at tau=0 (ends in |0>); T2 pulls P(1)
    # up toward 1/2 as (1 - exp(-t/T2))/2, no fringes
    a, tau, c = fit_exp_decay(delays, p1s)
    np.testing.assert_allclose(tau, 10e-6, rtol=0.05)
    np.testing.assert_allclose(c, 0.5, atol=0.03)


def test_rb_decay_recovers_depolarization(sim1):
    """RB survival decays with depth; fit_rb recovers the injected
    per-pulse depolarization (alpha = (1-p)^2: two pulses/Clifford)."""
    model = ReadoutPhysics(
        sigma=0.0, device=DeviceModel('bloch', depol_per_pulse=0.01))
    rng = np.random.default_rng(5)
    depths = [2, 4, 8, 16, 32]
    surv = []
    for d in depths:
        acc = []
        for _ in range(3):
            prog = []
            for i in rb_sequence(rng, d):
                prog += clifford_instructions('Q0', i)
            prog.append({'name': 'read', 'qubit': ['Q0']})
            out = _p1(sim1, prog, model)
            acc.append(1.0 - float(np.asarray(out['meas_p1'])[0, 0, 0]))
        surv.append(np.mean(acc))
    assert surv[0] > surv[-1] + 0.1           # it decays with depth
    alpha, epc, _ = fit_rb(depths, np.asarray(surv))
    np.testing.assert_allclose(alpha, (1 - 0.01) ** 2, atol=2e-3)


def test_projective_sampling_statistics(sim1):
    """The sampled-bit path: X90 then measure gives Bernoulli(1/2) bits
    whose mean matches P(1) within CLT bounds, deterministic per key."""
    model = ReadoutPhysics(sigma=0.01, device=DeviceModel('bloch'))
    prog = [{'name': 'X90', 'qubit': ['Q0']},
            {'name': 'read', 'qubit': ['Q0']}]
    B = 512
    out = _p1(sim1, prog, model, shots=B, key=3)
    bits = np.asarray(out['meas_bits'])[:, 0, 0]
    assert abs(bits.mean() - 0.5) < 4 * 0.5 / np.sqrt(B)
    out2 = _p1(sim1, prog, model, shots=B, key=3)
    np.testing.assert_array_equal(bits, np.asarray(out2['meas_bits'])[:, 0, 0])
    # and the recorded expectation is exactly 1/2 on every shot
    np.testing.assert_allclose(np.asarray(out['meas_p1'])[:, 0, 0], 0.5,
                               atol=1e-5)


def test_active_reset_bloch_closed_loop(sim1):
    """Feedback works on the collapsed state: active reset drives a
    thermal population to |0> (the conditional X180 sees the
    post-measurement pole, not the pre-measurement superposition)."""
    model = ReadoutPhysics(sigma=0.01, p1_init=0.5,
                           device=DeviceModel('bloch'))
    B = 64
    out = _p1(sim1, active_reset(['Q0']), model, shots=B, key=1,
              init=np.arange(B).reshape(B, 1) % 2)
    bloch = np.asarray(out['bloch'])          # [B, 1, 3]
    np.testing.assert_allclose(bloch[:, 0, 2], 1.0, atol=1e-5)
    # reset branch (2 extra pulses) ran exactly where the bit read 1
    bits = np.asarray(out['meas_bits'])[:, 0, 0]
    np.testing.assert_array_equal(np.asarray(out['n_pulses'])[:, 0],
                                  2 + 2 * bits)


def test_per_core_detuning_two_qubits():
    """Per-core parameters: two qubits Ramsey at different detunings in
    one physics-closed batch."""
    sim = Simulator(n_qubits=2)
    model = ReadoutPhysics(
        sigma=0.0, device=DeviceModel('bloch',
                                      detuning_hz=(0.3e6, 0.9e6)))
    delays = np.linspace(0, 8e-6, 17)
    ps = {0: [], 1: []}
    for d in delays:
        prog = ramsey_program('Q0', float(d)) + ramsey_program('Q1', float(d))
        out = _p1(sim, prog, model)
        for c in (0, 1):
            ps[c].append(float(np.asarray(out['meas_p1'])[0, c, 0]))
    f0, _, _ = fit_ramsey(delays, np.asarray(ps[0]))
    f1, _, _ = fit_ramsey(delays, np.asarray(ps[1]))
    np.testing.assert_allclose(f0, 0.3e6, rtol=0.02)
    np.testing.assert_allclose(f1, 0.9e6, rtol=0.02)


def test_device_kind_conflict_raises(sim1):
    from distributed_processor_tpu.sim.physics import physics_config
    from distributed_processor_tpu.sim.interpreter import InterpreterConfig
    with pytest.raises(ValueError, match='conflicting device'):
        physics_config(InterpreterConfig(device='bloch'), ReadoutPhysics())
    with pytest.raises(ValueError, match='unknown device kind'):
        DeviceModel('su3')
