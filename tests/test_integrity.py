"""End-to-end integrity fabric (integrity.py + its detection points).

The silent-data-corruption contract, pinned here (docs/ROBUSTNESS.md
"Integrity"):

* **Digests are content digests** — program/stat digests survive
  pickle round trips and memory-layout changes, and any single flipped
  bit changes them.
* **Every trust boundary detects** — a corrupted persistent-store
  entry is a counted miss (never a wrong program); a garbled peer
  spec costs exactly itself in a catalog merge; a flipped wire frame
  is a typed :class:`WireCorruptionError` + connection reset, never a
  hang or a silent unpickle of garbage.
* **The audit sampler never cries wolf and never misses** — clean
  traffic at ``audit_sample=1`` produces zero violations; injected
  corruption is flagged (flag mode), or failed-and-retried to a
  bit-correct result / a typed IntegrityError (strict mode).
* **The scrubber benches a corrupting device** — persistent canary
  mismatches route into the standard quarantine -> bit-checked canary
  re-admission lifecycle while traffic re-homes to healthy executors.
* **The fleet survives wire corruption** — a flipped frame between
  router and replica tears down, re-dials, retries, and still returns
  bit-identical results.

This module is listed in tools/check_junit.py NO_SKIP_MODULES: pure
CPU + localhost sockets, no legitimate skip condition.
"""

import json
import os
import pickle
import socket
import threading
import time
import zlib

import numpy as np
import pytest

import jax

from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import machine_program_from_cmds
from distributed_processor_tpu.integrity import (IntegrityError,
                                                 content_crc32,
                                                 diff_stats, flip_bit,
                                                 flip_payload_bit,
                                                 program_digest,
                                                 stats_digest)
from distributed_processor_tpu.serve import (BucketCatalog, ChaosMonkey,
                                             ChaosPlan,
                                             ExecutionService,
                                             FleetRouter, ReplicaClient,
                                             ReplicaLostError,
                                             RetryPolicy,
                                             WireCorruptionError)
from distributed_processor_tpu.serve import transport
from distributed_processor_tpu.serve.batcher import bucket_key
from distributed_processor_tpu.serve.service import _normalize_cfg
from distributed_processor_tpu.serve.transport import ReplicaServer
from distributed_processor_tpu.sim.interpreter import (InterpreterConfig,
                                                       simulate_batch)
from distributed_processor_tpu.utils import profiling

pytestmark = [pytest.mark.serve, pytest.mark.integrity]


def _mp(salt=0):
    core = [isa.pulse_cmd(amp_word=1000 + 7 * salt + 13 * i, cfg_word=0,
                          env_word=3, cmd_time=10 + 20 * i)
            for i in range(3)] + [isa.done_cmd()]
    return machine_program_from_cmds([core])


_CFG = InterpreterConfig(max_steps=2 * 8 + 64, max_pulses=8 + 2,
                         max_meas=2, max_resets=2)


def _bits(rng, shots=3):
    return rng.integers(0, 2, size=(shots, 1, 2)).astype(np.int32)


def _solo(mp, bits):
    ncfg, _ = _normalize_cfg(_CFG, isa.shape_bucket(mp.n_instr))
    return jax.tree.map(np.asarray, simulate_batch(mp, bits, cfg=ncfg))


def _assert_same(got, want, label=''):
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_array_equal(
            np.asarray(got[k]), np.asarray(want[k]),
            err_msg=f'{label}: stat {k!r} diverged')


def _corrupt_stats(stats, bit=3, index=1):
    """One flipped bit in the first integer stat — the injection model
    every detection test shares (chaos.py does the same)."""
    out = dict(stats)
    for k in sorted(out):
        a = np.asarray(out[k])
        if a.dtype.kind in 'iu' and a.size:
            out[k] = flip_bit(a, bit=bit, index=index)
            return out
    raise AssertionError('no integer stat to corrupt')


def _wait(pred, timeout=30.0, interval=0.01, msg='condition'):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f'timed out waiting for {msg}')


# ---------------------------------------------------------------------------
# digests
# ---------------------------------------------------------------------------

def test_program_digest_content_not_identity():
    """Same content -> same digest (across a pickle round trip and a
    non-contiguous view); one flipped SoA bit -> different digest."""
    mp = _mp(1)
    d = program_digest(mp)
    assert d == program_digest(mp)
    assert d == program_digest(pickle.loads(pickle.dumps(mp)))
    assert d != program_digest(_mp(2))

    mutated = pickle.loads(pickle.dumps(mp))
    f = next(f.name for f in
             type(mutated.soa).__dataclass_fields__.values()
             if np.asarray(getattr(mutated.soa, f.name)).size)
    object.__setattr__(
        mutated.soa, f,
        flip_bit(np.asarray(getattr(mutated.soa, f)), bit=0, index=0))
    assert program_digest(mutated) != d


def test_stats_digest_order_independent_and_bit_sensitive():
    rng = np.random.default_rng(0)
    stats = {'meas': rng.integers(0, 2, (3, 1, 2)).astype(np.int32),
             'fault': np.zeros((3,), np.int32)}
    d = stats_digest(stats)
    assert d == stats_digest(dict(reversed(list(stats.items()))))
    bad = _corrupt_stats(stats)
    assert stats_digest(bad) != d
    assert diff_stats(bad, stats) and not diff_stats(stats, stats)


def test_flip_bit_contract():
    a = np.arange(6, dtype=np.int32).reshape(2, 3)
    b = flip_bit(a, bit=4, index=7)            # index wraps mod size
    assert b.shape == a.shape
    assert int(np.sum(a != b)) == 1
    assert int(a.reshape(-1)[1]) ^ int(b.reshape(-1)[1]) == 16
    with pytest.raises(ValueError):
        flip_bit(np.zeros(3, np.float32))
    with pytest.raises(ValueError):
        flip_bit(np.zeros(0, np.int32))
    data = b'integrity'
    flipped = flip_payload_bit(data, bit_index=11)
    assert len(flipped) == len(data) and flipped != data
    assert content_crc32((flipped,)) != content_crc32((data,))


# ---------------------------------------------------------------------------
# persistent store + catalog trust boundaries
# ---------------------------------------------------------------------------

def test_store_digest_mismatch_is_counted_miss(tmp_path):
    """A store entry whose program bytes mutated AFTER the entry was
    written (the rsync'd/shared-warm-tier threat) is a miss that bumps
    ``integrity.store_digest_fail`` and removes the entry — never a
    silently wrong MachineProgram."""
    from distributed_processor_tpu.compilecache.store import \
        PersistentStore
    store = PersistentStore(str(tmp_path))
    mp = _mp(3)
    store.save('k1', 'f' * 16, mp)
    loaded = store.load('k1', 'f' * 16)
    assert loaded is not None
    assert program_digest(loaded) == program_digest(mp)

    fname = store._fname('k1', 'f' * 16)
    with open(fname, 'rb') as f:
        payload = pickle.loads(zlib.decompress(f.read()))
    payload['mp_pickle'] = flip_payload_bit(payload['mp_pickle'],
                                            bit_index=321)
    with open(fname, 'wb') as f:
        f.write(zlib.compress(pickle.dumps(payload)))

    before = profiling.counter_get('integrity.store_digest_fail')
    assert store.load('k1', 'f' * 16) is None
    assert profiling.counter_get(
        'integrity.store_digest_fail') == before + 1
    assert not os.path.exists(fname)     # dropped, rewrite starts clean


def test_catalog_merge_drops_garbled_peer_specs(tmp_path):
    """A peer that wrote garbled spec entries into the shared catalog
    costs exactly those entries — counted under ``catalog.merge_drops``
    — while every valid spec still merges."""
    path = str(tmp_path / 'catalog.json')
    mp = _mp(4)
    ncfg, _ = _normalize_cfg(_CFG, isa.shape_bucket(mp.n_instr))
    spec = bucket_key(mp, ncfg).bind(n_programs=2, n_shots=4)
    BucketCatalog(path).record(spec)

    with open(path, 'r', encoding='utf-8') as f:
        doc = json.load(f)
    skewed = dict(doc['specs'][0], version=999)
    doc['specs'] = [{'not': 'a spec'}, skewed,
                    doc['specs'][0], 'garbage']
    with open(path, 'w', encoding='utf-8') as f:
        json.dump(doc, f)

    before = profiling.counter_get('catalog.merge_drops')
    survivors = BucketCatalog(path).load()
    assert survivors == [spec]
    assert profiling.counter_get('catalog.merge_drops') == before + 3


# ---------------------------------------------------------------------------
# wire checksums
# ---------------------------------------------------------------------------

def test_recv_frame_oversize_header_is_typed():
    """A length prefix past the wire bound (corrupt header / desynced
    stream) raises WireCorruptionError instead of attempting a giant
    allocation-then-hang read."""
    a, b = socket.socketpair()
    try:
        a.sendall(transport._HDR.pack(transport._MAX_FRAME + 1, 0))
        before = profiling.counter_get('integrity.wire_checksum_fail')
        with pytest.raises(WireCorruptionError):
            transport.recv_frame(b)
        assert profiling.counter_get(
            'integrity.wire_checksum_fail') == before + 1
        assert isinstance(WireCorruptionError('x'), ConnectionError)
    finally:
        a.close()
        b.close()


def test_recv_frame_truncation_is_typed():
    """A frame cut off mid-payload (peer died) is a plain
    ConnectionError — distinguishable from corruption, never a hang."""
    a, b = socket.socketpair()
    try:
        data = pickle.dumps((1, 'ping', {}))
        a.sendall(transport._HDR.pack(len(data), zlib.crc32(data))
                  + data[:3])
        a.close()
        with pytest.raises(ConnectionError):
            transport.recv_frame(b)
    finally:
        b.close()


def test_server_resets_connection_on_flipped_frame():
    """A raw socket sending a CRC-stamped frame with one payload bit
    flipped: the server must detect (counter) and reset the connection
    — never unpickle the garbled bytes, never answer, never hang."""
    svc = ExecutionService(_CFG, max_batch_programs=2, max_wait_ms=1.0)
    srv = ReplicaServer(svc)
    try:
        data = pickle.dumps((1, 'gossip', {}))
        frame = transport._HDR.pack(len(data), zlib.crc32(data)) \
            + flip_payload_bit(data, bit_index=99)
        before = profiling.counter_get('integrity.wire_checksum_fail')
        with socket.create_connection(srv.address, timeout=10) as s:
            s.sendall(frame)
            s.settimeout(10)
            assert s.recv(4096) == b''       # reset, not a reply
        _wait(lambda: profiling.counter_get(
            'integrity.wire_checksum_fail') >= before + 1,
            msg='wire_checksum_fail counter')
    finally:
        srv.close()
        svc.shutdown()


def test_client_recv_corruption_is_replica_lost():
    """With the chaos corruptor flipping every received frame, a
    client call fails typed (ReplicaLostError after the connection
    reset) — the corrupted reply never reaches the caller."""
    svc = ExecutionService(_CFG, max_batch_programs=2, max_wait_ms=1.0)
    srv = ReplicaServer(svc)
    client = None
    prev = transport.install_wire_corruptor(
        lambda data: flip_payload_bit(data, bit_index=17))
    try:
        # the corruptor is process-global: the server garbles the
        # request frame, or the client garbles the reply — either
        # boundary must surface the same typed loss
        with pytest.raises((ReplicaLostError, WireCorruptionError)):
            client = ReplicaClient(srv.address)
            client.call('gossip', {}, timeout_s=30.0)
    finally:
        transport.install_wire_corruptor(prev)
        if client is not None:
            client.close()
        srv.close()
        svc.shutdown()


# ---------------------------------------------------------------------------
# audit sampler
# ---------------------------------------------------------------------------

def test_audit_clean_traffic_zero_false_positives():
    """audit_sample=1 on clean traffic: audits happen, zero
    mismatches, zero integrity_violation events — the auditor must
    never cry wolf (cross-engine fault-code skew is escalated to a
    confirm run, not flagged)."""
    rng = np.random.default_rng(7)
    with ExecutionService(_CFG, max_batch_programs=4, max_wait_ms=2.0,
                          audit_sample=1.0) as svc:
        handles = [(svc.submit(_mp(s), _bits(rng)), s)
                   for s in range(4)]
        for h, s in handles:
            h.result(timeout=120)
        st = svc.stats()
        assert st['integrity']['audit_sample'] == 1.0
        assert st['integrity']['audits'] >= 1
        assert st['integrity']['mismatches'] == 0
        assert all(not d['integrity_bad'] for d in st['devices'])
        assert svc.flight_recorder.counts().get(
            'integrity_violation', 0) == 0


def test_audit_flag_mode_detects_and_edge_triggers():
    """Flag mode: corrupted results are flagged (counter + ONE
    edge-triggered integrity_violation event while bad persists) but
    still delivered; a clean audit clears the executor's bad bit."""
    rng = np.random.default_rng(8)
    corrupting = [True]
    with ExecutionService(_CFG, max_batch_programs=1, max_wait_ms=1.0,
                          audit_sample=1.0, audit_mode='flag') as svc:
        orig = svc._run_batch

        def run_batch(ex, key, batch, cfg):
            results = orig(ex, key, batch, cfg)
            if corrupting[0]:
                return [_corrupt_stats(r) for r in results]
            return results

        svc._run_batch = run_batch
        before = profiling.counter_get('integrity.mismatches')
        mp, bits = _mp(5), _bits(rng)
        got = svc.submit(mp, bits).result(timeout=120)
        # delivered-but-flagged: the tainted bits DID reach the caller
        assert diff_stats(got, _solo(mp, bits))
        svc.submit(_mp(6), _bits(rng)).result(timeout=120)
        st = svc.stats()
        assert st['integrity']['mismatches'] >= 2
        assert profiling.counter_get('integrity.mismatches') >= before + 2
        assert any(d['integrity_bad'] for d in st['devices'])
        # edge-triggered: two bad audits, ONE violation event
        assert svc.flight_recorder.counts()['integrity_violation'] == 1

        corrupting[0] = False
        mp2, bits2 = _mp(7), _bits(rng)
        _assert_same(svc.submit(mp2, bits2).result(timeout=120),
                     _solo(mp2, bits2), 'clean after flag')
        assert all(not d['integrity_bad']
                   for d in svc.stats()['devices'])


def test_audit_strict_mode_fails_typed_never_delivers():
    """Strict mode with every attempt corrupted: the handle must fail
    with IntegrityError once retries exhaust — tainted bits are never
    delivered."""
    rng = np.random.default_rng(9)
    with ExecutionService(
            _CFG, max_batch_programs=1, max_wait_ms=1.0,
            audit_sample=1.0, audit_mode='strict',
            breaker_threshold=10,
            retry_policy=RetryPolicy(max_attempts=2,
                                     backoff_s=0.001)) as svc:
        orig = svc._run_batch
        svc._run_batch = lambda ex, key, batch, cfg: [
            _corrupt_stats(r) for r in orig(ex, key, batch, cfg)]
        h = svc.submit(_mp(10), _bits(rng))
        with pytest.raises(IntegrityError):
            h.result(timeout=120)
        st = svc.stats()
        assert st['integrity']['mismatches'] >= 2     # original + retry
        assert st['retry_exhausted'] >= 1


def test_audit_strict_mode_retries_to_correct_bits():
    """Strict mode with a single corrupted attempt: the request is
    failed internally, retried, and completes bit-identical to the
    solo run — detected corruption costs one retry, never wrong
    bits."""
    rng = np.random.default_rng(10)
    fired = []
    with ExecutionService(
            _CFG, max_batch_programs=1, max_wait_ms=1.0,
            audit_sample=1.0, audit_mode='strict',
            breaker_threshold=10,
            retry_policy=RetryPolicy(max_attempts=4,
                                     backoff_s=0.001)) as svc:
        orig = svc._run_batch

        def run_batch(ex, key, batch, cfg):
            results = orig(ex, key, batch, cfg)
            if not fired:
                fired.append(True)
                return [_corrupt_stats(r) for r in results]
            return results

        svc._run_batch = run_batch
        mp, bits = _mp(11), _bits(rng)
        got = svc.submit(mp, bits).result(timeout=120)
        assert fired
        _assert_same(got, _solo(mp, bits), 'strict retry')
        st = svc.stats()
        assert st['integrity']['mismatches'] >= 1
        assert st['retries'] >= 1


def test_chaos_corrupt_outcome_is_never_silent():
    """The ChaosMonkey 'corrupt' outcome under a strict auditor: every
    injected flip is detected — the handle either completes
    bit-identically (a retry drew 'ok') or fails with a typed
    IntegrityError.  Silently wrong bits are the one impossible
    outcome."""
    rng = np.random.default_rng(11)
    plan = ChaosPlan(seed=11, p_corrupt=1.0)
    with ExecutionService(
            _CFG, max_batch_programs=1, max_wait_ms=1.0,
            audit_sample=1.0, audit_mode='strict',
            breaker_threshold=10,
            retry_policy=RetryPolicy(max_attempts=2,
                                     backoff_s=0.001)) as svc:
        with ChaosMonkey(svc, plan) as monkey:
            mp, bits = _mp(12), _bits(rng)
            h = svc.submit(mp, bits)
            try:
                got = h.result(timeout=120)
            except IntegrityError:
                got = None
            assert monkey.injected['corrupt'] >= 1
            if got is not None:
                _assert_same(got, _solo(mp, bits), 'chaos corrupt')
        assert svc.stats()['integrity']['mismatches'] >= 1


# ---------------------------------------------------------------------------
# background scrubber -> quarantine -> re-admission
# ---------------------------------------------------------------------------

def test_scrubber_quarantines_corrupting_executor_and_readmits():
    """Acceptance: a device that starts silently corrupting is caught
    by the scrubber WITHOUT tenant traffic, quarantined through the
    breaker, traffic re-homes to the healthy executor, and the device
    is re-admitted through the bit-checked canary once it stops
    corrupting."""
    rng = np.random.default_rng(12)
    with ExecutionService(
            _CFG, max_batch_programs=2, max_wait_ms=1.0, devices=2,
            scrub_interval_s=0.03, breaker_threshold=2,
            breaker_cooldown_ms=50.0, supervise_interval_ms=10.0,
            retry_policy=RetryPolicy(max_attempts=4,
                                     backoff_s=0.01)) as svc:
        # golden canary reference must exist before corruption starts
        _wait(lambda: svc._canary_ref is not None,
              msg='scrubber golden reference')
        orig = svc._run_batch

        def run_batch(ex, key, batch, cfg):
            results = orig(ex, key, batch, cfg)
            if ex.idx == 0:
                return [_corrupt_stats(r) for r in results]
            return results

        svc._run_batch = run_batch
        _wait(lambda: svc.stats()['integrity']['quarantines'] >= 1,
              msg='scrubber quarantine')
        st = svc.stats()
        assert st['health']['quarantined'] >= 1
        assert st['integrity']['scrubber_fail'] >= 2   # threshold runs
        assert svc.flight_recorder.counts()['scrubber_fail'] >= 2

        # traffic re-homes to the healthy executor, bit-identical
        mp, bits = _mp(13), _bits(rng)
        _assert_same(svc.submit(mp, bits).result(timeout=120),
                     _solo(mp, bits), 'quarantined pool')

        # corruption stops -> canary re-admission restores the pool
        svc._run_batch = orig
        _wait(lambda: svc.stats()['health']['live'] == 2,
              msg='canary re-admission')
        assert svc.stats()['readmissions'] >= 1


# ---------------------------------------------------------------------------
# fleet: digests + frame CRCs end to end
# ---------------------------------------------------------------------------

def test_fleet_wire_corruption_detected_and_retried():
    """FleetRouter(integrity=True) against an in-process replica: a
    clean submit round-trips program + result digests; one flipped
    frame is detected (CRC), the connection torn down and re-dialed on
    the gossip cadence, and the request retried to a bit-identical
    result — corruption costs latency, never wrong bits."""
    svc = ExecutionService(_CFG, max_batch_programs=2, max_wait_ms=1.0)
    srv = ReplicaServer(svc)
    rng = np.random.default_rng(13)
    mp, bits = _mp(14), _bits(rng)
    want = _solo(mp, bits)
    prev = None
    fired = []
    try:
        with FleetRouter(
                gossip_interval_ms=50.0, liveness_window_ms=300.0,
                integrity=True,
                retry_policy=RetryPolicy(max_attempts=8,
                                         backoff_s=0.05)) as router:
            router.add_replica('r0', srv.address)
            _assert_same(router.submit(mp, bits, cfg=_CFG)
                         .result(timeout=120), want, 'clean fleet')

            def one_shot(data):
                # burn the single flip on a payload-sized frame (the
                # submit or its result), not a gossip heartbeat
                if not fired and len(data) > 512:
                    fired.append(True)
                    return flip_payload_bit(data, bit_index=41)
                return data

            prev = transport.install_wire_corruptor(one_shot)
            try:
                got = router.submit(mp, bits, cfg=_CFG) \
                    .result(timeout=120)
            finally:
                transport.install_wire_corruptor(prev)
                prev = None
            assert fired, 'corruptor never fired'
            _assert_same(got, want, 'post-corruption retry')
            # the torn connection is re-dialed on the gossip cadence
            _wait(lambda: router.stats()['replica_up'] >= 2,
                  msg='gossip-cadence reconnect')
            _assert_same(router.submit(mp, bits, cfg=_CFG)
                         .result(timeout=120), want, 'post-reconnect')
    finally:
        if prev is not None:
            transport.install_wire_corruptor(prev)
        srv.close()
        svc.shutdown()
