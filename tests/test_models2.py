"""Repetition-code LUT round, GHZ program, multihost helpers."""

import numpy as np
import pytest

from distributed_processor_tpu.models import (
    repetition_round_machine_program, repetition_config, majority_lut,
    corrected_counts, ghz_program, make_default_qchip)
from distributed_processor_tpu.sim import simulate, simulate_batch
from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.parallel import (
    initialize_multihost, make_global_mesh, host_local_batch,
    global_shot_array)


def test_majority_lut_distance3():
    table = majority_lut(3)
    assert table[0b000] == 0          # no error
    assert table[0b001] == 0b001      # single flip corrected
    assert table[0b010] == 0b010
    assert table[0b110] == 0b001      # minority bit 0 corrected
    assert table[0b111] == 0


def test_repetition_round_corrections():
    n = 3
    mp = repetition_round_machine_program(n)
    cfg = repetition_config(n)
    for pattern in range(8):
        bits = np.array([[(pattern >> i) & 1] for i in range(n)])
        out = simulate(mp, meas_bits=bits, cfg=cfg)
        assert np.all(np.asarray(out['err']) == 0), pattern
        want = majority_lut(n)[pattern]
        got = list(corrected_counts(out, n))
        assert got == [(want >> i) & 1 for i in range(n)], pattern


def test_repetition_round_batched_random_errors():
    n, shots = 3, 64
    mp = repetition_round_machine_program(n)
    cfg = repetition_config(n)
    rng = np.random.default_rng(0)
    bits = rng.integers(0, 2, (shots, n, 1))
    out = simulate_batch(mp, bits, cfg=cfg)
    assert np.all(np.asarray(out['err']) == 0)
    table = majority_lut(n)
    counts = corrected_counts(out, n)
    for s in range(shots):
        addr = sum(int(bits[s, i, 0]) << i for i in range(n))
        want = [(table[addr] >> i) & 1 for i in range(n)]
        assert list(counts[s]) == want


def test_ghz_program_compiles_and_runs():
    qubits = ['Q0', 'Q1', 'Q2']
    qchip = make_default_qchip(3)
    mp = compile_to_machine(ghz_program(qubits), qchip, n_qubits=3)
    out = simulate(mp)
    assert np.all(np.asarray(out['err']) == 0)
    assert np.all(np.asarray(out['done']))
    # every core reads out (rdlo pulse present)
    for c in range(3):
        n = int(out['n_pulses'][c])
        assert 2 in np.asarray(out['rec_elem'][c, :n])


def test_multihost_single_process_helpers():
    info = initialize_multihost()
    assert info['process_count'] == 1
    mesh = make_global_mesh(n_mp=2)
    assert mesh.axis_names == ('dp', 'mp')
    local, offset = host_local_batch(mesh, 16)
    assert local == 16 and offset == 0
    arr = global_shot_array(mesh, np.arange(16 * 3).reshape(16, 3),
                            (16, 3))
    assert arr.shape == (16, 3)
    with pytest.raises(ValueError):
        host_local_batch(mesh, 15)


def test_wide_core_axis_32_qubits():
    """Scale sanity on the core axis: a 32-qubit program with sync
    barriers and physics-closed active reset compiles and executes with
    every lane correct — most tests run 2 or 8 cores; this pins the
    wide-MIMD shape (one lane per qubit core, reference: one proc per
    qubit)."""
    import numpy as np
    from distributed_processor_tpu.simulator import Simulator
    from distributed_processor_tpu.models.experiments import active_reset
    from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                       run_physics_batch)
    n = 32
    qubits = [f'Q{i}' for i in range(n)]
    sim = Simulator(n_qubits=n)
    mp = sim.compile(active_reset(qubits))
    assert mp.n_cores == n
    rng = np.random.default_rng(0)
    init = rng.integers(0, 2, (4, n)).astype(np.int32)
    out = run_physics_batch(mp, ReadoutPhysics(sigma=0.01), 0, 4,
                            init_states=init,
                            max_steps=mp.n_instr * 4 + 64,
                            max_pulses=8, max_meas=2)
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))
    np.testing.assert_array_equal(np.asarray(out['meas_bits'])[:, :, 0],
                                  init)
    np.testing.assert_array_equal(np.asarray(out['n_pulses']),
                                  2 + 2 * init)
    np.testing.assert_array_equal(np.asarray(out['qturns']) % 4 // 2, 0)
