"""DSP ops tests: waveform synthesis, demod, discrimination, meas LUT."""

import numpy as np
import jax.numpy as jnp
import pytest

from distributed_processor_tpu.ops import (
    synthesize_element, pulse_window_weights, demod_iq, demod_iq_pallas,
    discriminate, demod_and_discriminate, MeasLUT, stack_window_weights,
    iq_to_complex)
from distributed_processor_tpu.elements import ENV_CW_SENTINEL


def _rec(pulses, max_p=8):
    """Build a pulse-record dict from a list of pulse dicts."""
    fields = ('gtime', 'env', 'phase', 'freq_rel', 'amp', 'elem')
    rec = {f: np.zeros(max_p, dtype=np.float32 if f == 'freq_rel' else np.int32)
           for f in fields}
    for i, p in enumerate(pulses):
        for f in fields:
            rec[f][i] = p.get(f, 0)
    rec['n_pulses'] = np.int32(len(pulses))
    return {k: jnp.asarray(v) for k, v in rec.items()}


def test_synthesize_single_pulse_window():
    env = np.ones(8, complex) * 0.5
    rec = _rec([dict(gtime=2, env=(2 << 12) | 0, phase=0, freq_rel=0.0,
                     amp=0xffff, elem=0)])
    out = iq_to_complex(synthesize_element(rec, env, spc=4, interp=1, n_clks=8))
    # pulse spans DAC samples [8, 16); amp 1.0 * env 0.5, DC carrier
    assert np.allclose(out[:8], 0)
    assert np.allclose(out[8:16], 0.5, atol=1e-6)
    assert np.allclose(out[16:], 0)


def test_synthesize_carrier_phase_coherence():
    env = np.ones(16, complex)
    freq_rel = 0.125   # freq = fsamp/8 -> period 8 samples
    rec = _rec([dict(gtime=0, env=(4 << 12) | 0, phase=0, freq_rel=freq_rel,
                     amp=0xffff, elem=0)])
    out = iq_to_complex(synthesize_element(rec, env, spc=4, interp=1, n_clks=4))
    n = np.arange(16)
    np.testing.assert_allclose(out, np.exp(2j * np.pi * freq_rel * n),
                               atol=1e-5)
    # phase word rotates the carrier: pi/2 = 2^15 counts of 2^17
    rec2 = _rec([dict(gtime=0, env=(4 << 12) | 0, phase=1 << 15,
                      freq_rel=freq_rel, amp=0xffff, elem=0)])
    out2 = iq_to_complex(synthesize_element(rec2, env, spc=4, interp=1, n_clks=4))
    np.testing.assert_allclose(out2, out * 1j, atol=1e-5)


def test_synthesize_cw_holds_until_next_pulse():
    env = np.concatenate([np.ones(4), 0.25 * np.ones(4)]).astype(complex)
    rec = _rec([
        dict(gtime=0, env=(ENV_CW_SENTINEL << 12) | 0, phase=0, freq_rel=0.0,
             amp=0xffff, elem=0),
        dict(gtime=4, env=(1 << 12) | 1, phase=0, freq_rel=0.0,
             amp=0xffff, elem=0),
    ])
    out = iq_to_complex(synthesize_element(rec, env, spc=4, interp=1, n_clks=8))
    assert np.allclose(out[:16], 1.0)          # CW holds env[0]
    assert np.allclose(out[16:20], 0.25)       # next pulse takes over
    assert np.allclose(out[20:], 0)


def test_synthesize_interp_ratio():
    env = np.array([1.0, -1.0], complex)
    rec = _rec([dict(gtime=0, env=(1 << 12) | 0, phase=0, freq_rel=0.0,
                     amp=0xffff, elem=0)])
    # interp 2: each env sample covers 2 DAC samples; 4 env slots * 2 = 8
    out = iq_to_complex(synthesize_element(rec, env, spc=4, interp=2, n_clks=4))
    assert np.allclose(out[0:2], 1.0) and np.allclose(out[2:4], -1.0)


def test_demod_matched_filter():
    fsamp, fr = 2e9, 0.125   # integer cycles over the window (no leakage)
    spc, n_clks = 4, 16
    n = np.arange(n_clks * spc)
    adc = np.real(0.7 * np.exp(2j * np.pi * fr * n))[None, :]
    w = pulse_window_weights(0, n_clks, spc, fr * fsamp, fsamp)
    iq = iq_to_complex(demod_iq(adc, w))
    # matched filter: I accumulates 0.7 * N/2
    assert abs(iq[0, 0].real - 0.7 * len(n) / 2) < 1e-2
    # orthogonal frequency demods to ~0
    w2 = pulse_window_weights(0, n_clks, spc, 0.25 * fsamp, fsamp)
    iq2 = iq_to_complex(demod_iq(adc, w2))
    assert abs(iq2[0, 0]) < 1e-3 * len(n)


def test_demod_pallas_matches_reference():
    rng = np.random.default_rng(0)
    adc = rng.standard_normal((37, 64)).astype(np.float32)
    w = rng.standard_normal((64, 6)).astype(np.float32)
    ref = np.asarray(demod_iq(adc, w))
    got = np.asarray(demod_iq_pallas(adc, w, block_s=16, interpret=True))
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_discriminate_centroids():
    c0, c1 = np.array([0 + 0j]), np.array([2 + 2j])
    iq = np.array([[[0.1, 0.1]], [[1.9, 1.8]], [[0.9, 1.2]]])
    bits = np.asarray(discriminate(iq, c0, c1))
    assert list(bits[:, 0]) == [0, 1, 1]


def test_readout_chain_fidelity():
    # BASELINE config 2 shape: synthesize readout tones for states 0/1 with
    # noise, demod, threshold; fidelity must be high at good SNR
    rng = np.random.default_rng(1)
    fsamp, fr = 2e9, 0.05
    spc, n_clks = 4, 64
    N = n_clks * spc
    n = np.arange(N)
    shots = 512
    states = rng.integers(0, 2, shots)
    # state-dependent phase shift of the readout tone
    phase = np.where(states, np.pi / 2, 0.0)
    adc = np.real(np.exp(2j * np.pi * fr * n[None, :] + 1j * phase[:, None]))
    adc = (adc + 0.5 * rng.standard_normal((shots, N))).astype(np.float32)
    w = stack_window_weights([pulse_window_weights(0, n_clks, spc,
                                                   fr * fsamp, fsamp)], N)
    c0 = np.array([N / 2 + 0j])
    c1 = np.array([(N / 2) * np.exp(1j * np.pi / 2)])
    bits, iq = demod_and_discriminate(adc, w, c0, c1)
    fidelity = np.mean(np.asarray(bits)[:, 0] == states)
    assert fidelity > 0.99


def test_meas_lut_parity():
    # 3-input parity LUT distributing to 5 cores (meas_lut.sv geometry)
    mask = [True, True, True, False, False]
    table = np.zeros(8, dtype=np.int32)
    for a in range(8):
        par = bin(a).count('1') & 1
        table[a] = 0b11111 if par else 0
    lut = MeasLUT(mask, table)
    bits = np.array([[1, 0, 0, 1, 1],
                     [1, 1, 0, 0, 0],
                     [1, 1, 1, 0, 1]])
    out = np.asarray(lut(bits))
    np.testing.assert_array_equal(out[0], [1] * 5)   # parity 1
    np.testing.assert_array_equal(out[1], [0] * 5)   # parity 0
    np.testing.assert_array_equal(out[2], [1] * 5)   # parity 1
    assert int(lut.address(np.array([1, 0, 1, 1, 1]))) == 0b101


def test_stack_window_weights_offsets():
    w1 = np.ones((4, 2), np.float32)
    w2 = 2 * np.ones((4, 2), np.float32)
    W = stack_window_weights([w1, w2], 12, starts=[0, 8])
    assert W.shape == (12, 4)
    assert np.all(W[:4, 0] == 1) and np.all(W[4:, 0] == 0)
    assert np.all(W[8:, 2] == 2) and np.all(W[:8, 2] == 0)


def _rand_rec(rng, n_pulses, n_clks, spc, env_slots, max_p=16):
    """Random non-overlapping pulse records on element 0."""
    pulses, t = [], 2
    for _ in range(n_pulses):
        L = int(rng.integers(1, 4))          # env length in 4-sample groups
        addr = int(rng.integers(0, env_slots - L))
        t += int(rng.integers(2, 8))
        pulses.append(dict(
            gtime=t, env=(L << 12) | addr,
            phase=int(rng.integers(1 << 17)),
            freq_rel=float(rng.uniform(0, 0.4)),
            amp=int(rng.integers(1 << 16)), elem=0))
        t += (L * 4) // spc + 2
    return _rec(pulses, max_p=max_p)


@pytest.mark.parametrize('seed', range(3))
def test_waveform_pallas_matches_reference(seed):
    from distributed_processor_tpu.ops import synthesize_element_pallas
    rng = np.random.default_rng(seed)
    spc, n_clks = 4, 256                      # 1024 samples = 2 blocks @512
    env = (rng.uniform(-1, 1, 64) + 1j * rng.uniform(-1, 1, 64)) * 0.9
    rec = _rand_rec(rng, 5, n_clks, spc, env_slots=12)
    want = np.asarray(synthesize_element(rec, env, spc=spc, interp=1,
                                         n_clks=n_clks))
    got = np.asarray(synthesize_element_pallas(rec, env, spc=spc, interp=1,
                                               n_clks=n_clks,
                                               interpret=True))
    np.testing.assert_allclose(got, want, atol=2e-3)


def test_waveform_pallas_interp_and_cw():
    from distributed_processor_tpu.ops import synthesize_element_pallas
    env = np.concatenate([np.ones(4), 0.25 * np.ones(4)]).astype(complex)
    rec = _rec([
        dict(gtime=0, env=(ENV_CW_SENTINEL << 12) | 0, phase=0,
             freq_rel=0.0, amp=0xffff, elem=0),
        dict(gtime=16, env=(1 << 12) | 1, phase=0, freq_rel=0.0,
             amp=0xffff, elem=0),
    ])
    for interp in (1, 2):
        want = np.asarray(synthesize_element(rec, env, spc=4, interp=interp,
                                             n_clks=128))
        got = np.asarray(synthesize_element_pallas(
            rec, env, spc=4, interp=interp, n_clks=128, interpret=True))
        np.testing.assert_allclose(got, want, atol=2e-3,
                                   err_msg=f'interp={interp}')


def test_waveform_pallas_env_overrun_holds_last_sample():
    """Env window past the table end: both implementations hold the last
    envelope sample (the reference clamp semantics)."""
    from distributed_processor_tpu.ops import synthesize_element_pallas
    env = np.full(8, 0.5, complex)
    rec = _rec([dict(gtime=0, env=(4 << 12) | 0, phase=0, freq_rel=0.0,
                     amp=0xffff, elem=0)])     # claims 16 samples, table 8
    want = np.asarray(synthesize_element(rec, env, spc=4, interp=1,
                                         n_clks=128))
    got = np.asarray(synthesize_element_pallas(rec, env, spc=4, interp=1,
                                               n_clks=128, interpret=True))
    np.testing.assert_allclose(got, want, atol=2e-3)
    assert abs(got[12, 0] - 0.5) < 1e-3        # held past the table end
