"""Two-qubit Clifford RB through the statevec device (models/rb2q.py).

Round-3 'done' criterion: 2q RB recovers an injected two-qubit
depolarization rate distinct from the 1q rate.  Both recoveries here
are pinned against EXACT closed forms (global 1q/2q depolarizing
channels commute through their Clifford twirls), so the assertions are
binomial-CI-tight rather than fit-tolerance-loose:

* 1q RB survival = 1/2 + 1/2 * (1 - 4 p1 / 3)^n_pulses  (depol1 only)
* 2q RB survival = 1/4 + 3/4 * (1 - 16 p2 / 15)^n_cz    (depol2 only)

and each protocol is blind to the other channel by construction —
the distinctness the criterion asks for.
"""

import numpy as np
import pytest

from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.models.coupling import couplings_from_qchip
from distributed_processor_tpu.models.default_qchip import make_default_qchip
from distributed_processor_tpu.models.rb import rb_program
from distributed_processor_tpu.models.rb2q import (
    N_CLIFFORD2, clifford2_table, count_cz, depol2_survival,
    inverse2_index, rb2q_program, rb2q_sequence)
from distributed_processor_tpu.sim.device import DeviceModel
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)

KW = dict(max_steps=8000, max_pulses=192, max_meas=4)


@pytest.fixture(scope='module')
def sim2():
    return Simulator(n_qubits=2)


@pytest.fixture(scope='module')
def qchip2():
    return make_default_qchip(2)


def _run(sim, qchip, prog, shots, key, p1=0.0, p2=0.0):
    mp = sim.compile(prog)
    cps = couplings_from_qchip(mp, qchip)
    model = ReadoutPhysics(sigma=0.0, device=DeviceModel(
        'statevec', couplings=cps, depol_per_pulse=p1,
        depol2_per_pulse=p2))
    out = run_physics_batch(mp, model, key, shots,
                            init_states=np.zeros((shots, 2), np.int32),
                            **KW)
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))
    return np.asarray(out['meas_bits'])[:, :, 0]


def test_group_is_the_full_c2():
    """11,520 elements, closed under products, with working inverses."""
    words, unitaries, _ = clifford2_table()
    assert len(words) == N_CLIFFORD2
    rng = np.random.default_rng(4)
    for _ in range(10):
        i, j = rng.integers(N_CLIFFORD2, size=2)
        prod = unitaries[i] @ unitaries[j]
        k = inverse2_index(prod)              # raises if not in group
        closed = unitaries[k] @ prod
        assert abs(abs(np.trace(closed)) - 4) < 1e-6


def test_sequence_recovery_closes():
    words, unitaries, _ = clifford2_table()
    rng = np.random.default_rng(0)
    for depth in (1, 3, 7):
        seq = rb2q_sequence(rng, depth)
        net = np.eye(4, dtype=complex)
        for i in seq:
            net = unitaries[i] @ net
        assert abs(abs(np.trace(net)) - 4) < 1e-6


def test_noiseless_survival_is_exact(sim2, qchip2):
    """Every compiled C2 Clifford is exact under the statevec model:
    a depth-3 sequence + recovery returns |00> on every shot."""
    prog, info = rb2q_program('Q0', 'Q1', 3, seed=5)
    bits = _run(sim2, qchip2, prog, shots=64, key=5)
    assert not np.any(bits), 'noiseless 2q RB must return |00> exactly'
    assert info['n_cz'] >= 1


def test_depol2_recovered_from_2q_rb(sim2, qchip2):
    """Injected 2q depolarization is recovered: per-sequence survival
    matches the exact closed form within binomial CI, and the
    two-depth alpha estimate inverts to the injected p2."""
    p2, shots = 0.03, 768
    points = []
    for depth, seed in ((2, 1), (5, 2)):
        prog, info = rb2q_program('Q0', 'Q1', depth, seed=seed)
        bits = _run(sim2, qchip2, prog, shots=shots, key=seed, p2=p2)
        surv = float(np.all(bits == 0, axis=1).mean())
        pred = depol2_survival(p2, info['n_cz'])
        se = np.sqrt(pred * (1 - pred) / shots)
        assert abs(surv - pred) < 4 * se, (depth, surv, pred)
        points.append((info['n_cz'], surv))
    (n1, s1), (n2, s2) = points
    assert n2 > n1
    alpha = ((s2 - 0.25) / (s1 - 0.25)) ** (1.0 / (n2 - n1))
    p2_hat = 15.0 * (1.0 - alpha) / 16.0
    np.testing.assert_allclose(p2_hat, p2, rtol=0.35)


def test_channels_are_distinct(sim2, qchip2):
    """The 1q and 2q error channels are separately visible: depol2
    leaves 1q RB untouched (no coupling pulses fire), while depol1
    decays 1q RB by its own exact closed form — two protocols, two
    rates, each matching its injection."""
    depth, shots, p1 = 6, 768, 0.01
    prog1q = rb_program(['Q0', 'Q1'], depth, seed=3)
    # depol2 only: 1q RB is blind to the 2q channel
    bits = _run(sim2, qchip2, prog1q, shots=64, key=9, p2=0.2)
    assert not np.any(bits)
    # depol1 only: exact per-pulse decay (2 X90 per Clifford, depth+1
    # Cliffords including the recovery)
    bits = _run(sim2, qchip2, prog1q, shots=shots, key=10, p1=p1)
    n_pulses = 2 * (depth + 1)
    pred = 0.5 + 0.5 * (1.0 - 4.0 * p1 / 3.0) ** n_pulses
    for q in range(2):
        surv = float((bits[:, q] == 0).mean())
        se = np.sqrt(pred * (1 - pred) / shots)
        assert abs(surv - pred) < 4 * se, (q, surv, pred)


def test_interleaved_rb_isolates_cz_error(sim2, qchip2):
    """Interleaved 2q RB: the reference-vs-interleaved decay ratio
    recovers the interleaved CZ's own error.  With depol2-only errors
    every survival has an exact closed form (global depolarization
    commutes through Cliffords), so both curves are pinned within
    binomial CI, and the standard estimator alpha_int/alpha_ref inverts
    to the per-CZ depolarization."""
    from distributed_processor_tpu.models.rb2q import (
        rb2q_interleaved_program, element_index, _CZ)
    assert element_index(_CZ) >= 0         # CZ is in the table
    p2, shots = 0.03, 768
    # same seeds so the random Cliffords match between the two curves
    ref, intl = {}, {}
    for depth, seed in ((2, 21), (5, 22)):
        prog_r, info_r = rb2q_program('Q0', 'Q1', depth, seed=seed)
        bits = _run(sim2, qchip2, prog_r, shots=shots, key=seed, p2=p2)
        ref[depth] = (info_r['n_cz'], float(np.all(bits == 0, 1).mean()))
        prog_i, info_i = rb2q_interleaved_program('Q0', 'Q1', depth,
                                                  seed=seed)
        bits = _run(sim2, qchip2, prog_i, shots=shots, key=seed + 50,
                    p2=p2)
        intl[depth] = (info_i['n_cz'], float(np.all(bits == 0, 1).mean()))
        # both curves follow the exact closed form
        for n_cz, surv in (ref[depth], intl[depth]):
            pred = depol2_survival(p2, n_cz)
            se = np.sqrt(pred * (1 - pred) / shots)
            assert abs(surv - pred) < 4 * se, (depth, n_cz, surv, pred)
    # the estimator: per-depth alphas from the two-depth pairs, ratio
    # -> per-CZ depolarization.  The recoveries' own CZ counts vary, so
    # the interleaved-vs-reference count difference across depths
    # ('extra', dominated by the 3 added interleaves; 5 for these
    # seeds) sets the ratio's exponent rather than assuming exactly
    # one CZ per step — the count-exact form of the standard estimator.
    a_ref = ((ref[5][1] - 0.25) / (ref[2][1] - 0.25)) ** (1 / 3)
    a_int = ((intl[5][1] - 0.25) / (intl[2][1] - 0.25)) ** (1 / 3)
    extra = (intl[5][0] - intl[2][0]) - (ref[5][0] - ref[2][0])
    assert extra >= 1, (ref, intl)
    alpha_cz = (a_int / a_ref) ** (3 / extra)
    p2_hat = 15.0 * (1.0 - alpha_cz) / 16.0
    # Delta-method CI instead of a fixed rtol band: alpha_cz is a
    # RATIO of two noisy decay fits, so its spread is set by the four
    # binomial survivals, not by p2's magnitude — at these shot counts
    # the propagated sd is comparable to p2 itself and a fixed
    # rtol=0.4 band flaked on unlucky seeds.  Derivation:
    #   ln(alpha_cz) = [ln(i5-1/4) - ln(i2-1/4)
    #                   - ln(r5-1/4) + ln(r2-1/4)] / extra
    # with the four survivals independent, so
    #   Var[ln(alpha_cz)] = sum_s Var[s] / (s-1/4)^2 / extra^2,
    #   Var[s] = s(1-s)/shots (binomial),
    # and p2_hat = 15(1-alpha_cz)/16 gives, to first order,
    #   sd(p2_hat) = 15/16 * alpha_cz * sd(ln alpha_cz).
    surv = (ref[2][1], ref[5][1], intl[2][1], intl[5][1])
    var_ln = sum(s * (1 - s) / (shots * (s - 0.25) ** 2)
                 for s in surv) / extra ** 2
    sd = 15.0 / 16.0 * alpha_cz * np.sqrt(var_ln)
    assert abs(p2_hat - p2) < 4 * sd + 1e-3, (p2_hat, p2, sd)
