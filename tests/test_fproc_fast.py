"""Timestamped fproc fabric: the fast engines serve lut+fproc feedback.

The per-slot production-clock plane (``meas_time``) makes LUT reads a
pure function of (bit planes, timestamp planes, read service time):
the served slot per masked producer is the newest bit produced
STRICTLY before the read's service time (slot-0 fallback), so any
dispatch granularity that serves the read from final planes returns
the same bits — which is what lifted the lut+fproc ban from the
block/pallas rungs (docs/PERF.md "Feedback on the fast engines").

Pinned here, per stat and fault-word included: bit-identity of
generic vs block vs pallas(interpret) on the repetition lut+fproc
round, on an adversarial interleaving program whose old latest-bit
semantics would have served a different slot, on starvation
terminals, under vmap, on the dp=2/cores-sharded mesh (the GSPMD
block path), and — slow-marked — on the golden suite run under the
LUT fabric.  tools/check_junit.py fails the suite if anything here
skips.
"""

import jax
import numpy as np
import pytest

from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import machine_program_from_cmds
from distributed_processor_tpu.models.default_qchip import make_default_qchip
from distributed_processor_tpu.models.golden_suite import GOLDEN_PROGRAMS
from distributed_processor_tpu.models.repetition import (
    _lut_fabric_kwargs, repetition_round_machine_program)
from distributed_processor_tpu.parallel import (make_cores_mesh,
                                                sharded_cores_simulate)
from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.sim import (ERR_FPROC_DEADLOCK, run_oracle,
                                           simulate_batch)
from distributed_processor_tpu.sim.interpreter import (
    FAULT_FPROC_STARVED, InterpreterConfig, _program_constants,
    _run_batch_engine, _soa_static, block_ineligible, block_trace_count,
    cores_ineligible, pallas_ineligible, pallas_trace_count,
    program_traits, resolve_engine, straightline_ineligible)

pytestmark = pytest.mark.feedback

_N_DEV = len(jax.devices())

_ENGINES = ('generic', 'block', 'pallas')


def _cfg(kw, engine):
    extra = {'pallas_interpret': True} if engine == 'pallas' else {}
    return InterpreterConfig(engine=engine, **extra, **kw)


def _assert_identical(ref: dict, out: dict, msg: str = ''):
    """Every stat bit-identical — the fault word included; 'steps' is
    the only engine-dependent diagnostic."""
    assert set(ref) == set(out), msg
    for k in sorted(ref):
        if k == 'steps':
            continue
        np.testing.assert_array_equal(
            np.asarray(ref[k]), np.asarray(out[k]), err_msg=f'{msg}{k}')


def mp_of(*cmd_lists, **kw):
    return machine_program_from_cmds(list(cmd_lists), **kw)


@pytest.fixture(scope='module')
def rep():
    """Repetition lut+fproc round + a shot batch that exercises every
    syndrome (module scope: the engine traces are the expensive part)."""
    mp = repetition_round_machine_program(n_data=3)
    kw = dict(mp.static_bounds(), max_meas=4, max_resets=4,
              **_lut_fabric_kwargs(3))
    bits = np.random.default_rng(9).integers(0, 2, (8, mp.n_cores, 4))
    return mp, kw, bits


# ---------------------------------------------------------------------------
# eligibility: the ban is gone, the named blockers that remain are right
# ---------------------------------------------------------------------------

def test_fast_engines_eligible_on_lut_fproc(rep):
    """The lut+fproc repetition round is block- AND pallas-eligible;
    forcing either resolves."""
    mp, kw, _ = rep
    cfg = InterpreterConfig(**kw)
    assert block_ineligible(mp, cfg) is None
    assert pallas_ineligible(mp, cfg) is None
    from dataclasses import replace
    assert resolve_engine(mp, replace(cfg, engine='block')) == 'block'
    assert resolve_engine(mp, replace(cfg, engine='pallas')) == 'pallas'


def test_span_lut_ineligibility_named():
    """The straight-line span keeps its precise blockers — each named:
    func_id=0 own-fresh reads, a masked trigger at/after the read
    index, and an unconfigured LUT."""
    base = dict(max_steps=128, max_pulses=8, max_meas=2)
    meas = lambda t: isa.pulse_cmd(freq_word=3, cfg_word=2,
                                   env_word=(2 << 12) | 0, cmd_time=t)
    own = mp_of([meas(10),
                 isa.alu_cmd('alu_fproc', 'i', 0, 'id1', write_reg_addr=5,
                             func_id=0),
                 isa.done_cmd()])
    cfg = InterpreterConfig(fabric='lut', lut_mask=(True,),
                            lut_table=(0, 1), **base)
    assert 'func_id=0' in straightline_ineligible(own, cfg)
    # producer's second possibly-measurement trigger sits AFTER the
    # read index: planes not final at the span serve -> named reject
    late = mp_of([meas(10), meas(200), isa.done_cmd()],
                 [isa.alu_cmd('alu_fproc', 'i', 0, 'id1',
                              write_reg_addr=5, func_id=1),
                  isa.done_cmd()])
    cfg2 = InterpreterConfig(fabric='lut', lut_mask=(True, False),
                             lut_table=(0, 3), **base)
    assert 'possibly-measurement trigger' in \
        straightline_ineligible(late, cfg2)
    # no mask/table configured
    cfg3 = InterpreterConfig(fabric='lut', **base)
    assert 'lut_mask' in straightline_ineligible(late, cfg3)
    # ... and none of these block the block engine
    for mp_, c in ((own, cfg), (late, cfg2)):
        assert block_ineligible(mp_, c) is None


# ---------------------------------------------------------------------------
# bit-identity: repetition round, adversarial interleaving, starvation
# ---------------------------------------------------------------------------

def test_repetition_round_bit_identity(rep):
    """generic vs block vs pallas(interpret) on the lut+fproc round:
    every stat identical, corrections syndrome-dependent, oracle
    agreement per shot."""
    mp, kw, bits = rep
    outs = {e: simulate_batch(mp, bits, cfg=_cfg(kw, e))
            for e in _ENGINES}
    for e in _ENGINES[1:]:
        _assert_identical(outs['generic'], outs[e], msg=f'{e}: ')
    # the workload must exercise the feedback: pulse counts vary by shot
    assert len(np.unique(np.asarray(outs['generic']['n_pulses']))) > 1
    for s in range(bits.shape[0]):
        orc = run_oracle(mp, meas_bits=bits[s], fabric='lut',
                         lut_mask=kw['lut_mask'], lut_table=kw['lut_table'])
        np.testing.assert_array_equal(
            [len(p) for p in orc['pulses']],
            np.asarray(outs['generic']['n_pulses'][s]),
            err_msg=f'oracle shot {s}')


def _adversarial_mp():
    """Producer measures at t=10 and t=200; the reader's LUT read
    services at ~103 — between the two production times.  The old
    latest-bit semantics could serve either slot depending on how
    producer instructions interleave with the read (dispatch
    granularity); the timestamped fabric always serves slot 0."""
    meas = lambda t: isa.pulse_cmd(freq_word=3, cfg_word=2,
                                   env_word=(2 << 12) | 0, cmd_time=t)
    core_meas = [meas(10), meas(200), isa.done_cmd()]
    core_read = [
        isa.idle(100),
        isa.alu_cmd('jump_fproc', 'i', 1, 'eq', jump_cmd_ptr=3, func_id=1),
        isa.jump_i(4),
        isa.pulse_cmd(freq_word=9, cfg_word=0, env_word=(2 << 12) | 0,
                      cmd_time=400),
        isa.done_cmd(),
    ]
    return mp_of(core_meas, core_read)


def test_adversarial_interleaving_bit_identity():
    """The dispatch-granularity trap: engines with different service
    granularities (per-step gather vs block-boundary serve) must agree
    because the serve is time-indexed, and the served slot must be the
    FIRST measurement (produced before the read), not the latest."""
    mp = _adversarial_mp()
    kw = dict(max_steps=256, max_pulses=8, max_meas=2, fabric='lut',
              lut_mask=(True, False), lut_table=(0, 0b11))
    bits = np.array([[[0, 0], [0, 0]], [[0, 1], [0, 0]],
                     [[1, 0], [0, 0]], [[1, 1], [0, 0]]])
    outs = {e: simulate_batch(mp, bits, cfg=_cfg(kw, e))
            for e in _ENGINES}
    for e in _ENGINES[1:]:
        _assert_identical(outs['generic'], outs[e], msg=f'{e}: ')
    # reader pulse fires iff slot-0 bit == 1 (shots 2,3), NOT the
    # latest recorded bit (which would fire shots 1,3)
    np.testing.assert_array_equal(
        np.asarray(outs['generic']['n_pulses'])[:, 1], [0, 0, 1, 1])
    assert not np.any(np.asarray(outs['generic']['err']))
    for s in range(bits.shape[0]):
        orc = run_oracle(mp, meas_bits=bits[s], fabric='lut',
                         lut_mask=kw['lut_mask'], lut_table=kw['lut_table'])
        assert len(orc['pulses'][1]) == int(s >= 2), f'oracle shot {s}'
    # this is exactly the shape the span must NOT host (planes not
    # final at the read index) — named reject, block engine serves it
    assert 'possibly-measurement trigger' in straightline_ineligible(
        mp, InterpreterConfig(**kw))


def test_starvation_terminal_identity():
    """A masked producer that can never measure starves the reader:
    every engine lands the same terminal (ERR_FPROC_DEADLOCK +
    fproc_starved fault, done, pc frozen)."""
    core_dead = [isa.done_cmd()]
    core_read = [
        isa.idle(100),
        isa.alu_cmd('jump_fproc', 'i', 1, 'eq', jump_cmd_ptr=3, func_id=1),
        isa.jump_i(4),
        isa.pulse_cmd(freq_word=9, cfg_word=0, env_word=(2 << 12) | 0,
                      cmd_time=400),
        isa.done_cmd(),
    ]
    mp = mp_of(core_dead, core_read)
    kw = dict(max_steps=256, max_pulses=8, max_meas=2, fabric='lut',
              lut_mask=(True, False), lut_table=(0, 0b11))
    bits = np.zeros((1, 2, 2), int)
    outs = {e: simulate_batch(mp, bits, cfg=_cfg(kw, e))
            for e in _ENGINES}
    for e in _ENGINES[1:]:
        _assert_identical(outs['generic'], outs[e], msg=f'{e}: ')
    g = outs['generic']
    assert int(g['err'][0, 1]) == ERR_FPROC_DEADLOCK
    assert int(g['fault'][0, 1]) == FAULT_FPROC_STARVED
    assert bool(np.all(np.asarray(g['done'])))
    assert int(g['pc'][0, 1]) == 1          # frozen at the read


# ---------------------------------------------------------------------------
# composition: vmap, cores-sharded mesh, retrace budget
# ---------------------------------------------------------------------------

def test_lut_fproc_under_vmap(rep):
    """The timestamped serve is a plain JAX program: vmapping the block
    engine over a leading group axis matches the vmapped generic."""
    mp, kw, _ = rep
    cfg = InterpreterConfig(**kw)
    soa, spc, interp, sync_part = _program_constants(mp, cfg)
    prog = _soa_static(mp)
    traits = program_traits(mp)
    bits = np.asarray(np.random.default_rng(7).integers(
        0, 2, size=(3, 4, mp.n_cores, 4)), np.int32)

    def blk(mb):
        return _run_batch_engine(None, spc, interp, sync_part, mb, cfg,
                                 mp.n_cores, engine='block', prog=prog)

    def gen(mb):
        return _run_batch_engine(soa, spc, interp, sync_part, mb, cfg,
                                 mp.n_cores, engine='generic',
                                 traits=traits)

    b = jax.jit(jax.vmap(blk))(bits)
    g = jax.jit(jax.vmap(gen))(bits)
    _assert_identical(g, b, msg='vmap: ')


def test_cores_sharded_block_bit_identity(rep):
    """engine='block' under the ('dp','cores') mesh — the GSPMD block
    path — is eligible and bit-identical to both the local block and
    local generic engines (conftest forces an 8-device CPU host, so
    dp=2 x cores=3 always fits; no skip)."""
    mp, kw, bits = rep
    assert _N_DEV >= 6, 'conftest should have forced 8 CPU devices'
    mesh = make_cores_mesh(n_cores=3, n_dp=2)
    blk = InterpreterConfig(engine='block', cores_axis='cores', **kw)
    assert cores_ineligible(mp, blk) is None
    assert resolve_engine(mp, blk) == 'block'
    sharded = sharded_cores_simulate(
        mp, bits, mesh, cfg=InterpreterConfig(engine='block', **kw))
    for name, local in (
            ('generic', simulate_batch(mp, bits, cfg=_cfg(kw, 'generic'))),
            ('block', simulate_batch(mp, bits, cfg=_cfg(kw, 'block')))):
        for k in sorted(set(local) & set(sharded)):
            if k == 'steps':
                continue
            np.testing.assert_array_equal(
                np.asarray(local[k]), np.asarray(sharded[k]),
                err_msg=f'sharded-block vs local {name}: {k}')


def test_retrace_budget(rep):
    """One trace per engine per program content; identical re-calls
    come from the content-keyed jit cache untraced."""
    mp, kw, bits = rep
    n_blk, n_pal = block_trace_count(), pallas_trace_count()
    for _ in range(2):
        simulate_batch(mp, bits, cfg=_cfg(kw, 'block'))
        simulate_batch(mp, bits, cfg=_cfg(kw, 'pallas'))
    assert block_trace_count() - n_blk <= 1
    assert pallas_trace_count() - n_pal <= 1


# ---------------------------------------------------------------------------
# fault-injection: feedback mutants agree across the fast engines
# ---------------------------------------------------------------------------

@pytest.mark.faults
def test_feedback_fuzz_consistency():
    """Tier-1 slice of tools/faultfuzz.py's feedback cross-check:
    generic vs block vs pallas(interpret) agree on timing-independent
    fault codes over mutated lut+fproc programs."""
    from distributed_processor_tpu.sim import faultinject as fi
    r = fi.check_feedback_consistency(seed=0, n=8, shots=2)
    assert not r['failures'], r['failures']
    assert r['checked'] >= 4, r    # the check must not skip itself dry


# ---------------------------------------------------------------------------
# serve: feedback programs dispatch on the fast singleton rung
# ---------------------------------------------------------------------------

@pytest.mark.serve
def test_serve_singleton_block_serves_feedback(rep):
    """A repetition lut+fproc round submitted solo to a
    singleton_engine='block' service dispatches on the block rung
    (the old ladder bounced it to generic) and returns the
    simulate_batch stats bit-for-bit."""
    from distributed_processor_tpu.serve import ExecutionService
    mp, kw, bits = rep
    cfg = InterpreterConfig(**kw)
    with ExecutionService(max_batch_programs=1, max_wait_ms=1.0,
                          singleton_engine='block') as svc:
        got = svc.submit(mp, bits.astype(np.int32),
                         cfg=cfg).result(timeout=300)
        stats = svc.stats()
    assert stats['engine_dispatches'] == {'block': 1}
    want = jax.tree.map(np.asarray, simulate_batch(mp, bits, cfg=cfg))
    _assert_identical(want, got, msg='serve: ')


# ---------------------------------------------------------------------------
# golden suite under the LUT fabric (slow: a trace per program x engine)
# ---------------------------------------------------------------------------

def _golden_lut_setup(name):
    """(mp, kw, bits) for a golden re-wired onto the LUT fabric: a
    parity table over up to 4 masked cores, every core's output bit
    driven."""
    n_qubits, thunk = GOLDEN_PROGRAMS[name]
    qchip = make_default_qchip(max(n_qubits, 2))
    mp = compile_to_machine(thunk(), qchip, n_qubits=n_qubits)
    C = mp.n_cores
    k = min(C, 4)
    table = tuple(((1 << C) - 1) if bin(a).count('1') & 1 else 0
                  for a in range(1 << k))
    kw = dict(mp.static_bounds(), max_meas=16, max_resets=64,
              fabric='lut', lut_mask=(True,) * k + (False,) * (C - k),
              lut_table=table)
    bits = np.random.default_rng(17).integers(0, 2, size=(4, C, 16))
    return mp, kw, bits


@pytest.mark.slow
@pytest.mark.parametrize('name', sorted(GOLDEN_PROGRAMS))
def test_golden_suite_lut_bit_identity(name):
    """Every golden program re-run under the LUT fabric: generic vs
    block vs pallas(interpret) identical on every stat, fault words
    included.  Starvation/deadlock terminals under the re-wired
    feedback still count — the terminals must match too."""
    mp, kw, bits = _golden_lut_setup(name)
    outs = {'generic': simulate_batch(mp, bits, cfg=_cfg(kw, 'generic'))}
    if bool(outs['generic']['incomplete']):
        # the parity re-wiring turned a feedback-conditioned loop
        # unbounded: a truncated run's stats depend on the engine's
        # step granularity, so the identity contract does not apply
        # (not a skip — the check_junit gate treats skips as
        # regressions; test_some_golden_completes_under_lut pins that
        # this branch cannot swallow the whole suite)
        return
    for e in _ENGINES[1:]:
        outs[e] = simulate_batch(mp, bits, cfg=_cfg(kw, e))
    for e in _ENGINES[1:]:
        _assert_identical(outs['generic'], outs[e], msg=f'{name} {e}: ')


@pytest.mark.slow
def test_fproc_feedback_ladder_contract():
    """The bench row's perf contract: the block rung retires the deep
    feedback workload in >= 4x fewer outer iterations than generic
    within one trace, with the bit-identity gate (asserted inside the
    row, before any timing) holding."""
    import bench
    row = bench.fproc_feedback_ladder(n_data=3, rounds=4, k_corr=12,
                                      batch=32)
    assert 'ineligible' not in row['block'], row['block']
    assert row['iteration_reduction'] >= 4.0, row
    assert row['block_retraces'] <= 1, row


@pytest.mark.slow
def test_some_golden_completes_under_lut():
    """The golden-lut identity sweep must not pass vacuously: the
    feedback-heavy goldens complete under the parity re-wiring."""
    for name in ('active_reset_2q', 'fproc_hold', 'linear_x90_read'):
        mp, kw, bits = _golden_lut_setup(name)
        out = simulate_batch(mp, bits, cfg=_cfg(kw, 'generic'))
        assert not bool(out['incomplete']), name
