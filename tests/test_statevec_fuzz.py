"""Randomized statevec fuzz vs an independent TIME-ORDERED oracle.

The statevec engine's most delicate machinery is the discrete-event
gate: cores advance per instruction step, and the gate must make
cross-core application order equal schedule order for non-commuting
coupled pulses.  This fuzz pins it adversarially: random multi-core
programs with arbitrary cross-core time interleavings (1q rotations,
ZX cross-resonance, ZZ drives, mid-circuit projective readouts,
per-core detuning) are executed by the engine, and independently by a
straightforward numpy simulator that simply SORTS ALL EVENTS BY
TRIGGER TIME and applies them one at a time — no step machinery, no
frontiers, no fixpoint.  Sampled bits must match exactly (same
projective uniforms) and final state vectors up to global phase.

A gate-ordering bug (a pulse admitted before a time-earlier
non-commuting one) shows up as a fidelity/bit mismatch here even when
the curated tests' schedules happen to be benign.
"""

import numpy as np
import pytest

import jax

from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import machine_program_from_cmds
from distributed_processor_tpu.sim.device import (DeviceModel,
                                                  ZX90_AMP_DEFAULT,
                                                  ZZ90_AMP_DEFAULT)
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)

C, SHOTS, M = 3, 16, 4
X90_AMP = 31457
COUPLINGS = ((0, 1, 1, 'zx'), (1, 1, 2, 'zx'), (0, 2, 2, 'zz'))


def _random_program(rng):
    """Per-core pulse lists with globally interleaved distinct trigger
    times.  Returns (cmds_per_core, events) where events are
    (time, core, kind, amp, phase) with kind in {'1q','zx','zz','meas'}."""
    n_per_core = [int(rng.integers(3, 7)) for _ in range(C)]
    total = sum(n_per_core)
    times = rng.choice(np.arange(100, 100 + 200 * total, 50),
                       size=total, replace=False)
    times = np.sort(times)
    # deal the sorted global times round-robin-randomly to cores so
    # each core's own sequence is increasing but cross-core order is
    # arbitrary
    owner = rng.permutation(np.repeat(np.arange(C),
                                      n_per_core))
    cmds = [[] for _ in range(C)]
    events = []
    n_meas = [0] * C
    for t, c in zip(times, owner):
        c = int(c)
        choices = ['1q', 'meas'] if n_meas[c] < 2 else ['1q']
        if c == 0:
            choices += ['zx', 'zz']
        elif c == 1:
            choices += ['zx']
        kind = rng.choice(choices)
        amp = int(rng.integers(0, 60000))
        phase = int(rng.integers(0, 1 << 17))
        if kind == 'meas':
            cmds[c].append(isa.pulse_cmd(
                freq_word=0, phase_word=0, amp_word=30000,
                env_word=(8 << 12), cfg_word=2, cmd_time=int(t)))
            n_meas[c] += 1
            events.append((int(t), c, 'meas', 0, 0))
        else:
            freq_word = {'1q': 0, 'zx': 1, 'zz': 2}[kind]
            cmds[c].append(isa.pulse_cmd(
                freq_word=freq_word, phase_word=phase, amp_word=amp,
                env_word=4096, cfg_word=0, cmd_time=int(t)))
            events.append((int(t), c, kind, amp, phase))
    for c in range(C):
        cmds[c].append(isa.done_cmd())
    return cmds, sorted(events)


def _patch_tables(mp):
    """Hand-built programs carry empty tables: give the measurement
    element a real window so the resolver has energy."""
    for t in mp.tables:
        t.envs[2] = np.ones(32, complex)
        t.freqs[2] = {'freq': np.array([0.0]), 'iq15': np.zeros((1, 15))}


def _rot_1q(theta, phi):
    c, s = np.cos(theta / 2), np.sin(theta / 2)
    return np.array([[c, -1j * np.exp(-1j * phi) * s],
                     [-1j * np.exp(1j * phi) * s, c]])


def _apply_1q(psi, U, c):
    psi = np.moveaxis(psi.reshape((2,) * C), c, 0)
    psi = np.tensordot(U, psi, axes=[[1], [0]])
    return np.moveaxis(psi, 0, c).reshape(-1)


def _apply_pair(psi, U4, a, b):
    psi = np.moveaxis(psi.reshape((2,) * C), (a, b), (0, 1))
    sh = psi.shape
    psi = (U4 @ psi.reshape(4, -1)).reshape(sh)
    return np.moveaxis(psi, (0, 1), (a, b)).reshape(-1)


def _bit1(c):
    d = np.arange(1 << C)
    return ((d >> (C - 1 - c)) & 1).astype(float)


def _oracle(events, det_cyc, meas_u, shot):
    """Straight-line time-ordered replay: no steps, no gate."""
    psi = np.zeros(1 << C, complex)
    psi[0] = 1.0
    last_t = {c: 2 for c in range(C)}        # INIT_TIME
    slot = [0] * C
    bits = {}
    for (t, c, kind, amp, phase) in events:
        # free evolution of THIS core over its gap (detuning only)
        dt = t - last_t[c]
        alpha = 2 * np.pi * det_cyc[c] * dt
        z = 1.0 - 2.0 * _bit1(c)
        psi = psi * np.exp(-0.5j * alpha * z)
        last_t[c] = t
        phi = 2 * np.pi * phase / (1 << 17)
        if kind == '1q':
            theta = (np.pi / 2) * amp / X90_AMP
            psi = _apply_1q(psi, _rot_1q(theta, phi), c)
        elif kind == 'zx':
            tgt = {0: 1, 1: 2}[c]
            theta = (np.pi / 2) * amp / ZX90_AMP_DEFAULT
            up, dn = _rot_1q(theta, phi), _rot_1q(-theta, phi)
            U4 = np.block([[up, np.zeros((2, 2))],
                           [np.zeros((2, 2)), dn]])
            psi = _apply_pair(psi, U4, c, tgt)
        elif kind == 'zz':
            theta = (np.pi / 2) * amp / ZZ90_AMP_DEFAULT
            zz = (1 - 2 * _bit1(0)) * (1 - 2 * _bit1(2))
            psi = psi * np.exp(-0.5j * theta * zz)
        else:  # meas
            p1 = float(np.sum(_bit1(c) * np.abs(psi) ** 2))
            u = meas_u[shot, c, slot[c]]
            bit = int(u < p1)
            keep = _bit1(c) if bit else 1 - _bit1(c)
            psi = psi * keep / np.sqrt(max(bit and p1 or 1 - p1, 1e-12))
            bits[(c, slot[c])] = bit
            slot[c] += 1
    return psi, bits


@pytest.mark.parametrize('seed', range(12))
def test_engine_matches_time_ordered_oracle(seed):
    rng = np.random.default_rng(seed)
    cmds, events = _random_program(rng)
    mp = machine_program_from_cmds(cmds)
    _patch_tables(mp)
    det = tuple(float(x) for x in rng.uniform(-1e6, 1e6, C))
    model = ReadoutPhysics(
        sigma=0.0, p1_init=0.0, x90_amp=X90_AMP,
        device=DeviceModel('statevec', couplings=COUPLINGS,
                           detuning_hz=det))
    out = run_physics_batch(mp, model, seed, SHOTS, max_steps=2048,
                            max_pulses=16, max_meas=M)
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err'])), \
        np.asarray(out['err']).tolist()

    det_cyc = model.device.per_clock_rates(C)[0]
    key = jax.random.PRNGKey(seed)
    meas_u = np.asarray(jax.random.uniform(
        jax.random.fold_in(key, 0x424c4f43), (SHOTS, C, M)))
    eng_bits = np.asarray(out['meas_state'])
    eng_psi = np.asarray(out['psi'])
    for shot in range(SHOTS):
        psi_o, bits_o = _oracle(events, det_cyc, meas_u, shot)
        for (c, s), b in bits_o.items():
            assert int(eng_bits[shot, c, s]) == b, \
                (seed, shot, c, s, int(eng_bits[shot, c, s]), b)
        fid = abs(np.vdot(psi_o, eng_psi[shot]))
        assert fid > 1 - 1e-4, (seed, shot, fid)
