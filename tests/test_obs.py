"""Flight-deck observability (obs/): the contract.

Three load-bearing properties, per docs/OBSERVABILITY.md:

* **Tracing is complete and honest** — a sampled request's span chain
  connects submit -> queued -> coalesce.ripen -> dispatch -> execute ->
  demux -> done, retry/steal/migration hops included under seeded
  chaos; the Chrome-trace export is schema-valid; sampling 0 produces
  ZERO spans (the off path is the default and must stay free).
* **The metrics registry is exact** — counters/gauges/histograms round
  through snapshot/restore unchanged, histogram percentiles match
  numpy over the raw window, and the Prometheus exposition is parseable.
* **Telemetry names are frozen** — every pre-existing ``stats()`` key
  and ``serve.*`` counter name is pinned by a literal manifest here;
  renaming one breaks dashboards, so it must break this test first.

The flight recorder's ring/dump mechanics are covered here too; its
integration (breaker trips, chaos injections landing in the ring) rides
the chaos span test.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import jax

from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import machine_program_from_cmds
from distributed_processor_tpu.obs import (DEFAULT_BUCKETS,
                                           FlightRecorder, Histogram,
                                           MetricsRegistry, STAGE_ORDER,
                                           Tracer, chrome_trace_events,
                                           write_chrome_trace)
from distributed_processor_tpu.serve import (ChaosMonkey, ChaosPlan,
                                             ExecutionService,
                                             RetryPolicy)
from distributed_processor_tpu.serve.service import _normalize_cfg
from distributed_processor_tpu.sim.interpreter import (InterpreterConfig,
                                                       simulate_batch)
from distributed_processor_tpu.utils import profiling

pytestmark = [pytest.mark.obs, pytest.mark.serve]

_N_DEV = len(jax.devices())


def _mp(salt=0):
    core = [isa.pulse_cmd(amp_word=1000 + 7 * salt + 13 * i, cfg_word=0,
                          env_word=3, cmd_time=10 + 20 * i)
            for i in range(3)] + [isa.done_cmd()]
    return machine_program_from_cmds([core])


_CFG = InterpreterConfig(max_steps=2 * 8 + 64, max_pulses=8 + 2,
                         max_meas=2, max_resets=2)


def _bits(rng, shots=3):
    return rng.integers(0, 2, size=(shots, 1, 2)).astype(np.int32)


def _solo(mp, bits):
    ncfg, _ = _normalize_cfg(_CFG, isa.shape_bucket(mp.n_instr))
    return jax.tree.map(np.asarray, simulate_batch(mp, bits, cfg=ncfg))


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


def test_registry_counters_gauges_histograms():
    reg = MetricsRegistry()
    assert reg.inc('a.b') == 1
    assert reg.inc('a.b', 4) == 5
    assert reg.get('a.b') == 5
    assert reg.get('missing') == 0
    reg.set_gauge('depth', 7)
    assert reg.gauge('depth') == 7
    assert reg.gauge('nope', default=-1.0) == -1.0
    h = reg.histogram('lat_ms')
    for v in (1.0, 2.0, 3.0, 100.0):
        h.observe(v)
    assert h.count == 4
    assert h.sum == pytest.approx(106.0)
    # get-or-create returns the same object
    assert reg.histogram('lat_ms') is h
    assert reg.counters()['a.b'] == 5


def test_histogram_percentiles_match_numpy():
    h = Histogram('x', window=512)
    rng = np.random.default_rng(3)
    vals = rng.exponential(10.0, size=300)
    for v in vals:
        h.observe(float(v))
    for p in (50, 90, 99):
        assert h.percentile(p) == pytest.approx(
            float(np.percentile(np.asarray(h.values()), p)))
    assert Histogram('empty').percentile(50) is None


def test_histogram_window_is_bounded():
    h = Histogram('x', window=16)
    for v in range(100):
        h.observe(float(v))
    assert len(h.values()) == 16       # raw window bounded...
    assert h.count == 100              # ...cumulative counts are not


def test_registry_snapshot_restore_roundtrip():
    reg = MetricsRegistry()
    reg.inc('c', 3)
    reg.set_gauge('g', 1.5)
    reg.observe('h', 12.0)
    snap = reg.snapshot()
    reg.inc('c', 10)
    reg.inc('new', 1)
    reg.set_gauge('g', 9.0)
    reg.observe('h', 99.0)
    reg.restore(snap)
    assert reg.get('c') == 3
    assert reg.get('new') == 0
    assert reg.gauge('g') == 1.5
    assert reg.histogram('h').count == 1
    assert reg.histogram('h').values() == [12.0]


def test_prometheus_text_exposition():
    reg = MetricsRegistry()
    reg.inc('serve.submitted', 2)
    reg.set_gauge('serve.svc0.queue_depth', 3)
    reg.observe('serve.latency_ms', 1.7)
    text = reg.prometheus_text()
    assert '# TYPE serve_submitted counter' in text
    assert 'serve_submitted 2' in text
    assert '# TYPE serve_svc0_queue_depth gauge' in text
    assert '# TYPE serve_latency_ms histogram' in text
    # cumulative buckets: every boundary present plus +Inf, and the
    # 1.7 observation lands in every le >= 2.5 bucket
    assert 'serve_latency_ms_bucket{le="+Inf"} 1' in text
    assert f'serve_latency_ms_bucket{{le="{DEFAULT_BUCKETS[0]}"}} 0' \
        in text
    assert 'serve_latency_ms_count 1' in text


def test_profiling_facade_delegates_to_registry():
    profiling.counter_inc('obs.test.facade', 2)
    assert profiling.counter_get('obs.test.facade') == 2
    assert profiling.counters()['obs.test.facade'] == 2
    assert profiling.registry().get('obs.test.facade') == 2
    assert 'obs_test_facade 2' in profiling.prometheus_text()
    # the conftest autouse fixture restores around every test; verify
    # the snapshot API it uses round-trips
    snap = profiling.registry_snapshot()
    profiling.counter_inc('obs.test.facade', 100)
    profiling.registry_restore(snap)
    assert profiling.counter_get('obs.test.facade') == 2


def test_registry_thread_safety():
    reg = MetricsRegistry()

    def worker():
        for _ in range(500):
            reg.inc('n')
            reg.observe('h', 1.0)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.get('n') == 4000
    assert reg.histogram('h').count == 4000


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_recorder_ring_and_dump(tmp_path):
    rec = FlightRecorder(capacity=4)
    for i in range(10):
        rec.record('retry', seq=i)
    rec.record('breaker_trip', executor='cpu:0')
    assert rec.recorded == 11
    events = rec.events()
    assert len(events) == 4                      # ring keeps the tail
    assert events[-1]['kind'] == 'breaker_trip'
    assert events[-1]['executor'] == 'cpu:0'
    assert [e['seq'] for e in events[:-1]] == [7, 8, 9]
    assert rec.events(kind='breaker_trip') == [events[-1]]
    assert rec.counts() == {'retry': 3, 'breaker_trip': 1}
    p = tmp_path / 'flight.json'
    rec.dump(str(p))
    doc = json.loads(p.read_text())
    assert doc['capacity'] == 4
    assert doc['recorded'] == 11
    assert doc['counts'] == {'retry': 3, 'breaker_trip': 1}
    assert [e['kind'] for e in doc['events']] \
        == ['retry', 'retry', 'retry', 'breaker_trip']
    # monotonic sequence numbers survive the ring
    seqs = [e['seq'] for e in doc['events']]
    assert seqs == sorted(seqs)


# ---------------------------------------------------------------------------
# tracer unit behavior
# ---------------------------------------------------------------------------


def test_tracer_sampling_zero_allocates_nothing():
    t = Tracer(0.0)
    assert not t.enabled
    assert all(t.maybe_start() is None for _ in range(100))
    assert t.contexts() == []


def test_tracer_sampling_fraction_is_deterministic():
    t = Tracer(0.25, keep=100)
    got = [t.maybe_start() for _ in range(100)]
    assert sum(1 for c in got if c is not None) == 25
    t2 = Tracer(0.25, keep=100)
    got2 = [t2.maybe_start() for _ in range(100)]
    assert [c is None for c in got] == [c is None for c in got2]


def test_chrome_trace_event_shape(tmp_path):
    t = Tracer(1.0)
    ctx = t.maybe_start()
    t0 = 100.0
    ctx.instant('submit', t=t0, seq=0)
    ctx.span('queued', t0, t0 + 0.5, bucket='b')
    ctx.span('execute', t0 + 0.5, t0 + 0.7, device='cpu:0')
    ctx.instant('done', t=t0 + 0.7, outcome='ok')
    events = chrome_trace_events(t.contexts(), pid='svc')
    assert len(events) == 4
    for e in events:
        assert e['pid'] == 'svc'
        assert e['tid'] == f'req-{ctx.trace_id}'
        assert e['ts'] >= 0
        assert e['ph'] in ('X', 'i')
    x = [e for e in events if e['ph'] == 'X']
    assert [e['name'] for e in x] == ['queued', 'execute']
    assert x[0]['dur'] == pytest.approx(0.5e6)   # seconds -> us
    assert x[0]['args'] == {'bucket': 'b'}
    p = tmp_path / 'trace.json'
    n = write_chrome_trace(str(p), t.contexts())
    doc = json.loads(p.read_text())
    assert set(doc) == {'traceEvents', 'displayTimeUnit'}
    assert doc['displayTimeUnit'] == 'ms'
    assert len(doc['traceEvents']) == n == 4


# ---------------------------------------------------------------------------
# end-to-end tracing through the service
# ---------------------------------------------------------------------------


def test_service_trace_off_by_default():
    rng = np.random.default_rng(0)
    with ExecutionService(_CFG, max_batch_programs=4,
                          max_wait_ms=2.0) as svc:
        h = svc.submit(_mp(), _bits(rng))
        h.result(timeout=60)
        assert h.trace() is None
        assert svc._tracer.maybe_start() is None
        assert svc.dump_trace(os.devnull) == 0


def test_service_trace_full_chain(tmp_path):
    rng = np.random.default_rng(1)
    mps = [_mp(s) for s in range(3)]
    with ExecutionService(_CFG, max_batch_programs=4, max_wait_ms=2.0,
                          trace_sample=1.0) as svc:
        handles = [svc.submit(mp, _bits(rng)) for mp in mps]
        for mp, h in zip(mps, handles):
            h.result(timeout=60)
        for h in handles:
            spans = h.trace()
            assert spans is not None
            names = [s['name'] for s in spans]
            for need in ('submit', 'queued', 'coalesce.ripen',
                         'dispatch', 'execute', 'demux', 'done'):
                assert need in names, f'missing {need!r} in {names}'
            # the duration chain is connected and ordered: each stage
            # starts no earlier than the previous stage's start, and
            # queued -> dispatch -> execute ends are monotonic
            by = {s['name']: s for s in spans}
            assert by['queued']['t0'] <= by['queued']['t1'] \
                <= by['dispatch']['t1'] <= by['execute']['t1'] \
                <= by['demux']['t1']
            assert by['dispatch']['args']['classification'] \
                in ('cold', 'warm', 'aot')
            done = [s for s in spans if s['name'] == 'done']
            assert done[-1]['args']['outcome'] == 'ok'
        p = tmp_path / 'trace.json'
        n = svc.dump_trace(str(p))
    doc = json.loads(p.read_text())
    evs = doc['traceEvents']
    assert len(evs) == n > 0
    assert {e['ph'] for e in evs} <= {'X', 'i'}
    # one tid per request, all three requests present
    assert len({e['tid'] for e in evs}) == 3
    # stage names are drawn from the documented taxonomy
    assert {e['name'] for e in evs if e['ph'] == 'X'} \
        <= set(STAGE_ORDER)


def test_service_trace_sampling_fraction():
    rng = np.random.default_rng(2)
    with ExecutionService(_CFG, max_batch_programs=8, max_wait_ms=2.0,
                          trace_sample=0.5) as svc:
        handles = [svc.submit(_mp(s % 3), _bits(rng))
                   for s in range(8)]
        for h in handles:
            h.result(timeout=60)
        traced = [h for h in handles if h.trace() is not None]
        assert len(traced) == 4        # deterministic 1-in-2


@pytest.mark.chaos
@pytest.mark.skipif(
    _N_DEV < 2,
    reason=f'multi-hop trace test needs >=2 devices (host advertises '
           f'{_N_DEV} device(s); off-TPU force more with '
           f'--xla_force_host_platform_device_count)')
def test_trace_multi_hop_retry_chain_under_chaos(tmp_path):
    """Scripted crashes trip a breaker on a dp=2 pool while every
    request is traced: some retried/migrated request must show the
    full multi-hop chain — retry instants, >= 2 queued spans (one per
    attempt), a migrate or unpark hop — and the breaker trip + chaos
    injections must land in the flight recorder, with the whole chain
    visible in the exported Chrome trace."""
    mps = [_mp(s) for s in range(4)]
    plan = ChaosPlan(seed=7, script=('crash',) * 4)
    with ExecutionService(
            _CFG, max_batch_programs=4, max_wait_ms=2.0,
            max_queue=1024, devices=2,
            retry_policy=RetryPolicy(max_attempts=6, backoff_s=0.005),
            breaker_threshold=2, breaker_cooldown_ms=60.0,
            supervise_interval_ms=10.0, trace_sample=1.0,
            trace_keep=256) as svc:
        for n_programs in (1, 2, 4):
            svc.warmup(mps[0], shots=3, n_programs=n_programs)
        rng = np.random.default_rng(7)
        with ChaosMonkey(svc, plan) as monkey:
            pairs = [(mps[i % 4], _bits(rng)) for i in range(24)]
            handles = [svc.submit(mp, b) for mp, b in pairs]
            for (mp, b), h in zip(pairs, handles):
                got = h.result(timeout=120)
                want = _solo(mp, b)
                for k in want:
                    np.testing.assert_array_equal(
                        np.asarray(got[k]), np.asarray(want[k]))
        assert monkey.script_exhausted()
        retried = [h for h in handles if h.retries >= 1]
        assert retried, 'scripted crashes produced no retried request'
        chains = 0
        for h in retried:
            spans = h.trace()
            names = [s['name'] for s in spans]
            if 'retry' not in names:
                continue       # retried as an uninvolved batch-mate
            assert names.count('queued') >= 2, \
                f'retried request missing per-attempt queued spans: ' \
                f'{names}'
            assert 'batch_error' in names
            assert 'chaos' in names
            done = [s for s in spans if s['name'] == 'done']
            assert len(done) == 1 and done[0]['args']['outcome'] == 'ok'
            chains += 1
        assert chains >= 1
        # the incident is in the flight recorder, in event order
        kinds = [e['kind'] for e in svc.flight_recorder.events()]
        assert 'chaos_inject' in kinds
        assert 'retry' in kinds
        assert 'breaker_trip' in kinds
        trip = svc.flight_recorder.events(kind='breaker_trip')[0]
        assert set(trip) >= {'seq', 't', 'mono', 'kind', 'executor',
                             'breaker'}
        assert trip['breaker']['trips'] >= 1
        # chaos injection precedes the breaker trip it caused
        assert kinds.index('chaos_inject') < kinds.index('breaker_trip')
        p = tmp_path / 'chaos-trace.json'
        n = svc.dump_trace(str(p))
    doc = json.loads(p.read_text())
    names = {e['name'] for e in doc['traceEvents']}
    assert {'retry', 'queued', 'chaos', 'execute', 'done'} <= names
    assert n == len(doc['traceEvents'])


def test_flight_auto_dump_on_executor_death(tmp_path):
    """An injected dispatcher death makes the supervisor dump the
    flight ring into flight_dump_dir — the post-mortem exists without
    anyone asking for it."""
    plan = ChaosPlan(seed=0, script=('die',))
    rng = np.random.default_rng(0)
    with ExecutionService(
            _CFG, max_batch_programs=4, max_wait_ms=2.0,
            retry_policy=RetryPolicy(max_attempts=6, backoff_s=0.005),
            supervise_interval_ms=10.0,
            flight_dump_dir=str(tmp_path)) as svc:
        svc.warmup(_mp(), shots=3, n_programs=1)
        with ChaosMonkey(svc, plan):
            h = svc.submit(_mp(), _bits(rng))
            h.result(timeout=120)
        deadline = time.monotonic() + 30.0
        dump = tmp_path / f'flight-{svc.name}.json'
        while not dump.exists() and time.monotonic() < deadline:
            time.sleep(0.02)
    assert dump.exists(), 'supervisor did not auto-dump the flight ring'
    doc = json.loads(dump.read_text())
    assert 'executor_death' in doc['counts']


# ---------------------------------------------------------------------------
# frozen telemetry manifests
# ---------------------------------------------------------------------------

# every pre-existing stats() key, frozen: renaming one breaks dashboards
_STATS_KEYS = {
    'queue_depth', 'submitted', 'completed', 'failed', 'cancelled',
    'expired', 'rejected', 'dispatches', 'programs_dispatched',
    'batch_occupancy', 'engine_dispatches', 'coalesce_efficiency',
    'n_devices', 'work_stealing', 'steals', 'warmups', 'warmup',
    'supervision', 'health', 'parked', 'retries', 'retry_exhausted',
    'shed', 'overload_rejected', 'breaker_trips', 'readmissions',
    'executor_deaths', 'hangs', 'canary', 'est_wait_ms', 'compile',
    'source', 'devices', 'compile_cache', 'latency_p50_ms',
    'latency_p99_ms', 'latency_samples', 'integrity', 'streaming',
    'tenants', 'calibration',
}
_WARMUP_KEYS = {'aot_compiled', 'replayed', 'in_progress'}
_HEALTH_KEYS = {'live', 'quarantined', 'probing'}
_CANARY_KEYS = {'ok', 'fail'}
_COMPILE_KEYS = {'cold', 'warm', 'per_bucket'}
_SOURCE_KEYS = {'submitted', 'pending_compile'}
_DEVICE_KEYS = {
    'device', 'index', 'busy', 'health', 'queue_depth', 'dispatches',
    'programs_dispatched', 'batch_occupancy', 'engine_dispatches',
    'steals', 'stolen_from', 'cold_compiles', 'warm_hits',
    'home_buckets', 'breaker_trips', 'consecutive_failures',
    'readmissions', 'hangs', 'deaths', 'respawns', 'canary_ok',
    'canary_fail', 'integrity_bad',
}
_INTEGRITY_KEYS = {'audit_sample', 'audit_mode', 'audits',
                   'mismatches', 'scrubber_runs', 'scrubber_fail',
                   'quarantines'}
_STREAMING_KEYS = {'open_sessions', 'rounds_in_flight',
                   'rounds_submitted', 'rounds_served',
                   'round_deadline_misses', 'sessions_opened',
                   'sessions_expired'}
_CALIBRATION_KEYS = {'open_sessions', 'sessions_opened', 'steps',
                     'converged', 'diverged'}
# per-tenant stats block (docs/SERVING.md "Tenants"): the billing
# surface — admission outcomes plus the four usage meters
_TENANT_KEYS = {'queued', 'submitted', 'completed', 'failed', 'shed',
                'quota_rejected', 'shots', 'device_ms', 'compile_ms',
                'bytes_wire', 'weight'}
# serve.* counters the service maintains in the global registry
_SERVE_COUNTERS = {
    'serve.submitted', 'serve.dispatches',
    'serve.programs_dispatched', 'serve.compile.cold',
    'serve.compile.warm',
}


def test_stats_key_manifest_is_byte_compatible():
    rng = np.random.default_rng(5)
    with ExecutionService(_CFG, max_batch_programs=4,
                          max_wait_ms=2.0) as svc:
        handles = [svc.submit(_mp(s), _bits(rng)) for s in range(3)]
        for h in handles:
            h.result(timeout=60)
        snap = svc.stats()
    assert set(snap) == _STATS_KEYS
    assert set(snap['warmup']) == _WARMUP_KEYS
    assert set(snap['health']) == _HEALTH_KEYS
    assert set(snap['canary']) == _CANARY_KEYS
    assert set(snap['compile']) == _COMPILE_KEYS
    assert set(snap['source']) == _SOURCE_KEYS
    assert set(snap['integrity']) == _INTEGRITY_KEYS
    assert set(snap['streaming']) == _STREAMING_KEYS
    assert set(snap['calibration']) == _CALIBRATION_KEYS
    for dev in snap['devices']:
        assert set(dev) == _DEVICE_KEYS
    for label, row in snap['compile']['per_bucket'].items():
        assert set(row) == {'cold', 'warm', 'cold_ms_mean',
                            'warm_ms_mean', 'compile_ms_est'}
    for tenant, row in snap['tenants'].items():
        assert set(row) == _TENANT_KEYS
    assert 'default' in snap['tenants']    # untagged traffic is billed
    assert snap['latency_samples'] == 3


def test_serve_counter_names_preserved():
    rng = np.random.default_rng(6)
    before = {k: profiling.counter_get(k) for k in _SERVE_COUNTERS}
    with ExecutionService(_CFG, max_batch_programs=4,
                          max_wait_ms=2.0) as svc:
        h = svc.submit(_mp(), _bits(rng))
        h.result(timeout=60)
        # second same-shape round hits the warm jit cache
        h2 = svc.submit(_mp(), _bits(rng))
        h2.result(timeout=60)
    after = profiling.counters()
    for name in _SERVE_COUNTERS:
        assert after.get(name, 0) > before[name], \
            f'counter {name!r} did not advance under a served request'
    # the service's latency histogram also feeds the fleet-wide one
    assert profiling.registry().histogram('serve.latency_ms').count >= 1


# serve.stream.* counters, separate from _SERVE_COUNTERS: only a
# streaming session advances them, so the ordinary-submit test above
# must not require them
_STREAM_COUNTERS = {
    'serve.stream.sessions_opened', 'serve.stream.sessions_closed',
    'serve.stream.rounds_submitted', 'serve.stream.rounds_served',
}


def test_stream_counter_names_preserved():
    from distributed_processor_tpu.models.qec import (
        qec_config, qec_multiround_machine_program)
    rng = np.random.default_rng(9)
    mp = qec_multiround_machine_program(n_data=3, rounds=1)
    cfg = qec_config(3, record_pulses=False)
    before = {k: profiling.counter_get(k) for k in _STREAM_COUNTERS}
    with ExecutionService() as svc:
        with svc.open_stream(mp, cfg=cfg) as sess:
            sess.submit_rounds(rng.integers(
                0, 2, (4, 3, mp.n_cores, cfg.max_meas)).astype(np.int32))
            list(sess.results(timeout=60))
        # rounds_served is written by the dispatcher just after the
        # handles resolve; give it its scheduling slice
        deadline = time.monotonic() + 10.0
        while svc.stats()['streaming']['rounds_served'] < 4 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        snap = svc.stats()
    assert set(snap['streaming']) == _STREAMING_KEYS
    assert snap['streaming']['rounds_submitted'] == 4
    assert snap['streaming']['rounds_served'] == 4
    after = profiling.counters()
    for name in _STREAM_COUNTERS:
        assert after.get(name, 0) > before[name], \
            f'counter {name!r} did not advance under a streamed session'


# serve.calib.* counters (docs/SERVING.md "Calibration sessions"),
# separate from _SERVE_COUNTERS for the same reason as the stream set:
# only a calibration session advances them
_CALIB_COUNTERS = {
    'serve.calib.sessions_opened', 'serve.calib.sessions_closed',
    'serve.calib.steps', 'serve.calib.converged',
}


def test_calib_counter_names_preserved():
    from distributed_processor_tpu.models import make_default_qchip
    from distributed_processor_tpu.models.experiments import rabi_program
    qchip = make_default_qchip(2)
    before = {k: profiling.counter_get(k) for k in _CALIB_COUNTERS}
    with ExecutionService() as svc:
        with svc.open_calibration(knob='amplitude') as sess:
            h = sess.submit_step(rabi_program('Q0', 0.3), qchip,
                                 shots=2, n_qubits=2)
            h.result(timeout=120)
            sess.note_loss(0.1)
            sess.mark_converged({'amp': 0.3})
        snap = svc.stats()
    assert set(snap['calibration']) == _CALIBRATION_KEYS
    assert snap['calibration']['sessions_opened'] >= 1
    assert snap['calibration']['steps'] >= 1
    assert snap['calibration']['converged'] >= 1
    assert snap['calibration']['open_sessions'] == 0
    after = profiling.counters()
    for name in _CALIB_COUNTERS:
        assert after.get(name, 0) > before[name], \
            f'counter {name!r} did not advance under a calibration'


# tenant.* counter family (docs/SERVING.md "Tenants"): billing-grade
# per-tenant meters on the global registry, so the fleet rollup sums
# them across replicas for free.  Frozen per-tenant suffixes; the
# family is tenant-name parameterized.
_TENANT_COUNTER_SUFFIXES = {
    'submitted', 'completed', 'shots', 'device_ms',
}


def test_tenant_counter_names_preserved():
    rng = np.random.default_rng(11)
    names = {f'tenant.acme.{s}' for s in _TENANT_COUNTER_SUFFIXES}
    before = {k: profiling.counter_get(k) for k in names}
    with ExecutionService(_CFG, max_batch_programs=4,
                          max_wait_ms=2.0) as svc:
        h = svc.submit(_mp(), _bits(rng), tenant='acme')
        h.result(timeout=60)
    after = profiling.counters()
    for name in names:
        assert after.get(name, 0) > before[name], \
            f'counter {name!r} did not advance under a tenant request'


def test_compile_cache_counters_on_registry():
    from distributed_processor_tpu.compilecache import CompileCache
    from distributed_processor_tpu.models import make_default_qchip

    qchip = make_default_qchip(2)
    prog = [{'name': 'X90', 'qubit': ['Q0']}]
    cache = CompileCache(capacity=8)
    cache.get_or_compile(prog, qchip, n_qubits=2)
    cache.get_or_compile(prog, qchip, n_qubits=2)
    assert profiling.counter_get('compilecache.misses') == 1
    assert profiling.counter_get('compilecache.hits') == 1
    assert profiling.registry().histogram(
        'compilecache.compile_ms').count == 1
    # cache_invalidate lands in an attached flight recorder
    rec = FlightRecorder()
    cache.recorder = rec
    st = cache.stats()
    cache.invalidate_epoch('nonexistent-fp')
    ev = rec.events(kind='cache_invalidate')
    assert len(ev) == 1 and ev[0]['entries'] == 0
    assert cache.stats()['invalidations'] == st['invalidations'] + 1
