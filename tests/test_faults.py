"""Trap-and-report runtime: per-lane fault codes, the static validator,
and the fault-injection harness.

The contract: a fault-free program's outputs are BIT-IDENTICAL with the
fault carry in place (zero-cost ORs in the while-loop state), every
injected defect is rejected statically or trapped with the right code
(never silent), fault counts aggregate through every sweep driver and
survive checkpoint/resume, and an unreadable checkpoint is quarantined
instead of crashing the campaign.  docs/ROBUSTNESS.md is the prose
spec.
"""

import json
import os
import signal
import subprocess
import sys
import time
import warnings

import numpy as np
import pytest

from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import (ProgramValidationError,
                                               machine_program_from_cmds,
                                               validate_program)
from distributed_processor_tpu.models import active_reset
from distributed_processor_tpu.parallel import (make_mesh,
                                                run_multi_sweep,
                                                run_physics_sweep)
from distributed_processor_tpu.sim import faultinject as fi
from distributed_processor_tpu.sim.interpreter import (FAULT_CODES,
                                                       FaultError,
                                                       InterpreterConfig,
                                                       fault_shot_counts,
                                                       simulate_batch)
from distributed_processor_tpu.sim.physics import ReadoutPhysics
from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.utils.results import (SweepAccumulator,
                                                     save_results)


def _fault_names(fault):
    counts = np.asarray(fault_shot_counts(fault))
    return {name for (name, _), c in zip(FAULT_CODES, counts) if c}


def _loop_mp(iters=1000):
    """Counted loop whose iteration count dwarfs any small step budget."""
    core = [isa.alu_cmd('reg_alu', 'i', iters, 'id0', write_reg_addr=0),
            isa.pulse_cmd(amp_word=1000, cfg_word=0, env_word=3,
                          cmd_time=10),
            isa.alu_cmd('reg_alu', 'i', -1, 'add', 0, write_reg_addr=0),
            isa.alu_cmd('jump_cond', 'i', 0, 'le', 0, jump_cmd_ptr=1),
            isa.done_cmd()]
    return machine_program_from_cmds([core])


# ---------------------------------------------------------------------------
# fault-free bit-identity
# ---------------------------------------------------------------------------

def test_fault_free_zero_on_all_engines():
    """A valid branch-free program reports an all-zero fault word on
    every engine, and the engines agree bit-for-bit on the outputs."""
    cmds = [[isa.pulse_cmd(amp_word=1000, cfg_word=0, env_word=3,
                           cmd_time=10 + 20 * i) for i in range(3)]
            + [isa.done_cmd()]] * 2
    mp = machine_program_from_cmds(cmds)
    mb = np.zeros((4, mp.n_cores, 2), np.int32)
    outs = {}
    for eng in fi.ENGINES:
        out = simulate_batch(mp, mb, cfg=InterpreterConfig(
            max_steps=64, max_meas=2, engine=eng))
        assert _fault_names(out['fault']) == set(), eng
        outs[eng] = out
    for eng in ('block', 'straightline'):
        np.testing.assert_array_equal(outs['generic']['n_pulses'],
                                      outs[eng]['n_pulses'], eng)
        np.testing.assert_array_equal(outs['generic']['regs'],
                                      outs[eng]['regs'], eng)


def test_fault_free_simulator_run():
    sim = Simulator(n_qubits=2)
    out = sim.run(active_reset(['Q0', 'Q1']), shots=8, p1=0.5)
    assert _fault_names(out['fault']) == set()


# ---------------------------------------------------------------------------
# BUDGET_EXHAUSTED through every execution path (acceptance criterion:
# single, multi-program, spanned, mesh-sharded; checkpoint round-trip)
# ---------------------------------------------------------------------------

def test_budget_exhaustion_single():
    mp = _loop_mp()
    mb = np.zeros((4, 1, 2), np.int32)
    out = simulate_batch(mp, mb, cfg=InterpreterConfig(max_steps=6,
                                                       max_meas=2))
    assert _fault_names(out['fault']) == {'budget_exhausted'}
    counts = np.asarray(fault_shot_counts(out['fault']))
    assert counts[0] == 4           # every shot trapped


def test_budget_exhaustion_multi_span_mesh_checkpoint(tmp_path):
    """The counted-loop budget trap reports identically through the
    ensemble driver's host loop, span path, and dp=2 mesh path, and the
    counts survive a checkpoint/resume round-trip bit-identically."""
    mps = [_loop_mp(), _loop_mp(1)]     # [0] traps, [1] finishes
    kw = dict(total_shots=16, batch=4, p1=0.5, key=3, max_steps=8,
              max_meas=2)
    with warnings.catch_warnings():
        warnings.simplefilter('ignore', UserWarning)
        full = run_multi_sweep(mps, **kw)
        assert full['fault_shots']['budget_exhausted'].tolist() == [16, 0]
        for name, _ in FAULT_CODES[1:]:
            assert full['fault_shots'][name].tolist() == [0, 0], name
        spanned = run_multi_sweep(mps, span=2, **kw)
        mesh = run_multi_sweep(mps, mesh=make_mesh(n_dp=2), **kw)
        # interrupted at half the shots, then resumed to the full count
        ck = str(tmp_path / 'faults.npz')
        run_multi_sweep(mps, checkpoint=ck, checkpoint_every=1,
                        **{**kw, 'total_shots': 8})
        resumed = run_multi_sweep(mps, checkpoint=ck, checkpoint_every=1,
                                  **kw)
    for name, _ in FAULT_CODES:
        ref = full['fault_shots'][name].tolist()
        assert spanned['fault_shots'][name].tolist() == ref, name
        assert mesh['fault_shots'][name].tolist() == ref, name
        assert resumed['fault_shots'][name].tolist() == ref, name


def test_budget_exhaustion_physics_sweep():
    """The physics driver exposes summed per-code counts and its strict
    mode raises AFTER completing (counts preserved on the error)."""
    sim = Simulator(n_qubits=2)
    mp = sim.compile(active_reset(['Q0', 'Q1']))
    model = ReadoutPhysics(sigma=0.01, p1_init=0.5)
    kw = dict(max_steps=3, max_pulses=8, max_meas=2)
    with warnings.catch_warnings():
        warnings.simplefilter('ignore', UserWarning)
        out = run_physics_sweep(mp, model, 32, 16, key=5, **kw)
        assert out['fault_shots']['budget_exhausted'] == 32
        with pytest.raises(FaultError) as ei:
            run_physics_sweep(mp, model, 32, 16, key=5,
                              fault_mode='strict', **kw)
    assert np.asarray(ei.value.counts)[0] == 32


def test_strict_mode_simulator_run():
    mp = _loop_mp()
    sim = Simulator(n_qubits=1)
    with pytest.raises(FaultError):
        sim.run(mp, shots=4, p1=0.5, max_steps=6, max_meas=2,
                fault_mode='strict')


# ---------------------------------------------------------------------------
# static validator
# ---------------------------------------------------------------------------

def test_validator_jump_oob():
    cmds = [[isa.pulse_cmd(amp_word=100, cfg_word=0, env_word=3,
                           cmd_time=10),
             isa.jump_i(99), isa.done_cmd()]]
    with pytest.raises(ProgramValidationError) as ei:
        validate_program(machine_program_from_cmds(cmds))
    assert 'jump_oob' in ei.value.codes
    (code, core, instr, msg), = [e for e in ei.value.errors
                                 if e[0] == 'jump_oob']
    assert (core, instr) == (0, 1) and '99' in msg


def test_validator_no_done_and_infinite_loop():
    pulse = isa.pulse_cmd(amp_word=100, cfg_word=0, env_word=3,
                          cmd_time=10)
    with pytest.raises(ProgramValidationError) as ei:
        validate_program(machine_program_from_cmds([[pulse, pulse]]))
    assert 'no_done' in ei.value.codes
    with pytest.raises(ProgramValidationError) as ei:
        validate_program(machine_program_from_cmds(
            [[pulse, isa.jump_i(0), isa.done_cmd()]]))
    assert 'infinite_loop' in ei.value.codes


def test_validator_sync_mismatch_and_coordinates():
    """Branch-free participants with diverging barrier sequences are a
    static reject; the error names both cores."""
    pulse = isa.pulse_cmd(amp_word=100, cfg_word=0, env_word=3,
                          cmd_time=10)
    cmds = [[pulse, isa.sync(0), isa.done_cmd()],
            [pulse, isa.sync(1), isa.done_cmd()]]
    with pytest.raises(ProgramValidationError) as ei:
        validate_program(machine_program_from_cmds(cmds))
    assert 'sync_mismatch' in ei.value.codes


def test_validator_accepts_valid_programs():
    sim = Simulator(n_qubits=2)
    mp = sim.compile(active_reset(['Q0', 'Q1']))
    validate_program(mp, sim.interpreter_config(mp))   # no raise
    validate_program(_loop_mp())                       # counted loop ok


# ---------------------------------------------------------------------------
# checkpoint quarantine (satellite 1)
# ---------------------------------------------------------------------------

def _write_checkpoint(path):
    save_results(path, {'x': np.arange(64, dtype=np.int64)},
                 meta={'n_batches': 3, 'fingerprint_version': 5})


def test_quarantine_truncated_checkpoint(tmp_path):
    ck = str(tmp_path / 'acc.npz')
    _write_checkpoint(ck)
    data = open(ck, 'rb').read()
    with open(ck, 'wb') as f:
        f.write(data[:len(data) // 2])
    with pytest.warns(UserWarning, match='quarantined'):
        acc = SweepAccumulator.resume(ck, checkpoint_every=1)
    assert acc.n_batches == 0 and acc.state == {}
    assert not os.path.exists(ck)
    assert os.path.exists(ck + '.corrupt-0')
    # a second corruption gets its own specimen number
    _write_checkpoint(ck)
    with open(ck, 'r+b') as f:
        f.truncate(10)
    with pytest.warns(UserWarning, match='quarantined'):
        SweepAccumulator.resume(ck)
    assert os.path.exists(ck + '.corrupt-1')


def test_quarantine_bitflipped_checkpoint(tmp_path):
    import struct
    import zipfile
    ck = str(tmp_path / 'acc.npz')
    _write_checkpoint(ck)
    with zipfile.ZipFile(ck) as z:
        info = z.getinfo('x.npy')
    data = bytearray(open(ck, 'rb').read())
    # flip one bit INSIDE the member's compressed payload (the local
    # header's own name/extra lengths locate it; zip slack bytes would
    # be silently ignored)
    ho = info.header_offset
    fnlen, eflen = struct.unpack('<HH', bytes(data[ho + 26:ho + 30]))
    data[ho + 30 + fnlen + eflen + info.compress_size // 2] ^= 0xff
    with open(ck, 'wb') as f:
        f.write(bytes(data))
    with pytest.warns(UserWarning, match='quarantined'):
        acc = SweepAccumulator.resume(ck)
    assert acc.n_batches == 0
    assert os.path.exists(ck + '.corrupt-0')


def test_quarantine_strict_raises(tmp_path):
    ck = str(tmp_path / 'acc.npz')
    _write_checkpoint(ck)
    with open(ck, 'r+b') as f:
        f.truncate(8)
    with pytest.raises(ValueError, match='unreadable'):
        SweepAccumulator.resume(ck, meta={'fingerprint_version': 5},
                                strict=True)
    assert os.path.exists(ck)       # strict quarantines nothing
    assert not os.path.exists(ck + '.corrupt-0')


# ---------------------------------------------------------------------------
# CLI surface (satellite 4)
# ---------------------------------------------------------------------------

def _cli_prog(tmp_path):
    prog = tmp_path / 'prog.json'
    prog.write_text(json.dumps([{'name': 'X90', 'qubit': ['Q0']},
                                {'name': 'read', 'qubit': ['Q0']}]))
    return str(prog)


def test_cli_run_fault_table_and_strict(tmp_path, capsys):
    from distributed_processor_tpu.cli import main
    prog = _cli_prog(tmp_path)
    main(['--qubits', '1', 'run', prog, '--shots', '4',
          '--max-steps', '2'])
    cap = capsys.readouterr()
    out = json.loads(cap.out)
    assert out['fault_shots']['budget_exhausted'] == 4
    assert 'fault summary' in cap.err
    with pytest.raises(SystemExit) as ei:
        main(['--qubits', '1', 'run', prog, '--shots', '4',
              '--max-steps', '2', '--strict-faults'])
    assert ei.value.code == 2
    capsys.readouterr()
    # fault-free: no table, no nonzero counts
    main(['--qubits', '1', 'run', prog, '--shots', '4'])
    cap = capsys.readouterr()
    assert 'fault summary' not in cap.err
    assert not any(json.loads(cap.out)['fault_shots'].values())


def test_cli_sweep_fault_table_and_strict(tmp_path, capsys):
    from distributed_processor_tpu.cli import main
    prog = _cli_prog(tmp_path)
    argv = ['--qubits', '1', 'sweep', prog, '--shots', '16',
            '--batch', '8', '--sigma', '0.01', '--p1-init', '0.5',
            '--max-steps', '2']
    with warnings.catch_warnings():
        warnings.simplefilter('ignore', UserWarning)
        main(argv)
        cap = capsys.readouterr()
        assert json.loads(cap.out)['fault_shots']['budget_exhausted'] == 16
        assert 'fault summary' in cap.err
        with pytest.raises(SystemExit) as ei:
            main(argv + ['--strict-faults'])
    assert ei.value.code == 2
    assert 'budget_exhausted' in capsys.readouterr().err


# ---------------------------------------------------------------------------
# preemption safety (satellite 2): SIGKILL a checkpointed sweep mid-run,
# resume, and the final statistics are bit-identical
# ---------------------------------------------------------------------------

_SWEEP_CHILD = '''
import sys
from distributed_processor_tpu.cli import main
main(sys.argv[1:])
'''


def test_sweep_survives_sigkill(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    prog = _cli_prog(tmp_path)
    ck = str(tmp_path / 'kill.npz')
    argv = ['--qubits', '1', 'sweep', prog, '--shots', '64',
            '--batch', '4', '--sigma', '0.01', '--p1-init', '0.5',
            '--key', '7']
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               PYTHONPATH=repo + os.pathsep
               + os.environ.get('PYTHONPATH', ''))
    # uninterrupted reference, in this process (compile cache warm)
    from distributed_processor_tpu.cli import main
    import io, contextlib
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        main(argv)
    ref = json.loads(buf.getvalue())

    child = subprocess.Popen(
        [sys.executable, '-c', _SWEEP_CHILD] + argv
        + ['--checkpoint', ck, '--checkpoint-every', '1'],
        env=env, cwd=repo, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)
    # kill -9 as soon as the first checkpoint lands (mid-run for any
    # interesting interleaving; if the child wins the race the resume
    # below still must reproduce the reference exactly)
    deadline = time.time() + 120
    while time.time() < deadline and child.poll() is None \
            and not os.path.exists(ck):
        time.sleep(0.05)
    if child.poll() is None:
        child.send_signal(signal.SIGKILL)
    child.wait()
    assert os.path.exists(ck), 'child never wrote a checkpoint'

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        main(argv + ['--checkpoint', ck, '--checkpoint-every', '1'])
    resumed = json.loads(buf.getvalue())
    assert resumed == ref


# ---------------------------------------------------------------------------
# fault-injection harness (tier-1 slice of tools/faultfuzz.py)
# ---------------------------------------------------------------------------

@pytest.mark.faults
def test_fuzz_quick_slice():
    """One (base x mutator) cycle: every mutant rejected, trapped, or
    provably benign — no SILENT/MISTRAPPED/INCONSISTENT verdicts."""
    rep = fi.run_fuzz(seed=0, n=28)
    assert rep.ok, rep.failures
    assert rep.n == 28


@pytest.mark.faults
def test_fuzz_vmap_consistency():
    assert fi.check_vmap_consistency(seed=0, n=4) == 0


@pytest.mark.faults
def test_fuzz_mesh_consistency():
    bad = fi.check_mesh_consistency(seed=0, n=2)
    assert bad <= 0                 # -1 = skipped (<2 devices)
