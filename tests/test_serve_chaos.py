"""Self-healing serving under injected faults (serve/chaos.py).

What test_serve.py pins on the happy path, this suite pins UNDER FIRE:
with seeded crashes, hangs, slowdowns and dispatcher deaths injected
below the retry/breaker machinery, every handle still terminates
(zero ``result()`` timeouts — the deadlock class the supervision layer
exists to prevent), every completion is still bit-identical to its
solo ``simulate_batch`` run, and every failure is a TYPED error.
Around the soak: the breaker trip → quarantine → canary → re-admit
lifecycle on a single executor, retry-budget exhaustion surfacing the
ORIGINAL infrastructure error, overload shedding and deadline-aware
early rejection, dead-dispatcher respawn, the hang watchdog retrying
elsewhere while the straggler's stale attempt token discards its late
completion, and the forced-shutdown no-deadlock regression.

The soak needs >= 2 devices (quarantine with a surviving peer); the
module skips only on a genuinely single-device host and
tools/check_junit.py fails CI when it skips on anything else (the
chaos mirror of the multi-device BAD SKIP gate).
"""

import threading
import time

import numpy as np
import pytest

import jax

from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import machine_program_from_cmds
from distributed_processor_tpu.serve import (CancelledError, ChaosError,
                                             ChaosMonkey, ChaosPlan,
                                             ChaosThreadDeath,
                                             CircuitBreaker,
                                             ExecutionService,
                                             OverloadError, RetryPolicy,
                                             ShutdownError)
from distributed_processor_tpu.serve.chaos import soak
from distributed_processor_tpu.serve.request import RequestHandle
from distributed_processor_tpu.serve.service import _normalize_cfg
from distributed_processor_tpu.sim.interpreter import (InterpreterConfig,
                                                       simulate_batch)

_N_DEV = len(jax.devices())

pytestmark = [
    pytest.mark.chaos,
    pytest.mark.serve,
    pytest.mark.skipif(
        _N_DEV < 2,
        reason=f'serve chaos tests need >=2 devices (host advertises '
               f'{_N_DEV} device(s); off-TPU force more with '
               f'--xla_force_host_platform_device_count)'),
]


def _mp(salt=0):
    """Branch-free single-core program in the 8-instruction bucket;
    ``salt`` varies the pulse words so distinct requests carry
    distinct contents inside one shape bucket."""
    core = [isa.pulse_cmd(amp_word=1000 + 7 * salt + 13 * i, cfg_word=0,
                          env_word=3, cmd_time=10 + 20 * i)
            for i in range(3)] + [isa.done_cmd()]
    return machine_program_from_cmds([core])


_CFG = InterpreterConfig(max_steps=2 * 8 + 64, max_pulses=8 + 2,
                         max_meas=2, max_resets=2)


def _solo(mp, bits):
    ncfg, _ = _normalize_cfg(_CFG, isa.shape_bucket(mp.n_instr))
    return jax.tree.map(np.asarray, simulate_batch(mp, bits, cfg=ncfg))


def _assert_same(got, want, label=''):
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_array_equal(
            np.asarray(got[k]), np.asarray(want[k]),
            err_msg=f'{label}: stat {k!r} diverged')


def _bits(rng, shots=3):
    return rng.integers(0, 2, size=(shots, 1, 2)).astype(np.int32)


def _wait_all_live(svc, timeout_s=30.0):
    """Poll until every executor is re-admitted (canary probes run on
    the supervisor's cadence, so re-admission is asynchronous)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        health = svc.stats()['health']
        if health['live'] == len(svc._executors):
            return
        time.sleep(0.02)
    raise AssertionError(
        f'executors not all re-admitted within {timeout_s} s: '
        f'{svc.stats()["health"]}')


def _svc(**kw):
    base = dict(max_batch_programs=4, max_wait_ms=2.0, max_queue=1024,
                retry_policy=RetryPolicy(max_attempts=6,
                                         backoff_s=0.005),
                breaker_threshold=2, breaker_cooldown_ms=60.0,
                supervise_interval_ms=10.0)
    base.update(kw)
    return ExecutionService(_CFG, **base)


# -- the acceptance soak ------------------------------------------------


def test_chaos_soak_dp2_terminates_bit_identical():
    """>=100 requests against a dp=2 pool while the monkey injects a
    scripted breaker trip, then probabilistic crashes/hangs/slowdowns.
    Every handle terminates, every completion is bit-identical, the
    quarantined executor is re-admitted and SERVES again within this
    test (the post-chaos clean round)."""
    mps = [_mp(s) for s in range(4)]
    # 4 scripted crashes over 2 executors: by pigeonhole at least one
    # breaker (threshold 2) reaches its streak and trips — the soak is
    # guaranteed a quarantine + canary re-admission regardless of how
    # the dispatchers interleave their draws
    plan = ChaosPlan(seed=7, script=('crash',) * 4,
                     p_crash=0.10, p_hang=0.02, p_slow=0.10,
                     hang_s=0.8, slow_s=0.005)
    with _svc(devices=2, hang_timeout_s=0.3) as svc:
        # pre-compile every occupancy on both devices: a cold XLA
        # compile inside a dispatch would read as a hang to the 0.3 s
        # watchdog and the soak would measure compile churn, not chaos
        for n_programs in (1, 2, 4):
            svc.warmup(mps[0], shots=3, n_programs=n_programs)
        with ChaosMonkey(svc, plan) as monkey:
            report = soak(svc, mps, _CFG, n_requests=100, shots=3,
                          seed=7, result_timeout_s=120.0)
        assert monkey.script_exhausted()
        assert report.submitted == 100
        assert report.hung == 0, 'a handle result() timed out'
        assert report.bit_mismatches == 0
        assert report.terminated() == report.submitted
        # under a 6-attempt budget and ~10% crash rate nothing should
        # exhaust its retries; every submission completes
        assert report.completed == 100, dict(report.errors)
        stats = svc.stats()
        assert stats['breaker_trips'] >= 1
        assert stats['readmissions'] >= 1
        assert stats['retries'] >= report.retries >= 1
        # chaos is uninstalled: canaries now run clean, so every
        # executor must come back, and a clean round must serve on it
        _wait_all_live(svc)
        rng = np.random.default_rng(123)
        post = [(mp, _bits(rng)) for mp in mps for _ in range(2)]
        handles = [svc.submit(mp, b, cfg=_CFG) for mp, b in post]
        for (mp, b), h in zip(post, handles):
            _assert_same(h.result(timeout=60.0), _solo(mp, b),
                         'post-chaos round')
        assert svc.stats()['health']['quarantined'] == 0


# -- breaker lifecycle --------------------------------------------------


def test_breaker_trip_quarantine_canary_readmit_single_executor():
    """Two scripted crashes on the ONLY executor: breaker trips, the
    in-flight request parks, a canary probe re-admits after cooldown,
    and the parked request then completes bit-identically — service
    heals with no healthy peer to lean on."""
    mp, bits = _mp(), _bits(np.random.default_rng(0))
    plan = ChaosPlan(seed=0, script=('crash', 'crash'))
    with _svc() as svc:
        with ChaosMonkey(svc, plan):
            h = svc.submit(mp, bits, cfg=_CFG)
            got = h.result(timeout=60.0)
        _assert_same(got, _solo(mp, bits), 'healed request')
        assert h.retries == 2
        stats = svc.stats()
        assert stats['breaker_trips'] >= 1
        assert stats['readmissions'] >= 1
        assert stats['canary']['ok'] >= 1
        assert stats['health']['live'] == 1


def test_circuit_breaker_unit():
    br = CircuitBreaker(threshold=2, cooldown_s=1.0, cooldown_mult=2.0,
                        max_cooldown_s=3.0)
    assert not br.record_failure()
    assert br.record_failure()          # streak hits the threshold
    br.trip(now=100.0)
    assert br.trips == 1
    assert not br.ready_to_probe(100.5)
    assert br.ready_to_probe(101.0)
    br.trip(now=101.0)                  # failed canary: cooldown doubles
    assert not br.ready_to_probe(102.5)
    assert br.ready_to_probe(103.0)
    br.readmit()
    assert br.readmissions == 1
    assert br.consecutive == 0
    br.record_failure()
    br.record_success()                 # success resets the streak
    assert br.consecutive == 0
    br.trip(now=200.0)                  # re-admission reset the cooldown
    assert br.ready_to_probe(201.0)


def test_retry_policy_schedule():
    p = RetryPolicy(max_attempts=4, backoff_s=0.02, backoff_mult=2.0,
                    max_backoff_s=0.05)
    assert [p.delay_s(i) for i in range(4)] == [0.02, 0.04, 0.05, 0.05]
    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError):
        ChaosPlan(script=('explode',))
    with pytest.raises(ValueError):
        ChaosPlan(p_crash=0.9, p_hang=0.2)


# -- retry budget -------------------------------------------------------


def test_retry_budget_exhaustion_surfaces_original_error():
    """A request that crashes on every attempt fails with the ORIGINAL
    infrastructure error once the RetryPolicy budget is spent — typed,
    not a timeout, not a generic wrapper."""
    mp, bits = _mp(), _bits(np.random.default_rng(1))
    plan = ChaosPlan(seed=0, script=('crash',) * 8)
    with _svc(retry_policy=RetryPolicy(max_attempts=2,
                                       backoff_s=0.005),
              breaker_threshold=100) as svc:
        with ChaosMonkey(svc, plan):
            h = svc.submit(mp, bits, cfg=_CFG)
            with pytest.raises(ChaosError, match='injected crash'):
                h.result(timeout=60.0)
        assert h.retries == 1           # attempt 2 of 2 was the last
        stats = svc.stats()
        assert stats['retry_exhausted'] == 1
        assert stats['failed'] >= 1


# -- overload control ---------------------------------------------------


def test_overload_shed_and_deadline_reject():
    """With the executor pinned busy and a warm service-time EWMA, a
    higher-priority submission sheds the lowest-priority queued request
    (it fails with OverloadError) and a submission whose deadline the
    estimated wait already exceeds is rejected at admission."""
    mp = _mp()
    rng = np.random.default_rng(2)
    with _svc(devices=None, max_batch_programs=1, max_wait_ms=0.0,
              max_est_wait_ms=0.001, supervision=False) as svc:
        # warm the EWMA (depth is 0 at each submit, so admission passes)
        for _ in range(2):
            svc.submit(mp, _bits(rng), cfg=_CFG).result(timeout=60.0)
        assert svc.stats()['est_wait_ms'] is not None
        started, release = threading.Event(), threading.Event()
        orig = svc._run_batch

        def pinned(ex, key, batch, cfg):
            started.set()
            release.wait(30.0)
            return orig(ex, key, batch, cfg)

        svc._run_batch = pinned
        try:
            busy = svc.submit(mp, _bits(rng), cfg=_CFG)
            assert started.wait(30.0)
            # depth 0 (the busy batch is claimed): admitted and queued
            low = svc.submit(mp, _bits(rng), cfg=_CFG, priority=0)
            # depth 1 -> est wait > max_est_wait_ms: the higher-priority
            # newcomer evicts the queued low-priority request
            high = svc.submit(mp, _bits(rng), cfg=_CFG, priority=1)
            with pytest.raises(OverloadError, match='shed'):
                low.result(timeout=5.0)
            # deadline-aware early reject: the estimate alone already
            # blows this deadline, so admission refuses synchronously
            with pytest.raises(OverloadError, match='deadline'):
                svc.submit(mp, _bits(rng), cfg=_CFG, deadline_ms=0.01)
            # nothing of lower priority queued -> the newcomer itself
            # is refused
            with pytest.raises(OverloadError, match='overloaded'):
                svc.submit(mp, _bits(rng), cfg=_CFG, priority=0)
        finally:
            release.set()
        assert busy.result(timeout=60.0)
        assert high.result(timeout=60.0)
        stats = svc.stats()
        assert stats['shed'] == 1
        assert stats['overload_rejected'] == 2


# -- executor death and hang --------------------------------------------


def test_dispatcher_death_respawn_and_recovery():
    """An injected BaseException kills the dispatcher thread outright;
    the supervisor detects the dead thread, recovers the in-flight
    batch, respawns the dispatcher, and the request completes."""
    mp, bits = _mp(), _bits(np.random.default_rng(3))
    plan = ChaosPlan(seed=0, script=('die',))
    with _svc() as svc:
        with ChaosMonkey(svc, plan):
            h = svc.submit(mp, bits, cfg=_CFG)
            got = h.result(timeout=60.0)
        _assert_same(got, _solo(mp, bits), 'post-death request')
        assert h.retries >= 1
        stats = svc.stats()
        assert stats['executor_deaths'] == 1
        assert stats['devices'][0]['respawns'] == 1
        assert stats['readmissions'] >= 1
        assert stats['health']['live'] == 1


def test_hang_watchdog_retries_elsewhere_stale_attempt_discarded():
    """A dispatch hung past ``hang_timeout_s`` is detected by the
    watchdog and retried on the healthy peer well before the hang
    resolves; when the straggler finally completes, its stale attempt
    token discards the late result instead of double-completing the
    handle."""
    mp, bits = _mp(), _bits(np.random.default_rng(4))
    plan = ChaosPlan(seed=0, script=('hang',), hang_s=1.5)
    with _svc(devices=2, hang_timeout_s=0.3) as svc:
        # warm both executors so the retry is not a cold compile
        svc.warmup(mp, shots=3, n_programs=1)
        with ChaosMonkey(svc, plan):
            t0 = time.monotonic()
            h = svc.submit(mp, bits, cfg=_CFG)
            got = h.result(timeout=60.0)
            dt = time.monotonic() - t0
            _assert_same(got, _solo(mp, bits), 'watchdog retry')
            assert dt < 1.4, (
                f'completion took {dt:.2f} s: the watchdog did not '
                f'retry ahead of the 1.5 s hang')
            assert h.retries >= 1
            # let the straggler finish INSIDE the chaos window and
            # prove its stale completion was discarded, not raced
            time.sleep(1.6 - dt if dt < 1.6 else 0)
            _assert_same(h.result(timeout=1.0), _solo(mp, bits),
                         'post-straggler result unchanged')
        stats = svc.stats()
        assert stats['hangs'] >= 1
        assert stats['breaker_trips'] >= 1


# -- cancel vs retry race ----------------------------------------------


def test_attempt_token_blocks_stale_completion():
    h = RequestHandle()
    t1 = h._claim()
    assert t1 and not h.done()
    assert h._requeue(t1)               # supervision retried it
    assert h.retries == 1
    assert not h._fulfill({'x': 1}, token=t1)   # straggler: stale token
    assert not h._fail(RuntimeError('stale'), token=t1)
    assert not h.done()
    t2 = h._claim()
    assert t2 and t2 != t1
    assert h._fulfill({'x': 2}, token=t2)
    assert h.result(timeout=0) == {'x': 2}


def test_cancel_racing_retry_never_double_runs():
    """cancel() between an infrastructure failure and the retry
    re-queue wins: the handle is CancelledError, the retry re-queue is
    refused, and a straggling attempt can no longer complete it."""
    h = RequestHandle()
    tok = h._claim()
    assert not h.cancel()               # in flight: past the boundary
    assert h._requeue(tok)              # infra failure parks it...
    assert h.cancel()                   # ...and cancel wins the race
    assert not h._requeue(tok)          # stale retry: refused
    assert h._claim() == 0              # never dispatches again
    assert not h._fulfill({'x': 3})
    assert h.cancelled()
    with pytest.raises(CancelledError):
        h.result(timeout=0)


def test_cancel_during_retry_backoff_in_service():
    """Integration: a request parked for retry backoff is cancellable;
    the parked entry is dropped and never re-dispatched."""
    mp, bits = _mp(), _bits(np.random.default_rng(5))
    plan = ChaosPlan(seed=0, script=('crash',) * 4)
    with _svc(retry_policy=RetryPolicy(max_attempts=6, backoff_s=0.5),
              breaker_threshold=100) as svc:
        with ChaosMonkey(svc, plan):
            h = svc.submit(mp, bits, cfg=_CFG)
            deadline = time.monotonic() + 30.0
            while h.retries == 0 and time.monotonic() < deadline:
                time.sleep(0.005)       # first crash parks it
            assert h.retries >= 1
            assert h.cancel()
            with pytest.raises(CancelledError):
                h.result(timeout=5.0)
        # the parked entry must drain without dispatching the handle
        deadline = time.monotonic() + 10.0
        while svc.stats()['parked'] and time.monotonic() < deadline:
            time.sleep(0.01)
        assert svc.stats()['parked'] == 0
        assert h.cancelled()


# -- forced shutdown ----------------------------------------------------


def test_forced_shutdown_never_deadlocks_result():
    """Regression for the satellite contract: after
    ``shutdown(drain=False)`` with a dispatch wedged mid-flight,
    ``result(timeout=)`` raises typed ShutdownError — it must never
    deadlock, and the straggler's late completion must not overwrite
    the shutdown failure."""
    mp = _mp()
    rng = np.random.default_rng(6)
    started, release = threading.Event(), threading.Event()
    svc = _svc(supervision=False)
    orig = svc._run_batch

    def wedged(ex, key, batch, cfg):
        started.set()
        release.wait(30.0)
        return orig(ex, key, batch, cfg)

    svc._run_batch = wedged
    try:
        h_flight = svc.submit(mp, _bits(rng), cfg=_CFG)
        assert started.wait(30.0)
        h_queued = svc.submit(mp, _bits(rng), cfg=_CFG)
        svc.shutdown(drain=False, timeout=0.3)
        for h in (h_flight, h_queued):
            with pytest.raises(ShutdownError):
                h.result(timeout=5.0)
        assert isinstance(h_flight.exception(timeout=0),
                          CancelledError)   # ShutdownError subclasses it
    finally:
        release.set()
        # join the straggling dispatcher so no thread outlives the test
        # (the conftest leak probe watches the whole dproc-serve family)
        svc.shutdown(drain=False)
    with pytest.raises(ShutdownError):
        h_flight.result(timeout=0)      # the late completion was stale
