"""Resonator ring-up readout channel (ReadoutPhysics.ring_tau).

Round-2 review item 2: the per-sample resolve paths must have modeling
power the analytic matched-filter shortcut cannot collapse.  With
``ring_tau > 0`` the state-dependent transmission builds up as
``1 - exp(-(s+1)/ring_tau)`` over the window, so early samples carry
less discrimination information than their energy suggests — the
per-sample/fused modes simulate it, the analytic mode (exact only for
the flat response) is now measurably optimistic at short windows.
"""

import warnings

import numpy as np
import pytest

from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)

KW = dict(max_steps=200, max_pulses=16, max_meas=4)


@pytest.fixture(scope='module')
def read_mp():
    sim = Simulator(n_qubits=1)
    return sim.compile([{'name': 'read', 'qubit': ['Q0']}])


def _err_rate(mp, model, B=768, key=11):
    """Assignment error of the resolved bits against the device state."""
    init = (np.arange(B) % 2).astype(np.int32).reshape(B, 1)
    out = run_physics_batch(mp, model, key, B, init_states=init, **KW)
    assert not bool(out['incomplete'])
    bits = np.asarray(out['meas_bits'])[:, 0, 0]
    return float(np.mean(bits != init[:, 0]))


def test_sigma_zero_ring_keeps_assignment(read_mp):
    """Pure attenuation without noise: discrimination margins shrink
    symmetrically (default g0/g1), bits still match the state."""
    model = ReadoutPhysics(sigma=0.0, ring_tau=256.0, window_samples=256)
    assert _err_rate(read_mp, model) == 0.0


def test_persample_fused_bit_identical_with_ring(read_mp):
    """The fused Pallas kernel implements the same ring-up contract:
    bit-identical to the XLA per-sample path at sigma=0."""
    init = (np.arange(32) % 2).astype(np.int32).reshape(32, 1)
    outs = {}
    for mode in ('persample', 'fused'):
        model = ReadoutPhysics(sigma=0.0, ring_tau=96.0,
                               window_samples=128, resolve_mode=mode)
        outs[mode] = np.asarray(run_physics_batch(
            read_mp, model, 5, 32, init_states=init, **KW)['meas_bits'])
    np.testing.assert_array_equal(outs['persample'], outs['fused'])


def test_ring_degrades_fidelity_vs_analytic(read_mp):
    """The review's 'done' criterion: per-sample and analytic modes
    measurably differ in assignment fidelity once the channel has
    structure.  sigma is set so the flat model is nearly error-free
    while the rung-up channel (~2.7x SNR loss at W = ring_tau) is not."""
    kw = dict(sigma=4.0, ring_tau=256.0, window_samples=256)
    err_ps = _err_rate(read_mp, ReadoutPhysics(**kw))
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')   # analytic+ring warns by design
        err_an = _err_rate(
            read_mp, ReadoutPhysics(**kw, resolve_mode='analytic'))
    assert err_an < 0.02, err_an          # flat model: near-perfect
    assert err_ps > err_an + 0.05, (err_ps, err_an)   # structure matters


def test_fidelity_vs_window_length_curve(read_mp):
    """The calibration curve: with ring_tau fixed, assignment fidelity
    improves monotonically with window length (longer windows integrate
    past the transient) — examples/readout_window_calibration.py plots
    exactly this sweep."""
    errs = []
    for w in (64, 256, 1024):
        model = ReadoutPhysics(sigma=4.0, ring_tau=128.0,
                               window_samples=w)
        errs.append(_err_rate(read_mp, model))
    assert errs[0] > errs[1] > errs[2], errs
    assert errs[2] < 0.01, errs


def test_analytic_with_ring_warns(read_mp):
    model = ReadoutPhysics(sigma=0.1, ring_tau=64.0, window_samples=64,
                           resolve_mode='analytic')
    with pytest.warns(UserWarning, match='flat-response'):
        run_physics_batch(read_mp, model, 0, 4,
                          init_states=np.zeros((4, 1), np.int32), **KW)


def test_ring_zero_unchanged(read_mp):
    """ring_tau=0 is bit-exact backward compatibility: same bits as a
    model without the field ever set."""
    init = (np.arange(64) % 2).astype(np.int32).reshape(64, 1)
    a = run_physics_batch(read_mp, ReadoutPhysics(sigma=0.4), 9, 64,
                          init_states=init, **KW)
    b = run_physics_batch(read_mp,
                          ReadoutPhysics(sigma=0.4, ring_tau=0.0), 9, 64,
                          init_states=init, **KW)
    np.testing.assert_array_equal(np.asarray(a['meas_bits']),
                                  np.asarray(b['meas_bits']))
