"""Resonator ring-up readout channel (ReadoutPhysics.ring_tau).

Round-2 review item 2: the per-sample resolve paths must have modeling
power the analytic matched-filter shortcut cannot collapse.  With
``ring_tau > 0`` the state-dependent transmission builds up as
``1 - exp(-(s+1)/ring_tau)`` over the window, so early samples carry
less discrimination information than their energy suggests — the
per-sample/fused modes simulate it, the analytic mode (exact only for
the flat response) is now measurably optimistic at short windows.
"""

import warnings

import numpy as np
import pytest

from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)

KW = dict(max_steps=200, max_pulses=16, max_meas=4)


@pytest.fixture(scope='module')
def read_mp():
    sim = Simulator(n_qubits=1)
    return sim.compile([{'name': 'read', 'qubit': ['Q0']}])


def _err_rate(mp, model, B=768, key=11):
    """Assignment error of the resolved bits against the device state."""
    init = (np.arange(B) % 2).astype(np.int32).reshape(B, 1)
    out = run_physics_batch(mp, model, key, B, init_states=init, **KW)
    assert not bool(out['incomplete'])
    bits = np.asarray(out['meas_bits'])[:, 0, 0]
    return float(np.mean(bits != init[:, 0]))


def test_sigma_zero_ring_keeps_assignment(read_mp):
    """Pure attenuation without noise: discrimination margins shrink
    symmetrically (default g0/g1), bits still match the state."""
    model = ReadoutPhysics(sigma=0.0, ring_tau=256.0, window_samples=256)
    assert _err_rate(read_mp, model) == 0.0


def test_persample_fused_bit_identical_with_ring(read_mp):
    """The fused Pallas kernel implements the same ring-up contract:
    bit-identical to the XLA per-sample path at sigma=0."""
    init = (np.arange(32) % 2).astype(np.int32).reshape(32, 1)
    outs = {}
    for mode in ('persample', 'fused'):
        model = ReadoutPhysics(sigma=0.0, ring_tau=96.0,
                               window_samples=128, resolve_mode=mode)
        outs[mode] = np.asarray(run_physics_batch(
            read_mp, model, 5, 32, init_states=init, **KW)['meas_bits'])
    np.testing.assert_array_equal(outs['persample'], outs['fused'])


def test_ring_degrades_fidelity_vs_analytic(read_mp):
    """The review's 'done' criterion: per-sample and analytic modes
    measurably differ in assignment fidelity once the channel has
    structure.  sigma is set so the flat model is nearly error-free
    while the rung-up channel (~2.7x SNR loss at W = ring_tau) is not."""
    kw = dict(sigma=4.0, ring_tau=256.0, window_samples=256)
    err_ps = _err_rate(read_mp, ReadoutPhysics(**kw))
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')   # analytic+ring warns by design
        err_an = _err_rate(
            read_mp, ReadoutPhysics(**kw, resolve_mode='analytic'))
    assert err_an < 0.02, err_an          # flat model: near-perfect
    assert err_ps > err_an + 0.05, (err_ps, err_an)   # structure matters


def test_fidelity_vs_window_length_curve(read_mp):
    """The calibration curve: with ring_tau fixed, assignment fidelity
    improves monotonically with window length (longer windows integrate
    past the transient) — examples/readout_window_calibration.py plots
    exactly this sweep."""
    errs = []
    for w in (64, 256, 1024):
        model = ReadoutPhysics(sigma=4.0, ring_tau=128.0,
                               window_samples=w)
        errs.append(_err_rate(read_mp, model))
    assert errs[0] > errs[1] > errs[2], errs
    assert errs[2] < 0.01, errs


def test_analytic_with_ring_warns(read_mp):
    model = ReadoutPhysics(sigma=0.1, ring_tau=64.0, window_samples=64,
                           resolve_mode='analytic')
    with pytest.warns(UserWarning, match='flat-response'):
        run_physics_batch(read_mp, model, 0, 4,
                          init_states=np.zeros((4, 1), np.int32), **KW)


def test_ring_zero_unchanged(read_mp):
    """ring_tau=0 is bit-exact backward compatibility: same bits as a
    model without the field ever set."""
    init = (np.arange(64) % 2).astype(np.int32).reshape(64, 1)
    a = run_physics_batch(read_mp, ReadoutPhysics(sigma=0.4), 9, 64,
                          init_states=init, **KW)
    b = run_physics_batch(read_mp,
                          ReadoutPhysics(sigma=0.4, ring_tau=0.0), 9, 64,
                          init_states=init, **KW)
    np.testing.assert_array_equal(np.asarray(a['meas_bits']),
                                  np.asarray(b['meas_bits']))


@pytest.fixture(scope='module')
def dc_read_mp():
    """A read program whose rdlo carrier aliases to DC (readfreq = 3 x
    the 2 GS/s element rate): the matched-filter template is flat, so
    low-frequency noise hits it head-on."""
    from distributed_processor_tpu.models.default_qchip import \
        make_default_qchip_dict
    from distributed_processor_tpu.qchip import QChip
    d = make_default_qchip_dict(1)
    d['Qubits']['Q0']['readfreq'] = 6.0e9
    sim = Simulator(qchip=QChip(d), n_qubits=1)
    return sim.compile([{'name': 'read', 'qubit': ['Q0']}])


def test_colored_noise_penalty_vs_window(read_mp, dc_read_mp):
    """Round-3 item 8: AR(1)-correlated ADC noise and the matched
    filter.  The penalty is SPECTRAL: AR(1) is low-pass, so against a
    low-IF (here DC-aliased) template the accumulated noise variance
    gains the double sum over rho^|t-t'| (~(1+rho)/(1-rho) = 39x at
    rho=0.95) and fidelity collapses, while at the default 400 MHz
    aliased IF the same noise is spectrally rejected and fidelity is
    no worse than white.  Both halves pinned, plus the
    fidelity-vs-window-length curve under the colored channel."""
    # low-IF: the colored-noise penalty, across the window-length curve
    curve = {}
    for rho in (0.0, 0.95):
        curve[rho] = [
            _err_rate(dc_read_mp, ReadoutPhysics(
                sigma=4.0, noise_ar1=rho, window_samples=w), B=1024)
            for w in (64, 256, 1024)]
    for white, colored in zip(curve[0.0], curve[0.95]):
        assert colored > white + 0.05, curve
    # the colored curve still improves with window (it IS integrating,
    # just ~corr-length times slower)
    assert curve[0.95][0] > curve[0.95][2], curve
    assert curve[0.0][2] < 0.01, curve
    # high-IF: the same noise is spectrally rejected by demodulation
    err_w = _err_rate(read_mp, ReadoutPhysics(
        sigma=4.0, noise_ar1=0.0, window_samples=256), B=1024)
    err_c = _err_rate(read_mp, ReadoutPhysics(
        sigma=4.0, noise_ar1=0.95, window_samples=256), B=1024)
    assert err_c <= err_w + 0.01, (err_c, err_w)


def test_colored_noise_statistics():
    """The generated AR(1) process is what it claims: unit stationary
    variance and lag-1 autocorrelation rho, across chunk boundaries
    (the IIR carry)."""
    import jax
    import jax.numpy as jnp
    from distributed_processor_tpu.sim.physics import _ar1_tables
    rho, ck, n_chunks = 0.9, 128, 8
    T, rpow = _ar1_tables(jnp.float32(rho), ck)
    key = jax.random.PRNGKey(0)
    B = 512
    n_prev = jax.random.normal(jax.random.fold_in(key, 0x41523149), (B,))
    chunks = []
    for k in range(n_chunks):
        w = jax.random.normal(jax.random.fold_in(key, k), (B, ck))
        n = jnp.einsum('bs,ts->bt', w, T) + n_prev[:, None] * rpow
        chunks.append(n)
        n_prev = n[:, -1]
    x = np.asarray(jnp.concatenate(chunks, axis=1))     # [B, ck*n_chunks]
    np.testing.assert_allclose(x.var(), 1.0, atol=0.05)
    lag1 = np.mean(x[:, 1:] * x[:, :-1])
    np.testing.assert_allclose(lag1, rho, atol=0.05)
    # boundary continuity: correlation across the chunk seam too
    seam = np.mean(x[:, ck - 1] * x[:, ck])
    np.testing.assert_allclose(seam, rho, atol=0.1)


def test_colored_noise_mode_validation(read_mp):
    for mode in ('analytic', 'fused'):
        with pytest.raises(ValueError, match='persample'):
            run_physics_batch(read_mp, ReadoutPhysics(
                sigma=1.0, noise_ar1=0.5, resolve_mode=mode), 0, 2, **KW)
    with pytest.raises(ValueError, match='noise_ar1'):
        run_physics_batch(read_mp, ReadoutPhysics(
            sigma=1.0, noise_ar1=1.5), 0, 2, **KW)
