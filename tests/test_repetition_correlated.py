"""Repetition-code correlated-error sensitivity (round-3 criterion 3).

The distance-3 majority vote corrects any single flip, so independent
errors of strength f leak through only at O(f^2) — but a correlated
two-qubit error (both qubits of a pair flipped by ONE event) defeats it
linearly.  With the statevec device, pairwise-correlated errors are
physically real (2q Pauli channel on coupling pulses), and the
physics-closed LUT round measurably distinguishes them from independent
errors of equal-or-greater marginal strength.
"""

import numpy as np
import pytest

from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.models.coupling import couplings_from_qchip
from distributed_processor_tpu.models.default_qchip import make_default_qchip
from distributed_processor_tpu.models.repetition import (
    correlated_noise_stage, independent_noise_stage,
    repetition_logical_program, repetition_physics_kwargs)
from distributed_processor_tpu.sim.device import DeviceModel
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)

SHOTS = 4096


@pytest.fixture(scope='module')
def setup():
    return Simulator(n_qubits=3), make_default_qchip(3)


def _round(setup, noise, key, **dev_kw):
    sim, qchip = setup
    prog = repetition_logical_program(3, noise)
    mp = sim.compile(prog)
    cps = couplings_from_qchip(mp, qchip)
    model = ReadoutPhysics(sigma=0.0, device=DeviceModel(
        'statevec', couplings=cps, **dev_kw))
    out = run_physics_batch(mp, model, key, SHOTS,
                            init_states=np.zeros((SHOTS, 3), np.int32),
                            max_steps=8000, **repetition_physics_kwargs(3))
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))
    syndrome = np.asarray(out['meas_state'])[:, :, 0]   # pre-correction
    final = np.asarray(out['meas_bits'])[:, :, 1]       # post-correction
    return syndrome, (final.sum(axis=1) >= 2)           # logical flip


def test_noiseless_round_is_silent(setup):
    syndrome, logical = _round(setup, correlated_noise_stage([(0, 1),
                                                              (1, 2)]), 0)
    assert not np.any(syndrome) and not np.any(logical)


def test_correlated_beats_majority_vote(setup):
    """Pairwise-correlated errors produce a logical error rate several
    times the independent rate at matched (here: strictly smaller)
    marginal flip probabilities — the linear-vs-quadratic signature."""
    p2 = 0.05
    syn_c, log_c = _round(setup, correlated_noise_stage([(0, 1), (1, 2)]),
                          1, depol2_per_pulse=p2)
    # independent stage tuned to a HIGHER per-qubit marginal than any
    # correlated-channel qubit sees (2p/3 = 0.0527 > 2*8*p2/15 - eps)
    p1 = 0.079
    syn_i, log_i = _round(setup, independent_noise_stage([0, 1, 2]),
                          2, depol_per_pulse=p1)
    marg_c, marg_i = syn_c.mean(axis=0), syn_i.mean(axis=0)
    assert np.all(marg_i >= marg_c - 0.01), (marg_c, marg_i)
    rate_c, rate_i = log_c.mean(), log_i.mean()
    # independent errors follow the exact majority-vote closed form
    f = 2 * p1 / 3
    pred_i = 3 * f**2 * (1 - f) + f**3
    assert abs(rate_i - pred_i) < 4 * np.sqrt(pred_i * (1 - pred_i) / SHOTS)
    # correlated errors leak through linearly: several-fold worse
    assert rate_c > 2.0 * rate_i, (rate_c, rate_i)
    assert rate_c > 0.015


def test_single_independent_flip_always_corrected(setup):
    """Determinism check on the correction path itself: with exactly
    one qubit flipped at injection (X180 via two X90s), the round
    always restores the codeword — zero logical errors."""
    sim, qchip = setup
    noise = [{'name': 'X90', 'qubit': ['Q1']},
             {'name': 'X90', 'qubit': ['Q1']}]
    syndrome, logical = _round((sim, qchip), noise, 3)
    assert np.all(syndrome == [0, 1, 0])
    assert not np.any(logical)
