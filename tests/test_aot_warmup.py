"""AOT bucket precompilation: BucketSpec, aot_compile_batch, the
learned catalog, and the service warmup paths (docs/SERVING.md "Cold
start & warmup").

The load-bearing properties, in order:

* **Identity**: a BucketSpec survives the JSON round-trip exactly
  (including traits and the cfg's tuple fields), equality/hash ignore
  ``traits`` (the coalescing contract — mixed-trait programs share a
  batch) while ``identity()`` includes them (the exact-executable key).
* **Bit-identity**: a request served by an AOT-precompiled executable
  equals the lazily jit-compiled dispatch per stat, including the
  fault word — warmup is a latency optimization, never a semantic one.
* **Durability**: a catalog recorded by one service replays in a
  FRESH PROCESS, where the startup warmup thread precompiles every
  spec and the first real request classifies warm.
* **Liveness**: catalog replay runs on a background thread; admission
  and dispatch never wait for it, even if a compile wedges.

This module is listed in tools/check_junit.py NO_SKIP_MODULES: it runs
on the forced CPU mesh and has no legitimate skip condition.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax

from distributed_processor_tpu import isa
from distributed_processor_tpu.models import (active_reset,
                                              make_default_qchip,
                                              rb_ensemble)
from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.serve import (BucketCatalog, BucketSpec,
                                             ExecutionService,
                                             bucket_key)
from distributed_processor_tpu.serve import service as service_mod
from distributed_processor_tpu.serve.service import _normalize_cfg
from distributed_processor_tpu.sim.interpreter import (
    InterpreterConfig, aot_cache_size, aot_compile_batch,
    aot_eviction_count, clear_aot_cache, program_traits,
    set_aot_cache_cap, simulate_batch)
from distributed_processor_tpu.utils import profiling

pytestmark = pytest.mark.serve


def _ensemble(n_qubits, depth, n_seqs, seed):
    qubits = [f'Q{i}' for i in range(n_qubits)]
    qchip = make_default_qchip(n_qubits)
    return [compile_to_machine(active_reset(qubits) + prog, qchip,
                               n_qubits=n_qubits)
            for prog in rb_ensemble(qubits, depth, n_seqs, seed=seed)]


def _cfg_for(mps, **kw):
    bucket = max(isa.shape_bucket(mp.n_instr) for mp in mps)
    base = dict(max_steps=2 * bucket + 64, max_pulses=bucket + 2,
                max_meas=2, max_resets=2, record_pulses=False)
    base.update(kw)
    return InterpreterConfig(**base)


# ---------------------------------------------------------------------------
# BucketSpec: round-trip, hashing, traits semantics
# ---------------------------------------------------------------------------

def test_bucketspec_roundtrip_and_identity():
    mps = _ensemble(2, 2, 1, seed=3)
    cfg = _cfg_for(mps)
    ncfg, _ = _normalize_cfg(cfg, isa.shape_bucket(mps[0].n_instr))
    tmpl = bucket_key(mps[0], ncfg)
    assert isinstance(tmpl, BucketSpec) and not tmpl.bound
    assert tmpl.traits == program_traits(mps[0])

    spec = tmpl.bind(n_programs=4, n_shots=8)
    assert spec.bound and spec.template() == tmpl
    assert spec.label() == f'{tmpl.label()}p4s8'
    assert spec.shape_sig() == ('multi', 4, 8, True)

    # exact JSON round trip: equality AND the traits __eq__ ignores
    back = BucketSpec.from_json(json.loads(json.dumps(spec.to_json())))
    assert back == spec and hash(back) == hash(spec)
    assert back.identity() == spec.identity()
    assert back.cfg == spec.cfg and back.traits == spec.traits

    # traits are excluded from equality/hash (mixed-trait programs
    # must coalesce into one bucket) but included in identity()
    from dataclasses import replace
    stripped = replace(spec, traits=None)
    assert stripped == spec and hash(stripped) == hash(spec)
    assert stripped.identity() != spec.identity()

    # version skew is rejected, not silently misparsed
    doc = spec.to_json()
    doc['version'] = 999
    with pytest.raises(ValueError):
        BucketSpec.from_json(doc)


def test_bucket_spec_matches_dispatch_padding():
    """service.bucket_spec pads occupancy exactly like live dispatch
    (pow2) and normalizes cfg exactly like _execute."""
    mps = _ensemble(2, 2, 1, seed=4)
    cfg = _cfg_for(mps)
    svc = ExecutionService(cfg, max_batch_programs=8, max_wait_ms=1.0)
    try:
        spec = svc.bucket_spec(mps[0], shots=16, n_programs=3)
        assert spec.n_programs == 4 and spec.n_shots == 16  # 3 -> pow2
        ncfg, _ = _normalize_cfg(cfg, isa.shape_bucket(mps[0].n_instr))
        assert spec.template() == bucket_key(mps[0], ncfg)
        # unbound templates are rejected by warmup and the catalog
        with pytest.raises(ValueError):
            svc.warmup(spec.template())
    finally:
        svc.shutdown()


# ---------------------------------------------------------------------------
# AOT executable bit-identity (including the fault word)
# ---------------------------------------------------------------------------

def test_aot_dispatch_bit_identical_to_lazy():
    """The same coalesced batch served (a) by the lazily jit-compiled
    path and (b) by the AOT-precompiled executable must agree per stat,
    including 'faults' — and (b) must actually hit the AOT cache."""
    mps = _ensemble(2, 2, 2, seed=7)
    cfg = _cfg_for(mps)
    rng = np.random.default_rng(9)
    bits = [rng.integers(0, 2, (8, mps[0].n_cores, 2)).astype(np.int32)
            for _ in mps]
    ncfg, _ = _normalize_cfg(cfg, isa.shape_bucket(mps[0].n_instr))
    refs = [jax.tree.map(np.asarray, simulate_batch(mp, b, cfg=ncfg))
            for mp, b in zip(mps, bits)]

    def serve_once(warm):
        svc = ExecutionService(cfg, max_batch_programs=2,
                               max_wait_ms=50.0)
        try:
            if warm:
                report = svc.warmup(svc.bucket_spec(mps[0], shots=8,
                                                    n_programs=2))
                assert report and all(r['compile_ms'] >= 0.0
                                      for r in report)
            handles = [svc.submit(mp, b) for mp, b in zip(mps, bits)]
            res = [h.result(timeout=600) for h in handles]
            st = svc.stats()
        finally:
            svc.shutdown()
        return res, st

    clear_aot_cache()
    lazy_res, _ = serve_once(warm=False)   # lazy jit dispatch
    assert aot_cache_size() == 0

    hits0 = profiling.counter_get('aot_hit')
    aot_res, st = serve_once(warm=True)    # AOT executable dispatch
    assert aot_cache_size() >= 1
    assert profiling.counter_get('aot_hit') - hits0 >= 1
    assert st['warmup']['aot_compiled'] >= 1

    for i, want in enumerate(refs):
        for got in (lazy_res[i], aot_res[i]):
            assert set(got) == set(want)
            assert 'fault' in want
            for k in want:
                np.testing.assert_array_equal(
                    np.asarray(got[k]), np.asarray(want[k]),
                    err_msg=f'prog{i}:{k}')


# ---------------------------------------------------------------------------
# catalog: record in one process, replay in a fresh one
# ---------------------------------------------------------------------------

_REPLAY_CHILD = r'''
import json, os, sys, time
sys.path.insert(0, {repo!r})
os.environ['JAX_PLATFORMS'] = 'cpu'
from distributed_processor_tpu.serve import BucketCatalog, ExecutionService
from distributed_processor_tpu.serve.benchmark import _workload

specs = BucketCatalog({path!r}).load()
mps, bits, cfg = _workload(1, 2, 2, {shots}, 7)
svc = ExecutionService(cfg, max_batch_programs=2, max_wait_ms=5.0,
                       warmup_catalog={path!r})
try:
    deadline = time.monotonic() + 300.0
    while svc.stats()['warmup']['in_progress'] > 0:
        assert time.monotonic() < deadline, 'replay never finished'
        time.sleep(0.01)
    pre = svc.stats()
    res = svc.submit(mps[0], bits[0]).result(timeout=300)
    st = svc.stats()
finally:
    svc.shutdown()
print(json.dumps({{
    'n_specs': len(specs),
    'aot_compiled': st['warmup']['aot_compiled'],
    'replayed': st['warmup']['replayed'],
    'cold_after_replay': st['compile']['cold'] - pre['compile']['cold'],
    'regs_sum': int(__import__('numpy').asarray(res['regs']).sum()),
}}))
'''


def test_catalog_replay_across_restart(tmp_path):
    """A service with ``warmup_catalog`` learns its dispatched buckets;
    a FRESH PROCESS replaying that catalog precompiles them at startup
    and serves its first request warm (the cold-start kill shot)."""
    from distributed_processor_tpu.serve.benchmark import _workload
    path = str(tmp_path / 'buckets.json')
    mps, bits, cfg = _workload(1, 2, 2, 4, 7)
    svc = ExecutionService(cfg, max_batch_programs=2, max_wait_ms=5.0,
                           warmup_catalog=path)
    try:
        ref = svc.submit(mps[0], bits[0]).result(timeout=600)
    finally:
        svc.shutdown()
    cat = BucketCatalog(path)
    specs = cat.load()
    assert len(specs) >= 1 and all(s.bound for s in specs)
    assert os.path.exists(path)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = _REPLAY_CHILD.format(repo=repo, path=path, shots=4)
    proc = subprocess.run([sys.executable, '-c', child],
                          capture_output=True, text=True, timeout=570)
    assert proc.returncode == 0, proc.stderr[-2000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert row['n_specs'] == len(specs)
    assert row['replayed'] == len(specs)
    assert row['aot_compiled'] >= len(specs)   # per device executor
    # the first real request after replay classifies WARM: the compile
    # happened at startup, outside any request's latency budget
    assert row['cold_after_replay'] == 0
    assert row['regs_sum'] == int(np.asarray(ref['regs']).sum())


def test_catalog_tolerates_corruption(tmp_path):
    path = tmp_path / 'buckets.json'
    path.write_text('{definitely not json')
    assert BucketCatalog(str(path)).load() == []
    # a valid catalog with a bad magic is treated as empty, not fatal
    path.write_text(json.dumps({'magic': 'other', 'version': 1,
                                'specs': []}))
    assert len(BucketCatalog(str(path))) == 0


def test_catalog_aging_and_cap(tmp_path):
    """Unbounded growth is the catalog's failure mode: a retired
    workload's buckets would be AOT-recompiled at every startup
    forever.  Specs not re-observed within ``max_age_runs``
    :meth:`begin_run` generations are pruned; ``max_specs`` caps the
    size with least-recently-seen eviction; files written before the
    aging change still load."""
    mps = _ensemble(2, 2, 1, seed=9)
    cfg = _cfg_for(mps)
    ncfg, _ = _normalize_cfg(cfg, isa.shape_bucket(mps[0].n_instr))
    tmpl = bucket_key(mps[0], ncfg)

    def spec(p):
        return tmpl.bind(n_programs=p, n_shots=4)

    path = str(tmp_path / 'cat.json')

    def reopen():
        return BucketCatalog(path, max_specs=8, max_age_runs=2)

    # generation 1: two specs recorded
    cat = reopen()
    cat.begin_run()
    assert cat.record(spec(1)) and cat.record(spec(2))
    assert not cat.record(spec(1))    # dup refreshes, doesn't re-add
    assert len(cat) == 2

    # generations 2-4: only spec(1) re-observed each run; spec(2)'s
    # last-seen falls beyond the 2-run horizon and is pruned
    for _ in range(3):
        cat = reopen()
        cat.begin_run()
        cat.record(spec(1))
    live = reopen().begin_run()
    idents = {s.identity() for s in live}
    assert spec(1).identity() in idents
    assert spec(2).identity() not in idents

    # size cap: least-recently-seen evicted first, the newest survives
    capped = BucketCatalog(str(tmp_path / 'cap.json'), max_specs=2)
    capped.begin_run()
    for p in (1, 2, 4):
        capped.record(spec(p))
    kept = {s.identity() for s in capped.load()}
    assert len(kept) == 2 and spec(4).identity() in kept

    # a pre-aging v1 file (no runs/last_seen keys) still loads, and a
    # post-aging file is still read by a plain no-limit catalog
    doc = json.load(open(path))
    assert doc['version'] == 1 and 'runs' in doc and 'last_seen' in doc
    doc.pop('runs'), doc.pop('last_seen')
    old = str(tmp_path / 'old.json')
    json.dump(doc, open(old, 'w'))
    assert {s.identity() for s in BucketCatalog(old).load()} == idents
    assert {s.identity() for s in BucketCatalog(path).load()} == idents


# ---------------------------------------------------------------------------
# liveness: replay never blocks admission
# ---------------------------------------------------------------------------

def test_warmup_replay_never_blocks_admission(tmp_path, monkeypatch):
    """Requests must admit and complete while catalog replay is still
    wedged mid-compile: the warmup thread is an optimization running
    beside the dispatch path, never in front of it."""
    mps = _ensemble(2, 2, 1, seed=11)
    cfg = _cfg_for(mps)
    ncfg, _ = _normalize_cfg(cfg, isa.shape_bucket(mps[0].n_instr))
    path = str(tmp_path / 'buckets.json')
    cat = BucketCatalog(path)
    cat.record(bucket_key(mps[0], ncfg).bind(n_programs=1, n_shots=4))

    gate = threading.Event()
    stalled = threading.Event()

    def wedged_compile(spec, jax_device=None):
        stalled.set()
        gate.wait(60.0)     # held until the request has completed
        return 0.0

    # the replay thread resolves the name through the service module
    monkeypatch.setattr(service_mod, 'aot_compile_batch',
                        wedged_compile)
    rng = np.random.default_rng(0)
    bits = rng.integers(0, 2, (4, mps[0].n_cores, 2)).astype(np.int32)
    svc = ExecutionService(cfg, max_batch_programs=2, max_wait_ms=1.0,
                           warmup_catalog=path)
    try:
        assert stalled.wait(30.0)
        assert svc.stats()['warmup']['in_progress'] > 0
        res = svc.submit(mps[0], bits).result(timeout=600)
        assert np.asarray(res['regs']).shape[0] == 4
        # the whole request lifecycle ran with replay still wedged
        assert svc.stats()['warmup']['in_progress'] > 0
        gate.set()
        deadline = time.monotonic() + 60.0
        while svc.stats()['warmup']['in_progress'] > 0:
            assert time.monotonic() < deadline
            time.sleep(0.01)
    finally:
        gate.set()
        svc.shutdown()


# ---------------------------------------------------------------------------
# stats: the cold/warm split and the warmup block
# ---------------------------------------------------------------------------

def test_warmup_stats_cold_warm_split():
    """Warmup classifies cold (untimed); the first real request then
    classifies warm and contributes a timed warm sample, so the
    per-bucket view separates compile cost from execute cost."""
    clear_aot_cache()       # process-level cache would zero compile_ms
    mps = _ensemble(2, 2, 1, seed=13)
    cfg = _cfg_for(mps)
    rng = np.random.default_rng(1)
    bits = rng.integers(0, 2, (4, mps[0].n_cores, 2)).astype(np.int32)
    svc = ExecutionService(cfg, max_batch_programs=2, max_wait_ms=1.0)
    try:
        spec = svc.bucket_spec(mps[0], shots=4, n_programs=1)
        report = svc.warmup(spec)
        assert [r['cold'] for r in report] == [True]
        assert report[0]['compile_ms'] > 0.0
        st = svc.stats()
        assert st['warmup'] == {'aot_compiled': 1, 'replayed': 0,
                                'in_progress': 0}
        label = spec.template().label()
        per = st['compile']['per_bucket'][label]
        assert per['cold'] == 1 and per['warm'] == 0
        assert per['cold_ms_mean'] is None    # warmups are untimed
        assert per['compile_ms_est'] is None

        svc.submit(mps[0], bits).result(timeout=600)
        per = svc.stats()['compile']['per_bucket'][label]
        assert per['warm'] == 1
        assert per['warm_ms_mean'] is not None \
            and per['warm_ms_mean'] > 0.0
    finally:
        svc.shutdown()


# ---------------------------------------------------------------------------
# AOT executable cache: LRU bound
# ---------------------------------------------------------------------------

def test_aot_cache_lru_bound():
    """The executable cache is bounded, least-recently-USED first: a
    long-lived replica serving diverse traffic must not pin every
    executable it ever compiled (each holds device buffers).  Eviction
    costs a recompile on the next dispatch of that bucket — never
    correctness — and is counted ('aot_evictions')."""
    clear_aot_cache()
    mps = _ensemble(2, 2, 1, seed=17)
    cfg = _cfg_for(mps)
    ncfg, _ = _normalize_cfg(cfg, isa.shape_bucket(mps[0].n_instr))
    tmpl = bucket_key(mps[0], ncfg)
    specs = [tmpl.bind(n_programs=p, n_shots=2) for p in (1, 2, 4)]
    old = set_aot_cache_cap(2)
    try:
        ev0 = aot_eviction_count()
        assert aot_compile_batch(specs[0]) > 0
        assert aot_compile_batch(specs[1]) > 0
        assert aot_cache_size() == 2
        # touch spec 0 so spec 1 becomes the LRU victim
        assert aot_compile_batch(specs[0]) == 0.0
        assert aot_compile_batch(specs[2]) > 0
        assert aot_cache_size() == 2
        assert aot_eviction_count() == ev0 + 1
        # the recently-used executable survived; the victim recompiles
        assert aot_compile_batch(specs[0]) == 0.0
        assert aot_compile_batch(specs[1]) > 0
        assert aot_eviction_count() == ev0 + 2
        # lowering the cap evicts immediately, oldest-used first
        set_aot_cache_cap(1)
        assert aot_cache_size() == 1
        assert aot_eviction_count() == ev0 + 3
        assert aot_compile_batch(specs[1]) == 0.0   # newest survived
        with pytest.raises(ValueError):
            set_aot_cache_cap(0)
    finally:
        set_aot_cache_cap(old)
        clear_aot_cache()


# ---------------------------------------------------------------------------
# catalog: concurrent writers merge, never clobber
# ---------------------------------------------------------------------------

def test_catalog_concurrent_writers_merge_not_clobber(tmp_path):
    """Fleet replicas share ONE catalog file (the shared warm tier): a
    write through one handle must MERGE with specs other handles wrote
    since it last read (advisory flock + merge-on-load), never clobber
    them — a respawn racing a peer's record would otherwise forget
    buckets and cold-start them forever."""
    mps = _ensemble(2, 2, 1, seed=21)
    cfg = _cfg_for(mps)
    ncfg, _ = _normalize_cfg(cfg, isa.shape_bucket(mps[0].n_instr))
    tmpl = bucket_key(mps[0], ncfg)
    path = str(tmp_path / 'shared.json')

    a, b = BucketCatalog(path), BucketCatalog(path)
    a.begin_run()
    b.begin_run()               # b's in-memory view predates a's write
    assert a.record(tmpl.bind(n_programs=1, n_shots=4))
    assert b.record(tmpl.bind(n_programs=2, n_shots=4))
    idents = {s.identity() for s in BucketCatalog(path).load()}
    assert tmpl.bind(n_programs=1, n_shots=4).identity() in idents
    assert tmpl.bind(n_programs=2, n_shots=4).identity() in idents

    # contention: interleaved writers through distinct handles (the
    # flock serializes across open files, in- or cross-process); every
    # recorded spec must survive to the final on-disk state
    handles = [BucketCatalog(path) for _ in range(4)]
    for h in handles:
        h.begin_run()

    def write(k):
        for p in range(1, 9):
            handles[k].record(tmpl.bind(n_programs=p, n_shots=4 + k))

    threads = [threading.Thread(target=write, args=(k,))
               for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    final = {s.identity() for s in BucketCatalog(path).load()}
    want = {tmpl.bind(n_programs=p, n_shots=4 + k).identity()
            for k in range(4) for p in range(1, 9)}
    assert want <= final
