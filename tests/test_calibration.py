"""Readout calibration + T2 echo + profiling utilities."""

import numpy as np
import jax
import pytest

from distributed_processor_tpu.models import (
    IQReadoutModel, calibrate_readout, fit_centroids, readout_fidelity,
    t2_echo_program, make_default_qchip)
from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.sim import simulate
from distributed_processor_tpu.utils import StageTimer


def test_calibration_recovers_centroids():
    model = IQReadoutModel(centers0=np.array([1 + 0j, 0 + 1j]),
                           centers1=np.array([-1 + 0j, 0 - 1j]),
                           sigma=0.2)
    c0, c1, fid = calibrate_readout(model, jax.random.PRNGKey(0),
                                    shots=2048)
    np.testing.assert_allclose(np.asarray(c0),
                               [[1, 0], [0, 1]], atol=0.05)
    np.testing.assert_allclose(np.asarray(c1),
                               [[-1, 0], [0, -1]], atol=0.05)
    assert np.all(np.asarray(fid) > 0.99)


def test_fidelity_degrades_with_noise():
    clean = IQReadoutModel(np.array([1 + 0j]), np.array([-1 + 0j]), 0.1)
    noisy = IQReadoutModel(np.array([1 + 0j]), np.array([-1 + 0j]), 1.5)
    _, _, f_clean = calibrate_readout(clean, jax.random.PRNGKey(1), 2048)
    _, _, f_noisy = calibrate_readout(noisy, jax.random.PRNGKey(1), 2048)
    assert float(f_clean[0]) > float(f_noisy[0])
    assert 0.5 < float(f_noisy[0]) < 0.95


def test_t2_echo_compiles_and_runs():
    qchip = make_default_qchip(1)
    mp = compile_to_machine(t2_echo_program('Q0', 1e-6), qchip, n_qubits=1)
    out = simulate(mp)
    assert int(out['err'][0]) == 0
    n = int(out['n_pulses'][0])
    assert n == 4 + 2          # 4 drive pulses + read pair
    # the echo delay separates pulse 2 from pulse 1 by >= delay/2
    gt = np.asarray(out['rec_gtime'][0, :n])
    assert gt[1] - gt[0] >= (1e-6 / 2) / 2e-9


def test_stage_timer():
    import jax.numpy as jnp
    t = StageTimer()
    out = t.stage('mul', lambda: jnp.arange(64) * 2)
    assert out.shape == (64,)
    assert 'mul' in t.report()
