"""Differentiable physics + gradient-based calibration
(docs/CALIBRATION.md, docs/SERVING.md "Calibration sessions").

The contract, pinned here:

* **Gradient correctness**: ``sim.grad.grad_loss`` agrees with central
  finite differences on every knob's smooth loss to ``RTOL`` (the
  pinned tolerance below); the hard discrimination threshold
  (:func:`~.physics._acc_to_bit`'s ``proj > 0``) has an EXACTLY-zero
  gradient; the straight-through surrogate is the exact hard bit
  forward and the documented sigmoid surrogate backward; the score-
  function estimator is unbiased on sampled branch bits; and
  ``grad_loss_batch`` (vmap over candidates) is bit-identical to the
  sequential per-candidate path.
* **Compile-front-door stress**: N amplitude-varying candidates are N
  distinct content keys (no aliasing), a repeated calibration burst
  re-hits its own entries with zero evictions (no LRU thrash), and a
  live-qchip writeback flushes EXACTLY the stale epoch's entries —
  other qchips' entries stay warm — counted by the new
  ``writeback_flushes`` stat.
* **Closed loops through serve**: gradient descent on the amplitude,
  DRAG and readout-window knobs converges with candidates submitted
  through ``ExecutionService.submit_source`` under a
  ``CalibrationSession``, writes the tuned value back to the live
  ``QChip`` (fingerprint changes, round-trips through ``to_dict``),
  and a diverged loop is a counted observable outcome
  (``stats()['calibration']['diverged']``), never a writeback.

This module is listed in tools/check_junit.py NO_SKIP_MODULES: it runs
on pure CPU (jnp forward models + the serve tier's CPU interpreter)
and has no legitimate skip condition.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_processor_tpu.calib import CalibrationSession, calibrate
from distributed_processor_tpu.compilecache import CompileCache
from distributed_processor_tpu.models import make_default_qchip
from distributed_processor_tpu.models.experiments import rabi_program
from distributed_processor_tpu.qchip import QChip
from distributed_processor_tpu.serve import ExecutionService
from distributed_processor_tpu.sim.grad import (AMP_SCALE, LossSpec,
                                                PARAM_NAME, grad_loss,
                                                grad_loss_batch,
                                                hard_threshold,
                                                score_function_grad,
                                                st_threshold)

pytestmark = pytest.mark.calib

# THE pinned finite-difference tolerance (ISSUE 20 acceptance): the
# analytic gradient of every smooth loss must agree with central
# differences to this relative tolerance at every probe point below.
RTOL = 0.02

RESULT_TIMEOUT = 300.0


def _fd(pname, x, spec, eps):
    """Central finite difference of the calibration loss, evaluated
    through the same float32 ``grad_loss`` front door the loops use."""
    lp, _ = grad_loss({pname: x + eps}, spec)
    lm, _ = grad_loss({pname: x - eps}, spec)
    return (float(lp) - float(lm)) / (2.0 * eps)


# ---------------------------------------------------------------------------
# gradient correctness (tentpole (a))
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('x', [0.30, 0.45, 0.65])
def test_fd_agreement_amplitude(x):
    spec = LossSpec(knob='amplitude', x90_amp=0.48)
    _, grads = grad_loss({'amp': x}, spec)
    g = float(grads['amp'])
    fd = _fd('amp', x, spec, eps=1e-3)
    assert g == pytest.approx(fd, rel=RTOL)


@pytest.mark.parametrize('alpha', [0.2, 0.6, 1.5])
def test_fd_agreement_drag(alpha):
    # the loop-default spec: at the gate's nominal -270 MHz detuning
    # the gaussian's spectral weight underflows float32 and both the
    # analytic and FD gradients are exactly zero — the softer model
    # detuning keeps the loss in float32 range (docs/CALIBRATION.md)
    spec = LossSpec(knob='drag', drag_delta=-30e6)
    _, grads = grad_loss({'alpha': alpha}, spec)
    g = float(grads['alpha'])
    fd = _fd('alpha', alpha, spec, eps=1e-2)
    assert g == pytest.approx(fd, rel=RTOL)


@pytest.mark.parametrize('start', [48.0, 160.0, 280.0])
def test_fd_agreement_readout_window(start):
    spec = LossSpec(knob='readout_window', window_edge=8.0)
    _, grads = grad_loss({'window_start': start}, spec)
    g = float(grads['window_start'])
    fd = _fd('window_start', start, spec, eps=1.0)
    assert g == pytest.approx(fd, rel=RTOL)


def test_hard_threshold_gradient_exactly_zero():
    """The exact discrimination bit is piecewise constant: its gradient
    is identically zero — INCLUDING at the boundary — which is exactly
    why the loops never differentiate through it (pinned as documented
    behavior, not a bug)."""
    proj = jnp.array([-2.0, -1e-6, 0.0, 1e-6, 2.0], jnp.float32)

    def loss(scale):
        return jnp.sum(hard_threshold(scale * proj))

    g = jax.grad(loss)(jnp.float32(1.0))
    assert float(g) == 0.0


def test_st_threshold_forward_is_hard_bit_backward_is_surrogate():
    proj = jnp.array([-3.0, -0.5, 0.0, 0.5, 3.0], jnp.float32)
    np.testing.assert_array_equal(np.asarray(st_threshold(proj)),
                                  np.asarray(hard_threshold(proj)))
    temp = 0.7
    g = jax.grad(
        lambda p: jnp.sum(st_threshold(p, jnp.float32(temp))))(proj)
    sg = jax.nn.sigmoid(proj / temp)
    np.testing.assert_allclose(np.asarray(g),
                               np.asarray(sg * (1 - sg) / temp),
                               rtol=1e-6)
    # temp is an estimator knob, not a physical parameter: zero grad
    gt = jax.grad(
        lambda t: jnp.sum(st_threshold(proj, t)))(jnp.float32(temp))
    assert float(gt) == 0.0


def test_score_function_grad_unbiased():
    """REINFORCE on sampled branch bits: for f(b) = 2b + 1,
    d/dp E[f] = f(1) - f(0) = 2 exactly; the estimator's mean over a
    seeded 20k-sample draw must land within 0.2 of it."""
    rng = np.random.default_rng(20)
    p = 0.3
    bits = (rng.random(20000) < p).astype(np.float32)
    f_vals = 2.0 * bits + 1.0
    est = float(score_function_grad(p, bits, f_vals))
    assert abs(est - 2.0) < 0.2


@pytest.mark.parametrize('knob,vals', [
    ('amplitude', np.linspace(0.2, 0.8, 9)),
    ('readout_window', np.linspace(16.0, 400.0, 7)),
])
def test_grad_loss_batch_bit_identical_to_sequential(knob, vals):
    """The calibration burst evaluates its whole candidate population
    in one vmap dispatch; that dispatch must be bit-identical to the
    sequential per-candidate path (same contract as the serving tier's
    batched-vs-sequential pins)."""
    spec = (LossSpec(knob='readout_window', window_edge=8.0)
            if knob == 'readout_window' else LossSpec(knob=knob))
    pname = PARAM_NAME[knob]
    vals = np.asarray(vals, np.float32)
    b_loss, b_grads = grad_loss_batch({pname: vals}, spec)
    for i, v in enumerate(vals):
        loss, grads = grad_loss({pname: v}, spec)
        assert np.array_equal(np.asarray(b_loss)[i], np.asarray(loss))
        assert np.array_equal(np.asarray(b_grads[pname])[i],
                              np.asarray(grads[pname]))


# ---------------------------------------------------------------------------
# compile front door under calibration traffic (satellites 1 + 2)
# ---------------------------------------------------------------------------

def test_candidate_amplitudes_are_distinct_cache_keys():
    """N amplitude-varying candidates -> N distinct content keys, all
    compiled (miss) once and re-hit byte-for-byte on resubmission."""
    cache = CompileCache(capacity=64)
    qchip = make_default_qchip(2)
    amps = np.linspace(0.1, 0.9, 16)
    keys, statuses = set(), []
    for a in amps:
        _, status, key = cache.get_or_compile(
            rabi_program('Q0', float(a)), qchip, n_qubits=2)
        keys.add(key)
        statuses.append(status)
    assert len(keys) == len(amps)
    assert statuses == ['miss'] * len(amps)
    for a in amps:
        _, status, key = cache.get_or_compile(
            rabi_program('Q0', float(a)), qchip, n_qubits=2)
        assert status == 'hit' and key in keys
    snap = cache.stats()
    assert snap['misses'] == len(amps)
    assert snap['hits'] == len(amps)
    assert snap['evictions'] == 0


def test_calibration_burst_no_lru_thrash_through_service():
    """A calibration burst (nearly-identical candidate programs) must
    not thrash the service's LRU: the second identical burst is all
    hits, zero new program compiles, zero evictions.  (Executor jit
    compiles are NOT pinned here: bound-bucket shapes depend on
    coalescing occupancy, which is timing-dependent.)"""
    qchip = make_default_qchip(2)
    amps = np.linspace(0.2, 0.65, 10)
    with ExecutionService() as svc:
        for h in [svc.submit_source(rabi_program('Q0', float(a)), qchip,
                                    shots=2, n_qubits=2)
                  for a in amps]:
            h.result(timeout=RESULT_TIMEOUT)
        s1 = svc.compile_cache.stats()
        for h in [svc.submit_source(rabi_program('Q0', float(a)), qchip,
                                    shots=2, n_qubits=2)
                  for a in amps]:
            h.result(timeout=RESULT_TIMEOUT)
        s2 = svc.compile_cache.stats()
    assert s1['evictions'] == 0 and s2['evictions'] == 0
    assert s2['misses'] == s1['misses']
    assert s2['hits'] >= s1['hits'] + len(amps)


def test_fingerprint_roundtrip_and_exact_stale_epoch_flush():
    """The PR 9 regression pin with a REAL writer: a live-qchip
    mutation (the writeback signature) flushes exactly the stale
    epoch's entries on the next submission — the other qchip's entry
    stays warm — and the fingerprint round-trips through
    ``to_dict``/reload both before and after the writeback."""
    qa = make_default_qchip(2)
    qb = make_default_qchip(2)
    # qb is a different calibration epoch (different readout tune)
    qb.gates['Q1read'].contents[0].amp = 0.3
    cache = CompileCache(capacity=64)
    prog_a = rabi_program('Q0', 0.3)
    prog_b = rabi_program('Q1', 0.5)
    fp_a1 = qa.fingerprint()
    assert QChip(qa.to_dict()).fingerprint() == fp_a1
    assert qb.fingerprint() != fp_a1
    _, st_a, _ = cache.get_or_compile(prog_a, qa, n_qubits=2)
    _, st_b, key_b = cache.get_or_compile(prog_b, qb, n_qubits=2)
    assert st_a == 'miss' and st_b == 'miss'
    snap0 = cache.stats()
    assert snap0['writeback_flushes'] == 0

    # the calibration writeback: retune one gate amplitude in place
    qa.gates['Q0X90'].contents[0].amp = 0.51
    fp_a2 = qa.fingerprint()
    assert fp_a2 != fp_a1
    assert QChip(qa.to_dict()).fingerprint() == fp_a2

    _, st_a2, key_a2 = cache.get_or_compile(prog_a, qa, n_qubits=2)
    assert st_a2 == 'miss'   # new epoch, new key, recompiled
    snap = cache.stats()
    assert snap['writeback_flushes'] == 1
    # EXACTLY the stale epoch: qa had one entry under fp_a1
    assert snap['invalidated_entries'] - snap0['invalidated_entries'] == 1
    # ... and qb's entry survived the flush
    _, st_b2, key_b2 = cache.get_or_compile(prog_b, qb, n_qubits=2)
    assert st_b2 == 'hit' and key_b2 == key_b
    assert key_a2 != key_b


# ---------------------------------------------------------------------------
# closed loops through the serve tier (tentpole (b)/(c))
# ---------------------------------------------------------------------------

def test_closed_loop_amplitude_converges_and_writes_back():
    """The flagship loop (ISSUE 20 acceptance): the device truth
    drifted to x90 = 0.52 while the qchip still says 0.48; the loop
    must find the truth through serve-tier candidate submissions,
    write it back to the live qchip, and flush exactly the stale
    compile-cache epoch via lineage tracking."""
    spec = LossSpec(knob='amplitude', x90_amp=0.52)
    qchip = make_default_qchip(2)
    assert qchip.gates['Q0X90'].contents[0].amp == pytest.approx(0.48)
    with ExecutionService() as svc:
        res = calibrate(svc, qchip, knob='amplitude', qubit='Q0',
                        spec=spec, shots=4, n_qubits=2,
                        result_timeout=RESULT_TIMEOUT)
        snap = svc.stats()
        cache_snap = svc.compile_cache.stats()
    assert res.converged and not res.diverged
    assert res.params['amp'] == pytest.approx(0.52, abs=5e-3)
    # loss trajectory descended
    assert res.losses[-1] < res.losses[0]
    # writeback landed on the LIVE qchip and moved its epoch
    assert qchip.gates['Q0X90'].contents[0].amp == \
        pytest.approx(res.params['amp'])
    assert res.fp_before != res.fp_after
    assert res.fp_after == qchip.fingerprint()
    # the post-writeback probe flushed the stale epoch: at least one
    # entry (the candidates compiled under fp_before), at most one per
    # step, through the lineage (writeback) path exactly once
    assert 1 <= res.flushed <= res.steps
    assert cache_snap['writeback_flushes'] == 1
    # session accounting: one converged session, fully closed
    assert snap['calibration']['sessions_opened'] == 1
    assert snap['calibration']['converged'] == 1
    assert snap['calibration']['diverged'] == 0
    assert snap['calibration']['open_sessions'] == 0
    assert snap['calibration']['steps'] == res.steps
    assert res.session['state'] == 'converged'


def test_closed_loop_readout_window_converges_and_writes_back():
    """Second acceptance knob: readout-window placement descends the
    soft-window SNR model to its interior optimum (the window fully
    rung up but not yet falling off the record) and writes the start
    back as the read pulses' t0."""
    qchip = make_default_qchip(2)
    for pulse in qchip.gates['Q0read'].contents:
        assert pulse.t0 == pytest.approx(0.0)
    with ExecutionService() as svc:
        res = calibrate(svc, qchip, knob='readout_window', qubit='Q0',
                        shots=4, n_qubits=2,
                        result_timeout=RESULT_TIMEOUT)
        snap = svc.stats()
    assert res.converged, res.detail
    start = res.params['window_start']
    # optimum sits near horizon - width = 320 samples (soft edges and
    # the ring-up tail shift it slightly)
    assert 260.0 <= start <= 400.0
    assert res.losses[-1] < res.losses[0]
    for pulse in qchip.gates['Q0read'].contents:
        assert pulse.t0 == pytest.approx(start * 1e-9)
    assert res.fp_before != res.fp_after
    assert 1 <= res.flushed <= res.steps
    assert snap['calibration']['converged'] == 1


def test_closed_loop_drag_converges():
    """DRAG-coefficient loop: spectral-leakage descent lands near the
    derivative-cancellation point alpha ~ 1 and writes the tuned alpha
    into the gate's envelope paradict."""
    qchip = make_default_qchip(2)
    with ExecutionService() as svc:
        res = calibrate(svc, qchip, knob='drag', qubit='Q0',
                        shots=4, n_qubits=2,
                        result_timeout=RESULT_TIMEOUT)
    assert res.converged, res.detail
    assert res.params['alpha'] == pytest.approx(1.0, abs=0.25)
    assert res.losses[-1] < res.losses[0]
    gate = qchip.gates['Q0X90'].contents[0]
    assert gate.env['paradict']['alpha'] == \
        pytest.approx(res.params['alpha'])
    assert res.fp_before != res.fp_after


def test_diverged_loop_is_counted_and_never_writes_back():
    """Divergence is a counted, observable outcome: a hopeless step
    size blows the loop out of bounds, the session lands in the
    ``diverged`` counter, and the live qchip is UNTOUCHED (no
    writeback, no epoch change)."""
    qchip = make_default_qchip(2)
    fp0 = qchip.fingerprint()
    with ExecutionService() as svc:
        res = calibrate(svc, qchip, knob='amplitude', qubit='Q0',
                        lr=5.0, shots=2, n_qubits=2,
                        result_timeout=RESULT_TIMEOUT)
        snap = svc.stats()
    assert res.diverged and not res.converged
    assert res.detail['reason']
    assert res.fp_before is None and res.fp_after is None
    assert res.flushed is None
    assert qchip.fingerprint() == fp0
    assert qchip.gates['Q0X90'].contents[0].amp == pytest.approx(0.48)
    assert snap['calibration']['diverged'] == 1
    assert snap['calibration']['converged'] == 0
    assert snap['calibration']['open_sessions'] == 0
    assert res.session['state'] == 'diverged'


def test_session_rejects_use_after_terminal():
    """Session lifecycle hygiene: a terminal session refuses further
    terminal transitions and a closed session refuses steps."""
    qchip = make_default_qchip(2)
    with ExecutionService() as svc:
        sess = svc.open_calibration(knob='amplitude')
        h = sess.submit_step(rabi_program('Q0', 0.3), qchip, shots=2,
                             n_qubits=2)
        h.result(timeout=RESULT_TIMEOUT)
        sess.mark_converged({'amp': 0.3})
        with pytest.raises(RuntimeError):
            sess.mark_diverged('too late')
        sess.close()
        with pytest.raises(RuntimeError):
            sess.submit_step(rabi_program('Q0', 0.3), qchip, shots=2,
                             n_qubits=2)
        assert svc.stats()['calibration']['open_sessions'] == 0
    assert isinstance(sess, CalibrationSession)


def test_executed_amp_word_closes_the_loop():
    """The loop linearizes at the AS-EXECUTED amplitude: the candidate
    word read back from rec_amp quantizes to round(amp * AMP_SCALE)."""
    from distributed_processor_tpu.calib.loops import _executed_amp
    qchip = make_default_qchip(2)
    amp = 0.337
    with ExecutionService() as svc:
        h = svc.submit_source(rabi_program('Q0', amp), qchip, shots=2,
                              n_qubits=2)
        res = h.result(timeout=RESULT_TIMEOUT)
    x_exec = _executed_amp(res, amp)
    assert x_exec == pytest.approx(amp, abs=1.0 / AMP_SCALE)
    assert x_exec == int(round(amp * AMP_SCALE)) / AMP_SCALE
    # a word the service never played is a loop bug and raises
    with pytest.raises(RuntimeError):
        _executed_amp(res, 0.9991)


# ---------------------------------------------------------------------------
# CLI (satellite 5)
# ---------------------------------------------------------------------------

def test_cli_calibrate_smoke(capsys):
    from distributed_processor_tpu.cli import main
    main(['calibrate', '--qubits', '2', '--shots', '2'])
    out = json.loads(capsys.readouterr().out)
    assert out['knob'] == 'amplitude'
    assert out['converged'] is True
    assert out['params']['amp'] == pytest.approx(0.52, abs=5e-3)
    assert len(out['losses']) == out['steps']
    assert out['service']['converged'] == 1


def test_cli_calibrate_exits_nonzero_on_divergence(capsys):
    from distributed_processor_tpu.cli import main
    with pytest.raises(SystemExit) as exc:
        main(['calibrate', '--qubits', '2', '--shots', '2',
              '--lr', '5.0'])
    assert 'diverged' in str(exc.value)
    out = json.loads(capsys.readouterr().out)
    assert out['diverged'] is True
    assert out['service']['diverged'] == 1
