"""Finite-horizon CW readout demodulation (round-3 weak #5).

The element contract allows CW (hold-until-next) readout envelopes
(reference: python/distproc/hwconfig.py:12-67 get_cw_env_word); round 3
flagged them as ERR_CW_MEAS because a CW window has no intrinsic
length.  ``ReadoutPhysics.cw_horizon`` closes the hole: CW measurement
windows demodulate over a configured horizon, with the envelope playing
through its table and holding the final sample.

The pin: the default qchip's rdlo envelope is a square, so a CW window
with horizon equal to the finite envelope's sample count must produce
BIT-IDENTICAL results to the finite program under the same key — in
every resolve mode — and the analytic closed form must agree with the
per-sample chain exactly at sigma=0.
"""

import numpy as np
import pytest

from distributed_processor_tpu.elements import ENV_CW_SENTINEL
from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.sim.interpreter import ERR_CW_MEAS
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)

KW = dict(max_steps=1024, max_pulses=8, max_meas=2)
SHOTS = 256


@pytest.fixture(scope='module')
def programs():
    """(finite_mp, cw_mp, n_samp): the same compiled read program with
    the rdlo env word patched to the CW sentinel in the copy."""
    import copy
    sim = Simulator(n_qubits=1)
    mp = sim.compile([{'name': 'read', 'qubit': ['Q0']}])
    soa = mp.soa
    meas_rows = (np.asarray(soa.p_cfg) & 0b11) == 2
    assert np.any(meas_rows)
    envw = int(np.asarray(soa.p_env)[meas_rows][0])
    n_words, addr = (envw >> 12) & 0xfff, envw & 0xfff
    ecfg = mp.tables[0].elem_cfgs[2]
    n_samp = n_words * 4 * int(ecfg.interp_ratio)
    cw_mp = copy.deepcopy(mp)
    cw_mp.soa.p_env[np.asarray(meas_rows)] = \
        (ENV_CW_SENTINEL << 12) | addr
    return mp, cw_mp, n_samp


def test_cw_without_horizon_is_an_error(programs):
    _, cw_mp, _ = programs
    model = ReadoutPhysics(sigma=0.0)
    out = run_physics_batch(cw_mp, model, 0, 4, **KW)
    err = np.asarray(out['err'])
    assert np.all(err & ERR_CW_MEAS), 'CW readout must flag ERR_CW_MEAS'


@pytest.mark.parametrize('mode', ['persample', 'fused', 'analytic'])
def test_cw_matches_finite_square_window(programs, mode):
    """Square envelope + hold == square envelope: CW at horizon n_samp
    is bit-identical to the finite program, per resolve mode."""
    mp, cw_mp, n_samp = programs
    kw = dict(sigma=15.0, p1_init=0.5, resolve_mode=mode)
    fin = run_physics_batch(mp, ReadoutPhysics(**kw), 7, SHOTS, **KW)
    cw = run_physics_batch(cw_mp, ReadoutPhysics(cw_horizon=n_samp, **kw),
                           7, SHOTS, **KW)
    for out in (fin, cw):
        assert not np.any(np.asarray(out['err']))
        assert not bool(out['incomplete'])
    np.testing.assert_array_equal(np.asarray(fin['meas_bits']),
                                  np.asarray(cw['meas_bits']))
    # the noise is doing real work: some assignment errors at this sigma
    mism = np.asarray(cw['meas_bits'])[:, 0, 0] \
        != np.asarray(cw['meas_state'])[:, 0, 0]
    assert 0 < mism.mean() < 0.5


def test_cw_analytic_agrees_with_persample_noiseless(programs):
    _, cw_mp, n_samp = programs
    outs = [run_physics_batch(
        cw_mp, ReadoutPhysics(sigma=0.0, p1_init=0.5, resolve_mode=m,
                              cw_horizon=n_samp), 3, SHOTS, **KW)
        for m in ('persample', 'analytic')]
    np.testing.assert_array_equal(np.asarray(outs[0]['meas_bits']),
                                  np.asarray(outs[1]['meas_bits']))
    # noiseless discrimination is perfect
    np.testing.assert_array_equal(np.asarray(outs[0]['meas_bits'])[:, 0, 0],
                                  np.asarray(outs[0]['meas_state'])[:, 0, 0])


def test_cw_shorter_horizon_less_energy(programs):
    """Half the horizon integrates half the energy: assignment error at
    fixed sigma must rise."""
    _, cw_mp, n_samp = programs
    errs = []
    for h in (n_samp, n_samp // 4):
        out = run_physics_batch(
            cw_mp, ReadoutPhysics(sigma=12.0, p1_init=0.5,
                                  cw_horizon=h), 11, 2048, **KW)
        bits = np.asarray(out['meas_bits'])[:, 0, 0]
        true = np.asarray(out['meas_state'])[:, 0, 0]
        errs.append((bits != true).mean())
    assert errs[1] > errs[0] * 1.5, errs


def test_cw_horizon_validation(programs):
    _, cw_mp, _ = programs
    with pytest.raises(ValueError, match='cw_horizon'):
        run_physics_batch(cw_mp, ReadoutPhysics(cw_horizon=10**6), 0, 2,
                          **KW)
