"""Register-parameterized 2D sweep tests (BASELINE config 5 shape)."""

import numpy as np

from distributed_processor_tpu.parallel import (
    swept_pulse_machine_program, grid_init_regs, sweep_cfg, make_mesh,
    sharded_simulate)
from distributed_processor_tpu.sim import simulate_batch


def test_grid_sweep_single_compile():
    n_cores = 2
    mp = swept_pulse_machine_program(n_cores, n_pulses=2)
    amps = [0x1000, 0x2000, 0x3000]
    freqs = [0, 1]
    regs = grid_init_regs(amps, freqs, n_cores)
    assert regs.shape == (6, n_cores, 16)
    cfg = sweep_cfg(mp, n_pulses_per_core=3)
    bits = np.zeros((6, n_cores, cfg.max_meas), int)
    out = simulate_batch(mp, bits, init_regs=regs, cfg=cfg)
    assert np.all(np.asarray(out['err']) == 0)
    # every sweep point played its own amplitude / frequency words
    rec_amp = np.asarray(out['rec_amp'])       # [points, cores, P]
    rec_freq = np.asarray(out['rec_freq'])
    for p in range(6):
        a, f = regs[p, 0, 0], regs[p, 0, 1]
        assert np.all(rec_amp[p, :, :2] == a)
        assert np.all(rec_freq[p, :, :2] == f)
    # the fixed readout pulse is unaffected by the sweep registers
    assert np.all(rec_amp[:, :, 2] == 0xffff)


def test_grid_sweep_sharded_over_mesh():
    n_cores = 8
    mp = swept_pulse_machine_program(n_cores, n_pulses=1)
    regs = grid_init_regs(np.arange(8) * 0x800, [0], n_cores)   # 8 points
    cfg = sweep_cfg(mp, n_pulses_per_core=2)
    bits = np.zeros((8, n_cores, cfg.max_meas), int)
    mesh = make_mesh(n_dp=8)
    out = sharded_simulate(mp, bits, mesh, init_regs=regs, cfg=cfg)
    local = simulate_batch(mp, bits, init_regs=regs, cfg=cfg)
    np.testing.assert_array_equal(np.asarray(out['rec_amp']),
                                  np.asarray(local['rec_amp']))
    np.testing.assert_array_equal(np.asarray(out['rec_gtime']),
                                  np.asarray(local['rec_gtime']))
