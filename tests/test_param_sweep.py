"""Register-parameterized 2D sweep tests (BASELINE config 5 shape)."""

import numpy as np
import pytest

from distributed_processor_tpu.parallel import (
    swept_pulse_machine_program, grid_init_regs, sweep_cfg, make_mesh,
    sharded_simulate, sweep_stats)
from distributed_processor_tpu.sim import simulate_batch


def test_grid_sweep_single_compile():
    n_cores = 2
    mp = swept_pulse_machine_program(n_cores, n_pulses=2)
    amps = [0x1000, 0x2000, 0x3000]
    freqs = [0, 1]
    regs = grid_init_regs(amps, freqs, n_cores)
    assert regs.shape == (6, n_cores, 16)
    cfg = sweep_cfg(mp, n_pulses_per_core=3)
    bits = np.zeros((6, n_cores, cfg.max_meas), int)
    out = simulate_batch(mp, bits, init_regs=regs, cfg=cfg)
    assert np.all(np.asarray(out['err']) == 0)
    # every sweep point played its own amplitude / frequency words
    rec_amp = np.asarray(out['rec_amp'])       # [points, cores, P]
    rec_freq = np.asarray(out['rec_freq'])
    for p in range(6):
        a, f = regs[p, 0, 0], regs[p, 0, 1]
        assert np.all(rec_amp[p, :, :2] == a)
        assert np.all(rec_freq[p, :, :2] == f)
    # the fixed readout pulse is unaffected by the sweep registers
    assert np.all(rec_amp[:, :, 2] == 0xffff)


def test_grid_sweep_sharded_over_mesh():
    n_cores = 8
    mp = swept_pulse_machine_program(n_cores, n_pulses=1)
    regs = grid_init_regs(np.arange(8) * 0x800, [0], n_cores)   # 8 points
    cfg = sweep_cfg(mp, n_pulses_per_core=2)
    bits = np.zeros((8, n_cores, cfg.max_meas), int)
    mesh = make_mesh(n_dp=8)
    out = sharded_simulate(mp, bits, mesh, init_regs=regs, cfg=cfg)
    local = simulate_batch(mp, bits, init_regs=regs, cfg=cfg)
    np.testing.assert_array_equal(np.asarray(out['rec_amp']),
                                  np.asarray(local['rec_amp']))
    np.testing.assert_array_equal(np.asarray(out['rec_gtime']),
                                  np.asarray(local['rec_gtime']))


def test_sweep_stats_uses_init_regs():
    """Regression: sweep statistics must see the per-point register file,
    not an all-zero one (advisor finding).  Register 2 gates a branch
    around the pulse, so mean_pulses depends on init_regs."""
    from distributed_processor_tpu import isa
    from distributed_processor_tpu.decoder import machine_program_from_cmds
    from distributed_processor_tpu.sim.oracle import START_NCLKS

    n_cores = 2
    cmds = [
        isa.alu_cmd('jump_cond', 'r', 2, 'id0', jump_cmd_ptr=2),
        isa.pulse_cmd(freq_word=0, phase_word=0, amp_word=0x8000,
                      env_word=(3 << 12), cfg_word=0,
                      cmd_time=START_NCLKS + 8),
        isa.done_cmd(),
    ]
    mp = machine_program_from_cmds([list(cmds) for _ in range(n_cores)])
    cfg = sweep_cfg(mp, n_pulses_per_core=2)
    # 4 sweep points: reg2 = 0, 1, 0, 1  ->  pulse plays on points 0 and 2
    regs = np.zeros((4, n_cores, isa.N_REGS), dtype=np.int32)
    regs[1, :, 2] = 1
    regs[3, :, 2] = 1
    bits = np.zeros((4, n_cores, cfg.max_meas), int)
    mesh = make_mesh(n_dp=4)
    stats = sweep_stats(mp, bits, mesh, init_regs=regs, cfg=cfg)
    assert float(stats['err_rate']) == 0.0
    np.testing.assert_allclose(np.asarray(stats['mean_pulses']),
                               np.full(n_cores, 0.5))
    local = simulate_batch(mp, bits, init_regs=regs, cfg=cfg)
    np.testing.assert_array_equal(np.asarray(local['n_pulses']),
                                  [[1, 1], [0, 0], [1, 1], [0, 0]])


def test_compiled_register_sweep_physics_rabi():
    """Register-parameterized sweep through the COMPILED path with the
    measurement loop closed by physics: declare an amp-typed variable,
    reference it from a drive pulse, preload it per shot via
    make_init_regs (the simulator-side analog of the reference host
    writing parameter registers over the FPGA bus), and watch the
    classical Rabi staircase emerge from demodulated bits — one
    compile, the amplitude axis pure data."""
    from distributed_processor_tpu.pipeline import compile_to_machine
    from distributed_processor_tpu.decoder import make_init_regs
    from distributed_processor_tpu.models import make_default_qchip
    from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                       run_physics_batch)
    qchip = make_default_qchip(1)
    program = [
        {'name': 'declare', 'var': 'drive_amp', 'dtype': 'amp',
         'scope': ['Q0']},
        {'name': 'pulse', 'freq': 'Q0.freq', 'phase': 0.0,
         'amp': 'drive_amp',
         'env': {'env_func': 'cos_edge_square',
                 'paradict': {'ramp_fraction': 0.25}},
         'twidth': 32e-9, 'dest': 'Q0.qdrv'},
        {'name': 'read', 'qubit': ['Q0']},
    ]
    mp = compile_to_machine(program, qchip, n_qubits=1)
    assert mp.reg_maps[0]['drive_amp']['dtype'] == ('amp', 0)

    amps = np.linspace(0.0, 1.0, 16)
    regs = make_init_regs(mp, {'drive_amp': amps}, n_shots=16)
    model = ReadoutPhysics(sigma=0.01, p1_init=0.0)
    out = run_physics_batch(mp, model, 0, 16,
                            init_states=np.zeros((16, 1), np.int32),
                            init_regs=regs, max_steps=mp.n_instr * 4 + 64,
                            max_pulses=8, max_meas=2)
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))
    bits = np.asarray(out['meas_bits'])[:, 0, 0]
    # classical model: state = (round(amp / x90_amp) >> 1) & 1 with the
    # default-qchip X90 amplitude 0.48
    expect = (np.round(amps / 0.48).astype(int) >> 1) & 1
    np.testing.assert_array_equal(bits, expect)


def test_make_init_regs_errors():
    from distributed_processor_tpu.pipeline import compile_to_machine
    from distributed_processor_tpu.decoder import make_init_regs
    from distributed_processor_tpu.models import make_default_qchip
    mp = compile_to_machine(
        [{'name': 'declare', 'var': 'v', 'dtype': 'int', 'scope': ['Q0']},
         {'name': 'X90', 'qubit': ['Q0']}],
        make_default_qchip(1), n_qubits=1)
    regs = make_init_regs(mp, {'v': 7})
    assert regs[0, mp.reg_maps[0]['v']['index']] == 7
    with pytest.raises(KeyError, match='nope'):
        make_init_regs(mp, {'nope': 1})
    with pytest.raises(ValueError, match='n_shots'):
        make_init_regs(mp, {'v': np.arange(4)})        # array, no n_shots
    with pytest.raises(ValueError, match='n_shots'):
        make_init_regs(mp, {'v': np.arange(4)}, n_shots=8)  # length mismatch


def test_physics_sweep_driver_resumes(tmp_path):
    """run_physics_sweep: batched physics-closed accumulation with a
    checkpoint; an interrupted sweep resumed from disk produces the
    identical statistics (the key stream is indexed by batch)."""
    from distributed_processor_tpu.simulator import Simulator
    from distributed_processor_tpu.models.experiments import active_reset
    from distributed_processor_tpu.parallel import run_physics_sweep
    from distributed_processor_tpu.sim.physics import ReadoutPhysics

    sim = Simulator(n_qubits=2)
    mp = sim.compile(active_reset(['Q0', 'Q1']))
    model = ReadoutPhysics(sigma=0.01, p1_init=0.5)
    kw = dict(max_steps=mp.n_instr * 4 + 64, max_pulses=8, max_meas=2)

    full = run_physics_sweep(mp, model, 64, 16, key=5, **kw)
    assert full['shots'] == 64
    assert full['err_shots'] == 0 and full['incomplete_batches'] == 0
    assert np.all((full['meas1_rate'] > 0.3) & (full['meas1_rate'] < 0.7))
    np.testing.assert_allclose(full['mean_pulses'],
                               2 + 2 * full['meas1_rate'])

    # run 2 of 4 batches, "crash", resume the rest: identical result
    ckpt = str(tmp_path / 'sweep.npz')
    part = run_physics_sweep(mp, model, 32, 16, key=5, checkpoint=ckpt,
                             checkpoint_every=1, **kw)
    assert part['shots'] == 32
    resumed = run_physics_sweep(mp, model, 64, 16, key=5, checkpoint=ckpt,
                                checkpoint_every=1, **kw)
    assert resumed['shots'] == 64
    np.testing.assert_array_equal(resumed['meas1_rate'],
                                  full['meas1_rate'])
    np.testing.assert_array_equal(resumed['mean_pulses'],
                                  full['mean_pulses'])
    # a checkpoint from a different sweep identity is rejected
    with pytest.raises(ValueError, match='different sweep'):
        run_physics_sweep(mp, model, 64, 32, key=5, checkpoint=ckpt, **kw)
    with pytest.raises(ValueError, match='positive'):
        run_physics_sweep(mp, model, 0, 16, key=5, **kw)


def test_physics_sweep_driver_sharded(tmp_path):
    """run_physics_sweep(mesh=...): every batch shards over dp with
    per-(batch, shard) key folding; statistics reduce on-device.  The
    sharded sweep completes and its checkpoint is identity-distinct
    from a single-device one."""
    from distributed_processor_tpu.simulator import Simulator
    from distributed_processor_tpu.models.experiments import active_reset
    from distributed_processor_tpu.parallel import (run_physics_sweep,
                                                    make_mesh)
    from distributed_processor_tpu.sim.physics import ReadoutPhysics

    sim = Simulator(n_qubits=2)
    mp = sim.compile(active_reset(['Q0', 'Q1']))
    model = ReadoutPhysics(sigma=0.01, p1_init=0.5)
    kw = dict(max_steps=mp.n_instr * 4 + 64, max_pulses=8, max_meas=2)
    mesh = make_mesh(n_dp=8)

    out = run_physics_sweep(mp, model, 64, 32, key=5, mesh=mesh, **kw)
    assert out['shots'] == 64
    assert out['err_shots'] == 0 and out['incomplete_batches'] == 0
    assert np.all((out['meas1_rate'] > 0.3) & (out['meas1_rate'] < 0.7))
    np.testing.assert_allclose(out['mean_pulses'],
                               2 + 2 * out['meas1_rate'])

    # a single-device checkpoint cannot be resumed on the mesh
    ckpt = str(tmp_path / 's.npz')
    run_physics_sweep(mp, model, 32, 32, key=5, checkpoint=ckpt, **kw)
    with pytest.raises(ValueError, match='different sweep'):
        run_physics_sweep(mp, model, 64, 32, key=5, checkpoint=ckpt,
                          mesh=mesh, **kw)


def test_physics_sweep_warns_on_incomplete_batches(tmp_path):
    """ADVICE r2: incomplete shots dilute the reported means — the
    driver must warn rather than let the counter go unnoticed."""
    from distributed_processor_tpu.simulator import Simulator
    from distributed_processor_tpu.models.experiments import active_reset
    from distributed_processor_tpu.parallel import run_physics_sweep
    from distributed_processor_tpu.sim.physics import ReadoutPhysics

    sim = Simulator(n_qubits=2)
    mp = sim.compile(active_reset(['Q0', 'Q1']))
    model = ReadoutPhysics(sigma=0.01, p1_init=0.5)
    with pytest.warns(UserWarning, match='did not finish'):
        out = run_physics_sweep(mp, model, 32, 16, key=5,
                                max_steps=3, max_pulses=8, max_meas=2)
    assert out['incomplete_batches'] == 2


def test_prebuilt_tables_mismatch_rejected():
    """Advisor round-3: tables built for a different window/chunk/mode/
    meas_elem must be rejected, not silently chunk-sliced wrong."""
    from dataclasses import replace
    from distributed_processor_tpu.simulator import Simulator
    from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                       prepare_physics_tables,
                                                       run_physics_batch)
    sim = Simulator(n_qubits=1)
    mp = sim.compile([{'name': 'X90', 'qubit': ['Q0']},
                      {'name': 'read', 'qubit': ['Q0']}])
    model = ReadoutPhysics(sigma=0.0, window_samples=512)
    tabs = prepare_physics_tables(mp, model)
    # matching tables run fine
    out = run_physics_batch(mp, model, 0, 2, tables=tabs, max_steps=512,
                            max_pulses=8, max_meas=2)
    assert not bool(out['incomplete'])
    for wrong in (replace(model, window_samples=256),
                  replace(model, resolve_chunk=64),
                  replace(model, resolve_mode='analytic')):
        with pytest.raises(ValueError, match='different resolve'):
            run_physics_batch(mp, wrong, 0, 2, tables=tabs, max_steps=512,
                              max_pulses=8, max_meas=2)
    # same shapes, different CONTENT: a program against another qchip
    # (shifted readout frequency) must be rejected by the digest
    from distributed_processor_tpu.models.default_qchip import \
        make_default_qchip_dict
    from distributed_processor_tpu.qchip import QChip
    d = make_default_qchip_dict(1)
    d['Qubits']['Q0']['readfreq'] = 6.5e9
    sim_b = Simulator(qchip=QChip(d), n_qubits=1)
    mp_b = sim_b.compile([{'name': 'X90', 'qubit': ['Q0']},
                          {'name': 'read', 'qubit': ['Q0']}])
    with pytest.raises(ValueError, match='digest'):
        run_physics_batch(mp_b, model, 0, 2, tables=tabs, max_steps=512,
                          max_pulses=8, max_meas=2)


def test_strict_resume_rejects_version_skew(tmp_path):
    """Advisor round-3: strict=True refuses unfingerprinted or
    version-skewed checkpoints that the lenient path accepts with a
    warning."""
    from distributed_processor_tpu.utils.results import SweepAccumulator
    path = str(tmp_path / 'acc.npz')
    # legacy checkpoint: no identity at all
    acc = SweepAccumulator(path)
    acc.add({'n': np.int64(3)})
    acc.save()
    meta = {'fingerprint_version': 2, 'batch': 16}
    with pytest.warns(UserWarning, match='no identity'):
        SweepAccumulator.resume(path, meta=meta)
    with pytest.raises(ValueError, match='strict resume'):
        SweepAccumulator.resume(path, meta=meta, strict=True)
    # version-skewed checkpoint
    acc = SweepAccumulator(path, meta={'fingerprint_version': 1,
                                       'batch': 16})
    acc.add({'n': np.int64(3)})
    acc.save()
    with pytest.warns(UserWarning, match='fingerprint version'):
        SweepAccumulator.resume(path, meta=meta)
    with pytest.raises(ValueError, match='strict resume'):
        SweepAccumulator.resume(path, meta=meta, strict=True)
    # matching version passes strict
    acc = SweepAccumulator(path, meta=meta)
    acc.add({'n': np.int64(3)})
    acc.save()
    got = SweepAccumulator.resume(path, meta=meta, strict=True)
    assert got.n_batches == 1
