"""Register-parameterized 2D sweep tests (BASELINE config 5 shape)."""

import numpy as np

from distributed_processor_tpu.parallel import (
    swept_pulse_machine_program, grid_init_regs, sweep_cfg, make_mesh,
    sharded_simulate, sweep_stats)
from distributed_processor_tpu.sim import simulate_batch


def test_grid_sweep_single_compile():
    n_cores = 2
    mp = swept_pulse_machine_program(n_cores, n_pulses=2)
    amps = [0x1000, 0x2000, 0x3000]
    freqs = [0, 1]
    regs = grid_init_regs(amps, freqs, n_cores)
    assert regs.shape == (6, n_cores, 16)
    cfg = sweep_cfg(mp, n_pulses_per_core=3)
    bits = np.zeros((6, n_cores, cfg.max_meas), int)
    out = simulate_batch(mp, bits, init_regs=regs, cfg=cfg)
    assert np.all(np.asarray(out['err']) == 0)
    # every sweep point played its own amplitude / frequency words
    rec_amp = np.asarray(out['rec_amp'])       # [points, cores, P]
    rec_freq = np.asarray(out['rec_freq'])
    for p in range(6):
        a, f = regs[p, 0, 0], regs[p, 0, 1]
        assert np.all(rec_amp[p, :, :2] == a)
        assert np.all(rec_freq[p, :, :2] == f)
    # the fixed readout pulse is unaffected by the sweep registers
    assert np.all(rec_amp[:, :, 2] == 0xffff)


def test_grid_sweep_sharded_over_mesh():
    n_cores = 8
    mp = swept_pulse_machine_program(n_cores, n_pulses=1)
    regs = grid_init_regs(np.arange(8) * 0x800, [0], n_cores)   # 8 points
    cfg = sweep_cfg(mp, n_pulses_per_core=2)
    bits = np.zeros((8, n_cores, cfg.max_meas), int)
    mesh = make_mesh(n_dp=8)
    out = sharded_simulate(mp, bits, mesh, init_regs=regs, cfg=cfg)
    local = simulate_batch(mp, bits, init_regs=regs, cfg=cfg)
    np.testing.assert_array_equal(np.asarray(out['rec_amp']),
                                  np.asarray(local['rec_amp']))
    np.testing.assert_array_equal(np.asarray(out['rec_gtime']),
                                  np.asarray(local['rec_gtime']))


def test_sweep_stats_uses_init_regs():
    """Regression: sweep statistics must see the per-point register file,
    not an all-zero one (advisor finding).  Register 2 gates a branch
    around the pulse, so mean_pulses depends on init_regs."""
    from distributed_processor_tpu import isa
    from distributed_processor_tpu.decoder import machine_program_from_cmds
    from distributed_processor_tpu.sim.oracle import START_NCLKS

    n_cores = 2
    cmds = [
        isa.alu_cmd('jump_cond', 'r', 2, 'id0', jump_cmd_ptr=2),
        isa.pulse_cmd(freq_word=0, phase_word=0, amp_word=0x8000,
                      env_word=(3 << 12), cfg_word=0,
                      cmd_time=START_NCLKS + 8),
        isa.done_cmd(),
    ]
    mp = machine_program_from_cmds([list(cmds) for _ in range(n_cores)])
    cfg = sweep_cfg(mp, n_pulses_per_core=2)
    # 4 sweep points: reg2 = 0, 1, 0, 1  ->  pulse plays on points 0 and 2
    regs = np.zeros((4, n_cores, isa.N_REGS), dtype=np.int32)
    regs[1, :, 2] = 1
    regs[3, :, 2] = 1
    bits = np.zeros((4, n_cores, cfg.max_meas), int)
    mesh = make_mesh(n_dp=4)
    stats = sweep_stats(mp, bits, mesh, init_regs=regs, cfg=cfg)
    assert float(stats['err_rate']) == 0.0
    np.testing.assert_allclose(np.asarray(stats['mean_pulses']),
                               np.full(n_cores, 0.5))
    local = simulate_batch(mp, bits, init_regs=regs, cfg=cfg)
    np.testing.assert_array_equal(np.asarray(local['n_pulses']),
                                  [[1, 1], [0, 0], [1, 1], [0, 0]])
