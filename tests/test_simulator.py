"""Simulator facade tests: run API, waveform rendering, and the full
physics loop (BASELINE config 2: synthesize readout -> demod -> bits)."""

import numpy as np
import pytest

from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.ops import (pulse_window_weights, demod_iq,
                                           stack_window_weights,
                                           iq_to_complex)
from distributed_processor_tpu.ops.demod import discriminate


@pytest.fixture(scope='module')
def sim2():
    return Simulator(n_qubits=2)


def test_run_dict_program(sim2):
    out = sim2.run([{'name': 'X90', 'qubit': ['Q0']},
                    {'name': 'read', 'qubit': ['Q0']}])
    assert int(out['err'][0]) == 0
    assert int(out['n_pulses'][0]) == 3


def test_run_qasm_batch(sim2):
    out = sim2.run('qubit[1] q; reset q[0];', shots=8, p1=0.5)
    assert np.asarray(out['n_pulses']).shape == (8, 1)
    assert np.all(np.asarray(out['err']) == 0)


def test_waveform_x90_matches_env(sim2):
    """The rendered qdrv trace must be the calibrated DRAG envelope times
    the carrier — checked against an independent reconstruction."""
    out = sim2.run([{'name': 'X90', 'qubit': ['Q0']}])
    mp = out['_mp']
    wf = sim2.waveforms(out)
    trace = iq_to_complex(wf[0][0])          # core 0, qdrv

    n = int(out['n_pulses'][0])
    assert n == 1
    gtime = int(out['rec_gtime'][0, 0])
    amp_word = int(out['rec_amp'][0, 0])
    spc = mp.tables[0].elem_cfgs[0].samples_per_clk
    env = np.asarray(mp.tables[0].envs[0]) / (2**15 - 1)
    freq_hz = mp.tables[0].freqs[0]['freq'][int(out['rec_freq'][0, 0])]
    fs = mp.tables[0].elem_cfgs[0].sample_freq

    start = gtime * spc
    env_word = int(out['rec_env'][0, 0])
    n_env = ((env_word >> 12) & 0xfff) * 4
    k = np.arange(n_env)
    expected = (amp_word / (2**16 - 1)) * env[:n_env] \
        * np.exp(2j * np.pi * (freq_hz / fs) * (start + k))
    got = trace[start:start + n_env]
    np.testing.assert_allclose(got, expected, atol=1e-4)
    # nothing before the pulse
    assert np.allclose(trace[:start], 0)


def test_readout_physics_loop(sim2):
    """Config 2: run read, synthesize the rdlo tone, demod with a matched
    window, discriminate against calibrated centroids."""
    out = sim2.run([{'name': 'read', 'qubit': ['Q0']}])
    mp = out['_mp']
    wf = sim2.waveforms(out)
    rdlo = wf[0][2]                          # core 0, elem 2 trace [N, 2]

    ecfg = mp.tables[0].elem_cfgs[2]
    spc = ecfg.samples_per_clk
    # locate the rdlo pulse record
    elems = np.asarray(out['rec_elem'][0, :int(out['n_pulses'][0])])
    i = int(np.nonzero(elems == 2)[0][0])
    gtime = int(out['rec_gtime'][0, i])
    dur = int(out['rec_dur'][0, i])
    freq_hz = mp.tables[0].freqs[2]['freq'][int(out['rec_freq'][0, i])]

    w = pulse_window_weights(gtime, dur, spc, freq_hz, ecfg.sample_freq)
    W = stack_window_weights([w], rdlo.shape[0], starts=[gtime * spc])
    # demod the I component of the synthesized trace (ADC sees I)
    iq = iq_to_complex(demod_iq(rdlo[None, :, 0], W))[0, 0]
    n_win = dur * spc
    # matched filter on a unit tone: |IQ| ~ n_win/2 (amp=1.0 rdlo pulse)
    assert abs(iq) > 0.4 * n_win / 2
    # discriminates cleanly against centroids on/off the tone
    bits = discriminate(
        np.array([[[iq.real, iq.imag]]]),
        centers0=np.array([0j]), centers1=np.array([iq]))
    assert int(bits[0, 0]) == 1


def test_waveform_batched_shot_selection(sim2):
    out = sim2.run('qubit[1] q; reset q[0];', shots=4,
                   meas_bits=np.concatenate([np.zeros((2, 1, 16), int),
                                             np.ones((2, 1, 16), int)]))
    wf0 = sim2.waveforms(out, shot=0)
    wf3 = sim2.waveforms(out, shot=3, n_clks=600)
    # measured-1 shot plays the two extra X90s on qdrv
    e0 = np.abs(iq_to_complex(wf0[0][0])).sum()
    e3 = np.abs(iq_to_complex(wf3[0][0])).sum()
    assert e3 > e0


def test_deep_on_device_loop_bounded_memory(sim2):
    """A 256-iteration on-device shot loop executes without the record
    state scaling with step count (slot-indexed records: [B,C,P,F] is
    the only pulse buffer), and matches the scalar oracle."""
    from distributed_processor_tpu.models.experiments import loop_shots_program
    from distributed_processor_tpu.sim.oracle import run_oracle

    sim = Simulator(n_qubits=1)
    n_iter = 256
    prog = loop_shots_program([{'name': 'X90', 'qubit': ['Q0']}],
                              n_iter, scope=['Q0'])
    mp = sim.compile(prog)
    out = sim.run(mp, shots=4, max_steps=16 * (n_iter + 2),
                  max_pulses=n_iter + 8, max_meas=1, max_resets=2)
    assert not bool(out['incomplete'])
    assert np.all(np.asarray(out['err']) == 0)
    o = run_oracle(mp, max_steps=16 * (n_iter + 2))
    n_eng = int(np.asarray(out['n_pulses'])[0, 0])
    assert n_eng == len(o['pulses'][0]) >= n_iter
    # per-iteration schedules repeat: pulse times advance by a fixed delta
    gt = np.asarray(out['rec_gtime'])[0, 0, :n_eng]
    deltas = np.diff(gt)
    assert np.all(deltas == deltas[0])
    assert np.array_equal(gt, [p['gtime'] for p in o['pulses'][0]])


def test_static_loop_bounds_size_deep_loops():
    """interpreter_config sizes budgets from static loop analysis, so a
    deep counter loop runs to completion with NO explicit budget
    overrides (round-1 review: deep loops silently truncated under the
    old 64*n_instr heuristic)."""
    from distributed_processor_tpu.models.experiments import loop_shots_program

    sim = Simulator(n_qubits=1)
    n_iter = 300                        # > the old fallback of 64
    prog = loop_shots_program([{'name': 'X90', 'qubit': ['Q0']}],
                              n_iter, scope=['Q0'])
    mp = sim.compile(prog)
    # the analysis recognizes the counter idiom exactly
    loops = mp.loop_bounds(0)
    assert len(loops) == 1 and loops[0][2] == n_iter + 1
    bounds = mp.static_bounds()
    assert bounds['max_pulses'] >= n_iter + 1
    out = sim.run(mp, shots=2, max_meas=1)      # no budget overrides
    assert not bool(out['incomplete'])
    assert np.all(np.asarray(out['err']) == 0)
    assert int(np.asarray(out['n_pulses'])[0, 0]) >= n_iter


def test_loop_bounds_refuses_data_driven_loops():
    """Static analysis must return None (fallback), never a confident
    wrong bound, when the counter is data-driven: seeded via init_regs,
    updated from fproc data, or looping via a backward jump_i."""
    from distributed_processor_tpu import isa
    from distributed_processor_tpu.decoder import machine_program_from_cmds

    # counter seeded only by init_regs (no in-program initializer)
    mp = machine_program_from_cmds([[
        isa.alu_cmd('reg_alu', 'i', -1, 'add', 1, write_reg_addr=1),  # 0
        isa.alu_cmd('jump_cond', 'i', 0, 'le', 1, jump_cmd_ptr=0),    # 1
        isa.done_cmd(),
    ]])
    assert mp.loop_bounds(0) == [(0, 1, None)]

    # fproc-driven counter update inside the body
    mp = machine_program_from_cmds([[
        isa.alu_cmd('reg_alu', 'i', 0, 'id0', write_reg_addr=1),      # 0
        isa.alu_cmd('alu_fproc', 'i', 0, 'add', write_reg_addr=1,
                    func_id=0),                                       # 1
        isa.alu_cmd('reg_alu', 'i', 1, 'add', 1, write_reg_addr=1),   # 2
        isa.alu_cmd('jump_cond', 'i', 10, 'ge', 1, jump_cmd_ptr=1),   # 3
        isa.done_cmd(),
    ]])
    assert mp.loop_bounds(0) == [(1, 3, None)]

    # poll loop: forward jump_fproc exit + backward jump_i
    mp = machine_program_from_cmds([[
        isa.alu_cmd('jump_fproc', 'i', 1, 'eq', jump_cmd_ptr=2,
                    func_id=0),                                       # 0
        isa.jump_i(0),                                                # 1
        isa.done_cmd(),                                               # 2
    ]])
    bounds = mp.static_bounds(loop_fallback=50)
    assert bounds['max_steps'] > 50     # fallback applied to the span


def test_truncation_warns_loudly():
    """Exhausting max_steps raises a RuntimeWarning naming the budget,
    instead of only setting a quiet flag."""
    import warnings
    from distributed_processor_tpu.models.experiments import loop_shots_program

    sim = Simulator(n_qubits=1)
    prog = loop_shots_program([{'name': 'X90', 'qubit': ['Q0']}],
                              200, scope=['Q0'])
    mp = sim.compile(prog)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter('always')
        out = sim.run(mp, shots=2, max_steps=32, max_meas=1)
    assert bool(out['incomplete'])
    assert any('max_steps' in str(w.message) for w in caught)


def test_loop_bounds_exact_at_single_trip():
    """A down-counting do-while whose limit already covers the seed
    still has a statically exact bound of 1 (the body runs once before
    the back-edge test) — not a loop_fallback over-allocation."""
    from distributed_processor_tpu import isa
    from distributed_processor_tpu.decoder import machine_program_from_cmds
    for op, init, lim, want in (('le', 5, 5, 1), ('le', 5, 9, 1),
                                ('le', 5, 0, 5), ('ge', 5, 3, 1),
                                ('ge', 0, 9, 10)):
        step = -1 if op == 'le' else 1
        mp = machine_program_from_cmds([[
            isa.alu_cmd('reg_alu', 'i', init, 'id0', write_reg_addr=1),
            isa.alu_cmd('reg_alu', 'i', step, 'add', 1, write_reg_addr=1),
            isa.alu_cmd('jump_cond', 'i', lim, op, 1, jump_cmd_ptr=1),
            isa.done_cmd(),
        ]])
        assert mp.loop_bounds(0) == [(1, 2, want)], (op, init, lim)
    # int32 counter wrap breaks the closed form: fall back (None), never
    # a confident under-sized bound (the wrapped comparison re-enters)
    mp = machine_program_from_cmds([[
        isa.alu_cmd('reg_alu', 'i', 2**31 - 1, 'id0', write_reg_addr=1),
        isa.alu_cmd('reg_alu', 'i', 1, 'add', 1, write_reg_addr=1),
        isa.alu_cmd('jump_cond', 'i', 0, 'ge', 1, jump_cmd_ptr=1),
        isa.done_cmd(),
    ]])
    assert mp.loop_bounds(0) == [(1, 2, None)]
