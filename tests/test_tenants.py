"""Tenant isolation fabric (docs/SERVING.md "Tenants"): the contract.

Five load-bearing properties:

* **Fair queueing is weighted and starvation-free** — deficit
  round-robin interleaves tenants by configured weight above the
  (priority, arrival) order, so a greedy tenant's thousandth request
  cannot starve a victim's first; a single-tenant queue reduces
  exactly to the legacy claim order.
* **Quotas are typed and non-retryable** — admission past a tenant's
  max-queued / shots-per-s / compile-submissions-per-s limit raises
  :class:`QuotaExceededError` (program-class: retrying cannot help),
  distinct from :class:`OverloadError` backpressure, and never sheds
  another tenant's work.
* **Metering is billing-grade** — per-tenant shots / device-ms /
  compile-ms / bytes-on-wire counters match ground truth exactly,
  including under chaos retries (only token-valid resolutions bill).
* **Streams inherit their session's tenant** and in-flight session
  chunks plus service-internal work are exempt from overload shedding
  driven by another tenant's admission pressure.
* **Elasticity is hysteretic** — the autoscale policy acts only on a
  SUSTAINED breach/slack signal and respects the action cooldown, so
  a noisy p99 cannot flap the replica population.
"""

import time

import numpy as np
import pytest

from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import machine_program_from_cmds
from distributed_processor_tpu.serve import (ChaosMonkey, ChaosPlan,
                                             Coalescer,
                                             ExecutionService,
                                             OverloadError, RetryPolicy)
from distributed_processor_tpu.serve.batcher import shed_exempt
from distributed_processor_tpu.serve.fleet import AutoscalePolicy
from distributed_processor_tpu.serve.request import (QuotaExceededError,
                                                     Request,
                                                     RequestHandle)
from distributed_processor_tpu.serve.transport import (ReplicaClient,
                                                       ReplicaServer)
from distributed_processor_tpu.sim.interpreter import (
    InterpreterConfig, is_infrastructure_error)
from distributed_processor_tpu.utils import profiling

pytestmark = [pytest.mark.tenants, pytest.mark.serve]


def _mp(salt=0):
    core = [isa.pulse_cmd(amp_word=1000 + 7 * salt + 13 * i, cfg_word=0,
                          env_word=3, cmd_time=10 + 20 * i)
            for i in range(3)] + [isa.done_cmd()]
    return machine_program_from_cmds([core])


_CFG = InterpreterConfig(max_steps=2 * 8 + 64, max_pulses=8 + 2,
                         max_meas=2, max_resets=2)


def _bits(rng, shots=3):
    return rng.integers(0, 2, size=(shots, 1, 2)).astype(np.int32)


def _req(seq, tenant='default', priority=0, rounds=None, sid=None):
    return Request(mp=None, meas_bits=None, init_regs=None, cfg=None,
                   strict=False, n_shots=3, priority=priority,
                   deadline=None, seq=seq, handle=RequestHandle(),
                   rounds=rounds, sid=sid, tenant=tenant)


# ---------------------------------------------------------------------------
# DRR fair queueing (Coalescer unit)
# ---------------------------------------------------------------------------


def test_drr_interleaves_tenants_against_fifo():
    """A greedy tenant fills the queue before the victim's first
    request arrives: with fair queueing on, the very first popped
    batch still contains victim work — strict global FIFO would make
    the victim wait out the entire greedy backlog."""
    q = Coalescer(max_batch_programs=4, max_wait_s=0.0,
                  tenant_weights={'greedy': 1.0, 'victim': 1.0})
    key = ('b',)
    for seq in range(12):
        q.push(key, _req(seq, tenant='greedy'))
    q.push(key, _req(100, tenant='victim'))
    q.push(key, _req(101, tenant='victim'))
    _, batch, _ = q.pop_batch(flush=True)
    tenants = [r.tenant for r in batch]
    assert 'victim' in tenants, \
        f'victim starved out of the first batch: {tenants}'
    # within each tenant, arrival order is preserved
    greedy_seqs = [r.seq for r in batch if r.tenant == 'greedy']
    assert greedy_seqs == sorted(greedy_seqs)


def test_drr_weights_shape_throughput():
    """weight 3 vs 1: over enough batches the heavy tenant claims
    roughly 3x the light one's slots (exact thirds here because both
    stay backlogged the whole time)."""
    q = Coalescer(max_batch_programs=4, max_wait_s=0.0,
                  tenant_weights={'heavy': 3.0, 'light': 1.0})
    key = ('b',)
    for seq in range(40):
        q.push(key, _req(seq, tenant='heavy'))
        q.push(key, _req(1000 + seq, tenant='light'))
    served = {'heavy': 0, 'light': 0}
    for _ in range(10):
        _, batch, _ = q.pop_batch(flush=True)
        for r in batch:
            served[r.tenant] += 1
    assert served['heavy'] + served['light'] == 40
    # 3:1 weights -> 30:10 of the first 40 slots
    assert served['heavy'] == pytest.approx(30, abs=3)


def test_drr_single_tenant_reduces_to_legacy_order():
    legacy = Coalescer(max_batch_programs=3, max_wait_s=0.0)
    fair = Coalescer(max_batch_programs=3, max_wait_s=0.0,
                     tenant_weights={})
    key = ('b',)
    reqs_a = [_req(s, priority=s % 2) for s in range(7)]
    reqs_b = [_req(s, priority=s % 2) for s in range(7)]
    for ra, rb in zip(reqs_a, reqs_b):
        legacy.push(key, ra)
        fair.push(key, rb)
    while len(legacy):
        _, ba, _ = legacy.pop_batch(flush=True)
        _, bb, _ = fair.pop_batch(flush=True)
        assert [r.seq for r in ba] == [r.seq for r in bb]
    assert len(fair) == 0


def test_drr_priority_order_preserved_within_tenant():
    q = Coalescer(max_batch_programs=2, max_wait_s=0.0,
                  tenant_weights={'a': 1.0})
    key = ('b',)
    q.push(key, _req(0, tenant='a', priority=0))
    q.push(key, _req(1, tenant='a', priority=5))
    _, batch, _ = q.pop_batch(flush=True)
    assert [r.seq for r in batch] == [1, 0]   # high priority first


# ---------------------------------------------------------------------------
# shed preference + exemption (Coalescer unit)
# ---------------------------------------------------------------------------


def test_shed_prefers_most_over_quota_tenants_newest():
    q = Coalescer(max_batch_programs=8, max_wait_s=10.0)
    key = ('b',)
    q.push(key, _req(0, tenant='calm'))
    q.push(key, _req(1, tenant='greedy'))
    q.push(key, _req(2, tenant='greedy'))
    got = q.shed_candidate(below_priority=1,
                           tenant_pressure={'greedy': 3.0, 'calm': 0.1})
    assert got is not None
    _, victim = got
    # the most-over-quota tenant's NEWEST request goes first
    assert victim.tenant == 'greedy' and victim.seq == 2


def test_shed_exempts_stream_chunks_and_internal_work():
    assert shed_exempt(_req(5, rounds=4))          # stream chunk
    assert shed_exempt(_req(5, sid=7))             # session-owned
    assert shed_exempt(_req(-1))                   # canary/audit work
    assert not shed_exempt(_req(5))
    q = Coalescer(max_batch_programs=8, max_wait_s=10.0)
    key = ('b',)
    q.push(key, _req(10, tenant='victim', rounds=4, sid=1))
    q.push(key, _req(-3, tenant='victim'))
    # only exempt work queued: nothing may be shed, no matter how much
    # admission pressure another tenant generates
    assert q.shed_candidate(
        below_priority=1, tenant_pressure={'victim': 99.0}) is None
    q.push(key, _req(11, tenant='victim'))
    got = q.shed_candidate(below_priority=1,
                           tenant_pressure={'victim': 99.0})
    assert got is not None and got[1].seq == 11


# ---------------------------------------------------------------------------
# quotas: typed, non-retryable, never shed another tenant's work
# ---------------------------------------------------------------------------


def test_quota_exceeded_is_typed_and_non_retryable():
    rng = np.random.default_rng(0)
    # quota errors are program-class: the retry machinery must
    # surface them, not burn attempts
    assert not is_infrastructure_error(QuotaExceededError('x'))
    assert not issubclass(QuotaExceededError, OverloadError)
    with ExecutionService(
            _CFG, max_batch_programs=8, max_wait_ms=1000.0,
            tenants={'capped': {'max_queued': 1}}) as svc:
        # the long latency dial keeps the first request queued while
        # the over-quota second one arrives
        h1 = svc.submit(_mp(), _bits(rng), tenant='capped')
        with pytest.raises(QuotaExceededError):
            svc.submit(_mp(), _bits(rng), tenant='capped')
        # other tenants are untouched by the capped tenant's limit
        h2 = svc.submit(_mp(), _bits(rng), tenant='other')
        st = svc.stats()
        assert st['tenants']['capped']['quota_rejected'] == 1
        assert st['tenants']['other']['quota_rejected'] == 0
        assert profiling.counter_get(
            'tenant.capped.quota_rejected') == 1
        h1.result(timeout=120)
        h2.result(timeout=120)


def test_shots_rate_limit_token_bucket():
    rng = np.random.default_rng(1)
    with ExecutionService(
            _CFG, max_batch_programs=4, max_wait_ms=2.0,
            tenants={'meter': {'shots_per_s': 1.0,
                               'shots_burst': 6.0}}) as svc:
        svc.warmup(_mp(), shots=6, n_programs=1)
        h = svc.submit(_mp(), _bits(rng, shots=6), tenant='meter')
        h.result(timeout=60)
        # the bucket is drained: the next submission must wait ~1s/shot
        with pytest.raises(QuotaExceededError):
            svc.submit(_mp(), _bits(rng, shots=6), tenant='meter')
        # other tenants have their own (unconfigured = unlimited) budget
        svc.submit(_mp(), _bits(rng, shots=6),
                   tenant='other').result(timeout=60)


def test_compile_submission_rate_limit():
    from distributed_processor_tpu.models import make_default_qchip
    qchip = make_default_qchip(2)
    prog = [{'name': 'X90', 'qubit': ['Q0']}]
    with ExecutionService(
            _CFG,
            tenants={'src': {'compiles_per_s': 0.001,
                             'compiles_burst': 1.0}}) as svc:
        h = svc.submit_source(prog, qchip, shots=3, n_qubits=2,
                              tenant='src')
        h.result(timeout=120)
        with pytest.raises(QuotaExceededError):
            svc.submit_source(prog, qchip, shots=3, n_qubits=2,
                              tenant='src')
        st = svc.stats()
        assert st['tenants']['src']['quota_rejected'] == 1
        assert st['tenants']['src']['compile_ms'] > 0.0


# ---------------------------------------------------------------------------
# metering: exact against ground truth
# ---------------------------------------------------------------------------


def test_usage_metering_matches_ground_truth():
    rng = np.random.default_rng(2)
    with ExecutionService(_CFG, max_batch_programs=4,
                          max_wait_ms=2.0) as svc:
        plan = [('acme', 3), ('acme', 5), ('bob', 2)]
        handles = [(t, svc.submit(_mp(), _bits(rng, shots=n), tenant=t))
                   for t, n in plan]
        for _t, h in handles:
            h.result(timeout=60)
        st = svc.stats()['tenants']
    assert st['acme']['submitted'] == 2
    assert st['acme']['completed'] == 2
    assert st['acme']['shots'] == 8          # exactly 3 + 5
    assert st['acme']['queued'] == 0
    assert st['acme']['device_ms'] > 0.0
    assert st['bob']['shots'] == 2
    assert profiling.counter_get('tenant.acme.shots') == 8
    assert profiling.counter_get('tenant.bob.shots') == 2


@pytest.mark.chaos
def test_metering_exactly_once_under_chaos_retries():
    """Scripted crashes force retries: the shots meter must equal the
    ground-truth total exactly — a crashed attempt's device time is
    not billed, and the retried completion bills exactly once."""
    rng = np.random.default_rng(3)
    plan = ChaosPlan(seed=7, script=('crash',) * 2)
    with ExecutionService(
            _CFG, max_batch_programs=4, max_wait_ms=2.0,
            retry_policy=RetryPolicy(max_attempts=6, backoff_s=0.005),
            supervise_interval_ms=10.0) as svc:
        svc.warmup(_mp(), shots=3, n_programs=1)
        with ChaosMonkey(svc, plan) as monkey:
            handles = [svc.submit(_mp(), _bits(rng), tenant='acme')
                       for _ in range(12)]
            for h in handles:
                h.result(timeout=120)
        assert monkey.script_exhausted()
        assert any(h.retries >= 1 for h in handles)
        st = svc.stats()['tenants']['acme']
    assert st['submitted'] == 12
    assert st['completed'] == 12
    assert st['failed'] == 0
    assert st['queued'] == 0
    assert st['shots'] == 12 * 3    # exactly once despite retries


# ---------------------------------------------------------------------------
# streams: tenant inheritance + unsheddable chunks
# ---------------------------------------------------------------------------


@pytest.mark.qec
def test_stream_chunks_inherit_session_tenant():
    from distributed_processor_tpu.models.qec import (
        qec_config, qec_multiround_machine_program)
    rng = np.random.default_rng(4)
    mp = qec_multiround_machine_program(n_data=3, rounds=1)
    cfg = qec_config(3, record_pulses=False)
    with ExecutionService() as svc:
        with svc.open_stream(mp, cfg=cfg, tenant='qec-lab') as sess:
            assert sess.tenant == 'qec-lab'
            sess.submit_rounds(rng.integers(
                0, 2, (4, 3, mp.n_cores, cfg.max_meas)).astype(np.int32))
            list(sess.results(timeout=60))
        st = svc.stats()['tenants']
    assert st['qec-lab']['completed'] == 1
    # shot-rounds are the billed unit: rounds * n_shots
    assert st['qec-lab']['shots'] == 4 * 3


# ---------------------------------------------------------------------------
# wire: tenant carriage + bytes metering (in-process replica)
# ---------------------------------------------------------------------------


@pytest.mark.fleet
def test_wire_carries_tenant_and_meters_bytes():
    rng = np.random.default_rng(5)
    svc = ExecutionService(_CFG, max_batch_programs=2, max_wait_ms=1.0)
    srv = ReplicaServer(svc)
    client = None
    try:
        client = ReplicaClient(srv.address)
        payload = dict(mp=_mp(), meas_bits=_bits(rng), shots=None,
                       init_regs=None, cfg=_CFG, priority=0,
                       deadline_ms=None, fault_mode=None,
                       tenant='wire-acme')
        client.call('submit', payload, timeout_s=120.0)
        st = svc.stats()['tenants']['wire-acme']
        assert st['completed'] == 1
        # request frame + response frame both billed, headers included
        assert st['bytes_wire'] > 0
        assert profiling.counter_get(
            'tenant.wire-acme.bytes_wire') == st['bytes_wire']
    finally:
        if client is not None:
            client.close()
        srv.close()
        svc.shutdown()


# ---------------------------------------------------------------------------
# autoscale policy: hysteresis, cooldown, bounds (pure unit)
# ---------------------------------------------------------------------------


def test_autoscale_requires_sustained_breach():
    p = AutoscalePolicy(min_replicas=1, max_replicas=4,
                        breach_sustain_s=1.0, slack_sustain_s=5.0,
                        cooldown_s=2.0)
    assert p.decide(True, 2, 0.0) is None      # breach just started
    assert p.decide(True, 2, 0.5) is None      # not sustained yet
    assert p.decide(False, 2, 0.6) is None     # blip resets the window
    assert p.decide(True, 2, 0.7) is None
    assert p.decide(True, 2, 1.6) is None      # window restarted at 0.7
    assert p.decide(True, 2, 1.8) == 'up'      # sustained 0.7 -> 1.8


def test_autoscale_cooldown_and_slack_hysteresis():
    p = AutoscalePolicy(min_replicas=1, max_replicas=4,
                        breach_sustain_s=0.5, slack_sustain_s=1.0,
                        cooldown_s=10.0)
    assert p.decide(True, 1, 0.0) is None
    assert p.decide(True, 1, 0.6) == 'up'
    # immediately-following slack may NOT undo the scale-up: both the
    # slack-sustain window and the cooldown must elapse
    assert p.decide(False, 2, 0.7) is None
    assert p.decide(False, 2, 2.0) is None     # slack sustained, cooling
    assert p.decide(False, 2, 10.7) == 'down'  # cooldown finally up
    # and the down cannot immediately flap back up: breach must
    # re-sustain AND the fresh cooldown must elapse
    assert p.decide(True, 1, 10.8) is None
    assert p.decide(True, 1, 11.5) is None     # sustained, still cooling
    assert p.decide(True, 1, 20.8) == 'up'


def test_autoscale_respects_population_bounds():
    p = AutoscalePolicy(min_replicas=2, max_replicas=3,
                        breach_sustain_s=0.0, slack_sustain_s=0.0,
                        cooldown_s=0.0)
    assert p.decide(True, 3, 1.0) is None      # at max: no up
    assert p.decide(False, 2, 2.0) is None     # at min: no down
    assert p.decide(True, 2, 3.0) == 'up'
    assert p.decide(False, 3, 4.0) == 'down'
    with pytest.raises(ValueError):
        AutoscalePolicy(min_replicas=0)
    with pytest.raises(ValueError):
        AutoscalePolicy(min_replicas=3, max_replicas=2)


def test_router_per_tenant_slo_budget_breaches():
    from distributed_processor_tpu.serve.router import FleetRouter
    router = FleetRouter(
        slo_budgets={'tenant:acme': {'p99_ms': 1.0}},
        slo_min_samples=4)
    try:
        assert not router.slo_breached()
        for _ in range(8):
            router._observe_stage('tenant:acme', 50.0)
        router._check_slo()
        assert router.slo_breached()
        st = router.stats()
        assert st['slo']['tenant:acme']['breached']
        assert st['slo_breaches'] == 1
        kinds = [e['kind']
                 for e in router.flight_recorder.events()]
        assert kinds.count('slo_breach') == 1    # edge-triggered
        router._check_slo()
        assert [e['kind'] for e in router.flight_recorder.events()
                ].count('slo_breach') == 1
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# adversarial isolation: greedy vs victim through the live service
# ---------------------------------------------------------------------------


def test_greedy_tenant_cannot_starve_or_shed_victim():
    """A greedy tenant floods admission while a victim trickles: with
    weights + quotas on, every victim request completes, none are
    shed, and the greedy tenant's overflow is rejected against ITS
    OWN quota (typed), never absorbed as victim pain."""
    rng = np.random.default_rng(6)
    # The shots token bucket (36-shot burst, negligible refill) caps the
    # greedy flood deterministically: at 3 shots/request exactly 12 of
    # the 40 submissions are admitted no matter how fast the executor
    # drains the queue, so the rejection assertions below cannot race
    # against warm jit caches.
    with ExecutionService(
            _CFG, max_batch_programs=4, max_wait_ms=2.0,
            max_queue=64,
            tenants={'greedy': {'weight': 1.0, 'max_queued': 16,
                                'shots_per_s': 0.001,
                                'shots_burst': 36.0},
                     'victim': {'weight': 4.0}}) as svc:
        svc.warmup(_mp(), shots=3, n_programs=4)
        greedy_handles, greedy_rejects = [], 0
        for _ in range(40):
            try:
                greedy_handles.append(
                    svc.submit(_mp(), _bits(rng), tenant='greedy'))
            except QuotaExceededError:
                greedy_rejects += 1
        victim_handles = [svc.submit(_mp(), _bits(rng), tenant='victim')
                          for _ in range(4)]
        for h in victim_handles:
            h.result(timeout=120)      # completes, not shed, typed-free
        for h in greedy_handles:
            try:
                h.result(timeout=120)
            except OverloadError:
                pass                   # greedy may be shed; victim never
        st = svc.stats()['tenants']
        assert greedy_rejects >= 28    # the cap actually bit (bucket
        assert len(greedy_handles) <= 12   # covers 12 admits at most)
        assert st['victim']['completed'] == 4
        assert st['victim']['shed'] == 0
        assert st['victim']['quota_rejected'] == 0
        assert st['greedy']['quota_rejected'] == greedy_rejects
        assert st['victim']['shots'] == 4 * 3
