"""Emitted straight-line executor vs the generic fetch-dispatch engine.

Round-5 exec lever (b) (docs/PERF.md "the measured overhead budget"):
forward-jump-only programs unroll at trace time into per-instruction
specialized step bodies — no program fetch, no opcode dispatch, no
while-loop carry.  The contract is EXACT equality with the generic
engine on every output (bits, records, timing, error bits, device
co-state), pinned here on the bench-shaped program through the
injected-bits path and the physics-closed path on both 1q devices.
"""

import numpy as np
import pytest

from bench import build_machine_program
from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import machine_program_from_cmds
from distributed_processor_tpu.sim.interpreter import (
    InterpreterConfig, simulate_batch, straightline_ineligible,
    use_straightline)


@pytest.fixture(scope='module')
def bench_mp():
    return build_machine_program(4, 3)


def _cfg(mp, **kw):
    return InterpreterConfig(
        max_steps=2 * mp.n_instr + 64,
        max_pulses=int(mp.max_pulses_per_core(1)) + 4,
        max_meas=2, max_resets=2, **kw)


def test_bench_program_is_eligible(bench_mp):
    assert straightline_ineligible(bench_mp, _cfg(bench_mp)) is None
    # default is the generic engine (compile-amortization: the jit
    # cache keys on program content in straight-line mode); None = auto
    assert not use_straightline(bench_mp, _cfg(bench_mp))
    assert use_straightline(bench_mp, _cfg(bench_mp, straightline=None))


def test_injected_bits_equality(bench_mp):
    """Every output key identical (records, regs, qclk, err, meas
    bookkeeping) on the active-reset + RB program with random bits."""
    mp = bench_mp
    rng = np.random.default_rng(0)
    bits = rng.integers(0, 2, size=(64, mp.n_cores, 2))
    gen = simulate_batch(mp, bits, cfg=_cfg(mp, straightline=False))
    sl = simulate_batch(mp, bits, cfg=_cfg(mp, straightline=True))
    assert set(gen) == set(sl)
    for k in gen:
        if k == 'steps':     # counts engine iterations, not semantics
            continue
        np.testing.assert_array_equal(np.asarray(gen[k]),
                                      np.asarray(sl[k]), err_msg=k)


def test_injected_bits_equality_physics_cfg(bench_mp):
    """Engine-independent output SCHEMA under a physics cfg on the
    injected-bits path: the generic engine used to leak its internal
    ``phys_wait`` stall carry where the straight-line executor popped
    it, so the key set depended on which engine ran.  Values must match
    too — with every bit injected valid no lane ever stalls, so the
    physics co-state evolves identically."""
    mp = bench_mp
    rng = np.random.default_rng(2)
    bits = rng.integers(0, 2, size=(16, mp.n_cores, 2))
    outs = {}
    for slf in (False, True):
        outs[slf] = simulate_batch(
            mp, bits, cfg=_cfg(mp, physics=True, straightline=slf))
    assert set(outs[False]) == set(outs[True])
    assert 'phys_wait' not in outs[False]
    assert 'paused' not in outs[False]
    for k in outs[False]:
        if k == 'steps':
            continue
        np.testing.assert_array_equal(np.asarray(outs[False][k]),
                                      np.asarray(outs[True][k]),
                                      err_msg=k)


_PHYSICS_EQ_BODY = '''
import numpy as np
import jax
jax.config.update('jax_platforms', 'cpu')
from bench import build_machine_program
from distributed_processor_tpu.sim.device import DeviceModel
from distributed_processor_tpu.sim.interpreter import InterpreterConfig
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)
mp = build_machine_program(4, 3)
for devkind in ('parity', 'bloch'):
    dev = DeviceModel(devkind,
                      detuning_hz=0.3e6 if devkind == 'bloch' else 0.0,
                      t1_s=50e-6 if devkind == 'bloch' else float('inf'))
    model = ReadoutPhysics(sigma=0.05, p1_init=0.2, device=dev)
    outs = {}
    for slf in (False, True):
        outs[slf] = run_physics_batch(
            mp, model, 5, 128,
            cfg=InterpreterConfig(
                max_steps=2 * mp.n_instr + 64,
                max_pulses=int(mp.max_pulses_per_core(1)) + 4,
                max_meas=2, max_resets=2, straightline=slf))
        assert not bool(outs[slf]['incomplete'])
    for k in outs[False]:
        if k == 'steps':
            continue
        np.testing.assert_array_equal(
            np.asarray(outs[False][k]), np.asarray(outs[True][k]),
            err_msg=devkind + ':' + k)
print('EQUAL')
'''


def test_physics_closed_equality_subprocess():
    """Physics-closed epoch loop: the straight-line pass pauses lanes
    at unresolved readouts and resumes exactly like the generic engine
    — meas_bits, device co-state, and error bits all bit-identical on
    both 1q devices.

    Runs in a fresh subprocess: the unrolled physics module is the
    largest single CPU compile in the suite, and XLA has been seen
    segfaulting on it inside the long-lived full-suite process (heap
    state after ~350 tests) while compiling it cleanly in a fresh one.
    """
    import os
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([sys.executable, '-c', _PHYSICS_EQ_BODY], env=env,
                       cwd=repo, capture_output=True, text=True,
                       timeout=900)
    assert r.returncode == 0 and 'EQUAL' in r.stdout, \
        (r.returncode, r.stderr[-2000:])


def test_packed_ctrl_equivalent(bench_mp):
    """The packed [K, B, C] control carry (round-5 lever (a), measured
    negative but kept as an exact knob) produces identical outputs."""
    mp = bench_mp
    rng = np.random.default_rng(1)
    bits = rng.integers(0, 2, size=(32, mp.n_cores, 2))
    a = simulate_batch(mp, bits, cfg=_cfg(mp))
    b = simulate_batch(mp, bits, cfg=_cfg(mp, packed_ctrl=True))
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=k)


def test_loop_program_falls_back():
    """A backward jump (on-device loop) is ineligible: auto mode runs
    the generic engine, straightline=True raises with the reason."""
    mp = machine_program_from_cmds([[
        isa.pulse_cmd(cmd_time=100, cfg_word=0, env_word=4096),
        isa.alu_cmd('reg_alu', 'i', 1, 'add', alu_in1=0,
                    write_reg_addr=0),
        isa.alu_cmd('jump_cond', 'i', 3, 'ge', alu_in1=0,
                    jump_cmd_ptr=0),
        isa.done_cmd(),
    ]])
    cfg = InterpreterConfig(max_steps=128, max_pulses=8, max_meas=2)
    assert 'backward jump' in straightline_ineligible(mp, cfg)
    assert not use_straightline(mp, cfg)
    out = simulate_batch(mp, np.zeros((4, 1, 2), int), cfg=cfg)
    assert not bool(out['incomplete'])
    with pytest.raises(ValueError, match='backward jump'):
        simulate_batch(mp, np.zeros((4, 1, 2), int),
                       cfg=InterpreterConfig(max_steps=128, max_pulses=8,
                                             max_meas=2,
                                             straightline=True))


@pytest.mark.parametrize('seed', range(8))
def test_random_forward_programs_engine_equality(seed):
    """Adversarial pin on the duplicated instruction semantics: random
    forward-only programs (pulses with jittered trigger times incl.
    deliberate misses, pulse_write/pulse_reset/idle, REG_ALU chains,
    forward jump_i/jump_cond, INC_QCLK rewinds, self sticky fproc
    reads, measurement pulses) must produce IDENTICAL outputs — state,
    records, timing, error bits — on both engines with random injected
    bits."""
    rng = np.random.default_rng(100 + seed)
    C = 2
    cores = []
    for c in range(C):
        n_body = int(rng.integers(8, 14))
        cmds = []
        t = 20
        for i in range(n_body):
            kind = rng.choice(['pt', 'pw', 'alu', 'jc', 'ji', 'idle',
                               'rst', 'fproc', 'incq'],
                              p=[.3, .1, .15, .1, .05, .1, .05, .1, .05])
            if kind == 'pt':
                # occasionally schedule in the past: both engines must
                # flag ERR_MISSED_TRIG identically
                t += int(rng.integers(-5, 60))
                cmds.append(isa.pulse_cmd(
                    cmd_time=max(t, 0), cfg_word=int(rng.integers(0, 3)),
                    env_word=int(rng.integers(0, 1 << 14)),
                    amp_word=int(rng.integers(0, 1 << 16)),
                    phase_word=int(rng.integers(0, 1 << 17)),
                    freq_word=int(rng.integers(0, 4))))
            elif kind == 'pw':
                cmds.append(isa.pulse_cmd(
                    amp_word=int(rng.integers(0, 1 << 16)),
                    phase_word=int(rng.integers(0, 1 << 17))))
            elif kind == 'alu':
                cmds.append(isa.alu_cmd(
                    'reg_alu', rng.choice(['i', 'r']),
                    int(rng.integers(-50, 50)),
                    rng.choice(['add', 'sub', 'eq', 'le', 'ge']),
                    alu_in1=int(rng.integers(0, 4)),
                    write_reg_addr=int(rng.integers(0, 4))))
            elif kind == 'jc':
                # forward target within the eventual body (clipped when
                # the program is assembled below)
                cmds.append(('jc', int(rng.integers(-20, 20)),
                             rng.choice(['eq', 'le', 'ge'])))
            elif kind == 'ji':
                cmds.append(('ji',))
            elif kind == 'idle':
                t += int(rng.integers(0, 80))
                cmds.append(isa.idle(t))
            elif kind == 'rst':
                cmds.append(isa.pulse_reset())
            elif kind == 'fproc':
                cmds.append(('fproc', int(rng.integers(0, 2))))
            else:
                cmds.append(isa.alu_cmd('inc_qclk', 'i',
                                        int(rng.integers(-30, 30)),
                                        'add'))
        # resolve placeholder jumps now that the length is known: every
        # target strictly forward, landing inside the body or on DONE
        n = len(cmds) + 1                      # + trailing DONE
        out = []
        for i, cmd in enumerate(cmds):
            if isinstance(cmd, tuple) and cmd[0] == 'jc':
                tgt = int(rng.integers(i + 1, n))
                out.append(isa.alu_cmd('jump_cond', 'i', cmd[1], cmd[2],
                                       alu_in1=int(rng.integers(0, 4)),
                                       jump_cmd_ptr=tgt))
            elif isinstance(cmd, tuple) and cmd[0] == 'ji':
                tgt = int(rng.integers(i + 1, n))
                out.append(isa.jump_i(tgt))
            elif isinstance(cmd, tuple) and cmd[0] == 'fproc':
                tgt = int(rng.integers(i + 1, n))
                op = 'jump_fproc' if cmd[1] else 'alu_fproc'
                out.append(isa.alu_cmd(
                    op, 'i', int(rng.integers(0, 2)), 'eq',
                    write_reg_addr=int(rng.integers(0, 4)),
                    jump_cmd_ptr=tgt, func_id=c))
            else:
                out.append(cmd)
        out.append(isa.done_cmd())
        cores.append(out)
    mp = machine_program_from_cmds(cores)
    cfg_kw = dict(max_steps=256, max_pulses=32, max_meas=8, max_resets=8)
    assert straightline_ineligible(
        mp, InterpreterConfig(**cfg_kw)) is None, 'generator bug'
    bits = rng.integers(0, 2, size=(16, C, 8))
    gen = simulate_batch(mp, bits,
                         cfg=InterpreterConfig(straightline=False,
                                               **cfg_kw))
    sl = simulate_batch(mp, bits,
                        cfg=InterpreterConfig(straightline=True,
                                              **cfg_kw))
    assert set(gen) == set(sl)
    for k in gen:
        if k == 'steps':
            continue
        np.testing.assert_array_equal(np.asarray(gen[k]),
                                      np.asarray(sl[k]),
                                      err_msg=f'seed {seed}: {k}')


def test_sticky_race_and_missed_trigger_flags_match(bench_mp):
    """Error-bit semantics survive specialization: a deliberately
    mis-scheduled program (trigger in the past after an idle) flags
    ERR_MISSED_TRIG identically on both engines."""
    from distributed_processor_tpu.sim.interpreter import ERR_MISSED_TRIG
    mp = machine_program_from_cmds([[
        isa.idle(500),
        isa.pulse_cmd(cmd_time=100, cfg_word=0, env_word=4096),
        isa.done_cmd(),
    ]])
    cfg = dict(max_steps=64, max_pulses=8, max_meas=2)
    for slf in (False, True):
        out = simulate_batch(mp, np.zeros((4, 1, 2), int),
                             cfg=InterpreterConfig(straightline=slf,
                                                   **cfg))
        assert np.all(np.asarray(out['err']) & ERR_MISSED_TRIG), slf
