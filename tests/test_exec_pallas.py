"""Pallas megastep exec engine vs the generic fetch-dispatch engine.

The fourth engine-ladder rung (docs/PERF.md "Engine ladder",
``engine='pallas'``): a whole straight-line span — a forward-jump-only
program, or one superinstruction body inside the block engine's outer
loop — runs as ONE kernel call with the per-shot carry resident in
VMEM.  The contract is EXACT equality with the generic engine on every
output (bits, records, timing, fault word, device-free stats) — pinned
here on the golden suite, under vmap, under a dp-sharded mesh, and on
the fault-injection corpus's timing-independent codes.

Every test here runs on CPU through the kernel interpreter
(``pallas_interpret`` resolves to True off-TPU) — tools/check_junit.py
fails the suite if any of these testcases SKIPS, so the rung cannot
silently stop being exercised.
"""

import numpy as np
import pytest

import jax

from bench import build_machine_program
from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import (machine_program_from_cmds,
                                               stack_machine_programs)
from distributed_processor_tpu.models.default_qchip import make_default_qchip
from distributed_processor_tpu.models.golden_suite import GOLDEN_PROGRAMS
from distributed_processor_tpu.parallel import make_mesh, sharded_simulate
from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.serve import ExecutionService
from distributed_processor_tpu.sim import faultinject as fi
from distributed_processor_tpu.sim import interpreter as interp_mod
from distributed_processor_tpu.sim.interpreter import (
    InterpreterConfig, _pallas_mode, _program_constants, _run_batch_engine,
    _soa_static, pallas_ineligible, pallas_trace_count, program_traits,
    resolve_engine, simulate_batch, simulate_multi_batch)

pytestmark = pytest.mark.pallas


@pytest.fixture(scope='module')
def bench_mp():
    return build_machine_program(4, 3)


def _cfg(mp, **kw):
    return InterpreterConfig(
        max_steps=2 * mp.n_instr + 64,
        max_pulses=int(mp.max_pulses_per_core(1)) + 4,
        max_meas=2, max_resets=2, **kw)


def _assert_equal_outputs(a, b, skip=('steps',), msg=''):
    assert set(a) == set(b), msg
    for k in a:
        if k in skip:
            continue
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=f'{msg}{k}')


def _span_mp():
    """Forward-jump-only program: runs WHOLE as one span kernel."""
    return machine_program_from_cmds([[
        isa.pulse_cmd(amp_word=1000, cfg_word=0, env_word=(8 << 12) | 3,
                      cmd_time=10),
        isa.alu_cmd('reg_alu', 'i', 5, 'add', alu_in1=1,
                    write_reg_addr=1),
        isa.pulse_cmd(amp_word=2000, cfg_word=2, env_word=(4 << 12) | 1,
                      cmd_time=40),
        isa.done_cmd(),
    ]])


def _loop_mp():
    """Counted backward loop: straightline-ineligible, block-eligible —
    pallas rides the block outer loop with kernel bodies."""
    return machine_program_from_cmds([[
        isa.pulse_cmd(cmd_time=100, cfg_word=0, env_word=4096),
        isa.alu_cmd('reg_alu', 'i', 1, 'add', alu_in1=0,
                    write_reg_addr=0),
        isa.alu_cmd('jump_cond', 'i', 3, 'ge', alu_in1=0,
                    jump_cmd_ptr=0),
        isa.done_cmd(),
    ]])


# ---------------------------------------------------------------------------
# golden suite bit-identity (per stat, fault word included)
# ---------------------------------------------------------------------------

# see tests/test_blocks.py: the frontend-loop goldens are
# non-terminating by construction, and truncated runs legitimately
# diverge between engines (instruction- vs block-granular cutoff)
_NONTERMINATING_GOLDENS = frozenset({'simple_loop', 'nested_loop'})


@pytest.mark.parametrize('name', sorted(GOLDEN_PROGRAMS))
def test_golden_suite_pallas_equality(name):
    """Every terminating golden program runs bit-identically on the
    pallas engine — every output key, the fault word included."""
    if name in _NONTERMINATING_GOLDENS:
        return
    n_qubits, thunk = GOLDEN_PROGRAMS[name]
    qchip = make_default_qchip(max(n_qubits, 2))
    mp = compile_to_machine(thunk(), qchip, n_qubits=n_qubits)
    cfg_kw = dict(mp.static_bounds(), max_meas=16, max_resets=64)
    rng = np.random.default_rng(17)
    bits = rng.integers(0, 2, size=(8, mp.n_cores, 16))
    gen = simulate_batch(mp, bits,
                         cfg=InterpreterConfig(engine='generic', **cfg_kw))
    assert not bool(gen['incomplete']), name
    pal = simulate_batch(mp, bits, cfg=InterpreterConfig(
        engine='pallas', pallas_interpret=True, **cfg_kw))
    _assert_equal_outputs(gen, pal, msg=f'{name}: ')


def test_fault_word_identity_span():
    """A span-mode program that overflows its pulse budget traps the
    same fault word per shot on both engines (bit-identity includes
    the fault machinery, not just the happy path)."""
    mp = _span_mp()
    kw = dict(max_steps=2 * mp.n_instr + 64, max_pulses=1, max_meas=2,
              max_resets=2)
    bits = np.zeros((4, mp.n_cores, 2), np.int32)
    gen = simulate_batch(mp, bits,
                         cfg=InterpreterConfig(engine='generic', **kw))
    assert np.any(np.asarray(gen['fault'])), 'fixture must actually trap'
    pal = simulate_batch(mp, bits, cfg=InterpreterConfig(
        engine='pallas', pallas_interpret=True, **kw))
    _assert_equal_outputs(gen, pal)


# ---------------------------------------------------------------------------
# mode selection + ladder resolution + eligibility
# ---------------------------------------------------------------------------

def test_pallas_mode_selection():
    cfg = InterpreterConfig(max_steps=128, max_pulses=8, max_meas=2)
    assert _pallas_mode(_soa_static(_span_mp()), cfg) == 'span'
    assert _pallas_mode(_soa_static(_loop_mp()), cfg) == 'block'


def test_forced_pallas_on_ineligible_raises():
    mp = _span_mp()
    base = dict(max_steps=128, max_pulses=8, max_meas=2)
    for bad in (dict(trace=True), dict(physics=True, device='parity')):
        cfg = InterpreterConfig(engine='pallas', **base, **bad)
        assert pallas_ineligible(mp, cfg)
        with pytest.raises(ValueError, match='ineligible'):
            resolve_engine(mp, cfg)
        if 'physics' not in bad:    # physics has its own entry guard
            with pytest.raises(ValueError, match='ineligible'):
                simulate_batch(mp, np.zeros((2, 1, 2), int), cfg=cfg)


def test_auto_prefers_pallas_on_listed_backends(monkeypatch, bench_mp):
    base = dict(max_steps=128, max_pulses=8, max_meas=2)
    span, loop = _span_mp(), _loop_mp()
    # this host's backend is not in the default allow-list -> XLA rungs
    assert jax.default_backend() not in interp_mod._PALLAS_AUTO_BACKENDS
    assert resolve_engine(
        span, InterpreterConfig(engine='auto', **base)) == 'straightline'
    assert resolve_engine(
        loop, InterpreterConfig(engine='auto', **base)) == 'block'
    # with the backend allow-listed, auto prefers pallas on BOTH shapes
    monkeypatch.setattr(interp_mod, '_PALLAS_AUTO_BACKENDS',
                        interp_mod._PALLAS_AUTO_BACKENDS
                        + (jax.default_backend(),))
    assert resolve_engine(
        span, InterpreterConfig(engine='auto', **base)) == 'pallas'
    assert resolve_engine(
        loop, InterpreterConfig(engine='auto', **base)) == 'pallas'
    # the size caps still apply: past them auto falls down the ladder
    monkeypatch.setattr(interp_mod, 'SL_AUTO_MAX_INSTR', 2)
    monkeypatch.setattr(interp_mod, 'BLOCK_AUTO_MAX_UNROLL', 1)
    assert resolve_engine(
        span, InterpreterConfig(engine='auto', **base)) != 'pallas'
    assert resolve_engine(
        loop, InterpreterConfig(engine='auto', **base)) != 'pallas'
    # forcing stays available regardless of backend allow-listing
    assert resolve_engine(
        bench_mp, _cfg(bench_mp, engine='pallas')) == 'pallas'


def test_multi_batch_rejects_pallas():
    mp = _span_mp()
    mmp = stack_machine_programs([mp, mp])
    bits = np.zeros((2, 4, mp.n_cores, 2), np.int32)
    with pytest.raises(ValueError, match='pallas'):
        simulate_multi_batch(mmp, bits, cfg=InterpreterConfig(
            max_steps=128, max_pulses=8, max_meas=2, engine='pallas'))


# ---------------------------------------------------------------------------
# composition: vmap, mesh, retrace budget
# ---------------------------------------------------------------------------

def test_pallas_engine_under_vmap(bench_mp):
    """The megastep executor is a plain JAX program: vmapping it over a
    leading group axis matches the vmapped generic engine exactly."""
    mp = bench_mp
    cfg = _cfg(mp, pallas_interpret=True)
    soa, spc, interp, sync_part = _program_constants(mp, cfg)
    prog = _soa_static(mp)
    traits = program_traits(mp)
    rng = np.random.default_rng(7)
    bits = np.asarray(
        rng.integers(0, 2, size=(3, 8, mp.n_cores, 2)), np.int32)

    def pal(mb):
        return _run_batch_engine(None, spc, interp, sync_part, mb, cfg,
                                 mp.n_cores, engine='pallas', prog=prog)

    def gen(mb):
        return _run_batch_engine(soa, spc, interp, sync_part, mb, cfg,
                                 mp.n_cores, engine='generic',
                                 traits=traits)

    p = jax.jit(jax.vmap(pal))(bits)
    g = jax.jit(jax.vmap(gen))(bits)
    _assert_equal_outputs(g, p, msg='vmap: ')


def test_sharded_pallas_matches_local_generic(bench_mp):
    """dp=2 mesh: the pallas engine inside shard_map produces the same
    per-shot outputs as a local generic run."""
    mp = bench_mp
    rng = np.random.default_rng(11)
    bits = rng.integers(0, 2, size=(16, mp.n_cores, 2))
    mesh = make_mesh(n_dp=2)
    sharded = sharded_simulate(mp, bits, mesh,
                               cfg=_cfg(mp, engine='pallas',
                                        pallas_interpret=True))
    local = simulate_batch(mp, bits, cfg=_cfg(mp, engine='generic'))
    for k in sharded:   # sharded_simulate drops the scalar diagnostics
        np.testing.assert_array_equal(np.asarray(sharded[k]),
                                      np.asarray(local[k]), err_msg=k)


def test_pallas_retrace_budget():
    """Content-keyed jit: one trace per program content, zero on the
    identical repeat call."""
    mp = _span_mp()
    kw = dict(max_steps=2 * mp.n_instr + 64, max_pulses=8, max_meas=2,
              max_resets=2)
    cfg = InterpreterConfig(engine='pallas', pallas_interpret=True, **kw)
    bits = np.zeros((4, mp.n_cores, 2), np.int32)
    n0 = pallas_trace_count()
    out = simulate_batch(mp, bits, cfg=cfg)
    n1 = pallas_trace_count()
    assert n1 - n0 <= 1, 'more than one pallas trace for one program'
    out2 = simulate_batch(mp, bits, cfg=cfg)
    assert pallas_trace_count() == n1, 'retrace on an identical call'
    _assert_equal_outputs(out, out2, skip=())


# ---------------------------------------------------------------------------
# fault-injection corpus cross-check (timing-independent codes)
# ---------------------------------------------------------------------------

def test_faultfuzz_generic_vs_pallas():
    """The fuzzed mutant corpus judges generic and pallas together:
    cross-engine agreement on the timing-independent fault codes, and
    no silent or mistrapped mutants on either engine (pallas-ineligible
    mutant shapes fall back per the harness contract)."""
    rep = fi.run_fuzz(seed=0, n=8, engines=('generic', 'pallas'))
    assert rep.n == 8
    assert rep.ok, rep.failures


# ---------------------------------------------------------------------------
# serving integration: singleton dispatch + per-engine stats
# ---------------------------------------------------------------------------

def test_serve_singleton_pallas_and_engine_stats():
    mp = _span_mp()
    kw = dict(max_steps=2 * mp.n_instr + 64, max_pulses=8, max_meas=2,
              max_resets=2)
    cfg = InterpreterConfig(**kw)
    rng = np.random.default_rng(5)
    bits = rng.integers(0, 2, size=(4, mp.n_cores, 2)).astype(np.int32)
    with ExecutionService(max_batch_programs=1, max_wait_ms=1.0,
                          singleton_engine='pallas') as svc:
        got = svc.submit(mp, bits, cfg=cfg).result(timeout=300)
        stats = svc.stats()
    assert stats['engine_dispatches'] == {'pallas': 1}
    want = jax.tree.map(np.asarray,
                        simulate_batch(mp, bits, cfg=cfg))
    _assert_equal_outputs(got, want, msg='serve: ')


def test_serve_rejections_name_full_ladder():
    mp = _span_mp()
    # submitting a content-keyed engine is rejected with the ladder
    with ExecutionService(max_wait_ms=1.0) as svc:
        with pytest.raises(ValueError, match='pallas'):
            svc.submit(mp, shots=2, cfg=InterpreterConfig(
                max_steps=64, max_meas=2, engine='pallas'))
        h = svc.submit(mp, shots=2, cfg=InterpreterConfig(
            max_steps=64, max_pulses=8, max_meas=2, max_resets=2))
        h.result(timeout=300)
        stats = svc.stats()
    # the multi path books its dispatches as generic
    assert stats['engine_dispatches'] == {'generic': 1}
    # an unknown singleton engine fails construction, naming the ladder
    with pytest.raises(ValueError, match='pallas'):
        ExecutionService(singleton_engine='warp')
