"""Compiler pipeline tests.

Two families:

* semantic unit tests (z-phase accumulation, schedule start times, core
  grouping) with expectations derived from the timing model;
* golden-parity tests: compiled per-core asm compared against the
  reference implementation's expected outputs (parsed from
  /root/reference/python/test/test_outputs/*.txt as data oracles).
"""

import ast
import os

import numpy as np
import pytest

import distributed_processor_tpu as dp
from distributed_processor_tpu import compiler as cm
from distributed_processor_tpu.ir import passes as ps
from distributed_processor_tpu.ir import instructions as iri

from conftest import assert_close_tree

FAST_CLOCKS = {'alu_instr_clks': 2, 'fpga_clk_period': 2.e-9,
               'jump_cond_clks': 3, 'jump_fproc_clks': 4,
               'pulse_regwrite_clks': 1}


class MockElement(dp.hwconfig.ElementConfig):
    """Hardware-independent element (constant words) for golden tests,
    mirroring the reference test mock (python/test/test_compiler.py:18-47)."""

    def __init__(self, samples_per_clk, interp_ratio):
        super().__init__(2.e-9, samples_per_clk)

    def get_phase_word(self, phase):
        return 0

    def get_env_word(self, env_start_ind, env_length):
        return 0

    def get_cw_env_word(self, env_start_ind):
        return 0

    def get_env_buffer(self, env):
        return np.zeros(10)

    def get_freq_buffer(self, freqs):
        return np.zeros(10)

    def get_freq_addr(self, freq_ind):
        return 0

    def get_amp_word(self, amplitude):
        return 0

    def length_nclks(self, tlength):
        return int(np.ceil(tlength / self.fpga_clk_period))

    def get_cfg_word(self, elem_ind, mode_bits):
        return elem_ind


def load_golden(reference_root, name):
    """Parse a reference golden file (python-literal dict, possibly with
    numpy array reprs) into plain python structures."""
    path = os.path.join(reference_root, 'python/test/test_outputs', name)
    with open(path) as f:
        text = f.read().rstrip('\n')
    try:
        return ast.literal_eval(text)
    except (ValueError, SyntaxError):
        return eval(text, {'__builtins__': {}},
                    {'array': lambda x, dtype=None: list(x),
                     'float32': 'float32', 'dtype': lambda x: x})


def compile_program(program, qchip, fpga_config=None):
    if fpga_config is None:
        fpga_config = dp.FPGAConfig(**FAST_CLOCKS)
    elif isinstance(fpga_config, dict):
        fpga_config = dp.FPGAConfig(**fpga_config)
    compiler = dp.Compiler(program)
    compiler.run_ir_passes(cm.get_passes(fpga_config, qchip))
    return compiler


@pytest.fixture(scope='module')
def qchip(qchipcfg_path):
    return dp.QChip(qchipcfg_path)


def sorted_prog_dict(prog):
    """Reference-golden shape: dict keyed by sorted proc-group tuples."""
    return {key: prog.program[key] for key in sorted(prog.program.keys())}


def test_phase_resolve(qchip):
    program = [{'name': 'X90', 'qubit': ['Q0']},
               {'name': 'X90', 'qubit': ['Q1']},
               {'name': 'X90Z90', 'qubit': ['Q0']},
               {'name': 'X90', 'qubit': ['Q0']},
               {'name': 'virtual_z', 'qubit': ['Q0'], 'phase': np.pi / 4},
               {'name': 'X90', 'qubit': ['Q0']},
               {'name': 'X90', 'qubit': ['Q1']}]
    compiler = compile_program(program, qchip)
    pulses = compiler.ir_prog.blocks['block_0']['instructions']
    assert pulses[0].phase == 0
    assert pulses[1].phase == 0
    assert pulses[3].phase == np.pi / 2
    assert pulses[4].phase == 3 * np.pi / 4
    assert pulses[5].phase == 0


def test_basic_schedule(qchip):
    program = [{'name': 'X90', 'qubit': ['Q0']},
               {'name': 'X90', 'qubit': ['Q1']},
               {'name': 'X90Z90', 'qubit': ['Q0']},
               {'name': 'X90', 'qubit': ['Q0']},
               {'name': 'X90', 'qubit': ['Q1']},
               {'name': 'read', 'qubit': ['Q0']}]
    compiler = compile_program(program, qchip)
    pulses = compiler.ir_prog.blocks['block_0']['instructions']
    assert [p.start_time for p in pulses] == [5, 5, 21, 37, 13, 53, 353]


def test_linear_compile_golden(qchip, reference_root):
    program = [{'name': 'X90', 'qubit': ['Q0']},
               {'name': 'X90', 'qubit': ['Q1']},
               {'name': 'read', 'qubit': ['Q0']}]
    prog = compile_program(program, qchip).compile()
    golden = load_golden(reference_root, 'test_linear_compile_out.txt')
    assert_close_tree(sorted_prog_dict(prog), golden)


def test_pulse_compile_golden(qchip, reference_root):
    program = [{'name': 'X90', 'qubit': ['Q0']},
               {'name': 'X90', 'qubit': ['Q1']},
               {'name': 'X90Z90', 'qubit': ['Q0']},
               {'name': 'X90', 'qubit': ['Q0']},
               {'name': 'X90', 'qubit': ['Q1']},
               {'name': 'pulse', 'phase': np.pi / 2, 'freq': 'Q0.freq',
                'env': np.ones(100), 'twidth': 24.e-9, 'amp': 0.5,
                'dest': 'Q0.qdrv'},
               {'name': 'read', 'qubit': ['Q0']}]
    prog = compile_program(program, qchip).compile()
    golden = load_golden(reference_root, 'test_pulse_compile_out.txt')
    actual = sorted_prog_dict(prog)
    # numpy envelope arrays serialize as lists in the golden file
    for core in actual:
        for instr in actual[core]:
            if isinstance(instr.get('env'), np.ndarray):
                instr['env'] = list(instr['env'])
    assert_close_tree(actual, golden)


def test_pulse_compile_ir_input(qchip, reference_root):
    program = [iri.Gate('X90', 'Q0'),
               iri.Gate('X90', 'Q1'),
               iri.Gate('X90Z90', 'Q0'),
               iri.Gate('X90', 'Q0'),
               iri.Gate('X90', 'Q1'),
               iri.Pulse(phase=np.pi / 2, freq='Q0.freq', env=np.ones(100),
                         twidth=24.e-9, amp=0.5, dest='Q0.qdrv'),
               iri.Gate('read', 'Q0')]
    prog = compile_program(program, qchip).compile()
    golden = load_golden(reference_root, 'test_pulse_compile_out.txt')
    actual = sorted_prog_dict(prog)
    for core in actual:
        for instr in actual[core]:
            if isinstance(instr.get('env'), np.ndarray):
                instr['env'] = list(instr['env'])
    assert_close_tree(actual, golden)


def test_multirst_golden(qchip, reference_root):
    program = [{'name': 'X90', 'qubit': ['Q0']},
               {'name': 'branch_fproc', 'alu_cond': 'eq', 'cond_lhs': 1,
                'func_id': 1, 'true': [],
                'false': [{'name': 'X90', 'qubit': ['Q0']}], 'scope': ['Q0']},
               {'name': 'branch_fproc', 'alu_cond': 'eq', 'cond_lhs': 1,
                'func_id': 0, 'true': [],
                'false': [{'name': 'X90', 'qubit': ['Q1']}], 'scope': ['Q1']},
               {'name': 'X90', 'qubit': ['Q1']}]
    prog = compile_program(program, qchip).compile()
    golden = load_golden(reference_root, 'test_multirst_cfg.txt')
    assert_close_tree(sorted_prog_dict(prog), golden)


MULTIRST_FPROC_PROGRAM = [
    {'name': 'X90', 'qubit': ['Q0']},
    {'name': 'branch_fproc', 'alu_cond': 'eq', 'cond_lhs': 1,
     'func_id': 'Q0.meas', 'true': [],
     'false': [{'name': 'X90', 'qubit': ['Q0']}], 'scope': ['Q0']},
    {'name': 'branch_fproc', 'alu_cond': 'eq', 'cond_lhs': 1,
     'func_id': 'Q1.meas', 'true': [],
     'false': [{'name': 'X90', 'qubit': ['Q1']}], 'scope': ['Q1']},
    {'name': 'X90', 'qubit': ['Q1']}]


def test_multirst_fproc_res_golden(qchip, reference_root, channelcfg_path):
    prog = compile_program(MULTIRST_FPROC_PROGRAM, qchip, dp.FPGAConfig()).compile()
    golden = load_golden(reference_root, 'test_multirst_fproc_res_cfg.txt')
    assert_close_tree(sorted_prog_dict(prog), golden)
    # the assembled result must build without error
    channel_configs = dp.load_channel_configs(channelcfg_path)
    asm = dp.GlobalAssembler(prog, channel_configs, MockElement)
    asm.get_assembled_program()


def test_fproc_hold_golden(qchip, reference_root, channelcfg_path):
    program = [{'name': 'X90', 'qubit': ['Q0']},
               {'name': 'read', 'qubit': ['Q0']},
               {'name': 'X90', 'qubit': ['Q0']},
               {'name': 'read', 'qubit': ['Q1']},
               {'name': 'branch_fproc', 'alu_cond': 'eq', 'cond_lhs': 1,
                'func_id': 'Q0.meas', 'true': [],
                'false': [{'name': 'X90', 'qubit': ['Q0']}], 'scope': ['Q0']},
               {'name': 'branch_fproc', 'alu_cond': 'eq', 'cond_lhs': 1,
                'func_id': 'Q1.meas', 'true': [],
                'false': [{'name': 'X90', 'qubit': ['Q1']}], 'scope': ['Q1']},
               {'name': 'X90', 'qubit': ['Q1']}]
    prog = compile_program(program, qchip, dp.FPGAConfig()).compile()
    golden = load_golden(reference_root, 'test_fproc_hold.txt')
    assert_close_tree(sorted_prog_dict(prog), golden)
    channel_configs = dp.load_channel_configs(channelcfg_path)
    dp.GlobalAssembler(prog, channel_configs, MockElement).get_assembled_program()


def test_simple_loop_golden(qchip, reference_root):
    program = [{'name': 'X90', 'qubit': ['Q0']},
               {'name': 'read', 'qubit': ['Q0']},
               {'name': 'X90', 'qubit': ['Q1']},
               {'name': 'Z90', 'qubit': ['Q0']},
               {'name': 'X90', 'qubit': ['Q0']},
               {'name': 'declare', 'var': 'loopind', 'dtype': 'int', 'scope': ['Q0']},
               {'name': 'loop', 'cond_lhs': 10, 'cond_rhs': 'loopind',
                'alu_cond': 'ge', 'scope': ['Q0'],
                'body': [{'name': 'X90', 'qubit': ['Q0']},
                         {'name': 'X90', 'qubit': ['Q0']}]},
               {'name': 'read', 'qubit': ['Q0']},
               {'name': 'X90', 'qubit': ['Q1']}]
    prog = compile_program(program, qchip).compile()
    golden = load_golden(reference_root, 'test_simple_loop.txt')
    assert_close_tree(sorted_prog_dict(prog), golden)


def test_compound_loop_golden(qchip, reference_root):
    fpga_config = dict(FAST_CLOCKS, pulse_load_clks=4)
    program = [{'name': 'X90', 'qubit': ['Q0']},
               {'name': 'read', 'qubit': ['Q0']},
               {'name': 'X90', 'qubit': ['Q1']},
               {'name': 'declare', 'var': 'loopind', 'dtype': 'int', 'scope': ['Q0']},
               {'name': 'loop', 'cond_lhs': 10, 'cond_rhs': 'loopind',
                'alu_cond': 'ge', 'scope': ['Q0', 'Q1'],
                'body': [{'name': 'X90', 'qubit': ['Q0']},
                         {'name': 'X90', 'qubit': ['Q0']}]},
               {'name': 'CR', 'qubit': ['Q1', 'Q0']},
               {'name': 'X90', 'qubit': ['Q1']}]
    prog = compile_program(program, qchip, fpga_config).compile()
    golden = load_golden(reference_root, 'test_compound_loop.txt')
    assert_close_tree(sorted_prog_dict(prog), golden)


def test_nested_loop_golden(qchip, reference_root):
    fpga_config = dict(FAST_CLOCKS, pulse_load_clks=4)
    program = [{'name': 'X90', 'qubit': ['Q0']},
               {'name': 'read', 'qubit': ['Q0']},
               {'name': 'X90', 'qubit': ['Q1']},
               {'name': 'declare', 'var': 'loopind', 'dtype': 'int', 'scope': ['Q0']},
               {'name': 'declare', 'var': 'loopind2', 'dtype': 'int', 'scope': ['Q0']},
               {'name': 'loop', 'cond_lhs': 10, 'cond_rhs': 'loopind',
                'alu_cond': 'ge', 'scope': ['Q0', 'Q1'],
                'body': [{'name': 'X90', 'qubit': ['Q0']},
                         {'name': 'X90', 'qubit': ['Q0']},
                         {'name': 'loop', 'cond_lhs': 10, 'cond_rhs': 'loopind2',
                          'alu_cond': 'ge', 'scope': ['Q0', 'Q1'],
                          'body': [{'name': 'X90', 'qubit': ['Q1']},
                                   {'name': 'read', 'qubit': ['Q0']}]}]},
               {'name': 'CR', 'qubit': ['Q1', 'Q0']},
               {'name': 'X90', 'qubit': ['Q1']}]
    prog = compile_program(program, qchip, fpga_config).compile()
    golden = load_golden(reference_root, 'test_nested_loop.txt')
    assert_close_tree(sorted_prog_dict(prog), golden)


def test_hw_virtualz_golden(qchip, reference_root, channelcfg_path):
    program = [{'name': 'declare', 'var': 'q0_phase', 'scope': ['Q0'],
                'dtype': 'phase'},
               {'name': 'bind_phase', 'var': 'q0_phase', 'freq': 'Q0.freq'},
               {'name': 'X90', 'qubit': ['Q0']},
               {'name': 'X90', 'qubit': ['Q1']},
               {'name': 'virtual_z', 'qubit': 'Q0', 'phase': np.pi / 2},
               {'name': 'X90', 'qubit': ['Q0']},
               {'name': 'read', 'qubit': ['Q0']}]
    prog = compile_program(program, qchip).compile()
    golden = load_golden(reference_root, 'test_hw_virtualz_out.txt')
    assert_close_tree(sorted_prog_dict(prog), golden)
    channel_configs = dp.load_channel_configs(channelcfg_path)
    dp.GlobalAssembler(prog, channel_configs, MockElement).get_assembled_program()


def test_linear_compile_globalasm_golden(qchip, reference_root, channelcfg_path):
    program = [{'name': 'X90', 'qubit': ['Q0']},
               {'name': 'X90', 'qubit': ['Q1']},
               {'name': 'read', 'qubit': ['Q0']}]
    prog = compile_program(program, qchip).compile()
    channel_configs = dp.load_channel_configs(channelcfg_path)
    asm_prog = dp.GlobalAssembler(prog, channel_configs, MockElement) \
        .get_assembled_program()
    sorted_prog = {ci: {b: asm_prog[ci][b] for b in sorted(asm_prog[ci])}
                   for ci in sorted(asm_prog)}
    golden = load_golden(reference_root, 'test_linear_compile_globalasm.txt')
    assert sorted_prog == golden


def test_core_scoper_groupings():
    scoper = dp.ir.CoreScoper(
        ('Q0.rdrv', 'Q0.rdlo', 'Q0.qdrv', 'Q1.rdrv', 'Q1.qdrv', 'Q1.rdlo'))
    expected = {dest: ('Q0.qdrv', 'Q0.rdrv', 'Q0.rdlo')
                for dest in ('Q0.rdrv', 'Q0.rdlo', 'Q0.qdrv')}
    expected.update({dest: ('Q1.qdrv', 'Q1.rdrv', 'Q1.rdlo')
                     for dest in ('Q1.rdrv', 'Q1.rdlo', 'Q1.qdrv')})
    assert scoper.proc_groupings == expected


def test_core_scoper_bychan():
    scoper = dp.ir.CoreScoper(
        ('Q0.rdrv', 'Q0.rdlo', 'Q0.qdrv', 'Q1.rdrv', 'Q1.qdrv', 'Q1.rdlo'),
        proc_grouping=[('{qubit}.qdrv',), ('{qubit}.rdrv', '{qubit}.rdlo')])
    assert scoper.proc_groupings['Q0.qdrv'] == ('Q0.qdrv',)
    assert scoper.proc_groupings['Q0.rdlo'] == ('Q0.rdrv', 'Q0.rdlo')
    assert scoper.proc_groupings['Q1.rdrv'] == ('Q1.rdrv', 'Q1.rdlo')


def test_user_schedule_lints(qchip):
    program = [{'name': 'pulse', 'phase': 0., 'freq': 'Q0.freq',
                'env': np.ones(100), 'twidth': 24.e-9, 'amp': 0.5,
                'dest': 'Q0.qdrv', 'start_time': 5},
               {'name': 'pulse', 'phase': 0., 'freq': 'Q0.freq',
                'env': np.ones(100), 'twidth': 24.e-9, 'amp': 0.5,
                'dest': 'Q0.rdrv', 'start_time': 8},
               {'name': 'pulse', 'phase': 0., 'freq': 'Q0.freq',
                'env': np.ones(100), 'twidth': 24.e-9, 'amp': 0.5,
                'dest': 'Q0.qdrv', 'start_time': 11}]
    compiler = dp.Compiler(program)
    fpga_config = dp.FPGAConfig(**FAST_CLOCKS)
    compiler.run_ir_passes(cm.get_passes(
        fpga_config, qchip, compiler_flags=cm.CompilerFlags(schedule=False)))
    compiler.compile()


def test_user_wrong_schedule_raises(qchip):
    program = [{'name': 'pulse', 'phase': 0., 'freq': 'Q0.freq',
                'env': np.ones(100), 'twidth': 24.e-9, 'amp': 0.5,
                'dest': 'Q0.qdrv', 'start_time': 5},
               {'name': 'pulse', 'phase': 0., 'freq': 'Q0.freq',
                'env': np.ones(100), 'twidth': 24.e-9, 'amp': 0.5,
                'dest': 'Q0.rdrv', 'start_time': 6}]
    compiler = dp.Compiler(program)
    fpga_config = dp.FPGAConfig(**FAST_CLOCKS)
    with pytest.raises(Exception):
        compiler.run_ir_passes(cm.get_passes(
            fpga_config, qchip, compiler_flags=cm.CompilerFlags(schedule=False)))


def test_serialize_roundtrip_every_pass(qchip, reference_root, channelcfg_path):
    """Re-serialise the IR after every pass and recompile: same golden."""
    program = MULTIRST_FPROC_PROGRAM
    fpga_config = dp.FPGAConfig()
    pass_list = cm.get_passes(fpga_config, qchip)
    compiler = None
    for ir_pass in pass_list:
        compiler = dp.Compiler(program)
        compiler.run_ir_passes([ir_pass])
        program = compiler.ir_prog.serialize()
    prog = compiler.compile()
    golden = load_golden(reference_root, 'test_multirst_fproc_res_cfg.txt')
    assert_close_tree(sorted_prog_dict(prog), golden)


def test_compiled_program_save_load(qchip, tmp_path):
    program = [{'name': 'X90', 'qubit': ['Q0']},
               {'name': 'read', 'qubit': ['Q0']}]
    prog = compile_program(program, qchip).compile()
    path = str(tmp_path / 'prog.json')
    prog.save(path)
    loaded = dp.load_compiled_program(path)
    assert set(loaded.program.keys()) == set(prog.program.keys())
    for grp in prog.program:
        assert_close_tree(loaded.program[grp], prog.program[grp])
    assert loaded.fpga_config.alu_instr_clks == prog.fpga_config.alu_instr_clks


def test_zphase_join_mismatch_rejected():
    """Reference-faithful conservatism (found by fuzzing): a virtual-z
    accumulated on one qubit reaches a post-loop join both directly and
    via the *other* qubit's loop-control chain, where it is stale (the
    loop predates later Z90s).  The reference's ResolveVirtualZ rejects
    exactly this shape (reference: python/distproc/ir/passes.py:457-491
    — the predecessor-consistency check; its docstring prescribes
    BindPhase for phases that must cross such joins)."""
    program = [
        {'name': 'virtual_z', 'qubit': 'Q1', 'phase': 0.3},
        {'name': 'declare', 'var': 'i', 'dtype': 'int', 'scope': ['Q0']},
        {'name': 'loop', 'cond_lhs': 2, 'cond_rhs': 'i', 'alu_cond': 'ge',
         'scope': ['Q0'],
         'body': [{'name': 'X90', 'qubit': ['Q0']},
                  {'name': 'alu', 'op': 'add', 'lhs': 1, 'rhs': 'i',
                   'out': 'i'}]},
        {'name': 'Z90', 'qubit': ['Q1']},      # Q1 phase moves on
        {'name': 'read', 'qubit': ['Q1']},
        {'name': 'branch_fproc', 'alu_cond': 'eq', 'cond_lhs': 1,
         'func_id': 'Q1.meas', 'scope': ['Q1'],
         'true': [{'name': 'X90', 'qubit': ['Q1']}], 'false': []},
        {'name': 'X90', 'qubit': ['Q0']},      # join sees stale Q1 phase
    ]
    sim_mod = pytest.importorskip('distributed_processor_tpu.simulator')
    with pytest.raises(ValueError, match='z-phase mismatch'):
        sim_mod.Simulator(n_qubits=2).compile(program)
