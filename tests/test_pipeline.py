"""End-to-end tests: dict program -> compiler -> assembler -> decoder ->
JAX interpreter (BASELINE configs 1/3/4), plus sharded sweeps on the
virtual 8-device CPU mesh (config 5 shape)."""

import numpy as np
import pytest

import distributed_processor_tpu as dp
from distributed_processor_tpu.pipeline import compile_to_machine, compile_program
from distributed_processor_tpu.sim import simulate, simulate_batch
from distributed_processor_tpu.models import (
    make_channel_configs, active_reset, rb_program, sample_meas_bits)
from distributed_processor_tpu.models.rb import clifford_table, rb_sequence
from distributed_processor_tpu.parallel import (
    make_mesh, sharded_simulate, sweep_stats, sharded_demod)


@pytest.fixture(scope='module')
def qchip(qchipcfg_path):
    return dp.QChip(qchipcfg_path)


def test_x90_read_end_to_end(qchip):
    # BASELINE config 1: X90 + readout, 1 qubit, full stack
    program = [{'name': 'X90', 'qubit': ['Q0']},
               {'name': 'read', 'qubit': ['Q0']}]
    mp = compile_to_machine(program, qchip, n_qubits=1)
    out = simulate(mp)
    assert int(out['err'][0]) == 0 and bool(out['done'][0])
    n = int(out['n_pulses'][0])
    assert n == 3                     # X90 on qdrv, read on rdrv + rdlo
    elems = list(np.asarray(out['rec_elem'][0, :n]))
    assert sorted(elems) == [0, 1, 2]
    # pulse times must equal the scheduler's start_times
    prog = compile_program(program, qchip)
    sched = [i for i in prog.program[next(iter(prog.program))]
             if i.get('op') == 'pulse']
    want = sorted(p['start_time'] for p in sched)
    got = sorted(np.asarray(out['rec_qtime'][0, :n]))
    assert list(got) == want
    # the rdlo pulse registered a measurement
    assert int(out['n_meas'][0]) == 1


def test_active_reset_end_to_end(qchip):
    # BASELINE config 3: measurement-conditioned branch via fproc
    mp = compile_to_machine(active_reset(['Q0']), qchip, n_qubits=1)
    out0 = simulate(mp, meas_bits=np.zeros((1, 4), int))
    out1 = simulate(mp, meas_bits=np.ones((1, 4), int))
    assert int(out0['err'][0]) == 0 and int(out1['err'][0]) == 0
    # measured |1> branch plays two extra X90 pulses
    assert int(out1['n_pulses'][0]) == int(out0['n_pulses'][0]) + 2


def test_two_qubit_parallel_rb(qchip):
    # BASELINE config 4 shape: simultaneous RB on two cores
    program = rb_program(['Q0', 'Q1'], depth=3, seed=7)
    mp = compile_to_machine(program, qchip, n_qubits=2)
    out = simulate(mp)
    assert np.all(np.asarray(out['err']) == 0)
    assert np.all(np.asarray(out['done']))
    # each core: 2 pulses per Clifford x (3+1) + rdrv/rdlo read pair
    for c in range(2):
        assert int(out['n_pulses'][c]) == 2 * 4 + 2
    # barrier alignment: both cores' read pulses land at the same time
    def read_times(c):
        n = int(out['n_pulses'][c])
        elem = np.asarray(out['rec_elem'][c, :n])
        t = np.asarray(out['rec_gtime'][c, :n])
        return t[elem == 2]
    np.testing.assert_array_equal(read_times(0), read_times(1))


def test_clifford_table_properties():
    triples, unitaries = clifford_table()
    assert len(triples) == 24
    # closure under inversion: every sequence inverts to identity
    rng = np.random.default_rng(3)
    seq = rb_sequence(rng, 10)
    net = np.eye(2)
    for i in seq:
        net = unitaries[i] @ net
    assert abs(abs(np.trace(net)) - 2) < 1e-9


def test_sharded_simulate_matches_vmap(qchip):
    mp = compile_to_machine(active_reset(['Q0']), qchip, n_qubits=1)
    import jax
    key = jax.random.PRNGKey(0)
    bits = np.asarray(sample_meas_bits(key, [0.3], 16, 4))
    mesh = make_mesh(n_dp=8)
    sharded = sharded_simulate(mp, bits, mesh)
    local = simulate_batch(mp, bits)
    np.testing.assert_array_equal(np.asarray(sharded['n_pulses']),
                                  np.asarray(local['n_pulses']))
    np.testing.assert_array_equal(np.asarray(sharded['regs']),
                                  np.asarray(local['regs']))


def test_sweep_stats_psum(qchip):
    mp = compile_to_machine(active_reset(['Q0']), qchip, n_qubits=1)
    import jax
    bits = np.asarray(sample_meas_bits(jax.random.PRNGKey(1), [1.0], 32, 4))
    mesh = make_mesh(n_dp=8)
    stats = sweep_stats(mp, bits, mesh)
    assert float(stats['err_rate']) == 0
    base = np.asarray(simulate(mp, meas_bits=np.ones((1, 4), int))['n_pulses'])
    np.testing.assert_allclose(np.asarray(stats['mean_pulses']), base)


def test_sharded_demod_matches_local():
    from distributed_processor_tpu.ops import demod_iq
    rng = np.random.default_rng(0)
    adc = rng.standard_normal((16, 64)).astype(np.float32)
    w = rng.standard_normal((64, 4)).astype(np.float32)
    mesh = make_mesh(n_dp=4, n_mp=2)
    got = np.asarray(sharded_demod(adc, w, mesh))
    want = np.asarray(demod_iq(adc, w))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_sharded_physics_stats(qchip):
    """Physics-closed execution sharded over dp: per-shard epoch loops,
    psum statistics; deterministic all-excited init reads all 1s and
    runs the reset branch everywhere."""
    from distributed_processor_tpu.parallel import sharded_physics_stats
    from distributed_processor_tpu.sim.physics import ReadoutPhysics
    mp = compile_to_machine(active_reset(['Q0']), qchip, n_qubits=1)
    mesh = make_mesh(n_dp=8)
    model = ReadoutPhysics(sigma=0.01, p1_init=1.0)
    stats = sharded_physics_stats(
        mp, model, 3, 32, mesh,
        max_steps=mp.n_instr * 4 + 64, max_pulses=16, max_meas=2,
        max_resets=1)
    assert float(stats['err_rate']) == 0.0
    np.testing.assert_allclose(np.asarray(stats['meas1_rate']), 1.0)
    np.testing.assert_allclose(np.asarray(stats['mean_pulses']), 4.0)
    # analytic resolve mode shards identically
    stats2 = sharded_physics_stats(
        mp, ReadoutPhysics(sigma=0.01, p1_init=1.0,
                           resolve_mode='analytic'),
        3, 32, mesh, max_steps=mp.n_instr * 4 + 64, max_pulses=16,
        max_meas=2, max_resets=1)
    np.testing.assert_allclose(np.asarray(stats2['meas1_rate']), 1.0)
