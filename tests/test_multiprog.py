"""Program-as-data multi-sequence execution (``simulate_multi_batch``).

The contract under test: N compiled programs stacked into one
``[n_progs, n_cores, n_instr]`` SoA tensor, DONE-padded into a shape
bucket and vmapped over the generic engine inside ONE jit, produce
bit-identical results to running each program alone — and the jit cache
keys on the BUCKET SHAPE, not program content, so a second ensemble of
fresh random sequences in the same bucket triggers no retrace.
"""

from dataclasses import replace

import numpy as np
import pytest

from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import (MultiMachineProgram,
                                               stack_machine_programs)
from distributed_processor_tpu.models import (active_reset,
                                              make_default_qchip,
                                              rb_ensemble)
from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.sim.interpreter import (
    InterpreterConfig, multi_trace_count, simulate_batch,
    simulate_multi_batch)


def _ensemble(n_qubits, depth, n_seqs, seed):
    qubits = [f'Q{i}' for i in range(n_qubits)]
    qchip = make_default_qchip(n_qubits)
    return [compile_to_machine(active_reset(qubits) + prog, qchip,
                               n_qubits=n_qubits)
            for prog in rb_ensemble(qubits, depth, n_seqs, seed=seed)]


def _bucket_cfg(mmp, **kw):
    return InterpreterConfig(max_steps=2 * mmp.n_instr + 64,
                             max_pulses=mmp.n_instr + 2,
                             max_meas=2, max_resets=2, **kw)


def test_shape_bucket():
    assert isa.shape_bucket(1) == 8
    assert isa.shape_bucket(8) == 8
    assert isa.shape_bucket(9) == 16
    assert isa.shape_bucket(64) == 64
    assert isa.shape_bucket(65) == 128
    with pytest.raises(ValueError):
        isa.shape_bucket(0)


def test_stack_validates_core_count():
    mps = _ensemble(2, 1, 1, seed=0) + _ensemble(3, 1, 1, seed=0)
    with pytest.raises(ValueError, match='core-count'):
        stack_machine_programs(mps)


def test_stacked_ensemble_shape_and_padding():
    # deliberately mixed depths: the shorter member must be DONE-padded
    mps = _ensemble(2, 2, 2, seed=3) + _ensemble(2, 1, 1, seed=4)
    mmp = stack_machine_programs(mps)
    assert isinstance(mmp, MultiMachineProgram)
    assert mmp.n_progs == 3
    assert mmp.n_cores == mps[0].n_cores
    assert mmp.n_instr == isa.shape_bucket(max(m.n_instr for m in mps))
    kind = np.asarray(mmp.soa.kind)
    for i, mp in enumerate(mps):
        np.testing.assert_array_equal(kind[i, :, :mp.n_instr],
                                      np.asarray(mp.soa.kind))
        assert np.all(kind[i, :, mp.n_instr:] == isa.K_DONE)


def test_multi_equals_per_program_both_engines():
    """Bit-identity of the stacked ensemble against per-program runs on
    BOTH engines — including a shorter DONE-padded member, whose padding
    must be semantically invisible."""
    mps = _ensemble(2, 2, 2, seed=5) + _ensemble(2, 1, 1, seed=6)
    mmp = stack_machine_programs(mps)
    cfg = _bucket_cfg(mmp)
    rng = np.random.default_rng(7)
    bits = rng.integers(0, 2,
                        size=(3, 16, mmp.n_cores, 2)).astype(np.int32)
    multi = simulate_multi_batch(mmp, bits, cfg=cfg)
    for i, mp in enumerate(mps):
        gen = simulate_batch(mp, bits[i],
                             cfg=replace(cfg, straightline=False))
        sl = simulate_batch(mp, bits[i],
                            cfg=replace(cfg, straightline=True))
        assert set(gen) == set(sl) == set(multi)
        for k in gen:
            got = np.asarray(multi[k])
            got_i = got[i] if got.ndim else got
            np.testing.assert_array_equal(
                got_i, np.asarray(gen[k]), err_msg=f'prog {i} gen: {k}')
            if k != 'steps':    # engine iteration count, not semantics
                np.testing.assert_array_equal(
                    got_i, np.asarray(sl[k]), err_msg=f'prog {i} sl: {k}')
        assert not bool(np.asarray(multi['incomplete'])[i])


def test_meas_bits_broadcast_and_init_regs_forms():
    mps = _ensemble(2, 1, 2, seed=8)
    mmp = stack_machine_programs(mps)
    cfg = _bucket_cfg(mmp)
    rng = np.random.default_rng(9)
    shared = rng.integers(0, 2,
                          size=(8, mmp.n_cores, 2)).astype(np.int32)
    out = simulate_multi_batch(mmp, shared, cfg=cfg)
    per = simulate_multi_batch(
        mmp, np.broadcast_to(shared[None], (2,) + shared.shape), cfg=cfg)
    for k in out:
        np.testing.assert_array_equal(np.asarray(out[k]),
                                      np.asarray(per[k]), err_msg=k)
    # per-program [P, C, R] registers broadcast over shots
    regs = np.zeros((2, mmp.n_cores, isa.N_REGS), np.int32)
    out2 = simulate_multi_batch(mmp, shared, init_regs=regs, cfg=cfg)
    np.testing.assert_array_equal(np.asarray(out2['regs']),
                                  np.asarray(out['regs']))
    with pytest.raises(ValueError, match='n_progs'):
        simulate_multi_batch(
            mmp, rng.integers(0, 2, size=(3, 8, mmp.n_cores, 2)),
            cfg=cfg)


def test_straightline_cfg_rejected():
    mps = _ensemble(2, 1, 2, seed=10)
    mmp = stack_machine_programs(mps)
    with pytest.raises(ValueError, match='generic engine'):
        simulate_multi_batch(
            mmp, np.zeros((2, 4, mmp.n_cores, 2), np.int32),
            cfg=_bucket_cfg(mmp, straightline=True))


def test_same_shape_ensemble_no_retrace():
    """The acceptance contract: EXACTLY one retrace per shape bucket.
    A second ensemble of fresh random sequences in the same bucket must
    reuse the compiled executable; a different bucket traces once."""
    mps_a = _ensemble(2, 2, 3, seed=21)
    mps_b = _ensemble(2, 2, 3, seed=99)      # fresh random content
    mmp_a = stack_machine_programs(mps_a)
    mmp_b = stack_machine_programs(mps_b)
    assert mmp_a.n_instr == mmp_b.n_instr    # same depth -> same bucket
    rng = np.random.default_rng(13)
    bits = rng.integers(0, 2,
                        size=(3, 8, mmp_a.n_cores, 2)).astype(np.int32)
    cfg = _bucket_cfg(mmp_a)
    c0 = multi_trace_count()
    out_a = simulate_multi_batch(mmp_a, bits, cfg=cfg)
    c1 = multi_trace_count()
    out_b = simulate_multi_batch(mmp_b, bits, cfg=cfg)
    c2 = multi_trace_count()
    assert c1 - c0 <= 1                      # 1, or 0 if already warm
    assert c2 == c1, 'same-shape ensemble retraced'
    # fresh random content flows through the SHARED executable: the
    # recorded pulse phases differ, while the structural outputs (every
    # Clifford is exactly two pulses, bits are injected) coincide
    assert not np.array_equal(np.asarray(out_a['rec_phase']),
                              np.asarray(out_b['rec_phase']))
    for k in ('n_pulses', 'incomplete'):
        np.testing.assert_array_equal(np.asarray(out_a[k]),
                                      np.asarray(out_b[k]), err_msg=k)
    # a deeper ensemble lands in a different bucket: exactly one more
    mps_c = _ensemble(2, 14, 3, seed=21)
    mmp_c = stack_machine_programs(mps_c)
    assert mmp_c.n_instr != mmp_a.n_instr, 'depths chose the same bucket'
    simulate_multi_batch(mmp_c, bits, cfg=_bucket_cfg(mmp_c))
    assert multi_trace_count() == c2 + 1


def test_bucket_cfg_defaults_key_on_bucket_not_content():
    """Omitting cfg derives the execution budget from the BUCKET, so two
    same-bucket ensembles share the default cfg too (a content-derived
    budget would silently retrace and defeat the amortization)."""
    mps_a = _ensemble(2, 2, 2, seed=31)
    mps_b = _ensemble(2, 2, 2, seed=32)
    mmp_a = stack_machine_programs(mps_a)
    mmp_b = stack_machine_programs(mps_b)
    bits = np.zeros((2, 4, mmp_a.n_cores, 2), np.int32)
    simulate_multi_batch(mmp_a, bits, max_meas=2, max_resets=2)
    c = multi_trace_count()
    simulate_multi_batch(mmp_b, bits, max_meas=2, max_resets=2)
    assert multi_trace_count() == c


def test_run_multi_sweep_resumes(tmp_path):
    """Driver-level ensemble sweep: one-shot run == checkpointed
    two-stage run, and a swapped ensemble is rejected on resume."""
    from distributed_processor_tpu.parallel import run_multi_sweep
    mps = _ensemble(2, 1, 2, seed=41)
    full = run_multi_sweep(mps, total_shots=8, batch=4, p1=0.5, key=3,
                           max_meas=2, max_resets=2)
    assert full['mean_pulses'].shape == (2, mps[0].n_cores)
    assert full['err_rate'].shape == (2,)
    assert full['shots'] == 8 and full['incomplete_batches'] == 0
    ckpt = str(tmp_path / 'multi.npz')
    # stage 1: first batch only, then resume to the full shot count
    run_multi_sweep(mps, total_shots=4, batch=4, p1=0.5, key=3,
                    checkpoint=ckpt, max_meas=2, max_resets=2)
    resumed = run_multi_sweep(mps, total_shots=8, batch=4, p1=0.5, key=3,
                              checkpoint=ckpt, max_meas=2, max_resets=2)
    for k in ('mean_pulses', 'err_rate', 'mean_qclk'):
        np.testing.assert_allclose(resumed[k], full[k], err_msg=k)
    # asking for LESS than the checkpoint holds is a caller error
    with pytest.raises(ValueError, match='holds'):
        run_multi_sweep(mps, total_shots=4, batch=4, p1=0.5, key=3,
                        checkpoint=ckpt, max_meas=2, max_resets=2)
    # a different ensemble must not resume this checkpoint
    other = _ensemble(2, 1, 2, seed=55)
    with pytest.raises(ValueError):
        run_multi_sweep(other, total_shots=12, batch=4, p1=0.5, key=3,
                        checkpoint=ckpt, strict_resume=True,
                        max_meas=2, max_resets=2)
