"""Self-contained golden tests: compiler + assembler outputs pinned by
committed files, no reference checkout needed.

Mirrors the reference's golden-file strategy (reference:
python/test/test_compiler.py str()-comparison against
test_outputs/*.txt, with *_err.txt dumps on mismatch) using this repo's
own programs and built-in calibration (models/golden_suite.py).  On
mismatch the actual output is written next to the golden as
``<name>_err.json`` for diffing, the same workflow the reference uses.

Regenerate after an intentional compiler change with::

    python -m distributed_processor_tpu.models.golden_suite
"""

import json
import os

import pytest

from distributed_processor_tpu.models.golden_suite import (
    GOLDEN_PROGRAMS, compile_golden, canonical_json)

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), 'goldens')


@pytest.mark.parametrize('name', sorted(GOLDEN_PROGRAMS))
def test_golden(name):
    path = os.path.join(GOLDEN_DIR, name + '.json')
    assert os.path.exists(path), \
        f'missing golden {path}: run python -m ' \
        f'distributed_processor_tpu.models.golden_suite'
    actual = json.loads(canonical_json(compile_golden(name)))
    with open(path) as f:
        golden = json.load(f)
    if actual != golden:
        err_path = os.path.join(GOLDEN_DIR, name + '_err.json')
        with open(err_path, 'w') as f:
            f.write(canonical_json(actual) + '\n')
        # byte-level buffers are the tightest signal — name them first
        for core in golden.get('assembled', {}):
            for k in ('cmd_buf', 'env_buffers', 'freq_buffers'):
                assert actual['assembled'][core][k] \
                    == golden['assembled'][core][k], \
                    f'{name}: core {core} {k} differs (actual written ' \
                    f'to {err_path})'
        assert actual == golden, \
            f'{name}: asm output differs (actual written to {err_path})'
