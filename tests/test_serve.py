"""Continuous-batching execution service (serve/): the contract.

The load-bearing property is BIT-IDENTITY: a request's demuxed stats
equal the solo ``simulate_batch`` run of the same program under the
same cfg, per stat including the fault word — coalescing is a pure
scheduling optimization, never a semantic one.  Around that: strict
faults stay on the offending handle (batch-mates unharmed),
cancellation/deadlines act at batch boundaries, admission control is
synchronous, shutdown drains or cancels cleanly, and many submitter
threads can hammer one service (the slow stress test).  Every test
shuts its service down — tests/conftest.py prints the junit-gated
thread-leak marker if a dispatcher survives.
"""

import threading
import time

import numpy as np
import pytest

import jax

from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import (machine_program_from_cmds,
                                               stack_machine_programs)
from distributed_processor_tpu.models import (active_reset,
                                              make_default_qchip,
                                              rb_ensemble)
from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.serve import (CancelledError, Coalescer,
                                             DeadlineError,
                                             ExecutionService,
                                             QueueFullError,
                                             ServiceClosedError,
                                             bucket_key)
from distributed_processor_tpu.serve.request import Request
from distributed_processor_tpu.sim.interpreter import (FaultError,
                                                       InterpreterConfig,
                                                       demux_multi_batch,
                                                       simulate_batch,
                                                       simulate_multi_batch)

pytestmark = pytest.mark.serve


def _ensemble(n_qubits, depth, n_seqs, seed):
    qubits = [f'Q{i}' for i in range(n_qubits)]
    qchip = make_default_qchip(n_qubits)
    return [compile_to_machine(active_reset(qubits) + prog, qchip,
                               n_qubits=n_qubits)
            for prog in rb_ensemble(qubits, depth, n_seqs, seed=seed)]


def _cfg_for(mps, **kw):
    bucket = max(isa.shape_bucket(mp.n_instr) for mp in mps)
    base = dict(max_steps=2 * bucket + 64, max_pulses=bucket + 2,
                max_meas=2, max_resets=2)
    base.update(kw)
    return InterpreterConfig(**base)


def _solo(mp, bits, cfg, **kw):
    return jax.tree.map(np.asarray, simulate_batch(mp, bits, cfg=cfg,
                                                   **kw))


def _assert_same(got, want, label=''):
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(want[k]),
                                      err_msg=f'{label}:{k}')


def _loop_mp(iters=1000):
    """Counted loop that exhausts any small step budget (traps)."""
    core = [isa.alu_cmd('reg_alu', 'i', iters, 'id0', write_reg_addr=0),
            isa.pulse_cmd(amp_word=1000, cfg_word=0, env_word=3,
                          cmd_time=10),
            isa.alu_cmd('reg_alu', 'i', -1, 'add', 0, write_reg_addr=0),
            isa.alu_cmd('jump_cond', 'i', 0, 'le', 0, jump_cmd_ptr=1),
            isa.done_cmd()]
    return machine_program_from_cmds([core])


def _clean_mp():
    """Branch-free single-core program in _loop_mp's shape bucket."""
    core = [isa.pulse_cmd(amp_word=1000, cfg_word=0, env_word=3,
                          cmd_time=10 + 20 * i) for i in range(3)] \
        + [isa.done_cmd()]
    return machine_program_from_cmds([core])


# ---------------------------------------------------------------------------
# demux helper + stacking validation (the satellites the service rides on)
# ---------------------------------------------------------------------------

def test_demux_matches_direct_multi():
    mps = _ensemble(2, 2, 3, seed=5)
    cfg = _cfg_for(mps)
    rng = np.random.default_rng(1)
    bits = rng.integers(0, 2, (len(mps), 8, mps[0].n_cores, 2)) \
        .astype(np.int32)
    out = jax.tree.map(np.asarray,
                       simulate_multi_batch(mps, bits, cfg=cfg))
    for i, mp in enumerate(mps):
        got = demux_multi_batch(out, i)
        want = _solo(mp, bits[i], cfg)
        _assert_same(got, want, f'prog{i}')


def test_demux_trims_replication_padding():
    mps = _ensemble(2, 2, 2, seed=6)
    cfg = _cfg_for(mps)
    rng = np.random.default_rng(2)
    short = rng.integers(0, 2, (5, mps[0].n_cores, 2)).astype(np.int32)
    # pad request 0 up to 8 shots by replicating its own last row
    padded = np.concatenate([short, np.repeat(short[-1:], 3, 0)])
    full = rng.integers(0, 2, (8, mps[0].n_cores, 2)).astype(np.int32)
    out = jax.tree.map(np.asarray, simulate_multi_batch(
        mps, np.stack([padded, full]), cfg=cfg))
    got = demux_multi_batch(out, 0, n_shots=5)
    _assert_same(got, _solo(mps[0], short, cfg), 'trimmed')


def test_stack_mismatch_names_program_index():
    mps = _ensemble(2, 2, 2, seed=7) + [_loop_mp()]   # 1 core vs many
    with pytest.raises(ValueError, match=r'program 2'):
        stack_machine_programs(mps)


# ---------------------------------------------------------------------------
# the service: bit-identity through coalesced dispatch
# ---------------------------------------------------------------------------

def test_service_bit_identity_mixed_buckets_and_shots():
    """Requests with unequal shot counts and DIFFERENT shape buckets
    (depth 2 vs depth 12) coalesce into per-bucket batches, and every
    demuxed result equals the solo run."""
    small = _ensemble(2, 2, 3, seed=8)
    big = _ensemble(2, 12, 2, seed=9)
    cfg_s, cfg_b = _cfg_for(small), _cfg_for(big)
    assert isa.shape_bucket(small[0].n_instr) \
        != isa.shape_bucket(big[0].n_instr)
    rng = np.random.default_rng(3)
    reqs = [(mp, cfg_s, rng.integers(0, 2, (4 + 3 * i, mp.n_cores, 2))
             .astype(np.int32)) for i, mp in enumerate(small)]
    reqs += [(mp, cfg_b, rng.integers(0, 2, (6, mp.n_cores, 2))
              .astype(np.int32)) for mp in big]
    with ExecutionService(max_batch_programs=8, max_wait_ms=25.0) as svc:
        handles = [svc.submit(mp, bits, cfg=cfg)
                   for mp, cfg, bits in reqs]
        results = [h.result(timeout=300) for h in handles]
        stats = svc.stats()
    assert stats['completed'] == len(reqs)
    assert stats['dispatches'] >= 2          # one per bucket at least
    assert stats['queue_depth'] == 0
    assert sum(n * c for n, c in stats['batch_occupancy'].items()) \
        == len(reqs)
    for (mp, cfg, bits), got in zip(reqs, results):
        _assert_same(got, _solo(mp, bits, cfg), 'serve')


def test_service_init_regs_and_shots_only():
    mps = _ensemble(2, 2, 2, seed=10)
    cfg = _cfg_for(mps)
    regs = np.arange(mps[0].n_cores * isa.N_REGS, dtype=np.int32) \
        .reshape(mps[0].n_cores, isa.N_REGS) % 7
    with ExecutionService(cfg, max_batch_programs=2,
                          max_wait_ms=25.0) as svc:
        h0 = svc.submit(mps[0], shots=4, init_regs=regs)
        h1 = svc.submit(mps[1], shots=4)
        r0, r1 = h0.result(timeout=300), h1.result(timeout=300)
    zeros = np.zeros((4, mps[0].n_cores, cfg.max_meas), np.int32)
    _assert_same(r0, _solo(mps[0], zeros, cfg, init_regs=regs), 'regs')
    _assert_same(r1, _solo(mps[1], zeros, cfg), 'zero-bits')


def test_strict_fault_isolation():
    """One coalesced batch: a strict faulting request raises on ITS
    handle only; the count-mode faulting mate reports counts in-band;
    the clean mates are fulfilled bit-identically."""
    faulty_strict, faulty_count = _loop_mp(), _loop_mp()
    clean_a, clean_b = _clean_mp(), _clean_mp()
    cfg = InterpreterConfig(max_steps=6, max_pulses=8, max_meas=2)
    bits = np.zeros((4, 1, 2), np.int32)
    with ExecutionService(cfg, max_batch_programs=4,
                          max_wait_ms=50.0) as svc:
        hs = svc.submit(faulty_strict, bits, fault_mode='strict')
        hc = svc.submit(faulty_count, bits)
        h1 = svc.submit(clean_a, bits)
        h2 = svc.submit(clean_b, bits)
        with pytest.raises(FaultError) as ei:
            hs.result(timeout=300)
        out_c = hc.result(timeout=300)
        out_1 = h1.result(timeout=300)
        out_2 = h2.result(timeout=300)
        stats = svc.stats()
    # strict+count normalize to the same bucket cfg -> ONE batch: the
    # isolation below happened between batch-mates, not across batches
    assert stats['dispatches'] == 1
    assert stats['batch_occupancy'] == {4: 1}
    assert stats['completed'] == 3 and stats['failed'] == 1
    assert np.asarray(ei.value.counts)[0] == 4      # budget_exhausted x4
    assert np.asarray(out_c['fault']).all()         # in-band counts
    for out in (out_1, out_2):
        assert not np.asarray(out['fault']).any()
    _assert_same(out_1, _solo(clean_a, bits, cfg), 'clean-mate')


def test_cancel_timeout_deadline():
    mps = _ensemble(2, 2, 3, seed=11)
    cfg = _cfg_for(mps)
    bits = np.zeros((2, mps[0].n_cores, cfg.max_meas), np.int32)
    # max_batch_programs never reached + long wait -> requests sit queued
    with ExecutionService(cfg, max_batch_programs=64,
                          max_wait_ms=60_000.0) as svc:
        h_cancel = svc.submit(mps[0], bits)
        h_wait = svc.submit(mps[1], bits)
        h_dead = svc.submit(mps[2], bits, deadline_ms=80.0)
        assert h_cancel.cancel()
        assert h_cancel.cancelled() and h_cancel.done()
        with pytest.raises(CancelledError):
            h_cancel.result()
        assert not h_cancel.cancel()        # second call lost
        with pytest.raises(TimeoutError):
            h_wait.result(timeout=0.05)
        with pytest.raises(DeadlineError):
            h_dead.result(timeout=30)       # dispatcher wakes at deadline
        assert h_wait.cancel()
        stats = svc.stats()
        assert stats['cancelled'] >= 1 or stats['queue_depth'] >= 1
        svc.shutdown(drain=False)
    final = svc.stats()
    assert final['expired'] == 1
    # h_cancel is observed during pruning; h_wait's cancel may race the
    # shutdown's queue clear, so the count is a lower bound
    assert final['cancelled'] >= 1
    assert final['completed'] == 0


def test_queue_full_admission_then_drain():
    mps = _ensemble(2, 2, 3, seed=12)
    cfg = _cfg_for(mps)
    bits = np.zeros((2, mps[0].n_cores, cfg.max_meas), np.int32)
    svc = ExecutionService(cfg, max_batch_programs=64,
                           max_wait_ms=60_000.0, max_queue=2)
    try:
        h0 = svc.submit(mps[0], bits)
        h1 = svc.submit(mps[1], bits)
        with pytest.raises(QueueFullError):
            svc.submit(mps[2], bits)
        svc.shutdown(drain=True, timeout=300)   # flushes the queue
        _assert_same(h0.result(), _solo(mps[0], bits, cfg), 'drained0')
        _assert_same(h1.result(), _solo(mps[1], bits, cfg), 'drained1')
        stats = svc.stats()
        assert stats['rejected'] == 1 and stats['completed'] == 2
        with pytest.raises(ServiceClosedError):
            svc.submit(mps[0], bits)
    finally:
        svc.shutdown()


def test_shutdown_drain_under_load():
    mps = _ensemble(2, 2, 6, seed=13)
    cfg = _cfg_for(mps)
    bits = np.zeros((3, mps[0].n_cores, cfg.max_meas), np.int32)
    svc = ExecutionService(cfg, max_batch_programs=3, max_wait_ms=5.0)
    handles = [svc.submit(mp, bits) for mp in mps]
    svc.shutdown(drain=True, timeout=300)
    for mp, h in zip(mps, handles):
        assert h.done()
        _assert_same(h.result(), _solo(mp, bits, cfg), 'drain')
    assert svc.stats()['completed'] == len(mps)
    assert not any(t.name.startswith('dproc-serve-dispatch')
                   and t.is_alive() for t in threading.enumerate())


def test_submit_rejects_unservable_cfgs():
    mp = _ensemble(2, 2, 1, seed=14)[0]
    with ExecutionService(max_wait_ms=1.0) as svc:
        for bad in (dict(engine='straightline'), dict(engine='block'),
                    dict(straightline=True),
                    dict(opcode_histogram=True)):
            with pytest.raises(ValueError):
                svc.submit(mp, shots=2, cfg=InterpreterConfig(
                    max_steps=64, max_meas=2, **bad))
        with pytest.raises(ValueError):
            svc.submit(mp)                   # neither meas_bits nor shots
        with pytest.raises(ValueError):
            svc.submit(mp, np.zeros((2, mp.n_cores + 3, 2), np.int32))


def test_coalescer_priority_and_ripening():
    """Batcher unit semantics, no threads: priority lanes order the
    batch, count threshold and wait deadline both ripen a bucket."""
    mp = _clean_mp()
    cfg = InterpreterConfig(max_steps=64, max_meas=2)
    key = bucket_key(mp, cfg)
    bits = np.zeros((2, 1, 2), np.int32)

    def req(seq, priority=0, deadline=None):
        return Request(mp=mp, meas_bits=bits, init_regs=None, cfg=cfg,
                       strict=False, n_shots=2, priority=priority,
                       deadline=deadline, seq=seq)

    co = Coalescer(max_batch_programs=2, max_wait_s=60.0)
    for r in (req(0), req(1, priority=5), req(2)):
        co.push(key, r)
    assert len(co) == 3
    k, batch, expired = co.pop_batch()      # 3 >= ... no: cap is 2
    assert k == key and not expired
    assert [r.seq for r in batch] == [1, 0]   # priority 5 first, FIFO next
    # leftover bucket (1 request) is not ripe until the wait deadline
    k2, batch2, _ = co.pop_batch()
    assert k2 is None and len(co) == 1
    assert 0 < co.next_event() <= 60.0
    k3, batch3, _ = co.pop_batch(now=time.monotonic() + 61.0)
    assert k3 == key and [r.seq for r in batch3] == [2]
    # expired requests are failed during pruning, not dispatched
    dead = req(3, deadline=time.monotonic() - 1.0)
    co.push(key, dead)
    k4, _, expired4 = co.pop_batch()
    assert k4 is None and expired4 == [dead]
    with pytest.raises(DeadlineError):
        dead.handle.result()


@pytest.mark.slow
def test_concurrent_submitter_stress():
    """8 submitter threads x 6 requests each against one service:
    every result bit-identical to its solo run, counters consistent."""
    mps = _ensemble(2, 2, 4, seed=15)
    cfg = _cfg_for(mps)
    rng = np.random.default_rng(4)
    n_threads, per_thread = 8, 6
    jobs = [[(mps[rng.integers(len(mps))],
              rng.integers(0, 2, (int(rng.integers(2, 9)),
                                  mps[0].n_cores, 2)).astype(np.int32))
             for _ in range(per_thread)] for _ in range(n_threads)]
    results = [[None] * per_thread for _ in range(n_threads)]
    errors = []
    with ExecutionService(cfg, max_batch_programs=8,
                          max_wait_ms=5.0, max_queue=512) as svc:
        def worker(tid):
            try:
                hs = [svc.submit(mp, bits) for mp, bits in jobs[tid]]
                for j, h in enumerate(hs):
                    results[tid][j] = h.result(timeout=600)
            except Exception as e:      # pragma: no cover - surfaced below
                errors.append((tid, e))
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(600)
        stats = svc.stats()
    assert not errors, errors
    assert stats['submitted'] == n_threads * per_thread
    assert stats['completed'] == n_threads * per_thread
    assert stats['coalesce_efficiency'] >= 1.0
    assert stats['latency_samples'] == n_threads * per_thread
    solo_cache = {}
    for tid in range(n_threads):
        for (mp, bits), got in zip(jobs[tid], results[tid]):
            ck = (id(mp), bits.shape[0], bits.tobytes())
            if ck not in solo_cache:
                solo_cache[ck] = _solo(mp, bits, cfg)
            _assert_same(got, solo_cache[ck], f't{tid}')


def test_single_device_stats_surface():
    """The default (no ``devices=``) service is one unpinned executor:
    stats() still carries the multi-device surface — one device row,
    zero steals, cold/warm compile classification — so dashboards need
    no schema fork between laptop and pod deployments."""
    mps = _ensemble(2, 2, 1, seed=21)
    cfg = _cfg_for(mps)
    bits = np.zeros((2, mps[0].n_cores, 2), np.int32)
    with ExecutionService(cfg, max_batch_programs=4,
                          max_wait_ms=2.0) as svc:
        svc.submit(mps[0], bits).result(timeout=300)
        svc.submit(mps[0], bits).result(timeout=300)
        stats = svc.stats()
    assert stats['n_devices'] == 1
    assert stats['work_stealing'] is False
    assert stats['steals'] == 0 and stats['warmups'] == 0
    assert len(stats['devices']) == 1
    dev = stats['devices'][0]
    assert dev['device'] == 'default' and dev['home_buckets'] == 1
    assert dev['dispatches'] == stats['dispatches'] == 2
    comp = stats['compile']
    assert comp['cold'] == 1 and comp['warm'] == 1
    assert sum(v['cold'] for v in comp['per_bucket'].values()) == 1
