"""Leakage out of the computational subspace (statevec device).

Trajectory-unraveled absorbing leakage: each 1q drive pulse leaks with
probability ``leak_per_pulse * P(|1>)`` (the excited population drives
the 1->2 transition), the trajectory projects onto the core's |1>
component (collapsing entangled partners consistently), and the core
is frozen — later drives, couplings, and T1/T2 no-op; readouts return
``leak_readout_bit``.  The single-instruction
theta=pi pulse train makes the accumulation EXACT: poles are fixed
points of the no-jump back-action, so the post-pulse excited
population alternates 1, 0, 1, 0, ... and after 2k pi pulses the leak
probability is exactly 1 - (1 - p)^k.
"""

PI_PULSE = {'name': 'pulse', 'dest': 'Q0.qdrv', 'freq': 4.2e9,
            'phase': 0.0, 'amp': 0.96, 'twidth': 24e-9,
            'env': {'env_func': 'square', 'paradict': {}}}

import numpy as np
import pytest

from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.models.coupling import couplings_from_qchip
from distributed_processor_tpu.models.default_qchip import make_default_qchip
from distributed_processor_tpu.sim.device import DeviceModel
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)

KW = dict(max_steps=4000, max_pulses=128, max_meas=4)


@pytest.fixture(scope='module')
def sim2():
    return Simulator(n_qubits=2)


def _run(sim, prog, shots, key, dev_kw, qchip=None, **kw):
    mp = sim.compile(prog)
    cps = couplings_from_qchip(mp, qchip or make_default_qchip(2))
    model = ReadoutPhysics(sigma=0.0, p1_init=0.0, device=DeviceModel(
        'statevec', couplings=cps, **dev_kw))
    out = run_physics_batch(mp, model, key, shots, **{**KW, **kw})
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))
    return out


def test_leak_accumulates_exactly(sim2):
    """After 2k single-instruction pi pulses from |0> the leaked
    fraction is 1 - (1-p)^k exactly: post-pulse P(|1>) alternates
    1, 0, ... and the no-jump back-action is a no-op at poles, so only
    every other pulse is exposed, at unit excited population."""
    p, k, shots = 0.08, 6, 2048
    prog = [dict(PI_PULSE) for _ in range(2 * k)] \
        + [{'name': 'read', 'qubit': ['Q0']}]
    out = _run(sim2, prog, shots, 3, dict(leak_per_pulse=p))
    leaked = np.asarray(out['leaked'])[:, 0]
    want = 1.0 - (1.0 - p) ** k
    se = np.sqrt(want * (1 - want) / shots)
    assert abs(leaked.mean() - want) < 4 * se, (leaked.mean(), want)
    # leaked shots read the leak bit (default 1); the un-leaked end in
    # |0> after the even pi count and read 0
    bits = np.asarray(out['meas_bits'])[:, 0, 0]
    np.testing.assert_array_equal(bits, leaked.astype(bits.dtype))


def test_leaked_core_is_frozen(sim2):
    """Once leaked, further drives no-op and every readout returns the
    leak bit: a pi pulse at p=1 leaks with certainty (post-pulse
    P(|1>) = 1), and a second pi pulse cannot bring the core back —
    an unleaked run reads 0 after the pair."""
    prog = [dict(PI_PULSE) for _ in range(4)] \
        + [{'name': 'read', 'qubit': ['Q0']}]
    out = _run(sim2, prog, 32, 1, dict(leak_per_pulse=1.0))
    assert np.all(np.asarray(out['leaked'])[:, 0])
    assert np.all(np.asarray(out['meas_bits'])[:, 0, 0] == 1)
    # same program, no leakage: X360 returns to |0>
    out = _run(sim2, prog, 32, 1, dict())
    assert np.all(np.asarray(out['meas_bits'])[:, 0, 0] == 0)


def test_leak_readout_bit_configurable(sim2):
    prog = [dict(PI_PULSE), {'name': 'read', 'qubit': ['Q0']}]
    out = _run(sim2, prog, 16, 2, dict(leak_per_pulse=1.0,
                                       leak_readout_bit=0))
    assert np.all(np.asarray(out['leaked'])[:, 0])
    assert np.all(np.asarray(out['meas_bits'])[:, 0, 0] == 0)


def test_leak_no_jump_back_action(sim2):
    """The no-jump branch is a real back-action: surviving trajectories
    damp their |1> amplitude by sqrt(1-p), so the ENSEMBLE reproduces
    the Kraus channel exactly.  X90 then read with leak_readout_bit=0:
    P(read 1) = (1 - p_jump) * P1' = 0.5 (1 - p) — distinguishable
    from the back-action-free (wrong) model's 0.5 (1 - 0.5 p)."""
    p, shots = 0.4, 4096
    prog = [{'name': 'X90', 'qubit': ['Q0']},
            {'name': 'read', 'qubit': ['Q0']}]
    out = _run(sim2, prog, shots, 11, dict(leak_per_pulse=p,
                                           leak_readout_bit=0))
    bits = np.asarray(out['meas_bits'])[:, 0, 0]
    want = 0.5 * (1.0 - p)                    # = 0.30
    wrong = 0.5 * (1.0 - 0.5 * p)             # = 0.40 without back-action
    se = np.sqrt(want * (1 - want) / shots)
    assert abs(bits.mean() - want) < 4 * se, (bits.mean(), want)
    assert abs(bits.mean() - wrong) > 8 * se
    # leak fraction itself: p * P1 = 0.2
    leaked = np.asarray(out['leaked'])[:, 0]
    se_l = np.sqrt(0.2 * 0.8 / shots)
    assert abs(leaked.mean() - 0.2) < 4 * se_l


def test_leak_deterministic_branches(sim2):
    """p=1 makes every branch deterministic through an entangling
    program: the prep X90 either jumps (P1 = 1/2) or the no-jump
    back-action projects the survivor to |0>; survivors' CZ (no 1q
    pulses on Q1, unlike CNOT's target X90) maps |00> -> |00>, and
    their final pi pulse (P1 = 1 after it) leaks with certainty.
    Every shot therefore ends with Q0 leaked and Q1 = 0 exactly —
    zz-coupling masking for leaked controls, the no-jump projection
    (p=1 survivor -> |0>), and the jump projection all exercised."""
    prog = [{'name': 'virtual_z', 'qubit': ['Q0'], 'phase': np.pi / 2},
            {'name': 'X90', 'qubit': ['Q0']},
            {'name': 'virtual_z', 'qubit': ['Q0'], 'phase': np.pi / 2},
            {'name': 'barrier', 'qubit': ['Q0', 'Q1']},
            {'name': 'CZ', 'qubit': ['Q0', 'Q1']},
            {'name': 'barrier', 'qubit': ['Q0', 'Q1']},
            dict(PI_PULSE),
            {'name': 'barrier', 'qubit': ['Q0', 'Q1']},
            {'name': 'read', 'qubit': ['Q0']},
            {'name': 'read', 'qubit': ['Q1']}]
    out = _run(sim2, prog, 256, 7, dict(leak_per_pulse=1.0))
    leaked = np.asarray(out['leaked'])
    bits = np.asarray(out['meas_bits'])[:, :, 0]
    assert np.all(leaked[:, 0]) and not np.any(leaked[:, 1])
    assert np.all(bits[:, 0] == 1)
    assert not np.any(bits[:, 1])


def _run_iq(sim, prog, shots, key, dev_kw, model_kw, **kw):
    mp = sim.compile(prog)
    cps = couplings_from_qchip(mp, make_default_qchip(2))
    model = ReadoutPhysics(p1_init=0.0, device=DeviceModel(
        'statevec', couplings=cps, **dev_kw), **model_kw)
    out = run_physics_batch(mp, model, key, shots, **{**KW, **kw})
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))
    return out


def test_iq_leakage_bit_emerges_from_geometry(sim2):
    """IQ-level leakage readout (round-4 review missing #3): with g2
    set, a leaked core's window traverses the REAL demod chain with the
    |2> response and the bit emerges from where g2 projects on the
    g0/g1 axis — g2 at g1 reads 1, g2 at g0 reads 0, with no forced
    bit anywhere."""
    prog = [dict(PI_PULSE), {'name': 'read', 'qubit': ['Q0']}]
    for g2, want in ((-0.6 + 0.8j, 1), (1.0 + 0.0j, 0)):
        out = _run_iq(sim2, prog, 64, 7, dict(leak_per_pulse=1.0),
                      dict(sigma=0.01, g2=g2))
        assert np.all(np.asarray(out['leaked'])[:, 0])
        bits = np.asarray(out['meas_bits'])[:, 0, 0]
        assert np.all(bits == want), (g2, want, bits[:8])


def test_iq_leakage_3class_repeated_readout(sim2):
    """The leakage-detection experiment: a pi pulse with p_leak = 0.5
    either leaks (physically |2>) or survives in |1>; two consecutive
    readouts through the 3-class discriminator separate them — leaked
    shots classify 2 on BOTH reads (the |2> response is persistent),
    survivors classify 1.  The fabric bit maps class 2 to
    leak_readout_bit, so branching programs keep working."""
    p, shots = 0.5, 512
    prog = [dict(PI_PULSE),
            {'name': 'read', 'qubit': ['Q0']},
            {'name': 'read', 'qubit': ['Q0']}]
    out = _run_iq(sim2, prog, shots, 11, dict(leak_per_pulse=p),
                  dict(sigma=0.01, g2=-0.9 - 0.4j, classify3=True))
    leaked = np.asarray(out['leaked'])[:, 0]
    cls = np.asarray(out['meas_class'])[:, 0, :2]
    bits = np.asarray(out['meas_bits'])[:, 0, :2]
    se = np.sqrt(p * (1 - p) / shots)
    assert abs(leaked.mean() - p) < 4 * se
    np.testing.assert_array_equal(cls[leaked], 2)
    np.testing.assert_array_equal(cls[~leaked], 1)
    np.testing.assert_array_equal(bits[leaked], 1)   # class 2 -> leak bit
    np.testing.assert_array_equal(bits[~leaked], 1)


def test_iq_path_matches_fast_path_geometry(sim2):
    """With g2 placed exactly at g1 and leak_readout_bit = 1 the
    emergent IQ bits equal the documented fast path's forced bits at
    moderate noise — the shortcut is the geometry's limit, not a
    different model."""
    prog = [dict(PI_PULSE), {'name': 'read', 'qubit': ['Q0']}]
    kw = dict(leak_per_pulse=1.0)
    fast = _run_iq(sim2, prog, 128, 3, kw, dict(sigma=0.02))
    iq = _run_iq(sim2, prog, 128, 3, kw, dict(sigma=0.02, g2=-0.6 + 0.8j))
    np.testing.assert_array_equal(np.asarray(fast['meas_bits']),
                                  np.asarray(iq['meas_bits']))


def test_iq_leakage_validation(sim2):
    prog = [dict(PI_PULSE), {'name': 'read', 'qubit': ['Q0']}]
    mp = sim2.compile(prog)
    with pytest.raises(ValueError, match='leak_per_pulse'):
        run_physics_batch(mp, ReadoutPhysics(
            g2=1.0j, device=DeviceModel('statevec')), 0, 1, **KW)
    with pytest.raises(ValueError, match='classify3'):
        run_physics_batch(mp, ReadoutPhysics(
            classify3=True,
            device=DeviceModel('statevec', leak_per_pulse=0.1)),
            0, 1, **KW)
    # a leak2-only device still has a |2> population channel (the
    # coupling-pulse mechanism) — g2 must be accepted, not rejected
    out = run_physics_batch(mp, ReadoutPhysics(
        g2=1.0j, device=DeviceModel('statevec', leak2_per_pulse=0.1)),
        0, 1, **KW)
    assert not bool(out['incomplete'])


def test_cr_leak_accumulates_exactly(sim2):
    """Coupling-pulse-induced leakage (round-4 review's admitted-limit
    item): prepare the control in |1>, fire k zero-amplitude CR pulses
    (couplings with no rotation, so P(|1>) stays 1 exactly), and the
    leaked fraction follows 1 - (1-p)^k — the same closed form as the
    1q channel, now driven by the 2q-gate mechanism."""
    from distributed_processor_tpu.models.repetition import \
        correlated_noise_stage
    p, k, shots = 0.1, 4, 2048
    prog = [{'name': 'X90', 'qubit': ['Q0']},
            {'name': 'X90', 'qubit': ['Q0']}]
    for _ in range(k):
        prog += correlated_noise_stage([(0, 1)])
    prog += [{'name': 'read', 'qubit': ['Q0']},
             {'name': 'read', 'qubit': ['Q1']}]
    out = _run(sim2, prog, shots, 13, dict(leak2_per_pulse=p))
    leaked = np.asarray(out['leaked'])
    want = 1.0 - (1.0 - p) ** k
    se = np.sqrt(want * (1 - want) / shots)
    assert abs(leaked[:, 0].mean() - want) < 4 * se, \
        (leaked[:, 0].mean(), want)
    assert not np.any(leaked[:, 1])          # target never leaks here
    # 1q channel off: a pure-1q program is untouched by leak2
    prog1q = [{'name': 'X90', 'qubit': ['Q0']}] * 4 \
        + [{'name': 'read', 'qubit': ['Q0']}]
    out = _run(sim2, prog1q, 64, 1, dict(leak2_per_pulse=0.9))
    assert not np.any(np.asarray(out['leaked']))


def test_cr_leak_responds_in_interleaved_rb(sim2):
    """The interleaved-RB CZ error responds to coupling-induced
    leakage: with leak2 as the ONLY error channel, the interleaved
    curve (extra CZ per step) decays measurably below the reference
    curve at the same depth — leakage shows up exactly where a
    calibration workflow would look for CZ error."""
    from distributed_processor_tpu.models.coupling import \
        couplings_from_qchip as cfq
    from distributed_processor_tpu.models.rb2q import (
        rb2q_interleaved_program, rb2q_program)
    p2, depth, shots, seed = 0.12, 4, 2048, 31
    qchip = make_default_qchip(2)
    surv = {}
    for tag, builder in (('ref', rb2q_program),
                         ('int', rb2q_interleaved_program)):
        prog, info = builder('Q0', 'Q1', depth, seed=seed)
        mp = sim2.compile(prog)
        model = ReadoutPhysics(sigma=0.0, p1_init=0.0, device=DeviceModel(
            'statevec', couplings=cfq(mp, qchip), leak2_per_pulse=p2))
        out = run_physics_batch(mp, model, seed, shots,
                                max_steps=8000, max_pulses=192, max_meas=4)
        assert not bool(out['incomplete'])
        assert not np.any(np.asarray(out['err']))
        bits = np.asarray(out['meas_bits'])[:, :, 0]
        surv[tag] = (info['n_cz'], float(np.all(bits == 0, axis=1).mean()))
    (n_ref, s_ref), (n_int, s_int) = surv['ref'], surv['int']
    assert n_int > n_ref
    se = np.sqrt(0.25 / shots)
    assert s_int < s_ref - 4 * se, (surv,)


def test_seepage_returns_core_to_service(sim2):
    """Deterministic seepage chain (leak=1, seep=1): pi pulse 1 leaks
    with certainty, pi pulse 2 seeps the core back (no rotation), pi
    pulse 3 rotates the recovered |1> to |0> — the shot ends unleaked
    reading 0, while the absorbing model stays stuck at the leak bit."""
    prog = [dict(PI_PULSE) for _ in range(3)] \
        + [{'name': 'read', 'qubit': ['Q0']}]
    out = _run(sim2, prog, 32, 2, dict(leak_per_pulse=1.0,
                                       seep_per_pulse=1.0))
    assert not np.any(np.asarray(out['leaked'])[:, 0])
    assert not np.any(np.asarray(out['meas_bits'])[:, 0, 0])
    out = _run(sim2, prog, 32, 2, dict(leak_per_pulse=1.0))
    assert np.all(np.asarray(out['leaked'])[:, 0])
    assert np.all(np.asarray(out['meas_bits'])[:, 0, 0] == 1)


def test_seepage_ensemble_rate(sim2):
    """Partial seepage statistics on the same chain: a shot reads 0
    iff it seeped at pulse 2 (then rotated home at pulse 3); seeping at
    pulse 3 re-enters in |1> and reads 1, like never seeping at all.
    P(read 0) = s and P(still leaked) = (1-s)^2, both within CI."""
    s, shots = 0.4, 4096
    prog = [dict(PI_PULSE) for _ in range(3)] \
        + [{'name': 'read', 'qubit': ['Q0']}]
    out = _run(sim2, prog, shots, 17, dict(leak_per_pulse=1.0,
                                           seep_per_pulse=s))
    bits = np.asarray(out['meas_bits'])[:, 0, 0]
    leaked = np.asarray(out['leaked'])[:, 0]
    se = np.sqrt(s * (1 - s) / shots)
    assert abs((bits == 0).mean() - s) < 4 * se
    want_l = (1 - s) ** 2
    se_l = np.sqrt(want_l * (1 - want_l) / shots)
    assert abs(leaked.mean() - want_l) < 4 * se_l


def test_seep_validation():
    with pytest.raises(ValueError, match='seep'):
        DeviceModel('statevec', seep_per_pulse=0.5)


def test_leakage_defeats_repetition_code():
    """The canonical QEC failure mode: a leaked data qubit reads 1
    forever, so the majority-vote round 'corrects' the healthy
    neighbours toward the error every time — logical failure rate far
    above the unleaked case at matched marginals."""
    from distributed_processor_tpu.models.repetition import (
        repetition_logical_program, independent_noise_stage,
        repetition_physics_kwargs)
    sim = Simulator(n_qubits=3)
    qchip = make_default_qchip(3)
    shots = 1024
    # leak injection: the noise stage's zero-amp pulses never excite,
    # so leak ~ p * P(|1>) never fires off them — use a real X180 on
    # the middle qubit with p_leak, which either leaks (stuck at 1) or
    # returns to 0 (X360 total over the stage + correction unused)
    noise = [{'name': 'X90', 'qubit': ['Q1']},
             {'name': 'X90', 'qubit': ['Q1']},
             {'name': 'X90', 'qubit': ['Q1']},
             {'name': 'X90', 'qubit': ['Q1']}]
    prog = repetition_logical_program(3, noise)
    mp = sim.compile(prog)
    cps = couplings_from_qchip(mp, qchip)
    model = ReadoutPhysics(sigma=0.0, p1_init=0.0, device=DeviceModel(
        'statevec', couplings=cps, leak_per_pulse=0.1))
    out = run_physics_batch(mp, model, 5, shots, max_steps=8000,
                            **repetition_physics_kwargs(3))
    assert not np.any(np.asarray(out['err']))
    leaked = np.asarray(out['leaked'])[:, 1]
    final = np.asarray(out['meas_bits'])[:, :, 1]   # post-correction
    assert 0.05 < leaked.mean() < 0.5
    # the leaked qubit still reads 1 AFTER the correction round — the
    # code cannot fix it, only mask it while the majority holds
    np.testing.assert_array_equal(final[leaked, 1],
                                  np.ones(int(leaked.sum()), final.dtype))
    # unleaked shots are fully corrected
    assert not np.any(final[~leaked])
