"""Cross-chip ICI fabric: one program's core axis sharded over the mesh.

The tentpole property is BIT-IDENTITY BY CONSTRUCTION: the cores-sharded
interpreter all_gathers the producer-side words (done/time/meas) over
the ``'cores'`` mesh axis with ``tiled=True``, so every shard sees the
same full-width arrays a single-device run computes, and every
downstream consumer (sticky/fresh/lut fproc, the sync barrier) is
elementwise or a same-order reduction over that full width.  These
tests pin that equality per output key — the fault word included — on
every golden-suite program that fits both layouts, on the lut+fproc
repetition-code workload, under vmap, and for a program whose core
count spans >= 2 devices.  Retrace budget (<= 1 trace per mesh shape)
and the MeasLUT hoisted-constant stability ride along.

The whole module skips only on a genuinely single-device host; the
skip reason records the advertised count and tools/check_junit.py
fails CI when these tests skip on a host advertising more (the
ICI-fabric mirror of the multi-device serve BAD SKIP gate).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import machine_program_from_cmds
from distributed_processor_tpu.models.default_qchip import make_default_qchip
from distributed_processor_tpu.models.golden_suite import GOLDEN_PROGRAMS
from distributed_processor_tpu.models.repetition import (
    _lut_fabric_kwargs, repetition_round_machine_program)
from distributed_processor_tpu.ops.fabric import MeasLUT
from distributed_processor_tpu.parallel import (make_cores_mesh, make_mesh,
                                                run_cores_sweep,
                                                sharded_cores_rounds,
                                                sharded_cores_simulate,
                                                sharded_cores_stat_sums)
from distributed_processor_tpu.parallel.param_sweep import \
    swept_pulse_machine_program
from distributed_processor_tpu.parallel.sweep import shard_map
from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.sim.interpreter import (
    InterpreterConfig, _program_constants, _run_batch_engine,
    cores_ineligible, cores_trace_count, program_traits, resolve_engine,
    simulate, simulate_batch, simulate_rounds)

_N_DEV = len(jax.devices())

pytestmark = [
    pytest.mark.multichip,
    pytest.mark.skipif(
        _N_DEV < 2,
        reason=f'ICI-fabric tests need >=2 devices (host advertises '
               f'{_N_DEV} device(s); off-TPU force more with '
               f'--xla_force_host_platform_device_count)'),
]


def _assert_identical(single: dict, sharded: dict, msg: str = ''):
    """Every key the sharded path returns must equal the single-device
    run bit-for-bit (the fault word included).  'steps'/'incomplete'
    are host-loop bookkeeping the sharded entry deliberately drops."""
    missing = set(single) - set(sharded) - {'steps', 'incomplete',
                                            'op_hist'}
    assert not missing, f'{msg}sharded run dropped keys: {missing}'
    for k in sorted(set(single) & set(sharded)):
        np.testing.assert_array_equal(
            np.asarray(single[k]), np.asarray(sharded[k]),
            err_msg=f'{msg}{k}: sharded != single-device')


def _golden_mp(name):
    n_qubits, thunk = GOLDEN_PROGRAMS[name]
    qchip = make_default_qchip(max(n_qubits, 2))
    return compile_to_machine(thunk(), qchip, n_qubits=n_qubits)


def _fitting_mesh(n_cores: int):
    """Largest cores-shard count that divides the program and fits the
    host, paired with dp=2 when devices allow; None when the program
    cannot shard (single core, or no divisor fits >= 2 devices)."""
    for shards in range(min(n_cores, _N_DEV), 1, -1):
        if n_cores % shards:
            continue
        n_dp = 2 if 2 * shards <= _N_DEV else 1
        return make_cores_mesh(n_cores=shards, n_dp=n_dp)
    return None


# ---------------------------------------------------------------------------
# golden suite bit-identity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('name', sorted(GOLDEN_PROGRAMS))
def test_golden_suite_sharded_bit_identity(name):
    """Every golden program that fits both layouts runs bit-identically
    sharded over the ('dp', 'cores') mesh — all output keys, the fault
    word included."""
    mp = _golden_mp(name)
    mesh = _fitting_mesh(mp.n_cores)
    if mesh is None:
        return   # single-core golden: nothing to shard (not a skip —
                 # the check_junit ICI gate treats skips as regressions)
    kw = dict(mp.static_bounds(), max_meas=16, max_resets=64)
    bits = np.random.default_rng(17).integers(
        0, 2, size=(4 * int(mesh.shape['dp']), mp.n_cores, 16))
    single = simulate_batch(
        mp, bits, cfg=InterpreterConfig(engine='generic', **kw))
    sharded = sharded_cores_simulate(mp, bits, mesh,
                                     cfg=InterpreterConfig(**kw))
    _assert_identical(single, sharded, msg=f'{name}: ')


def test_some_golden_actually_sharded():
    """At least one golden must exercise the sharded path — otherwise
    the parametrized identity test above silently passes vacuously."""
    fitted = [n for n in GOLDEN_PROGRAMS
              if _fitting_mesh(_golden_mp(n).n_cores) is not None]
    assert fitted, 'no golden program fits a >=2-shard cores mesh'


# ---------------------------------------------------------------------------
# lut + fproc repetition-code workload
# ---------------------------------------------------------------------------

def _rep_setup(n_data=3):
    mp = repetition_round_machine_program(n_data=n_data)
    kw = dict(mp.static_bounds(), max_meas=4, max_resets=4,
              **_lut_fabric_kwargs(n_data))
    return mp, kw


def test_lut_repetition_sharded_bit_identity():
    """The repetition-code round on the LUT fabric — every data core's
    measurement feeding the syndrome address, corrections fed back per
    core — is bit-identical sharded one core per device."""
    mp, kw = _rep_setup()
    mesh = _fitting_mesh(mp.n_cores)
    assert mesh is not None and int(mesh.shape['cores']) == mp.n_cores
    bits = np.random.default_rng(9).integers(
        0, 2, (4 * int(mesh.shape['dp']), mp.n_cores, 4))
    single = simulate_batch(
        mp, bits, cfg=InterpreterConfig(engine='generic', **kw))
    sharded = sharded_cores_simulate(mp, bits, mesh,
                                     cfg=InterpreterConfig(**kw))
    _assert_identical(single, sharded, msg='lut-repetition: ')
    # the workload must actually exercise the table: syndrome-dependent
    # corrections change per-shot pulse counts
    assert len(np.unique(np.asarray(single['n_pulses']))) > 1, \
        'repetition fixture fired no corrections — LUT path unexercised'


@pytest.mark.qec
def test_lut_repetition_rounds_sharded_bit_identity():
    """R syndrome rounds in ONE mesh dispatch (sharded_cores_rounds —
    the mesh composition of simulate_rounds, docs/PERF.md "Streaming
    QEC") equal the single-device rounds scan per stat, on the generic
    cores executor AND the GSPMD block executor — codes too wide for
    one device stream rounds with the same bit-identity contract."""
    mp, kw = _rep_setup()
    mesh = _fitting_mesh(mp.n_cores)
    assert mesh is not None and int(mesh.shape['cores']) == mp.n_cores
    rounds, n_dp = 3, int(mesh.shape['dp'])
    mb = np.random.default_rng(17).integers(
        0, 2, (rounds, 4 * n_dp, mp.n_cores, 4), dtype=np.int32)
    single = simulate_rounds(
        mp, mb, cfg=InterpreterConfig(engine='generic', **kw))
    for eng in ('generic', 'block'):
        sharded = sharded_cores_rounds(
            mp, mb, mesh, cfg=InterpreterConfig(engine=eng, **kw))
        _assert_identical(single, sharded,
                          msg=f'lut-repetition rounds[{eng}]: ')


def test_sharded_stat_sums_match_host_reference():
    """The collective-reduced statistics equal host-side folds of the
    full per-shot outputs (deterministic all_gather concat, not a
    float reduction)."""
    mp, kw = _rep_setup()
    mesh = _fitting_mesh(mp.n_cores)
    bits = np.random.default_rng(23).integers(
        0, 2, (4 * int(mesh.shape['dp']), mp.n_cores, 4))
    full = simulate_batch(
        mp, bits, cfg=InterpreterConfig(engine='generic', **kw))
    sums = sharded_cores_stat_sums(mp, bits, mesh,
                                   cfg=InterpreterConfig(**kw))
    np.testing.assert_array_equal(
        np.asarray(sums['pulse_sum']),
        np.asarray(full['n_pulses']).sum(axis=0))
    np.testing.assert_array_equal(
        np.asarray(sums['qclk_sum']),
        np.asarray(full['qclk']).sum(axis=0))
    assert int(sums['err_shots']) == int(
        np.sum(np.any(np.asarray(full['err']) != 0, axis=1)))
    assert not np.any(np.asarray(sums['fault_shots']))


def test_run_cores_sweep_driver():
    """The batched sweep driver over the cores mesh folds the same
    statistics the one-call path returns."""
    mp, kw = _rep_setup()
    mesh = _fitting_mesh(mp.n_cores)
    batch = 4 * int(mesh.shape['dp'])
    res = run_cores_sweep(mp, total_shots=2 * batch, batch=batch,
                          mesh=mesh, key=3, **kw)
    assert res['shots'] == 2 * batch and res['engine'] == 'generic'
    assert res['mean_pulses'].shape == (mp.n_cores,)
    assert set(res['fault_shots'].values()) == {0}


# ---------------------------------------------------------------------------
# vmap composition + many-core span + retrace budget
# ---------------------------------------------------------------------------

def test_vmap_generic_matches_sharded():
    """The sharded fabric equals the generic engine even when the
    reference is vmapped over a leading group axis — the identity is a
    property of the program, not of one batching layout."""
    mp, kw = _rep_setup()
    mesh = _fitting_mesh(mp.n_cores)
    cfg = InterpreterConfig(engine='generic', **kw)
    soa, spc, interp, sync_part = _program_constants(mp, cfg)
    traits = program_traits(mp)
    B = 2 * int(mesh.shape['dp'])
    bits = np.random.default_rng(31).integers(
        0, 2, size=(3, B, mp.n_cores, 4)).astype(np.int32)

    def gen(mb):
        return _run_batch_engine(soa, spc, interp, sync_part, mb, cfg,
                                 mp.n_cores, engine='generic',
                                 traits=traits)

    vm = jax.jit(jax.vmap(gen))(bits)
    for g in range(bits.shape[0]):
        sharded = sharded_cores_simulate(
            mp, bits[g], mesh,
            cfg=InterpreterConfig(**kw))
        for k in sorted(set(sharded) & set(vm)):
            np.testing.assert_array_equal(
                np.asarray(vm[k])[g], np.asarray(sharded[k]),
                err_msg=f'group {g} {k}: vmapped generic != sharded')


def test_many_cores_span_devices():
    """A program with more cores than one device's carry budget holds
    runs sharded over >= 2 devices, per-stat bit-identical to the
    single-device generic engine (the acceptance case)."""
    shards = 4 if _N_DEV >= 4 else 2
    n_cores = 2 * shards
    mp = swept_pulse_machine_program(n_cores)
    n_dp = 2 if 2 * shards <= _N_DEV else 1
    mesh = make_cores_mesh(n_cores=shards, n_dp=n_dp)
    kw = dict(mp.static_bounds(), max_meas=2, max_resets=2)
    rng = np.random.default_rng(41)
    bits = rng.integers(0, 2, (2 * n_dp, n_cores, 2))
    regs = np.zeros((2 * n_dp, n_cores, 16), np.int32)
    regs[..., 0] = rng.integers(0, 1 << 16, (2 * n_dp, n_cores))
    single = simulate_batch(mp, bits, init_regs=regs,
                            cfg=InterpreterConfig(engine='generic', **kw))
    sharded = sharded_cores_simulate(mp, bits, mesh, init_regs=regs,
                                     cfg=InterpreterConfig(**kw))
    _assert_identical(single, sharded, msg=f'{n_cores}-core span: ')


def test_retrace_budget_per_mesh_shape():
    """Two same-shape programs through the same mesh share ONE sharded
    trace: the program tensor is a traced argument, so the executor
    cache keys only on (mesh, cfg, traits)."""
    def build(amp):
        cores = []
        for _ in range(2):
            cores.append([isa.pulse_cmd(freq_word=1, amp_word=amp,
                                        env_word=(2 << 12), cfg_word=2,
                                        cmd_time=10),
                          isa.sync(3),
                          isa.done_cmd()])
        return machine_program_from_cmds(cores)

    mp_a, mp_b = build(0x1111), build(0x7777)
    mesh = make_cores_mesh(n_cores=2, n_dp=1)
    kw = dict(mp_a.static_bounds(), max_meas=2, max_resets=2)
    bits = np.zeros((2, 2, 2), np.int32)
    n0 = cores_trace_count()
    out_a = sharded_cores_simulate(mp_a, bits, mesh,
                                   cfg=InterpreterConfig(**kw))
    n1 = cores_trace_count()
    out_b = sharded_cores_simulate(mp_b, bits, mesh,
                                   cfg=InterpreterConfig(**kw))
    n2 = cores_trace_count()
    assert n1 - n0 <= 1, 'more than one trace for one mesh shape'
    assert n2 - n1 == 0, 'second same-shape program retraced'
    assert not np.array_equal(np.asarray(out_a['rec_amp']),
                              np.asarray(out_b['rec_amp'])), \
        'distinct programs produced identical pulse records — the ' \
        'program tensor is being baked into the trace'


# ---------------------------------------------------------------------------
# MeasLUT: hoisted constants + sharded table-gather
# ---------------------------------------------------------------------------

def _demo_lut():
    mask = (True, False, True)
    table = tuple((a ^ 0b101) & 0b111 for a in range(4))
    return MeasLUT(mask, table)


def test_meas_lut_call_retrace_stable():
    """__call__ is retrace-stable under jit: the address weights and
    bit shifts are construction-time jnp constants, so repeated calls
    with fresh same-shape arrays hit one trace."""
    lut = _demo_lut()
    traces = []

    @jax.jit
    def f(b):
        traces.append(1)
        return lut(b)

    rng = np.random.default_rng(5)
    a = f(rng.integers(0, 2, (4, 3)).astype(np.int32))
    b = f(rng.integers(0, 2, (4, 3)).astype(np.int32))
    assert len(traces) == 1, 'MeasLUT.__call__ retraced on second call'
    assert a.shape == b.shape == (4, 3)


def test_meas_lut_address_reference():
    """Hoisted-weight addressing equals the bit-by-bit reference."""
    lut = _demo_lut()
    bits = np.random.default_rng(6).integers(0, 2, (8, 3))
    addr = np.asarray(lut.address(bits))
    want = bits[:, 0] + 2 * bits[:, 2]      # masked cores 0, 2 LSB-first
    np.testing.assert_array_equal(addr, want)
    out = np.asarray(lut(bits))
    entry = np.asarray(lut.table)[want]
    np.testing.assert_array_equal(
        out, (entry[:, None] >> np.arange(3)) & 1)


def test_meas_lut_sharded_call_identity():
    """sharded_call on bits sharded over a 'cores' mesh axis returns
    the same full-width outputs as the replicated table gather."""
    n_dev = 2
    mesh = make_cores_mesh(n_cores=n_dev, n_dp=1)
    n_cores = 2 * n_dev
    mask = (True,) * n_cores
    table = tuple((a * 5) % (1 << n_cores) for a in range(1 << n_cores))
    lut = MeasLUT(mask, table)
    bits = np.random.default_rng(7).integers(
        0, 2, (8, n_cores)).astype(np.int32)

    def local(b):
        return lut.sharded_call(b, 'cores', axis=-1)

    fn = jax.jit(shard_map(local, mesh=mesh, in_specs=(P(None, 'cores'),),
                           out_specs=P(None, None), check_vma=False))
    np.testing.assert_array_equal(np.asarray(fn(bits)),
                                  np.asarray(lut(bits)))


# ---------------------------------------------------------------------------
# eligibility ladder: every blocker is named loudly
# ---------------------------------------------------------------------------

def test_cores_axis_blockers_named():
    mp, kw = _rep_setup()
    base = InterpreterConfig(cores_axis='cores', **kw)
    assert cores_ineligible(mp, base) is None
    assert resolve_engine(mp, base) == 'generic'
    # engine='block' is cores-ELIGIBLE since the timestamped fproc
    # fabric: the GSPMD block executor shards the boundary-step
    # gathers (docs/PERF.md "Feedback on the fast engines"); 'auto'
    # stays on the generic collective step
    blk = InterpreterConfig(cores_axis='cores',
                            **dict(kw, engine='block'))
    assert cores_ineligible(mp, blk) is None
    assert resolve_engine(mp, blk) == 'block'
    assert resolve_engine(
        mp, InterpreterConfig(cores_axis='cores',
                              **dict(kw, engine='auto'))) == 'generic'
    for bad, needle in [
            (dict(engine='pallas'), 'ineligible'),
            (dict(engine='fused'), 'ineligible'),
            (dict(straightline=True), 'ineligible'),
            (dict(trace=True), 'ineligible'),
            (dict(physics=True), 'epoch resolver'),
            (dict(engine='block', trace=True), 'block-ineligible')]:
        cfg = InterpreterConfig(cores_axis='cores', **dict(kw, **bad))
        reason = cores_ineligible(mp, cfg)
        assert reason, f'{bad} must be cores-ineligible'
        with pytest.raises(ValueError, match=needle):
            resolve_engine(mp, cfg)


def test_single_device_entry_points_reject_cores_axis():
    mp, kw = _rep_setup()
    cfg = InterpreterConfig(cores_axis='cores', **kw)
    bits = np.zeros((2, mp.n_cores, 4), np.int32)
    with pytest.raises(ValueError, match='sharded_cores_simulate'):
        simulate_batch(mp, bits, cfg=cfg)
    with pytest.raises(ValueError, match='sharded_cores_simulate'):
        simulate(mp, bits[0], cfg=cfg)


def test_sweep_entry_validates_mesh_and_divisibility():
    mp, kw = _rep_setup()
    bits = np.zeros((2, mp.n_cores, 4), np.int32)
    with pytest.raises(ValueError, match="'cores'"):
        sharded_cores_simulate(mp, bits, make_mesh(n_dp=2),
                               cfg=InterpreterConfig(**kw))
    mesh = make_cores_mesh(n_cores=2, n_dp=1)
    with pytest.raises(ValueError, match='not divisible'):
        sharded_cores_simulate(mp, bits, mesh,
                               cfg=InterpreterConfig(**kw))


def test_physics_sweep_rejects_cores_mesh():
    from distributed_processor_tpu.parallel import run_physics_sweep
    from distributed_processor_tpu.sim.physics import ReadoutPhysics
    mp, kw = _rep_setup()
    mesh = make_cores_mesh(n_cores=_N_DEV, n_dp=1)
    with pytest.raises(ValueError, match='epoch resolver'):
        run_physics_sweep(mp, ReadoutPhysics(sigma=0.05), 4, 4,
                          mesh=mesh, max_steps=256, max_pulses=8,
                          max_meas=4, max_resets=4)


def test_service_rejects_cores_axis():
    from distributed_processor_tpu.serve import ExecutionService
    from distributed_processor_tpu.serve.service import _normalize_cfg
    cfg = InterpreterConfig(cores_axis='cores', max_steps=64,
                            max_pulses=4)
    with pytest.raises(ValueError, match='cannot serve'):
        ExecutionService(cfg=cfg)
    with pytest.raises(ValueError, match='cannot serve'):
        _normalize_cfg(cfg, 16)
