"""Fleet-wide observability (docs/OBSERVABILITY.md "Fleet observability").

The cross-process observability contract, pinned here:

* **Clock alignment is bounded**: the NTP-style gossip estimator's
  offset is wrong by at most half the RTT of its best sample, and the
  min-RTT sample wins — synthetic probes with a known true offset pin
  the bound exactly.
* **The wire carries the router's sampling decision**: replicas trace
  exactly the requests the router sampled even with their own local
  sampling OFF, and the replica-side spans come back piggybacked and
  stitched into the router's context, monotone inside the wire window.
* **Merged metrics are exact**: ``FleetRouter.prometheus_text()``
  re-exposes every replica's ``serve.*`` series under a ``replica``
  label, and the unlabeled rollup equals the sum of the labeled
  series pulled directly over the wire.
* **The federated flight ring survives**: ``merged_flight`` produces
  one time-aligned stream with ``origin`` and ``t_router`` on every
  event, plus per-ring truncation (``dropped``) accounting.
* **Telemetry names are frozen**: fleet-level metric and span names
  are pinned by literal manifests — renaming one breaks dashboards,
  so it must break this test first.

This module is listed in tools/check_junit.py NO_SKIP_MODULES: it runs
on localhost TCP + the forced CPU backend with no hardware dependency.
"""

import importlib.util
import json
import pathlib
import re
import time

import numpy as np
import pytest

from distributed_processor_tpu.obs import (ClockOffsetEstimator,
                                           FlightRecorder,
                                           MetricsRegistry,
                                           STAGE_ORDER, Tracer,
                                           escape_label_value,
                                           merged_prometheus_text,
                                           prometheus_snapshot_lines)
from distributed_processor_tpu.serve import RetryPolicy
from distributed_processor_tpu.serve.benchmark import _workload
from distributed_processor_tpu.serve.fleet import Fleet

pytestmark = [pytest.mark.serve, pytest.mark.fleet]

_TOOLS = pathlib.Path(__file__).resolve().parents[1] / 'tools'


def _load_traceview():
    spec = importlib.util.spec_from_file_location(
        'traceview', _TOOLS / 'traceview.py')
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


traceview = _load_traceview()


@pytest.fixture(autouse=True)
def _serve_thread_leak_probe():
    """Override the per-test conftest probe: the module-scoped Fleet
    below keeps router/wire threads alive across tests BY DESIGN.  The
    leak boundary moves to module teardown (the autouse module fixture
    next), after the fleet has shut down."""
    yield


@pytest.fixture(autouse=True, scope='module')
def _fleet_thread_boundary():
    """After the module-scoped fleet shuts down, every dproc-serve*
    thread must be joined — prints the junit-gated marker otherwise."""
    import threading
    yield
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        leaked = sorted(t.name for t in threading.enumerate()
                        if t.name.startswith('dproc-serve')
                        and t.is_alive())
        if not leaked:
            return
        time.sleep(0.05)
    print(f'SERVICE THREAD LEAK: {leaked}')


# ---------------------------------------------------------------------------
# clock-offset estimator (obs/clock.py)
# ---------------------------------------------------------------------------

def test_clock_offset_estimator_bounded_skew():
    """Synthetic probes against a remote clock running exactly D ahead:
    however asymmetric each round trip, the estimate is within rtt/2 of
    the truth, and the min-RTT sample's (tightest) bound wins."""
    D = 0.25                  # true remote - local offset, seconds
    est = ClockOffsetEstimator()
    # (rtt, where inside the rtt the remote stamped its clock)
    probes = [(0.020, 0.9), (0.008, 0.1), (0.002, 0.8), (0.050, 0.5)]
    t = 100.0
    for rtt, frac in probes:
        est.add_sample(t, t + frac * rtt + D, t + rtt)
        t += 1.0
    min_rtt = min(rtt for rtt, _ in probes)
    assert est.n == len(probes)
    # the reported bound is half the best sample's RTT...
    assert est.uncertainty_s == pytest.approx(0.5 * min_rtt)
    # ...and the estimate honours it against the known truth
    assert abs(est.offset - D) <= est.uncertainty_s + 1e-12
    # mapping round-trips exactly
    assert est.to_local(est.to_remote(42.0)) == pytest.approx(42.0)


def test_clock_offset_estimator_empty_and_min_rtt():
    est = ClockOffsetEstimator()
    assert est.n == 0
    assert est.offset == 0.0
    assert est.uncertainty_s == float('inf')
    # a later, tighter probe displaces a sloppier earlier one
    est.add_sample(0.0, 0.55, 1.0)          # rtt 1.0, offset 0.05
    est.add_sample(10.0, 10.1005, 10.001)   # rtt 1ms, offset ~0.1
    assert est.uncertainty_s == pytest.approx(0.0005)
    assert est.offset == pytest.approx(0.1, abs=0.001)


# ---------------------------------------------------------------------------
# deterministic wire sampling (obs/trace.py)
# ---------------------------------------------------------------------------

def test_tracer_wire_sampling_is_deterministic():
    """Two processes holding the same rate must agree on the same
    trace ids — the router's decision rides the wire and the replica
    re-derives nothing, but the pure function still has to match."""
    a, b = Tracer(sample=0.25), Tracer(sample=0.25)
    for tid in range(32):
        assert a.sampled(tid) == b.sampled(tid) == (tid % 4 == 0)
    off = Tracer(sample=0.0)
    assert not any(off.sampled(t) for t in range(32))
    assert off.maybe_start() is None
    full = Tracer(sample=1.0)
    assert all(full.sampled(t) for t in range(32))


def test_tracer_set_sample_keeps_retention_and_forced_start():
    tr = Tracer(sample=0.0, keep=8)
    # forced start (the wire-carried decision): retained regardless of
    # the local rate
    ctx = tr.start(7)
    assert ctx.trace_id == 7 and tr.contexts() == [ctx]
    tr.set_sample(1.0)
    assert tr.contexts() == [ctx]       # retention survives the retune
    assert tr.maybe_start() is not None


# ---------------------------------------------------------------------------
# flight-recorder truncation accounting (obs/recorder.py)
# ---------------------------------------------------------------------------

def test_flight_recorder_dropped_counter():
    """A wrapped ring is a TRUNCATED incident timeline — the dump must
    say so, not read as a quiet one."""
    fr = FlightRecorder(capacity=4)
    assert fr.dropped == 0
    for i in range(10):
        fr.record('ev', i=i)
    assert fr.recorded == 10
    assert fr.dropped == 6
    assert len(fr.events()) == 4
    assert [e['i'] for e in fr.events()] == [6, 7, 8, 9]
    doc = fr.to_json()
    assert doc['recorded'] == 10 and doc['dropped'] == 6
    assert json.loads(json.dumps(doc)) == doc      # JSON-clean


# ---------------------------------------------------------------------------
# Prometheus escaping + merged exposition (obs/metrics.py)
# ---------------------------------------------------------------------------

def test_escape_label_value():
    assert escape_label_value('plain') == 'plain'
    assert escape_label_value('a\\b') == 'a\\\\b'
    assert escape_label_value('a"b') == 'a\\"b'
    assert escape_label_value('a\nb') == 'a\\nb'
    assert escape_label_value('\\"\n') == '\\\\\\"\\n'
    # and through the label-rendering path end to end
    lines = prometheus_snapshot_lines(
        {'counters': {'serve.submitted': 1}},
        labels={'replica': 'r"0\\x\n'})
    assert 'serve_submitted{replica="r\\"0\\\\x\\n"} 1' in lines


def test_merged_prometheus_text_rollup_and_labels():
    ra, rb = MetricsRegistry(), MetricsRegistry()
    ra.inc('serve.submitted', 2)
    rb.inc('serve.submitted', 3)
    rb.inc('serve.only_b', 1)
    ra.set_gauge('serve.queue_depth', 4.0)
    ra.observe('serve.latency_ms', 1.0)
    rb.observe('serve.latency_ms', 2.0)
    lines = merged_prometheus_text(
        {'r0': ra.snapshot(), 'r1': rb.snapshot()}, label='replica')
    # counters: one TYPE line, an unlabeled rollup = the sum, then one
    # labeled series per replica (absent replicas omitted, not zeroed)
    assert lines.count('# TYPE serve_submitted counter') == 1
    assert 'serve_submitted 5' in lines
    assert 'serve_submitted{replica="r0"} 2' in lines
    assert 'serve_submitted{replica="r1"} 3' in lines
    assert 'serve_only_b{replica="r1"} 1' in lines
    assert not any(ln.startswith('serve_only_b{replica="r0"}')
                   for ln in lines)
    # gauges never roll up (summing queue depths across processes is a
    # lie); labeled series only
    assert 'serve_queue_depth{replica="r0"} 4.0' in lines
    assert not any(re.fullmatch(r'serve_queue_depth [\d.]+', ln)
                   for ln in lines)
    # histograms: ladders agree here, so the rollup sums buckets/n/sum
    assert 'serve_latency_ms_count 2' in lines
    assert 'serve_latency_ms_sum 3.0' in lines
    assert 'serve_latency_ms_count{replica="r0"} 1' in lines
    assert 'serve_latency_ms_count{replica="r1"} 1' in lines


def test_merged_histogram_rollup_skipped_on_ladder_mismatch():
    ra, rb = MetricsRegistry(), MetricsRegistry()
    ra.observe('serve.latency_ms', 1.0)
    rb.observe('serve.latency_ms', 2.0, buckets=(1.0, 10.0))
    lines = merged_prometheus_text(
        {'r0': ra.snapshot(), 'r1': rb.snapshot()})
    # per-replica series survive; no unlabeled (summed) rollup exists
    assert 'serve_latency_ms_count{replica="r0"} 1' in lines
    assert 'serve_latency_ms_count{replica="r1"} 1' in lines
    assert not any(re.fullmatch(r'serve_latency_ms_count \d+', ln)
                   for ln in lines)


# ---------------------------------------------------------------------------
# traceview rejects empty/invalid traces (tools/traceview.py)
# ---------------------------------------------------------------------------

def test_traceview_stage_order_matches_obs():
    """tools/traceview.py carries a copy of the canonical stage order
    (it must stay importable without the package); keep them in sync."""
    assert tuple(traceview.STAGE_ORDER) == tuple(STAGE_ORDER)


@pytest.mark.parametrize('content,msg', [
    ('{not json', 'not valid JSON'),
    ('[1, 2]', 'expected a Chrome Trace object'),
    ('{"other": 1}', 'no "traceEvents" array'),
    ('{"traceEvents": []}', 'zero events'),
])
def test_traceview_summarize_rejects(tmp_path, content, msg):
    p = tmp_path / 'bad.json'
    p.write_text(content)
    with pytest.raises(ValueError, match=re.escape(msg)):
        traceview.summarize(str(p))


def test_traceview_main_exits_nonzero_on_empty(tmp_path, capsys):
    p = tmp_path / 'empty.json'
    p.write_text('{"traceEvents": []}')
    assert traceview.main([str(p)]) == 1
    assert 'traceview: cannot read' in capsys.readouterr().err
    assert traceview.main([str(tmp_path / 'absent.json')]) == 1


# ---------------------------------------------------------------------------
# frozen fleet telemetry manifests
# ---------------------------------------------------------------------------

# every router-exposed fleet_* metric, frozen (Prometheus names):
# renaming one breaks dashboards, so it must break this test first
_FLEET_COUNTERS = {
    'fleet_submitted', 'fleet_completed', 'fleet_failed',
    'fleet_retries', 'fleet_retry_exhausted', 'fleet_failovers',
    'fleet_replica_down', 'fleet_replica_up', 'fleet_gossip_stale',
    'fleet_breaker_trips', 'fleet_readmissions', 'fleet_slo_breaches',
}
_FLEET_GAUGES = {
    'fleet_n_replicas', 'fleet_n_routable', 'fleet_parked',
    'fleet_heartbeat_age_ms',
}
# every span name a stitched fleet trace may contain, frozen: the
# router-side stages/hops plus the replica-side service taxonomy
_FLEET_SPAN_NAMES = set(STAGE_ORDER) | {
    'failover', 'park', 'unpark', 'steal', 'migrate', 'retry',
    'retry_exhausted', 'requeue', 'chaos', 'shed', 'batch_error',
    'done',
}
_ROUTER_CORE_SPANS = {'submit', 'route', 'wire.send', 'wire.await'}


def _prom_series(text: str, name: str) -> dict:
    """{replica_label_or_None: value} for one exact metric name."""
    out = {}
    pat = re.compile(
        rf'^{re.escape(name)}(?:{{replica="([^"]*)"}})? (\S+)$')
    for ln in text.splitlines():
        m = pat.match(ln)
        if m:
            out[m.group(1)] = float(m.group(2))
    return out


def test_fleet_metric_manifest_is_byte_compatible():
    """An empty router (no replicas, no traffic) must already expose
    every frozen fleet_* series — dashboards key on the names existing
    from boot, not appearing after the first failover."""
    from distributed_processor_tpu.serve import FleetRouter
    with FleetRouter(name='manifest') as router:
        text = router.prometheus_text(timeout_s=1.0)
    for pn in sorted(_FLEET_COUNTERS):
        assert f'# TYPE {pn} counter' in text, pn
        assert _prom_series(text, pn), pn
    for pn in sorted(_FLEET_GAUGES - {'fleet_heartbeat_age_ms'}):
        assert f'# TYPE {pn} gauge' in text, pn
        assert _prom_series(text, pn), pn
    # per-replica gauges: TYPE line always present, series per replica
    assert '# TYPE fleet_heartbeat_age_ms gauge' in text


# ---------------------------------------------------------------------------
# live fleet: replica processes on localhost TCP
# ---------------------------------------------------------------------------

N_REQS = 6


@pytest.fixture(scope='module')
def workload():
    return _workload(N_REQS, 2, 2, 4, seed=7)


@pytest.fixture(scope='module')
def fleet(workload):
    mps, bits, cfg = workload
    # trace_sample goes to the ROUTER ONLY: the replicas' local
    # samplers stay off, so every replica-side span in the tests below
    # exists because the router's decision rode the wire (the tentpole
    # contract), not because the replica sampled on its own
    with Fleet(2,
               service={'max_batch_programs': 4, 'max_wait_ms': 5.0,
                        'max_queue': 256},
               env={'XLA_FLAGS':
                    '--xla_force_host_platform_device_count=1'},
               router_kwargs={
                   'retry_policy': RetryPolicy(max_attempts=10,
                                               backoff_s=0.05,
                                               max_backoff_s=1.0),
                   'trace_sample': 1.0,
                   'trace_keep': 64,
                   # impossible budget + tiny warm-up window: the SLO
                   # watch must breach as soon as traffic flows
                   'slo_budgets': {'total': {'p99_ms': 1e-4}},
                   'slo_min_samples': 4,
               }) as f:
        for rid in f.replica_ids():
            f.router.call_replica(
                rid, 'submit',
                dict(mp=mps[0], meas_bits=bits[0], cfg=cfg),
                timeout_s=600.0)
        yield f


def _run_workload(fleet, workload):
    mps, bits, cfg = workload
    handles = [fleet.submit(mps[i], bits[i], cfg=cfg)
               for i in range(N_REQS)]
    for h in handles:
        h.result(timeout=300)


def _stitched_contexts(fleet):
    """Retained router contexts that completed a full wire round."""
    return [c for c in fleet.router.trace_contexts()
            if any(s['name'] == 'wire.await' for s in c.spans)]


def test_wire_trace_stitching_monotone(fleet, workload, tmp_path):
    """The acceptance shape: a sampled request's context holds the
    router-side spans AND the replica-side spans (tagged with the
    serving replica), clock-aligned inside the wire window so the
    waterfall is monotone, and the export drives traceview."""
    _run_workload(fleet, workload)
    ctxs = _stitched_contexts(fleet)
    assert ctxs, 'no stitched contexts at trace_sample=1.0'
    rids = set(fleet.replica_ids())
    saw_replica_side = False
    for ctx in ctxs:
        names = [s['name'] for s in ctx.spans]
        assert set(names) <= _FLEET_SPAN_NAMES, set(names) - \
            _FLEET_SPAN_NAMES
        assert _ROUTER_CORE_SPANS <= set(names)
        wire = [s for s in ctx.spans if s['name'] == 'wire.await']
        ws = min(s['t0'] for s in wire)
        we = max(s['t1'] for s in wire)
        for s in ctx.spans:
            rid = s['args'].get('replica')
            if rid is None:
                continue
            saw_replica_side = True
            assert rid in rids
            assert s['name'] in _FLEET_SPAN_NAMES
            # clamped into the wire window => monotone ordering
            # against the router-side spans is guaranteed
            assert ws - 1e-9 <= s['t0'] <= we + 1e-9
            if s['t1'] is not None:
                assert s['t0'] <= s['t1'] <= we + 1e-9
    assert saw_replica_side, \
        'no replica-side spans piggybacked back over the wire'
    # the dump round-trips through the waterfall tool: the fleet pid
    # row exists and wire.await carries its wire_ms column
    out = tmp_path / 'fleet_trace.json'
    n = fleet.dump_trace(str(out))
    assert n > 0
    summary = traceview.summarize(str(out))
    assert summary['events'] == n
    assert summary['processes'] >= 1
    stages = {s['stage']: s for s in summary['stages']}
    assert 'wire.await' in stages
    assert 'wire_p50_ms' in stages['wire.await']


def test_router_stage_histograms_feed_stats(fleet, workload):
    _run_workload(fleet, workload)
    s = fleet.stats()
    assert s['completed'] >= N_REQS
    # stitched per-stage histograms observed replica-side stages too
    text = fleet.prometheus_text()
    assert '# TYPE fleet_stage_wire_await_ms histogram' in text
    assert _prom_series(text, 'fleet_stage_wire_await_ms_count')


def test_prometheus_per_replica_sums_match_direct(fleet, workload):
    """The acceptance criterion: the labeled serve.* series equal the
    snapshots pulled directly from each replica, and the unlabeled
    rollup is exactly their sum."""
    _run_workload(fleet, workload)
    text = fleet.prometheus_text()
    direct = {rid: fleet.router.call_replica(rid, 'fleet-metrics',
                                             timeout_s=30.0)['metrics']
              for rid in fleet.replica_ids()}
    series = _prom_series(text, 'serve_submitted')
    assert set(series) == set(direct) | {None}
    for rid, snap in direct.items():
        want = snap['counters'].get('serve.submitted', 0)
        # the direct pull ran after the exposition pull; monotone
        # counters can only have grown in between
        assert series[rid] <= want
        assert want - series[rid] <= N_REQS
    assert series[None] == sum(v for rid, v in series.items()
                               if rid is not None)
    # the two replicas between them served everything this module sent
    assert series[None] > 0


def test_gossip_op_carries_flight_digest_and_clock(fleet):
    """The gossip reply is the observability piggyback: stats + the
    replica's monotonic stamp (clock probe) + a flight-ring digest."""
    for rid in fleet.replica_ids():
        resp = fleet.router.call_replica(rid, 'gossip', timeout_s=30.0)
        assert {'stats', 'mono', 'flight'} <= set(resp)
        assert isinstance(resp['mono'], float)
        fl = resp['flight']
        assert {'recorded', 'dropped', 'counts', 'tail'} <= set(fl)
        assert fl['dropped'] >= 0
    # the router-side estimators converge off the same heartbeats:
    # same-host clocks share an epoch, so offsets are RTT-scale tiny
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        offs = fleet.router.clock_offsets()
        if set(offs) == set(fleet.replica_ids()):
            break
        time.sleep(0.05)
    assert set(offs) == set(fleet.replica_ids()), offs
    for rid, o in offs.items():
        assert o['samples'] > 0
        assert o['uncertainty_s'] < float('inf')
        assert abs(o['offset_s']) <= max(1.0, 10 * o['uncertainty_s'])


def test_merged_flight_is_time_aligned(fleet, workload):
    _run_workload(fleet, workload)
    mf = fleet.merged_flight(pull=True)
    assert {'router', 'replicas', 'clock_offsets', 'events'} <= set(mf)
    assert mf['router']['recorded'] >= 0
    assert mf['router']['dropped'] >= 0
    assert set(mf['replicas']) == set(fleet.replica_ids())
    for rid, ring in mf['replicas'].items():
        assert ring['source'] in ('pull', 'gossip')
        assert ring['recorded'] >= 0 and ring['dropped'] >= 0
    origins = {e['origin'] for e in mf['events']}
    assert 'router' in origins, mf['router']
    for e in mf['events']:
        assert 'origin' in e and 't_router' in e and 'kind' in e
    aligned = [e['t_router'] for e in mf['events']
               if e['t_router'] is not None]
    assert aligned == sorted(aligned)
    # the merged doc is what servechaos --flight-out dumps: JSON-clean
    json.dumps(mf)


def test_slo_watch_breaches_on_impossible_budget(fleet, workload):
    """The module budget (p99 <= 0.1 µs on 'total') cannot be met by
    any real round trip: after enough samples and a gossip tick the
    watch must have fired — counter, stats detail, and flight event."""
    _run_workload(fleet, workload)
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        s = fleet.stats()
        if s.get('slo_breaches', 0) >= 1:
            break
        time.sleep(0.05)
    assert s.get('slo_breaches', 0) >= 1, s
    slo = s['slo']
    assert 'total' in slo and slo['total']['breached']
    assert slo['total']['p99_ms'] > 0
    assert slo['total']['samples'] >= 4
    kinds = [e['kind'] for e in fleet.router.flight_recorder.events()]
    assert 'slo_breach' in kinds
    # and the breach is visible on the exposition
    series = _prom_series(fleet.prometheus_text(),
                          'fleet_slo_breaches')
    assert series[None] >= 1
