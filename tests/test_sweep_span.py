"""Device-resident sweep spans (``span=`` on both sweep drivers).

The contract under test: folding ``span`` batches into ONE dispatch —
a ``lax.scan`` over batch indices with a donated on-device stats carry
(``sim.interpreter.make_span_runner``, driven pipelined by
``parallel.sweep.run_spanned``) — is BIT-IDENTICAL to the per-batch
host loop: the same ``fold_in(key, i)`` stream folds into the same
int32 sums, for spans that divide or straddle the batch count, across
checkpoint resume points landing mid-span or on span edges, on both
engines, and under a dp mesh.  Checkpoints carry no span: they are
interchangeable across span choices.
"""

import json

import numpy as np
import pytest

from distributed_processor_tpu.models import (active_reset,
                                              make_default_qchip,
                                              rb_ensemble)
from distributed_processor_tpu.parallel import (make_mesh,
                                                run_multi_sweep,
                                                run_physics_sweep)
from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.sim.interpreter import span_trace_count
from distributed_processor_tpu.sim.physics import ReadoutPhysics
from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.utils.results import (SweepAccumulator,
                                                     load_results)


def _physics():
    sim = Simulator(n_qubits=2)
    mp = sim.compile(active_reset(['Q0', 'Q1']))
    model = ReadoutPhysics(sigma=0.01, p1_init=0.5)
    kw = dict(max_steps=mp.n_instr * 4 + 64, max_pulses=8, max_meas=2)
    return mp, model, kw


def _ensemble(n_seqs, seed):
    qchip = make_default_qchip(2)
    return [compile_to_machine(active_reset(['Q0', 'Q1']) + prog, qchip,
                               n_qubits=2)
            for prog in rb_ensemble(['Q0', 'Q1'], 1, n_seqs, seed=seed)]


def _assert_same(a: dict, b: dict, ctx=''):
    assert set(a) == set(b), ctx
    for k in a:
        if isinstance(a[k], dict):
            _assert_same(a[k], b[k], ctx=f'{ctx}{k}.')
        else:
            np.testing.assert_array_equal(
                np.asarray(a[k]), np.asarray(b[k]), err_msg=f'{ctx}{k}')


def test_physics_span_parity():
    """Exact stats equality vs the host loop for a span that is 1, one
    that straddles the batch count, and one equal to it (4 batches)."""
    mp, model, kw = _physics()
    loop = run_physics_sweep(mp, model, 64, 16, key=5, **kw)
    for span in (1, 3, 4):
        sp = run_physics_sweep(mp, model, 64, 16, key=5, span=span, **kw)
        _assert_same(loop, sp, f'span={span}: ')


def test_physics_span_parity_both_engines():
    """The physics path honors cfg.straightline; spans must be
    bit-identical to the loop on BOTH engines."""
    mp, model, kw = _physics()
    for sl in (False, True):
        loop = run_physics_sweep(mp, model, 48, 16, key=9,
                                 straightline=sl, **kw)
        sp = run_physics_sweep(mp, model, 48, 16, key=9, span=2,
                               straightline=sl, **kw)
        _assert_same(loop, sp, f'straightline={sl}: ')


def test_multi_span_parity_and_err_shots():
    """Ensemble driver: spanned == loop exactly, and the result carries
    the per-program err_shots numerator behind err_rate."""
    mps = _ensemble(2, seed=41)
    loop = run_multi_sweep(mps, total_shots=16, batch=4, p1=0.5, key=3,
                           max_meas=2, max_resets=2)
    assert loop['err_shots'].shape == (2,)
    assert np.issubdtype(loop['err_shots'].dtype, np.integer)
    np.testing.assert_array_equal(loop['err_shots'],
                                  loop['err_rate'] * loop['shots'])
    for span in (3, 4):
        sp = run_multi_sweep(mps, total_shots=16, batch=4, p1=0.5,
                             key=3, span=span, max_meas=2, max_resets=2)
        _assert_same(loop, sp, f'span={span}: ')


def test_span_checkpoint_resume(tmp_path):
    """Resume landing mid-span and on a span edge both reproduce the
    uncheckpointed loop exactly, and a span-written checkpoint resumes
    under a different span (span is not sweep identity)."""
    mp, model, kw = _physics()
    full = run_physics_sweep(mp, model, 128, 16, key=7, **kw)
    # 5 batches is mid-span for span=3 (grid cells [0,3) [3,6) [6,8))
    ck = str(tmp_path / 'mid.npz')
    run_physics_sweep(mp, model, 80, 16, key=7, span=3, checkpoint=ck,
                      checkpoint_every=1, **kw)
    resumed = run_physics_sweep(mp, model, 128, 16, key=7, span=3,
                                checkpoint=ck, checkpoint_every=1, **kw)
    _assert_same(full, resumed, 'mid-span resume: ')
    # 6 batches is exactly a span edge
    ck2 = str(tmp_path / 'edge.npz')
    run_physics_sweep(mp, model, 96, 16, key=7, span=3, checkpoint=ck2,
                      checkpoint_every=3, **kw)
    assert int(load_results(ck2)[1]['n_batches']) == 6
    resumed2 = run_physics_sweep(mp, model, 128, 16, key=7, span=3,
                                 checkpoint=ck2, checkpoint_every=3,
                                 **kw)
    _assert_same(full, resumed2, 'span-edge resume: ')
    # a checkpoint written WITH a span resumes WITHOUT one (and the
    # other way around): the fingerprint carries no span
    ck3 = str(tmp_path / 'cross.npz')
    run_physics_sweep(mp, model, 80, 16, key=7, span=4, checkpoint=ck3,
                      **kw)
    crossed = run_physics_sweep(mp, model, 128, 16, key=7, checkpoint=ck3,
                                **kw)
    _assert_same(full, crossed, 'cross-span resume: ')


def test_span_trace_counts():
    """Every FULL span of a sweep shares one compiled executable; a
    trailing partial span costs exactly one more."""
    mp, model, kw = _physics()
    c0 = span_trace_count()
    run_physics_sweep(mp, model, 96, 16, key=11, span=3, **kw)
    assert span_trace_count() - c0 == 1, \
        'span dividing n_batches must compile exactly once'
    c1 = span_trace_count()
    run_physics_sweep(mp, model, 112, 16, key=11, span=3, **kw)
    assert span_trace_count() - c1 == 2, \
        'trailing partial span must add exactly one trace'


def test_span_mesh_parity():
    """dp=2 CPU mesh: the sharded per-batch loop and the sharded span
    (shard_map inside the scan) fold identical stats."""
    mp, model, kw = _physics()
    mesh = make_mesh(n_dp=2)
    loop = run_physics_sweep(mp, model, 96, 16, key=5, mesh=mesh, **kw)
    sp = run_physics_sweep(mp, model, 96, 16, key=5, mesh=mesh, span=4,
                           **kw)
    _assert_same(loop, sp, 'mesh: ')


def test_add_span_checkpoint_crossing(tmp_path):
    """add_span writes when the batch count CROSSES a checkpoint_every
    multiple (snap to span edges), and equals add for n=1."""
    path = str(tmp_path / 'acc.npz')
    acc = SweepAccumulator(path, checkpoint_every=4)
    acc.add_span({'x': np.int32(1)}, 3)
    assert not (tmp_path / 'acc.npz').exists()    # 3 < 4: no write yet
    acc.add_span({'x': np.int32(1)}, 3)           # 6 crosses 4
    assert int(load_results(path)[1]['n_batches']) == 6
    acc.add_span({'x': np.int32(1)}, 3)           # 9 crosses 8
    arrays, meta = load_results(path)
    assert int(meta['n_batches']) == 9 and int(arrays['x']) == 3
    with pytest.raises(ValueError, match='span'):
        acc.add_span({'x': np.int32(1)}, 0)


def test_cli_sweep_span(tmp_path, capsys):
    """`sweep --span` passes through to the driver bit-identically, and
    a checkpoint cadence that cannot snap to span edges is rejected."""
    from distributed_processor_tpu.cli import main
    prog = tmp_path / 'prog.json'
    prog.write_text(json.dumps([{'name': 'X90', 'qubit': ['Q0']},
                                {'name': 'read', 'qubit': ['Q0']},
                                {'name': 'read', 'qubit': ['Q1']}]))
    argv = ['--qubits', '2', 'sweep', str(prog), '--shots', '32',
            '--batch', '8', '--sigma', '0.01', '--p1-init', '0.5']
    main(argv)
    base = json.loads(capsys.readouterr().out)
    main(argv + ['--span', '2'])
    spanned = json.loads(capsys.readouterr().out)
    assert base == spanned and base['shots'] == 32
    with pytest.raises(SystemExit, match='multiple'):
        main(argv + ['--span', '4', '--checkpoint-every', '3'])
    with pytest.raises(SystemExit, match='span'):
        main(argv + ['--span', '0'])
