"""Fused measure-in-megastep engine + bit-packed VMEM carry.

The fifth engine-ladder rung (docs/PERF.md "fused epoch",
``engine='fused'``): when a span hits a measurement instruction, the
readout window is synthesized and demodulated INSIDE the span kernel,
so the bit lands in the carry's measurement slot at the trigger and the
physics epoch ``while_loop`` collapses to one trip — no
exec -> resolve -> inject round-trip per measurement layer.  The
bit-packed carry (``packed_carry=True``) shrinks the megastep's
HBM-crossing streams by packing booleans/enums/counters to their
static widths.

Contract pinned here: EXACT per-stat equality with the generic engine
(fault word included) on branch-on-measurement programs and the golden
suite, composition under vmap and a dp=2 mesh, the <= 1 retrace
budget, and the engine-selection/ineligibility surface.  Every test
runs on CPU through the kernel interpreter (``pallas_interpret``
resolves to True off-TPU) — tools/check_junit.py fails the suite if
any of these testcases SKIPS.
"""

import numpy as np
import pytest

import jax

from bench import build_machine_program
from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import machine_program_from_cmds
from distributed_processor_tpu.models.default_qchip import make_default_qchip
from distributed_processor_tpu.models.experiments import active_reset
from distributed_processor_tpu.models.golden_suite import GOLDEN_PROGRAMS
from distributed_processor_tpu.parallel import make_mesh
from distributed_processor_tpu.parallel.sweep import sharded_physics_stat_sums
from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.serve import ExecutionService
from distributed_processor_tpu.sim import faultinject as fi
from distributed_processor_tpu.sim.interpreter import (
    InterpreterConfig, _program_constants, _run_batch_engine, _soa_static,
    carry_packspec, carry_stream_bytes, fused_ineligible, pallas_trace_count,
    program_traits, resolve_engine, simulate_batch)
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)
from distributed_processor_tpu.simulator import Simulator

pytestmark = pytest.mark.pallas


@pytest.fixture(scope='module')
def reset_mp():
    """Active reset: mid-circuit measurement + branch on the bit."""
    sim = Simulator(n_qubits=2)
    return sim.compile(active_reset(['Q0', 'Q1']))


KW = dict(max_pulses=32, max_meas=4)
SIGMA0 = ReadoutPhysics(sigma=0.0)


def _run(mp, init, engine=None, **kw):
    merged = {**KW, **kw}
    return run_physics_batch(mp, SIGMA0, 5, init.shape[0],
                             init_states=init,
                             max_steps=mp.n_instr * 4 + 64,
                             **({'engine': engine} if engine else {}),
                             **merged)


def _assert_equal_outputs(a, b, skip=('steps', 'epochs'), msg=''):
    assert set(a) == set(b), msg
    for k in a:
        if k in skip:
            continue
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=f'{msg}{k}')


def _span_mp():
    """Forward-jump-only injected-bits program (no measurement)."""
    return machine_program_from_cmds([[
        isa.pulse_cmd(amp_word=1000, cfg_word=0, env_word=(8 << 12) | 3,
                      cmd_time=10),
        isa.alu_cmd('reg_alu', 'i', 5, 'add', alu_in1=1,
                    write_reg_addr=1),
        isa.pulse_cmd(amp_word=2000, cfg_word=2, env_word=(4 << 12) | 1,
                      cmd_time=40),
        isa.done_cmd(),
    ]])


def _loop_mp():
    """Counted backward loop: span-ineligible, so fused-ineligible."""
    return machine_program_from_cmds([[
        isa.pulse_cmd(cmd_time=100, cfg_word=0, env_word=4096),
        isa.alu_cmd('reg_alu', 'i', 1, 'add', alu_in1=0,
                    write_reg_addr=0),
        isa.alu_cmd('jump_cond', 'i', 3, 'ge', alu_in1=0,
                    jump_cmd_ptr=0),
        isa.done_cmd(),
    ]])


# ---------------------------------------------------------------------------
# measure-in-megastep bit-identity (branch-on-measurement, fault word)
# ---------------------------------------------------------------------------

def test_fused_bit_identity_active_reset(reset_mp):
    """The fused engine retires the whole active-reset program —
    measurement, demodulation, branch — in ONE epoch, bit-identical to
    the generic engine's epoch loop on every stat."""
    rng = np.random.default_rng(7)
    init = rng.integers(0, 2, (16, 2)).astype(np.int32)
    gen = _run(reset_mp, init, engine='generic')
    fus = _run(reset_mp, init, engine='fused')
    _assert_equal_outputs(gen, fus, msg='fused: ')
    # the bits are REAL demodulated bits (sigma=0: bit == state) and
    # the epoch while_loop collapsed
    np.testing.assert_array_equal(np.asarray(fus['meas_bits'])[:, :, 0],
                                  init)
    assert int(np.asarray(gen['epochs'])) > 1
    assert int(np.asarray(fus['epochs'])) == 1


def test_fused_fault_word_identity(reset_mp):
    """A starved pulse budget traps the same fault word per shot on
    both engines — bit-identity includes the fault machinery."""
    rng = np.random.default_rng(3)
    init = rng.integers(0, 2, (8, 2)).astype(np.int32)
    gen = _run(reset_mp, init, engine='generic', max_pulses=1)
    assert np.any(np.asarray(gen['fault'])), 'fixture must actually trap'
    fus = _run(reset_mp, init, engine='fused', max_pulses=1)
    _assert_equal_outputs(gen, fus, msg='fault: ')


def test_fused_packed_carry_parity(reset_mp):
    """The bit-packed carry layout composes with the fused engine:
    pack/unpack shims at the kernel boundary are bit-transparent."""
    rng = np.random.default_rng(11)
    init = rng.integers(0, 2, (8, 2)).astype(np.int32)
    fus = _run(reset_mp, init, engine='fused')
    packed = _run(reset_mp, init, engine='fused', packed_carry=True)
    _assert_equal_outputs(fus, packed, skip=(), msg='packed fused: ')


def test_fused_golden_suite_sweep():
    """Every golden program either runs bit-identically through the
    fused engine or is rejected with a named ineligibility — never a
    silent wrong answer.  At least one golden must actually exercise
    the fused path."""
    compared = rejected = 0
    for name in sorted(GOLDEN_PROGRAMS):
        n_qubits, thunk = GOLDEN_PROGRAMS[name]
        qchip = make_default_qchip(max(n_qubits, 2))
        mp = compile_to_machine(thunk(), qchip, n_qubits=n_qubits)
        kw = dict(init_states=np.zeros((4, mp.n_cores), np.int32),
                  max_steps=4 * mp.n_instr + 64, max_pulses=64,
                  max_meas=16, max_resets=64)
        try:
            gen = run_physics_batch(mp, SIGMA0, 9, 4, engine='generic',
                                    **kw)
        except ValueError:
            continue        # golden outside the physics entry's domain
        try:
            fus = run_physics_batch(mp, SIGMA0, 9, 4, engine='fused',
                                    **kw)
        except ValueError as e:
            assert 'ineligible' in str(e), f'{name}: {e}'
            rejected += 1
            continue
        _assert_equal_outputs(gen, fus, msg=f'{name}: ')
        compared += 1
    assert compared >= 1, \
        f'no golden exercised the fused path ({rejected} rejected)'


# ---------------------------------------------------------------------------
# packed carry on the injected-bits pallas rung (golden suite + faults)
# ---------------------------------------------------------------------------

_NONTERMINATING_GOLDENS = frozenset({'simple_loop', 'nested_loop'})


@pytest.mark.parametrize('name', sorted(GOLDEN_PROGRAMS))
def test_golden_suite_packed_carry_equality(name):
    """Every terminating golden program runs bit-identically on the
    pallas engine with the bit-packed carry — every output key, the
    fault word included."""
    if name in _NONTERMINATING_GOLDENS:
        return
    n_qubits, thunk = GOLDEN_PROGRAMS[name]
    qchip = make_default_qchip(max(n_qubits, 2))
    mp = compile_to_machine(thunk(), qchip, n_qubits=n_qubits)
    cfg_kw = dict(mp.static_bounds(), max_meas=16, max_resets=64)
    rng = np.random.default_rng(17)
    bits = rng.integers(0, 2, size=(8, mp.n_cores, 16))
    gen = simulate_batch(mp, bits,
                         cfg=InterpreterConfig(engine='generic', **cfg_kw))
    assert not bool(gen['incomplete']), name
    pal = simulate_batch(mp, bits, cfg=InterpreterConfig(
        engine='pallas', pallas_interpret=True, packed_carry=True,
        **cfg_kw))
    _assert_equal_outputs(gen, pal, skip=('steps',), msg=f'{name}: ')


def test_packed_carry_fault_word_identity():
    """Packed carry round-trips the fault word exactly on a trapping
    span (the overflow-starved fixture)."""
    mp = _span_mp()
    kw = dict(max_steps=2 * mp.n_instr + 64, max_pulses=1, max_meas=2,
              max_resets=2)
    bits = np.zeros((4, mp.n_cores, 2), np.int32)
    gen = simulate_batch(mp, bits,
                         cfg=InterpreterConfig(engine='generic', **kw))
    assert np.any(np.asarray(gen['fault'])), 'fixture must actually trap'
    pal = simulate_batch(mp, bits, cfg=InterpreterConfig(
        engine='pallas', pallas_interpret=True, packed_carry=True, **kw))
    _assert_equal_outputs(gen, pal, skip=('steps',))


def test_packed_carry_reduction_floor():
    """The modeled per-shot carry bytes shrink >= 3x under the packed
    layout on the bench workload (the exec_profile row's claim)."""
    mp = build_machine_program(4, 6)
    cfg = InterpreterConfig(
        max_steps=2 * mp.n_instr + 64,
        max_pulses=int(mp.max_pulses_per_core(1)) + 4,
        max_meas=2, max_resets=2, record_pulses=False)
    unpacked, packed = carry_stream_bytes(mp, cfg)
    assert packed * 3 <= unpacked, (unpacked, packed)


# ---------------------------------------------------------------------------
# composition: vmap, dp=2 mesh, retrace budget
# ---------------------------------------------------------------------------

def test_packed_carry_under_vmap():
    """The packed-carry megastep is a plain JAX program: vmapping it
    over a leading group axis matches the vmapped generic engine."""
    mp = _span_mp()
    cfg = InterpreterConfig(max_steps=2 * mp.n_instr + 64, max_pulses=8,
                            max_meas=2, max_resets=2,
                            pallas_interpret=True, packed_carry=True)
    soa, spc, interp, sync_part = _program_constants(mp, cfg)
    prog = _soa_static(mp)
    traits = program_traits(mp)
    pack = carry_packspec(mp, cfg)
    rng = np.random.default_rng(7)
    bits = np.asarray(
        rng.integers(0, 2, size=(3, 8, mp.n_cores, 2)), np.int32)

    def pal(mb):
        return _run_batch_engine(None, spc, interp, sync_part, mb, cfg,
                                 mp.n_cores, engine='pallas', prog=prog,
                                 pack=pack)

    def gen(mb):
        return _run_batch_engine(soa, spc, interp, sync_part, mb, cfg,
                                 mp.n_cores, engine='generic',
                                 traits=traits)

    p = jax.jit(jax.vmap(pal))(bits)
    g = jax.jit(jax.vmap(gen))(bits)
    _assert_equal_outputs(g, p, skip=('steps',), msg='vmap: ')


def test_fused_dp2_mesh(reset_mp):
    """dp=2 mesh: the fused engine inside shard_map produces exactly
    the per-shard statistics of the generic epoch loop (same keys, same
    thermal sampling, bit-identical demodulated bits)."""
    mesh = make_mesh(n_dp=2)
    kw = dict(max_steps=reset_mp.n_instr * 4 + 64, **KW)
    gen = sharded_physics_stat_sums(reset_mp, SIGMA0, 21, 32, mesh,
                                    engine='generic', **kw)
    fus = sharded_physics_stat_sums(reset_mp, SIGMA0, 21, 32, mesh,
                                    engine='fused', **kw)
    assert set(gen) == set(fus)
    for k in gen:
        np.testing.assert_array_equal(np.asarray(gen[k]),
                                      np.asarray(fus[k]), err_msg=k)


def test_fused_retrace_budget(reset_mp):
    """Identical fused calls share one trace: the fused span executor
    books at most one pallas trace for one program content."""
    rng = np.random.default_rng(13)
    init = rng.integers(0, 2, (4, 2)).astype(np.int32)
    n0 = pallas_trace_count()
    a = _run(reset_mp, init, engine='fused')
    n1 = pallas_trace_count()
    assert n1 - n0 <= 1, 'more than one fused trace for one program'
    b = _run(reset_mp, init, engine='fused')
    assert pallas_trace_count() == n1, 'retrace on an identical call'
    _assert_equal_outputs(a, b, skip=())


# ---------------------------------------------------------------------------
# engine selection + ineligibility surface
# ---------------------------------------------------------------------------

def test_fused_engine_selection(reset_mp):
    phys = dict(max_steps=256, max_pulses=16, max_meas=4,
                physics=True, device='parity')
    assert resolve_engine(
        reset_mp, InterpreterConfig(engine='fused', **phys)) == 'fused'
    # 'auto' never picks fused: its remaining gates live in the readout
    # model, which resolve_engine cannot see
    assert resolve_engine(
        reset_mp, InterpreterConfig(engine='auto', **phys)) != 'fused'


def test_fused_ineligibility_named(reset_mp):
    base = dict(max_steps=256, max_pulses=16, max_meas=4)
    # injected-bits cfg (physics=False): no window to demodulate
    cfg = InterpreterConfig(engine='fused', **base)
    assert fused_ineligible(reset_mp, cfg)
    with pytest.raises(ValueError, match='ineligible'):
        resolve_engine(reset_mp, cfg)
    with pytest.raises(ValueError, match='fused'):
        simulate_batch(reset_mp, np.zeros((2, 2, 4), np.int32), cfg=cfg)
    # span-ineligible program (backward loop)
    loop_cfg = InterpreterConfig(engine='fused', physics=True,
                                 device='parity', **base)
    assert fused_ineligible(_loop_mp(), loop_cfg)
    with pytest.raises(ValueError, match='ineligible'):
        resolve_engine(_loop_mp(), loop_cfg)
    # model-level blocker: noise makes the in-kernel energy sum diverge
    # from the resolver's float realization, so sigma > 0 is rejected
    rng = np.random.default_rng(5)
    init = rng.integers(0, 2, (4, 2)).astype(np.int32)
    with pytest.raises(ValueError, match='sigma'):
        run_physics_batch(reset_mp, ReadoutPhysics(sigma=0.05), 5, 4,
                          init_states=init, max_steps=256,
                          engine='fused', **KW)


def test_faultfuzz_generic_vs_fused():
    """The mutant corpus cross-checks generic vs fused on the
    timing-independent fault codes (physics-closed at sigma=0);
    ineligible mutants skip, none may diverge."""
    r = fi.check_fused_consistency(seed=0, n=24, shots=2)
    assert not r['failures'], r['failures']
    assert r['checked'] >= 1, 'no mutant exercised the fused engine'


# ---------------------------------------------------------------------------
# serving integration: the serve tier names the fused mode
# ---------------------------------------------------------------------------

def test_serve_rejects_fused(reset_mp):
    # a submitted cfg pinning the fused engine is rejected, named
    with ExecutionService(max_wait_ms=1.0) as svc:
        with pytest.raises(ValueError, match='fused'):
            svc.submit(reset_mp, shots=2, cfg=InterpreterConfig(
                max_steps=64, max_meas=4, engine='fused'))
    # the singleton ladder rejects fused at construction, naming why
    with pytest.raises(ValueError, match='fused'):
        ExecutionService(singleton_engine='fused')
    # an unknown singleton engine's message names the full ladder,
    # fused included
    with pytest.raises(ValueError, match='fused'):
        ExecutionService(singleton_engine='warp')
