"""Block-compiled interpreter engine vs the generic fetch-dispatch engine.

The CFG superinstruction ladder rung (docs/PERF.md "Engine ladder"):
host-side block extraction partitions each program into maximal
straight-line runs between branch points, and ``_exec_blocks`` executes
a whole block per outer while_loop iteration through deduplicated
specialized bodies.  The contract is EXACT equality with the generic
engine on every output (bits, records, timing, error bits, device
co-state) plus a >=4x reduction in outer-loop iterations on the
deep-RB bench shape — pinned here on the golden suite, on random
branchy CFG fuzz programs (loops, syncs, fproc reads), under vmap, and
under a dp-sharded mesh.
"""

import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from bench import build_machine_program
from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import (extract_blocks,
                                               machine_program_from_cmds)
from distributed_processor_tpu.hwconfig import FPGAConfig
from distributed_processor_tpu.models.default_qchip import make_default_qchip
from distributed_processor_tpu.models.golden_suite import GOLDEN_PROGRAMS
from distributed_processor_tpu.ops.fabric import MeasLUT
from distributed_processor_tpu.parallel import make_mesh, sharded_simulate
from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.sim.interpreter import (
    InterpreterConfig, _program_constants, _run_batch_engine, _soa_static,
    block_ineligible, block_trace_count, program_traits, resolve_engine,
    simulate_batch)


@pytest.fixture(scope='module')
def bench_mp():
    return build_machine_program(4, 3)


def _cfg(mp, **kw):
    return InterpreterConfig(
        max_steps=2 * mp.n_instr + 64,
        max_pulses=int(mp.max_pulses_per_core(1)) + 4,
        max_meas=2, max_resets=2, **kw)


def _assert_equal_outputs(a, b, skip=('steps',), msg=''):
    assert set(a) == set(b), msg
    for k in a:
        if k in skip:
            continue
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=f'{msg}{k}')


# ---------------------------------------------------------------------------
# CFG extraction invariants (analysis view + runtime table)
# ---------------------------------------------------------------------------

def _check_cfg_invariants(mp):
    """The invariants :func:`decoder.extract_blocks` and
    :func:`isa.build_block_table` promise, checked exhaustively."""
    kind = np.asarray(mp.soa.kind)
    jump_addr = np.asarray(mp.soa.jump_addr)
    C, N = kind.shape
    enders = set(isa.BLOCK_TERMINATORS) | {isa.K_DONE}
    blocks = extract_blocks(mp)
    assert len(blocks) == C
    for c in range(C):
        rows = blocks[c]
        # partition of [0, N) exactly, in order
        assert rows[0, 0] == 0
        np.testing.assert_array_equal(rows[:-1, 0] + rows[:-1, 1],
                                      rows[1:, 0])
        assert int(rows[-1, 0] + rows[-1, 1]) == N
        assert np.all(rows[:, 1] >= 1)
        starts = set(int(s) for s in rows[:, 0])
        for s, length, k in rows:
            if k != -1:
                assert k in enders
                assert int(kind[c, s + length - 1]) == k
            else:
                # fall-through split: only an incoming edge may split here
                assert int(kind[c, s + length - 1]) not in enders \
                    or s + length == N
        # every in-range jump target is a block start
        jmask = np.isin(kind[c], [isa.K_JUMP_I, isa.K_JUMP_COND,
                                  isa.K_JUMP_FPROC])
        for t in jump_addr[c][jmask]:
            if 0 <= int(t) < N:
                assert int(t) in starts, f'core {c}: target {t}'
    # runtime layout: union-refined, deduplicated
    bid_at, bodies = isa.build_block_table(mp.soa)
    assert bid_at.shape == (N,)
    fields = mp.soa.asdict()
    for s, length in bodies:
        assert length >= isa.BLOCK_MIN_LEN
        seg = kind[:, s:s + length]
        assert not np.any(np.isin(seg, list(isa.BLOCK_TERMINATORS))), \
            f'body at {s} contains a terminator on some core'
    for s in np.nonzero(bid_at >= 0)[0]:
        bid = int(bid_at[s])
        assert 0 <= bid < len(bodies)
        s0, length = bodies[bid]
        # dedup claim: the interval's content IS the representative's
        for name, arr in fields.items():
            arr = np.asarray(arr)
            np.testing.assert_array_equal(
                arr[:, s:s + length], arr[:, s0:s0 + length],
                err_msg=f'dedup mismatch at {s} vs rep {s0}: {name}')


def test_bench_program_cfg_invariants(bench_mp):
    _check_cfg_invariants(bench_mp)


# ---------------------------------------------------------------------------
# CFG fuzz: random branchy programs (counted loops, syncs, fproc reads)
# ---------------------------------------------------------------------------

def _random_branchy_program(rng):
    """Random 2-core program with backward counted loops (terminating by
    construction: counter regs 4..7 are reserved for loop counters and
    random ALU only ever writes regs 0..3), forward jumps, self sticky
    fproc reads, and (half the time) a global SYNC barrier."""
    C = 2
    use_sync = bool(rng.integers(0, 2))
    cores = []
    for c in range(C):
        cmds = []
        t = 20

        def plain(n):
            nonlocal t
            for _ in range(n):
                kind = rng.choice(['pt', 'pw', 'alu', 'idle', 'rst',
                                   'incq'], p=[.3, .15, .25, .15, .05, .1])
                if kind == 'pt':
                    t += int(rng.integers(-5, 60))
                    cmds.append(isa.pulse_cmd(
                        cmd_time=max(t, 0),
                        cfg_word=int(rng.integers(0, 3)),
                        env_word=int(rng.integers(0, 1 << 14)),
                        amp_word=int(rng.integers(0, 1 << 16)),
                        phase_word=int(rng.integers(0, 1 << 17)),
                        freq_word=int(rng.integers(0, 4))))
                elif kind == 'pw':
                    cmds.append(isa.pulse_cmd(
                        amp_word=int(rng.integers(0, 1 << 16)),
                        phase_word=int(rng.integers(0, 1 << 17))))
                elif kind == 'alu':
                    cmds.append(isa.alu_cmd(
                        'reg_alu', rng.choice(['i', 'r']),
                        int(rng.integers(-50, 50)),
                        rng.choice(['add', 'sub', 'eq', 'le', 'ge']),
                        alu_in1=int(rng.integers(0, 4)),
                        write_reg_addr=int(rng.integers(0, 4))))
                elif kind == 'idle':
                    t += int(rng.integers(0, 80))
                    cmds.append(isa.idle(t))
                elif kind == 'rst':
                    cmds.append(isa.pulse_reset())
                else:
                    cmds.append(isa.alu_cmd('inc_qclk', 'i',
                                            int(rng.integers(-30, 30)),
                                            'add'))

        def branchy(n):
            # forward-jump / fproc placeholders mixed into a plain chunk,
            # resolved once the core's length is known
            for _ in range(n):
                r = rng.random()
                if r < 0.25:
                    cmds.append(('jc', int(rng.integers(-20, 20)),
                                 rng.choice(['eq', 'le', 'ge'])))
                elif r < 0.35:
                    cmds.append(('ji',))
                elif r < 0.55:
                    cmds.append(('fproc', int(rng.integers(0, 2))))
                else:
                    plain(1)

        def loop(counter_reg):
            # counted backward loop: body of PLAIN instructions only, so
            # any forward entry point still reaches the increment and
            # the loop terminates from every reachable state
            start = len(cmds)
            plain(int(rng.integers(1, 4)))
            cmds.append(isa.alu_cmd('reg_alu', 'i', 1, 'add',
                                    alu_in1=counter_reg,
                                    write_reg_addr=counter_reg))
            cmds.append(isa.alu_cmd('jump_cond', 'i',
                                    int(rng.integers(2, 5)), 'ge',
                                    alu_in1=counter_reg,
                                    jump_cmd_ptr=start))

        branchy(int(rng.integers(3, 7)))
        loop(4)
        if use_sync:
            cmds.append(isa.sync(0))
        branchy(int(rng.integers(2, 6)))
        if rng.integers(0, 2):
            loop(5)
        # resolve placeholders: every target strictly forward, landing
        # inside the body or on DONE
        n = len(cmds) + 1
        out = []
        for i, cmd in enumerate(cmds):
            if isinstance(cmd, tuple) and cmd[0] == 'jc':
                out.append(isa.alu_cmd(
                    'jump_cond', 'i', cmd[1], cmd[2],
                    alu_in1=int(rng.integers(0, 4)),
                    jump_cmd_ptr=int(rng.integers(i + 1, n))))
            elif isinstance(cmd, tuple) and cmd[0] == 'ji':
                out.append(isa.jump_i(int(rng.integers(i + 1, n))))
            elif isinstance(cmd, tuple) and cmd[0] == 'fproc':
                op = 'jump_fproc' if cmd[1] else 'alu_fproc'
                out.append(isa.alu_cmd(
                    op, 'i', int(rng.integers(0, 2)), 'eq',
                    write_reg_addr=int(rng.integers(0, 4)),
                    jump_cmd_ptr=int(rng.integers(i + 1, n)), func_id=c))
            else:
                out.append(cmd)
        out.append(isa.done_cmd())
        cores.append(out)
    return machine_program_from_cmds(cores)


@pytest.mark.parametrize('seed', range(8))
def test_cfg_fuzz_invariants_and_engine_equality(seed):
    """Adversarial pin on the whole block pipeline: random branchy
    programs must satisfy the CFG invariants AND produce IDENTICAL
    outputs on the block and generic engines with random injected
    bits."""
    rng = np.random.default_rng(300 + seed)
    mp = _random_branchy_program(rng)
    _check_cfg_invariants(mp)
    bounds = mp.static_bounds()
    cfg_kw = dict(bounds, max_meas=8, max_resets=128)
    assert block_ineligible(mp, InterpreterConfig(**cfg_kw)) is None
    bits = rng.integers(0, 2, size=(16, mp.n_cores, 8))
    gen = simulate_batch(mp, bits,
                         cfg=InterpreterConfig(engine='generic', **cfg_kw))
    # truncated runs diverge by construction — the fuzz only pins
    # completed ones, and static_bounds must deliver completion
    assert not bool(gen['incomplete']), f'seed {seed}: generic truncated'
    blk = simulate_batch(mp, bits,
                         cfg=InterpreterConfig(engine='block', **cfg_kw))
    _assert_equal_outputs(gen, blk, msg=f'seed {seed}: ')


# ---------------------------------------------------------------------------
# golden suite bit-identity
# ---------------------------------------------------------------------------

# The frontend-loop goldens compile `while (k >= var)` with a body
# that never writes `var` — non-terminating by construction (goldens
# pin COMPILATION, not execution).  Truncated runs legitimately
# diverge between engines (instruction- vs block-granular cutoff), so
# only the terminating ones enter the execution-equality pin; the CFG
# invariants still cover all of them.  Terminating backward loops are
# covered by the fuzz programs above.
_NONTERMINATING_GOLDENS = frozenset({'simple_loop', 'nested_loop'})


@pytest.mark.parametrize('name', sorted(GOLDEN_PROGRAMS))
def test_golden_suite_block_equality(name):
    """Every golden program (loops, fproc holds, virtual-z, GHZ, RB)
    satisfies the CFG invariants; every terminating one runs
    bit-identically on the block engine."""
    n_qubits, thunk = GOLDEN_PROGRAMS[name]
    qchip = make_default_qchip(max(n_qubits, 2))
    mp = compile_to_machine(thunk(), qchip, n_qubits=n_qubits)
    _check_cfg_invariants(mp)
    if name in _NONTERMINATING_GOLDENS:
        return
    cfg_kw = dict(mp.static_bounds(), max_meas=16, max_resets=64)
    rng = np.random.default_rng(17)
    bits = rng.integers(0, 2, size=(8, mp.n_cores, 16))
    gen = simulate_batch(mp, bits,
                         cfg=InterpreterConfig(engine='generic', **cfg_kw))
    assert not bool(gen['incomplete']), name
    blk = simulate_batch(mp, bits,
                         cfg=InterpreterConfig(engine='block', **cfg_kw))
    _assert_equal_outputs(gen, blk, msg=f'{name}: ')


# ---------------------------------------------------------------------------
# physics-closed equality (subprocess: largest CPU compile in the suite)
# ---------------------------------------------------------------------------

_BLOCK_PHYSICS_EQ_BODY = '''
import numpy as np
import jax
jax.config.update('jax_platforms', 'cpu')
from bench import build_machine_program
from distributed_processor_tpu.sim.device import DeviceModel
from distributed_processor_tpu.sim.interpreter import InterpreterConfig
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)
mp = build_machine_program(4, 3)
for devkind in ('parity', 'bloch'):
    dev = DeviceModel(devkind,
                      detuning_hz=0.3e6 if devkind == 'bloch' else 0.0,
                      t1_s=50e-6 if devkind == 'bloch' else float('inf'))
    model = ReadoutPhysics(sigma=0.05, p1_init=0.2, device=dev)
    outs = {}
    for eng in ('generic', 'block'):
        outs[eng] = run_physics_batch(
            mp, model, 5, 64,
            cfg=InterpreterConfig(
                max_steps=2 * mp.n_instr + 64,
                max_pulses=int(mp.max_pulses_per_core(1)) + 4,
                max_meas=2, max_resets=2, engine=eng))
        assert not bool(outs[eng]['incomplete']), (devkind, eng)
    assert set(outs['generic']) == set(outs['block'])
    for k in outs['generic']:
        if k in ('steps', 'epochs'):   # engine iteration bookkeeping
            continue
        np.testing.assert_array_equal(
            np.asarray(outs['generic'][k]), np.asarray(outs['block'][k]),
            err_msg=devkind + ':' + k)
print('EQUAL')
'''


def test_block_physics_closed_equality_subprocess():
    """Physics-closed epoch loop on both 1q devices: the block engine
    pauses lanes at unresolved readouts (fproc reads are block
    terminators, so the pause points are the generic engine's) and the
    resolved meas_bits / device co-state / error bits are all
    bit-identical."""
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([sys.executable, '-c', _BLOCK_PHYSICS_EQ_BODY],
                       env=env, cwd=repo, capture_output=True, text=True,
                       timeout=900)
    assert r.returncode == 0 and 'EQUAL' in r.stdout, \
        (r.returncode, r.stderr[-2000:])


# ---------------------------------------------------------------------------
# vmap and mesh composition
# ---------------------------------------------------------------------------

def test_block_engine_under_vmap(bench_mp):
    """The block executor is a plain JAX program: vmapping it over a
    leading group axis matches the vmapped generic engine exactly."""
    mp = bench_mp
    cfg = _cfg(mp)
    soa, spc, interp, sync_part = _program_constants(mp, cfg)
    prog = _soa_static(mp)
    traits = program_traits(mp)
    rng = np.random.default_rng(7)
    bits = np.asarray(
        rng.integers(0, 2, size=(3, 8, mp.n_cores, 2)), np.int32)

    def blk(mb):
        return _run_batch_engine(None, spc, interp, sync_part, mb, cfg,
                                 mp.n_cores, engine='block', prog=prog)

    def gen(mb):
        return _run_batch_engine(soa, spc, interp, sync_part, mb, cfg,
                                 mp.n_cores, engine='generic',
                                 traits=traits)

    b = jax.jit(jax.vmap(blk))(bits)
    g = jax.jit(jax.vmap(gen))(bits)
    _assert_equal_outputs(g, b, msg='vmap: ')


def test_sharded_block_matches_local_generic(bench_mp):
    """dp=2 mesh: the block engine inside shard_map produces the same
    per-shot outputs as a local generic run."""
    mp = bench_mp
    rng = np.random.default_rng(11)
    bits = rng.integers(0, 2, size=(16, mp.n_cores, 2))
    mesh = make_mesh(n_dp=2)
    sharded = sharded_simulate(mp, bits, mesh,
                               cfg=_cfg(mp, engine='block'))
    local = simulate_batch(mp, bits, cfg=_cfg(mp, engine='generic'))
    for k in sharded:   # sharded_simulate drops the scalar diagnostics
        np.testing.assert_array_equal(np.asarray(sharded[k]),
                                      np.asarray(local[k]), err_msg=k)


# ---------------------------------------------------------------------------
# the perf contract: iteration reduction + retrace budget
# ---------------------------------------------------------------------------

def _iteration_reduction(depth: int, batch: int = 32):
    mp = build_machine_program(8, depth)
    kw = dict(max_steps=2 * mp.n_instr + 64,
              max_pulses=int(mp.max_pulses_per_core(1)) + 4,
              max_meas=2, max_resets=2, record_pulses=False)
    rng = np.random.default_rng(23)
    bits = rng.integers(0, 2, size=(batch, mp.n_cores, 2))
    gen = simulate_batch(mp, bits,
                         cfg=InterpreterConfig(engine='generic', **kw))
    n0 = block_trace_count()
    blk = simulate_batch(mp, bits,
                         cfg=InterpreterConfig(engine='block', **kw))
    n1 = block_trace_count()
    assert n1 - n0 <= 1, 'more than one block trace for one bucket'
    # identical call: content-keyed jit cache must serve it untraced
    blk2 = simulate_batch(mp, bits,
                          cfg=InterpreterConfig(engine='block', **kw))
    assert block_trace_count() == n1, 'retrace on an identical call'
    for out in (gen, blk):
        assert not bool(out['incomplete'])
        assert not np.any(np.asarray(out['err']))
    _assert_equal_outputs(gen, blk)
    _assert_equal_outputs(blk, blk2, skip=())
    return int(gen['steps']), int(blk['steps'])


def test_block_iteration_reduction_depth30():
    """Depth-30 8q active-reset RB: >=4x fewer outer-loop iterations
    (measured 72 -> 3, a 24x reduction), at most one trace per
    (bucket, engine), and bit-identical outputs."""
    g, b = _iteration_reduction(30)
    assert g >= 4 * b, (g, b)


@pytest.mark.slow
def test_block_iteration_reduction_depth100():
    """The ISSUE's headline shape — depth-100 8q active-reset RB
    (212 -> 3 iterations, 70x).  Slow: the specialized-body compile is
    ~2 min on CPU (quadratic in the deduped unroll)."""
    g, b = _iteration_reduction(100, batch=8)
    assert g >= 4 * b, (g, b)


# ---------------------------------------------------------------------------
# engine ladder resolution + eligibility
# ---------------------------------------------------------------------------

def _loop_mp():
    return machine_program_from_cmds([[
        isa.pulse_cmd(cmd_time=100, cfg_word=0, env_word=4096),
        isa.alu_cmd('reg_alu', 'i', 1, 'add', alu_in1=0,
                    write_reg_addr=0),
        isa.alu_cmd('jump_cond', 'i', 3, 'ge', alu_in1=0,
                    jump_cmd_ptr=0),
        isa.done_cmd(),
    ]])


def test_resolve_engine_ladder(bench_mp):
    # engine=None preserves the legacy straightline tri-state default
    assert resolve_engine(bench_mp, _cfg(bench_mp)) == 'generic'
    assert resolve_engine(bench_mp, _cfg(bench_mp, straightline=None)) \
        == 'straightline'
    # auto: small branch-free program unrolls straight-line
    assert resolve_engine(bench_mp, _cfg(bench_mp, engine='auto')) \
        == 'straightline'
    # auto: a loop is straightline-ineligible but block-eligible
    mp = _loop_mp()
    cfg = InterpreterConfig(max_steps=128, max_pulses=8, max_meas=2)
    from dataclasses import replace
    assert resolve_engine(mp, replace(cfg, engine='auto')) == 'block'
    assert resolve_engine(mp, replace(cfg, engine='generic')) == 'generic'
    with pytest.raises(ValueError, match='unknown engine'):
        resolve_engine(mp, replace(cfg, engine='bogus'))
    # auto: every segment under BLOCK_MIN_LEN -> no bodies -> generic
    tiny = machine_program_from_cmds([[
        isa.alu_cmd('reg_alu', 'i', 1, 'add', alu_in1=0,
                    write_reg_addr=0),
        isa.alu_cmd('jump_cond', 'i', 3, 'ge', alu_in1=0,
                    jump_cmd_ptr=0),
        isa.done_cmd(),
    ]])
    assert resolve_engine(tiny, replace(cfg, engine='auto')) == 'generic'


def test_block_ineligibility_raises():
    mp = _loop_mp()
    base = dict(max_steps=128, max_pulses=8, max_meas=2)
    assert 'trace' in block_ineligible(
        mp, InterpreterConfig(trace=True, **base))
    with pytest.raises(ValueError, match='trace'):
        simulate_batch(mp, np.zeros((4, 1, 2), int),
                       cfg=InterpreterConfig(engine='block', trace=True,
                                             **base))
    # the LUT fabric is BLOCK-ELIGIBLE since the timestamped fproc
    # fabric (meas_time plane): reads are time-indexed — a pure
    # function of the planes and the request clock — so the block
    # boundary step serves them dispatch-granularity-invariantly
    fmp = machine_program_from_cmds([[
        isa.pulse_cmd(cmd_time=100, cfg_word=0, env_word=4096),
        isa.alu_cmd('alu_fproc', 'i', 0, 'eq', write_reg_addr=0,
                    func_id=0),
        isa.done_cmd(),
    ]])
    lut_cfg = InterpreterConfig(fabric='lut', lut_mask=(True,),
                                lut_table=(0, 1), **base)
    assert block_ineligible(fmp, lut_cfg) is None
    from dataclasses import replace
    assert resolve_engine(fmp, replace(lut_cfg, engine='block')) \
        == 'block'
    # the own-fresh read (func_id=0) under lut keeps per-step stall
    # semantics: SPAN-ineligible (block hosts it), named as such
    from distributed_processor_tpu.sim.interpreter import \
        straightline_ineligible
    assert 'func_id=0' in straightline_ineligible(fmp, lut_cfg)


# ---------------------------------------------------------------------------
# opcode histogram: engine-invariant retired-instruction counts
# ---------------------------------------------------------------------------

def test_op_hist_exact_and_engine_invariant():
    """A known program retires known instructions: the histogram counts
    them exactly and identically on every engine (which is what makes
    block mode's 'only pay for opcodes present' claim observable)."""
    mp = machine_program_from_cmds([[
        isa.pulse_cmd(cmd_time=100, cfg_word=0, env_word=4096),
        isa.idle(200),
        isa.alu_cmd('reg_alu', 'i', 1, 'add', alu_in1=0,
                    write_reg_addr=0),
        isa.done_cmd(),
    ]])
    kw = dict(max_steps=64, max_pulses=8, max_meas=2,
              opcode_histogram=True)
    bits = np.zeros((4, 1, 2), int)
    outs = {eng: simulate_batch(mp, bits,
                                cfg=InterpreterConfig(engine=eng, **kw))
            for eng in ('generic', 'block', 'straightline')}
    h = np.asarray(outs['generic']['op_hist'])
    assert h[isa.K_PULSE_TRIG] == 4     # 4 shots x 1 retirement each
    assert h[isa.K_IDLE] == 4
    assert h[isa.K_REG_ALU] == 4
    for eng in ('block', 'straightline'):
        np.testing.assert_array_equal(
            h, np.asarray(outs[eng]['op_hist']), err_msg=eng)
    # and on a looping program (block vs generic only)
    lmp = _loop_mp()
    louts = {eng: simulate_batch(lmp, bits,
                                 cfg=InterpreterConfig(engine=eng, **kw))
             for eng in ('generic', 'block')}
    np.testing.assert_array_equal(
        np.asarray(louts['generic']['op_hist']),
        np.asarray(louts['block']['op_hist']))


# ---------------------------------------------------------------------------
# meas-LUT contents from hardware config (satellite: hwconfig round-trip)
# ---------------------------------------------------------------------------

def test_fpga_config_meas_lut_roundtrip():
    mask, table = (True, False, True), (0, 5, 2, 7)
    fc = FPGAConfig(n_cores=3, meas_lut_mask=mask, meas_lut_table=table)
    d = fc.to_dict()
    assert d['meas_lut_mask'] == list(mask)
    assert d['meas_lut_table'] == list(table)
    fc2 = FPGAConfig(**d)
    assert fc2.meas_lut_mask == mask and fc2.meas_lut_table == table
    # unconfigured configs serialize exactly as before these fields
    # existed (the committed goldens pin this)
    assert 'meas_lut_mask' not in FPGAConfig().to_dict()
    # JSON-borne lists normalize to the hashable tuples static configs
    # require
    fc3 = FPGAConfig(n_cores=3, meas_lut_mask=[1, 0, 1],
                     meas_lut_table=[0, 5, 2, 7])
    assert fc3.meas_lut_mask == mask and fc3.meas_lut_table == table


def test_fpga_config_meas_lut_validation():
    with pytest.raises(ValueError, match='meas_lut_table'):
        FPGAConfig(meas_lut_mask=(True, True), meas_lut_table=(0,))


def test_meas_lut_from_fpga_config():
    mask = (True, False, True, True)
    table = tuple(int(x) for x in
                  np.random.default_rng(3).integers(0, 16, 8))
    fc = FPGAConfig(n_cores=4, meas_lut_mask=mask, meas_lut_table=table)
    lut = MeasLUT.from_fpga_config(fc)
    ref = MeasLUT(mask, table)
    for pattern in range(16):
        bits = np.array([(pattern >> i) & 1 for i in range(4)])
        np.testing.assert_array_equal(np.asarray(lut(bits)),
                                      np.asarray(ref(bits)),
                                      err_msg=str(pattern))
    with pytest.raises(ValueError, match='no meas LUT'):
        MeasLUT.from_fpga_config(FPGAConfig())


def test_interpreter_config_threads_hwconfig_lut():
    fc = FPGAConfig(n_cores=2, meas_lut_mask=(True, True),
                    meas_lut_table=(0, 1, 2, 3))
    cfg = InterpreterConfig.from_fpga_config(fc)
    assert cfg.lut_mask == (True, True)
    assert cfg.lut_table == (0, 1, 2, 3)
    # explicit kw wins, like every field
    over = InterpreterConfig.from_fpga_config(
        fc, lut_mask=(True, False), lut_table=(0, 1))
    assert over.lut_mask == (True, False) and over.lut_table == (0, 1)
    assert InterpreterConfig.from_fpga_config(FPGAConfig()).lut_mask == ()


# ---------------------------------------------------------------------------
# bench degraded fallback (satellite: preflight failure -> CPU rerun)
# ---------------------------------------------------------------------------

def test_bench_degraded_fallback(tmp_path):
    """A forced preflight failure must not kill the bench: it reruns
    itself on CPU, exits 0, and both the stdout JSON and the artifact
    carry the degraded flag so the number is never read as a chip
    number."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    art = tmp_path / 'bench_artifact.json'
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               BENCH_PREFLIGHT_FAIL='1', BENCH_SECONDARIES='0',
               BENCH_ARTIFACT=str(art), BENCH_NO_CACHE='1',
               BENCH_QUBITS='2', BENCH_DEPTH='2', BENCH_SHOTS='256',
               BENCH_BATCH='128', BENCH_MODE='persample')
    env.pop('BENCH_DEGRADED', None)
    r = subprocess.run([sys.executable, 'bench.py'], env=env, cwd=repo,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, (r.returncode, r.stderr[-2000:])
    line = [l for l in r.stdout.splitlines() if l.startswith('{')][-1]
    res = json.loads(line)
    assert res['degraded'] is True
    assert res['value'] > 0
    doc = json.loads(art.read_text())
    assert doc['degraded'] is True
    assert doc['result']['degraded'] is True
    assert 'headline' in doc and 'preflight' in doc


def test_bench_preflight_hard_watchdog():
    """A hang OUTSIDE the probe thread (backend plugin import, thread
    creation under a wedged runtime — BENCH_PREFLIGHT_HANG provokes
    it) is bounded by the BENCH_PREFLIGHT_TIMEOUT hard watchdog: the
    bench exits typed (rc 2 with a stage='watchdog' attempt in the
    error JSON) instead of stalling forever; BENCH_DEGRADED=1 mimics
    the already-degraded child so no second CPU rerun spawns."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               BENCH_PREFLIGHT_HANG='1', BENCH_PREFLIGHT_TIMEOUT='2',
               BENCH_DEGRADED='1')
    t0 = time.monotonic()
    r = subprocess.run(
        [sys.executable, '-c', 'import bench; bench._preflight()'],
        env=env, cwd=repo, capture_output=True, text=True, timeout=120)
    assert r.returncode == 2, (r.returncode, r.stderr[-2000:])
    assert time.monotonic() - t0 < 60.0, 'watchdog did not bound the hang'
    line = [l for l in r.stdout.splitlines() if l.startswith('{')][-1]
    res = json.loads(line)
    attempts = res['detail']['preflight_attempts']
    assert attempts[0]['stage'] == 'watchdog'
    assert 'BENCH_PREFLIGHT_TIMEOUT' in attempts[0]['error']
    assert res['value'] == 0
    assert 'watchdog fired' in r.stderr
