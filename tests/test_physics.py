"""Physics-closed measurement feedback (sim/physics.py).

The loop the reference closes in hardware — rdlo pulse -> demod ->
meas/meas_valid -> fproc -> branch (reference: hdl/core_state_mgr.sv:45-56)
— is closed numerically here: no test in this file injects measurement
bits; every branch resolves on bits demodulated from synthesized readout
windows.
"""

import numpy as np
import pytest

from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.models.experiments import active_reset
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)
from distributed_processor_tpu.sim.oracle import run_oracle


@pytest.fixture(scope='module')
def reset_mp():
    sim = Simulator(n_qubits=2)
    return sim.compile(active_reset(['Q0', 'Q1']))


KW = dict(max_pulses=32, max_meas=4)


def _run(mp, model, key, init, **kw):
    return run_physics_batch(mp, model, key, init.shape[0],
                             init_states=init,
                             max_steps=mp.n_instr * 4 + 64, **KW, **kw)


def test_active_reset_closes_loop(reset_mp):
    """Excited qubits read |1>, take the reset branch, end in |0> —
    with the bit coming from the demodulated window, not injection."""
    model = ReadoutPhysics(sigma=0.01)
    init = np.array([[1, 0], [0, 1], [1, 1], [0, 0]], np.int32)
    out = _run(reset_mp, model, 0, init)
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))
    bits = np.asarray(out['meas_bits'])[:, :, 0]
    np.testing.assert_array_equal(bits, init)      # low noise: bit == state
    # the reset branch (2 extra X90 pulses) ran exactly where bit == 1
    n_pulses = np.asarray(out['n_pulses'])
    np.testing.assert_array_equal(n_pulses, 2 + 2 * init)
    # and the device ended in the ground state everywhere
    np.testing.assert_array_equal(np.asarray(out['qturns']) % 4 // 2, 0)
    assert np.all(np.asarray(out['meas_bits_valid'])[:, :, 0])


def test_sigma_zero_bits_equal_state(reset_mp):
    model = ReadoutPhysics(sigma=0.0)
    rng = np.random.default_rng(7)
    init = rng.integers(0, 2, (16, 2)).astype(np.int32)
    out = _run(reset_mp, model, 5, init)
    np.testing.assert_array_equal(
        np.asarray(out['meas_bits'])[:, :, 0], init)


def test_noise_seed_flips_branch(reset_mp):
    """VERDICT round-1 criterion: flipping the IQ-noise seed flips which
    branch executes (readout infidelity emerges from the noise)."""
    model = ReadoutPhysics(sigma=60.0)      # near the discrimination boundary
    init = np.array([[1, 1]], np.int32)
    outcomes = set()
    for seed in range(12):
        out = _run(reset_mp, model, seed, init)
        bit = int(np.asarray(out['meas_bits'])[0, 0, 0])
        npul = int(np.asarray(out['n_pulses'])[0, 0])
        assert npul == 2 + 2 * bit          # branch followed the noisy bit
        outcomes.add(bit)
    assert outcomes == {0, 1}


def test_engine_vs_oracle_with_resolved_bits(reset_mp):
    """The engine's control flow under physics-resolved bits must equal
    the scalar oracle's under those same bits injected cocotb-style."""
    model = ReadoutPhysics(sigma=20.0)
    rng = np.random.default_rng(3)
    init = rng.integers(0, 2, (6, 2)).astype(np.int32)
    out = _run(reset_mp, model, 42, init)
    bits = np.asarray(out['meas_bits'])
    for s in range(init.shape[0]):
        o = run_oracle(reset_mp, meas_bits=bits[s])
        for c in range(2):
            npul = int(np.asarray(out['n_pulses'])[s, c])
            assert npul == len(o['pulses'][c])
            for p in range(npul):
                for fld, key in (('gtime', 'rec_gtime'), ('amp', 'rec_amp'),
                                 ('env', 'rec_env'), ('elem', 'rec_elem'),
                                 ('phase', 'rec_phase')):
                    assert int(np.asarray(out[key])[s, c, p]) \
                        == int(o['pulses'][c][p][fld]), (s, c, p, fld)
        np.testing.assert_array_equal(np.asarray(out['qclk'])[s], o['qclk'])
        assert np.all(np.asarray(out['done'])[s] == o['done'])


def test_fresh_fabric_physics():
    """The fresh-measurement fabric also resolves through the DSP.

    Fresh semantics (reference: hdl/core_state_mgr.sv WAIT_MEAS) serve
    the first measurement completing strictly *after* the read request,
    so the read must issue *before* the bit is ready: shorten the Hold to
    land the request inside the demod latency window, and give the branch
    body explicit schedule slack (a delay) to absorb the fabric wait the
    static scheduler cannot see — the exact trade the reference resolves
    in sticky mode by holding past the full FPROC_MEAS_CLKS."""
    from distributed_processor_tpu.hwconfig import FPGAConfig, FPROCChannel
    fc = FPGAConfig(fproc_channels={
        f'Q{i}.meas': FPROCChannel(id=(f'Q{i}.rdlo', 'core_ind'),
                                   hold_after_chans=[f'Q{i}.rdlo'],
                                   hold_nclks=40)
        for i in range(2)})
    sim = Simulator(n_qubits=2, fpga_config=fc)
    program = []
    for q in ('Q0', 'Q1'):
        program += [
            {'name': 'read', 'qubit': [q]},
            {'name': 'branch_fproc', 'alu_cond': 'eq', 'cond_lhs': 1,
             'func_id': f'{q}.meas', 'scope': [q],
             'true': [{'name': 'delay', 't': 1e-6, 'qubit': [q]},
                      {'name': 'X90', 'qubit': [q]},
                      {'name': 'X90', 'qubit': [q]}],
             'false': []},
        ]
    mp = sim.compile(program)
    model = ReadoutPhysics(sigma=0.01)
    init = np.array([[1, 0], [0, 1]], np.int32)
    out = _run(mp, model, 1, init, fabric='fresh')
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))
    np.testing.assert_array_equal(
        np.asarray(out['meas_bits'])[:, :, 0], init)
    np.testing.assert_array_equal(np.asarray(out['n_pulses']), 2 + 2 * init)


def test_simulator_facade_physics():
    """Simulator.run(physics=...) end-to-end from a dict program."""
    sim = Simulator(n_qubits=2)
    out = sim.run(active_reset(['Q0', 'Q1']), shots=8,
                  physics=ReadoutPhysics(sigma=0.01, p1_init=1.0))
    assert not bool(out['incomplete'])
    bits = np.asarray(out['meas_bits'])[:, :, 0]
    np.testing.assert_array_equal(bits, 1)      # all start excited
    np.testing.assert_array_equal(np.asarray(out['n_pulses']), 4)


def test_window_matches_synthesize_element(reset_mp):
    """_synth_windows must reproduce the element model's numeric contract:
    the readout window it demodulates against equals the corresponding
    slice of the full synthesize_element trace."""
    import jax.numpy as jnp
    from distributed_processor_tpu.ops.waveform import synthesize_element
    from distributed_processor_tpu.elements import IQ_SCALE
    from distributed_processor_tpu.sim.physics import (_physics_tables,
                                                       _synth_windows)
    model = ReadoutPhysics(sigma=0.0)
    init = np.array([[1, 0]], np.int32)
    out = _run(reset_mp, model, 0, init)
    tables = _physics_tables(reset_mp, model.meas_elem)[:4]
    W = int(_physics_tables(reset_mp, model.meas_elem)[4])
    st = {k: jnp.asarray(np.asarray(out[k]))
          for k in ('meas_amp', 'meas_phase', 'meas_freq', 'meas_env',
                    'meas_gtime', 'n_meas')}
    y_i, y_q = _synth_windows(st, tables, W)

    c = 0
    ecfg = reset_mp.tables[c].elem_cfgs[model.meas_elem]
    ftab = np.asarray(reset_mp.tables[c].freqs[model.meas_elem]['freq'])
    frel = np.concatenate([ftab / ecfg.sample_freq, [0.0]])
    P = np.asarray(out['rec_gtime']).shape[-1]
    sel = lambda k: np.asarray(out[k])[0, c]
    is_meas = sel('rec_elem') == model.meas_elem
    rec = {'gtime': sel('rec_gtime'), 'env': sel('rec_env'),
           'phase': sel('rec_phase'), 'amp': sel('rec_amp'),
           'elem': sel('rec_elem'),
           'freq_rel': frel[np.clip(sel('rec_freq'), 0, len(frel) - 1)],
           'n_pulses': int(np.asarray(out['n_pulses'])[0, c])}
    env_table = np.asarray(reset_mp.tables[c].envs[model.meas_elem]) / IQ_SCALE
    gt = int(sel('rec_gtime')[is_meas][0])
    dur = int(sel('rec_dur')[is_meas][0])
    spc = ecfg.samples_per_clk
    trace = np.asarray(synthesize_element(
        rec, env_table, spc=spc, interp=ecfg.interp_ratio,
        n_clks=gt + dur + 4, elem=model.meas_elem))
    n_samp = dur * spc
    win = trace[gt * spc: gt * spc + n_samp]
    np.testing.assert_allclose(np.asarray(y_i)[0, c, 0, :n_samp],
                               win[:, 0], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y_q)[0, c, 0, :n_samp],
                               win[:, 1], rtol=1e-4, atol=1e-5)


def test_resolve_modes_deterministic_identity(reset_mp):
    """'analytic' (exact distributional shortcut) and 'fused' (Pallas
    kernel, ops/resolve_pallas.py) must produce bit-identical results to
    the per-sample XLA path at sigma=0 — all three reduce to the sign of
    the clean matched-filter projection."""
    rng = np.random.default_rng(9)
    init = rng.integers(0, 2, (16, 2)).astype(np.int32)
    outs = {}
    for mode in ('persample', 'analytic', 'fused'):
        model = ReadoutPhysics(sigma=0.0, resolve_mode=mode)
        outs[mode] = _run(reset_mp, model, 3, init)
    for mode in ('analytic', 'fused'):
        np.testing.assert_array_equal(
            np.asarray(outs[mode]['meas_bits']),
            np.asarray(outs['persample']['meas_bits']))
        np.testing.assert_array_equal(
            np.asarray(outs[mode]['n_pulses']),
            np.asarray(outs['persample']['n_pulses']))
    np.testing.assert_array_equal(
        np.asarray(outs['analytic']['meas_bits'])[:, :, 0], init)


def test_resolve_modes_error_rate_matches(reset_mp):
    """At finite sigma the modes draw different noise streams but the
    same distribution: readout error rates agree statistically.
    sigma is set for ~10-30% infidelity; 512 shots x 2 cores give a
    binomial CI of ~+/-1.3% (3 sigma ~4%)."""
    # calibrate sigma to the window: error rate = Q(|g1-g0|*sqrt(E)/(2*sigma))
    rates = {}
    for mode in ('persample', 'analytic', 'fused'):
        model = ReadoutPhysics(sigma=45.0, resolve_mode=mode)
        out = run_physics_batch(reset_mp, model, 17, 512,
                                init_states=np.zeros((512, 2), np.int32),
                                max_steps=reset_mp.n_instr * 4 + 64, **KW)
        bits = np.asarray(out['meas_bits'])[:, :, 0]
        rates[mode] = float(bits.mean())      # |0> prepared: errors = 1s
    assert 0.005 < rates['analytic'] < 0.5    # noise actually flips bits
    assert abs(rates['analytic'] - rates['persample']) < 0.06, rates
    assert abs(rates['fused'] - rates['persample']) < 0.06, rates


def test_fused_resolve_active_reset_loop(reset_mp):
    """The fused kernel drives the closed loop end-to-end: low-noise
    active reset resolves every branch from its in-VMEM demod."""
    model = ReadoutPhysics(sigma=0.01, resolve_mode='fused')
    init = np.array([[1, 0], [0, 1], [1, 1], [0, 0]], np.int32)
    out = _run(reset_mp, model, 0, init)
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))
    np.testing.assert_array_equal(
        np.asarray(out['meas_bits'])[:, :, 0], init)
    np.testing.assert_array_equal(np.asarray(out['n_pulses']), 2 + 2 * init)
    np.testing.assert_array_equal(np.asarray(out['qturns']) % 4 // 2, 0)


def test_thermal_init_statistics(reset_mp):
    """Thermal sampling: excited fraction tracks p1_init."""
    model = ReadoutPhysics(sigma=0.01, p1_init=0.3)
    out = run_physics_batch(reset_mp, model, 11, 512,
                            max_steps=reset_mp.n_instr * 4 + 64, **KW)
    frac = float(np.asarray(out['meas_bits'])[:, :, 0].mean())
    assert 0.2 < frac < 0.4


def test_lut_fabric_physics_majority_correction():
    """The LUT fabric (reference: hdl/fproc_lut.sv + meas_lut.sv) closed
    by the DSP chain: every data core measures, the demodulated bits
    form the syndrome address, and each core branches on its own
    majority-vote correction bit — no injection anywhere.  Run over all
    8 initial 3-bit patterns; every core must end at the majority state.
    """
    from distributed_processor_tpu.models.repetition import (
        repetition_round_program, repetition_physics_kwargs)
    n = 3
    sim = Simulator(n_qubits=n)
    mp = sim.compile(repetition_round_program(n))
    init = np.array([[(s >> i) & 1 for i in range(n)] for s in range(8)],
                    np.int32)
    model = ReadoutPhysics(sigma=0.01)
    out = run_physics_batch(
        mp, model, 11, 8, init_states=init,
        max_steps=mp.n_instr * 6 + 64, **repetition_physics_kwargs(n))
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))
    # low noise: the measured syndrome is the initial pattern
    np.testing.assert_array_equal(np.asarray(out['meas_bits'])[:, :, 0],
                                  init)
    # every core corrected to the majority of its pattern
    maj = (init.sum(axis=1) * 2 > n).astype(np.int32)
    final = np.asarray(out['qturns']) % 4 // 2
    np.testing.assert_array_equal(final, np.broadcast_to(maj[:, None],
                                                         (8, n)))
    # corrections fired exactly on the minority cores
    np.testing.assert_array_equal(
        np.asarray(out['n_pulses']),
        2 + 2 * (init != maj[:, None]).astype(np.int32))


def test_qasm_source_to_physics_closed_loop():
    """Full stack, nothing injected: OpenQASM 3 source with
    measurement-conditioned branches -> compiler -> machine code ->
    batched interpretation with the readout loop closed by the DSP
    chain.  The ``if (c[i] == 1) x`` correction follows the emergent
    bit, returning every qubit to ground."""
    from distributed_processor_tpu.frontend import qasm_to_program
    from distributed_processor_tpu.pipeline import compile_to_machine
    from distributed_processor_tpu.models import make_default_qchip
    src = '''
        OPENQASM 3;
        qubit[2] q;
        bit[2] c;
        c[0] = measure q[0];
        c[1] = measure q[1];
        if (c[0] == 1) { x q[0]; }
        if (c[1] == 1) { x q[1]; }
    '''
    mp = compile_to_machine(qasm_to_program(src), make_default_qchip(2),
                            n_qubits=2)
    init = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.int32)
    out = _run(mp, ReadoutPhysics(sigma=0.01), 2, init)
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))
    np.testing.assert_array_equal(np.asarray(out['meas_bits'])[:, :, 0],
                                  init)
    np.testing.assert_array_equal(np.asarray(out['n_pulses']),
                                  2 + 2 * init)
    np.testing.assert_array_equal(np.asarray(out['qturns']) % 4 // 2, 0)


def test_multi_round_reset_steady_state():
    """Three feedback rounds under heavy readout noise: each round is a
    separate resolve epoch (measure -> demod -> conditional flip), and
    the excited population converges to the per-measurement readout
    error e — the fixed point of symmetric-error active reset
    (P(1) -> e*P(0) + e*P(1) = e: a wrong 0-readout leaves |1>, a wrong
    1-readout flips |0> back up)."""
    sim = Simulator(n_qubits=2)
    mp3 = sim.compile(active_reset(['Q0', 'Q1'], n_rounds=3))
    # sigma chosen for substantial (~20-30%) readout error
    model = ReadoutPhysics(sigma=40.0)
    shots = 512
    init = np.ones((shots, 2), np.int32)       # all excited
    out = run_physics_batch(mp3, model, 123, shots, init_states=init,
                            max_steps=mp3.n_instr * 6 + 64,
                            max_pulses=32, max_meas=4)
    assert not bool(out['incomplete'])
    assert int(np.asarray(out['epochs'])) >= 3   # one resolve per round
    assert np.all(np.asarray(out['meas_bits_valid'])[:, :, :3])

    # readout error from round 1: every shot starts |1>, so a 0 bit is
    # an error
    e = 1.0 - float(np.asarray(out['meas_bits'])[:, :, 0].mean())
    assert 0.1 < e < 0.4, e                    # noise regime as intended
    final_excited = float((np.asarray(out['qturns']) % 4 // 2).mean())
    # steady state = e (binomial CI at 512x2 shots ~ +/-1.3%, 3sig ~4%)
    assert abs(final_excited - e) < 0.05, (final_excited, e)


def test_cw_measurement_pulse_flagged():
    """A CW (hold-until-next) readout envelope has no window length for
    the resolver — physics mode must set ERR_CW_MEAS instead of quietly
    producing zero-energy bits (docs/PHYSICS.md "Known model limits")."""
    from distributed_processor_tpu import isa
    from distributed_processor_tpu.decoder import machine_program_from_cmds
    from distributed_processor_tpu.sim import ERR_CW_MEAS
    cmds = [
        isa.pulse_cmd(freq_word=0, cfg_word=2,          # meas elem
                      env_word=(0xfff << 12) | 0,       # CW sentinel
                      amp_word=30000, cmd_time=10),
        isa.done_cmd(),
    ]
    mp = machine_program_from_cmds([cmds])
    out = run_physics_batch(mp, ReadoutPhysics(sigma=0.0), 0, 1,
                            init_states=np.zeros((1, 1), np.int32),
                            max_steps=32, max_pulses=4, max_meas=2)
    assert int(np.asarray(out['err'])[0, 0]) & ERR_CW_MEAS
    # injected-bits mode is unaffected (bits don't come from windows)
    from distributed_processor_tpu.sim import simulate
    out2 = simulate(mp, meas_bits=np.array([[1, 0]]), max_steps=32,
                    max_pulses=4, max_meas=2)
    assert int(np.asarray(out2['err'])[0]) & ERR_CW_MEAS == 0


def _two_envelope_mp():
    """One core, two readout gates with different envelope lengths —
    two distinct envelope-table addresses on the measurement element."""
    import copy
    from distributed_processor_tpu.qchip import Gate, _entry_from_dict
    sim = Simulator(n_qubits=1)
    entries = sim.qchip.gates['Q0read'].to_dict()
    g2 = copy.deepcopy(entries)
    for e in g2:
        e['twidth'] = e['twidth'] / 2
    sim.qchip.gates['Q0read2'] = Gate('Q0read2',
                                      [_entry_from_dict(e) for e in g2])
    return sim.compile([{'name': 'read', 'qubit': ['Q0']},
                        {'name': 'read2', 'qubit': ['Q0']}])


def test_fused_compact_rows_multi_envelope():
    """The fused kernel's static-address row select (round-3 perf work)
    must be exact with MULTIPLE envelope addresses in play: bit-equal
    to the XLA per-sample path at sigma=0, and to the full-Toeplitz
    fused path (rows analysis disabled)."""
    from distributed_processor_tpu.sim import physics as ph
    mp = _two_envelope_mp()
    assert ph._static_meas_env_addrs(mp) == (0, 256)
    init = (np.arange(24) % 2).astype(np.int32).reshape(24, 1)
    kw = dict(max_steps=200, max_pulses=16, max_meas=4)
    outs = {}
    for mode in ('fused', 'persample'):
        model = ReadoutPhysics(sigma=0.0, resolve_mode=mode)
        outs[mode] = np.asarray(run_physics_batch(
            mp, model, 5, 24, init_states=init, **kw)['meas_bits'])
    np.testing.assert_array_equal(outs['fused'], outs['persample'])
    np.testing.assert_array_equal(outs['fused'][:, 0, 0], init[:, 0])
    # full-Toeplitz fallback (what >8 envelopes / register-sourced env
    # words get) agrees bit-for-bit
    orig = ph._static_meas_env_addrs
    ph._static_meas_env_addrs = lambda *a, **k: None
    try:
        model = ReadoutPhysics(sigma=0.0, resolve_mode='fused')
        full = np.asarray(run_physics_batch(
            mp, model, 5, 24, init_states=init, **kw)['meas_bits'])
    finally:
        ph._static_meas_env_addrs = orig
    np.testing.assert_array_equal(full, outs['fused'])


def test_static_env_addrs_fallbacks():
    """The static envelope-address analysis must refuse (None) exactly
    when the value set is data-dependent: a register-sourced env write."""
    from distributed_processor_tpu import isa
    from distributed_processor_tpu.decoder import machine_program_from_cmds
    from distributed_processor_tpu.sim.physics import _static_meas_env_addrs
    mp = machine_program_from_cmds([[
        isa.alu_cmd('reg_alu', 'i', 4096, 'id0', write_reg_addr=1),
        isa.pulse_cmd(env_regaddr=1, freq_word=1, phase_word=0,
                      amp_word=10, cfg_word=2, cmd_time=10),
        isa.done_cmd()]])
    assert _static_meas_env_addrs(mp) is None
    mp2 = machine_program_from_cmds([[
        isa.pulse_cmd(env_word=(2 << 12) | 3, freq_word=1, phase_word=0,
                      amp_word=10, cfg_word=2, cmd_time=10),
        isa.done_cmd()]])
    assert _static_meas_env_addrs(mp2) == (0, 12)   # {0} + 3*4


def test_steps_per_iter_unroll_equivalent():
    """steps_per_iter > 1 (while-body unroll, the exec-phase perf knob)
    is bit-identical to the default on a feedback program."""
    from distributed_processor_tpu.simulator import Simulator
    from distributed_processor_tpu.models.experiments import active_reset
    from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                       run_physics_batch)
    sim = Simulator(n_qubits=2)
    mp = sim.compile(active_reset(['Q0', 'Q1'], n_rounds=2))
    model = ReadoutPhysics(sigma=0.05, p1_init=0.5)
    kw = dict(max_steps=4 * mp.n_instr + 64, max_pulses=16, max_meas=4,
              max_resets=4)
    base = run_physics_batch(mp, model, 3, 64, **kw)
    for k in (2, 5):
        unr = run_physics_batch(mp, model, 3, 64, steps_per_iter=k, **kw)
        assert not bool(unr['incomplete'])
        np.testing.assert_array_equal(np.asarray(base['meas_bits']),
                                      np.asarray(unr['meas_bits']))
        np.testing.assert_array_equal(np.asarray(base['err']),
                                      np.asarray(unr['err']))
        np.testing.assert_array_equal(np.asarray(base['qclk']),
                                      np.asarray(unr['qclk']))
    # max_steps-boundary exactness: a budget that cuts execution short
    # must produce identical results and step counts for every k (the
    # unroll masks past-budget sub-steps to no-ops)
    for short in (7, 10):
        kw_s = dict(kw, max_steps=short)
        b = run_physics_batch(mp, model, 3, 16, **kw_s)
        for k in (2, 5):
            u = run_physics_batch(mp, model, 3, 16, steps_per_iter=k,
                                  **kw_s)
            assert int(u['steps']) == int(b['steps'])
            for f in ('meas_bits', 'err', 'qclk', 'done', 'n_meas'):
                np.testing.assert_array_equal(np.asarray(b[f]),
                                              np.asarray(u[f]), f)
