"""JAX ISA-interpreter tests.

Strategy mirrors the reference's cocotb suite (reference:
cocotb/proc/test_proc.py): timed pulse dispatch, randomized ALU programs
against a scalar golden model, register-parameterized pulses, jumps,
qclk increments, fproc read/branch with injected measurement bits, and
the sync barrier — plus JAX-vs-oracle equivalence on random programs and
shot-batched divergent control flow (the TPU-native axis).
"""

import numpy as np
import pytest

from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import machine_program_from_cmds
from distributed_processor_tpu.sim import simulate, simulate_batch, run_oracle
from distributed_processor_tpu.sim.oracle import alu as oracle_alu, START_NCLKS
from distributed_processor_tpu.sim import (ERR_MISSED_TRIG, ERR_FPROC_DEADLOCK)


def mp_of(*cmd_lists, **kw):
    return machine_program_from_cmds(list(cmd_lists), **kw)


def test_timed_pulse_dispatch():
    # analog of cocotb pulse_i_test: pulse fires exactly at cmd_time
    prog = mp_of([
        isa.pulse_cmd(freq_word=0x55, phase_word=0x1234, amp_word=0x8000,
                      env_word=(3 << 12) | 1, cfg_word=0, cmd_time=10),
        isa.done_cmd(),
    ])
    out = simulate(prog)
    assert int(out['n_pulses'][0]) == 1
    assert int(out['rec_qtime'][0, 0]) == 10
    assert int(out['rec_gtime'][0, 0]) == 10
    assert int(out['rec_freq'][0, 0]) == 0x55
    assert int(out['rec_phase'][0, 0]) == 0x1234
    assert int(out['rec_amp'][0, 0]) == 0x8000
    assert int(out['rec_elem'][0, 0]) == 0
    # 3 groups of 4 env samples at 16 samples/clk -> ceil(12/16) = 1 clk
    assert int(out['rec_dur'][0, 0]) == 1
    assert int(out['err'][0]) == 0
    assert bool(out['done'][0])


def test_pulse_param_persistence_and_reg_source():
    # analog of cocotb pulse_reg_test: params latch; one param from a reg
    prog = mp_of([
        isa.alu_cmd('reg_alu', 'i', 0x1abcd, 'id0', write_reg_addr=3),
        isa.pulse_cmd(freq_word=7, amp_word=0x1111, cfg_word=1),   # write only
        isa.pulse_cmd(phase_regaddr=3, cmd_time=40),               # trig
        isa.pulse_cmd(amp_word=0x2222, cmd_time=60),               # re-trig
        isa.done_cmd(),
    ])
    out = simulate(prog)
    assert int(out['n_pulses'][0]) == 2
    # first trig: freq/amp latched earlier, phase from reg 3 (17-bit masked)
    assert int(out['rec_freq'][0, 0]) == 7
    assert int(out['rec_amp'][0, 0]) == 0x1111
    assert int(out['rec_phase'][0, 0]) == 0x1abcd & 0x1ffff
    assert int(out['rec_elem'][0, 0]) == 1
    # second trig: only amp updated, everything else persists
    assert int(out['rec_amp'][0, 1]) == 0x2222
    assert int(out['rec_freq'][0, 1]) == 7
    assert int(out['rec_phase'][0, 1]) == 0x1abcd & 0x1ffff


def test_missed_trigger_flags_error():
    prog = mp_of([
        isa.alu_cmd('reg_alu', 'i', 1, 'id0', write_reg_addr=0),
        isa.pulse_cmd(freq_word=1, cmd_time=3),   # qclk is already past 3
        isa.done_cmd(),
    ])
    out = simulate(prog)
    assert int(out['err'][0]) & ERR_MISSED_TRIG


@pytest.mark.parametrize('seed', range(4))
def test_randomized_alu_vs_golden(seed):
    # analog of cocotb reg_i_test: random ALU ops vs the golden model
    rng = np.random.default_rng(seed)
    ops = list(isa.ALU_OPS)
    cmds, expected = [], {}
    regs = [0] * isa.N_REGS
    for r in range(4):   # seed some registers
        v = int(rng.integers(-2**20, 2**20))
        cmds.append(isa.alu_cmd('reg_alu', 'i', v, 'id0', write_reg_addr=r))
        regs[r] = v
    for _ in range(40):
        op = ops[int(rng.integers(len(ops)))]
        in1 = int(rng.integers(4))
        out = int(rng.integers(4, 12))
        if rng.integers(2):
            in0r = int(rng.integers(4))
            cmds.append(isa.alu_cmd('reg_alu', 'r', in0r, op, in1,
                                    write_reg_addr=out))
            regs[out] = oracle_alu(isa.ALU_OPS[op], regs[in0r], regs[in1])
        else:
            imm = int(rng.integers(-2**20, 2**20))
            cmds.append(isa.alu_cmd('reg_alu', 'i', imm, op, in1,
                                    write_reg_addr=out))
            regs[out] = oracle_alu(isa.ALU_OPS[op], imm, regs[in1])
    cmds.append(isa.done_cmd())
    out = simulate(mp_of(cmds))
    np.testing.assert_array_equal(np.asarray(out['regs'][0]), regs)


def test_conditional_loop():
    # decrement reg 0 from 5 to 0 via a backward conditional jump
    cmds = [
        isa.alu_cmd('reg_alu', 'i', 5, 'id0', write_reg_addr=0),      # 0: n=5
        isa.alu_cmd('reg_alu', 'i', -1, 'add', 0, write_reg_addr=0),  # 1: n-=1
        isa.alu_cmd('jump_cond', 'i', 0, 'le', 0, jump_cmd_ptr=1),    # 2: 0<n?
        isa.done_cmd(),                                               # 3
    ]
    out = simulate(mp_of(cmds))
    assert int(out['regs'][0, 0]) == 0
    assert bool(out['done'][0])
    # time: 2 + alu(5) + 5*(alu 5 + jump 5) = 57
    assert int(out['time'][0]) == 57


def test_inc_qclk_shifts_trigger():
    # inc_qclk by -20: subsequent cmd_time re-fires relative to shifted qclk
    cmds = [
        isa.pulse_cmd(freq_word=1, cfg_word=0, cmd_time=30),       # fires @30
        isa.alu_cmd('inc_qclk', 'i', -20),                         # qclk -= 20
        isa.pulse_cmd(freq_word=2, cmd_time=30),                   # fires @50
        isa.done_cmd(),
    ]
    out = simulate(mp_of(cmds))
    assert int(out['rec_gtime'][0, 0]) == 30
    assert int(out['rec_gtime'][0, 1]) == 50
    assert int(out['rec_qtime'][0, 1]) == 30
    assert int(out['err'][0]) == 0


def test_fproc_active_reset():
    # readout pulse -> hold -> branch on own measurement; bit=1 adds X pulse
    cmds = [
        isa.pulse_cmd(freq_word=3, cfg_word=2, env_word=(2 << 12) | 0,
                      cmd_time=10),                                # rdlo, dur 2
        isa.idle(80),                                              # hold
        isa.alu_cmd('jump_fproc', 'i', 1, 'eq', jump_cmd_ptr=4, func_id=0),
        isa.jump_i(5),
        isa.pulse_cmd(freq_word=9, cfg_word=0, env_word=(2 << 12) | 0,
                      cmd_time=200),                               # X90 flip
        isa.done_cmd(),
    ]
    prog = mp_of(cmds)
    out0 = simulate(prog, meas_bits=np.array([[0]]))
    out1 = simulate(prog, meas_bits=np.array([[1]]))
    assert int(out0['n_pulses'][0]) == 1
    assert int(out1['n_pulses'][0]) == 2
    assert int(out1['rec_gtime'][0, 1]) == 200
    assert int(out0['err'][0]) == 0 and int(out1['err'][0]) == 0
    # measurement available 64 clks after rdlo pulse end (10 + 2 + 64)
    assert int(out0['meas_avail'][0, 0]) == 76


def test_cross_core_fproc_read():
    # core 1 reads core 0's measurement via alu_fproc (fproc_meas fabric)
    core0 = [
        isa.pulse_cmd(freq_word=3, cfg_word=2, env_word=(2 << 12) | 0,
                      cmd_time=10),
        isa.done_cmd(),
    ]
    core1 = [
        isa.idle(100),
        isa.read_fproc(func_id=0, write_reg_addr=7),
        isa.done_cmd(),
    ]
    out = simulate(mp_of(core0, core1), meas_bits=np.array([[1], [0]]))
    assert int(out['regs'][1, 7]) == 1
    assert int(out['err'][1]) == 0


def test_sticky_race_window_flagged():
    """A sticky read landing within STICKY_RACE_MARGIN of a measurement's
    arrival is served deterministically but flagged ERR_STICKY_RACE /
    'sticky_race' in BOTH engines: hardware's 2-cycle handshake
    (fproc_meas.sv:23-34) makes the latched value timing-dependent
    there (docs/TIMING.md 'Race flagging')."""
    from distributed_processor_tpu.sim import ERR_STICKY_RACE
    # core0's rdlo pulse: avail = 10 (trig) + 2 (dur) + 64 = 76
    core0 = [
        isa.pulse_cmd(freq_word=3, cfg_word=2, env_word=(2 << 12) | 0,
                      cmd_time=10),
        isa.done_cmd(),
    ]

    def reader(idle_end):
        # read issues at idle_end + pulse_load_clks(3)
        return [isa.idle(idle_end),
                isa.read_fproc(func_id=0, write_reg_addr=7),
                isa.done_cmd()]

    # racy: read at t=71+3=74; 76 in (72, 76] -> flagged, bit still the
    # pre-measurement latch (0 measurements <= 74 -> data 0)
    prog = mp_of(core0, reader(71))
    bits = np.array([[1], [0]])
    out = simulate(prog, meas_bits=bits)
    orc = run_oracle(prog, meas_bits=bits)
    assert int(out['err'][1]) & ERR_STICKY_RACE
    assert 'sticky_race' in orc['err'][1]
    assert int(out['regs'][1, 7]) == 0 and orc['regs'][1, 7] == 0

    # safe: read at t=100+3=103; margin clear -> served, no flag
    prog = mp_of(core0, reader(100))
    out = simulate(prog, meas_bits=bits)
    orc = run_oracle(prog, meas_bits=bits)
    assert int(out['err'][1]) == 0 and orc['err'][1] == []
    assert int(out['regs'][1, 7]) == 1 and orc['regs'][1, 7] == 1


def test_sync_barrier_aligns_cores():
    # cores reach the barrier at different times; both pulse together after
    core0 = [
        isa.alu_cmd('reg_alu', 'i', 1, 'id0', write_reg_addr=0),
        isa.alu_cmd('reg_alu', 'i', 2, 'id0', write_reg_addr=0),
        isa.alu_cmd('reg_alu', 'i', 3, 'id0', write_reg_addr=0),
        isa.sync(0),
        isa.pulse_cmd(freq_word=1, cfg_word=0, cmd_time=5),
        isa.done_cmd(),
    ]
    core1 = [
        isa.sync(0),
        isa.pulse_cmd(freq_word=2, cfg_word=0, cmd_time=5),
        isa.done_cmd(),
    ]
    out = simulate(mp_of(core0, core1))
    # core0 arrives at t=2+15=17; release 17+4=21; both fire at qclk 5
    assert int(out['rec_gtime'][0, 0]) == 26
    assert int(out['rec_gtime'][1, 0]) == 26
    assert int(out['rec_qtime'][0, 0]) == 5
    assert np.all(np.asarray(out['err']) == 0)


def test_fproc_deadlock_detected():
    # fresh-mode read with the producer already done and no measurement
    cmds = [
        isa.read_fproc(func_id=0, write_reg_addr=0),
        isa.done_cmd(),
    ]
    out = simulate(mp_of(cmds), fabric='fresh',
                   meas_bits=np.zeros((1, 1), int))
    assert int(out['err'][0]) & ERR_FPROC_DEADLOCK


def _random_program(rng, n_cores=2, n_instr=30):
    """Random halting programs: straight-line ALU/pulse + forward jumps."""
    progs = []
    for _ in range(n_cores):
        cmds = []
        t = 40
        for i in range(n_instr):
            r = rng.integers(6)
            if r == 0:
                cmds.append(isa.alu_cmd(
                    'reg_alu', 'i', int(rng.integers(-1000, 1000)),
                    list(isa.ALU_OPS)[int(rng.integers(8))],
                    int(rng.integers(4)),
                    write_reg_addr=int(rng.integers(isa.N_REGS))))
            elif r == 1:
                cmds.append(isa.alu_cmd(
                    'reg_alu', 'r', int(rng.integers(4)),
                    list(isa.ALU_OPS)[int(rng.integers(8))],
                    int(rng.integers(4)),
                    write_reg_addr=int(rng.integers(isa.N_REGS))))
            elif r == 2:
                t += int(rng.integers(10, 50))
                cmds.append(isa.pulse_cmd(
                    freq_word=int(rng.integers(1 << 9)),
                    phase_word=int(rng.integers(1 << 17)),
                    amp_word=int(rng.integers(1 << 16)),
                    env_word=(int(rng.integers(1, 8)) << 12),
                    cfg_word=int(rng.integers(2)), cmd_time=t))
            elif r == 3:
                cmds.append(isa.pulse_cmd(
                    amp_word=int(rng.integers(1 << 16))))
            elif r == 4:
                t += int(rng.integers(200))
                cmds.append(isa.idle(t))
            else:
                # forward conditional jump (guaranteed halting)
                target = len(cmds) + 1 + int(rng.integers(1, 3))
                cmds.append(isa.alu_cmd(
                    'jump_cond', 'i', int(rng.integers(-2, 2)),
                    rng.choice(['eq', 'le', 'ge']), int(rng.integers(4)),
                    jump_cmd_ptr=min(target, n_instr)))
            t += 60
        cmds.append(isa.done_cmd())
        progs.append(cmds)
    return mp_of(*progs)


@pytest.mark.parametrize('seed', range(6))
def test_jax_matches_oracle_random_programs(seed):
    rng = np.random.default_rng(100 + seed)
    prog = _random_program(rng)
    bits = rng.integers(0, 2, size=(prog.n_cores, 8))
    jx = simulate(prog, meas_bits=bits, max_pulses=64)
    orc = run_oracle(prog, meas_bits=bits)
    np.testing.assert_array_equal(np.asarray(jx['regs']), orc['regs'])
    np.testing.assert_array_equal(np.asarray(jx['time']), orc['time'])
    np.testing.assert_array_equal(np.asarray(jx['qclk']), orc['qclk'])
    for c in range(prog.n_cores):
        n = int(jx['n_pulses'][c])
        assert n == len(orc['pulses'][c])
        for k, fld in (('qtime', 'qtime'), ('gtime', 'gtime'),
                       ('env', 'env'), ('phase', 'phase'), ('freq', 'freq'),
                       ('amp', 'amp'), ('cfg', 'cfg'), ('elem', 'elem'),
                       ('dur', 'dur')):
            got = np.asarray(jx['rec_' + k][c, :n])
            want = np.array([p[fld] for p in orc['pulses'][c]], dtype=int)
            np.testing.assert_array_equal(got, want, err_msg=f'core{c} {k}')


def test_time_wrap_int32_parity():
    """Past 2^31 the 32-bit hardware counters wrap; engine and oracle
    must diverge identically (two's-complement semantics, oracle doc)."""
    cmds = [
        isa.alu_cmd('inc_qclk', 'i', 0x7ff00000),
        isa.alu_cmd('inc_qclk', 'i', 0x7ff00000),     # qclk wraps negative
        isa.pulse_cmd(freq_word=1, cfg_word=0, env_word=(2 << 12),
                      cmd_time=0xffe00100),           # trig in wrapped region
        isa.alu_cmd('reg_alu', 'i', 0x7fffffff, 'add', 0, write_reg_addr=0),
        isa.done_cmd(),
    ]
    prog = mp_of(cmds)
    jx = simulate(prog, max_pulses=4)
    orc = run_oracle(prog)
    np.testing.assert_array_equal(np.asarray(jx['time']), orc['time'])
    np.testing.assert_array_equal(np.asarray(jx['qclk']), orc['qclk'])
    np.testing.assert_array_equal(np.asarray(jx['regs']), orc['regs'])
    assert int(jx['qclk'][0]) < 0                     # wrap actually happened
    n = int(jx['n_pulses'][0])
    assert n == len(orc['pulses'][0])
    for k in ('qtime', 'gtime'):
        np.testing.assert_array_equal(
            np.asarray(jx['rec_' + k][0, :n]),
            np.array([p[k] for p in orc['pulses'][0]], dtype=int))


def test_batched_shots_divergent_control_flow():
    # active reset over a shot batch: per-shot branch divergence
    cmds = [
        isa.pulse_cmd(freq_word=3, cfg_word=2, env_word=(2 << 12) | 0,
                      cmd_time=10),
        isa.idle(80),
        isa.alu_cmd('jump_fproc', 'i', 1, 'eq', jump_cmd_ptr=4, func_id=0),
        isa.jump_i(5),
        isa.pulse_cmd(freq_word=9, cfg_word=0, env_word=(2 << 12) | 0,
                      cmd_time=200),
        isa.done_cmd(),
    ]
    prog = mp_of(cmds)
    bits = np.array([[[0]], [[1]], [[1]], [[0]]])   # [shots, cores, meas]
    out = simulate_batch(prog, bits)
    np.testing.assert_array_equal(
        np.asarray(out['n_pulses'])[:, 0], [1, 2, 2, 1])
    assert np.all(np.asarray(out['err']) == 0)


def test_oracle_sticky_returns_latest_bit():
    # two measurements; read after both -> second bit (sticky semantics)
    cmds = [
        isa.pulse_cmd(freq_word=3, cfg_word=2, env_word=(2 << 12) | 0,
                      cmd_time=10),
        isa.pulse_cmd(freq_word=3, cfg_word=2, env_word=(2 << 12) | 0,
                      cmd_time=300),
        isa.idle(500),
        isa.read_fproc(func_id=0, write_reg_addr=2),
        isa.done_cmd(),
    ]
    prog = mp_of(cmds)
    out = simulate(prog, meas_bits=np.array([[0, 1]]))
    assert int(out['regs'][0, 2]) == 1
    orc = run_oracle(prog, meas_bits=np.array([[0, 1]]))
    assert orc['regs'][0, 2] == 1


def test_lut_fabric_syndrome_distribution():
    """fproc_lut mode: cores 0/1 measure; core 2 branches on the parity
    LUT output (reference: hdl/fproc_lut.sv + meas_lut.sv semantics)."""
    rd = lambda: isa.pulse_cmd(freq_word=3, cfg_word=2,
                               env_word=(2 << 12) | 0, cmd_time=10)
    core_meas = [rd(), isa.done_cmd()]
    core_read = [
        isa.idle(200),
        isa.alu_cmd('jump_fproc', 'i', 1, 'eq', jump_cmd_ptr=3, func_id=1),
        isa.jump_i(4),
        isa.pulse_cmd(freq_word=9, cfg_word=0, env_word=(2 << 12) | 0,
                      cmd_time=400),
        isa.done_cmd(),
    ]
    prog = mp_of(core_meas, list(core_meas), core_read)
    # parity LUT over cores 0,1; all-cores output mask
    table = tuple(0b111 if bin(a).count('1') & 1 else 0 for a in range(4))
    kw = dict(fabric='lut', lut_mask=(True, True, False), lut_table=table)
    for bits, expect_pulse in (((0, 0), 0), ((1, 0), 1), ((0, 1), 1),
                               ((1, 1), 0)):
        mb = np.array([[bits[0]], [bits[1]], [0]])
        out = simulate(prog, meas_bits=mb, **kw)
        assert int(out['n_pulses'][2]) == expect_pulse, (bits, expect_pulse)
        assert int(out['err'][2]) == 0
        orc = run_oracle(prog, meas_bits=mb, fabric='lut',
                         lut_mask=(True, True, False), lut_table=table)
        assert len(orc['pulses'][2]) == expect_pulse


def test_lut_fabric_own_fresh_read():
    """func_id 0 in lut mode waits for the core's own fresh measurement."""
    cmds = [
        isa.pulse_cmd(freq_word=3, cfg_word=2, env_word=(2 << 12) | 0,
                      cmd_time=10),
        isa.alu_cmd('alu_fproc', 'i', 0, 'id1', write_reg_addr=5, func_id=0),
        isa.done_cmd(),
    ]
    prog = mp_of(cmds)
    out = simulate(prog, meas_bits=np.array([[1]]), fabric='lut',
                   lut_mask=(True,), lut_table=(0, 1))
    assert int(out['regs'][0, 5]) == 1
    # fresh semantics: completion waits for meas_avail (pulse end + 64)
    assert int(out['time'][0]) >= 76


def test_instruction_trace_export():
    cmds = [
        isa.alu_cmd('reg_alu', 'i', 7, 'id0', write_reg_addr=0),
        isa.pulse_cmd(freq_word=1, cfg_word=0, cmd_time=20),
        isa.done_cmd(),
    ]
    out = simulate(mp_of(cmds), trace=True, max_steps=8)
    steps = int(out['steps'])
    pcs = list(np.asarray(out['trace_pc'][0, :steps]))
    assert pcs == [0, 1, 2]
    times = list(np.asarray(out['trace_time'][0, :steps]))
    assert times[0] == 2 and times[1] == 7   # INIT_TIME, +alu_instr_clks


def test_large_program_gather_fetch_matches_oracle():
    """A deep RB program (past the one-hot/gather fetch crossover) must
    execute identically to the scalar oracle — pins the gather fetch
    path (interpreter._FETCH_ONEHOT_MAX)."""
    import numpy as np
    from distributed_processor_tpu.simulator import Simulator
    from distributed_processor_tpu.models.rb import rb_program
    from distributed_processor_tpu.sim.interpreter import _FETCH_ONEHOT_MAX
    from distributed_processor_tpu.sim.oracle import run_oracle

    sim = Simulator(n_qubits=1)
    depth = 80
    mp = sim.compile(rb_program(['Q0'], depth, seed=11))
    assert mp.n_instr > _FETCH_ONEHOT_MAX, 'program too small for the test'
    out = sim.run(mp, shots=2, max_steps=mp.n_instr + 32,
                  max_pulses=int(mp.max_pulses_per_core(1)) + 4,
                  max_meas=4, max_resets=2)
    assert not bool(out['incomplete'])
    assert np.all(np.asarray(out['err']) == 0)
    o = run_oracle(mp)
    n = int(np.asarray(out['n_pulses'])[0, 0])
    assert n == len(o['pulses'][0]) > depth
    for fld, key in (('gtime', 'rec_gtime'), ('amp', 'rec_amp'),
                     ('phase', 'rec_phase'), ('env', 'rec_env'),
                     ('freq', 'rec_freq'), ('elem', 'rec_elem')):
        np.testing.assert_array_equal(
            np.asarray(out[key])[0, 0, :n],
            [p[fld] for p in o['pulses'][0]], err_msg=fld)
    np.testing.assert_array_equal(np.asarray(out['qclk'])[0], o['qclk'])


def test_record_pulses_off_same_results():
    """record_pulses=False must not change any semantic output — only
    drop the rec_* arrays (a memory/bandwidth knob for stats-only runs,
    where the loop-carried record state cannot be dead-code-eliminated)."""
    cmds = [
        isa.pulse_cmd(freq_word=3, cfg_word=2, env_word=(2 << 12) | 0,
                      cmd_time=10),
        isa.idle(80),
        isa.alu_cmd('jump_fproc', 'i', 1, 'eq', jump_cmd_ptr=4, func_id=0),
        isa.jump_i(5),
        isa.pulse_cmd(freq_word=9, cfg_word=0, env_word=(2 << 12) | 0,
                      cmd_time=200),
        isa.done_cmd(),
    ]
    prog = mp_of(cmds)
    bits = np.array([[[0]], [[1]], [[1]], [[0]]])
    on = simulate_batch(prog, bits)
    off = simulate_batch(prog, bits, record_pulses=False)
    assert not any(k.startswith('rec_') for k in off)
    for k in ('n_pulses', 'err', 'qclk', 'done', 'regs', 'n_meas'):
        np.testing.assert_array_equal(np.asarray(on[k]), np.asarray(off[k]))


def test_record_pulses_off_physics():
    """The physics-closed loop works without pulse records (its own
    meas_* bookkeeping is independent of rec_*)."""
    from distributed_processor_tpu.simulator import Simulator
    from distributed_processor_tpu.models.experiments import active_reset
    from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                       run_physics_batch)
    sim = Simulator(n_qubits=2)
    mp = sim.compile(active_reset(['Q0', 'Q1']))
    init = np.array([[1, 0], [0, 1]], np.int32)
    out = run_physics_batch(
        mp, ReadoutPhysics(sigma=0.01), 0, 2, init_states=init,
        max_steps=mp.n_instr * 4 + 64, max_pulses=32, max_meas=4,
        record_pulses=False)
    assert not bool(out['incomplete'])
    np.testing.assert_array_equal(
        np.asarray(out['meas_bits'])[:, :, 0], init)
    np.testing.assert_array_equal(np.asarray(out['n_pulses']), 2 + 2 * init)
    assert 'rec_gtime' not in out


def test_waveforms_requires_records():
    from distributed_processor_tpu.simulator import Simulator
    from distributed_processor_tpu.models.experiments import active_reset
    sim = Simulator(n_qubits=1)
    out = sim.run(active_reset(['Q0']), record_pulses=False)
    with pytest.raises(ValueError, match='record_pulses'):
        sim.waveforms(out)
