"""Sampled-bit calibration recovery at realistic scale (round-3 weak #4).

The bloch-device expectation tests read ``meas_p1`` with one shot and
sigma=0 — exact but not what a real calibration run does.  This is the
real workflow: per-core device parameters are recovered from SAMPLED
BITS, at realistic shot counts, through the NOISY readout channel
(finite sigma -> a few % assignment error), with every point executed
by the dp-sharded sweep driver over the 8-device CPU mesh — the same
path a hardware calibration would take (readout + fproc contract,
reference: python/distproc/hwconfig.py:69-98).

The free amplitude/offset in the fitters absorbs the readout-error
contrast loss ((1-2*eps) scaling), so frequency and decay constants
recover unbiased; tolerances are CI-stable at these shot counts.
"""

import numpy as np
import pytest

from distributed_processor_tpu.analysis import fit_ramsey, fit_t1
from distributed_processor_tpu.models.experiments import (ramsey_program,
                                                          t1_program)
from distributed_processor_tpu.parallel import run_physics_sweep, make_mesh
from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.sim.device import DeviceModel
from distributed_processor_tpu.sim.physics import ReadoutPhysics

KW = dict(max_steps=2000, max_pulses=32, max_meas=2)
SHOTS, BATCH = 2048, 2048     # per delay point; dp=8 -> 256 per shard


def _p1_curves(sim, programs, model, mesh, key0=0):
    """meas1_rate per core per program point, via the sweep driver."""
    curves = []
    for i, prog in enumerate(programs):
        mp = sim.compile(prog)
        out = run_physics_sweep(mp, model, SHOTS, BATCH, key=key0 + i,
                                mesh=mesh, **KW)
        assert out['err_shots'] == 0 and out['incomplete_batches'] == 0
        curves.append(out['meas1_rate'])
    return np.stack(curves)                      # [points, n_cores]


def test_ramsey_detuning_per_core_from_sampled_bits():
    """Per-core detunings recovered from noisy sampled-bit Ramsey
    fringes on the mesh — distinct values per core, ~15 readout-error
    percent contrast loss absorbed by the fit."""
    mesh = make_mesh(n_dp=8)
    sim = Simulator(n_qubits=2)
    det = (0.5e6, 0.8e6)
    model = ReadoutPhysics(
        sigma=15.0, p1_init=0.0,
        device=DeviceModel('bloch', detuning_hz=det, t2_s=40e-6))
    delays = np.linspace(0.1e-6, 6.1e-6, 14)
    # both qubits swept in one program: Q0's Ramsey then Q1's
    progs = [ramsey_program('Q0', float(d)) + ramsey_program('Q1', float(d))
             for d in delays]
    curves = _p1_curves(sim, progs, model, mesh)
    for c, want in enumerate(det):
        f, _, _ = fit_ramsey(delays, curves[:, c])
        np.testing.assert_allclose(f, want, rtol=0.05)


def test_t1_per_core_from_sampled_bits():
    """Per-core T1 recovered from sampled-bit decay through the noisy
    channel on the mesh."""
    mesh = make_mesh(n_dp=8)
    sim = Simulator(n_qubits=2)
    t1s = (12e-6, 25e-6)
    model = ReadoutPhysics(
        sigma=15.0, p1_init=0.0,
        device=DeviceModel('bloch', t1_s=t1s))
    delays = np.linspace(0.5e-6, 45e-6, 10)
    progs = [t1_program('Q0', float(d)) + t1_program('Q1', float(d))
             for d in delays]
    curves = _p1_curves(sim, progs, model, mesh)
    for c, want in enumerate(t1s):
        t1, _ = fit_t1(delays, curves[:, c])
        np.testing.assert_allclose(t1, want, rtol=0.12)


def test_assignment_error_is_really_there():
    """The channel is genuinely noisy at sigma=15: a |0>-prep read
    misassigns a few percent of shots — the recovery tests above go
    through a lossy channel, not a disguised noise-free one."""
    mesh = make_mesh(n_dp=8)
    sim = Simulator(n_qubits=1)
    mp = sim.compile([{'name': 'read', 'qubit': ['Q0']}])
    model = ReadoutPhysics(sigma=15.0, p1_init=0.0,
                           device=DeviceModel('bloch'))
    out = run_physics_sweep(mp, model, SHOTS, BATCH, key=3, mesh=mesh,
                            **KW)
    eps = float(out['meas1_rate'][0])
    assert 0.005 < eps < 0.15, eps
