"""Full-pipeline fuzzing: random dict programs through compile ->
assemble -> decode -> execute, JAX engine vs scalar oracle.

The randomized ISA tests (test_interpreter.py) fuzz hand-assembled
machine programs; this fuzzes the whole stack above them — gate
resolution, scheduling, assembly, decoding — using program-level
constructs (gates, virtual-z, barriers, delays, measurement branches,
counter loops).  Any engine/oracle divergence indicates a compiler,
assembler, decoder, or interpreter bug.
"""

import numpy as np
import pytest

from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.sim.oracle import run_oracle
from distributed_processor_tpu.sim import simulate


def _random_program(rng, qubits):
    """A random well-formed 2-qubit program using the compiler surface."""
    prog = []
    n = int(rng.integers(4, 10))
    loop_done = False
    for _ in range(n):
        r = int(rng.integers(0, 8))
        q = qubits[int(rng.integers(len(qubits)))]
        if r <= 2:
            prog.append({'name': rng.choice(['X90', 'Z90']), 'qubit': [q]})
        elif r == 3:
            prog.append({'name': 'virtual_z', 'qubit': q,
                         'phase': float(rng.uniform(-np.pi, np.pi))})
        elif r == 4:
            prog.append({'name': 'barrier', 'qubit': list(qubits)})
        elif r == 5:
            prog.append({'name': 'delay',
                         't': float(rng.integers(1, 50)) * 4e-9,
                         'qubit': [q]})
        elif r == 6:
            prog.append({'name': 'read', 'qubit': [q]})
            # arms must be z-phase-consistent at the join (the compiler
            # rejects divergent virtual-z accumulation, as the
            # reference does) — X90-only arms keep phases equal
            prog.append({
                'name': 'branch_fproc', 'alu_cond': 'eq', 'cond_lhs': 1,
                'func_id': f'{q}.meas', 'scope': [q],
                'true': [{'name': 'X90', 'qubit': [q]},
                         {'name': 'X90', 'qubit': [q]}],
                'false': [{'name': 'X90', 'qubit': [q]}]})
        elif not loop_done:
            loop_done = True          # one counter loop per program
            var = 'fz'
            reps = int(rng.integers(1, 4))
            body = [{'name': 'X90', 'qubit': [q]}]
            if rng.integers(2):       # branch inside the loop body (the
                reps = min(reps, 2)   # shape that exposed the ctrl-block
                body += [             # name collision, review round 2)
                    {'name': 'read', 'qubit': [q]},
                    {'name': 'branch_fproc', 'alu_cond': 'eq',
                     'cond_lhs': 1, 'func_id': f'{q}.meas', 'scope': [q],
                     'true': [{'name': 'X90', 'qubit': [q]},
                              {'name': 'X90', 'qubit': [q]}],
                     'false': [{'name': 'X90', 'qubit': [q]}]}]
            body.append({'name': 'alu', 'op': 'add', 'lhs': 1,
                         'rhs': var, 'out': var})
            prog.append({'name': 'declare', 'var': var, 'dtype': 'int',
                         'scope': [q]})
            prog.append({'name': 'loop', 'cond_lhs': reps,
                         'cond_rhs': var, 'alu_cond': 'ge', 'scope': [q],
                         'body': body})
    prog.append({'name': 'read', 'qubit': [qubits[0]]})
    return prog


@pytest.mark.parametrize('seed', range(8))
def test_random_program_engine_vs_oracle(seed):
    rng = np.random.default_rng(3000 + seed)
    sim = Simulator(n_qubits=2)
    mp = sim.compile(_random_program(rng, ['Q0', 'Q1']))
    bits = rng.integers(0, 2, size=(mp.n_cores, 6))
    cfg = sim.interpreter_config(mp, max_meas=6)
    out = simulate(mp, meas_bits=bits, cfg=cfg)
    orc = run_oracle(mp, meas_bits=bits, max_steps=cfg.max_steps)

    np.testing.assert_array_equal(np.asarray(out['regs']), orc['regs'],
                                  err_msg=f'seed {seed} regs')
    np.testing.assert_array_equal(np.asarray(out['qclk']), orc['qclk'],
                                  err_msg=f'seed {seed} qclk')
    assert np.all(np.asarray(out['done']) == orc['done']), seed
    for c in range(mp.n_cores):
        n = int(np.asarray(out['n_pulses'])[c])
        assert n == len(orc['pulses'][c]), (seed, c)
        for fld, key in (('gtime', 'rec_gtime'), ('qtime', 'rec_qtime'),
                         ('env', 'rec_env'), ('phase', 'rec_phase'),
                         ('freq', 'rec_freq'), ('amp', 'rec_amp'),
                         ('elem', 'rec_elem'), ('dur', 'rec_dur')):
            got = np.asarray(out[key][c, :n])
            want = np.array([p[fld] for p in orc['pulses'][c]], dtype=int)
            np.testing.assert_array_equal(
                got, want, err_msg=f'seed {seed} core {c} {fld}')
    # engine error bits and oracle error lists agree on "clean or not"
    for c in range(mp.n_cores):
        assert (int(np.asarray(out['err'])[c]) != 0) \
            == (len(orc['err'][c]) != 0), (seed, c, orc['err'][c])


@pytest.mark.parametrize('seed', range(3))
def test_random_program_sharded_matches_local(seed):
    """Sharding over the CPU mesh must be bit-identical to local
    execution for arbitrary compiled programs."""
    from distributed_processor_tpu.parallel import make_mesh, sharded_simulate
    from distributed_processor_tpu.sim import simulate_batch

    rng = np.random.default_rng(4000 + seed)
    sim = Simulator(n_qubits=2)
    mp = sim.compile(_random_program(rng, ['Q0', 'Q1']))
    cfg = sim.interpreter_config(mp, max_meas=6)
    bits = rng.integers(0, 2, size=(16, mp.n_cores, 6))
    mesh = make_mesh(n_dp=8)
    sharded = sharded_simulate(mp, bits, mesh, cfg=cfg)
    local = simulate_batch(mp, bits, cfg=cfg)
    for k in ('n_pulses', 'regs', 'qclk', 'err', 'rec_gtime', 'rec_amp'):
        np.testing.assert_array_equal(
            np.asarray(sharded[k]), np.asarray(local[k]),
            err_msg=f'seed {seed} {k}')


@pytest.mark.parametrize('seed', range(6))
def test_random_program_physics_vs_oracle(seed):
    """Random feedback programs with the measurement loop closed by the
    DSP chain: the control flow the physics engine takes under its
    emergent (noisy) bits must equal the scalar oracle's under those
    same bits injected cocotb-style — for arbitrary compiled programs,
    not just the active-reset idiom."""
    from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                       run_physics_batch)
    rng = np.random.default_rng(5000 + seed)
    sim = Simulator(n_qubits=2)
    mp = sim.compile(_random_program(rng, ['Q0', 'Q1']))
    base = sim.interpreter_config(mp, max_meas=6)
    model = ReadoutPhysics(sigma=30.0)      # noise flips some bits
    shots = 4
    init = rng.integers(0, 2, (shots, mp.n_cores)).astype(np.int32)
    out = run_physics_batch(mp, model, seed, shots, init_states=init,
                            cfg=base, max_steps=base.max_steps * 2)
    assert not bool(out['incomplete']), seed
    bits = np.asarray(out['meas_bits'])
    for s in range(shots):
        orc = run_oracle(mp, meas_bits=bits[s],
                         max_steps=base.max_steps * 2)
        np.testing.assert_array_equal(np.asarray(out['regs'])[s],
                                      orc['regs'], err_msg=f'{seed}/{s}')
        np.testing.assert_array_equal(np.asarray(out['qclk'])[s],
                                      orc['qclk'], err_msg=f'{seed}/{s}')
        assert np.all(np.asarray(out['done'])[s] == orc['done']), (seed, s)
        for c in range(mp.n_cores):
            n = int(np.asarray(out['n_pulses'])[s, c])
            assert n == len(orc['pulses'][c]), (seed, s, c)
            for fld, key in (('gtime', 'rec_gtime'), ('env', 'rec_env'),
                             ('phase', 'rec_phase'), ('amp', 'rec_amp'),
                             ('elem', 'rec_elem')):
                got = np.asarray(out[key][s, c, :n])
                want = np.array([p[fld] for p in orc['pulses'][c]],
                                dtype=int)
                np.testing.assert_array_equal(
                    got, want, err_msg=f'{seed}/{s} core {c} {fld}')
