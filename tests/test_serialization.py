"""Serialization round-trips, schedule linting, and on-device loop
execution semantics (qclk rewind)."""

import json
import numpy as np
import pytest

import distributed_processor_tpu as dp
from distributed_processor_tpu import compiler as cm
from distributed_processor_tpu.ir.program import IRProgram
from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.sim import simulate

from test_compiler import compile_program, sorted_prog_dict, FAST_CLOCKS


@pytest.fixture(scope='module')
def qchip(qchipcfg_path):
    return dp.QChip(qchipcfg_path)


MULTIRST = [
    {'name': 'X90', 'qubit': ['Q0']},
    {'name': 'branch_fproc', 'alu_cond': 'eq', 'cond_lhs': 1,
     'func_id': 'Q0.meas', 'true': [],
     'false': [{'name': 'X90', 'qubit': ['Q0']}], 'scope': ['Q0']},
    {'name': 'branch_fproc', 'alu_cond': 'eq', 'cond_lhs': 1,
     'func_id': 'Q1.meas', 'true': [],
     'false': [{'name': 'X90', 'qubit': ['Q1']}], 'scope': ['Q1']},
    {'name': 'X90', 'qubit': ['Q1']}]


def test_serialize_roundtrip_after_every_pass(qchip):
    """The IR must survive serialize -> rebuild at every pass boundary
    and still compile to the same per-core asm (the reference proves the
    same property in test_serialize_multrst, test_compiler.py:608-649)."""
    fpga_config = dp.FPGAConfig()
    passes = cm.get_passes(fpga_config, qchip)
    ref = compile_program(MULTIRST, qchip, fpga_config).compile()
    ref_prog = sorted_prog_dict(ref)

    for cut in range(len(passes) + 1):
        ir_prog = IRProgram(MULTIRST)
        for p in passes[:cut]:
            p.run_pass(ir_prog)
        rebuilt = IRProgram(ir_prog.serialize())
        for p in passes[cut:]:
            p.run_pass(rebuilt)
        compiler = dp.Compiler(MULTIRST)
        compiler.ir_prog = rebuilt
        got = sorted_prog_dict(compiler.compile())
        canon = lambda d: json.dumps({str(k): v for k, v in d.items()},
                                     default=str, sort_keys=True)
        assert canon(got) == canon(ref_prog), \
            f'mismatch when serializing after pass {cut}'


def test_compiled_program_save_load_roundtrip(tmp_path, qchip,
                                              channelcfg_path):
    from test_compiler import MockElement
    prog = compile_program(MULTIRST, qchip, dp.FPGAConfig()).compile()
    path = str(tmp_path / 'prog.json')
    prog.save(path)
    loaded = cm.load_compiled_program(path)
    assert loaded.fpga_config.alu_instr_clks == 5

    channel_configs = dp.load_channel_configs(channelcfg_path)
    a1 = dp.GlobalAssembler(prog, channel_configs,
                            MockElement).get_assembled_program()
    a2 = dp.GlobalAssembler(loaded, channel_configs,
                            MockElement).get_assembled_program()
    assert sorted(a1.keys()) == sorted(a2.keys())
    for core in a1:
        assert a1[core]['cmd_buf'] == a2[core]['cmd_buf']


def _user_scheduled(start2: int):
    env = {'env_func': 'square', 'paradict': {'phase': 0, 'amplitude': 1}}
    return [
        {'name': 'pulse', 'freq': 100e6, 'phase': 0, 'amp': 0.5,
         'twidth': 24e-9, 'env': env, 'dest': 'Q0.qdrv', 'start_time': 5},
        {'name': 'pulse', 'freq': 100e6, 'phase': 0, 'amp': 0.5,
         'twidth': 24e-9, 'env': env, 'dest': 'Q0.qdrv',
         'start_time': start2},
    ]


def test_lint_schedule_rejects_tight_timing(qchip):
    flags = cm.CompilerFlags(resolve_gates=False, schedule=False)
    # second pulse would issue before the pipeline frees (5 + 3 clks)
    with pytest.raises(Exception):
        compiler = dp.Compiler(_user_scheduled(6))
        compiler.run_ir_passes(cm.get_passes(dp.FPGAConfig(), qchip,
                                             compiler_flags=flags))
    # properly spaced version lints clean
    compiler = dp.Compiler(_user_scheduled(30))
    compiler.run_ir_passes(cm.get_passes(dp.FPGAConfig(), qchip,
                                         compiler_flags=flags))
    assert compiler.compile() is not None


def test_loop_qclk_rewind_execution(qchip):
    """On-device loop: each iteration re-triggers the same cmd_time via
    the inc_qclk rewind (reference: compiler.py:322-324); global pulse
    times advance by the loop delta_t."""
    program = [
        {'name': 'declare', 'var': 'i', 'dtype': 'int', 'scope': ['Q0']},
        {'name': 'set_var', 'var': 'i', 'value': 1},
        {'name': 'loop', 'cond_lhs': 5, 'cond_rhs': 'i', 'alu_cond': 'ge',
         'scope': ['Q0'],
         'body': [{'name': 'X90', 'qubit': ['Q0']},
                  {'name': 'alu', 'op': 'add', 'lhs': 1, 'rhs': 'i',
                   'out': 'i'}]},
        {'name': 'read', 'qubit': ['Q0']},
    ]
    mp = compile_to_machine(program, qchip, n_qubits=1)
    out = simulate(mp, max_steps=512, max_pulses=16, max_meas=4)
    assert int(out['err'][0]) == 0 and bool(out['done'][0])
    n = int(out['n_pulses'][0])
    assert n == 5 + 2          # 5 loop X90s + rdrv/rdlo read pair
    elems = np.asarray(out['rec_elem'][0, :n])
    qt = np.asarray(out['rec_qtime'][0, :n])
    gt = np.asarray(out['rec_gtime'][0, :n])
    loop_idx = np.nonzero(elems == 0)[0]
    # every iteration re-fires at the same qclk time...
    assert len(set(qt[loop_idx])) == 1
    # ...but globally spaced by a constant delta_t
    deltas = np.diff(gt[loop_idx])
    assert len(set(deltas)) == 1 and deltas[0] > 0
    # loop counter ended at 6 (ran i = 1..5)
    assert int(out['regs'][0, 0]) == 6 or 6 in np.asarray(out['regs'][0])
