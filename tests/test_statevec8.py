"""Statevec entangling device at flagship scale: C=8 compiled-path runs.

Round-4 review missing #1: every statevec test stopped at 4 qubits while
the reference ecosystem treats two-qubit calibrations as first-class at
full system size (reference: python/test/qubitcfg.json:1152 Q5Q4CNOT in
an 8-qubit library; python/distproc/hwconfig.py:112-115 N_CORES=8).
These tests run the [shots, 2^8] trajectory engine through the full
compiled path at C=8:

* GHZ-8: an H + 7-CNOT chain prepares the 8-qubit GHZ state and every
  shot's sampled bits agree across the whole chain (shot-exact parity,
  the entanglement witness a product state cannot fake).
* Distance-5 repetition with a correlated 2q error, embedded in an
  8-core machine: the pair channel's both-flip signature shows up in
  the syndrome correlations, and — unlike distance 3, which one
  correlated event defeats (tests/test_repetition_correlated.py) —
  the 5-qubit majority vote corrects every single pair event exactly.
"""

import numpy as np

from distributed_processor_tpu.simulator import Simulator
from distributed_processor_tpu.models.coupling import couplings_from_qchip
from distributed_processor_tpu.models.default_qchip import make_default_qchip
from distributed_processor_tpu.models.experiments import ghz_program
from distributed_processor_tpu.models.repetition import (
    correlated_noise_stage, majority_lut, repetition_logical_program)
from distributed_processor_tpu.sim.device import DeviceModel
from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                   run_physics_batch)

N = 8


def test_ghz8_shot_exact_parity():
    """All 8 sampled bits agree on every shot of a GHZ-8 preparation,
    with ~50/50 marginals — through compile, the discrete-event ordering
    gate (7 chained CR couplings), joint projective measurement, and the
    physics-closed readout chain at C=8."""
    sim = Simulator(n_qubits=N)
    qchip = make_default_qchip(N)
    mp = sim.compile(ghz_program([f'Q{i}' for i in range(N)]))
    cps = couplings_from_qchip(mp, qchip)
    assert len(cps) == N - 1          # the full CNOT chain is coupled
    model = ReadoutPhysics(sigma=0.0, device=DeviceModel(
        'statevec', couplings=cps))
    shots = 256
    out = run_physics_batch(mp, model, 2, shots,
                            init_states=np.zeros((shots, N), np.int32),
                            max_steps=40000, max_pulses=256, max_meas=4)
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))
    bits = np.asarray(out['meas_bits'])[:, :, 0]
    assert np.all(bits == bits[:, :1]), \
        'GHZ-8 bits must agree across all 8 cores on every shot'
    assert 0.4 < bits[:, 0].mean() < 0.6
    # every adjacent-pair ZZ parity is exactly +1
    for a in range(N - 1):
        zz = (1 - 2 * bits[:, a]) * (1 - 2 * bits[:, a + 1])
        assert zz.mean() == 1.0


def test_repetition5_correlated_error_at_c8():
    """Distance-5 repetition round in an 8-core machine (3 spectator
    cores read |0> and stay outside the LUT mask): a correlated (0,1)
    pair channel at p2=0.3 produces the both-flip syndrome correlation
    (P(both) = 4*p2/15, far above the independence product), and the
    5-way majority vote corrects every shot — a single pair event flips
    at most 2 of 5 data qubits, below the distance-5 threshold that
    defeats distance 3."""
    nd, p2, shots = 5, 0.3, 2048
    sim = Simulator(n_qubits=N)
    qchip = make_default_qchip(N)
    prog = repetition_logical_program(
        nd, correlated_noise_stage([(0, 1)], qchip)) + \
        [{'name': 'read', 'qubit': [f'Q{i}']} for i in range(nd, N)]
    mp = sim.compile(prog)
    assert mp.n_cores == N
    cps = couplings_from_qchip(mp, qchip)
    model = ReadoutPhysics(sigma=0.0, device=DeviceModel(
        'statevec', couplings=cps, depol2_per_pulse=p2))
    out = run_physics_batch(
        mp, model, 3, shots, init_states=np.zeros((shots, N), np.int32),
        max_steps=40000, max_pulses=16, max_meas=2,
        fabric='lut', lut_mask=(True,) * nd + (False,) * (N - nd),
        lut_table=majority_lut(nd))
    assert not bool(out['incomplete'])
    assert not np.any(np.asarray(out['err']))
    syn = np.asarray(out['meas_state'])[:, :nd, 0]    # pre-correction
    fin = np.asarray(out['meas_bits'])[:, :nd, 1]     # post-correction
    # both-flip correlation: P(flip0 & flip1) = 4*p2/15, >> independent
    p_both = float((syn[:, 0] & syn[:, 1]).mean())
    want = 4.0 * p2 / 15.0
    se = np.sqrt(want * (1 - want) / shots)
    assert abs(p_both - want) < 4 * se, (p_both, want)
    assert p_both > 2.0 * syn[:, 0].mean() * syn[:, 1].mean()
    # marginal flip rate per coupled qubit = 8*p2/15
    marg = 8.0 * p2 / 15.0
    se_m = np.sqrt(marg * (1 - marg) / shots)
    for q in (0, 1):
        assert abs(syn[:, q].mean() - marg) < 4 * se_m
    assert not np.any(syn[:, 2:])                     # untouched qubits
    # distance 5 corrects every single pair event: zero logical errors
    # AND a fully restored codeword on every shot
    assert not np.any(fin), 'distance-5 must correct all pair events'
    # spectator cores measured |0> and stayed out of the syndrome
    spect = np.asarray(out['meas_bits'])[:, nd:, 0]
    assert not np.any(spect)
