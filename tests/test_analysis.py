"""Experiment-curve fitting (analysis.py): synthetic noisy data with
known ground truth; fits must recover the parameters."""

import numpy as np

from distributed_processor_tpu.analysis import (fit_exp_decay, fit_t1,
                                                fit_rb, fit_ramsey)


def test_exp_decay_recovers_parameters():
    rng = np.random.default_rng(0)
    x = np.linspace(0, 100e-6, 40)
    y = 0.9 * np.exp(-x / 25e-6) + 0.05 + rng.normal(0, 0.01, x.shape)
    a, tau, c = fit_exp_decay(x, y)
    assert abs(a - 0.9) < 0.05
    assert abs(tau - 25e-6) < 2e-6
    assert abs(c - 0.05) < 0.03


def test_t1():
    x = np.linspace(0, 200e-6, 30)
    y = np.exp(-x / 42e-6)
    t1, _ = fit_t1(x, y)
    assert abs(t1 - 42e-6) < 1e-6


def test_rb_decay():
    rng = np.random.default_rng(1)
    depths = np.array([1, 2, 4, 8, 16, 32, 64, 128])
    p_true = 0.985
    surv = 0.48 * p_true ** depths + 0.5 \
        + rng.normal(0, 0.004, depths.shape)
    p, epc, (A, pf, B) = fit_rb(depths, surv)
    assert abs(p - p_true) < 0.004
    assert abs(epc - (1 - p_true) / 2) < 0.002
    assert abs(B - 0.5) < 0.05


def test_ramsey_frequency_and_t2():
    rng = np.random.default_rng(2)
    t = np.linspace(0, 20e-6, 200)
    f_true, t2_true = 350e3, 8e-6
    y = 0.45 * np.exp(-t / t2_true) * np.cos(2 * np.pi * f_true * t) \
        + 0.5 + rng.normal(0, 0.01, t.shape)
    f, t2, _ = fit_ramsey(t, y)
    assert abs(f - f_true) / f_true < 0.02
    assert abs(t2 - t2_true) / t2_true < 0.25


def test_rb_decay_unplateaued():
    """Robustness: a sweep that stops before the survival plateau gives
    a poor asymptote initialization; the adaptive (Levenberg) damping
    must still converge instead of walking p to 0."""
    depths = np.array([1, 2, 4, 8, 16, 32])
    surv = 0.5 * 0.99 ** depths + 0.5
    p, epc, _ = fit_rb(depths, surv)
    assert abs(p - 0.99) < 0.003
