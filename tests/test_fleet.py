"""Fleet federation under replica loss (serve/{transport,router,fleet}.py).

The fleet contract, pinned here (docs/FLEET.md):

* **Bit-identity across the wire**: a result served by a replica
  PROCESS over TCP equals the solo ``simulate_batch`` run per stat —
  federation is an availability layer, never a semantic one.
* **Typed errors cross the wire intact**: program-class failures
  (FaultError with its per-code counts, ProgramValidationError)
  pickle-round-trip and are NEVER retried; infrastructure errors are.
* **Replica loss is survivable**: SIGKILL a replica mid-flight and
  every recovered request completes bit-identically on a survivor;
  a SIGSTOP-wedged replica (TCP open, zero progress) is caught by
  gossip staleness, failed over, and re-admitted on SIGCONT.
* **Shared warm tiers**: a respawned replica replays the shared
  catalog and serves its first request with ZERO cold compiles.
* **No hung handles**: router shutdown fails everything pending with
  ShutdownError, same contract as the service.

This module is listed in tools/check_junit.py NO_SKIP_MODULES: it
spawns replica subprocesses on localhost TCP + the forced CPU backend
and has no legitimate skip condition.
"""

import pickle
import socket
import time

import numpy as np
import pytest

import jax

from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import (ProgramValidationError,
                                               machine_program_from_cmds)
from distributed_processor_tpu.serve import (CancelledError,
                                             DeadlineError,
                                             ExecutorLostError,
                                             FleetRouter, OverloadError,
                                             ReplicaLostError,
                                             RetryPolicy,
                                             ServiceClosedError,
                                             ShutdownError,
                                             is_terminal_error)
from distributed_processor_tpu.serve.benchmark import _workload
from distributed_processor_tpu.serve.fleet import Fleet
from distributed_processor_tpu.serve.transport import _picklable_error
from distributed_processor_tpu.sim.interpreter import (FaultError,
                                                       InterpreterConfig,
                                                       simulate_batch)

pytestmark = [pytest.mark.serve, pytest.mark.fleet]


@pytest.fixture(autouse=True)
def _serve_thread_leak_probe():
    """Override the per-test conftest probe: the module-scoped Fleet
    below keeps router/wire threads alive across tests BY DESIGN.  The
    leak boundary moves to module teardown (the autouse module fixture
    next), after the fleet has shut down."""
    yield


@pytest.fixture(autouse=True, scope='module')
def _fleet_thread_boundary():
    """After the module-scoped fleet shuts down, every dproc-serve*
    thread (router gossip/retry, fleet monitor, wire readers/waiters)
    must be joined — prints the junit-gated marker otherwise."""
    import threading
    yield
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        leaked = sorted(t.name for t in threading.enumerate()
                        if t.name.startswith('dproc-serve')
                        and t.is_alive())
        if not leaked:
            return
        time.sleep(0.05)
    print(f'SERVICE THREAD LEAK: {leaked}')


def _assert_same(got, want, label=''):
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_array_equal(
            np.asarray(got[k]), np.asarray(want[k]),
            err_msg=f'{label}: stat {k!r} diverged')


# ---------------------------------------------------------------------------
# error taxonomy and the wire
# ---------------------------------------------------------------------------

def test_terminal_error_taxonomy():
    """Program-class errors and explicit client outcomes are terminal
    at the router (never retried on another replica); infrastructure
    errors are retryable — retrying a deterministic program failure
    elsewhere would just fail again N times."""
    for exc in (FaultError([3, 0, 0, 0, 0, 0]),
                ProgramValidationError([('jump_oob', 0, 3,
                                         'target 9 outside [0, 5)')]),
                ValueError('bad shots'),
                DeadlineError('deadline passed'),
                CancelledError('cancelled'),
                ShutdownError('shutting down')):
        assert is_terminal_error(exc), exc
    for exc in (RuntimeError('executor crashed'),
                ExecutorLostError('dispatcher died'),
                ReplicaLostError('connection lost'),
                OverloadError('queue projected past deadline')):
        assert not is_terminal_error(exc), exc


def test_typed_errors_pickle_roundtrip():
    """The wire is pickle: the two program-class error types must
    round-trip with their payloads intact (FaultError's per-code
    counts feed the caller's fault table), and an unpicklable error
    must degrade to a typed RuntimeError naming the original, never
    kill the connection."""
    fe = pickle.loads(pickle.dumps(FaultError([2, 0, 1, 0, 0, 0])))
    assert isinstance(fe, FaultError)
    np.testing.assert_array_equal(fe.counts, [2, 0, 1, 0, 0, 0])
    pe = pickle.loads(pickle.dumps(ProgramValidationError(
        [('sync_mismatch', None, None, 'sync sets differ')])))
    assert isinstance(pe, ProgramValidationError)
    assert pe.errors == [('sync_mismatch', None, None,
                          'sync sets differ')]
    assert pe.codes == {'sync_mismatch'}

    assert _picklable_error(fe) is fe

    class Local(Exception):      # locally-defined: unpicklable
        pass

    wired = _picklable_error(Local('boom'))
    assert isinstance(wired, RuntimeError)
    assert 'Local' in str(wired) and 'boom' in str(wired)
    assert not is_terminal_error(wired)


# ---------------------------------------------------------------------------
# router unit tests (no replica processes)
# ---------------------------------------------------------------------------

def _tiny_mp():
    core = [isa.pulse_cmd(amp_word=1000, cfg_word=0, env_word=3,
                          cmd_time=10), isa.done_cmd()]
    return machine_program_from_cmds([core])


def test_router_validates_liveness_window():
    with pytest.raises(ValueError):
        FleetRouter(gossip_interval_ms=50.0, liveness_window_ms=50.0)


def test_gossip_staleness_marks_silent_replica_down():
    """A replica whose TCP connection stays open but that never
    answers gossip (the SIGSTOP failure mode) is marked down within
    the liveness window — connection loss alone cannot catch a wedge."""
    lis = socket.socket()
    lis.bind(('127.0.0.1', 0))
    lis.listen(4)                # connects land in the backlog; no one
    try:                         # ever reads or answers
        with FleetRouter(gossip_interval_ms=20.0,
                         liveness_window_ms=100.0) as router:
            router.add_replica('mute', lis.getsockname())
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                s = router.stats()
                if s['gossip_stale'] >= 1 \
                        and not s['replicas']['mute']['alive']:
                    break
                time.sleep(0.02)
            else:
                raise AssertionError(
                    f'silent replica never marked stale: {s}')
            kinds = [e['kind'] for e in
                     router.flight_recorder.events()]
            assert 'gossip_stale' in kinds and 'replica_down' in kinds
    finally:
        lis.close()


def test_router_shutdown_fails_parked_with_typed_error():
    """With zero routable replicas a request parks instead of failing
    fast (a respawn may be seconds away); shutdown must then fail it
    with ShutdownError — parked is never silently dropped."""
    router = FleetRouter(retry_policy=RetryPolicy(max_attempts=2,
                                                  backoff_s=0.005))
    h = router.submit(_tiny_mp(), np.zeros((2, 1, 2), np.int32),
                      cfg=InterpreterConfig(max_steps=32, max_meas=2))
    assert not h.done()
    router.shutdown()
    assert isinstance(h.exception(timeout=5), ShutdownError)
    with pytest.raises(ServiceClosedError):
        router.submit(_tiny_mp(), np.zeros((2, 1, 2), np.int32))


# ---------------------------------------------------------------------------
# live fleet: replica processes on localhost TCP
# ---------------------------------------------------------------------------

N_REQS = 4


@pytest.fixture(scope='module')
def workload():
    return _workload(N_REQS, 2, 2, 4, seed=3)


@pytest.fixture(scope='module')
def fleet(workload):
    mps, bits, cfg = workload
    with Fleet(2,
               service={'max_batch_programs': 4, 'max_wait_ms': 5.0,
                        'max_queue': 256},
               env={'XLA_FLAGS':
                    '--xla_force_host_platform_device_count=1'},
               # deep enough to park across a kill+wedge overlap (a
               # total outage until the respawn boots) in the soak
               router_kwargs={'retry_policy':
                              RetryPolicy(max_attempts=10,
                                          backoff_s=0.05,
                                          max_backoff_s=1.0)}) as f:
        # warm EVERY replica on the serving bucket so the tests below
        # measure federation behaviour, not first-compile latency
        # (bucket affinity would home all fleet.submit warmup on one)
        for rid in f.replica_ids():
            f.router.call_replica(
                rid, 'submit',
                dict(mp=mps[0], meas_bits=bits[0], cfg=cfg),
                timeout_s=600.0)
        yield f


@pytest.fixture(scope='module')
def refs(workload):
    mps, bits, cfg = workload
    return [jax.tree.map(np.asarray,
                         simulate_batch(mps[i], bits[i], cfg=cfg))
            for i in range(N_REQS)]


def _wait_routable(fleet, n, timeout_s=120.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        s = fleet.router.stats()
        if s['n_routable'] >= n:
            return s
        time.sleep(0.05)
    raise AssertionError(f'{n} replicas never routable: '
                         f'{fleet.router.stats()}')


def test_fleet_round_trip_bit_identity(fleet, workload, refs):
    mps, bits, cfg = workload
    handles = [fleet.submit(mps[i], bits[i], cfg=cfg)
               for i in range(N_REQS)]
    for i, h in enumerate(handles):
        _assert_same(h.result(timeout=300), refs[i], f'req {i}')
    s = fleet.stats()
    assert s['n_routable'] == 2 and s['completed'] >= N_REQS
    # per-replica stats reach through the wire
    rep = fleet.replica_stats(0)
    assert 'compile' in rep and 'warmup' in rep


def test_strict_fault_error_crosses_wire_untouched(fleet):
    """A strict-mode FaultError is a program-class outcome: it crosses
    the wire with its per-code counts byte-identical to the solo run
    and is NEVER retried — the retry layer must not burn its budget
    re-executing a deterministic trap on every replica."""
    core = [isa.alu_cmd('reg_alu', 'i', 1000, 'id0', write_reg_addr=0),
            isa.pulse_cmd(amp_word=1000, cfg_word=0, env_word=3,
                          cmd_time=10),
            isa.alu_cmd('reg_alu', 'i', -1, 'add', 0, write_reg_addr=0),
            isa.alu_cmd('jump_cond', 'i', 0, 'le', 0, jump_cmd_ptr=1),
            isa.done_cmd()]
    mp = machine_program_from_cmds([core])
    mb = np.zeros((4, 1, 2), np.int32)
    cfg = InterpreterConfig(max_steps=6, max_meas=2,
                            fault_mode='strict')
    with pytest.raises(FaultError) as solo:
        simulate_batch(mp, mb, cfg=cfg)

    before = fleet.stats()
    exc = fleet.submit(mp, mb, cfg=cfg).exception(timeout=300)
    after = fleet.stats()
    assert isinstance(exc, FaultError)
    np.testing.assert_array_equal(exc.counts, solo.value.counts)
    assert after['retries'] == before['retries']
    assert after['failed'] == before['failed'] + 1


def test_kill_failover_bit_identity_and_warm_respawn(fleet, workload,
                                                     refs):
    """SIGKILL the loaded replica with requests in flight: every
    request completes bit-identically on the survivor, and the monitor
    respawns the victim from the shared warm tiers — its first served
    request after warmup costs ZERO cold compiles."""
    mps, bits, cfg = workload
    _wait_routable(fleet, 2)
    before = fleet.router.stats()

    victim_rid = fleet.router.primary_replica()
    victim_idx = fleet.replica_ids().index(victim_rid)
    respawns0 = fleet.stats()['processes'][victim_rid]['respawns']

    handles = [fleet.submit(mps[i % N_REQS], bits[i % N_REQS], cfg=cfg)
               for i in range(2 * N_REQS)]
    fleet.kill(victim_idx)
    for i, h in enumerate(handles):
        _assert_same(h.result(timeout=300), refs[i % N_REQS],
                     f'req {i} after kill')

    after = fleet.router.stats()
    assert after['replica_down'] >= before['replica_down'] + 1

    # the monitor respawns the victim; the router re-admits it
    deadline = time.monotonic() + 240.0
    while time.monotonic() < deadline:
        st = fleet.stats()
        if st['processes'][victim_rid]['respawns'] > respawns0 \
                and st['replicas'].get(victim_rid, {}).get('routable'):
            break
        time.sleep(0.1)
    else:
        raise AssertionError(f'victim never respawned+re-admitted: '
                             f'{fleet.stats()}')

    # shared warm tiers: wait for catalog replay to finish, then the
    # first request served by the respawn must classify WARM (the
    # replay itself compiles — snapshot cold AFTER it settles)
    deadline = time.monotonic() + 240.0
    while time.monotonic() < deadline:
        rep = fleet.replica_stats(victim_rid)
        if rep['warmup']['in_progress'] == 0:
            break
        time.sleep(0.1)
    else:
        raise AssertionError('respawned replica warmup never settled')
    cold0 = rep['compile']['cold']
    got = fleet.router.call_replica(
        victim_rid, 'submit',
        dict(mp=mps[0], meas_bits=bits[0], cfg=cfg), timeout_s=300.0)
    _assert_same(got, refs[0], 'respawned replica')
    assert fleet.replica_stats(victim_rid)['compile']['cold'] == cold0


def test_wedge_gossip_failover_then_readmit(fleet, workload, refs):
    """SIGSTOP the loaded replica: its connection stays open so only
    gossip staleness can catch it; in-flight work fails over
    bit-identically, and SIGCONT re-admits it on the next heartbeat."""
    mps, bits, cfg = workload
    _wait_routable(fleet, 2)
    before = fleet.router.stats()

    victim_rid = fleet.router.primary_replica()
    victim_idx = fleet.replica_ids().index(victim_rid)
    handles = [fleet.submit(mps[i], bits[i], cfg=cfg)
               for i in range(N_REQS)]
    fleet.wedge(victim_idx)
    try:
        for i, h in enumerate(handles):
            _assert_same(h.result(timeout=300), refs[i],
                         f'req {i} under wedge')
        mid = fleet.router.stats()
        assert mid['gossip_stale'] >= before['gossip_stale'] + 1
        assert not mid['replicas'][victim_rid]['alive']
    finally:
        fleet.unwedge(victim_idx)

    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        s = fleet.router.stats()
        if s['replicas'][victim_rid]['routable']:
            break
        time.sleep(0.05)
    else:
        raise AssertionError(f'unwedged replica never re-admitted: '
                             f'{fleet.router.stats()}')
    assert s['replica_up'] >= before['replica_up'] + 1


@pytest.mark.slow
def test_fleet_soak_scripted_chaos(fleet, workload):
    """Small in-test mirror of tools/servechaos.py --fleet: scripted
    kill + wedge/unwedge under a paced stream; zero hangs, zero bit
    mismatches, goodput positive inside the kill window."""
    from distributed_processor_tpu.serve.chaos import fleet_soak
    mps, bits, cfg = workload
    _wait_routable(fleet, 2)
    n = 30
    report = fleet_soak(
        fleet, mps, cfg, n_requests=n, shots=4, seed=5, rate_hz=30.0,
        actions=[(n // 3, 'kill', -1), (n // 2, 'wedge', -1),
                 ((3 * n) // 4, 'unwedge', -1)],
        result_timeout_s=300.0)
    assert report.hung == 0
    assert report.bit_mismatches == 0
    assert report.terminated() == report.submitted
    kill_t = next(t for t, m, _ in report.actions if m == 'kill')
    assert report.ok_in_window(kill_t, kill_t + 2.0) > 0
