"""ISA encode/decode round-trip and bit-layout tests.

Layout constants are cross-checked against the gateware contract
(BASELINE.md): opcode at bits 123-127, immediate at 88, jump addr at 68,
fproc id at 52, pulse fields per hdl/pulse_reg.sv.
"""

import numpy as np
import pytest

from distributed_processor_tpu import isa


def test_twos_complement_roundtrip():
    rng = np.random.default_rng(0)
    vals = rng.integers(-2**31, 2**31 - 1, size=100)
    for v in vals:
        enc = isa.twos_complement(int(v))
        assert 0 <= enc < 2**32
        assert isa.from_twos_complement(enc) == int(v)
    with pytest.raises(ValueError):
        isa.twos_complement(2**31)


def test_pulse_cmd_layout():
    cmd = isa.pulse_cmd(freq_word=0x155, phase_word=0x1aaaa, amp_word=0xbeef,
                        env_word=0xabcdef, cfg_word=0x5, cmd_time=1234)
    # opcode pulse_write_trig
    assert (cmd >> 123) & 0x1f == 0b10010
    assert (cmd >> 5) & 0xffffffff == 1234
    assert (cmd >> 37) & 0xf == 0x5
    assert (cmd >> 42) & 0xffff == 0xbeef
    assert (cmd >> 60) & 0x1ff == 0x155
    assert (cmd >> 71) & 0x1ffff == 0x1aaaa
    assert (cmd >> 90) & 0xffffff == 0xabcdef
    # all write enables set, no reg selects ({wen, sel} with wen high)
    assert (cmd >> 41) & 1 == 1           # cfg wen
    assert (cmd >> 58) & 0b11 == 0b10     # amp ctl
    assert (cmd >> 114) & 0b11 == 0b10    # env ctl


def test_pulse_cmd_reg_param():
    cmd = isa.pulse_cmd(freq_regaddr=7, phase_word=3, cmd_time=10)
    assert (cmd >> 116) & 0xf == 7
    assert (cmd >> 69) & 0b11 == 0b11     # freq ctl bits = {reg, wen}
    with pytest.raises(ValueError):
        isa.pulse_cmd(freq_regaddr=1, phase_regaddr=2)


def test_pulse_write_without_time():
    cmd = isa.pulse_cmd(freq_word=5)
    assert (cmd >> 123) & 0x1f == 0b10000


def test_alu_cmd_layouts():
    cmd = isa.alu_cmd('reg_alu', 'i', -5, 'add', 3, write_reg_addr=9)
    assert (cmd >> 120) & 0xff == (0b00010 << 3) | 0b001
    assert (cmd >> 88) & 0xffffffff == isa.twos_complement(-5)
    assert (cmd >> 84) & 0xf == 3
    assert (cmd >> 80) & 0xf == 9

    cmd = isa.alu_cmd('reg_alu', 'r', 4, 'sub', 3, write_reg_addr=1)
    assert (cmd >> 120) & 0xff == (0b00011 << 3) | 0b010
    assert (cmd >> 116) & 0xf == 4

    cmd = isa.alu_cmd('jump_cond', 'i', 7, 'eq', 2, jump_cmd_ptr=99)
    assert (cmd >> 120) & 0xff == (0b00110 << 3) | 0b011
    assert (cmd >> 68) & 0xff == 99

    cmd = isa.alu_cmd('jump_fproc', 'i', 1, 'ge', jump_cmd_ptr=42, func_id=6)
    assert (cmd >> 120) & 0xff == (0b01010 << 3) | 0b101
    assert (cmd >> 52) & 0xff == 6
    assert (cmd >> 68) & 0xff == 42

    cmd = isa.alu_cmd('inc_qclk', 'i', -100)
    assert (cmd >> 120) & 0xff == (0b01100 << 3) | 0b001

    cmd = isa.sync(17)
    assert (cmd >> 123) & 0x1f == 0b01110
    assert (cmd >> 112) & 0xff == 17


def test_bytes_roundtrip():
    cmds = [isa.pulse_cmd(freq_word=1, cmd_time=5), isa.done_cmd(),
            isa.alu_cmd('reg_alu', 'i', 123, 'id0', 0, write_reg_addr=2)]
    buf = isa.cmds_to_bytes(cmds)
    assert len(buf) == 48
    assert isa.bytes_to_cmds(buf) == cmds


def test_decode_soa_roundtrip():
    cmds = [
        isa.pulse_cmd(freq_word=0x12, phase_word=0x345, amp_word=0x6789,
                      env_word=0x00abc, cfg_word=2, cmd_time=77),
        isa.pulse_cmd(phase_regaddr=5),
        isa.alu_cmd('reg_alu', 'i', -42, 'sub', 3, write_reg_addr=9),
        isa.alu_cmd('reg_alu', 'r', 11, 'ge', 3, write_reg_addr=1),
        isa.alu_cmd('jump_cond', 'i', 1, 'eq', 4, jump_cmd_ptr=13),
        isa.alu_cmd('jump_fproc', 'i', 0, 'le', jump_cmd_ptr=2, func_id=3),
        isa.alu_cmd('alu_fproc', 'i', 0, 'id1', write_reg_addr=6, func_id=1),
        isa.alu_cmd('inc_qclk', 'i', -1000),
        isa.jump_i(200),
        isa.sync(3),
        isa.idle(4096),
        isa.pulse_reset(),
        isa.done_cmd(),
    ]
    soa = isa.decode_soa(isa.cmds_to_bytes(cmds))
    k = soa.kind
    assert list(k) == [isa.K_PULSE_TRIG, isa.K_PULSE_WRITE, isa.K_REG_ALU,
                       isa.K_REG_ALU, isa.K_JUMP_COND, isa.K_JUMP_FPROC,
                       isa.K_ALU_FPROC, isa.K_INC_QCLK, isa.K_JUMP_I,
                       isa.K_SYNC, isa.K_IDLE, isa.K_PULSE_RESET, isa.K_DONE]
    assert soa.p_freq[0] == 0x12 and soa.p_phase[0] == 0x345
    assert soa.p_amp[0] == 0x6789 and soa.p_env[0] == 0x00abc
    assert soa.p_cfg[0] == 2 and soa.cmd_time[0] == 77
    assert soa.p_wen[0] == 0b11111 and soa.p_regsel[0] == 0
    # reg-sourced phase
    assert soa.p_wen[1] == 0b00010 and soa.p_regsel[1] == 0b00010
    assert soa.p_reg[1] == 5
    assert soa.imm[2] == -42 and soa.in1_reg[2] == 3 and soa.out_reg[2] == 9
    assert soa.in0_is_reg[3] == 1 and soa.in0_reg[3] == 11
    assert soa.jump_addr[4] == 13
    assert soa.func_id[5] == 3 and soa.jump_addr[5] == 2
    assert soa.out_reg[6] == 6 and soa.func_id[6] == 1
    assert soa.imm[7] == -1000
    assert soa.jump_addr[8] == 200
    assert soa.barrier[9] == 3
    assert soa.cmd_time[10] == 4096
    # all-zero word halts like DONE
    soa0 = isa.decode_soa(b'\x00' * 16)
    assert soa0.kind[0] == isa.K_DONE


def test_stack_soa_padding():
    a = isa.decode_soa(isa.cmds_to_bytes([isa.done_cmd()]))
    b = isa.decode_soa(isa.cmds_to_bytes([isa.jump_i(1), isa.done_cmd()]))
    stacked = isa.stack_soa([a, b], pad_to=4)
    assert stacked.kind.shape == (2, 4)
    assert stacked.kind[0, 0] == isa.K_DONE
    assert np.all(stacked.kind[:, 2:] == isa.K_DONE)


def test_disassemble():
    cmds = [isa.pulse_cmd(freq_word=9, env_word=(3 << 12) | 2, cfg_word=1,
                          cmd_time=55),
            isa.alu_cmd('reg_alu', 'i', 5, 'add', 2, write_reg_addr=3)]
    dis = isa.disassemble(isa.cmds_to_bytes(cmds))
    assert dis[0]['op'] == 'pulse_write_trig'
    assert dis[0]['cmd_time'] == 55 and dis[0]['freq'] == 9
    assert dis[0]['env_start'] == 2 and dis[0]['env_length'] == 3
    assert dis[1] == {'op': 'reg_alu', 'alu_op': 'add', 'in0': 5,
                      'in1_reg': 2, 'out_reg': 3}
