"""Multi-device execution service: the pod-scale serving pool.

What the single-device suite (test_serve.py) pins per request, this
suite pins per DEVICE: bucket-affinity routing sends each shape bucket
to a sticky home executor, work stealing migrates ripened batches to
idle devices, stolen requests re-run their deadline/cancel checks at
the re-queue boundary, warmup pre-compiles every device, and shutdown
under load joins every ``dproc-serve-dispatch-*`` thread (the conftest
leak probe + junit gate watch exactly that).  Bit-identity stays the
load-bearing property: a request's demuxed stats equal its solo
``simulate_batch`` run REGARDLESS of which device executed it.

The whole module skips only on a genuinely single-device host; the
skip reason records the advertised count and tools/check_junit.py
fails CI when these tests skip on a host advertising more (the
serve-tier mirror of the pallas BAD SKIP gate).
"""

import threading
import time

import numpy as np
import pytest

import jax

from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import machine_program_from_cmds
from distributed_processor_tpu.parallel.mesh import serving_devices
from distributed_processor_tpu.serve import (CancelledError, Coalescer,
                                             DeadlineError,
                                             ExecutionService,
                                             bucket_key)
from distributed_processor_tpu.serve.request import Request
from distributed_processor_tpu.serve.service import _normalize_cfg
from distributed_processor_tpu.sim.interpreter import (InterpreterConfig,
                                                       clear_aot_cache,
                                                       simulate_batch)
from distributed_processor_tpu.utils import profiling

_N_DEV = len(jax.devices())

pytestmark = [
    pytest.mark.serve,
    pytest.mark.skipif(
        _N_DEV < 2,
        reason=f'multi-device serve tests need >=2 devices (host '
               f'advertises {_N_DEV} device(s); off-TPU force more '
               f'with --xla_force_host_platform_device_count)'),
]


def _mp_small():
    """Branch-free single-core program in the 8-instruction bucket."""
    core = [isa.pulse_cmd(amp_word=1000 + 7 * i, cfg_word=0, env_word=3,
                          cmd_time=10 + 20 * i) for i in range(3)] \
        + [isa.done_cmd()]
    return machine_program_from_cmds([core])


def _mp_big():
    """Same shape family, 16-instruction bucket — a distinct routing
    key on the same service cfg."""
    core = [isa.pulse_cmd(amp_word=2000 + 11 * i, cfg_word=0,
                          env_word=3, cmd_time=10 + 20 * i)
            for i in range(10)] + [isa.done_cmd()]
    return machine_program_from_cmds([core])


_CFG = InterpreterConfig(max_steps=2 * 16 + 64, max_pulses=16 + 2,
                         max_meas=2, max_resets=2)


def _bits(rng, shots):
    return rng.integers(0, 2, size=(shots, 1, 2)).astype(np.int32)


def _solo(mp, bits):
    ncfg, _ = _normalize_cfg(_CFG, isa.shape_bucket(mp.n_instr))
    return jax.tree.map(np.asarray, simulate_batch(mp, bits, cfg=ncfg))


def _assert_same(got, want, label=''):
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(want[k]),
                                      err_msg=f'{label}:{k}')


def _no_leaked_dispatchers():
    return [t.name for t in threading.enumerate()
            if t.name.startswith('dproc-serve-dispatch')
            and t.is_alive()]


def test_dp2_routing_spreads_buckets_bit_identity():
    """dp=2 mesh serving: two shape buckets land on two home devices
    (sticky, deterministic), every result is bit-identical to its solo
    dispatch no matter which device ran it, and the per-device stats
    reconcile with the aggregates."""
    small, big = _mp_small(), _mp_big()
    rng = np.random.default_rng(3)
    reqs = [(small, _bits(rng, 4)) for _ in range(4)] \
        + [(big, _bits(rng, 4)) for _ in range(4)]
    with ExecutionService(_CFG, max_batch_programs=4, max_wait_ms=25.0,
                          devices=serving_devices(2),
                          work_stealing=False) as svc:
        handles = [svc.submit(mp, b) for mp, b in reqs]
        results = [h.result(timeout=300) for h in handles]
        st = svc.stats()
    for (mp, b), got in zip(reqs, results):
        _assert_same(got, _solo(mp, b), f'{mp.n_instr}instr')
    assert st['n_devices'] == 2
    assert st['steals'] == 0 and st['work_stealing'] is False
    # one home bucket and real dispatch traffic per device
    assert [d['home_buckets'] for d in st['devices']] == [1, 1]
    assert all(d['dispatches'] >= 1 for d in st['devices'])
    assert sum(d['dispatches'] for d in st['devices']) \
        == st['dispatches']
    assert sum(d['programs_dispatched'] for d in st['devices']) \
        == st['programs_dispatched'] == len(reqs)
    assert not _no_leaked_dispatchers()


def test_work_steal_migrates_ripe_batch_to_idle_device():
    """With the home device wedged mid-batch, an idle device steals the
    next ripened batch of the same bucket — counted in stats, results
    still bit-identical."""
    mp = _mp_small()
    rng = np.random.default_rng(4)
    bits = [_bits(rng, 4) for _ in range(4)]
    svc = ExecutionService(_CFG, max_batch_programs=2, max_wait_ms=5.0,
                           devices=2)
    try:
        svc.warmup(mp, shots=4, n_programs=2)
        orig, slowed = svc._run_batch, []

        def slow_first(ex, key, batch, cfg):
            if not slowed:
                slowed.append(ex.idx)
                time.sleep(0.5)     # hold the home busy past ripening
            return orig(ex, key, batch, cfg)

        svc._run_batch = slow_first
        handles = [svc.submit(mp, b) for b in bits]
        results = [h.result(timeout=300) for h in handles]
        st = svc.stats()
    finally:
        svc.shutdown()
    for b, got in zip(bits, results):
        _assert_same(got, _solo(mp, b), 'stolen-ok')
    assert st['steals'] >= 1
    assert sum(d['steals'] for d in st['devices']) == st['steals']
    assert sum(d['stolen_from'] for d in st['devices']) >= 1
    assert all(d['dispatches'] >= 1 for d in st['devices'])
    assert not _no_leaked_dispatchers()


def test_absorb_reruns_deadline_and_cancel_checks():
    """Satellite fix: a stolen batch's requests re-run deadline/cancel
    checks when re-queued on the thief — a migrated request cannot
    outlive its deadline silently, and a cancelled one is dropped."""
    mp = _mp_small()
    ncfg, _ = _normalize_cfg(_CFG, isa.shape_bucket(mp.n_instr))
    key = bucket_key(mp, ncfg)

    def mk(seq, deadline=None):
        return Request(mp=mp,
                       meas_bits=np.zeros((2, 1, 2), np.int32),
                       init_regs=None, cfg=ncfg, strict=False,
                       n_shots=2, priority=0, deadline=deadline,
                       seq=seq)

    now = time.monotonic()
    home, thief = Coalescer(4, 60.0), Coalescer(4, 60.0)
    live, doomed, dead = mk(0), mk(1, deadline=now + 0.01), mk(2)
    for r in (live, doomed, dead):
        home.push(key, r)
    assert dead.handle.cancel()
    later = now + 1.0     # past doomed's deadline, before age-ripeness
    moved = home.migrate_bucket(key, 4)
    assert len(moved) == 3 and len(home) == 0
    expired = thief.absorb(key, moved, now=later)
    # the expired request failed with DeadlineError AT the re-queue
    assert [r.seq for r in expired] == [1]
    with pytest.raises(DeadlineError):
        doomed.handle.result(timeout=0)
    # the cancelled one was dropped and counted, not re-queued
    assert thief.dropped_cancelled == 1
    assert len(thief) == 1 and live.migrations == 1
    # the survivor is immediately dispatchable on the thief (the batch
    # already ripened once at the victim — no second latency penalty)
    k, batch, exp = thief.pop_batch(now=later)
    assert k == key and [r.seq for r in batch] == [0] and not exp


def test_warmup_and_compile_stats():
    """Satellite: warmup pre-compiles the bucket's executable shape on
    EVERY device; stats()['compile'] and the serve.compile.* counters
    classify the first dispatch per (bucket, shape, device) cold and
    repeats warm."""
    mp = _mp_small()
    rng = np.random.default_rng(5)
    cold0 = profiling.counter_get('serve.compile.cold')
    warm0 = profiling.counter_get('serve.compile.warm')
    # the AOT executable cache is process-level (idempotent across
    # services); drop it so this test's warmup compiles are observable
    clear_aot_cache()
    with ExecutionService(_CFG, max_batch_programs=2, max_wait_ms=5.0,
                          devices=2) as svc:
        report = svc.warmup(mp, shots=4, n_programs=2)
        assert [r['cold'] for r in report] == [True, True]
        # AOT warmup really compiled (not dispatched) an executable
        # per device — compile_ms is the lower().compile() wall clock
        assert all(r['compile_ms'] > 0 for r in report)
        st = svc.stats()
        assert st['compile']['cold'] == 2
        assert st['compile']['warm'] == 0
        per = st['compile']['per_bucket']['c1i8']
        assert per['cold'] == 2 and per['warm'] == 0
        # warmup classifications are untimed: no dispatch happened yet
        assert per['cold_ms_mean'] is None
        assert st['warmups'] == 2
        assert st['warmup']['aot_compiled'] == 2
        assert st['dispatches'] == 0
        # a live batch of the warmed shape is a warm hit on its home
        handles = [svc.submit(mp, _bits(rng, 4)) for _ in range(2)]
        for h in handles:
            h.result(timeout=300)
        st = svc.stats()
    assert st['compile']['cold'] == 2
    assert st['compile']['warm'] == 1
    per = st['compile']['per_bucket']['c1i8']
    assert per['cold'] == 2 and per['warm'] == 1
    # the warm dispatch was timed; the cold side still has no timed
    # dispatch (both cold classifications were AOT warmups)
    assert per['warm_ms_mean'] is not None and per['warm_ms_mean'] > 0
    assert per['cold_ms_mean'] is None and per['compile_ms_est'] is None
    assert st['devices'][0]['warm_hits'] == 1   # home = first-sighted
    assert profiling.counter_get('serve.compile.cold') - cold0 == 2
    assert profiling.counter_get('serve.compile.warm') - warm0 == 1
    assert not _no_leaked_dispatchers()


def test_shutdown_under_load_joins_every_dispatcher():
    """Satellite: the conftest thread-leak probe with N executors —
    drain-shutdown under load completes every request and joins every
    per-device dispatcher thread."""
    ndev = min(4, _N_DEV)
    mp = _mp_small()
    rng = np.random.default_rng(6)
    bits = [_bits(rng, 2) for _ in range(8)]
    svc = ExecutionService(_CFG, max_batch_programs=2, max_wait_ms=2.0,
                           devices=ndev)
    handles = [svc.submit(mp, b) for b in bits]
    svc.shutdown(drain=True, timeout=300)
    for h, b in zip(handles, bits):
        _assert_same(h.result(timeout=0), _solo(mp, b), 'drained')
    assert not _no_leaked_dispatchers()
    # non-draining shutdown: queued work is cancelled, threads join
    svc = ExecutionService(_CFG, max_batch_programs=64,
                           max_wait_ms=60_000.0, devices=ndev)
    h = svc.submit(mp, bits[0])
    svc.shutdown(drain=False, timeout=300)
    with pytest.raises(CancelledError):
        h.result(timeout=0)
    assert not _no_leaked_dispatchers()


def test_bucket_affinity_is_sticky():
    """Re-submitting a bucket later still lands on its original home —
    the warm-cache affinity the router exists for."""
    mp = _mp_small()
    rng = np.random.default_rng(7)
    with ExecutionService(_CFG, max_batch_programs=2, max_wait_ms=5.0,
                          devices=2, work_stealing=False) as svc:
        for _round in range(3):
            hs = [svc.submit(mp, _bits(rng, 2)) for _ in range(2)]
            for h in hs:
                h.result(timeout=300)
        st = svc.stats()
    assert st['devices'][0]['dispatches'] == st['dispatches'] == 3
    assert st['devices'][1]['dispatches'] == 0
    assert st['devices'][1]['queue_depth'] == 0
    assert not _no_leaked_dispatchers()
