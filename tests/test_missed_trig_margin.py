"""Missed-trigger conservatism, quantified (round-2 review weak #5).

``ERR_MISSED_TRIG`` fires when a pulse trigger time is already past the
engine's issue clock, which accumulates the *scheduler's* per-
instruction costs (the documented worst-case latencies,
reference python/distproc/hwconfig.py:100-119).  The hardware FSM's
actual dwell can be shorter (cocotb/proc/test_proc.py:8-19:
ALU_INSTR_TIME=4 vs the scheduled 5), so a hand-scheduled program that
under-schedules by up to the accumulated difference would run on real
hardware but is flagged here — a false positive in the conservative
direction only.  These tests pin the flag boundary to EXACTLY the
documented cost accumulation in both engines (one clock earlier
flags, the boundary itself does not), which makes the conservatism
margin a computable quantity:

    margin(program) = sum over issued instructions of
                      (scheduled cost - RTL minimum dwell)

documented per instruction class in docs/TIMING.md "Missed-trigger
conservatism".  For pulse->pulse spacing the margin is zero (the
3-clock minimum spacing is itself the hardware contract,
hwconfig.py:106-107), so back-to-back pulse chains are flagged exactly
when hardware would miss.
"""

import numpy as np
import pytest

from distributed_processor_tpu import isa
from distributed_processor_tpu.decoder import machine_program_from_cmds
from distributed_processor_tpu.sim import simulate, run_oracle
from distributed_processor_tpu.sim import ERR_MISSED_TRIG
from distributed_processor_tpu.sim.oracle import INIT_TIME

ALU_CLKS = 5          # hwconfig alu_instr_clks (reference hwconfig.py:103)
JUMP_CLKS = 5         # jump_cond_clks (hwconfig.py:104)
PULSE_LOAD = 3        # pulse_load_clks / min spacing (hwconfig.py:106-107)
COCOTB_ALU_DWELL = 4  # cocotb ALU_INSTR_TIME (test_proc.py:15): the RTL
                      # FSM's observed per-ALU dwell — 1 clk under the
                      # scheduled worst case


def _engine_err(mp):
    out = simulate(mp, max_meas=2)
    return int(np.asarray(out['err'])[0])


def _oracle_errs(mp):
    return run_oracle(mp)['err'][0]


def _alu_chain_program(n_alu: int, trig: int):
    cmds = [isa.alu_cmd('reg_alu', 'i', 1, 'add', 0, write_reg_addr=0)
            for _ in range(n_alu)]
    cmds.append(isa.pulse_cmd(freq_word=1, phase_word=0, amp_word=1,
                              env_word=(1 << 12), cfg_word=0,
                              cmd_time=trig))
    cmds.append(isa.done_cmd())
    return machine_program_from_cmds([cmds])


@pytest.mark.parametrize('n_alu', [1, 4, 8])
def test_alu_chain_flag_boundary_exact(n_alu):
    """The flag boundary is exactly INIT_TIME + n*alu_instr_clks: a
    trigger AT the boundary issues cleanly, one clock earlier flags —
    in both engines."""
    boundary = INIT_TIME + n_alu * ALU_CLKS
    ok = _alu_chain_program(n_alu, boundary)
    assert _engine_err(ok) == 0
    assert _oracle_errs(ok) == []
    late = _alu_chain_program(n_alu, boundary - 1)
    assert _engine_err(late) & ERR_MISSED_TRIG
    assert 'missed_trig' in _oracle_errs(late)
    # the conservatism margin for this program: hardware (per the cocotb
    # dwell) would still meet any trigger down to INIT_TIME +
    # n*COCOTB_ALU_DWELL, i.e. the engine over-flags by exactly
    margin = n_alu * (ALU_CLKS - COCOTB_ALU_DWELL)
    assert margin == n_alu                      # 1 clk per ALU instr
    # triggers inside the margin ARE flagged (conservative direction)
    if margin:
        inside = _alu_chain_program(n_alu, boundary - margin)
        assert _engine_err(inside) & ERR_MISSED_TRIG


def test_pulse_spacing_margin_zero():
    """Back-to-back triggers at the 3-clock minimum spacing pass; one
    clock tighter flags.  The spacing is the hardware contract itself
    (hwconfig.py:106-107), so here the flag has ZERO conservatism —
    it fires exactly when hardware would miss."""
    def prog(spacing):
        t0 = INIT_TIME + 1
        cmds = [isa.pulse_cmd(freq_word=1, phase_word=0, amp_word=1,
                              env_word=(1 << 12), cfg_word=0, cmd_time=t0),
                isa.pulse_cmd(freq_word=2, cmd_time=t0 + spacing),
                isa.done_cmd()]
        return machine_program_from_cmds([cmds])
    assert _engine_err(prog(PULSE_LOAD)) == 0
    assert _oracle_errs(prog(PULSE_LOAD)) == []
    assert _engine_err(prog(PULSE_LOAD - 1)) & ERR_MISSED_TRIG
    assert 'missed_trig' in _oracle_errs(prog(PULSE_LOAD - 1))


def test_jump_boundary_exact():
    """A trigger right after a jump_i at the documented jump cost
    boundary (5 clks, = cocotb JUMP_INSTR_TIME — zero margin class)."""
    def prog(trig):
        cmds = [isa.jump_i(1),
                isa.pulse_cmd(freq_word=1, phase_word=0, amp_word=1,
                              env_word=(1 << 12), cfg_word=0,
                              cmd_time=trig),
                isa.done_cmd()]
        return machine_program_from_cmds([cmds])
    boundary = INIT_TIME + JUMP_CLKS
    assert _engine_err(prog(boundary)) == 0
    assert _engine_err(prog(boundary - 1)) & ERR_MISSED_TRIG
    assert 'missed_trig' in _oracle_errs(prog(boundary - 1))


def test_flagged_pulse_still_fires_slid():
    """A flagged trigger is not dropped: it fires at the issue clock
    (the slid time), loudly marked — matching the oracle."""
    mp = _alu_chain_program(2, INIT_TIME + 2 * ALU_CLKS - 3)
    out = simulate(mp, max_meas=2)
    assert int(np.asarray(out['err'])[0]) & ERR_MISSED_TRIG
    assert int(np.asarray(out['rec_gtime'])[0, 0]) == INIT_TIME + 2 * ALU_CLKS
    o = run_oracle(mp)
    assert o['pulses'][0][0]['gtime'] == INIT_TIME + 2 * ALU_CLKS
