"""Compiled (non-interpret) Pallas kernel parity on real TPU hardware.

The rest of the suite runs the Pallas kernels in interpret mode on the
CPU mesh; these tests compile them for the actual TPU and assert parity
with the XLA reference implementations — the bench-environment check
demanded by the round-1 review.  The assertions live in
ops/selftest.py and are the exact ones bench.py runs before timing.
Run on the bench host with::

    DPROC_TPU_TESTS=1 python -m pytest tests/ -m tpu

Under the default CPU-forced suite they skip.
"""

import pytest
import jax

from distributed_processor_tpu.ops.selftest import (
    check_demod_parity, check_waveform_parity)

pytestmark = pytest.mark.tpu

needs_tpu = pytest.mark.skipif(
    jax.devices()[0].platform != 'tpu',
    reason='needs a real TPU (DPROC_TPU_TESTS=1 on the bench host)')


@needs_tpu
def test_demod_pallas_compiled_matches_reference():
    check_demod_parity(interpret=False)


@needs_tpu
def test_waveform_pallas_compiled_matches_reference():
    check_waveform_parity(interpret=False)
