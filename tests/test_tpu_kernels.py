"""Compiled (non-interpret) Pallas kernel parity on real TPU hardware.

The rest of the suite runs the Pallas kernels in interpret mode on the
CPU mesh; these tests compile them for the actual TPU and assert parity
with the XLA reference implementations — the bench-environment check
demanded by the round-1 review.  The assertions live in
ops/selftest.py and are the exact ones bench.py runs before timing.
Run on the bench host with::

    DPROC_TPU_TESTS=1 python -m pytest tests/ -m tpu

Under the default CPU-forced suite they skip.
"""

import pytest
import jax

from distributed_processor_tpu.ops.selftest import (
    check_demod_parity, check_waveform_parity)

pytestmark = pytest.mark.tpu

needs_tpu = pytest.mark.skipif(
    jax.devices()[0].platform != 'tpu',
    reason='needs a real TPU (DPROC_TPU_TESTS=1 on the bench host)')


@needs_tpu
def test_demod_pallas_compiled_matches_reference():
    check_demod_parity(interpret=False)


@needs_tpu
def test_waveform_pallas_compiled_matches_reference():
    check_waveform_parity(interpret=False)


@needs_tpu
def test_fused_native_rng_statistical_parity():
    """The in-kernel counter-based ADC noise (pltpu.prng_random_bits +
    Box-Muller) must reproduce the streamed threefry generator's
    N(0, sigma^2) statistics: assignment-error rates of the two
    generators agree within CLT bounds at an error-prone sigma.
    ``fused_native_rng`` is a static model field, so the two runs
    compile (and execute) genuinely different programs."""
    import numpy as np
    from distributed_processor_tpu.simulator import Simulator
    from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                       run_physics_batch)

    sim = Simulator(n_qubits=1)
    mp = sim.compile([{'name': 'read', 'qubit': ['Q0']}])
    B = 4096
    init = (np.arange(B) % 2).astype(np.int32).reshape(B, 1)
    kw = dict(max_steps=200, max_pulses=16, max_meas=4)

    errs = {}
    for native in (True, False):
        model = ReadoutPhysics(sigma=8.0, resolve_chunk=256,
                               window_samples=256, resolve_mode='fused',
                               fused_native_rng=native)
        out = run_physics_batch(mp, model, 7, B, init_states=init, **kw)
        bits = np.asarray(out['meas_bits'])[:, 0, 0]
        errs[native] = float(np.mean(bits != init[:, 0]))
    # both generators see real errors, from DIFFERENT streams, and the
    # rates agree within 5 sigma of the binomial spread
    assert errs[False] > 0.02, errs
    spread = 5 * np.sqrt(errs[False] * (1 - errs[False]) / B)
    assert abs(errs[True] - errs[False]) < spread + 0.01, errs
