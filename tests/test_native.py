"""Native codec tests: C++ decode/encode bit-exact vs the Python path."""

import numpy as np
import pytest

from distributed_processor_tpu import isa, native


pytestmark = pytest.mark.skipif(not native.available(),
                                reason='native toolchain unavailable')


def _random_cmds(rng, n=200):
    cmds = []
    ops = list(isa.ALU_OPS)
    for _ in range(n):
        r = rng.integers(8)
        if r < 2:
            cmds.append(isa.pulse_cmd(
                freq_word=int(rng.integers(1 << 9)),
                phase_word=int(rng.integers(1 << 17)),
                amp_word=int(rng.integers(1 << 16)),
                env_word=int(rng.integers(1 << 24)),
                cfg_word=int(rng.integers(1 << 4)),
                cmd_time=int(rng.integers(1 << 32))))
        elif r == 2:
            cmds.append(isa.pulse_cmd(phase_regaddr=int(rng.integers(16)),
                                      amp_word=int(rng.integers(1 << 16))))
        elif r == 3:
            imr = 'ir'[int(rng.integers(2))]
            in0 = int(rng.integers(-2**31, 2**31)) if imr == 'i' \
                else int(rng.integers(16))
            cmds.append(isa.alu_cmd(
                'reg_alu', imr, in0,
                ops[int(rng.integers(8))], int(rng.integers(16)),
                write_reg_addr=int(rng.integers(16))))
        elif r == 4:
            cmds.append(isa.alu_cmd(
                'jump_fproc', 'i', int(rng.integers(-100, 100)),
                ops[int(rng.integers(8))],
                jump_cmd_ptr=int(rng.integers(256)),
                func_id=int(rng.integers(256))))
        elif r == 5:
            cmds.append(isa.sync(int(rng.integers(256))))
        elif r == 6:
            cmds.append(isa.idle(int(rng.integers(1 << 32))))
        else:
            cmds.append(isa.done_cmd())
    return cmds


def test_native_decode_matches_python():
    rng = np.random.default_rng(0)
    buf = isa.cmds_to_bytes(_random_cmds(rng))
    nat = isa.decode_soa(buf, use_native=True)
    py = isa.decode_soa(buf, use_native=False)
    for f in isa.SOA_FIELDS:
        np.testing.assert_array_equal(getattr(nat, f), getattr(py, f),
                                      err_msg=f)


def test_native_encode_matches_python():
    rng = np.random.default_rng(1)
    n = 100
    t = rng.integers(0, 1 << 32, n)
    env = rng.integers(0, 1 << 24, n)
    ph = rng.integers(0, 1 << 17, n)
    fr = rng.integers(0, 1 << 9, n)
    am = rng.integers(0, 1 << 16, n)
    cf = rng.integers(0, 1 << 4, n)
    got = native.encode_pulse_batch(
        t.astype(np.int64).view(np.int64).astype(np.uint32).view(np.int32)
        if False else np.asarray(t, np.uint32).view(np.int32),
        np.asarray(env, np.int32), np.asarray(ph, np.int32),
        np.asarray(fr, np.int32), np.asarray(am, np.int32),
        np.asarray(cf, np.int32))
    want = isa.cmds_to_bytes([
        isa.pulse_cmd(freq_word=int(fr[i]), phase_word=int(ph[i]),
                      amp_word=int(am[i]), env_word=int(env[i]),
                      cfg_word=int(cf[i]), cmd_time=int(t[i]))
        for i in range(n)])
    assert got == want


def test_native_decode_rejects_bad_opcode():
    bad = (0b11111 << 123).to_bytes(16, 'little')
    with pytest.raises(ValueError):
        native.decode_soa_fields(bad)
