"""Cross-stack semantic tests: compiler decisions observable in machine
execution, and buffer-format parity against the reference's disassembler
(imported as a data oracle, not copied)."""

import importlib.util
import sys

import numpy as np
import pytest

import distributed_processor_tpu as dp
from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.sim import simulate
from distributed_processor_tpu.elements import TPUElementConfig
from distributed_processor_tpu.models import make_default_qchip


@pytest.fixture(scope='module')
def qchip(qchipcfg_path):
    return dp.QChip(qchipcfg_path)


def test_virtual_z_lands_in_pulse_phase_words(qchip):
    """Software z-phase accumulation (ResolveVirtualZ) must appear in the
    executed pulse records' phase words."""
    program = [{'name': 'X90', 'qubit': ['Q0']},
               {'name': 'virtual_z', 'qubit': ['Q0'], 'phase': np.pi / 2},
               {'name': 'X90', 'qubit': ['Q0']},
               {'name': 'virtual_z', 'qubit': ['Q0'], 'phase': np.pi / 4},
               {'name': 'X90', 'qubit': ['Q0']}]
    mp = compile_to_machine(program, qchip, n_qubits=1)
    out = simulate(mp)
    assert int(out['err'][0]) == 0
    ecfg = TPUElementConfig()
    phases = [int(p) for p in np.asarray(out['rec_phase'][0, :3])]
    assert phases[0] == 0
    assert phases[1] == ecfg.get_phase_word(np.pi / 2)
    assert phases[2] == ecfg.get_phase_word(3 * np.pi / 4)


def test_cross_core_compiled_feedback(qchip):
    """Q1 branches on Q0's measurement: GlobalAssembler resolves
    'Q0.meas' to core 0's index and the interpreter routes the bit
    across cores (BASELINE config 4 coupling, compiled path)."""
    program = [
        {'name': 'read', 'qubit': ['Q0']},
        # the barrier puts Q0's readout timing in the branch block's
        # schedule ancestry (CFG edges follow last-writer-per-dest), so
        # the inserted Hold covers the cross-core measurement latency
        {'name': 'barrier', 'qubit': ['Q0', 'Q1']},
        {'name': 'branch_fproc', 'alu_cond': 'eq', 'cond_lhs': 1,
         'func_id': 'Q0.meas', 'scope': ['Q1'],
         'true': [{'name': 'X90', 'qubit': ['Q1']}], 'false': []},
        {'name': 'X90', 'qubit': ['Q0']},
    ]
    mp = compile_to_machine(program, qchip, n_qubits=2)
    out0 = simulate(mp, meas_bits=np.zeros((2, 4), int))
    out1 = simulate(mp, meas_bits=np.array([[1, 1, 1, 1], [0, 0, 0, 0]]))
    assert np.all(np.asarray(out0['err']) == 0)
    assert np.all(np.asarray(out1['err']) == 0)
    # Q0's bit = 1 adds one X90 on core 1
    assert int(out1['n_pulses'][1]) == int(out0['n_pulses'][1]) + 1
    # and leaves core 0 unchanged
    assert int(out1['n_pulses'][0]) == int(out0['n_pulses'][0])


class _Numpy1Shim:
    """numpy-1 compat for the reference module (written pre-numpy-2):
    buffers decode to object arrays of python ints so its mixed
    uint32/bigint arithmetic keeps numpy-1 semantics."""
    int32 = np.int64      # avoids numpy-2 strict overflow in astype

    def __getattr__(self, k):
        return getattr(np, k)

    def frombuffer(self, buf, dtype=None):
        return np.frombuffer(buf, dtype=dtype).astype(object)


def _load_reference_asmparse(reference_root):
    path = f'{reference_root}/python/distproc/asmparse.py'
    spec = importlib.util.spec_from_file_location('ref_asmparse', path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, f'{reference_root}/python')   # its distproc imports
    try:
        spec.loader.exec_module(mod)
    except Exception as e:            # pragma: no cover
        pytest.skip(f'reference asmparse not importable: {e}')
    finally:
        sys.path.remove(f'{reference_root}/python')
    mod.numpy = _Numpy1Shim()
    mod.vsign16 = np.vectorize(mod.sign16, otypes=[object])
    mod.vsign32 = np.vectorize(mod.sign32, otypes=[object])
    return mod


def test_env_buffer_parity_with_reference_parser(reference_root):
    """Our packed envelope buffers decode identically under the
    reference's envparse (word = signed 16-bit Q low | I << 16; the
    reference reads real from the high half, asmparse.py:61-62)."""
    ref = _load_reference_asmparse(reference_root)
    ecfg = TPUElementConfig(samples_per_clk=16)
    rng = np.random.default_rng(0)
    env = (rng.uniform(-1, 1, 64) + 1j * rng.uniform(-1, 1, 64)) * 0.9
    buf = ecfg.get_env_buffer(env)
    ours = np.asarray(buf, dtype='<u4')
    theirs = np.asarray(ref.envparse(ours.tobytes()), dtype=complex)
    from distributed_processor_tpu.elements import unpack_iq
    decoded = unpack_iq(ours)
    np.testing.assert_array_equal(np.real(decoded), np.real(theirs))
    np.testing.assert_array_equal(np.imag(decoded), np.imag(theirs))


def test_freq_buffer_parity_with_reference_parser(reference_root):
    """Frequency buffers: word 0 (the 32-bit phase increment) must
    decode to the same frequency under the reference's freqparse."""
    ref = _load_reference_asmparse(reference_root)
    ecfg = TPUElementConfig(samples_per_clk=16)   # 8 GS/s
    freqs = [100e6, 4.2e9, 6.5536e9]
    buf = ecfg.get_freq_buffer(freqs)
    parsed = ref.freqparse(np.asarray(buf, dtype='<u4').tobytes(),
                           ecfg.sample_freq)
    np.testing.assert_allclose(np.asarray(parsed['freq'], float), freqs,
                               rtol=1e-6)
    # the lane phasors decode to unit-magnitude IQ under their parser
    mags = np.abs(np.asarray(parsed['iq15'], dtype=complex)) / (2**15 - 1)
    np.testing.assert_allclose(mags, 1.0, atol=2e-4)


def test_cmdparse_parity_on_pulse_command(reference_root):
    """A pulse command we encode must field-decode identically under the
    reference's cmdparse."""
    ref = _load_reference_asmparse(reference_root)
    from distributed_processor_tpu import isa
    cmd = isa.pulse_cmd(freq_word=0x123, phase_word=0x1abcd, amp_word=0x8421,
                        env_word=(7 << 12) | 3, cfg_word=0x5, cmd_time=4242)
    parsed = ref.cmdparse(int(cmd).to_bytes(16, 'little'))[0]
    assert parsed['cmdtime'] == 4242
    assert parsed['freq'] == 0x123
    assert parsed['phase'] == 0x1abcd
    assert parsed['amp'] == 0x8421
    assert parsed['cfg'] == 0x5
    assert parsed['env_start'] == 3 and parsed['env_length'] == 7


def test_disasm_fields_match_reference_on_compiled_program(reference_root):
    """The CLI disassembler path (isa.disassemble over assembled
    buffers) must agree field-for-field with the reference's cmdparse on
    a fully compiled program — the round-1 review's done-criterion for
    the disasm fix (reference: python/distproc/asmparse.py:12-44)."""
    ref = _load_reference_asmparse(reference_root)
    from distributed_processor_tpu import isa
    from distributed_processor_tpu.pipeline import compile_program
    from distributed_processor_tpu.assembler import GlobalAssembler
    from distributed_processor_tpu.models import make_channel_configs
    program = [{'name': 'X90', 'qubit': ['Q0']},
               {'name': 'virtual_z', 'qubit': ['Q0'], 'phase': np.pi / 2},
               {'name': 'X90', 'qubit': ['Q0']},
               {'name': 'read', 'qubit': ['Q0']},
               {'name': 'branch_fproc', 'alu_cond': 'eq', 'cond_lhs': 1,
                'func_id': 'Q0.meas', 'scope': ['Q0'],
                'true': [{'name': 'X90', 'qubit': ['Q0']}], 'false': []}]
    prog = compile_program(program, make_default_qchip(2))
    asm = GlobalAssembler(prog, make_channel_configs(1), TPUElementConfig)
    cmd_buf = asm.get_assembled_program()['0']['cmd_buf']

    ours = isa.disassemble(cmd_buf)
    theirs = ref.cmdparse(cmd_buf)
    assert len(ours) == len(theirs)
    n_pulses = 0
    for d, r in zip(ours, theirs):
        if d['op'] not in ('pulse_write', 'pulse_write_trig'):
            continue
        n_pulses += 1
        # reference cmdparse decodes the raw field bits unconditionally;
        # compare every immediate (non-register) operand we print
        for k_our, k_ref in (('amp', 'amp'), ('phase', 'phase'),
                             ('freq', 'freq'), ('cfg', 'cfg'),
                             ('env_start', 'env_start'),
                             ('env_length', 'env_length')):
            if isinstance(d.get(k_our), int):
                assert d[k_our] == int(r[k_ref]), (d, r, k_our)
        if 'cmd_time' in d:
            assert d['cmd_time'] == int(r['cmdtime']), (d, r)
    assert n_pulses >= 5     # X90 x3 + rdrv/rdlo pair
