"""Multi-tenant compile front door: content-addressed cache tests.

Covers the service-grade contract of ``compilecache/``:

* content addressing is deterministic — repeated compiles and
  dict-key-reordered sources produce byte-identical MachinePrograms
  and identical keys;
* hit/miss/LRU-evict accounting; eviction falls back to the disk tier;
* the persistent store survives a process restart (subprocess) and
  tolerates corrupt entries;
* singleflight: an 8-thread stampede on one program compiles exactly
  once;
* ``QChip.fingerprint()`` and calibration-epoch invalidation: one gate
  amplitude retune flushes exactly the affected entries, other qchips'
  entries stay warm;
* admission validation rejects malformed programs with ``(core,
  instr)`` coordinates before anything reaches a device;
* ``ExecutionService.submit_source`` end-to-end: results bit-identical
  to ``compile_to_machine`` + ``submit``, including the QASM3 text
  path.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from distributed_processor_tpu.compilecache import (
    CompileCache, PersistentStore, content_key, machine_program_bytes)
from distributed_processor_tpu.decoder import (ProgramValidationError,
                                               machine_program_from_cmds)
from distributed_processor_tpu.models import (active_reset,
                                              make_default_qchip,
                                              rb_ensemble)
from distributed_processor_tpu.pipeline import (cached_compile_to_machine,
                                                compile_to_machine)

N_QUBITS = 2
QUBITS = ['Q0', 'Q1']


def _programs(n, seed=0, depth=2):
    return [active_reset(QUBITS) + p
            for p in rb_ensemble(QUBITS, depth, n, seed=seed)]


def _reorder(prog):
    """The same program with every instruction dict's key order
    reversed — must compile and key identically."""
    return [dict(reversed(list(d.items()))) for d in prog]


@pytest.fixture(scope='module')
def qchip():
    return make_default_qchip(N_QUBITS)


# ---------------------------------------------------------------------------
# determinism: the precondition for content addressing
# ---------------------------------------------------------------------------

def test_compile_to_machine_byte_stable(qchip):
    """Two compiles of the same source — and of a dict-key-reordered
    copy — produce byte-identical MachinePrograms."""
    prog = _programs(1)[0]
    b1 = machine_program_bytes(compile_to_machine(prog, qchip,
                                                  n_qubits=N_QUBITS))
    b2 = machine_program_bytes(compile_to_machine(prog, qchip,
                                                  n_qubits=N_QUBITS))
    b3 = machine_program_bytes(compile_to_machine(_reorder(prog), qchip,
                                                  n_qubits=N_QUBITS))
    assert b1 == b2, 'repeated compile is not byte-stable'
    assert b1 == b3, 'dict-key reordering changed the compiled bytes'


def test_content_key_order_insensitive_and_distinct(qchip):
    p1, p2 = _programs(2)
    k1 = content_key(p1, qchip, n_qubits=N_QUBITS)
    assert content_key(_reorder(p1), qchip, n_qubits=N_QUBITS) == k1
    assert content_key(p2, qchip, n_qubits=N_QUBITS) != k1
    # explicit defaults key the same as omitted arguments
    from distributed_processor_tpu.compiler import CompilerFlags
    from distributed_processor_tpu.hwconfig import FPGAConfig
    assert content_key(p1, qchip, n_qubits=N_QUBITS,
                       fpga_config=FPGAConfig(n_cores=N_QUBITS),
                       compiler_flags=CompilerFlags()) == k1
    # pad_to is part of the key (it changes decode shapes)
    assert content_key(p1, qchip, n_qubits=N_QUBITS, pad_to=256) != k1


def test_qasm_source_keys_byte_for_byte(qchip):
    qasm = ('OPENQASM 3.0;\nqubit[2] q;\nx q[0];\n')
    k = content_key(qasm, qchip, n_qubits=N_QUBITS)
    assert content_key(qasm, qchip, n_qubits=N_QUBITS) == k
    assert content_key(qasm + ' ', qchip, n_qubits=N_QUBITS) != k


# ---------------------------------------------------------------------------
# hit / miss / LRU-evict
# ---------------------------------------------------------------------------

def test_hit_miss_lru_evict(qchip):
    progs = _programs(3)
    cache = CompileCache(capacity=2)
    mp0, s, _ = cache.get_or_compile(progs[0], qchip, n_qubits=N_QUBITS)
    assert s == 'miss'
    mp0b, s, _ = cache.get_or_compile(_reorder(progs[0]), qchip,
                                      n_qubits=N_QUBITS)
    assert s == 'hit' and mp0b is mp0
    cache.get_or_compile(progs[1], qchip, n_qubits=N_QUBITS)
    # capacity 2, recency order is [p0, p1]: compiling p2 evicts p0
    cache.get_or_compile(progs[2], qchip, n_qubits=N_QUBITS)
    st = cache.stats()
    assert st['evictions'] == 1 and st['size'] == 2
    _, s, _ = cache.get_or_compile(progs[0], qchip, n_qubits=N_QUBITS)
    assert s == 'miss', 'evicted entry should recompile'
    assert cache.stats()['misses'] == 4


def test_evicted_entry_comes_back_from_disk(qchip, tmp_path):
    progs = _programs(2)
    cache = CompileCache(capacity=1, cache_dir=str(tmp_path))
    cache.get_or_compile(progs[0], qchip, n_qubits=N_QUBITS)
    cache.get_or_compile(progs[1], qchip, n_qubits=N_QUBITS)  # evicts 0
    mp, s, _ = cache.get_or_compile(progs[0], qchip, n_qubits=N_QUBITS)
    assert s == 'disk', 'eviction should fall back to the disk tier'
    assert machine_program_bytes(mp) == machine_program_bytes(
        compile_to_machine(progs[0], qchip, n_qubits=N_QUBITS))


def test_cached_result_bit_identical_to_direct(qchip):
    prog = _programs(1)[0]
    mp = cached_compile_to_machine(prog, qchip, n_qubits=N_QUBITS,
                                   cache=CompileCache())
    assert machine_program_bytes(mp) == machine_program_bytes(
        compile_to_machine(prog, qchip, n_qubits=N_QUBITS))


# ---------------------------------------------------------------------------
# persistent store
# ---------------------------------------------------------------------------

_CHILD = r'''
import json, sys
from distributed_processor_tpu.compilecache import (CompileCache,
                                                    machine_program_bytes)
from distributed_processor_tpu.models import (active_reset,
                                              make_default_qchip,
                                              rb_ensemble)
qchip = make_default_qchip(2)
prog = (active_reset(['Q0', 'Q1'])
        + rb_ensemble(['Q0', 'Q1'], 2, 1, seed=7)[0])
cache = CompileCache(cache_dir=sys.argv[1])
mp, status, key = cache.get_or_compile(prog, qchip, n_qubits=2)
print(json.dumps({'status': status, 'key': key,
                  'n_bytes': len(machine_program_bytes(mp))}))
'''


@pytest.mark.slow
def test_persistent_store_survives_process_restart(tmp_path):
    """Two fresh processes share a cache dir: the first compiles cold,
    the second starts warm from disk with the identical content key."""
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    out = []
    for _ in range(2):
        r = subprocess.run([sys.executable, '-c', _CHILD, str(tmp_path)],
                           capture_output=True, text=True, env=env,
                           timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]
        out.append(json.loads(r.stdout.strip().splitlines()[-1]))
    assert out[0]['status'] == 'miss'
    assert out[1]['status'] == 'disk', 'restart did not hit the store'
    assert out[0]['key'] == out[1]['key']
    assert out[0]['n_bytes'] == out[1]['n_bytes']


def test_store_corrupt_entry_is_a_miss(qchip, tmp_path):
    prog = _programs(1)[0]
    cache = CompileCache(cache_dir=str(tmp_path))
    _, _, key = cache.get_or_compile(prog, qchip, n_qubits=N_QUBITS)
    (entry,) = [f for f in os.listdir(tmp_path) if f.endswith('.mpc')]
    with open(os.path.join(tmp_path, entry), 'wb') as f:
        f.write(b'garbage not zlib')
    fresh = CompileCache(cache_dir=str(tmp_path))
    _, s, _ = fresh.get_or_compile(prog, qchip, n_qubits=N_QUBITS)
    assert s == 'miss', 'corrupt entry must be a miss, not an error'
    # the recompile overwrote it: next fresh cache hits disk again
    _, s, _ = CompileCache(cache_dir=str(tmp_path)).get_or_compile(
        prog, qchip, n_qubits=N_QUBITS)
    assert s == 'disk'


def test_store_version_skew_is_a_miss(qchip, tmp_path):
    prog = _programs(1)[0]
    cache = CompileCache(cache_dir=str(tmp_path))
    cache.get_or_compile(prog, qchip, n_qubits=N_QUBITS)
    import pickle
    import zlib
    (entry,) = [f for f in os.listdir(tmp_path) if f.endswith('.mpc')]
    fname = os.path.join(tmp_path, entry)
    with open(fname, 'rb') as f:
        payload = pickle.loads(zlib.decompress(f.read()))
    payload['version'] += 1
    with open(fname, 'wb') as f:
        f.write(zlib.compress(pickle.dumps(payload)))
    _, s, _ = CompileCache(cache_dir=str(tmp_path)).get_or_compile(
        prog, qchip, n_qubits=N_QUBITS)
    assert s == 'miss'


# ---------------------------------------------------------------------------
# singleflight
# ---------------------------------------------------------------------------

def test_singleflight_stampede_compiles_once(qchip):
    """8 threads racing the same never-seen program: exactly one
    compile; everyone gets the same MachineProgram object."""
    prog = _programs(1, seed=42)[0]
    calls = []
    release = threading.Event()

    def slow_compile(program, qc, **kw):
        calls.append(threading.get_ident())
        release.wait(timeout=30)
        return compile_to_machine(program, qc, **kw)

    cache = CompileCache(compile_fn=slow_compile)
    results = [None] * 8
    barrier = threading.Barrier(8)

    def worker(i):
        barrier.wait()
        if i == 0:
            # give the stampede a beat to pile onto the flight, then
            # let the owner's compile proceed
            time.sleep(0.1)
            release.set()
        results[i] = cache.get_or_compile(prog, qchip,
                                          n_qubits=N_QUBITS)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert len(calls) == 1, f'stampede compiled {len(calls)} times'
    mps = {id(r[0]) for r in results}
    assert len(mps) == 1, 'waiters got different program objects'
    st = cache.stats()
    assert st['misses'] == 1
    assert st['singleflight_waits'] >= 1


def test_singleflight_failure_propagates_to_waiters(qchip):
    prog = _programs(1, seed=43)[0]
    gate = threading.Event()

    def broken_compile(program, qc, **kw):
        gate.wait(timeout=30)
        raise RuntimeError('compiler exploded')

    cache = CompileCache(compile_fn=broken_compile)
    errors = []
    barrier = threading.Barrier(4)

    def worker(i):
        barrier.wait()
        if i == 0:
            time.sleep(0.05)
            gate.set()
        try:
            cache.get_or_compile(prog, qchip, n_qubits=N_QUBITS)
        except RuntimeError as e:
            errors.append(str(e))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert len(errors) == 4, 'every waiter must see the typed failure'
    # the failure was not cached: a later attempt re-runs the compiler
    gate.set()
    with pytest.raises(RuntimeError):
        cache.get_or_compile(prog, qchip, n_qubits=N_QUBITS)


# ---------------------------------------------------------------------------
# qchip fingerprint + calibration-epoch invalidation
# ---------------------------------------------------------------------------

def test_fingerprint_stable_and_mutation_sensitive():
    a, b = make_default_qchip(N_QUBITS), make_default_qchip(N_QUBITS)
    assert a.fingerprint() == b.fingerprint()
    fp = b.fingerprint()
    b.gates['Q0X90'].contents[0].amp = 0.123
    assert b.fingerprint() != fp, 'amp retune must change the epoch'
    # and it is the VALUE that matters, not the mutation path
    c = make_default_qchip(N_QUBITS)
    c.gates['Q0X90'].contents[0].amp = 0.123
    assert c.fingerprint() == b.fingerprint()


def test_epoch_invalidation_flushes_exactly_affected(tmp_path):
    """Retuning qchip A flushes A's entries (memory AND disk) and
    leaves qchip B's entries warm."""
    qa, qb = make_default_qchip(N_QUBITS), make_default_qchip(N_QUBITS)
    qb.gates['Q1X90'].contents[0].amp = 0.3   # distinct calibration
    progs = _programs(2)
    cache = CompileCache(cache_dir=str(tmp_path))
    for p in progs:
        cache.get_or_compile(p, qa, n_qubits=N_QUBITS)
        cache.get_or_compile(p, qb, n_qubits=N_QUBITS)
    assert cache.stats()['size'] == 4
    # retune one gate on qa; resubmitting through the SAME object
    # auto-flushes the stale epoch
    qa.gates['Q0X90'].contents[0].amp = 0.6
    _, s, _ = cache.get_or_compile(progs[0], qa, n_qubits=N_QUBITS)
    assert s == 'miss'
    st = cache.stats()
    assert st['invalidations'] == 1
    assert st['invalidated_entries'] == 4, \
        '2 memory + 2 disk entries of the stale epoch'
    # qb's entries never went anywhere
    for p in progs:
        _, s, _ = cache.get_or_compile(p, qb, n_qubits=N_QUBITS)
        assert s == 'hit', "other qchip's entries must stay warm"
    # the stale epoch's OTHER program is gone from disk too
    _, s, _ = cache.get_or_compile(progs[1], qa, n_qubits=N_QUBITS)
    assert s == 'miss'


def test_explicit_invalidate_epoch(qchip, tmp_path):
    prog = _programs(1)[0]
    cache = CompileCache(cache_dir=str(tmp_path))
    cache.get_or_compile(prog, qchip, n_qubits=N_QUBITS)
    n = cache.invalidate_epoch(qchip.fingerprint())
    assert n == 2, 'one memory + one disk entry'
    _, s, _ = cache.get_or_compile(prog, qchip, n_qubits=N_QUBITS)
    assert s == 'miss'


# ---------------------------------------------------------------------------
# admission validation
# ---------------------------------------------------------------------------

def _malformed_mp():
    """A decodable program whose jump target is out of bounds — the
    validator rejects it with coordinates (tests/test_faults.py pins
    the codes)."""
    from distributed_processor_tpu import isa
    cmds = [[isa.pulse_cmd(amp_word=100, cfg_word=0, env_word=3,
                           cmd_time=10),
             isa.jump_i(99), isa.done_cmd()]]
    return machine_program_from_cmds(cmds)


def test_validation_rejection_carries_coordinates(qchip):
    cache = CompileCache(compile_fn=lambda *a, **kw: _malformed_mp())
    prog = _programs(1, seed=44)[0]
    with pytest.raises(ProgramValidationError) as ei:
        cache.get_or_compile(prog, qchip, n_qubits=N_QUBITS)
    assert 'jump_oob' in ei.value.codes
    (code, core, instr, msg), = [e for e in ei.value.errors
                                 if e[0] == 'jump_oob']
    assert (core, instr) == (0, 1)
    st = cache.stats()
    assert st['validation_rejects'] == 1
    assert st['size'] == 0, 'a rejected program must never be cached'


def test_validation_can_be_disabled(qchip):
    cache = CompileCache(compile_fn=lambda *a, **kw: _malformed_mp(),
                         validate=False)
    mp, s, _ = cache.get_or_compile(_programs(1, seed=44)[0], qchip,
                                    n_qubits=N_QUBITS)
    assert s == 'miss' and mp.n_cores == 1


# ---------------------------------------------------------------------------
# serve-tier front door: submit_source
# ---------------------------------------------------------------------------

def _svc(**kw):
    from distributed_processor_tpu.serve.service import ExecutionService
    return ExecutionService(max_wait_ms=5.0, **kw)


def test_submit_source_bit_identical_to_compile_plus_submit(qchip):
    progs = _programs(2, seed=45)
    with _svc() as svc:
        refs = []
        for p in progs:
            mp = compile_to_machine(p, qchip, n_qubits=N_QUBITS)
            refs.append(svc.submit(mp, shots=16).result(timeout=120))
        handles = [svc.submit_source(p, qchip, shots=16,
                                     n_qubits=N_QUBITS)
                   for p in progs]
        results = [h.result(timeout=120) for h in handles]
        for got, want in zip(results, refs):
            for k in want:
                np.testing.assert_array_equal(np.asarray(got[k]),
                                              np.asarray(want[k]))
        st = svc.stats()
        assert st['source']['submitted'] == 2
        cc = st['compile_cache']
        assert cc['misses'] == 2 and cc['hits'] == 0


def test_submit_source_qasm_path(qchip):
    """OpenQASM 3 text through the front door matches the frontend +
    compile + submit path bit for bit."""
    from distributed_processor_tpu.frontend import qasm_to_program
    qasm = ('OPENQASM 3;\n'
            'include "stdgates.inc";\n'
            'qubit[2] q;\n'
            'bit[2] c;\n'
            'x q[0];\n'
            'c[0] = measure q[0];\n'
            'c[1] = measure q[1];\n')
    mp = compile_to_machine(qasm_to_program(qasm), qchip,
                            n_qubits=N_QUBITS)
    with _svc() as svc:
        want = svc.submit(mp, shots=8).result(timeout=120)
        got = svc.submit_source(qasm, qchip, shots=8,
                                n_qubits=N_QUBITS).result(timeout=120)
        for k in want:
            np.testing.assert_array_equal(np.asarray(got[k]),
                                          np.asarray(want[k]))
        # warm resubmission of the same text never re-parses
        svc.submit_source(qasm, qchip, shots=8,
                          n_qubits=N_QUBITS).result(timeout=120)
        assert svc.stats()['compile_cache']['hits'] >= 1


def test_submit_source_warm_hits_share_one_compile(qchip):
    prog = _programs(1, seed=46)[0]
    with _svc() as svc:
        handles = [svc.submit_source(prog, qchip, shots=4,
                                     n_qubits=N_QUBITS)
                   for _ in range(6)]
        for h in handles:
            h.result(timeout=120)
        cc = svc.stats()['compile_cache']
        assert cc['misses'] == 1
        assert cc['hits'] + cc['singleflight_waits'] == 5


def test_submit_source_validation_failure_lands_on_handle(qchip):
    cache = CompileCache(compile_fn=lambda *a, **kw: _malformed_mp())
    prog = _programs(1, seed=47)[0]
    with _svc(compile_cache=cache) as svc:
        h = svc.submit_source(prog, qchip, shots=4, n_qubits=N_QUBITS)
        with pytest.raises(ProgramValidationError) as ei:
            h.result(timeout=120)
        assert 'jump_oob' in ei.value.codes
        assert h.done()


def test_submit_source_shutdown_without_drain_fails_typed(qchip):
    """Abandoning ship mid-compile: every pending source handle
    terminates with a typed error, nothing hangs, no thread leaks.
    The in-flight compile lands on ServiceClosedError (its submit
    arrives after closing), queued ones on ShutdownError."""
    from distributed_processor_tpu.serve.request import (
        CancelledError, ServiceClosedError)
    gate = threading.Event()

    def slow_compile(program, qc, **kw):
        gate.wait(timeout=10)
        return compile_to_machine(program, qc, **kw)

    svc = _svc(compile_cache=CompileCache(compile_fn=slow_compile),
               compile_workers=1)
    try:
        handles = [svc.submit_source(p, qchip, shots=4,
                                     n_qubits=N_QUBITS)
                   for p in _programs(3, seed=48)]
        releaser = threading.Timer(0.2, gate.set)
        releaser.start()
        svc.shutdown(drain=False)
    finally:
        gate.set()
    for h in handles:
        assert h.done()
        with pytest.raises((CancelledError, ServiceClosedError)):
            h.result(timeout=5)
    # drain=False still compiles nothing new after shutdown
    with pytest.raises(ServiceClosedError):
        svc.submit_source(_programs(1, seed=49)[0], qchip, shots=4,
                          n_qubits=N_QUBITS)
