#!/usr/bin/env python
"""Headline benchmark: 8-qubit active-reset + randomized-benchmarking
sweep on one chip, with the measurement loop closed by the real DSP
chain (nothing injected).

Measured per batch (steady state, post-jit), all inside ONE jitted XLA
computation (sim/physics.py epoch loop):

  thermal init-state sampling -> batched ISA interpretation (per-shot
  divergent control flow) -> for every fired readout window: waveform
  synthesis (envelope playback + phase-coherent carrier) -> state-
  dependent channel response + per-sample ADC noise -> matched-filter
  demodulation -> state discrimination -> the emergent bits feed the
  fproc fabric and resolve the active-reset branches -> execution
  resumes until all shots complete.

This is the numeric analog of the reference's hardware loop (rdlo pulse
-> external demod -> meas/meas_valid -> core_state_mgr.sv:45-56 ->
branch); the readout word contract is asmparse.py:46-86.

Before timing, the standalone Pallas kernels (ops/waveform_pallas.py
synthesis, ops/demod.demod_iq_pallas) run COMPILED (interpret=False) on
the bench device and are parity-checked against their XLA reference
implementations; the result is recorded in the detail dict.

Prints ONE JSON line: shots/sec/chip, vs_baseline relative to the
north-star target of 1e6 shots in 60 s (BASELINE.md) — the reference
publishes no numbers (it executes shots one at a time on FPGA hardware,
host-sequenced).

Env knobs: BENCH_SHOTS (total, default 1048576), BENCH_BATCH (per-device
batch, default 262144; 524288 also fits HBM with the stats-only carry —
see docs/PERF.md for the budget), BENCH_DEPTH (RB depth, default 12),
BENCH_SIGMA (ADC noise, default 0.05), BENCH_CHUNK (matched-filter
resolve chunk in samples, default 256 — smaller trades speed for peak
memory), BENCH_SWEEP_SHOTS/BENCH_SWEEP_BATCH/BENCH_SWEEP_SPAN (the
dispatch-amortization row's sweep shape, defaults 131072/2048/16),
BENCH_SERVE_REQS/BENCH_SERVE_SHOTS (the continuous-batching row's
request count and shots per request, defaults 32/32),
BENCH_SERVE_DP/BENCH_SERVE_DP_REQS/BENCH_SERVE_DP_SHOTS (the
multi-device scaling sub-row: executor counts '1,2' and its workload,
defaults 1,2/32/64 — runs in a forced-device-count CPU child when this
process sees fewer devices), BENCH_SERVE_OPEN_REQS/
BENCH_SERVE_OPEN_RATE/BENCH_SERVE_OPEN_DEVICES (the open-loop latency
row: request count, Poisson arrival rate in Hz, optional executor
count; defaults 48/40/single-device),
BENCH_COMPILE_TENANTS/BENCH_COMPILE_PROGRAMS/BENCH_COMPILE_DEPTH/
BENCH_COMPILE_SHOTS/BENCH_COMPILE_THREADS (the compile front-door row:
tenants x distinct programs of that RB depth, shots per submit_source
request, stampede width; defaults 4/4/4/8/8),
BENCH_TENANT_VICTIMS/BENCH_TENANT_GREEDY/BENCH_TENANT_SHOTS/
BENCH_TENANT_DEPTH/BENCH_TENANT_WEIGHT/BENCH_TENANT_RATIO (the
tenant-isolation row: victim request count, greedy backlog factor,
shots per request, RB depth, victim DRR weight, and the max allowed
fair-on/fair-off victim-p99 ratio asserted before reporting; defaults
8/8/8/2/8/1.5),
BENCH_OBS_REQS/BENCH_OBS_SHOTS/BENCH_OBS_SAMPLE (the observability
overhead row: workload shape and the intermediate trace-sampling
fraction, defaults 32/32/0.25; BENCH_OBS=0 skips the row),
BENCH_OBS_FLEET_REPLICAS/BENCH_OBS_FLEET_REQS/BENCH_OBS_FLEET_SHOTS
(the fleet observability-overhead row: replica processes and workload
for the off/sampled/full rounds through one fleet, defaults 2/24/8;
also gated by BENCH_OBS=0).

Besides the final stdout line, every completed row is written
incrementally and atomically to BENCH_ARTIFACT (default
bench_partial.json next to this script; set empty to disable), so a
killed or hung row cannot erase the rows already measured.
BENCH_ROW_TIMEOUT (seconds, default 0 = off) arms a soft per-row
watchdog around each secondary: a row that exceeds it records
``{"error": "timeout"}`` in the artifact and the remaining rows still
run.  Leave it off on CPU, where a single block-engine compile can
legitimately take minutes.

If the accelerator preflight fails all its backoff attempts, the bench
reruns itself in a CPU child process (JAX_PLATFORMS=cpu, CPU-sized
default shapes) and marks the artifact and the stdout JSON
``"degraded": true`` — the round keeps a parseable artifact and exit
code 0 instead of a zeroed value.  BENCH_LADDER_DEPTH sets the
engine-ladder row's RB depth (default 100; 0 skips the row).

The detail dict also reports `fused_pallas_shots_per_sec` (the same
chain hand-fused into one Pallas kernel with in-kernel counter-based
ADC noise, ops/resolve_pallas.py) and `analytic_shots_per_sec` (the
exact distributional shortcut — sim/physics.py _resolve_analytic: the
matched filter is linear, so its output distribution is computed
directly at O(1) per window).
The headline mode defaults to `auto`: the XLA and fused-Pallas
formulations of the same per-sample chain are raced for three batches
and the faster one runs the timed measurement (chosen mode recorded in
the detail dict).  `BENCH_MODE=persample|fused|analytic` pins it.

Each mode's program is compiled EXACTLY once (shared between the race,
the headline, and the secondaries), with resolve tables prepared in a
separate small jit and passed as device arrays; a repo-local persistent
XLA compilation cache (.jax_cache, BENCH_NO_CACHE=1 to disable) makes
re-runs skip compilation entirely.  `jit_s` is the headline mode's
actual first-call time and `compilation_cache` reports whether the
cache was warm, so the number is never silently flattered.
"""

import glob
import json
import os
import signal
import sys
import tempfile
import time
import zlib

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

# persistent XLA compilation cache (repo-local): a re-run of the bench
# (or any same-shape run) reuses compiled executables, so the one-time
# jit cost is paid once per machine, not once per process.  BENCH_NO_CACHE=1
# opts out; the cold/warm state is reported in the detail dict so jit_s
# is never silently flattered.  Enabled from main(), NOT at import —
# tests import helpers from this module, and flipping process-global
# cache config as an import side effect poisons their runs (a cached
# executable compiled for another machine's CPU features aborts the
# loading process outright).
_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          '.jax_cache')


def enable_compilation_cache():
    if not os.environ.get('BENCH_NO_CACHE'):
        os.makedirs(_CACHE_DIR, exist_ok=True)
        jax.config.update('jax_compilation_cache_dir', _CACHE_DIR)
        jax.config.update('jax_persistent_cache_min_compile_time_secs',
                          1.0)

import jax.numpy as jnp

from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.models import (
    active_reset, rb_program, make_default_qchip, couplings_from_qchip)
from distributed_processor_tpu.serve.benchmark import (
    availability_under_chaos, calibration_loop, compile_front_door,
    continuous_batching_comparison, fleet_failover,
    fleet_observability_overhead, multi_device_scaling,
    open_loop_latency, tenant_isolation)
from distributed_processor_tpu.sim.interpreter import InterpreterConfig
from distributed_processor_tpu.sim.physics import (
    ReadoutPhysics, run_physics_batch, prepare_physics_tables)

NORTH_STAR_SHOTS_PER_SEC = 1e6 / 60.0


def _cache_state() -> str:
    if os.environ.get('BENCH_NO_CACHE'):
        return 'disabled'
    pre = len(glob.glob(os.path.join(_CACHE_DIR, '*')))
    return f'enabled ({"warm" if pre else "cold"}: {pre} entries)'


def _fmt_sps(v):
    """Secondary shots/s: number, error string, or None (not measured)."""
    return round(v, 1) if isinstance(v, float) else v


class _RowTimeout(Exception):
    pass


def _timed_row(fn):
    """Run one secondary row under the per-row watchdog.

    ``BENCH_ROW_TIMEOUT`` (seconds, default 0 = off — CPU runs
    routinely spend minutes in one compile) arms a SIGALRM timer around
    the row; on expiry the row is abandoned with ``_RowTimeout`` and the
    caller records ``{'error': 'timeout'}``, so one wedged secondary
    cannot starve the rows after it.  SOFT: the alarm is delivered
    between Python bytecodes, so a row stuck inside a single device
    call is reaped when that call returns — the host-loop-structured
    secondaries (probe rounds, scaling, ladder) check out promptly.
    """
    t = float(os.environ.get('BENCH_ROW_TIMEOUT', 0) or 0)
    if not t or not hasattr(signal, 'SIGALRM'):
        return fn()

    def _alarm(signum, frame):
        raise _RowTimeout(f'row exceeded BENCH_ROW_TIMEOUT={t:g}s')

    old = signal.signal(signal.SIGALRM, _alarm)
    signal.setitimer(signal.ITIMER_REAL, t)
    try:
        return fn()
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old)


class _ArtifactWriter:
    """Incremental bench evidence: every completed row atomically
    rewrites the artifact JSON (tmp + os.replace, the ``save_results``
    discipline from utils/results.py), so a later row that hangs or is
    killed can never erase what already finished — BENCH_r05 shipped
    ``rc=2, value=0`` after one tunnel blip wiped the whole round.

    ``BENCH_ARTIFACT`` names the file (default ``bench_partial.json``
    next to this script); set it empty to disable.  A write failure is
    reported on stderr but never kills the bench: the stdout JSON line
    stays the primary output.
    """

    def __init__(self, path: str):
        self.path = path
        self.doc: dict = {}

    def row(self, name: str, value) -> None:
        self.doc[name] = value
        if not self.path:
            return
        try:
            tmp = self.path + '.tmp'
            with open(tmp, 'w') as f:
                json.dump(self.doc, f, indent=1)
            os.replace(tmp, self.path)
        except OSError as e:        # pragma: no cover - defensive
            print(f'artifact write failed: {e}', file=sys.stderr)


def build_machine_program(n_qubits: int, depth: int):
    qubits = [f'Q{i}' for i in range(n_qubits)]
    qchip = make_default_qchip(n_qubits)
    program = active_reset(qubits) + rb_program(qubits, depth, seed=1234)
    return compile_to_machine(program, qchip, n_qubits=n_qubits)


def build_entangling_program(n_qubits: int, layers: int):
    """Brickwork entangling workload for the ``statevec:cz`` probe:
    active reset, then per layer an X90 on every qubit and CZ across
    alternating adjacent pairs (barrier-fenced), then read all — the
    coupling map, the discrete-event ordering gate, and joint collapse
    all live at full system size, the scale the reference ecosystem
    treats as first-class for 2q calibrations (reference:
    python/test/qubitcfg.json:1152 Q5Q4CNOT in an 8-qubit library).
    Returns ``(machine_program, qchip)``."""
    qubits = [f'Q{i}' for i in range(n_qubits)]
    qchip = make_default_qchip(n_qubits)
    prog = active_reset(qubits)
    for layer in range(layers):
        prog.append({'name': 'barrier', 'qubit': qubits})
        for q in qubits:
            prog.append({'name': 'X90', 'qubit': [q]})
        prog.append({'name': 'barrier', 'qubit': qubits})
        for a in range(layer % 2, n_qubits - 1, 2):
            prog.append({'name': 'CZ', 'qubit': [f'Q{a}', f'Q{a + 1}']})
        prog.append({'name': 'barrier', 'qubit': qubits})
    for q in qubits:
        prog.append({'name': 'read', 'qubit': [q]})
    return compile_to_machine(prog, qchip, n_qubits=n_qubits), qchip


def pallas_compiled_parity() -> bool:
    """Run both Pallas kernels on this device and assert parity with the
    XLA reference implementations (shared assertions:
    ops/selftest.py, also run by tests/test_tpu_kernels.py).  Compiled
    (interpret=False) on TPU; interpret mode elsewhere so the bench
    still runs."""
    from distributed_processor_tpu.ops.selftest import pallas_parity_check
    interpret = jax.devices()[0].platform != 'tpu'
    pallas_parity_check(interpret)
    return not interpret


def large_program_scaling(n_qubits: int, small_depth: int,
                          batch: int = 32768):
    """Per-instruction throughput on a deep program (depth-100 RB, past
    the one-hot/gather fetch crossover) vs the headline program — the
    round-1 review's scale-test criterion.  Injected-bits interpretation
    only (the RB body has no feedback); median of 3 host-synced batches
    per label."""
    from distributed_processor_tpu.sim.interpreter import (
        _run_batch, _program_constants)

    results = {}
    for label, depth in (('small', small_depth), ('large', 100)):
        mp = build_machine_program(n_qubits, depth)
        # the scaling criterion targets the GENERIC engine (the
        # straight-line executor caps at SL_AUTO_MAX_INSTR anyway, so
        # mixing engines would confound the per-instruction ratio)
        cfg = InterpreterConfig(
            max_steps=2 * mp.n_instr + 64,
            max_pulses=int(mp.max_pulses_per_core(1)) + 4,
            max_meas=2, max_resets=2)
        soa, spc, interp, sync_part = _program_constants(mp, cfg)
        C = mp.n_cores

        @jax.jit
        def run(bits):
            out = _run_batch(soa, spc, interp, sync_part, bits, cfg, C)
            return (out['n_pulses'].sum(), out['err'].sum(),
                    out['incomplete'])

        bits = jnp.zeros((batch, C, cfg.max_meas), jnp.int32)
        # host-extract INSIDE every timed window and take the median of
        # 3: block_until_ready alone has been seen returning before the
        # tunneled device settles, corrupting single-sample timings
        int(jax.block_until_ready(run(bits))[1])
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            res = run(bits)
            truncated = bool(res[2])
            errs = int(res[1])
            ts.append(time.perf_counter() - t0)
            assert not truncated, f'{label} scaling run truncated'
            assert errs == 0, f'{label} scaling run set error bits'
        dt = sorted(ts)[1]
        results[label] = {
            'n_instr': mp.n_instr,
            'instr_shots_per_sec': round(batch * mp.n_instr / dt, 0),
        }
    small = results['small']['instr_shots_per_sec']
    large = results['large']['instr_shots_per_sec']
    results['large_vs_small_per_instr'] = round(large / small, 3)
    return results


def feedback_round_machine_program(n_data: int, rounds: int,
                                   k_corr: int):
    """Deep lut+fproc feedback workload for the feedback ladder: every
    data core runs ``rounds`` of measure -> branch on the parity LUT
    -> correction block (``k_corr`` drive pulses, the first one
    skipped when the syndrome is clear).  Unrolled (no loops), every
    round's trigger sits after the previous round's read — exactly
    the shape the straight-line span must reject and the block engine
    hosts (docs/PERF.md "Feedback on the fast engines")."""
    from distributed_processor_tpu import isa
    from distributed_processor_tpu.decoder import \
        machine_program_from_cmds
    meas = lambda t: isa.pulse_cmd(freq_word=3, cfg_word=2,
                                   env_word=(2 << 12) | 0, cmd_time=t)
    drv = lambda t: isa.pulse_cmd(freq_word=5, cfg_word=0,
                                  env_word=(2 << 12) | 0, cmd_time=t)
    cores = []
    for _c in range(n_data):
        cmds = []
        for r in range(rounds):
            t0 = 1000 * r
            cmds.append(meas(t0 + 10))
            cmds.append(isa.alu_cmd('jump_fproc', 'i', 0, 'eq',
                                    jump_cmd_ptr=len(cmds) + 2,
                                    func_id=1))
            for i in range(k_corr):
                cmds.append(drv(t0 + 500 + 4 * i))
        cmds.append(isa.done_cmd())
        cores.append(cmds)
    return machine_program_from_cmds(cores)


def fproc_feedback_ladder(n_data: int = 3, rounds: int = 6,
                          k_corr: int = 12, batch: int = 256):
    """Feedback-on-the-fast-engines row (docs/PERF.md "Feedback on
    the fast engines"): outer-loop iteration counts and warm
    per-batch times for generic vs block vs pallas on the deep
    lut+fproc feedback workload — the shape the engine ladder bounced
    to the generic rung before the timestamped fabric made LUT reads
    dispatch-granularity-invariant.  Bit-identity across engines
    (every stat, fault word included) is asserted BEFORE any timing;
    iteration counts are exact while_loop trips, so the reduction
    ratio is backend-independent; the block rung must stay within one
    trace of the content-keyed jit cache."""
    from distributed_processor_tpu.models.repetition import \
        _lut_fabric_kwargs
    from distributed_processor_tpu.sim.interpreter import (
        block_trace_count, resolve_engine, simulate_batch)
    mp = feedback_round_machine_program(n_data, rounds, k_corr)
    kw = dict(mp.static_bounds(), max_meas=rounds, max_resets=2,
              record_pulses=False, **_lut_fabric_kwargs(n_data))
    rng = np.random.default_rng(31)
    bits = rng.integers(0, 2,
                        size=(batch, n_data, rounds)).astype(np.int32)
    out = {'n_data': n_data, 'rounds': rounds, 'k_corr': k_corr,
           'batch': batch, 'n_instr': mp.n_instr}
    results = {}
    n_blk0 = block_trace_count()
    for eng in ('generic', 'block', 'pallas'):
        extra = {'pallas_interpret': True} \
            if eng == 'pallas' and jax.devices()[0].platform != 'tpu' \
            else {}
        cfg = InterpreterConfig(engine=eng, **extra, **kw)
        try:
            resolve_engine(mp, cfg)
        except ValueError as e:
            out[eng] = {'ineligible': str(e)[:200]}
            continue
        t0 = time.perf_counter()
        r = simulate_batch(mp, bits, cfg=cfg)
        steps = int(jax.block_until_ready(r['steps']))
        t_first = time.perf_counter() - t0
        assert not bool(r['incomplete']), f'{eng} feedback run truncated'
        assert int(np.asarray(r['err']).sum()) == 0, \
            f'{eng} feedback run set error bits'
        results[eng] = r
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            rr = simulate_batch(mp, bits, cfg=cfg)
            jax.block_until_ready(rr['err'])
            ts.append(time.perf_counter() - t0)
        out[eng] = {'iterations': steps,
                    'first_call_s': round(t_first, 3),
                    'warm_batch_s': round(sorted(ts)[1], 4)}
    # bit-identity gate: every engine that ran agrees with generic on
    # every stat (the fault word included) before the numbers count
    for eng, r in results.items():
        if eng == 'generic':
            continue
        for k in sorted(set(results['generic']) & set(r)):
            if k == 'steps':
                continue
            assert np.array_equal(np.asarray(results['generic'][k]),
                                  np.asarray(r[k])), \
                f'{eng} diverged from generic on {k}'
    out['block_retraces'] = block_trace_count() - n_blk0
    assert out['block_retraces'] <= 1, \
        'feedback ladder block rung retraced'
    out['iteration_reduction'] = round(
        out['generic']['iterations'] / out['block']['iterations'], 1)
    out['note'] = ('lut+fproc feedback served time-indexed from the '
                   'meas_time planes; identical bits/faults on every '
                   'engine, iterations are while_loop trips (exact)')
    return out


def qec_streaming(n_data: int = 3, rounds: int = 32, batch: int = 256,
                  engine: str = 'auto', chunks: int = 12,
                  chunk_rounds: int = 8):
    """Streaming-QEC row (docs/PERF.md "Streaming QEC"): one
    device-resident R-round scan + in-loop decode
    (``simulate_rounds``) vs R sequential single-round dispatches on
    the repetition-code round program, then the same workload served
    as a streaming traffic class (``StreamSession`` round chunks
    through an ExecutionService) for rounds/s and per-round tail
    latency.  Bit-identity — every stat, fault words included, plus
    in-loop decode vs host decode of the stacked history — is
    asserted BEFORE any timing; the dispatch-amortization factor
    (sequential time / scan time, both warm, host-synced per round on
    the sequential side exactly as a per-round serving loop would
    pay) is the row's headline and must reach 5x at R>=32 on CPU
    (BENCH_QEC_MIN_AMORT overrides, 0 disables the gate)."""
    from dataclasses import replace
    from distributed_processor_tpu.models.qec import (
        qec_config, qec_multiround_machine_program,
        repetition_decode_spec)
    from distributed_processor_tpu.ops.decode import decode_history
    from distributed_processor_tpu.serve import ExecutionService
    from distributed_processor_tpu.sim.interpreter import (
        resolve_engine, rounds_trace_count, simulate_batch,
        simulate_rounds)
    mp = qec_multiround_machine_program(n_data=n_data, rounds=1)
    cfg = qec_config(n_data, record_pulses=False, engine=engine)
    dec = repetition_decode_spec(n_data)
    rng = np.random.default_rng(47)
    mb = rng.integers(
        0, 2, (rounds, batch, mp.n_cores, cfg.max_meas)).astype(np.int32)
    rcfg = replace(cfg, rounds=rounds)
    out = {'n_data': n_data, 'rounds': rounds, 'batch': batch,
           'engine': resolve_engine(mp, cfg), 'n_instr': mp.n_instr}
    # bit-identity gate, before ANY timing: the R-round scan equals R
    # sequential single-round dispatches on every stat (fault words
    # included), and the in-loop decode equals the host decode of the
    # stacked syndrome history
    scan = {k: np.asarray(v) for k, v in
            simulate_rounds(mp, mb, cfg=rcfg, decode=dec).items()}
    seq = [simulate_batch(mp, mb[r], cfg=cfg) for r in range(rounds)]
    for k in sorted(set(scan) - {'syndrome_hist', 'decoded'}):
        stacked = np.stack([np.asarray(s[k]) for s in seq])
        assert stacked.shape == scan[k].shape and \
            np.array_equal(stacked, scan[k]), \
            f'rounds scan diverged from sequential dispatches on {k!r}'
    hist = np.transpose(mb[:, :, :n_data, dec.slot], (1, 0, 2))
    assert np.array_equal(scan['syndrome_hist'], hist), \
        'syndrome history does not match the injected meas planes'
    assert np.array_equal(scan['decoded'],
                          np.asarray(decode_history(hist, dec.scheme))), \
        'in-loop decode diverged from host decode of the history'
    out['bit_identity'] = (f'scan == {rounds} sequential dispatches on '
                           f'every stat incl fault words; in-loop '
                           f'decode == host decode')

    # dispatch amortization, both paths warm: the sequential side
    # host-syncs every round (what a per-round serving loop pays), the
    # scan side is one dispatch for all R rounds + the decode
    def t_scan():
        t0 = time.perf_counter()
        r = simulate_rounds(mp, mb, cfg=rcfg, decode=dec)
        jax.block_until_ready(r['decoded'])
        return time.perf_counter() - t0

    def t_seq():
        t0 = time.perf_counter()
        for r in range(rounds):
            jax.block_until_ready(
                simulate_batch(mp, mb[r], cfg=cfg)['err'])
        return time.perf_counter() - t0

    n_tr0 = rounds_trace_count()
    scan_s = sorted(t_scan() for _ in range(3))[1]
    seq_s = sorted(t_seq() for _ in range(3))[1]
    out['scan_retraces'] = rounds_trace_count() - n_tr0
    assert out['scan_retraces'] == 0, 'warm rounds scan retraced'
    out['scan_s'] = round(scan_s, 4)
    out['sequential_s'] = round(seq_s, 4)
    out['rounds_per_s'] = round(rounds / scan_s, 1)
    out['amortization'] = round(seq_s / scan_s, 1)
    min_amort = float(os.environ.get('BENCH_QEC_MIN_AMORT', 5.0))
    if min_amort and rounds >= 32:
        assert out['amortization'] >= min_amort, \
            (f'dispatch amortization {out["amortization"]}x below the '
             f'{min_amort}x floor at R={rounds}')

    # streaming traffic class: chunked rounds through a StreamSession
    # over a single-device service — per-round latency distribution
    # and served rounds/s with the whole serving stack in the loop
    svc = ExecutionService()
    try:
        sess = svc.open_stream(mp, cfg=cfg, decode=dec)
        shape = (chunk_rounds, batch, mp.n_cores, cfg.max_meas)
        # warm the chunk-shaped executable before the timed chunks
        sess.submit_rounds(rng.integers(0, 2, shape).astype(np.int32))
        next(sess.results(timeout=600))
        lat = []
        t_all = time.perf_counter()
        for _ in range(chunks):
            cmb = rng.integers(0, 2, shape).astype(np.int32)
            t0 = time.perf_counter()
            sess.submit_rounds(cmb).result(timeout=600)
            lat.append((time.perf_counter() - t0) / chunk_rounds)
        wall = time.perf_counter() - t_all
        summary = sess.close(timeout=600)
        assert summary['failed_chunks'] == 0
        assert summary['decoded'].shape == (batch, n_data)
    finally:
        svc.shutdown()
    lat_ms = np.asarray(lat) * 1e3
    out['stream'] = {
        'chunks': chunks, 'chunk_rounds': chunk_rounds,
        'rounds_per_s': round(chunks * chunk_rounds / wall, 1),
        'round_p50_ms': round(float(np.percentile(lat_ms, 50)), 3),
        'round_p99_ms': round(float(np.percentile(lat_ms, 99)), 3),
    }
    out['note'] = ('amortization = R host-synced single-round '
                   'dispatches vs one R-round scan+decode dispatch, '
                   'both warm; stream numbers pay the full serving '
                   'stack per chunk')
    return out


def engine_ladder(n_qubits: int, depth: int, batch: int = 256):
    """Engine-ladder row (docs/PERF.md "The engine ladder"): outer-loop
    iteration counts and warm per-batch times for the generic
    fetch-dispatch engine vs the block engine (CFG superinstructions
    between branch points) vs the pallas megastep engine (each block
    body one kernel call, carry resident in VMEM) on the
    depth-``depth`` active-reset RB program — the workload whose
    active-reset feedback loop is straight-line-INeligible but whose
    RB body is one giant block.  Iteration counts are exact ('steps'
    counts while_loop trips), so the reduction ratio is
    backend-independent; times are medians of 3 warmed host-synced
    batches per engine.  An engine the backend/program cannot run
    records ``{'ineligible': reason}`` (off-TPU the pallas rung runs
    under the kernel interpreter — correct but slow; the degraded
    rerun exercises exactly that path)."""
    from distributed_processor_tpu.sim.interpreter import (
        _block_plan, _soa_static, resolve_engine, simulate_batch)
    mp = build_machine_program(n_qubits, depth)
    _, bodies = _block_plan(_soa_static(mp))
    rng = np.random.default_rng(5)
    bits = rng.integers(0, 2,
                        size=(batch, mp.n_cores, 2)).astype(np.int32)
    out = {'n_qubits': n_qubits, 'depth': depth, 'batch': batch,
           'n_instr': mp.n_instr, 'n_blocks': len(bodies),
           'unrolled_rows': sum(L for _, L in bodies)}
    for eng in ('generic', 'block', 'pallas'):
        cfg = InterpreterConfig(
            max_steps=2 * mp.n_instr + 64,
            max_pulses=int(mp.max_pulses_per_core(1)) + 4,
            max_meas=2, max_resets=2, record_pulses=False, engine=eng)
        try:
            resolve_engine(mp, cfg)
        except ValueError as e:
            out[eng] = {'ineligible': str(e)[:200]}
            continue
        t0 = time.perf_counter()
        r = simulate_batch(mp, bits, cfg=cfg)
        steps = int(jax.block_until_ready(r['steps']))
        t_first = time.perf_counter() - t0
        assert not bool(r['incomplete']), f'{eng} ladder run truncated'
        assert int(np.asarray(r['err']).sum()) == 0, \
            f'{eng} ladder run set error bits'
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            rr = simulate_batch(mp, bits, cfg=cfg)
            jax.block_until_ready(rr['err'])
            ts.append(time.perf_counter() - t0)
        out[eng] = {'iterations': steps,
                    'first_call_s': round(t_first, 3),
                    'warm_batch_s': round(sorted(ts)[1], 4)}
    out['iteration_reduction'] = round(
        out['generic']['iterations'] / out['block']['iterations'], 1)
    out['note'] = ('same injected-bits batch all engines; iterations '
                   'are while_loop trips (exact), reduction holds on '
                   'any backend; pallas runs whole spans as single '
                   'kernel calls (span mode) or rides the block '
                   'iteration structure with each body as one kernel')
    return out


def multi_sequence_rb(n_qubits: int, depth: int, n_seqs: int = 16,
                      shots: int = 4096):
    """Compile-amortization headline: ``n_seqs`` DISTINCT random RB
    sequences at one depth, wall-clock INCLUDING compile.

    Baseline = the per-program content-keyed path (straightline auto,
    the engine the single-program headline opts into): every fresh
    random sequence is a fresh trace+compile, so a 16-sequence ensemble
    pays ~16 warm jits against seconds of compute.  Multi = ONE
    shape-bucketed ``simulate_multi_batch`` call — the whole ensemble
    vmapped inside one jit keyed on the bucket shape.  A second
    ensemble of fresh sequences in the same bucket then reuses the
    compiled executable outright (``multi_warm_s``), which is the
    actual RB workload: tens of random programs per depth, one compile.

    Ensemble seeds come from ``os.urandom`` so the persistent
    compilation cache cannot quietly warm the content-keyed baseline
    across bench runs — content keying genuinely cannot amortize fresh
    random sequences, and the measurement must say so.
    """
    from distributed_processor_tpu.decoder import stack_machine_programs
    from distributed_processor_tpu.models import rb_ensemble
    from distributed_processor_tpu.sim.interpreter import (
        multi_trace_count, simulate_batch, simulate_multi_batch,
        use_straightline)
    qubits = [f'Q{i}' for i in range(n_qubits)]
    qchip = make_default_qchip(n_qubits)

    def compile_ensemble(seed):
        t0 = time.perf_counter()
        mps = [compile_to_machine(active_reset(qubits) + prog, qchip,
                                  n_qubits=n_qubits)
               for prog in rb_ensemble(qubits, depth, n_seqs, seed=seed)]
        return mps, time.perf_counter() - t0

    seed_a, seed_b = (int.from_bytes(os.urandom(4), 'little')
                      for _ in range(2))
    mps, t_frontend = compile_ensemble(seed_a)
    C = mps[0].n_cores
    rng = np.random.default_rng(11)
    bits = rng.integers(0, 2, size=(n_seqs, shots, C, 2)).astype(np.int32)

    def cfg_for(mp):
        return InterpreterConfig(
            max_steps=2 * mp.n_instr + 64,
            max_pulses=int(mp.max_pulses_per_core(1)) + 4,
            max_meas=2, max_resets=2, record_pulses=False,
            straightline=None)

    assert use_straightline(mps[0], cfg_for(mps[0])), \
        'baseline must exercise the content-keyed straight-line path'
    # -- baseline: per-program, content-keyed (compile per sequence) ----
    err = 0
    t0 = time.perf_counter()
    for i, mp in enumerate(mps):
        out = simulate_batch(mp, bits[i], cfg=cfg_for(mp))
        err += int(jax.block_until_ready(out['err']).sum())
    t_per_program = time.perf_counter() - t0
    assert err == 0, f'baseline ensemble set error bits ({err})'

    # -- multi: one shape-bucketed compile for the whole ensemble -------
    mmp = stack_machine_programs(mps)
    cfg_multi = InterpreterConfig(
        max_steps=2 * mmp.n_instr + 64, max_pulses=mmp.n_instr + 2,
        max_meas=2, max_resets=2, record_pulses=False)
    traces0 = multi_trace_count()
    t0 = time.perf_counter()
    out = simulate_multi_batch(mmp, bits, cfg=cfg_multi)
    err = int(jax.block_until_ready(out['err']).sum())
    t_multi = time.perf_counter() - t0
    assert err == 0, f'multi ensemble set error bits ({err})'
    assert not np.any(np.asarray(out['incomplete'])), \
        'multi ensemble hit the step budget'

    # -- fresh sequences, same bucket: compile-free by construction -----
    mps_b, _ = compile_ensemble(seed_b)
    mmp_b = stack_machine_programs(mps_b, pad_to=mmp.n_instr)
    t0 = time.perf_counter()
    out_b = simulate_multi_batch(mmp_b, bits, cfg=cfg_multi)
    jax.block_until_ready(out_b['err'])
    t_multi_warm = time.perf_counter() - t0
    retraces = multi_trace_count() - traces0

    return {
        'n_seqs': n_seqs, 'depth': depth, 'shots_per_seq': shots,
        'bucket_n_instr': mmp.n_instr,
        'frontend_compile_s': round(t_frontend, 3),
        'per_program_s': round(t_per_program, 3),
        'multi_s': round(t_multi, 3),
        'multi_warm_s': round(t_multi_warm, 3),
        'speedup_vs_per_program': round(t_per_program / t_multi, 2),
        'warm_speedup_vs_per_program': round(
            t_per_program / t_multi_warm, 2),
        'retraces_both_ensembles': retraces,
        'note': 'wall-clock including compile; baseline re-jits per '
                'sequence (content-keyed), multi compiles once per '
                'shape bucket and fresh same-shape ensembles are free',
    }


def sweep_span_amortization(n_qubits: int, shots: int, batch: int,
                            span: int, sigma: float):
    """Dispatch-amortization row: the SAME physics-closed sweep through
    ``run_physics_sweep`` twice — per-batch host loop (``span=1``: one
    dispatch + one stats transfer per batch) vs spanned (``span=K``
    batches per ``lax.scan`` dispatch with a donated on-device carry,
    pipelined 1 deep) — on a deliberately dispatch-bound shape (small
    batch, many batches).  ``DispatchTimer`` splits the per-batch hot
    path's wall time into dispatch / device / transfer, making the
    round-5 "the fixed cost is dispatch/tunnel latency, not device
    time" diagnosis reproducible with one call.  The two executions'
    statistics are asserted bit-identical.

    Each sweep is timed twice: cold includes the trace+compile the
    drivers pay per call, warm (second call, persistent compilation
    cache hot) isolates the dispatch economics being measured.
    """
    from distributed_processor_tpu.parallel import run_physics_sweep
    from distributed_processor_tpu.parallel.sweep import physics_batch_stats
    from distributed_processor_tpu.utils.profiling import DispatchTimer
    if shots % batch:
        shots = (shots // batch) * batch
    n_batches = shots // batch
    mp = build_machine_program(n_qubits, 2)     # shallow: dispatch-bound
    cfg = InterpreterConfig(
        max_steps=2 * mp.n_instr + 64,
        max_pulses=int(mp.max_pulses_per_core(1)) + 4,
        max_meas=2, max_resets=2, record_pulses=False)
    model = ReadoutPhysics(sigma=sigma, p1_init=0.1)

    # instrument the exact per-batch step the span amortizes (the
    # driver's own construction: prepared tables passed as device args)
    tables = prepare_physics_tables(mp, model)

    @jax.jit
    def step(k, tabs):
        out = run_physics_batch(mp, model, k, batch, cfg=cfg,
                                tables=tabs)
        return dict(physics_batch_stats(out),
                    incomplete=out['incomplete'].astype(jnp.int32))

    key = jax.random.PRNGKey(7)
    jax.block_until_ready(step(key, tables))    # compile outside timing
    timer = DispatchTimer()
    for i in range(min(n_batches, 32)):
        timer.step(lambda: step(jax.random.fold_in(key, i), tables))

    def timed(**kw):
        t0 = time.perf_counter()
        out = run_physics_sweep(mp, model, shots, batch, key=7, cfg=cfg,
                                **kw)
        return out, time.perf_counter() - t0

    loop, t_loop = timed()
    spanned, t_span = timed(span=span)
    _, t_loop_warm = timed()
    _, t_span_warm = timed(span=span)
    for k in loop:
        assert np.array_equal(np.asarray(loop[k]),
                              np.asarray(spanned[k])), \
            f'spanned sweep diverged from the per-batch loop on {k!r}'

    return {
        'n_qubits': n_qubits, 'shots': shots, 'batch': batch,
        'n_batches': n_batches, 'span': span,
        'dispatches_loop': n_batches,
        'dispatches_span': -(-n_batches // span),
        'loop_s': round(t_loop, 3), 'span_s': round(t_span, 3),
        'speedup': round(t_loop / t_span, 2),
        'loop_warm_s': round(t_loop_warm, 3),
        'span_warm_s': round(t_span_warm, 3),
        'warm_speedup': round(t_loop_warm / t_span_warm, 2),
        'per_batch_breakdown': timer.breakdown(),
        'stats_identical': True,
        'note': 'same fold_in(key, i) stream both ways; spanned stats '
                'asserted bit-identical to the host loop',
    }


class _ModeStep:
    """One compiled physics step per resolve mode, built EXACTLY once
    and reused by the race, the headline measurement, and the
    secondaries — a fresh ``jax.jit`` closure per phase recompiled the
    whole program (jit-of-jit inlines), which is where round 2's
    22-second headline jit_s went.  Resolve tables are prepared in
    their own small jit (prepare_physics_tables) and passed as device
    arrays, keeping their gather-heavy construction out of the stepped
    module and off the per-batch path."""

    def __init__(self, mp, cfg, batch, sigma, chunk, mode,
                 device=None):
        self.mode = mode
        self.mp, self.cfg = mp, cfg
        kw = {} if device is None else {'device': device}
        self.model = ReadoutPhysics(sigma=sigma, p1_init=0.15,
                                    resolve_chunk=chunk,
                                    resolve_mode=mode, **kw)
        t0 = time.perf_counter()
        self.tables = jax.block_until_ready(
            prepare_physics_tables(mp, self.model))
        self.tables_s = time.perf_counter() - t0
        model = self.model

        @jax.jit
        def step(key, tables):
            out = run_physics_batch(mp, model, key, batch, cfg=cfg,
                                    tables=tables)
            # reductions inside the jit: XLA dead-code-eliminates the
            # big per-shot record outputs instead of materializing them
            return (jnp.sum(out['n_pulses'], axis=0), jnp.sum(out['err']),
                    jnp.sum(out['meas_bits'][:, :, 0], axis=0),
                    out['steps'], out['epochs'], out['incomplete'])

        self._step = step
        self.jit_s = None          # set by the first warm-up

    def __call__(self, key):
        return self._step(key, self.tables)

    def warm_up(self, key):
        """First call (compiles); records jit_s; host-syncs."""
        t0 = time.perf_counter()
        res = jax.block_until_ready(self(key))
        if self.jit_s is None:
            self.jit_s = time.perf_counter() - t0
        return res


def _race_modes(steps: dict) -> str:
    """Median of 3 warmed, host-synced batches per per-sample
    formulation; returns the faster mode's name (a single sample can be
    skewed by transient device conditions)."""
    times = {}
    for mode, step in steps.items():
        key = jax.random.PRNGKey(9)
        int(step.warm_up(key)[1])                      # compile + settle
        ts = []
        for r in range(3):
            t0 = time.perf_counter()
            res = step(jax.random.fold_in(key, r + 1))
            ok = int(res[1]) + int(res[5])             # host sync
            ts.append(time.perf_counter() - t0)
            assert ok == 0, f'{mode} race batch errored'
        times[mode] = sorted(ts)[1]
    return min(times, key=times.get)


# Google Cloud TPU v5e public per-chip peaks (the bench's roofline
# denominators; docs/PERF.md derives every numerator)
V5E_BF16_FLOPS = 197e12
V5E_HBM_GBPS = 819.0
V5E_HBM_GIB = 16.0


def utilization_accounting(mp, cfg, model, batch: int,
                           batch_s: float, epochs: int) -> dict:
    """Hardware-utilization accounting for the headline number
    (round-2 review missing #2): measured phase split (exec vs
    resolve) plus analytically derived FLOP/byte volumes -> achieved
    bandwidth and FLOP rate as fractions of the v5e peaks.  XLA's
    static cost analysis is NOT used for the totals: it guesses
    while-loop trip counts and cannot see inside the Pallas custom
    call; docs/PERF.md derives each formula and states what each phase
    is bound by.
    """
    from dataclasses import replace
    from distributed_processor_tpu.sim.device import DeviceModel
    from distributed_processor_tpu.sim.interpreter import (
        _init_state)
    from distributed_processor_tpu.sim.physics import (physics_config,
                                                       _physics_tables)
    C = mp.n_cores
    # the exec probe injects bits (no resolver), which has no device
    # co-state to evolve — measure the phase with the parity counter
    # regardless of the headline's device model
    pcfg = physics_config(cfg, replace(model,
                                       device=DeviceModel('parity')))

    # measured exec phase: the same ENGINE the headline runs (the
    # simulate_batch routing honours cfg.straightline, so the probe
    # times the straight-line executor when the headline uses it) with
    # injected bits standing in for the resolver
    from distributed_processor_tpu.sim.interpreter import simulate_batch

    def ex(bits):
        out = simulate_batch(mp, bits, cfg=pcfg)
        return out['n_pulses'].sum(), out['err'].sum(), out['steps']

    bits = jnp.zeros((batch, C, cfg.max_meas), jnp.int32)
    int(jax.block_until_ready(ex(bits))[1])
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        r = ex(bits)
        steps = int(r[2])
        ts.append(time.perf_counter() - t0)
    t_exec = sorted(ts)[1]
    t_resolve = max(batch_s - t_exec, 1e-9) / max(epochs, 1)

    # loop-carried state bytes (exact, from the carry shapes): every
    # while-loop iteration reads the carry and writes most of it back —
    # the 2x read+write estimate below is the exec phase's HBM model
    st = jax.eval_shape(lambda: _init_state(batch, C, pcfg))
    carry = sum(int(np.prod(v.shape)) * v.dtype.itemsize
                for v in jax.tree.leaves(st))
    carry += 2 * batch * C * cfg.max_meas * 4       # bits + valid
    exec_bytes = 2 * carry * steps
    exec_gbps = exec_bytes / t_exec / 1e9

    # resolve phase (per epoch), derived from the kernel structure: the
    # envelope fetch is a static-address row select when the program's
    # envelope words are statically known (physics._static_meas_env_addrs
    # — R_eff rows of elementwise selects), else a one_hot[lanes, R] @
    # T[R, W'] MXU matmul; plus O(W) elementwise carrier/noise/filter
    from distributed_processor_tpu.sim.physics import \
        _static_meas_env_addrs
    env_stack, freq_stack, _spc, interp_m, w_auto = \
        _physics_tables(mp, model.meas_elem)
    W = int(model.window_samples or w_auto)
    Wp = -(-W // 256) * 256
    rows = _static_meas_env_addrs(mp)
    if rows is not None and model.resolve_mode == 'fused':
        R = -(-max(len(rows), 8) // 8) * 8          # compact row table
        synth_flops = batch * C * Wp * 2 * max(len(rows) - 1, 1)
    else:
        Lp = env_stack.shape[1] + 64                 # padded planes (est)
        R = -(-Lp // 128) * 128
        synth_flops = batch * C * R * Wp * 2 * 2    # 2 planes, 2 flop/MAC
    elem_flops = batch * C * Wp * 24                # carrier+filter+noise
    res_flops = synth_flops + elem_flops
    res_bytes = (batch * C * 4 * (11 + 6)           # lane args + acc r/w
                 + (Wp // 256) * (C * 2 * R * 256 * 4))   # table slices
    # modeled bit-packed carry (interpreter.carry_stream_bytes): what the
    # same 2x read+write model prices when the pallas megastep streams the
    # bit/byte-packed layout instead of the raw int32 carry
    try:
        from distributed_processor_tpu.sim.interpreter import \
            carry_stream_bytes
        carry_u, carry_p = carry_stream_bytes(mp, pcfg)
        packed_row = {
            'carry_bytes_per_shot_unpacked': int(carry_u),
            'carry_bytes_per_shot_packed': int(carry_p),
            'packed_reduction': round(carry_u / carry_p, 2)
            if carry_p else None}
    except Exception as e:                 # non-span program: no megastep
        packed_row = {'carry_packed': f'{type(e).__name__}: {e}'[:120]}
    return {
        'exec_s': round(t_exec, 3),
        'resolve_s_per_epoch': round(t_resolve, 3),
        'interp_steps': steps,
        'carry_bytes_per_shot': int(carry / batch),
        **packed_row,
        'exec_hbm_gbps': round(exec_gbps, 1),
        'exec_hbm_frac': round(exec_gbps / V5E_HBM_GBPS, 3),
        'resolve_tflops': round(res_flops / 1e12, 3),
        'resolve_tflops_per_s': round(res_flops / t_resolve / 1e12, 1),
        'resolve_flops_frac_bf16_peak':
            round(res_flops / t_resolve / V5E_BF16_FLOPS, 3),
        'resolve_hbm_gbps': round(res_bytes / t_resolve / 1e9, 1),
        'note': 'exec is int32 control flow (VPU/latency-bound, no MXU '
                'work by construction); '
                + (f'resolve fetches envelopes via a {len(rows)}-way '
                   f'static-address row select (zero MXU work)'
                   if rows is not None and model.resolve_mode == 'fused'
                   else 'resolve rides the MXU via the one-hot envelope '
                        'fetch at f32-HIGHEST')
                + ' — see docs/PERF.md for derivations and the roofline '
                  'position',
    }


def fused_epoch_comparison(n_qubits: int, shots: int,
                           reps: int = 3) -> dict:
    """Measure-in-megastep vs the epoch-loop resolver (the
    ``fused_epoch`` row): the same sigma=0 active-reset workload
    (branch-on-measurement, physics-closed) run once through the default
    engine's exec->resolve->inject epoch ``while_loop`` and once with
    ``engine='fused'``, which demodulates the readout window inside the
    span kernel.  Bit-identity over every stat (fault word included) is
    asserted BEFORE any timing; the row reports the epoch round-trips
    eliminated and warm median batch times.

    Knobs: BENCH_FUSED_QUBITS / BENCH_FUSED_SHOTS / BENCH_FUSED_REPS;
    the degraded rerun pins tiny shapes (off-TPU the fused kernel runs
    in Pallas interpret mode).
    """
    from distributed_processor_tpu.simulator import Simulator
    from distributed_processor_tpu.models.experiments import active_reset
    from distributed_processor_tpu.sim.physics import (ReadoutPhysics,
                                                       run_physics_batch)
    sim = Simulator(n_qubits=n_qubits)
    mp = sim.compile(active_reset([f'Q{i}' for i in range(n_qubits)]))
    model = ReadoutPhysics(sigma=0.0)   # the fused eligibility envelope
    rng = np.random.default_rng(0)
    init = rng.integers(0, 2, (shots, mp.n_cores)).astype(np.int32)
    kw = dict(init_states=init, max_steps=mp.n_instr * 4 + 64,
              max_pulses=32, max_meas=4)

    def run(**extra):
        return run_physics_batch(mp, model, 5, shots, **kw, **extra)

    base = run()
    fused = run(engine='fused')
    # bit-identity gate before any timing: every stat, fault word
    # included ('epochs'/'steps' are the loop-structure counters the
    # fusion exists to change)
    mismatched = []
    for k in sorted(set(base) | set(fused)):
        if k in ('epochs', 'steps'):
            continue
        a, b = np.asarray(base[k]), np.asarray(fused[k])
        if a.shape != b.shape or not np.array_equal(a, b):
            mismatched.append(k)
    assert not mismatched, \
        f'fused/generic engines diverged on {mismatched}'
    ep_g = int(np.asarray(base['epochs']))
    ep_f = int(np.asarray(fused['epochs']))

    def timed(**extra):
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = run(**extra)
            jax.block_until_ready(out['meas_bits'])
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts))

    t_g, t_f = timed(), timed(engine='fused')
    return {
        'n_qubits': n_qubits, 'shots': shots, 'reps': reps,
        'platform': jax.devices()[0].platform,
        'bit_identity': True,
        'epochs_generic': ep_g, 'epochs_fused': ep_f,
        'exec_resolve_round_trips_eliminated': ep_g - ep_f,
        't_ms_generic': round(t_g * 1e3, 2),
        't_ms_fused': round(t_f * 1e3, 2),
        'speedup': round(t_g / t_f, 2) if t_f else None,
    }


def ici_fabric_comparison(n_cores: int, shots: int,
                          reps: int = 3) -> dict:
    """Cross-chip ICI fabric (the ``ici_fabric`` row): one
    repetition-code round's core axis sharded over the
    ``('dp', 'cores')`` mesh — the fproc/sync barrier riding
    ``lax.all_gather`` collectives over ICI — against the same
    workload on a single device.  Bit-identity over every output key
    (the fault word included) is asserted BEFORE any timing; the row
    reports warm median batch times plus a raw collective microbench
    (time per fabric-shaped all_gather and per scalar psum over the
    cores axis) that anchors the docs/PERF.md "ICI fabric" roofline.

    Knobs: BENCH_ICI_CORES / BENCH_ICI_SHOTS / BENCH_ICI_REPS; needs
    >= 2 devices (``_ici_fabric_row`` shells to a forced-device CPU
    child otherwise; the degraded rerun pins tiny shapes).
    """
    from jax.sharding import PartitionSpec as P
    from distributed_processor_tpu.models.repetition import (
        _lut_fabric_kwargs, repetition_round_machine_program)
    from distributed_processor_tpu.parallel import (
        make_cores_mesh, sharded_cores_simulate)
    from distributed_processor_tpu.parallel.sweep import shard_map
    from distributed_processor_tpu.sim.interpreter import (
        InterpreterConfig, simulate_batch)

    n_dev = len(jax.local_devices())
    shards = 1
    while (shards * 2 <= n_dev and n_cores % (shards * 2) == 0
           and shards * 2 <= n_cores):
        shards *= 2
    if shards < 2:
        return {'skipped': f'needs >= 2 devices dividing {n_cores} '
                           f'cores; host advertises {n_dev} device(s)'}
    mesh = make_cores_mesh(n_cores=shards, n_dp=1)
    mp = repetition_round_machine_program(n_data=n_cores)
    kw = dict(mp.static_bounds(), max_meas=4, max_resets=4,
              **_lut_fabric_kwargs(n_cores))
    rng = np.random.default_rng(2)
    bits = rng.integers(0, 2, (shots, n_cores, 4))

    # bit-identity gate before any timing: every key the sharded entry
    # returns, the fault word included
    single = simulate_batch(mp, bits,
                            cfg=InterpreterConfig(engine='generic', **kw))
    sharded = sharded_cores_simulate(mp, bits, mesh,
                                     cfg=InterpreterConfig(**kw))
    mismatched = [k for k in sorted(set(single) & set(sharded))
                  if not np.array_equal(np.asarray(single[k]),
                                        np.asarray(sharded[k]))]
    assert not mismatched, \
        f'sharded/single-device runs diverged on {mismatched}'

    def timed(fn, ready):
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fn()
            jax.block_until_ready(ready(out))
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts))

    t_single = timed(lambda: simulate_batch(
        mp, bits, cfg=InterpreterConfig(engine='generic', **kw)),
        lambda o: o['err'])
    t_sharded = timed(lambda: sharded_cores_simulate(
        mp, bits, mesh, cfg=InterpreterConfig(**kw)),
        lambda o: o['err'])

    # raw collective microbench: a dependency-chained scan of N
    # fabric-shaped collectives per axis primitive, timed warm — the
    # per-hop latency the sync/fproc barrier pays every interpreter
    # step (each chain step folds the gathered word back into the
    # carry so XLA cannot batch or elide the collectives)
    n_coll = 100
    x0 = np.zeros((shots, n_cores), np.int32)

    def ag_chain(x):
        def body(c, _):
            g = jax.lax.all_gather(c, 'cores', axis=1, tiled=True)
            return c + (jnp.sum(g, axis=1, keepdims=True)
                        .astype(jnp.int32) & 1), None
        return jax.lax.scan(body, x, None, length=n_coll)[0]

    def psum_chain(x):
        def body(c, _):
            s = jax.lax.psum(jnp.sum(c) & 1, 'cores')
            return c + s.astype(jnp.int32), None
        return jax.lax.scan(body, x, None, length=n_coll)[0]

    def coll_us(chain):
        fn = jax.jit(shard_map(chain, mesh=mesh,
                               in_specs=(P(None, 'cores'),),
                               out_specs=P(None, 'cores'),
                               check_vma=False))
        jax.block_until_ready(fn(x0))           # compile + warm
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(x0))
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts)) / n_coll * 1e6

    return {
        'n_cores': n_cores, 'cores_shards': shards, 'shots': shots,
        'reps': reps, 'platform': jax.devices()[0].platform,
        'bit_identity': True,
        't_ms_single_device': round(t_single * 1e3, 2),
        't_ms_sharded': round(t_sharded * 1e3, 2),
        'sharded_over_single': round(t_sharded / t_single, 3)
        if t_single else None,
        'allgather_us': round(coll_us(ag_chain), 2),
        'psum_us': round(coll_us(psum_chain), 2),
    }


def statevec_utilization(step: _ModeStep, batch: int,
                         t_batch: float) -> dict:
    """Roofline position of the statevec trajectory step (round-4
    review: 'the statevec step finally has real matmul-shaped work —
    report where it sits').

    The dominant traffic is the ``[B, 2^C]`` complex64 state itself:
    every channel stage that touches psi streams it through HBM once
    (read + write).  The touch count per interpreter step is derived
    from the model's static channel flags (sim/device.py
    ``statevec_static`` — zero-rate channels are dropped from the traced
    body, so they cost nothing): detuning 1; decay 2 per core (jump +
    dephase); 1q rotation 1 per core (+1 with leakage); measurement 2
    per core (probability reduce + projection); couplings 1 per entry
    (+1 with 2q depol).  FLOPs are the per-core einsums (~16*D per shot
    per 1q op, 64*D per coupling) — orders of magnitude under the MXU
    peak, so the step is HBM-bound by construction and the meaningful
    ceiling is the bandwidth fraction.  ``t_batch`` is the probe's
    interleaved MEDIAN batch time (the one variance-controlled number);
    steps/epochs come from one extra settled batch.
    """
    dev = step.model.device
    C = step.mp.n_cores
    D = 1 << C
    res = jax.block_until_ready(step(jax.random.PRNGKey(77)))
    assert not int(res[1]) and not int(res[5]), \
        'statevec utilization batch errored or ran out of steps'
    steps_n, epochs = int(res[3]), int(res[4])
    (cps, has_det, has_decay, _dp1, has_dp2, has_leak, _bit,
     has_leak1, has_leak2, _seep) = dev.statevec_static()
    touches = ((1 if has_det else 0)
               + C * ((2 if has_decay else 0) + 1
                      + (1 if has_leak1 else 0) + 2)
               + len(cps) * (1 + (1 if has_dp2 else 0)
                             + (1 if has_leak2 else 0)))
    psi_bytes = batch * D * 8                     # complex64 state
    traffic = 2.0 * touches * psi_bytes * steps_n
    flops = float(steps_n) * batch * D * (16 * C + 64 * len(cps))
    return {
        'steps': steps_n, 'epochs': epochs,
        'psi_bytes_per_shot': D * 8,
        'psi_touches_per_step': touches,
        'model_hbm_traffic_gb': round(traffic / 1e9, 1),
        'implied_hbm_gbps': round(traffic / t_batch / 1e9, 1),
        'implied_hbm_frac': round(traffic / t_batch / 1e9 / V5E_HBM_GBPS,
                                  3),
        'einsum_tflops_per_s': round(flops / t_batch / 1e12, 2),
        'flops_frac_bf16_peak': round(flops / t_batch / V5E_BF16_FLOPS,
                                      4),
        'note': 'HBM-bound by construction: the [B, 2^C] complex carry '
                'streams once per channel stage per step; einsum FLOPs '
                'are negligible against the MXU peak.  Traffic is the '
                'analytic touch model (not XLA cost_analysis — see '
                'docs/PERF.md), time is the interleaved probe median.',
    }


def _preflight(timeouts=(30.0, 60.0, 120.0)):
    """Fail fast with a diagnostic JSON if the accelerator backend hangs
    (a dead axon tunnel blocks forever inside backend init, which would
    otherwise stall the whole bench run silently).

    Two layers of protection.  The attempt loop
    (:func:`_preflight_attempts`) retries with backoff and per-attempt
    probe timeouts; the error JSON is emitted only after EVERY attempt
    fails, with the full per-attempt record (outcome, elapsed, error,
    and the probe STAGE in flight — device_init / allocate / compute).
    Above it, a HARD watchdog (``BENCH_PREFLIGHT_TIMEOUT`` seconds,
    default the attempt budget + 60) bounds the whole preflight: the
    per-attempt timeouts cannot catch a hang OUTSIDE the probe thread
    (backend plugin import, thread creation under a wedged runtime —
    ``BENCH_PREFLIGHT_HANG=1`` provokes it in tests), so on expiry the
    watchdog abandons the attempt loop, records a synthetic
    ``stage='watchdog'`` attempt, and degrades to the CPU self-rerun
    (exit 0, ``"degraded": true``) exactly like an ordinary preflight
    failure.  Returns the attempt record on success for the detail
    dict.
    """
    import threading
    budget = float(os.environ.get('BENCH_PREFLIGHT_TIMEOUT',
                                  sum(timeouts) + 60.0))
    done = threading.Event()
    box = []                    # [attempts] when the loop finished
    worker = threading.Thread(
        target=lambda: (box.append(_preflight_attempts(timeouts)),
                        done.set()),
        daemon=True)
    worker.start()
    if done.wait(budget) and box:
        attempts = box[0]
        if attempts and attempts[-1].get('ok'):
            return attempts
    else:
        attempts = [{'attempt': 0, 'ok': False, 'stage': 'watchdog',
                     'elapsed_s': round(budget, 3),
                     'error': (f'preflight exceeded the hard watchdog '
                               f'BENCH_PREFLIGHT_TIMEOUT={budget:g}s '
                               f'(hung outside the probe thread)')}]
        print(f'preflight watchdog fired after {budget:g}s',
              file=sys.stderr)
    if not os.environ.get('BENCH_DEGRADED'):
        _degraded_rerun(attempts)   # execs a CPU child; exits 0 on success
    print(json.dumps({
        'metric': 'shots/sec/chip, 8q active-reset+RB, physics-closed '
                  '(synth+demod+discriminate in-loop)',
        'value': 0, 'unit': 'shots/s', 'vs_baseline': 0,
        'detail': {'error': attempts[-1]['error'],
                   'preflight_attempts': attempts},
    }), flush=True)
    os._exit(2)


def _preflight_attempts(timeouts):
    """The preflight attempt loop (see :func:`_preflight`): probes the
    backend with per-attempt timeouts and backoff.  Always returns the
    full attempt record — the LAST entry's ``ok`` says whether the
    backend came up; the caller owns the failure path (degraded rerun
    or error JSON)."""
    import threading
    if os.environ.get('BENCH_PREFLIGHT_HANG'):
        # test hook: a hang the per-attempt machinery CANNOT see (the
        # wedge is before any probe thread exists) — only the outer
        # watchdog catches this
        threading.Event().wait()
    attempts = []
    for n, timeout_s in enumerate(timeouts, start=1):
        done = threading.Event()
        failure = []
        stage = ['device_init']     # last stage the probe entered

        def probe():
            try:
                fail_at = os.environ.get('BENCH_PREFLIGHT_FAIL')

                def _enter(s):
                    stage[0] = s
                    if fail_at in ('1', s):
                        # test hook: a dead backend is otherwise
                        # impossible to provoke deterministically in CI
                        # ('1' fails immediately; a stage name fails
                        # once the probe reaches that stage)
                        raise RuntimeError('forced preflight failure '
                                           f'at {s} '
                                           '(BENCH_PREFLIGHT_FAIL)')

                _enter('device_init')
                jax.devices()
                _enter('allocate')
                x = jnp.ones((8,))
                _enter('compute')
                float(x.sum())
                stage[0] = 'done'
            except Exception as e:      # fast failure: report, don't wait
                failure.append(f'{type(e).__name__}: {e}'[:300])
            finally:
                done.set()

        t0 = time.perf_counter()
        # a fresh daemon thread per attempt: a probe hung inside backend
        # init never returns, so the next attempt must not join it
        threading.Thread(target=probe, daemon=True).start()
        done.wait(timeout_s)
        elapsed = round(time.perf_counter() - t0, 3)
        if done.is_set() and not failure:
            attempts.append({'attempt': n, 'ok': True,
                             'elapsed_s': elapsed})
            return attempts
        attempts.append({
            'attempt': n, 'ok': False, 'elapsed_s': elapsed,
            'stage': stage[0],
            'error': failure[0] if failure else (
                f'accelerator backend unresponsive after {timeout_s:.0f}s '
                f'(hung in probe stage {stage[0]!r} — tunnel down?)')})
        print(f'preflight attempt {n}/{len(timeouts)} failed: '
              f'{attempts[-1]["error"]}', file=sys.stderr)
    return attempts


def _degraded_rerun(attempts):
    """Degraded-mode fallback: the accelerator backend is dead, but a
    zeroed perf artifact still wipes a round's evidence (the BENCH_r05
    failure class the artifact writer exists for).  Rerun the whole
    bench in a CPU child process (the JAX backend is process-global, so
    the rerun cannot happen in this process), with conservative default
    shapes unless the caller pinned them, and mark every output
    ``"degraded": true`` so a CPU number can never masquerade as a chip
    number.  Exits 0 when the child succeeds; falls through (to the
    error JSON + exit 2) when it does not."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS='cpu', BENCH_DEGRADED='1')
    # the forced-failure/hang test hooks must not fail the CPU child too
    env.pop('BENCH_PREFLIGHT_FAIL', None)
    env.pop('BENCH_PREFLIGHT_HANG', None)
    # CPU-sized defaults (only where the caller didn't pin a value):
    # the accelerator shapes are hours on a CPU
    for k, v in (('BENCH_SHOTS', '2048'), ('BENCH_BATCH', '1024'),
                 ('BENCH_MODE', 'persample'), ('BENCH_PROBE_ROUNDS', '2'),
                 ('BENCH_MULTI_SEQS', '4'), ('BENCH_MULTI_SHOTS', '256'),
                 ('BENCH_SWEEP_SHOTS', '8192'), ('BENCH_SWEEP_BATCH', '1024'),
                 ('BENCH_SWEEP_SPAN', '4'), ('BENCH_LADDER_DEPTH', '12'),
                 ('BENCH_SERVE_REQS', '8'), ('BENCH_SERVE_SHOTS', '16'),
                 ('BENCH_SERVE_DP_REQS', '8'),
                 ('BENCH_SERVE_DP_SHOTS', '16'),
                 ('BENCH_SERVE_OPEN_REQS', '12'),
                 ('BENCH_SERVE_OPEN_RATE', '30'),
                 ('BENCH_SERVE_OPEN_SHOTS', '8'),
                 ('BENCH_CHAOS_REQS', '24'),
                 ('BENCH_CHAOS_RATE', '40'),
                 ('BENCH_FLEET_REQS', '24'),
                 ('BENCH_FLEET_RATE', '20'),
                 ('BENCH_COMPILE_TENANTS', '3'),
                 ('BENCH_COMPILE_PROGRAMS', '2'),
                 ('BENCH_COMPILE_DEPTH', '2'),
                 ('BENCH_COMPILE_SHOTS', '8'),
                 ('BENCH_TENANT_VICTIMS', '4'),
                 ('BENCH_TENANT_GREEDY', '6'),
                 ('BENCH_TENANT_SHOTS', '4'),
                 ('BENCH_OBS_REQS', '8'), ('BENCH_OBS_SHOTS', '8'),
                 ('BENCH_OBS_FLEET_REQS', '12'),
                 ('BENCH_OBS_FLEET_SHOTS', '8'),
                 # exec_profile row under the kernel interpreter: tiny
                 # batches, one rep — the (a, b) fit is still real
                 ('PROFILE_BATCHES', '64,128,256'),
                 ('PROFILE_REPS', '1'),
                 # fused_epoch row in Pallas interpret mode: tiny shapes,
                 # the epoch count + bit-identity are still real
                 ('BENCH_FUSED_QUBITS', '2'),
                 ('BENCH_FUSED_SHOTS', '64'),
                 ('BENCH_FUSED_REPS', '1'),
                 # ici_fabric row on forced CPU devices: a tiny core
                 # count + batch — the collective latencies and the
                 # bit-identity gate are still real
                 ('BENCH_ICI_CORES', '4'),
                 ('BENCH_ICI_SHOTS', '64'),
                 ('BENCH_ICI_REPS', '1'),
                 # fproc_feedback_ladder row: a shallow feedback
                 # workload — the iteration reduction and bit-identity
                 # gate are shape-independent
                 ('BENCH_FEEDBACK_ROUNDS', '4'),
                 ('BENCH_FEEDBACK_CORR', '12'),
                 ('BENCH_FEEDBACK_SHOTS', '64'),
                 # qec_streaming row at CPU size: R stays 32 so the
                 # amortization floor is measured for real, the batch
                 # and chunk counts shrink
                 ('BENCH_QEC_SHOTS', '64'),
                 ('BENCH_QEC_ROUNDS', '32'),
                 ('BENCH_QEC_CHUNKS', '6'),
                 # calibration_loop row at CPU size: fewer shots per
                 # candidate — steps-to-converge, the epoch flush and
                 # the warm-hit assertion are shot-count independent
                 ('BENCH_CALIB_SHOTS', '2')):
        env.setdefault(k, v)
    print('preflight failed on the accelerator backend; rerunning the '
          'bench DEGRADED on CPU (JAX_PLATFORMS=cpu)', file=sys.stderr)
    rc = subprocess.call([sys.executable,
                          os.path.abspath(__file__)], env=env)
    if rc == 0:
        os._exit(0)
    print(f'degraded CPU rerun failed (rc={rc})', file=sys.stderr)


def _serve_scaling_row():
    """Multi-device serve scaling: the continuous-batching workload at
    dp=1,2,... per-device executors (``BENCH_SERVE_DP``, default
    '1,2').  Runs in-process when this process already sees enough
    devices (TPU hosts); otherwise shells out to a CPU child with
    ``--xla_force_host_platform_device_count`` so the executor pool is
    real — the ISSUE-sanctioned off-TPU path.  Either way the row
    carries per-device dispatch counts and the bit-identity gate runs
    before any timing (serve/benchmark.py)."""
    import re
    import subprocess
    dp_list = sorted({int(x) for x in os.environ.get(
        'BENCH_SERVE_DP', '1,2').split(',') if x})
    n_reqs = int(os.environ.get('BENCH_SERVE_DP_REQS', 32))
    shots = int(os.environ.get('BENCH_SERVE_DP_SHOTS', 64))
    depth = int(os.environ.get('BENCH_SERVE_DP_DEPTH', 2))
    if len(jax.local_devices()) >= dp_list[-1]:
        return multi_device_scaling(dp_list=dp_list, n_reqs=n_reqs,
                                    shots=shots, depth=depth)
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    flags = re.sub(r'--xla_force_host_platform_device_count=\d+', '',
                   env.get('XLA_FLAGS', ''))
    env['XLA_FLAGS'] = (flags + ' --xla_force_host_platform_device_'
                        f'count={dp_list[-1]}').strip()
    if not env.get('BENCH_NO_CACHE'):
        env.setdefault('JAX_COMPILATION_CACHE_DIR', _CACHE_DIR)
    env['PYTHONPATH'] = os.pathsep.join(
        p for p in (os.path.dirname(os.path.abspath(__file__)),
                    env.get('PYTHONPATH', '')) if p)
    proc = subprocess.run(
        [sys.executable, '-m',
         'distributed_processor_tpu.serve.benchmark', 'scaling',
         '--dp', ','.join(map(str, dp_list)), '--reqs', str(n_reqs),
         '--shots', str(shots), '--depth', str(depth)],
        env=env, capture_output=True, text=True,
        timeout=float(os.environ.get('BENCH_SERVE_DP_TIMEOUT', 1800)))
    if proc.returncode != 0:
        return {'error': f'forced-device child rc={proc.returncode}: '
                         f'{proc.stderr.strip()[-300:]}'}
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    row['forced_device_child'] = True
    return row


def _serve_open_loop_row():
    """Open-loop serve latency: p50/p99 under seeded Poisson-ish
    mixed-bucket arrivals (serve/benchmark.py).

    Runs the latency-SLO comparison by default (``BENCH_SERVE_OPEN_SLO
    =0`` opts out): the same arrival trace cold (catalog learning,
    compiles inside the timed window) then after catalog replay, with
    warmed p99 < unwarmed p99 and zero warm-round cold hits asserted
    inside the row.  ``BENCH_SERVE_OPEN_CATALOG`` persists the learned
    catalog instead of a throwaway temp file."""
    devs = os.environ.get('BENCH_SERVE_OPEN_DEVICES')
    return open_loop_latency(
        n_reqs=int(os.environ.get('BENCH_SERVE_OPEN_REQS', 48)),
        rate_hz=float(os.environ.get('BENCH_SERVE_OPEN_RATE', 40)),
        shots=int(os.environ.get('BENCH_SERVE_OPEN_SHOTS', 16)),
        devices=int(devs) if devs else None,
        slo=os.environ.get('BENCH_SERVE_OPEN_SLO', '1') not in ('', '0'),
        warmup_catalog=os.environ.get('BENCH_SERVE_OPEN_CATALOG') or None)


def _serve_chaos_row():
    """Availability under chaos: goodput fraction + p99 latency of an
    open-loop arrival stream while seeded crash/hang/slowdown faults
    are injected under the service's ``_run_batch`` — the supervision
    stack (bounded retries, breaker quarantine, hang watchdog, canary
    re-admission) is what keeps goodput near 1.0.  Bit-identity is
    asserted on every completed request and every handle must
    terminate before numbers are reported (serve/benchmark.py)."""
    devs = os.environ.get('BENCH_CHAOS_DEVICES')
    return availability_under_chaos(
        n_reqs=int(os.environ.get('BENCH_CHAOS_REQS', 80)),
        rate_hz=float(os.environ.get('BENCH_CHAOS_RATE', 60)),
        shots=int(os.environ.get('BENCH_CHAOS_SHOTS', 8)),
        seed=int(os.environ.get('BENCH_CHAOS_SEED', 0)),
        devices=int(devs) if devs else None,
        p_crash=float(os.environ.get('BENCH_CHAOS_P_CRASH', 0.08)),
        p_hang=float(os.environ.get('BENCH_CHAOS_P_HANG', 0.02)),
        p_slow=float(os.environ.get('BENCH_CHAOS_P_SLOW', 0.10)))


def _fleet_failover_row():
    """Fleet-tier availability: goodput + p99 of an open-loop stream
    over N replica PROCESSES while the loaded replica is SIGKILLed
    mid-stream (timed kill window) and respawned from the shared warm
    tiers.  Bit-identity, zero-hang, and positive kill-window goodput
    are asserted before any number is reported
    (serve/benchmark.py fleet_failover)."""
    return fleet_failover(
        n_replicas=int(os.environ.get('BENCH_FLEET_REPLICAS', 2)),
        n_reqs=int(os.environ.get('BENCH_FLEET_REQS', 60)),
        rate_hz=float(os.environ.get('BENCH_FLEET_RATE', 30)),
        shots=int(os.environ.get('BENCH_FLEET_SHOTS', 8)),
        seed=int(os.environ.get('BENCH_FLEET_SEED', 0)),
        kill_window_s=float(os.environ.get('BENCH_FLEET_KILL_WINDOW',
                                           2.0)))


def _observability_overhead_row():
    """What request tracing costs: the continuous-batching workload at
    trace_sample 0 (the default), a sampled fraction, and 1.0 — the
    tracing-off throughput must stay within noise of the untraced
    baseline (docs/OBSERVABILITY.md; asserted by the acceptance
    criterion, reported here).  ``BENCH_OBS_REQS`` / ``BENCH_OBS_SHOTS``
    size the workload, ``BENCH_OBS_SAMPLE`` sets the middle point."""
    n_reqs = int(os.environ.get('BENCH_OBS_REQS', 32))
    shots = int(os.environ.get('BENCH_OBS_SHOTS', 32))
    sampled = float(os.environ.get('BENCH_OBS_SAMPLE', 0.25))
    out = {'n_reqs': n_reqs, 'shots_per_req': shots}
    base_svc_s = None
    for label, sample in (('off', 0.0), ('sampled', sampled),
                          ('full', 1.0)):
        # dump to a throwaway file so trace_events reports the real
        # retained span-event count at each sampling level
        fd, tmp = tempfile.mkstemp(suffix='.trace.json')
        os.close(fd)
        try:
            row = continuous_batching_comparison(
                n_reqs=n_reqs, shots=shots, trace_sample=sample,
                trace_out=tmp)
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        entry = {
            'trace_sample': sample,
            'service_warm_s': row['service_warm_s'],
            'throughput_ratio': row['throughput_ratio'],
            'latency_p99_ms': row['latency_p99_ms'],
            'trace_events': row['trace_events'],
        }
        if base_svc_s is None:
            base_svc_s = row['service_warm_s']
        elif base_svc_s > 0:
            entry['overhead_vs_off'] = round(
                row['service_warm_s'] / base_svc_s - 1.0, 4)
        out[label] = entry
    return out


def _integrity_overhead_row():
    """What the integrity fabric costs: the continuous-batching
    workload with audits off (the default), sampled at
    ``BENCH_INTEGRITY_SAMPLE``, and strict ``audit_sample=1`` — the
    audits-off throughput must stay within noise of the unaudited
    baseline (zero-cost default), and the audited rounds report
    overhead proportional to the sampling rate plus the audit count
    actually paid (docs/ROBUSTNESS.md "Integrity").
    ``BENCH_INTEGRITY_REQS`` / ``BENCH_INTEGRITY_SHOTS`` size the
    workload."""
    n_reqs = int(os.environ.get('BENCH_INTEGRITY_REQS', 32))
    shots = int(os.environ.get('BENCH_INTEGRITY_SHOTS', 32))
    sampled = float(os.environ.get('BENCH_INTEGRITY_SAMPLE', 0.125))
    out = {'n_reqs': n_reqs, 'shots_per_req': shots}
    base_svc_s = None
    for label, kwargs in (
            ('off', {}),
            ('sampled', {'audit_sample': sampled,
                         'audit_mode': 'flag'}),
            ('strict', {'audit_sample': 1.0, 'audit_mode': 'strict'})):
        row = continuous_batching_comparison(
            n_reqs=n_reqs, shots=shots, service_kwargs=kwargs)
        entry = {
            'audit_sample': kwargs.get('audit_sample', 0.0),
            'audit_mode': kwargs.get('audit_mode', 'flag'),
            'service_warm_s': row['service_warm_s'],
            'throughput_ratio': row['throughput_ratio'],
            'latency_p99_ms': row['latency_p99_ms'],
            'audits': row['audits'],
            'audit_mismatches': row['audit_mismatches'],
        }
        if base_svc_s is None:
            base_svc_s = row['service_warm_s']
        elif base_svc_s > 0:
            entry['overhead_vs_off'] = round(
                row['service_warm_s'] / base_svc_s - 1.0, 4)
        out[label] = entry
    return out


def _fleet_observability_overhead_row():
    """What fleet-wide observability costs: the same closed-loop
    workload through one fleet of replica processes at trace_sample
    off / BENCH_OBS_SAMPLE / full, the router sampler retuned live
    between rounds.  The deltas isolate the cross-process tracing tax
    (wire trace ids, replica span capture, span piggyback, router
    stitching + clock alignment); bit-identity asserted per round and
    the full round must retain stitched spans before any overhead is
    reported (serve/benchmark.py fleet_observability_overhead)."""
    return fleet_observability_overhead(
        n_replicas=int(os.environ.get('BENCH_OBS_FLEET_REPLICAS', 2)),
        n_reqs=int(os.environ.get('BENCH_OBS_FLEET_REQS', 24)),
        shots=int(os.environ.get('BENCH_OBS_FLEET_SHOTS', 8)),
        seed=int(os.environ.get('BENCH_OBS_FLEET_SEED', 0)),
        sampled=float(os.environ.get('BENCH_OBS_SAMPLE', 0.25)))


def _compile_front_door_row():
    """Multi-tenant compile front door: N tenants x M duplicate source
    programs through the content-addressed compile cache vs uncached
    compile-per-request.  The row itself asserts the contract — exactly
    M cold compiles, 100% warm hit rate, a concurrent stampede
    compiling exactly once (singleflight), submit_source bit-identical
    to compile+submit, warm speedup >= 10x (serve/benchmark.py)."""
    return compile_front_door(
        n_tenants=int(os.environ.get('BENCH_COMPILE_TENANTS', 4)),
        n_programs=int(os.environ.get('BENCH_COMPILE_PROGRAMS', 4)),
        depth=int(os.environ.get('BENCH_COMPILE_DEPTH', 4)),
        shots=int(os.environ.get('BENCH_COMPILE_SHOTS', 8)),
        seed=int(os.environ.get('BENCH_COMPILE_SEED', 0)),
        stampede_threads=int(os.environ.get('BENCH_COMPILE_THREADS',
                                            8)))


def _tenant_isolation_row():
    """Tenant isolation: a greedy tenant dumps its whole backlog ahead
    of a victim's trickle, measured fair-off (arrival order) vs
    fair-on (weighted deficit round-robin).  The row asserts the
    isolation contract before reporting — zero victim sheds, exact
    victim billing (metered shots == ground truth), fair-on victim p99
    within a bounded ratio of fair-off — then reports both victim
    tails (serve/benchmark.py tenant_isolation)."""
    return tenant_isolation(
        n_victim=int(os.environ.get('BENCH_TENANT_VICTIMS', 8)),
        greedy_factor=int(os.environ.get('BENCH_TENANT_GREEDY', 8)),
        shots=int(os.environ.get('BENCH_TENANT_SHOTS', 8)),
        depth=int(os.environ.get('BENCH_TENANT_DEPTH', 2)),
        seed=int(os.environ.get('BENCH_TENANT_SEED', 0)),
        victim_weight=float(os.environ.get('BENCH_TENANT_WEIGHT', 8)),
        max_p99_ratio=float(os.environ.get('BENCH_TENANT_RATIO', 1.5)))


def _ici_fabric_row():
    """Cross-chip ICI fabric: the cores-sharded interpreter
    (``BENCH_ICI_CORES``-core repetition round, sync/fproc riding
    all_gather) vs single-device, bit-identity gated before timing,
    plus the raw collective latency microbench behind the docs/PERF.md
    "ICI fabric" roofline.  Runs in-process when this process already
    sees >= 2 devices (TPU hosts); otherwise shells out to a CPU child
    with ``--xla_force_host_platform_device_count`` so the collectives
    are real — the same off-TPU path as the serve scaling row."""
    import re
    import subprocess
    n_cores = int(os.environ.get('BENCH_ICI_CORES', 8))
    shots = int(os.environ.get('BENCH_ICI_SHOTS', 256))
    reps = int(os.environ.get('BENCH_ICI_REPS', 3))
    if len(jax.local_devices()) >= 2:
        return ici_fabric_comparison(n_cores, shots, reps=reps)
    want = 1
    while want * 2 <= n_cores and want * 2 <= 8:
        want *= 2
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    flags = re.sub(r'--xla_force_host_platform_device_count=\d+', '',
                   env.get('XLA_FLAGS', ''))
    env['XLA_FLAGS'] = (flags + ' --xla_force_host_platform_device_'
                        f'count={want}').strip()
    if not env.get('BENCH_NO_CACHE'):
        env.setdefault('JAX_COMPILATION_CACHE_DIR', _CACHE_DIR)
    env['PYTHONPATH'] = os.pathsep.join(
        p for p in (os.path.dirname(os.path.abspath(__file__)),
                    env.get('PYTHONPATH', '')) if p)
    code = (f'import json, bench; print(json.dumps('
            f'bench.ici_fabric_comparison({n_cores}, {shots}, '
            f'reps={reps})))')
    proc = subprocess.run(
        [sys.executable, '-c', code], env=env, capture_output=True,
        text=True,
        timeout=float(os.environ.get('BENCH_ICI_TIMEOUT', 900)))
    if proc.returncode != 0:
        return {'error': f'forced-device child rc={proc.returncode}: '
                         f'{proc.stderr.strip()[-300:]}'}
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    row['forced_device_child'] = True
    return row


def main():
    enable_compilation_cache()
    artifact = _ArtifactWriter(os.environ.get(
        'BENCH_ARTIFACT',
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     'bench_partial.json')))
    degraded = bool(os.environ.get('BENCH_DEGRADED'))
    if degraded:
        artifact.row('degraded', True)
    preflight = _preflight()
    artifact.row('preflight', preflight)
    n_qubits = int(os.environ.get('BENCH_QUBITS', 8))
    depth = int(os.environ.get('BENCH_DEPTH', 12))
    total_shots = int(os.environ.get('BENCH_SHOTS', 1048576))
    batch = int(os.environ.get('BENCH_BATCH', 262144))
    sigma = float(os.environ.get('BENCH_SIGMA', 0.05))
    chunk = int(os.environ.get('BENCH_CHUNK', 256))
    batch = min(batch, total_shots)
    n_batches = max(total_shots // batch, 1)
    total_shots = batch * n_batches

    cache_state = _cache_state()
    pallas_compiled = pallas_compiled_parity()

    t0 = time.perf_counter()
    mp = build_machine_program(n_qubits, depth)
    t_compile = time.perf_counter() - t0

    n_instr = mp.n_instr
    cfg = InterpreterConfig(
        max_steps=2 * n_instr + 64,
        max_pulses=int(mp.max_pulses_per_core(1)) + 4,
        max_meas=2, max_resets=2,
        # the measured step reduces to statistics inside the jit; not
        # carrying the [B, C, 9*max_pulses] record state through the
        # while_loop saves its read+write every instruction step
        record_pulses=False,
        # run-heavy single-program workload: opt into the emitted
        # straight-line executor where eligible (parity/bloch devices;
        # statevec stays on the generic engine) — compile once, run
        # the specialized module every batch
        straightline=None)
    headline_mode = os.environ.get('BENCH_MODE', 'auto')
    if headline_mode == 'fused' and jax.devices()[0].platform != 'tpu':
        # the fused kernel runs in TPU *interpret* mode off-TPU — hours
        # at bench batch; fall back rather than hang
        print('BENCH_MODE=fused needs a TPU; falling back to persample',
              file=sys.stderr)
        headline_mode = 'persample'
    C = mp.n_cores
    on_tpu = jax.devices()[0].platform == 'tpu'
    # BENCH_DEVICE=bloch runs the headline on the SU(2) device co-state
    # (phase-sensitive rotations, detuning/T1/T2, projective
    # measurement — sim/device.py) instead of the parity counter;
    # measured ~3% slower at bench shapes (the bloch_shots_per_sec
    # secondary reports it either way)
    bench_device = os.environ.get('BENCH_DEVICE', 'parity')

    def _device_model(kind):
        from distributed_processor_tpu.sim.device import DeviceModel
        if kind == 'bloch':
            return DeviceModel('bloch', t1_s=80e-6, t2_s=40e-6,
                               depol_per_pulse=0.002)
        if kind == 'statevec':
            # full trajectory engine on the headline workload (same
            # noise scales as the bloch probe, plus the 2q channel);
            # couplings derived from the headline program + qchip — the
            # 1q RB workload drives no cross-core frequencies, so the
            # honest map here is empty and the event-ordering gate is
            # structurally off; the statevec:cz probe measures the
            # gated entangling workload
            return DeviceModel('statevec', t1_s=80e-6, t2_s=40e-6,
                               depol_per_pulse=0.002,
                               depol2_per_pulse=0.002,
                               couplings=couplings_from_qchip(
                                   mp, make_default_qchip(n_qubits)))
        if kind != 'parity':
            raise SystemExit(
                f'BENCH_DEVICE={kind!r}: unknown device model '
                f"(one of 'parity', 'bloch', 'statevec')")
        return DeviceModel('parity')

    # one compiled step per mode, shared by race + headline + secondaries
    steps: dict = {}

    cz_layers = int(os.environ.get('BENCH_CZ_LAYERS', 4))

    def mode_step(mode, device=bench_device) -> _ModeStep:
        key = (mode, device)
        if key not in steps:
            if device == 'statevec:cz':
                from distributed_processor_tpu.sim.device import DeviceModel
                mp2, qchip2 = build_entangling_program(n_qubits, cz_layers)
                dev2 = DeviceModel(
                    'statevec', t1_s=80e-6, t2_s=40e-6,
                    depol_per_pulse=0.002, depol2_per_pulse=0.002,
                    couplings=couplings_from_qchip(mp2, qchip2))
                assert dev2.couplings, \
                    'entangling probe derived an empty coupling map'
                # the event gate can serialize cross-core triggers:
                # budget steps at n_instr x (cores + slack)
                cfg2 = InterpreterConfig(
                    max_steps=2 * mp2.n_instr * (mp2.n_cores + 2) + 64,
                    max_pulses=int(mp2.max_pulses_per_core(1)) + 4,
                    max_meas=2, max_resets=2, record_pulses=False)
                steps[key] = _ModeStep(mp2, cfg2, batch, sigma, chunk,
                                       mode, dev2)
            else:
                steps[key] = _ModeStep(mp, cfg, batch, sigma, chunk, mode,
                                       _device_model(device))
        return steps[key]

    if headline_mode == 'auto':
        # the XLA and fused-Pallas formulations of the same per-sample
        # chain trade places with device conditions (see docs/PHYSICS.md);
        # race three steady-state batches of each (same compiled steps
        # the measurement reuses) and take the faster.  Guarded: a race
        # failure must not cost the bench its one JSON output line
        headline_mode = 'persample'
        if on_tpu:
            try:
                headline_mode = _race_modes(
                    {m: mode_step(m) for m in ('persample', 'fused')})
            except Exception as e:      # pragma: no cover - defensive
                print(f'mode race failed ({e!r:.120}); using persample',
                      file=sys.stderr)
            print(f'auto headline mode: {headline_mode}', file=sys.stderr)

    step = mode_step(headline_mode)
    model = step.model

    def _headline_timed():
        key = jax.random.PRNGKey(0)
        # warm-up (compiles unless the race already did; jit_s records
        # the mode's actual first-call compile time either way)
        res = step.warm_up(key)
        err_total = int(res[1])
        assert not bool(res[5]), \
            'warm-up batch did not complete in max_steps'
        # timed batches are checked too (err/incomplete accumulated
        # below)

        # settle: two untimed host-synced batches between warm-up and
        # the measurement.  With a COLD persistent cache, deferred
        # one-off work (executable serialization of the just-compiled
        # modules) has been measured charging ~7 s to the first timed
        # batches (sustained 417k -> 108k shots/s on an otherwise
        # identical run); jit_s and compilation_cache already report the
        # cold state honestly, the timed loop should measure steady
        # state.
        for r in (101, 102):
            sres = jax.block_until_ready(step(jax.random.fold_in(key, r)))
            err_total += int(sres[1])
            assert not bool(sres[5]), 'settle batch did not complete'

        t0 = time.perf_counter()
        incomplete = 0
        prev = None
        for i in range(n_batches):
            key, sub = jax.random.split(key)
            # 1-deep pipelining: dispatch batch i+1 before extracting
            # batch i's scalars, so the tunneled host round-trip (~0.5 s
            # on axon) overlaps device compute — measured 2.8x sustained
            # throughput vs blocking per batch.  (Round 1 measured the
            # opposite with the full pulse-record state carried per
            # batch; the slim stats-only carry makes two in-flight
            # batches cheap.)  Deeper queues add nothing: the device is
            # already saturated.
            cur = step(sub)
            if prev is not None:
                err_total += int(prev[1])
                incomplete += int(prev[5])
            prev = cur
        res = jax.block_until_ready(prev)
        err_total += int(res[1])
        incomplete += int(res[5])
        elapsed = time.perf_counter() - t0
        assert not incomplete, \
            f'{incomplete} batches did not complete within max_steps'
        return key, res, err_total, elapsed

    # the r04/r05 caveat: preflight passed but the backend wedged inside
    # the timed headline loop.  The same per-row watchdog that guards the
    # secondaries covers the headline; on expiry the degraded CPU
    # self-rerun fires for THIS row too (not just preflight failure), so
    # an artifact never loses its headline entirely.
    try:
        key, res, err_total, elapsed = _timed_row(_headline_timed)
    except _RowTimeout as e:
        print(f'headline row timed out: {e}', file=sys.stderr)
        if not os.environ.get('BENCH_DEGRADED'):
            _degraded_rerun([{'attempt': 1, 'ok': False,
                              'stage': 'headline', 'error': str(e)}])
        print(json.dumps({
            'metric': 'shots/sec/chip, 8q active-reset+RB, '
                      'physics-closed (synth+demod+discriminate '
                      'in-loop)',
            'value': 0, 'unit': 'shots/s', 'vs_baseline': 0,
            'detail': {'error': f'headline timeout: {e}'},
        }), flush=True)
        os._exit(2)
    t_jit = step.jit_s
    artifact.row('headline', {
        'shots_per_sec': round(total_shots / elapsed, 1),
        'run_s': round(elapsed, 3), 'total_shots': total_shots,
        'batch': batch, 'mode': headline_mode, 'device': bench_device})

    # Cross-mode/device comparisons, VARIANCE-CONTROLLED (round-3 weak
    # #1): the tunneled device times +-30% run-to-run, so sequential
    # per-mode blocks confound mode differences with device drift.
    # Instead every probe (headline mode included, as the common
    # reference) is timed round-robin — one batch per probe per round,
    # R rounds — and reported as median +- IQR; cross-mode ratios are
    # ratios of contemporaneous medians with propagated relative
    # spread.  A ratio is distinguishable from drift only when its
    # deviation from 1 exceeds the quoted spread.
    other_device = 'parity' if bench_device == 'bloch' else 'bloch'
    probe_specs = [('headline:' + headline_mode, headline_mode,
                    bench_device)]
    probe_specs += [(m, m, bench_device)
                    for m in ('persample', 'fused', 'analytic')
                    if m != headline_mode
                    and not (m == 'fused' and not on_tpu)]
    probe_specs.append((f'device:{other_device}', headline_mode,
                        other_device))
    # the statevec trajectory engine at the bench workload (round-4
    # review missing #1): the same headline program on the [B, 2^C]
    # entangling co-state, plus the brickwork-CZ workload with the
    # coupling map + event-ordering gate live.  TPU-only: the bench
    # batch through the trajectory step is hours on CPU.
    from distributed_processor_tpu.sim.device import STATEVEC_MAX_CORES
    if on_tpu and n_qubits <= STATEVEC_MAX_CORES:
        if bench_device != 'statevec':
            probe_specs.append(('device:statevec', headline_mode,
                                'statevec'))
        probe_specs.append(('statevec:cz', headline_mode, 'statevec:cz'))
    # BENCH_SECONDARIES=0: headline only — every comparison row (probes,
    # utilization, scaling, multi-RB, sweep-span, engine ladder) is
    # skipped.  For smoke runs and the degraded-fallback test, where the
    # evidence wanted is "a parseable artifact with a headline", fast.
    secondaries = os.environ.get('BENCH_SECONDARIES', '1') != '0'
    if not secondaries:
        probe_specs = probe_specs[:1]
    probe_rounds = int(os.environ.get('BENCH_PROBE_ROUNDS', 5)) \
        if secondaries else 0
    probe_times: dict = {}
    probe_keys: dict = {}
    probes = []
    for name, mode, device in probe_specs:
        # guarded: a probe failure must not discard the headline
        # measurement already taken
        try:
            pstep = mode_step(mode, device)
            pkey = jax.random.PRNGKey(
                zlib.crc32(name.encode()) & 0x7fffffff)
            # force a host round-trip on the warm-up: block_until_ready
            # alone has been observed to return before the device
            # settles on the tunneled backend
            int(pstep.warm_up(pkey)[1])
            probes.append((name, pstep))
            probe_keys[name] = pkey
            probe_times[name] = []
        except Exception as e:      # pragma: no cover - defensive
            probe_times[name] = f'{type(e).__name__}: {e}'[:120]
    for _ in range(probe_rounds):
        for name, pstep in probes:
            try:
                # thread the key so every round times fresh batch data
                # (data-dependent iteration-count variance is part of
                # the spread being quoted)
                probe_keys[name], sub = jax.random.split(probe_keys[name])
                t0 = time.perf_counter()
                pres = jax.block_until_ready(pstep(sub))
                # host sync inside the window; err bits checked so a
                # probe number never quietly includes errored shots
                ok = not int(pres[5]) and not int(pres[1])
                dt = time.perf_counter() - t0
                assert ok, f'{name} batch incomplete or errored'
                probe_times[name].append(dt)
            except Exception as e:  # pragma: no cover - defensive
                # keep the rounds already collected: earlier samples are
                # valid measurements and still contribute a median
                probe_times[name] = {
                    'error': f'{type(e).__name__}: {e}'[:120],
                    'times': probe_times[name]}
                probes = [p for p in probes if p[0] != name]

    def _median_iqr(ts):
        ts = np.asarray(ts)
        med = float(np.median(ts))
        q1, q3 = float(np.percentile(ts, 25)), float(np.percentile(ts, 75))
        return med, q3 - q1

    probe_sps: dict = {}
    for name, ts in probe_times.items():
        err = None
        if isinstance(ts, dict):            # mid-run failure w/ partials
            err, ts = ts['error'], ts['times']
        if isinstance(ts, str) or not ts:
            probe_sps[name] = err or ts or 'no samples'
            continue
        med, iqr = _median_iqr(ts)
        probe_sps[name] = {
            'sps_median': round(batch / med, 1),
            'sps_iqr_frac': round(iqr / med, 4),
            'rounds': len(ts)}
        if err:
            probe_sps[name]['error'] = err

    def _ratio(a, b):
        """median ratio with summed relative IQR spread.  Probes that
        failed mid-run (partial rounds) are excluded: a ratio of
        non-contemporaneous medians — or one whose single-sample IQR is
        trivially 0 — defeats the interleaved variance control."""
        pa, pb = probe_sps.get(a), probe_sps.get(b)
        if not (isinstance(pa, dict) and isinstance(pb, dict)):
            return None
        if 'error' in pa or 'error' in pb or pa['rounds'] != pb['rounds']:
            return None
        return {'ratio': round(pa['sps_median'] / pb['sps_median'], 4),
                'spread': round(pa['sps_iqr_frac'] + pb['sps_iqr_frac'],
                                4)}

    ref = 'headline:' + headline_mode
    probe_ratios = {f'{n}/{headline_mode}': _ratio(n, ref)
                    for n, _m, _d in probe_specs[1:]}
    artifact.row('probes_interleaved', probe_sps)

    # legacy secondary keys, fed from the interleaved medians; a probe
    # that errored mid-run surfaces its error here (its partial median
    # stays visible in probes_interleaved)
    def _sps_of(name):
        p = probe_sps.get(name)
        if isinstance(p, dict):
            return p['error'] if 'error' in p else p['sps_median']
        return p
    secondary_sps = {m: _sps_of(m)
                     for m in ('persample', 'fused', 'analytic')}
    other_device_sps = _sps_of(f'device:{other_device}')

    # guarded: a failure here must not discard the minutes of headline
    # measurement already taken
    try:
        utilization = _timed_row(lambda: utilization_accounting(
            mp, cfg, model, batch, elapsed / n_batches, int(res[4]))) \
            if secondaries else None
    except _RowTimeout as e:
        utilization = {'error': 'timeout', 'detail': str(e)}
    except Exception as e:      # pragma: no cover - defensive
        utilization = {'error': f'{type(e).__name__}: {e}'[:200]}
    # statevec roofline rows, from the interleaved probe medians
    sv_utils = {}
    for nm, dv in (('device:statevec', 'statevec'),
                   ('statevec:cz', 'statevec:cz')):
        p = probe_sps.get(nm)
        if not (isinstance(p, dict) and 'error' not in p):
            continue
        try:
            sv_utils[nm] = statevec_utilization(
                steps[(headline_mode, dv)], batch,
                batch / p['sps_median'])
        except Exception as e:  # pragma: no cover - defensive
            sv_utils[nm] = {'error': f'{type(e).__name__}: {e}'[:200]}
    artifact.row('utilization', utilization)
    try:
        scaling = _timed_row(lambda: large_program_scaling(
            n_qubits, small_depth=depth)) if secondaries else None
    except _RowTimeout as e:
        scaling = {'error': 'timeout', 'detail': str(e)}
    except Exception as e:      # pragma: no cover - defensive
        scaling = {'error': f'{type(e).__name__}: {e}'[:200]}
    artifact.row('scaling', scaling)
    # multi-sequence RB: the compile-amortization row (program-as-data
    # ensemble in one shape-bucketed jit vs per-sequence content-keyed
    # compiles) — guarded like every secondary
    try:
        multi_rb = _timed_row(lambda: multi_sequence_rb(
            n_qubits, depth,
            n_seqs=int(os.environ.get('BENCH_MULTI_SEQS', 16)),
            shots=int(os.environ.get('BENCH_MULTI_SHOTS', 4096)))) \
            if secondaries else None
    except _RowTimeout as e:
        multi_rb = {'error': 'timeout', 'detail': str(e)}
    except Exception as e:      # pragma: no cover - defensive
        multi_rb = {'error': f'{type(e).__name__}: {e}'[:200]}
    artifact.row('multi_sequence_rb', multi_rb)
    # dispatch-amortization row: host loop vs device-resident span on a
    # dispatch-bound sweep shape — guarded like every secondary
    try:
        sweep_span = _timed_row(lambda: sweep_span_amortization(
            n_qubits,
            shots=int(os.environ.get('BENCH_SWEEP_SHOTS', 131072)),
            batch=int(os.environ.get('BENCH_SWEEP_BATCH', 2048)),
            span=int(os.environ.get('BENCH_SWEEP_SPAN', 16)),
            sigma=sigma)) if secondaries else None
    except _RowTimeout as e:
        sweep_span = {'error': 'timeout', 'detail': str(e)}
    except Exception as e:      # pragma: no cover - defensive
        sweep_span = {'error': f'{type(e).__name__}: {e}'[:200]}
    artifact.row('sweep_span', sweep_span)
    # engine-ladder row: generic vs block iteration counts + warm batch
    # times on deep active-reset RB — guarded like every secondary.
    # BENCH_LADDER_DEPTH=0 skips it (the block compile is minutes on
    # CPU at depth 100; the degraded rerun defaults it down to 12)
    ladder_depth = int(os.environ.get('BENCH_LADDER_DEPTH', 100)) \
        if secondaries else 0
    if ladder_depth:
        try:
            ladder = _timed_row(lambda: engine_ladder(n_qubits,
                                                      ladder_depth))
        except _RowTimeout as e:
            ladder = {'error': 'timeout', 'detail': str(e)}
        except Exception as e:  # pragma: no cover - defensive
            ladder = {'error': f'{type(e).__name__}: {e}'[:200]}
    else:
        ladder = None
    artifact.row('engine_ladder', ladder)
    # feedback-ladder row: generic vs block vs pallas on the deep
    # lut+fproc feedback workload — the rung the timestamped fabric
    # opened (bit-identity gated before timing; BENCH_FEEDBACK_SHOTS=0
    # skips it, the degraded rerun shrinks the shape)
    if secondaries and int(os.environ.get('BENCH_FEEDBACK_SHOTS', 256)):
        try:
            feedback_row = _timed_row(lambda: fproc_feedback_ladder(
                n_data=int(os.environ.get('BENCH_FEEDBACK_QUBITS', 3)),
                rounds=int(os.environ.get('BENCH_FEEDBACK_ROUNDS', 6)),
                k_corr=int(os.environ.get('BENCH_FEEDBACK_CORR', 12)),
                batch=int(os.environ.get('BENCH_FEEDBACK_SHOTS', 256))))
        except _RowTimeout as e:
            feedback_row = {'error': 'timeout', 'detail': str(e)}
        except Exception as e:  # pragma: no cover - defensive
            feedback_row = {'error': f'{type(e).__name__}: {e}'[:200]}
    else:
        feedback_row = None
    artifact.row('fproc_feedback_ladder', feedback_row)
    # exec-profile row: the per-engine (a, b) overhead decomposition
    # (tools/exec_profile.py decompose_engines) — the measured claim
    # that the pallas megastep deletes fixed per-step cost a.  Knobs
    # PROFILE_BATCHES / PROFILE_REPS / PROFILE_ENGINES match the
    # standalone tool; the degraded rerun shrinks them so the fit runs
    # under the kernel interpreter in seconds.  BENCH_EXEC_PROFILE=0
    # skips it.
    if secondaries and os.environ.get('BENCH_EXEC_PROFILE', '1') != '0':
        try:
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)), 'tools'))
            from exec_profile import (DEFAULT_BATCHES, DEFAULT_ENGINES,
                                      decompose_engines)
            profile_row = _timed_row(lambda: decompose_engines(
                n_qubits, depth,
                batches=[int(x) for x in os.environ.get(
                    'PROFILE_BATCHES',
                    ','.join(map(str, DEFAULT_BATCHES))).split(',')],
                reps=int(os.environ.get('PROFILE_REPS', 3)),
                engines=tuple(os.environ.get(
                    'PROFILE_ENGINES',
                    ','.join(DEFAULT_ENGINES)).split(','))))
        except _RowTimeout as e:
            profile_row = {'error': 'timeout', 'detail': str(e)}
        except Exception as e:  # pragma: no cover - defensive
            profile_row = {'error': f'{type(e).__name__}: {e}'[:200]}
    else:
        profile_row = None
    artifact.row('exec_profile', profile_row)
    # fused-epoch row: measure-in-megastep vs the epoch while_loop on a
    # physics-closed branch-on-measurement workload, bit-identity gated
    # before timing.  BENCH_FUSED_SHOTS=0 skips it.
    fused_shots = int(os.environ.get('BENCH_FUSED_SHOTS', 4096)) \
        if secondaries else 0
    if fused_shots:
        try:
            fused_row = _timed_row(lambda: fused_epoch_comparison(
                int(os.environ.get('BENCH_FUSED_QUBITS', 4)), fused_shots,
                reps=int(os.environ.get('BENCH_FUSED_REPS', 3))))
        except _RowTimeout as e:
            fused_row = {'error': 'timeout', 'detail': str(e)}
        except Exception as e:  # pragma: no cover - defensive
            fused_row = {'error': f'{type(e).__name__}: {e}'[:200]}
    else:
        fused_row = None
    artifact.row('fused_epoch', fused_row)
    # continuous-batching row: N concurrent single-program service
    # submissions (coalesced into shape-bucketed multi dispatches) vs N
    # sequential per-program simulate_batch calls, both warm, results
    # asserted bit-identical — guarded like every secondary
    try:
        serve_row = _timed_row(lambda: continuous_batching_comparison(
            n_reqs=int(os.environ.get('BENCH_SERVE_REQS', 32)),
            shots=int(os.environ.get('BENCH_SERVE_SHOTS', 32)))) \
            if secondaries else None
    except _RowTimeout as e:
        serve_row = {'error': 'timeout', 'detail': str(e)}
    except Exception as e:      # pragma: no cover - defensive
        serve_row = {'error': f'{type(e).__name__}: {e}'[:200]}

    # dp scaling sub-row: the same serve workload across 1, 2, ...
    # per-device executors (bucket-affinity routing + work stealing);
    # shells out to a forced-device-count CPU child when this process
    # sees fewer devices than the largest dp
    try:
        serve_scaling = _timed_row(_serve_scaling_row) \
            if secondaries else None
    except _RowTimeout as e:
        serve_scaling = {'error': 'timeout', 'detail': str(e)}
    except Exception as e:      # pragma: no cover - defensive
        serve_scaling = {'error': f'{type(e).__name__}: {e}'[:200]}
    if isinstance(serve_row, dict):
        serve_row['scaling_dp'] = serve_scaling
    artifact.row('continuous_batching', serve_row)

    # open-loop serve latency row: p50/p99 under Poisson-ish
    # mixed-bucket arrivals — queueing measured honestly (arrivals
    # do not wait for completions), all shapes warmed first
    try:
        serve_open = _timed_row(_serve_open_loop_row) \
            if secondaries else None
    except _RowTimeout as e:
        serve_open = {'error': 'timeout', 'detail': str(e)}
    except Exception as e:      # pragma: no cover - defensive
        serve_open = {'error': f'{type(e).__name__}: {e}'[:200]}
    artifact.row('serve_open_loop', serve_open)

    # availability-under-chaos row: the same open-loop stream with
    # seeded executor faults injected under _run_batch — goodput and
    # tail latency with the self-healing machinery doing its job
    try:
        serve_chaos = _timed_row(_serve_chaos_row) \
            if secondaries else None
    except _RowTimeout as e:
        serve_chaos = {'error': 'timeout', 'detail': str(e)}
    except Exception as e:      # pragma: no cover - defensive
        serve_chaos = {'error': f'{type(e).__name__}: {e}'[:200]}
    artifact.row('availability_under_chaos', serve_chaos)

    # fleet-failover row: the same discipline one tier up — replica
    # PROCESSES behind the FleetRouter, a timed SIGKILL of the loaded
    # replica, goodput required positive through the kill window
    try:
        fleet_row = _timed_row(_fleet_failover_row) \
            if secondaries else None
    except _RowTimeout as e:
        fleet_row = {'error': 'timeout', 'detail': str(e)}
    except Exception as e:      # pragma: no cover - defensive
        fleet_row = {'error': f'{type(e).__name__}: {e}'[:200]}
    artifact.row('fleet_failover', fleet_row)

    # compile front-door row: duplicate-program tenant traffic through
    # the content-addressed source->MachineProgram cache (dedup,
    # singleflight, submit_source bit-identity asserted inside)
    try:
        front_door = _timed_row(_compile_front_door_row) \
            if secondaries else None
    except _RowTimeout as e:
        front_door = {'error': 'timeout', 'detail': str(e)}
    except Exception as e:      # pragma: no cover - defensive
        front_door = {'error': f'{type(e).__name__}: {e}'[:200]}
    artifact.row('compile_front_door', front_door)

    # tenant-isolation row: greedy backlog vs victim trickle, fair-off
    # vs fair-on — isolation contract (zero victim sheds, exact
    # billing, bounded p99) asserted inside before any number reports
    try:
        tenant_row = _timed_row(_tenant_isolation_row) \
            if secondaries else None
    except _RowTimeout as e:
        tenant_row = {'error': 'timeout', 'detail': str(e)}
    except Exception as e:      # pragma: no cover - defensive
        tenant_row = {'error': f'{type(e).__name__}: {e}'[:200]}
    artifact.row('tenant_isolation', tenant_row)

    # observability-overhead row: the continuous-batching workload at
    # trace_sample off / sampled / full — what the flight-deck costs
    # when it is off (nothing) and when it is on (BENCH_OBS_* knobs)
    if secondaries and os.environ.get('BENCH_OBS', '1') != '0':
        try:
            obs_row = _timed_row(_observability_overhead_row)
        except _RowTimeout as e:
            obs_row = {'error': 'timeout', 'detail': str(e)}
        except Exception as e:  # pragma: no cover - defensive
            obs_row = {'error': f'{type(e).__name__}: {e}'[:200]}
    else:
        obs_row = None
    artifact.row('observability_overhead', obs_row)

    # fleet observability-overhead row: the same off/sampled/full
    # sweep one tier up — trace ids on the wire, replica span capture,
    # piggybacked spans, router-side stitching + clock alignment
    if secondaries and os.environ.get('BENCH_OBS', '1') != '0':
        try:
            fleet_obs_row = _timed_row(
                _fleet_observability_overhead_row)
        except _RowTimeout as e:
            fleet_obs_row = {'error': 'timeout', 'detail': str(e)}
        except Exception as e:  # pragma: no cover - defensive
            fleet_obs_row = {'error': f'{type(e).__name__}: {e}'[:200]}
    else:
        fleet_obs_row = None
    artifact.row('fleet_observability_overhead', fleet_obs_row)

    # integrity-overhead row: the same workload with the silent-data-
    # corruption auditor off / sampled / strict — what "zero-cost when
    # off, proportional when on" costs in practice (BENCH_INTEGRITY_*)
    if secondaries and os.environ.get('BENCH_INTEGRITY', '1') != '0':
        try:
            integrity_row = _timed_row(_integrity_overhead_row)
        except _RowTimeout as e:
            integrity_row = {'error': 'timeout', 'detail': str(e)}
        except Exception as e:  # pragma: no cover - defensive
            integrity_row = {'error': f'{type(e).__name__}: {e}'[:200]}
    else:
        integrity_row = None
    artifact.row('integrity_overhead', integrity_row)

    # cross-chip ICI fabric row: one program's core axis sharded over
    # the ('dp', 'cores') mesh, sync/fproc riding all_gather
    # collectives — bit-identity asserted before any timing, plus the
    # raw collective microbench behind the docs/PERF.md "ICI fabric"
    # roofline (BENCH_ICI_* knobs; BENCH_ICI_SHOTS=0 skips it)
    if secondaries and int(os.environ.get('BENCH_ICI_SHOTS', 256)):
        try:
            ici_row = _timed_row(_ici_fabric_row)
        except _RowTimeout as e:
            ici_row = {'error': 'timeout', 'detail': str(e)}
        except Exception as e:  # pragma: no cover - defensive
            ici_row = {'error': f'{type(e).__name__}: {e}'[:200]}
    else:
        ici_row = None
    artifact.row('ici_fabric', ici_row)

    # streaming-QEC row: one device-resident R-round scan + in-loop
    # decode vs R sequential dispatches (bit-identity gated before
    # timing, amortization floor asserted at R>=32), plus the
    # StreamSession serving numbers (BENCH_QEC_* knobs;
    # BENCH_QEC_SHOTS=0 skips it)
    if secondaries and int(os.environ.get('BENCH_QEC_SHOTS', 256)):
        try:
            qec_row = _timed_row(lambda: qec_streaming(
                n_data=int(os.environ.get('BENCH_QEC_DATA', 3)),
                rounds=int(os.environ.get('BENCH_QEC_ROUNDS', 32)),
                batch=int(os.environ.get('BENCH_QEC_SHOTS', 256)),
                engine=os.environ.get('BENCH_QEC_ENGINE', 'auto'),
                chunks=int(os.environ.get('BENCH_QEC_CHUNKS', 12)),
                chunk_rounds=int(
                    os.environ.get('BENCH_QEC_CHUNK_ROUNDS', 8))))
        except _RowTimeout as e:
            qec_row = {'error': 'timeout', 'detail': str(e)}
        except Exception as e:  # pragma: no cover - defensive
            qec_row = {'error': f'{type(e).__name__}: {e}'[:200]}
    else:
        qec_row = None
    artifact.row('qec_streaming', qec_row)

    # calibration-loop row: closed-loop gradient descent through the
    # serve tier — convergence to the drifted device truth, live-qchip
    # writeback and the exact stale-epoch flush ASSERTED before any
    # timing reports; plus the cold/warm rerun pair pinning the
    # compile cache's warm hit fraction at 1.0 (BENCH_CALIB_* knobs;
    # BENCH_CALIB_SHOTS=0 skips it)
    if secondaries and int(os.environ.get('BENCH_CALIB_SHOTS', 8)):
        try:
            calib_row = _timed_row(lambda: calibration_loop(
                knob=os.environ.get('BENCH_CALIB_KNOB', 'amplitude'),
                n_qubits=int(os.environ.get('BENCH_CALIB_QUBITS', 2)),
                shots=int(os.environ.get('BENCH_CALIB_SHOTS', 8)),
                true_x90=float(
                    os.environ.get('BENCH_CALIB_TRUE_X90', 0.52))))
        except _RowTimeout as e:
            calib_row = {'error': 'timeout', 'detail': str(e)}
        except Exception as e:  # pragma: no cover - defensive
            calib_row = {'error': f'{type(e).__name__}: {e}'[:200]}
    else:
        calib_row = None
    artifact.row('calibration_loop', calib_row)

    shots_per_sec = total_shots / elapsed
    bit1_frac = float(np.sum(np.asarray(res[2]))) / (batch * C)
    result = {
        'metric': 'shots/sec/chip, 8q active-reset+RB, physics-closed '
                  '(synth+demod+discriminate in-loop)',
        'value': round(shots_per_sec, 1),
        'unit': 'shots/s',
        # degraded = the accelerator preflight failed and this is the
        # CPU fallback run: the evidence survives, but the number must
        # never be read as a chip number
        'degraded': degraded,
        'vs_baseline': round(shots_per_sec / NORTH_STAR_SHOTS_PER_SEC, 3),
        'detail': {
            'n_qubits': n_qubits, 'rb_depth': depth,
            'total_shots': total_shots, 'batch': batch,
            'n_instr': n_instr, 'interp_steps': int(res[3]),
            'epochs': int(res[4]), 'sigma': sigma,
            'meas1_frac': round(bit1_frac, 4),
            'resolve_mode': model.resolve_mode,
            'device_model': bench_device,
            f'{other_device}_device_shots_per_sec':
                _fmt_sps(other_device_sps),
            'compile_s': round(t_compile, 3), 'jit_s': round(t_jit, 3),
            'tables_s': round(step.tables_s, 3),
            'mode_jit_s': {(m if d == 'parity' else f'{m}/{d}'):
                           (round(s.jit_s, 3) if s.jit_s else None)
                           for (m, d), s in steps.items()},
            'compilation_cache': cache_state,
            'run_s': round(elapsed, 3), 'err_shots': err_total,
            'persample_xla_shots_per_sec':
                _fmt_sps(secondary_sps['persample']),
            'fused_pallas_shots_per_sec': _fmt_sps(secondary_sps['fused']),
            'analytic_shots_per_sec': _fmt_sps(secondary_sps['analytic']),
            # variance-controlled cross-mode probes: round-robin
            # interleaved, median +- IQR per probe, ratios vs the
            # headline mode with propagated spread (a ratio is real
            # only when |ratio - 1| > spread)
            'probes_interleaved': probe_sps,
            'probe_ratios_vs_headline': probe_ratios,
            'statevec_cz_layers': cz_layers,
            'statevec_utilization': sv_utils or None,
            'scaling': scaling,
            'multi_sequence_rb': multi_rb,
            'sweep_span': sweep_span,
            'engine_ladder': ladder,
            'exec_profile': profile_row,
            'continuous_batching': serve_row,
            'serve_open_loop': serve_open,
            'availability_under_chaos': serve_chaos,
            'fleet_failover': fleet_row,
            'compile_front_door': front_door,
            'tenant_isolation': tenant_row,
            'observability_overhead': obs_row,
            'fleet_observability_overhead': fleet_obs_row,
            'integrity_overhead': integrity_row,
            'ici_fabric': ici_row,
            'qec_streaming': qec_row,
            'calibration_loop': calib_row,
            'preflight': preflight,
            'utilization': utilization,
            'pallas_compiled': pallas_compiled,
            'platform': jax.devices()[0].platform,
            'device': str(jax.devices()[0]),
        },
    }
    artifact.row('result', result)
    print(json.dumps(result))


if __name__ == '__main__':
    main()
