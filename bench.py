#!/usr/bin/env python
"""Headline benchmark: 8-qubit active-reset + randomized-benchmarking
sweep on one chip.

Pipeline measured per batch (steady state, post-jit):

  measurement-bit sampling -> batched ISA interpretation (per-shot
  divergent control flow through the active-reset branch) -> IQ readout
  model -> discrimination

Prints ONE JSON line: shots/sec/chip, with vs_baseline relative to the
north-star target of 1e6 shots in 60 s (BASELINE.md) — there is no
reference number to compare against (the reference publishes none; it
executes shots on FPGA hardware one at a time, host-sequenced).

Env knobs: BENCH_SHOTS (total, default 1048576), BENCH_BATCH (per-device
batch, default 262144), BENCH_DEPTH (RB depth, default 12).  Batch size
matters: big batches amortise the per-step while_loop dispatch; 262144
is the largest whose loop-carried record state fits HBM comfortably.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp

from distributed_processor_tpu.pipeline import compile_to_machine
from distributed_processor_tpu.models import (
    active_reset, rb_program, make_default_qchip, sample_meas_bits,
    IQReadoutModel)
from distributed_processor_tpu.sim.interpreter import (
    InterpreterConfig, _program_constants, _run_batch)
from distributed_processor_tpu.ops.demod import discriminate

NORTH_STAR_SHOTS_PER_SEC = 1e6 / 60.0


def build_machine_program(n_qubits: int, depth: int):
    qubits = [f'Q{i}' for i in range(n_qubits)]
    qchip = make_default_qchip(n_qubits)
    program = active_reset(qubits) + rb_program(qubits, depth, seed=1234)
    return compile_to_machine(program, qchip, n_qubits=n_qubits)


def main():
    n_qubits = int(os.environ.get('BENCH_QUBITS', 8))
    depth = int(os.environ.get('BENCH_DEPTH', 12))
    total_shots = int(os.environ.get('BENCH_SHOTS', 1048576))
    batch = int(os.environ.get('BENCH_BATCH', 262144))
    batch = min(batch, total_shots)
    n_batches = max(total_shots // batch, 1)
    total_shots = batch * n_batches

    t0 = time.perf_counter()
    mp = build_machine_program(n_qubits, depth)
    t_compile = time.perf_counter() - t0

    n_instr = mp.n_instr
    cfg = InterpreterConfig(
        max_steps=n_instr + 16,
        max_pulses=int(mp.max_pulses_per_core(1)) + 4,
        max_meas=4, max_resets=2)
    soa, spc, interp, sync_part = _program_constants(mp, cfg)
    C = mp.n_cores

    readout = IQReadoutModel(
        centers0=np.full(C, 1.0 + 0.0j), centers1=np.full(C, -0.6 + 0.8j),
        sigma=0.3)

    @jax.jit
    def step(key):
        kb, ki = jax.random.split(key)
        bits = sample_meas_bits(kb, jnp.full((C,), 0.15), batch, cfg.max_meas)
        out = _run_batch(soa, spc, interp, sync_part, bits, cfg, C)
        # readout physics on the final measurement of each core
        states = bits[:, :, 1]
        iq = readout.sample_iq(ki, states)
        final_bits = discriminate(iq, readout.c0, readout.c1)
        return (jnp.sum(out['n_pulses'], axis=0),
                jnp.sum(out['err']), jnp.sum(final_bits, axis=0),
                out['steps'])

    key = jax.random.PRNGKey(0)
    # warm-up / compile
    t0 = time.perf_counter()
    res = jax.block_until_ready(step(key))
    t_jit = time.perf_counter() - t0
    err_total = int(res[1])

    t0 = time.perf_counter()
    for i in range(n_batches):
        key, sub = jax.random.split(key)
        # block per batch: queueing several in-flight steps multiplies
        # peak HBM (each holds ~100s of MB of loop-carried state) and
        # stalls the allocator, measured ~3x slower than synchronous
        res = jax.block_until_ready(step(sub))
    elapsed = time.perf_counter() - t0
    err_total += int(res[1])

    shots_per_sec = total_shots / elapsed
    result = {
        'metric': 'shots/sec/chip, 8q active-reset+RB sweep (sim+readout)',
        'value': round(shots_per_sec, 1),
        'unit': 'shots/s',
        'vs_baseline': round(shots_per_sec / NORTH_STAR_SHOTS_PER_SEC, 3),
        'detail': {
            'n_qubits': n_qubits, 'rb_depth': depth,
            'total_shots': total_shots, 'batch': batch,
            'n_instr': n_instr, 'interp_steps': int(res[3]),
            'compile_s': round(t_compile, 3), 'jit_s': round(t_jit, 3),
            'run_s': round(elapsed, 3), 'err_shots': err_total,
            'platform': jax.devices()[0].platform,
            'device': str(jax.devices()[0]),
        },
    }
    print(json.dumps(result))


if __name__ == '__main__':
    main()
