"""QASM gate/qubit name mapping (reference: python/distproc/openqasm/
gate_map.py, qubit_map.py).

``GateMap`` translates a QASM gate call into native instruction dicts;
the default decomposes onto the X90 + virtual-Z native set the gate
library calibrates (reference DefaultGateMap: h -> vz + Y90, x -> two
X90, z -> vz(pi), gate_map.py:22-46).
"""

from __future__ import annotations

from abc import ABC, abstractmethod

import numpy as np


class QubitMap(ABC):
    @abstractmethod
    def get_hardware_qubit(self, register: str, index: int) -> str: ...


class DefaultQubitMap(QubitMap):
    """``q[i] -> Qi`` (reference: qubit_map.py:9)."""

    def get_hardware_qubit(self, register: str, index: int) -> str:
        return f'Q{index if index is not None else 0}'


class GateMap(ABC):
    @abstractmethod
    def get_qubic_gateinstr(self, name: str, qubits: list[str],
                            params: list) -> list[dict]: ...


def _vz(qubit, phase):
    return {'name': 'virtual_z', 'qubit': [qubit], 'phase': float(phase)}


def _x90(qubit):
    return {'name': 'X90', 'qubit': [qubit]}


class DefaultGateMap(GateMap):
    """Decomposition onto {X90, virtual-Z, CNOT, read}.

    Single-qubit maps use the standard Euler identities (all equal to
    the named gate up to global phase):

    * ``h  = Z(pi/2) X90 Z(pi/2)``
    * ``x  = X90 X90``,  ``sx = X90``
    * ``y  = Z(pi) X90 X90``  (X90 pair in the rotated frame)
    * ``z/s/sdg/t/tdg/rz/p`` -> pure virtual-Z
    * ``ry(t) = Z(-pi/2) rx(t) Z(pi/2)``; generic ``rx`` only for
      t = ±pi/2, pi (native-set multiples)
    """

    def get_qubic_gateinstr(self, name: str, qubits: list[str],
                            params: list) -> list[dict]:
        q = qubits[0]
        name = name.lower()
        if name == 'h':
            return [_vz(q, np.pi / 2), _x90(q), _vz(q, np.pi / 2)]
        if name == 'x':
            return [_x90(q), _x90(q)]
        if name == 'sx':
            return [_x90(q)]
        if name == 'y':
            return [_vz(q, np.pi), _x90(q), _x90(q)]
        if name == 'z':
            return [_vz(q, np.pi)]
        if name == 's':
            return [_vz(q, np.pi / 2)]
        if name == 'sdg':
            return [_vz(q, -np.pi / 2)]
        if name == 't':
            return [_vz(q, np.pi / 4)]
        if name == 'tdg':
            return [_vz(q, -np.pi / 4)]
        if name in ('rz', 'p', 'phase'):
            return [_vz(q, params[0])]
        if name == 'rx':
            return self._rx(q, params[0])
        if name == 'ry':
            return [_vz(q, -np.pi / 2)] + self._rx(q, params[0]) \
                + [_vz(q, np.pi / 2)]
        if name in ('cx', 'cnot'):
            return [{'name': 'CNOT', 'qubit': list(qubits)}]
        if name == 'cz':
            return [{'name': 'CZ', 'qubit': list(qubits)}]
        # fall through: assume a native gate name in the gate library
        return [{'name': name.upper() if name == 'x90' else name,
                 'qubit': list(qubits)}]

    def _rx(self, q, theta) -> list[dict]:
        theta = float(theta) % (2 * np.pi)
        if np.isclose(theta, np.pi / 2):
            return [_x90(q)]
        if np.isclose(theta, np.pi):
            return [_x90(q), _x90(q)]
        if np.isclose(theta, 0):
            return []
        # general angle (ZXZXZ Euler form, program order):
        # Rx(theta) = Z(pi/2) . X90 . Z(theta + pi) . X90 . Z(pi/2)
        return [_vz(q, np.pi / 2), _x90(q), _vz(q, theta + np.pi),
                _x90(q), _vz(q, np.pi / 2)]
