"""OpenQASM 3 -> native program translation.

Equivalent of the reference's ``QASMQubiCVisitor`` (reference:
python/distproc/openqasm/visitor.py:41-149), driven by the built-in
parser instead of the external ``openqasm3`` package:

* qubit declarations map through a :class:`~.gate_map.QubitMap`;
* gate calls map through a :class:`~.gate_map.GateMap`;
* ``reset`` expands to the read + branch_fproc active-reset idiom
  (reference: visitor.py:86-92);
* ``c[i] = measure q[j]`` emits a read and records which qubit feeds
  each classical bit, so later ``if (c[i] == v)`` branches become
  measurement branches (``branch_fproc``) — the part the reference left
  unfinished (visitor.py:113-119 "BranchingStatement unfinished");
* classical declarations/assignments become declare/set_var/alu chains
  with temporaries for nested expressions (reference: visitor.py:121-147).
"""

from __future__ import annotations

import numpy as np

from . import qasm_parser as qp
from .gate_map import GateMap, DefaultGateMap, QubitMap, DefaultQubitMap

_CMP_FLIP = {'==': '==', '<=': '>=', '>=': '<=', '<': '>', '>': '<'}


def _fold_nonstrict(op: str, const: int) -> int:
    """Fold ``const <= x`` / ``const > x`` onto the hardware's STRICT
    comparisons (alu.v:25-27: le is signed <, ge is >=):
    ``const <= x == const-1 < x``; ``const > x == const-1 >= x``.
    Rejects the INT32_MIN edge where the folded constant leaves the
    32-bit range (the condition is then trivial — drop it instead)."""
    if const == -2**31:
        raise QASMTranslationError(
            f'{op!r} against INT32_MIN folds out of the 32-bit range '
            f'(the condition is trivially '
            f'{"true" if op == "<=" else "false"} — drop it)')
    return const - 1


class QASMTranslationError(ValueError):
    pass


class QASMTranslator:
    """Stateful translator: one instance per QASM program."""

    def __init__(self, gate_map: GateMap = None, qubit_map: QubitMap = None):
        self.gate_map = gate_map or DefaultGateMap()
        self.qubit_map = qubit_map or DefaultQubitMap()
        self.qubit_regs: dict[str, int] = {}     # register name -> size
        self.bit_regs: dict[str, int] = {}
        self.int_vars: set[str] = set()
        self.bit_sources: dict[tuple, str] = {}  # (reg, idx) -> qubit name
        # QASM3 loop variables are loop-scoped: shadowing names map to
        # unique internal vars for the body's duration; sequential
        # sibling loops reuse one minted var (one hardware register)
        self._var_alias: dict[str, str] = {}
        self._loop_minted: dict[tuple, str] = {}
        self._tmp = 0

    # -- public ----------------------------------------------------------

    def translate(self, src: str) -> list[dict]:
        stmts = qp.parse_qasm(src)
        out = []
        for s in stmts:
            out.extend(self._stmt(s))
        return out

    # -- helpers ---------------------------------------------------------

    @property
    def all_qubits(self) -> list[str]:
        return [self.qubit_map.get_hardware_qubit(reg, i)
                for reg, size in self.qubit_regs.items()
                for i in range(size)]

    def _qubit(self, ref: qp.Ref) -> str:
        if ref.name not in self.qubit_regs:
            raise QASMTranslationError(f'{ref.name!r} is not a qubit register')
        return self.qubit_map.get_hardware_qubit(ref.name, ref.index)

    def _qubits_of(self, ref: qp.Ref) -> list[str]:
        """One hardware qubit for an indexed ref; the whole register for
        a bare-register ref (``delay[...] q;`` touches every qubit)."""
        if ref.index is None:
            if ref.name not in self.qubit_regs:
                raise QASMTranslationError(
                    f'{ref.name!r} is not a qubit register')
            return [self.qubit_map.get_hardware_qubit(ref.name, i)
                    for i in range(self.qubit_regs[ref.name])]
        return [self._qubit(ref)]

    def _tmpvar(self) -> str:
        self._tmp += 1
        return f'_qasm_tmp{self._tmp}'

    def _varname(self, name: str) -> str:
        """Resolve a source-level variable through active loop aliases."""
        return self._var_alias.get(name, name)

    def _operands_or_all(self, operands) -> list[str]:
        return [q for r in operands for q in self._qubits_of(r)] \
            or self.all_qubits

    # -- statements ------------------------------------------------------

    def _stmt(self, s) -> list[dict]:
        if isinstance(s, qp.Decl):
            return self._decl(s)
        if isinstance(s, qp.GateCall):
            qubits = [self._qubit(r) for r in s.operands]
            params = [self._const_expr(p) for p in s.params]
            return self.gate_map.get_qubic_gateinstr(s.name, qubits, params)
        if isinstance(s, qp.Reset):
            q = self._qubit(s.target)
            return [{'name': 'read', 'qubit': [q]},
                    {'name': 'branch_fproc', 'alu_cond': 'eq', 'cond_lhs': 1,
                     'func_id': f'{q}.meas', 'scope': [q],
                     'true': [{'name': 'X90', 'qubit': [q]},
                              {'name': 'X90', 'qubit': [q]}],
                     'false': []}]
        if isinstance(s, qp.Measure):
            q = self._qubit(s.target)
            if s.out is not None:
                if s.out.name not in self.bit_regs:
                    raise QASMTranslationError(
                        f'{s.out.name!r} is not a bit register')
                self.bit_sources[(s.out.name, s.out.index)] = q
            return [{'name': 'read', 'qubit': [q]}]
        if isinstance(s, qp.Barrier):
            return [{'name': 'barrier',
                     'qubit': self._operands_or_all(s.operands)}]
        if isinstance(s, qp.Assign):
            return self._assign(s)
        if isinstance(s, qp.If):
            return self._if(s)
        if isinstance(s, qp.For):
            return self._for(s)
        if isinstance(s, qp.While):
            return self._while(s)
        if isinstance(s, qp.Delay):
            return [{'name': 'delay', 't': s.duration,
                     'qubit': self._operands_or_all(s.operands)}]
        raise QASMTranslationError(f'unsupported statement {s}')

    def _decl(self, s: qp.Decl) -> list[dict]:
        if s.kind == 'qubit':
            self.qubit_regs[s.name] = s.size or 1
            return []
        if s.kind == 'bit':
            self.bit_regs[s.name] = s.size or 1
            return []
        # classical int/float variable
        self.int_vars.add(s.name)
        out = [{'name': 'declare', 'var': s.name, 'dtype': 'int',
                'scope': self.all_qubits}]
        if s.init is not None:
            pre, val = self._expr(s.init)
            out.extend(pre)
            out.append({'name': 'set_var', 'var': s.name, 'value': val})
        return out

    def _assign(self, s: qp.Assign) -> list[dict]:
        target = self._varname(s.target.name)
        if target not in self.int_vars:
            raise QASMTranslationError(
                f'{s.target.name!r} is not a declared variable')
        pre, val = self._expr(s.expr)
        if isinstance(val, str) or not pre:
            # simple value or variable: set_var / alu-into-target
            if pre and pre[-1].get('out') is not None:
                pre[-1]['out'] = target
                return pre
            return pre + [{'name': 'set_var', 'var': target,
                           'value': val}]
        pre[-1]['out'] = target
        return pre

    def _if(self, s: qp.If) -> list[dict]:
        if s.op not in _CMP_FLIP:
            raise QASMTranslationError(
                f'only ==/<=/>=/</> conditions supported, got {s.op!r}')
        op = s.op
        true = [i for st in s.true for i in self._stmt(st)]
        false = [i for st in s.false for i in self._stmt(st)]
        lhs, rhs = s.lhs, s.rhs
        # normalise: measured-bit or variable on the right, flipping the
        # comparison direction with the operand swap
        if isinstance(lhs, qp.Ref) and not isinstance(rhs, qp.Ref):
            lhs, rhs, op = rhs, lhs, _CMP_FLIP[op]
        if not isinstance(rhs, qp.Ref):
            raise QASMTranslationError('condition must involve a bit or var')
        # prefer constant folding (negative literals parse as BinOp(0-n))
        # so <=/> can fold into the constant; fall back to a register
        if isinstance(lhs, (qp.Ref, qp.BinOp)):
            try:
                pre, lhs_val = [], self._const_expr(lhs)
            except QASMTranslationError:
                pre, lhs_val = self._expr(lhs)
        else:
            pre, lhs_val = [], lhs
        # hardware triple is "lhs_val <alu_cond> rhs": le is STRICT
        # signed < (alu.v:25-27), so <=/> fold into an integer constant
        if op in ('==', '<', '>='):
            cond = {'==': 'eq', '<': 'le', '>=': 'ge'}[op]
        elif isinstance(lhs_val, (int, float)):
            if lhs_val != int(lhs_val):
                raise QASMTranslationError(
                    f'{op!r} against non-integer constant {lhs_val!r}: '
                    f'hardware comparisons are 32-bit integer')
            lhs_val = _fold_nonstrict(op, int(lhs_val))
            cond = 'le' if op == '<=' else 'ge'
        elif self._varname(rhs.name) in self.int_vars:
            # var-vs-var <=/>: swap operands with the flipped STRICT
            # complement — "a <= y" == "y >= a", "a > y" == "y < a" —
            # branch_var takes variables on both sides
            return pre + [{'name': 'branch_var',
                           'alu_cond': 'ge' if op == '<=' else 'le',
                           'cond_lhs': self._varname(rhs.name),
                           'cond_rhs': lhs_val,
                           'scope': self.all_qubits,
                           'true': true, 'false': false}]
        else:
            raise QASMTranslationError(
                f'{op!r} against a measured bit needs a constant side '
                f'(hardware le/ge are </>=)')
        key = (rhs.name, rhs.index)
        if key in self.bit_sources:          # measurement branch
            q = self.bit_sources[key]
            return pre + [{'name': 'branch_fproc', 'alu_cond': cond,
                           'cond_lhs': lhs_val, 'func_id': f'{q}.meas',
                           'scope': self.all_qubits,
                           'true': true, 'false': false}]
        if self._varname(rhs.name) in self.int_vars:   # variable branch
            return pre + [{'name': 'branch_var', 'alu_cond': cond,
                           'cond_lhs': lhs_val,
                           'cond_rhs': self._varname(rhs.name),
                           'scope': self.all_qubits,
                           'true': true, 'false': false}]
        raise QASMTranslationError(
            f'{rhs.name!r} is neither a measured bit nor a variable')

    def _loop_cond(self, lhs, op: str, rhs) -> tuple[int, str, str]:
        """Normalise a comparison to the hardware loop/branch triple
        ``(cond_lhs const, alu_cond in eq/ge/le, cond_rhs var)``.
        Strict comparisons fold into the integer constant (``x < K`` ==
        ``K-1 >= x``)."""
        if isinstance(lhs, qp.Ref) and self._varname(lhs.name) \
                in self.int_vars:
            if isinstance(rhs, qp.Ref):
                raise QASMTranslationError(
                    'loop conditions need one constant side')
            lhs, rhs, op = rhs, lhs, _CMP_FLIP.get(op, op)
        if not (isinstance(rhs, qp.Ref)
                and self._varname(rhs.name) in self.int_vars):
            raise QASMTranslationError(
                'loop condition must compare a declared variable')
        var = self._varname(rhs.name)
        const = self._const_expr(lhs)
        if const != int(const):
            raise QASMTranslationError('loop bounds must be integers')
        const = int(const)
        # condition is "const <alu_cond> var"; hardware le is STRICT
        # signed < (reference: hdl/alu.v:25-27), ge is >=, so the
        # non-native comparisons fold into the integer constant
        if op == '==':
            return const, 'eq', var
        if op == '<':
            return const, 'le', var
        if op == '>=':
            return const, 'ge', var
        if op in ('<=', '>'):
            return _fold_nonstrict(op, const), \
                ('le' if op == '<=' else 'ge'), var
        raise QASMTranslationError(f'unsupported loop comparison {op!r}')

    def _for(self, s: qp.For) -> list[dict]:
        """``for i in [a:step:b]`` -> hardware counter loop (the
        reference's loop instruction; the back-edge tests after each
        iteration, so a statically-empty range lowers to a no-op).
        The loop variable is loop-scoped per QASM3: shadowing an outer
        name maps it to a unique internal var for the body."""
        start = int(self._const_expr(s.start))
        step = int(self._const_expr(s.step))
        stop = int(self._const_expr(s.stop))
        if step == 0:
            raise QASMTranslationError('range step must be nonzero')
        if stop < start if step > 0 else stop > start:
            return []                        # statically empty: zero trips
        # minted vars are keyed by (enclosing alias context, name):
        # sequential siblings — at any nesting depth — share one
        # register (fresh vars per loop would exhaust the 16-register
        # file; set_var re-seeds it), while genuine shadowing (an
        # enclosing loop or a user variable owns the name) mints a
        # distinct internal var
        ctx = (self._var_alias.get(s.var), s.var)
        if ctx in self._loop_minted:
            var = self._loop_minted[ctx]
        elif ctx[0] is not None or s.var in self.int_vars:
            self._tmp += 1
            var = f'{s.var}__loop{self._tmp}'
            self._loop_minted[ctx] = var
        else:
            var = s.var
            self._loop_minted[ctx] = var
        declare = []
        if var not in self.int_vars:
            self.int_vars.add(var)
            declare = [{'name': 'declare', 'var': var, 'dtype': 'int',
                        'scope': self.all_qubits}]
        outer = self._var_alias.get(s.var)
        self._var_alias[s.var] = var
        try:
            body = [i for st in s.body for i in self._stmt(st)]
        finally:
            if outer is None:
                self._var_alias.pop(s.var, None)
            else:
                self._var_alias[s.var] = outer
        body.append({'name': 'alu', 'op': 'add', 'lhs': step,
                     'rhs': var, 'out': var})
        # QASM ranges are inclusive of `stop`: continue while
        # stop >= var (ascending) / var >= stop == stop-1 < var
        # (descending; hardware le is strict, alu.v:25-27)
        if step < 0 and stop == -2**31:
            raise QASMTranslationError(
                'descending range to INT32_MIN: the inclusive bound '
                'folds out of the 32-bit range')
        return declare + [
            {'name': 'set_var', 'var': var, 'value': start},
            {'name': 'loop',
             'cond_lhs': stop if step > 0 else stop - 1,
             'alu_cond': 'ge' if step > 0 else 'le',
             'cond_rhs': var, 'scope': self.all_qubits, 'body': body},
        ]

    def _while(self, s: qp.While) -> list[dict]:
        """``while (cond)`` -> branch_var guard around a do-while
        hardware loop (the loop's back-edge tests after the body, so the
        guard supplies the test-before-first-iteration semantics)."""
        cond_lhs, alu_cond, var = self._loop_cond(s.lhs, s.op, s.rhs)
        body = [i for st in s.body for i in self._stmt(st)]
        loop = {'name': 'loop', 'cond_lhs': cond_lhs,
                'alu_cond': alu_cond, 'cond_rhs': var,
                'scope': self.all_qubits, 'body': body}
        return [{'name': 'branch_var', 'alu_cond': alu_cond,
                 'cond_lhs': cond_lhs, 'cond_rhs': var,
                 'scope': self.all_qubits, 'true': [loop], 'false': []}]

    # -- expressions -----------------------------------------------------

    def _const_expr(self, e) -> float:
        """Fold a parameter expression to a number (pi supported)."""
        if isinstance(e, (int, float)):
            return e
        if isinstance(e, qp.Ref):
            if e.name in ('pi', 'π'):
                return np.pi
            if e.name in ('tau', 'τ'):
                return 2 * np.pi
            if e.name == 'euler':
                return np.e
            raise QASMTranslationError(
                f'gate parameters must be constant, got {e.name!r}')
        if isinstance(e, qp.BinOp):
            a, b = self._const_expr(e.lhs), self._const_expr(e.rhs)
            return {'+': a + b, '-': a - b, '*': a * b, '/': a / b,
                    '%': a % b}[e.op]
        raise QASMTranslationError(f'bad parameter expression {e}')

    def _expr(self, e) -> tuple[list[dict], object]:
        """Lower an expression to (instructions, value-or-varname) using
        temporaries for nesting (reference: visitor.py:121-147)."""
        if isinstance(e, (int, float)):
            return [], int(e)
        if isinstance(e, qp.Ref):
            name = self._varname(e.name)
            if name in self.int_vars:
                return [], name
            if e.name in ('pi', 'π'):
                return [], np.pi
            raise QASMTranslationError(f'unknown variable {e.name!r}')
        if isinstance(e, qp.BinOp):
            if e.op not in ('+', '-'):
                raise QASMTranslationError(
                    f'only +/- supported on variables, got {e.op!r}')
            pre_l, lhs = self._expr(e.lhs)
            pre_r, rhs = self._expr(e.rhs)
            # the processor ALU computes lhs <op> rhs with rhs a register
            if not isinstance(rhs, str):
                if isinstance(lhs, str) and e.op == '+':
                    lhs, rhs = rhs, lhs          # commute constant left
                else:
                    tmp = self._tmpvar()
                    pre_r += [
                        {'name': 'declare', 'var': tmp, 'dtype': 'int',
                         'scope': self.all_qubits},
                        {'name': 'set_var', 'var': tmp, 'value': rhs}]
                    rhs = tmp
            out = self._tmpvar()
            instrs = pre_l + pre_r + [
                {'name': 'declare', 'var': out, 'dtype': 'int',
                 'scope': self.all_qubits},
                {'name': 'alu', 'op': {'+': 'add', '-': 'sub'}[e.op],
                 'lhs': lhs, 'rhs': rhs, 'out': out}]
            return instrs, out
        raise QASMTranslationError(f'bad expression {e}')


def qasm_to_program(src: str, gate_map: GateMap = None,
                    qubit_map: QubitMap = None) -> list[dict]:
    """Translate OpenQASM 3 source to the native dict program format."""
    return QASMTranslator(gate_map, qubit_map).translate(src)
