from .visitor import qasm_to_program, QASMTranslator
from .gate_map import GateMap, DefaultGateMap, QubitMap, DefaultQubitMap
