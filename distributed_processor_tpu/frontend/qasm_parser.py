"""Minimal OpenQASM 3 parser (self-contained; no external dependency).

The reference frontend leans on the ``openqasm3`` package for parsing
(reference: python/distproc/openqasm/visitor.py:1-40) and only walks the
AST.  That package is not available here, so this module provides a
small tokenizer + recursive-descent parser for the practical subset the
translator consumes:

* ``OPENQASM 3;`` / ``include`` headers (ignored)
* ``qubit[n] q;`` / ``bit[n] c;`` / ``int[32] x = expr;`` declarations
* gate calls with optional parameter lists: ``rz(pi/2) q[0];``
* ``reset q[i];``
* ``c[i] = measure q[j];`` and bare ``measure q[j];``
* classical assignment ``x = a + 2 * b;``
* ``if (cond) { ... } else { ... }`` with comparison conditions
* ``for uint i in [a:b] { ... }`` / ``[a:step:b]`` (inclusive ranges)
* ``while (cond) { ... }``
* ``delay[100ns] q[0];`` (units ns/us/ms/s)
* ``barrier q;``

Output is a tiny AST of plain dataclasses consumed by
:mod:`.visitor`.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field


class QASMSyntaxError(ValueError):
    pass


# ---------------------------------------------------------------------------
# AST nodes
# ---------------------------------------------------------------------------

@dataclass
class Decl:
    kind: str            # 'qubit' | 'bit' | 'int' | 'float'
    name: str
    size: int | None = None
    init: object = None  # expression


@dataclass
class Ref:
    name: str
    index: int | None = None


@dataclass
class GateCall:
    name: str
    params: list = field(default_factory=list)   # expressions
    operands: list = field(default_factory=list)  # Refs


@dataclass
class Reset:
    target: Ref


@dataclass
class Measure:
    target: Ref
    out: Ref | None = None


@dataclass
class Assign:
    target: Ref
    expr: object


@dataclass
class If:
    lhs: object
    op: str              # '==' '!=' '<' '<=' '>' '>='
    rhs: object
    true: list = field(default_factory=list)
    false: list = field(default_factory=list)


@dataclass
class For:
    var: str
    start: object        # expressions (folded to ints by the visitor)
    step: object
    stop: object
    body: list = field(default_factory=list)


@dataclass
class While:
    lhs: object
    op: str
    rhs: object
    body: list = field(default_factory=list)


@dataclass
class Delay:
    duration: float      # seconds
    operands: list = field(default_factory=list)


@dataclass
class Barrier:
    operands: list = field(default_factory=list)


@dataclass
class BinOp:
    op: str
    lhs: object
    rhs: object


# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r'''
    (?P<ws>\s+|//[^\n]*|/\*.*?\*/)
  | (?P<num>\d+\.\d*(e[+-]?\d+)?|\.\d+(e[+-]?\d+)?|\d+(e[+-]?\d+)?)
  | (?P<id>[A-Za-z_$][A-Za-z_0-9]*)
  | (?P<str>"[^"]*")
  | (?P<op>==|!=|<=|>=|->|[-+*/%(){}\[\];,=<>:])
''', re.VERBOSE | re.DOTALL)


def tokenize(src: str) -> list[tuple[str, str]]:
    out, pos = [], 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise QASMSyntaxError(f'bad token at {src[pos:pos+20]!r}')
        pos = m.end()
        if m.lastgroup == 'ws' or (m.lastgroup and m.group('ws')):
            continue
        kind = m.lastgroup
        out.append((kind, m.group()))
    out.append(('eof', ''))
    return out


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------

_KEYWORDS = {'qubit', 'bit', 'int', 'float', 'reset', 'measure', 'if',
             'else', 'barrier', 'include', 'OPENQASM', 'pragma', 'const',
             'for', 'while', 'in', 'delay', 'uint', 'angle'}

_TIME_UNITS = {'ns': 1e-9, 'us': 1e-6, 'ms': 1e-3, 's': 1.0}


class Parser:
    def __init__(self, src: str):
        self.toks = tokenize(src)
        self.i = 0

    def peek(self, k: int = 0):
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self):
        tok = self.toks[self.i]
        self.i += 1
        return tok

    def expect(self, text: str):
        kind, val = self.next()
        if val != text:
            raise QASMSyntaxError(f'expected {text!r}, got {val!r}')
        return val

    # -- grammar ---------------------------------------------------------

    def parse(self) -> list:
        stmts = []
        while self.peek()[0] != 'eof':
            s = self.statement()
            if s is not None:
                stmts.append(s)
        return stmts

    def block(self) -> list:
        if self.peek()[1] == '{':
            self.next()
            out = []
            while self.peek()[1] != '}':
                s = self.statement()
                if s is not None:
                    out.append(s)
            self.next()
            return out
        s = self.statement()
        return [] if s is None else [s]

    def statement(self):
        kind, val = self.peek()
        if val == ';':
            self.next()
            return None
        if val in ('OPENQASM', 'include', 'pragma'):
            while self.next()[1] != ';':
                pass
            return None
        if val in ('qubit', 'bit', 'int', 'float', 'uint', 'angle',
                   'const'):
            return self.decl()
        if val == 'for':
            return self.for_stmt()
        if val == 'while':
            return self.while_stmt()
        if val == 'delay':
            return self.delay_stmt()
        if val == 'reset':
            self.next()
            t = self.ref()
            self.expect(';')
            return Reset(t)
        if val == 'barrier':
            self.next()
            return Barrier(self._ref_list())
        if val == 'if':
            return self.if_stmt()
        if val == 'measure':
            self.next()
            t = self.ref()
            self.expect(';')
            return Measure(t)
        if kind == 'id':
            # assignment (`x = ...`, `c[0] = measure ...`) or gate call
            save = self.i
            target = self.ref()
            if self.peek()[1] == '=':
                self.next()
                if self.peek()[1] == 'measure':
                    self.next()
                    src = self.ref()
                    self.expect(';')
                    return Measure(src, out=target)
                e = self.expr()
                self.expect(';')
                return Assign(target, e)
            self.i = save
            return self.gate_call()
        raise QASMSyntaxError(f'unexpected token {val!r}')

    def decl(self) -> Decl:
        kind = self.next()[1]
        if kind == 'const':
            kind = self.next()[1]
        size = None
        if self.peek()[1] == '[':
            self.next()
            size = int(self.next()[1])
            self.expect(']')
        name = self.next()[1]
        init = None
        if self.peek()[1] == '=':
            self.next()
            init = self.expr()
        self.expect(';')
        return Decl(kind, name, size, init)

    def for_stmt(self) -> For:
        """``for <type> name in [start:(step:)?stop] block`` — QASM3
        ranges are inclusive on both ends."""
        self.expect('for')
        if self.peek()[1] in ('int', 'uint', 'float', 'angle'):
            self.next()
            if self.peek()[1] == '[':        # width designator
                self.next()
                self.next()
                self.expect(']')
        kind, name = self.next()
        if kind != 'id' or name in _KEYWORDS:
            raise QASMSyntaxError(f'bad loop variable {name!r}')
        self.expect('in')
        self.expect('[')
        parts = [self.expr()]
        while self.peek()[1] == ':':
            self.next()
            parts.append(self.expr())
        self.expect(']')
        if len(parts) == 2:
            start, step, stop = parts[0], 1, parts[1]
        elif len(parts) == 3:
            start, step, stop = parts
        else:
            raise QASMSyntaxError('range must be [start:stop] or '
                                  '[start:step:stop]')
        return For(name, start, step, stop, self.block())

    def while_stmt(self) -> While:
        self.expect('while')
        self.expect('(')
        lhs = self.expr()
        op = self.next()[1]
        # '!=' has no eq/ge/le hardware-loop lowering: reject at parse
        if op not in ('==', '<', '<=', '>', '>='):
            raise QASMSyntaxError(
                f'unsupported while comparison {op!r} (use ==/</<=/>/>=)')
        rhs = self.expr()
        self.expect(')')
        return While(lhs, op, rhs, self.block())

    def delay_stmt(self) -> Delay:
        self.expect('delay')
        self.expect('[')
        kind, val = self.next()
        if kind != 'num':
            raise QASMSyntaxError(f'expected duration, got {val!r}')
        ukind, unit = self.next()
        if unit not in _TIME_UNITS:
            raise QASMSyntaxError(
                f'unknown time unit {unit!r} (use ns/us/ms/s)')
        self.expect(']')
        return Delay(float(val) * _TIME_UNITS[unit], self._ref_list())

    def if_stmt(self) -> If:
        self.expect('if')
        self.expect('(')
        lhs = self.expr()
        op = self.next()[1]
        if op not in ('==', '!=', '<', '<=', '>', '>='):
            raise QASMSyntaxError(f'bad comparison {op!r}')
        rhs = self.expr()
        self.expect(')')
        true = self.block()
        false = []
        if self.peek()[1] == 'else':
            self.next()
            false = self.block()
        return If(lhs, op, rhs, true, false)

    def gate_call(self) -> GateCall:
        name = self.next()[1]
        params = []
        if self.peek()[1] == '(':
            self.next()
            while self.peek()[1] != ')':
                params.append(self.expr())
                if self.peek()[1] == ',':
                    self.next()
            self.next()
        operands = [self.ref()]
        while self.peek()[1] == ',':
            self.next()
            operands.append(self.ref())
        self.expect(';')
        return GateCall(name, params, operands)

    def _ref_list(self) -> list:
        """Comma-separated operand refs terminated by ';' (consumed)."""
        ops = []
        while self.peek()[1] != ';':
            ops.append(self.ref())
            if self.peek()[1] == ',':
                self.next()
        self.next()
        return ops

    def ref(self) -> Ref:
        kind, name = self.next()
        if kind != 'id':
            raise QASMSyntaxError(f'expected identifier, got {name!r}')
        if name in _KEYWORDS:
            raise QASMSyntaxError(f'{name!r} is a reserved keyword')
        index = None
        if self.peek()[1] == '[':
            self.next()
            index = int(self.next()[1])
            self.expect(']')
        return Ref(name, index)

    # precedence-climbing arithmetic
    def expr(self):
        return self._additive()

    def _additive(self):
        lhs = self._multiplicative()
        while self.peek()[1] in ('+', '-'):
            op = self.next()[1]
            lhs = BinOp(op, lhs, self._multiplicative())
        return lhs

    def _multiplicative(self):
        lhs = self._unary()
        while self.peek()[1] in ('*', '/', '%'):
            op = self.next()[1]
            lhs = BinOp(op, lhs, self._unary())
        return lhs

    def _unary(self):
        if self.peek()[1] == '-':
            self.next()
            return BinOp('-', 0, self._unary())
        if self.peek()[1] == '(':
            self.next()
            e = self.expr()
            self.expect(')')
            return e
        kind, val = self.next()
        if kind == 'num':
            return float(val) if ('.' in val or 'e' in val) else int(val)
        if kind == 'id':
            index = None
            if self.peek()[1] == '[':
                self.next()
                index = int(self.next()[1])
                self.expect(']')
            return Ref(val, index)
        raise QASMSyntaxError(f'unexpected token in expression: {val!r}')


def parse_qasm(src: str) -> list:
    return Parser(src).parse()
