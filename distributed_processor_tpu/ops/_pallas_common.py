"""Shared Pallas plumbing for every kernel in :mod:`..ops`.

Three things used to be copy-pasted between ``resolve_pallas.py``,
``waveform_pallas.py``, ``demod.py`` (and now ``exec_pallas.py``):

* the guarded ``jax.experimental.pallas`` import (:data:`HAS_PALLAS`,
  with ``pl`` / ``pltpu`` re-exported so kernels import one module);
* the interpret-mode NORMALIZATION: ``interpret=True`` becomes
  ``pltpu.InterpretParams()`` where this jax ships it — the TPU
  interpreter simulates VMEM/SMEM + grid pipelining on CPU, and on
  those versions plain ``interpret=True`` has no lowering for SMEM
  scalars in some mosaic primitives.  On older jax (no
  ``InterpretParams``) ``True`` passes through to the generic pallas
  interpreter, which handles every construct these kernels use;
* the interpret-mode DEFAULT: kernels compile on TPU backends and fall
  back to the interpreter everywhere else (:func:`default_interpret`),
  so tier-1 CPU runs exercise the same kernel code paths.
"""

from __future__ import annotations

import jax

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAS_PALLAS = True
except ImportError:      # pragma: no cover - pallas ships with jax
    pl = None
    pltpu = None
    HAS_PALLAS = False


def default_interpret() -> bool:
    """Whether a Pallas kernel dispatched NOW should run under the
    interpreter: only a real TPU backend lowers mosaic kernels."""
    return jax.default_backend() != 'tpu'


def normalize_interpret(interpret):
    """Map ``interpret=True`` to ``pltpu.InterpretParams()`` (the TPU
    interpreter) when this jax provides it; ``False`` / an explicit
    params object / ``True`` on older jax pass through."""
    if interpret is True and hasattr(pltpu, 'InterpretParams'):
        return pltpu.InterpretParams()
    return interpret
