"""Shared Pallas plumbing for every kernel in :mod:`..ops`.

Three things used to be copy-pasted between ``resolve_pallas.py``,
``waveform_pallas.py``, ``demod.py`` (and now ``exec_pallas.py``):

* the guarded ``jax.experimental.pallas`` import (:data:`HAS_PALLAS`,
  with ``pl`` / ``pltpu`` re-exported so kernels import one module);
* the interpret-mode NORMALIZATION: ``interpret=True`` becomes
  ``pltpu.InterpretParams()`` where this jax ships it — the TPU
  interpreter simulates VMEM/SMEM + grid pipelining on CPU, and on
  those versions plain ``interpret=True`` has no lowering for SMEM
  scalars in some mosaic primitives.  On older jax (no
  ``InterpretParams``) ``True`` passes through to the generic pallas
  interpreter, which handles every construct these kernels use;
* the interpret-mode DEFAULT: kernels compile on TPU backends and fall
  back to the interpreter everywhere else (:func:`default_interpret`),
  so tier-1 CPU runs exercise the same kernel code paths.

Plus the bit-packed carry layout (:class:`BitPackPlan`): a static
first-fit assignment of small-width int32 fields into 32-bit carry
words, with pack/unpack as pure shift/mask jnp ops so the SAME code
runs on the XLA side of a kernel boundary and inside a Pallas kernel
body.  ``exec_pallas.span_call`` uses it to shrink the megastep's
HBM-crossing state stream (docs/PERF.md "fused epoch").
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAS_PALLAS = True
except ImportError:      # pragma: no cover - pallas ships with jax
    pl = None
    pltpu = None
    HAS_PALLAS = False


def default_interpret() -> bool:
    """Whether a Pallas kernel dispatched NOW should run under the
    interpreter: only a real TPU backend lowers mosaic kernels."""
    return jax.default_backend() != 'tpu'


def normalize_interpret(interpret):
    """Map ``interpret=True`` to ``pltpu.InterpretParams()`` (the TPU
    interpreter) when this jax provides it; ``False`` / an explicit
    params object / ``True`` on older jax pass through."""
    if interpret is True and hasattr(pltpu, 'InterpretParams'):
        return pltpu.InterpretParams()
    return interpret


class BitPackPlan:
    """Static first-fit packing of small-width int32 fields into 32-bit
    carry words.

    The layout is decided entirely from static metadata — an ordered
    list of ``(key, tail_shape, widths)`` leaves, where ``tail_shape``
    is the per-shot shape (no batch axis) and ``widths`` gives each
    flattened element's bit width (scalar = uniform).  Elements are
    assigned greedily in order, never straddling a word boundary, so
    every field is a single shift+mask on both sides.

    ``pack``/``unpack`` are pure shift/mask jnp ops over ``[B, ...]``
    arrays: the same code runs on the XLA side of a kernel boundary and
    inside a Pallas kernel body (no gathers, no dynamic indexing).

    Contract: packed values must lie in ``[0, 2**width)``.  ``pack``
    masks (so out-of-range inputs are truncated, matching the ISA's
    field-mask semantics) and ``unpack`` returns the non-negative
    residue — callers pick widths so this is the identity on every
    value the field can hold.
    """

    def __init__(self, leaves):
        self.shapes = {}
        self.slots = {}
        word, used = 0, 0
        for key, tail, widths in leaves:
            n = 1
            for d in tail:
                n *= int(d)
            ws = np.broadcast_to(np.asarray(widths, np.int64), (n,))
            sl = []
            for w in ws.tolist():
                if not 1 <= w <= 32:
                    raise ValueError(f'bit width {w} for {key!r} out of [1, 32]')
                if used + w > 32:
                    word, used = word + 1, 0
                sl.append((word, used, w))
                used += w
            self.shapes[key] = tuple(tail)
            self.slots[key] = sl
        self.n_words = word + (1 if used else 0)

    @staticmethod
    def _mask(w):
        return jnp.int32(-1) if w == 32 else jnp.int32((1 << w) - 1)

    def pack(self, leaves):
        """``{key: [B, *tail] int32} -> [B, n_words] int32``."""
        acc = [None] * self.n_words
        B = None
        for key, sl in self.slots.items():
            a = leaves[key].astype(jnp.int32)
            B = a.shape[0]
            flat = a.reshape(B, -1)
            for j, (wd, sh, w) in enumerate(sl):
                v = flat[:, j] & self._mask(w)
                if sh:
                    v = v << sh
                acc[wd] = v if acc[wd] is None else acc[wd] | v
        cols = [a if a is not None else jnp.zeros((B,), jnp.int32) for a in acc]
        return jnp.stack(cols, axis=-1)

    def unpack(self, words):
        """``[B, n_words] int32 -> {key: [B, *tail] int32}``."""
        out = {}
        for key, sl in self.slots.items():
            cols = []
            for wd, sh, w in sl:
                v = words[:, wd]
                if sh:
                    v = v >> sh
                cols.append(v & self._mask(w))
            flat = jnp.stack(cols, axis=-1)
            out[key] = flat.reshape((words.shape[0],) + self.shapes[key])
        return out
