from .waveform import (synthesize_element, pulse_window_weights,
                       resolve_pulse_freqs, iq_to_complex, complex_to_iq)
from .demod import (demod_iq, demod_iq_pallas, discriminate,
                    demod_and_discriminate, stack_window_weights)
from .fabric import MeasLUT
from .waveform_pallas import synthesize_element_pallas
