"""Measurement-distribution fabric: the syndrome LUT.

TPU-native equivalent of the reference's ``meas_lut`` gateware
(reference: hdl/meas_lut.sv, hdl/fproc_lut.sv): measurement bits from a
masked set of input cores form a table address; the table returns one
output bit per core.  Where the gateware hard-codes the mask and table
contents (reference: hdl/meas_lut.sv:16-20, TODO "make these writable"),
this implementation takes them as arrays — a batched table-gather over
the shot axis.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


class MeasLUT:
    """Configurable syndrome LUT over ``n_cores`` measurement bits.

    ``input_mask``: bool ``[n_cores]`` — which cores' bits form the
    address (bit i of the address is the i-th set core, LSB first).
    ``table``: int ``[2^k]`` — each entry is an n_cores-wide bitmask of
    output bits (one per core), matching the gateware's ``lut_mem``.
    """

    def __init__(self, input_mask, table):
        self.input_mask = np.asarray(input_mask, bool)
        self.table = jnp.asarray(table, jnp.int32)
        k = int(self.input_mask.sum())
        if len(table) != 1 << k:
            raise ValueError(f'table must have 2^{k} entries, got {len(table)}')
        # address bit position per core (0 for unmasked cores)
        self._addr_shift = np.zeros(len(self.input_mask), dtype=np.int32)
        self._addr_shift[self.input_mask] = np.arange(k)
        # Hoisted jnp constants: forming them per address()/__call__
        # made every call re-stage host->device transfers of the same
        # static masks, so jit retraced when the object identity (and
        # thus the constant) changed.  One weight vector folds mask and
        # shift: bits @ weights == sum(bits * mask << shift).
        self._addr_weights = jnp.asarray(
            self.input_mask.astype(np.int32) * (1 << self._addr_shift))
        self._bit_shifts = jnp.arange(len(self.input_mask),
                                      dtype=jnp.int32)

    @classmethod
    def from_fpga_config(cls, fpga_config) -> 'MeasLUT':
        """Build the LUT from :class:`~..hwconfig.FPGAConfig`'s
        ``meas_lut_mask`` / ``meas_lut_table`` fields — the writable
        analog of the contents the gateware hard-codes (reference:
        hdl/meas_lut.sv:16-20).  Raises when the config carries no LUT."""
        if not fpga_config.meas_lut_mask:
            raise ValueError(
                'FPGAConfig has no meas LUT configured (meas_lut_mask is '
                'empty); set meas_lut_mask + meas_lut_table')
        return cls(fpga_config.meas_lut_mask, fpga_config.meas_lut_table)

    def address(self, bits):
        """bits ``[..., n_cores]`` -> table address ``[...]``."""
        bits = jnp.asarray(bits, jnp.int32)
        return jnp.sum(bits * self._addr_weights, axis=-1)

    def __call__(self, bits):
        """bits ``[..., n_cores]`` -> per-core LUT output bits, same shape."""
        addr = self.address(bits)
        entry = self.table[addr]                        # [...]
        return (entry[..., None] >> self._bit_shifts) & 1

    def sharded_call(self, bits, axis_name, axis: int = -1):
        """``__call__`` for bits sharded over mesh axis ``axis_name``:
        all_gathers the per-shard bit slices (tiled, so the concat
        follows mesh-axis order and matches the replicated layout
        bit-for-bit), then runs the ordinary table gather.  Returns the
        FULL-width output on every shard — callers slice out their own
        cores.  Used by the cores-sharded interpreter fabric
        (sim/interpreter.py lut branch, docs/PERF.md "ICI fabric")."""
        full = jax.lax.all_gather(jnp.asarray(bits, jnp.int32),
                                  axis_name, axis=axis, tiled=True)
        return self(full)
