"""Measurement-distribution fabric: the syndrome LUT.

TPU-native equivalent of the reference's ``meas_lut`` gateware
(reference: hdl/meas_lut.sv, hdl/fproc_lut.sv): measurement bits from a
masked set of input cores form a table address; the table returns one
output bit per core.  Where the gateware hard-codes the mask and table
contents (reference: hdl/meas_lut.sv:16-20, TODO "make these writable"),
this implementation takes them as arrays — a batched table-gather over
the shot axis.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


class MeasLUT:
    """Configurable syndrome LUT over ``n_cores`` measurement bits.

    ``input_mask``: bool ``[n_cores]`` — which cores' bits form the
    address (bit i of the address is the i-th set core, LSB first).
    ``table``: int ``[2^k]`` — each entry is an n_cores-wide bitmask of
    output bits (one per core), matching the gateware's ``lut_mem``.
    """

    def __init__(self, input_mask, table):
        self.input_mask = np.asarray(input_mask, bool)
        self.table = jnp.asarray(table, jnp.int32)
        k = int(self.input_mask.sum())
        if len(table) != 1 << k:
            raise ValueError(f'table must have 2^{k} entries, got {len(table)}')
        # address bit position per core (0 for unmasked cores)
        self._addr_shift = np.zeros(len(self.input_mask), dtype=np.int32)
        self._addr_shift[self.input_mask] = np.arange(k)
        # Hoisted jnp constants: forming them per address()/__call__
        # made every call re-stage host->device transfers of the same
        # static masks, so jit retraced when the object identity (and
        # thus the constant) changed.  One weight vector folds mask and
        # shift: bits @ weights == sum(bits * mask << shift).
        self._addr_weights = jnp.asarray(
            self.input_mask.astype(np.int32) * (1 << self._addr_shift))
        self._bit_shifts = jnp.arange(len(self.input_mask),
                                      dtype=jnp.int32)

    @classmethod
    def from_fpga_config(cls, fpga_config) -> 'MeasLUT':
        """Build the LUT from :class:`~..hwconfig.FPGAConfig`'s
        ``meas_lut_mask`` / ``meas_lut_table`` fields — the writable
        analog of the contents the gateware hard-codes (reference:
        hdl/meas_lut.sv:16-20).  Raises when the config carries no LUT."""
        if not fpga_config.meas_lut_mask:
            raise ValueError(
                'FPGAConfig has no meas LUT configured (meas_lut_mask is '
                'empty); set meas_lut_mask + meas_lut_table')
        return cls(fpga_config.meas_lut_mask, fpga_config.meas_lut_table)

    def address(self, bits):
        """bits ``[..., n_cores]`` -> table address ``[...]``."""
        bits = jnp.asarray(bits, jnp.int32)
        return jnp.sum(bits * self._addr_weights, axis=-1)

    def __call__(self, bits):
        """bits ``[..., n_cores]`` -> per-core LUT output bits, same shape."""
        addr = self.address(bits)
        entry = self.table[addr]                        # [...]
        return (entry[..., None] >> self._bit_shifts) & 1

    def sharded_call(self, bits, axis_name, axis: int = -1):
        """``__call__`` for bits sharded over mesh axis ``axis_name``:
        all_gathers the per-shard bit slices (tiled, so the concat
        follows mesh-axis order and matches the replicated layout
        bit-for-bit), then runs the ordinary table gather.  Returns the
        FULL-width output on every shard — callers slice out their own
        cores.  Used by the cores-sharded interpreter fabric
        (sim/interpreter.py lut branch, docs/PERF.md "ICI fabric")."""
        full = jax.lax.all_gather(jnp.asarray(bits, jnp.int32),
                                  axis_name, axis=axis, tiled=True)
        return self(full)

    def timed_call(self, bit_planes, time_planes, n_meas, read_time):
        """Time-indexed LUT read — the dispatch-granularity-invariant
        semantics the fast engines serve (docs/PERF.md "Feedback on
        the fast engines").

        Instead of latching each masked producer's LATEST bit (whose
        value depends on how producer instructions interleave with the
        read), select per producer the newest bit PRODUCED strictly
        before the read's service time: with ``bit_planes`` ``[...,
        n_cores, n_slots]`` (per-slot measurement bits), ``time_planes``
        same shape (per-slot production clocks, ``INT32_MAX`` where
        unwritten), ``n_meas`` ``[..., n_cores]`` (slots recorded), and
        ``read_time`` ``[...]``, the served slot for producer ``p`` is
        ``max(#{m < n_meas_p : t_pm < read_time}, 1) - 1`` — count 0
        falls back to slot 0, the first recorded bit, matching the
        gateware's arm-then-accumulate ``LUT_WAIT``.  Strict ``<``
        because a producer whose clock sits exactly at ``read_time``
        can still fire a trigger there; once every producer's clock
        passes ``read_time`` the selection is FINAL, so any dispatch
        granularity that serves the read from these planes returns the
        same bits.  This is the reference semantics the interpreter
        engines implement inline (sim/interpreter.py lut serves);
        callers with only latest-bit vectors keep using ``__call__``.

        Returns ``(out_bits, slot)``: per-core LUT output bits
        ``[..., n_cores]`` and the selected slot per producer
        ``[..., n_cores]`` (for availability/validity lookups)."""
        bit_planes = jnp.asarray(bit_planes, jnp.int32)
        time_planes = jnp.asarray(time_planes, jnp.int32)
        n_meas = jnp.asarray(n_meas, jnp.int32)
        M = bit_planes.shape[-1]
        rec = jnp.arange(M, dtype=jnp.int32) < n_meas[..., None]
        early = rec & (time_planes
                       < jnp.asarray(read_time, jnp.int32)[..., None, None])
        cnt = jnp.sum(early.astype(jnp.int32), axis=-1)
        slot = jnp.maximum(cnt - 1, 0)
        sel = (jnp.arange(M, dtype=jnp.int32) == slot[..., None]) \
            .astype(jnp.int32)
        bits = jnp.sum(bit_planes * sel, axis=-1)
        return self(bits), slot
