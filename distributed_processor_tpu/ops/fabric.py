"""Measurement-distribution fabric: the syndrome LUT.

TPU-native equivalent of the reference's ``meas_lut`` gateware
(reference: hdl/meas_lut.sv, hdl/fproc_lut.sv): measurement bits from a
masked set of input cores form a table address; the table returns one
output bit per core.  Where the gateware hard-codes the mask and table
contents (reference: hdl/meas_lut.sv:16-20, TODO "make these writable"),
this implementation takes them as arrays — a batched table-gather over
the shot axis.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp


class MeasLUT:
    """Configurable syndrome LUT over ``n_cores`` measurement bits.

    ``input_mask``: bool ``[n_cores]`` — which cores' bits form the
    address (bit i of the address is the i-th set core, LSB first).
    ``table``: int ``[2^k]`` — each entry is an n_cores-wide bitmask of
    output bits (one per core), matching the gateware's ``lut_mem``.
    """

    def __init__(self, input_mask, table):
        self.input_mask = np.asarray(input_mask, bool)
        self.table = jnp.asarray(table, jnp.int32)
        k = int(self.input_mask.sum())
        if len(table) != 1 << k:
            raise ValueError(f'table must have 2^{k} entries, got {len(table)}')
        # address bit position per core (0 for unmasked cores)
        self._addr_shift = np.zeros(len(self.input_mask), dtype=np.int32)
        self._addr_shift[self.input_mask] = np.arange(k)

    @classmethod
    def from_fpga_config(cls, fpga_config) -> 'MeasLUT':
        """Build the LUT from :class:`~..hwconfig.FPGAConfig`'s
        ``meas_lut_mask`` / ``meas_lut_table`` fields — the writable
        analog of the contents the gateware hard-codes (reference:
        hdl/meas_lut.sv:16-20).  Raises when the config carries no LUT."""
        if not fpga_config.meas_lut_mask:
            raise ValueError(
                'FPGAConfig has no meas LUT configured (meas_lut_mask is '
                'empty); set meas_lut_mask + meas_lut_table')
        return cls(fpga_config.meas_lut_mask, fpga_config.meas_lut_table)

    def address(self, bits):
        """bits ``[..., n_cores]`` -> table address ``[...]``."""
        bits = jnp.asarray(bits, jnp.int32)
        shifts = jnp.asarray(self._addr_shift)
        mask = jnp.asarray(self.input_mask, jnp.int32)
        return jnp.sum(bits * mask * (1 << shifts), axis=-1)

    def __call__(self, bits):
        """bits ``[..., n_cores]`` -> per-core LUT output bits, same shape."""
        addr = self.address(bits)
        entry = self.table[addr]                        # [...]
        n = len(self.input_mask)
        return (entry[..., None] >> jnp.arange(n)) & 1
