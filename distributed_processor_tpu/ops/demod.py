"""Readout demodulation + state discrimination.

The reference's readout chain (IQ demod accumulator + state discriminator
producing the ``meas``/``meas_valid`` bits consumed by the fproc fabric)
lives in the out-of-repo gateware project; this repo only consumes its
output bits (reference: hdl/fproc_meas.sv meas inputs, SURVEY §1).  Here
the chain is implemented the TPU way:

* demod is a matmul: ``acc[shot, 2m:2m+2] = adc[shot, :] @ W[:, 2m:2m+2]``
  with the conj-reference weights from
  :func:`..ops.waveform.pulse_window_weights` — shots × samples on the
  MXU instead of a per-sample accumulator FSM;
* a Pallas kernel (:func:`demod_iq_pallas`) tiles the same contraction
  through VMEM for long traces, fusing the I/Q pair into one pass;
* discrimination projects IQ onto a separation axis and thresholds —
  one fused elementwise op.

I/Q results are real float32 with a trailing axis of 2 (no complex
dtypes on device — see :mod:`.waveform`).  All entry points are
jit/vmap/shard_map-friendly; the shot axis is the framework's
data-parallel axis.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ._pallas_common import HAS_PALLAS as _HAS_PALLAS, pl


def _as_iq_centers(c):
    """Accept complex [M] or real [M, 2] calibration centroids."""
    c = np.asarray(c)
    if np.iscomplexobj(c) or c.ndim == 1:
        return jnp.asarray(
            np.stack([np.real(c), np.imag(c)], axis=-1).astype(np.float32))
    return jnp.asarray(c, jnp.float32)


def demod_iq(adc, weights):
    """Demod ``[S, N]`` ADC traces against ``[N, 2M]`` window weights.

    Returns float32 ``[S, M, 2]`` I/Q accumulations (columns ``2m``/
    ``2m+1`` of ``weights`` are measurement m's I and Q references).
    """
    adc = jnp.asarray(adc, jnp.float32)
    weights = jnp.asarray(weights, jnp.float32)
    acc = adc @ weights                       # [S, 2M]
    return acc.reshape(acc.shape[0], -1, 2)


def stack_window_weights(weight_list, n_samples: int,
                         starts=None) -> np.ndarray:
    """Stack per-measurement ``[n, 2]`` window weights into the dense
    ``[n_samples, 2M]`` demod matrix (zero outside each window)."""
    M = len(weight_list)
    W = np.zeros((n_samples, 2 * M), dtype=np.float32)
    for m, w in enumerate(weight_list):
        s = 0 if starts is None else int(starts[m])
        n = min(len(w), n_samples - s)
        W[s:s + n, 2 * m] = w[:n, 0]
        W[s:s + n, 2 * m + 1] = w[:n, 1]
    return W


def _demod_kernel(adc_ref, w_ref, out_ref):
    out_ref[:] = jnp.dot(adc_ref[:], w_ref[:],
                         preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=('block_s', 'interpret'))
def demod_iq_pallas(adc, weights, block_s: int = 256, interpret: bool = False):
    """Pallas-tiled demod: shots blocked through VMEM, full contraction
    per block (readout windows are short; N fits VMEM comfortably).

    Matches :func:`demod_iq` in float32.  Set ``interpret=True`` off-TPU
    (tests run it on the CPU interpreter).
    """
    if not _HAS_PALLAS:   # pragma: no cover
        return demod_iq(adc, weights)
    adc = jnp.asarray(adc, jnp.float32)
    weights = jnp.asarray(weights, jnp.float32)
    S, N = adc.shape
    M2 = weights.shape[1]
    pad_s = (-S) % block_s
    if pad_s:
        adc = jnp.pad(adc, ((0, pad_s), (0, 0)))
    Sp = adc.shape[0]
    acc = pl.pallas_call(
        _demod_kernel,
        grid=(Sp // block_s,),
        in_specs=[
            pl.BlockSpec((block_s, N), lambda i: (i, 0)),
            pl.BlockSpec((N, M2), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_s, M2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((Sp, M2), jnp.float32),
        interpret=interpret,
    )(adc, weights)
    acc = acc[:S]
    return acc.reshape(S, -1, 2)


def discriminate(iq, centers0, centers1, threshold: float = 0.0):
    """Binary state discrimination by projection onto the |0>-|1> axis.

    ``iq``: ``[S, M, 2]`` I/Q points; ``centers0``/``centers1``: per-
    channel calibration centroids (complex ``[M]`` or real ``[M, 2]``).
    Returns int32 bits ``[S, M]``.
    """
    iq = jnp.asarray(iq, jnp.float32)
    c0, c1 = _as_iq_centers(centers0), _as_iq_centers(centers1)
    axis = c1 - c0                            # [M, 2]
    mid = (c0 + c1) / 2
    proj = jnp.sum((iq - mid[None]) * axis[None], axis=-1)
    return (proj > threshold).astype(jnp.int32)


def demod_and_discriminate(adc, weights, centers0, centers1,
                           use_pallas: bool = False,
                           interpret: bool = False):
    """Fused ADC trace -> discriminated bits (the full readout chain)."""
    if use_pallas:
        iq = demod_iq_pallas(adc, weights, interpret=interpret)
    else:
        iq = demod_iq(adc, weights)
    return discriminate(iq, centers0, centers1), iq
