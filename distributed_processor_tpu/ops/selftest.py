"""Pallas-kernel parity self-test, shared by bench.py and the
``tpu``-marked test suite so the 'bench runs the same assertions'
guarantee can't silently diverge.

The reference implementations being checked against are the pure-XLA
:func:`.demod.demod_iq` and :func:`.waveform.synthesize_element`; the
kernels are :func:`.demod.demod_iq_pallas` and
:func:`.waveform_pallas.synthesize_element_pallas`.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..elements import ENV_CW_SENTINEL
from .demod import demod_iq, demod_iq_pallas
from .waveform import synthesize_element
from .waveform_pallas import synthesize_element_pallas


def check_demod_parity(interpret: bool):
    """MXU demod kernel vs XLA matmul; raises on mismatch."""
    rng = np.random.default_rng(0)
    adc = rng.standard_normal((1000, 1024)).astype(np.float32)
    w = rng.standard_normal((1024, 8)).astype(np.float32)
    got = np.asarray(demod_iq_pallas(adc, w, interpret=interpret))
    want = np.asarray(demod_iq(adc, w))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-3)


def check_waveform_parity(interpret: bool):
    """NCO synthesis kernel vs XLA element model; raises on mismatch."""
    rng = np.random.default_rng(1)
    env = (rng.standard_normal(256) + 1j * rng.standard_normal(256)) * 0.5
    rec = {
        'gtime': jnp.asarray([4, 40, 90, 0], jnp.int32),
        'env': jnp.asarray([(32 << 12) | 0, (48 << 12) | 16,
                            (ENV_CW_SENTINEL << 12) | 8, 0], jnp.int32),
        'phase': jnp.asarray([0, 1 << 15, 1 << 14, 0], jnp.int32),
        'freq_rel': jnp.asarray([0.1, 0.23, 0.05, 0], jnp.float32),
        'amp': jnp.asarray([0xffff, 0x8000, 0x4000, 0], jnp.int32),
        'elem': jnp.asarray([0, 0, 0, 0], jnp.int32),
        'n_pulses': jnp.int32(3),
    }
    got = np.asarray(synthesize_element_pallas(
        rec, env, spc=4, interp=1, n_clks=128, block=512,
        interpret=interpret))
    want = np.asarray(synthesize_element(rec, env, spc=4, interp=1,
                                         n_clks=128))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def pallas_parity_check(interpret: bool) -> None:
    """Run both kernel parity checks; raises AssertionError on mismatch."""
    check_demod_parity(interpret)
    check_waveform_parity(interpret)
