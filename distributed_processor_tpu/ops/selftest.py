"""Pallas-kernel parity self-test, shared by bench.py and the
``tpu``-marked test suite so the 'bench runs the same assertions'
guarantee can't silently diverge.

The reference implementations being checked against are the pure-XLA
:func:`.demod.demod_iq` and :func:`.waveform.synthesize_element`, and
the generic interpreter engine; the kernels are
:func:`.demod.demod_iq_pallas`,
:func:`.waveform_pallas.synthesize_element_pallas`, and the
:mod:`.exec_pallas` megastep engine (``engine='pallas'``).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..elements import ENV_CW_SENTINEL
from .demod import demod_iq, demod_iq_pallas
from .waveform import synthesize_element
from .waveform_pallas import synthesize_element_pallas


def check_demod_parity(interpret: bool):
    """MXU demod kernel vs XLA matmul; raises on mismatch."""
    rng = np.random.default_rng(0)
    adc = rng.standard_normal((1000, 1024)).astype(np.float32)
    w = rng.standard_normal((1024, 8)).astype(np.float32)
    got = np.asarray(demod_iq_pallas(adc, w, interpret=interpret))
    want = np.asarray(demod_iq(adc, w))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-3)


def check_waveform_parity(interpret: bool):
    """NCO synthesis kernel vs XLA element model; raises on mismatch."""
    rng = np.random.default_rng(1)
    env = (rng.standard_normal(256) + 1j * rng.standard_normal(256)) * 0.5
    rec = {
        'gtime': jnp.asarray([4, 40, 90, 0], jnp.int32),
        'env': jnp.asarray([(32 << 12) | 0, (48 << 12) | 16,
                            (ENV_CW_SENTINEL << 12) | 8, 0], jnp.int32),
        'phase': jnp.asarray([0, 1 << 15, 1 << 14, 0], jnp.int32),
        'freq_rel': jnp.asarray([0.1, 0.23, 0.05, 0], jnp.float32),
        'amp': jnp.asarray([0xffff, 0x8000, 0x4000, 0], jnp.int32),
        'elem': jnp.asarray([0, 0, 0, 0], jnp.int32),
        'n_pulses': jnp.int32(3),
    }
    got = np.asarray(synthesize_element_pallas(
        rec, env, spc=4, interp=1, n_clks=128, block=512,
        interpret=interpret))
    want = np.asarray(synthesize_element(rec, env, spc=4, interp=1,
                                         n_clks=128))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def check_exec_parity(interpret: bool):
    """Megastep exec kernel vs the generic engine; raises on mismatch.

    Exact int32 equality on every retired stat (records, registers,
    clocks, fault word), in both kernel modes: a forward-only program
    (one span call) and a counted loop (block path, kernels inside the
    outer while_loop).
    """
    # deferred import: ops stays import-time independent of sim
    # (sim.physics imports ops); by selftest call time both are loaded
    from .. import isa
    from ..decoder import machine_program_from_cmds
    from ..sim.interpreter import InterpreterConfig, simulate_batch

    span = [[isa.pulse_cmd(amp_word=1000, cfg_word=0,
                           env_word=(8 << 12) | 3, cmd_time=10),
             isa.alu_cmd('reg_alu', 'i', 5, 'add', alu_in1=1,
                         write_reg_addr=1),
             isa.pulse_cmd(amp_word=2000, cfg_word=2,
                           env_word=(4 << 12) | 1, cmd_time=40),
             isa.done_cmd()]]
    loop = [[isa.alu_cmd('reg_alu', 'i', 0, 'add', write_reg_addr=2),
             isa.pulse_cmd(amp_word=500, cfg_word=1,
                           env_word=(4 << 12) | 2, cmd_time=12),
             isa.alu_cmd('reg_alu', 'i', 1, 'add', alu_in1=2,
                         write_reg_addr=2),
             isa.alu_cmd('jump_cond', 'i', 3, 'ge', alu_in1=2,
                         jump_cmd_ptr=1),
             isa.done_cmd()]]
    rng = np.random.default_rng(2)
    for cmds in (span, loop):
        mp = machine_program_from_cmds(cmds)
        kw = dict(max_steps=2 * mp.n_instr + 64, max_pulses=8,
                  max_meas=2, max_resets=2)
        bits = rng.integers(0, 2, size=(4, mp.n_cores, 2))
        want = simulate_batch(mp, bits,
                              cfg=InterpreterConfig(engine='generic',
                                                    **kw))
        got = simulate_batch(mp, bits, cfg=InterpreterConfig(
            engine='pallas', pallas_interpret=interpret, **kw))
        for k in want:
            if k == 'steps':
                continue
            np.testing.assert_array_equal(np.asarray(got[k]),
                                          np.asarray(want[k]), err_msg=k)

    # fused-measurement path: a span with mid-circuit measurements and a
    # branch on the demodulated bit, the window resolved INSIDE the span
    # kernel (engine='fused') vs the generic engine's epoch loop — exact
    # per-stat equality, fault word included ('steps'/'epochs' are the
    # loop-structure counters the fusion exists to change)
    from ..models.experiments import active_reset
    from ..sim.physics import ReadoutPhysics, run_physics_batch
    from ..simulator import Simulator
    mpf = Simulator(n_qubits=2).compile(active_reset(['Q0', 'Q1']))
    init = rng.integers(0, 2, (4, mpf.n_cores)).astype(np.int32)
    kwf = dict(init_states=init, max_steps=mpf.n_instr * 4 + 64,
               max_pulses=16, max_meas=4)
    want = run_physics_batch(mpf, ReadoutPhysics(sigma=0.0), 3, 4,
                             engine='generic', **kwf)
    got = run_physics_batch(mpf, ReadoutPhysics(sigma=0.0), 3, 4,
                            engine='fused', pallas_interpret=interpret,
                            **kwf)
    for k in want:
        if k in ('steps', 'epochs'):
            continue
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(want[k]), err_msg=k)
    assert int(np.asarray(got['epochs'])) == 1, \
        'fused engine did not collapse the epoch while_loop'


def pallas_parity_check(interpret: bool) -> None:
    """Run every kernel parity check; raises AssertionError on mismatch."""
    check_demod_parity(interpret)
    check_waveform_parity(interpret)
    check_exec_parity(interpret)
