"""Pallas TPU kernel for element waveform synthesis.

The reference synthesises waveforms in dedicated DDS gateware (the
out-of-repo signal-generator element); :func:`..ops.waveform.
synthesize_element` is the XLA reference implementation.  This kernel
tiles the trace through VMEM for long captures: the grid walks sample
blocks, a ``fori_loop`` over pulses accumulates windowed contributions,
and each pulse's envelope segment is fetched with a scalar-offset
dynamic slice (per-lane gathers don't vectorise on TPU; contiguous
slices do — the same design rule as the interpreter's one-hot fetch).

The carrier is generated exactly the way the hardware NCO does it:
a 32-bit integer phase accumulator (``inc * n mod 2^32``, wrapping int32
multiply) so phase stays exact for arbitrarily long traces — float32
``2*pi*f*n`` loses ~0.3 rad by a million samples.

Envelopes are pre-expanded by their interpolation ratio and padded by
one block on both sides, so every in-window lane's envelope index falls
inside the loaded slice with no per-lane clamping.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..elements import ENV_CW_SENTINEL

from ._pallas_common import HAS_PALLAS as _HAS_PALLAS, pl, pltpu

_TWO_PI_OVER_2_32 = float(2 * np.pi / 2 ** 32)


def _kernel(scal_ref, env_ref, out_ref, *, block: int, n_pulses: int):
    b = pl.program_id(0)
    n0 = b * block
    lane = jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0)[:, 0]
    n = n0 + lane

    def body(p, acc):
        s = scal_ref[0, p]
        e = scal_ref[1, p]
        env_off = scal_ref[2, p]          # into the padded expanded table
        inc = scal_ref[3, p]              # 32-bit NCO phase increment
        phase0 = scal_ref[4, p]           # phase word scaled to 2^32 units
        ampw = scal_ref[5, p]             # amp word (16 bit)
        is_cw = scal_ref[6, p]            # constant-envelope pulse

        in_win = (n >= s) & (n < e)
        # envelope slice: sample n reads padded index env_off + (n - s);
        # the slice start is a scalar, alignment is exact by construction
        # for in-window lanes; CW pulses pin the slice to their constant
        # segment; the clamp (a) protects out-of-window blocks and (b)
        # realises the reference's hold-last-sample overrun semantics
        # (the table's tail fill repeats the last sample)
        start = jnp.clip(env_off + (1 - is_cw) * (n0 - s), 0,
                         env_ref.shape[0] - block)
        ev_i = env_ref[pl.ds(start, block), 0]
        ev_q = env_ref[pl.ds(start, block), 1]
        # exact NCO: phase = (inc * n + phase0) mod 2^32 via int32 wrap
        pa = inc * n + phase0
        theta = pa.astype(jnp.float32) * _TWO_PI_OVER_2_32
        c, si = jnp.cos(theta), jnp.sin(theta)
        amp = ampw.astype(jnp.float32) / 65535.0
        contrib_i = amp * (ev_i * c - ev_q * si)
        contrib_q = amp * (ev_i * si + ev_q * c)
        mask = in_win.astype(jnp.float32)
        return (acc[0] + mask * contrib_i, acc[1] + mask * contrib_q)

    zero = jnp.zeros((block,), jnp.float32)
    acc_i, acc_q = jax.lax.fori_loop(0, n_pulses, body, (zero, zero))
    out_ref[:, 0] = acc_i
    out_ref[:, 1] = acc_q


@functools.partial(jax.jit,
                   static_argnames=('block', 'n_samples', 'interpret'))
def _synthesize_call(scal, env_padded, block, n_samples, interpret):
    n_pulses = scal.shape[1]
    env_shape = env_padded.shape
    return pl.pallas_call(
        functools.partial(_kernel, block=block, n_pulses=n_pulses),
        grid=(n_samples // block,),
        in_specs=[
            pl.BlockSpec((7, n_pulses), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec(env_shape, lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block, 2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_samples, 2), jnp.float32),
        interpret=interpret,
    )(scal, env_padded)


def synthesize_element_pallas(rec: dict, env_table, spc: int, interp: int,
                              n_clks: int, elem: int = 0, block: int = 512,
                              interpret: bool = False):
    """Pallas-tiled version of :func:`..ops.waveform.synthesize_element`.

    Same record/env inputs and output shape (``float32 [N, 2]``);
    CW pulses hold their start sample until the next pulse as in the
    reference implementation.  ``interpret=True`` runs off-TPU.
    """
    if not _HAS_PALLAS:   # pragma: no cover
        from .waveform import synthesize_element
        return synthesize_element(rec, env_table, spc, interp, n_clks, elem)

    n_samples = n_clks * spc
    if n_samples % block:
        raise ValueError(f'n_clks*spc={n_samples} must be a multiple of '
                         f'block={block}')

    # ---- host-side preparation (concrete numpy) ------------------------
    rec_np = {k: np.asarray(v) for k, v in rec.items()}
    P = int(rec_np['n_pulses'])
    valid = rec_np['elem'][:P] == elem
    idx = np.nonzero(valid)[0]

    env_table = np.asarray(env_table)
    if env_table.ndim == 1:
        env_table = np.stack([env_table.real, env_table.imag], -1)
    env_exp = np.repeat(env_table.astype(np.float32), interp, axis=0)
    pad = np.zeros((block, 2), np.float32)
    last = env_exp[-1:] if len(env_exp) else np.zeros((1, 2), np.float32)
    # tail fill repeats the last sample: an env window running past the
    # table holds the final sample, matching synthesize_element's clamp
    env_padded = np.concatenate(
        [pad, env_exp, np.broadcast_to(last, (block, 2))])

    scal = np.zeros((7, max(len(idx), 1)), dtype=np.int32)
    starts = rec_np['gtime'][idx] * spc
    env_words = rec_np['env'][idx]
    env_addr = (env_words & 0xfff) * 4
    env_nw = (env_words >> 12) & 0xfff
    is_cw = env_nw == ENV_CW_SENTINEL
    length = np.where(is_cw, n_samples, env_nw * 4 * interp)
    order = np.argsort(starts)
    nxt = np.full(len(idx), n_samples, dtype=np.int64)
    if len(idx):
        ss = starts[order]
        for k in range(len(idx) - 1):
            nxt[order[k]] = ss[k + 1]
    ends = np.where(is_cw, np.minimum(nxt, n_samples), starts + length)
    scal[0, :len(idx)] = starts
    scal[1, :len(idx)] = ends
    scal[2, :len(idx)] = env_addr * interp + block   # + front pad
    scal[6, :len(idx)] = is_cw
    for k in range(len(idx)):
        if is_cw[k]:
            # block-length constant segment holding the start sample;
            # the kernel pins its slice here (no per-block advance)
            samp = env_exp[min(int(env_addr[k]) * interp,
                               max(len(env_exp) - 1, 0))] \
                if len(env_exp) else np.zeros(2, np.float32)
            scal[2, k] = len(env_padded)
            env_padded = np.concatenate(
                [env_padded,
                 np.broadcast_to(samp, (block, 2)).astype(np.float32)])
    scal[3, :len(idx)] = (
        np.round(np.asarray(rec_np['freq_rel'][idx], np.float64)
                 * 2 ** 32).astype(np.int64) % (1 << 32)
    ).astype(np.uint32).view(np.int32)
    scal[4, :len(idx)] = (
        (np.asarray(rec_np['phase'][idx], np.int64) << 15) % (1 << 32)
    ).astype(np.uint32).view(np.int32)     # 17-bit word -> 2^32 units
    scal[5, :len(idx)] = rec_np['amp'][idx]
    if not len(idx):
        scal[1, 0] = 0                     # single no-op pulse entry

    return _synthesize_call(jnp.asarray(scal), jnp.asarray(env_padded),
                            block, n_samples, interpret)
