"""Fused Pallas TPU kernel for readout-window resolution.

One kernel pass implements the whole per-sample readout chain of
:mod:`..sim.physics` — envelope playback + phase-coherent carrier
synthesis, state-dependent channel response, per-sample ADC noise,
matched-filter demodulation — with every per-sample intermediate living
in VMEM.  The XLA formulation (``physics._resolve``) materialises
``[B, C, chunk]`` float32 arrays in HBM for the synthesized window, the
received signal, and every fusion boundary between them; at bench batch
sizes that is gigabytes of bandwidth per chunk.  Here HBM sees the
per-window scalars, the streamed noise chunk, and three ``[C, B]``
accumulators.

Numeric contract (same as ``physics._synth_window_chunk``, pinned by
tests): envelope sample at DAC index ``s`` is ``env[addr + s//interp]``
with hold-last-sample overrun semantics; carrier is the factored
phase-coherent form ``e^{i A} * basis(f, s)`` with the per-window scalar
``A`` supplied by the caller; the envelope fetch rides the MXU as
``one_hot(addr) @ T`` where ``T[r, j] = env[r + j//interp]`` is the
DAC-resolution sliding-window (Toeplitz) table (per-lane gathers do not
vectorise on TPU — the design rule everywhere in this repo).

ADC noise has two generators, selected by ``native_rng``:

* **In-kernel (default on real TPU)**: ``pltpu.prng_random_bits``
  seeded per (key, grid cell, chunk) feeds a Box-Muller transform in
  VMEM — the noise never exists in HBM.  The streamed alternative
  generates ``2*B*C*ck`` float32 normals per chunk with XLA threefry
  and round-trips them through HBM: at bench shapes that is ~2 GB per
  chunk of pure bandwidth plus the threefry compute, which measured as
  the bulk of the per-sample resolve cost (round-3 profiling; removing
  it took the fused resolve from ~0.4 s to ~0.1 s per batch).
* **Streamed (``native_rng=False``, and always under interpret)**:
  drawn outside the kernel with ``jax.random`` one chunk at a time
  inside the chunk ``lax.scan``.  This is the portable path: the TPU
  interpret mode stubs ``prng_random_bits`` to zeros, which would
  silently disable noise in off-TPU tests.

Both generators produce the same N(0, sigma^2) IQ noise distribution
(different streams); sigma=0 is bit-identical across all paths, and a
TPU-marked statistical-parity test pins the native generator against
the streamed one (tests/test_tpu_kernels.py).

The reference implements this chain in dedicated FPGA hardware (rdlo
pulse -> external demod -> meas bits, word formats
python/distproc/asmparse.py:46-86); this kernel is its TPU equivalent.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ._pallas_common import (HAS_PALLAS as _HAS_PALLAS, pl, pltpu,
                             normalize_interpret)


def _kernel(amp_ref, cosa_ref, sina_ref, gsi_ref, gsq_ref,
            fidx_ref, addr_ref, nsamp_ref, s0_ref, ring_ref,
            sig_ref, seed_ref, t_ref, bas_ref, *rest,
            tb: int, ck: int, n_f: int, ring: bool, native_rng: bool,
            rows: tuple):
    if native_rng:
        (acc_i_in, acc_q_in, energy_in,
         acc_i_ref, acc_q_ref, energy_ref) = rest
    else:
        (nz_ref, acc_i_in, acc_q_in, energy_in,
         acc_i_ref, acc_q_ref, energy_ref) = rest
    addr = addr_ref[0, 0, :]                                  # [TB] int32
    if rows is not None:
        # ---- envelope: static-address row select ----------------------
        # the program's envelope latch can only hold these addresses
        # (physics._static_meas_env_addrs, a sound over-approximation),
        # so the fetch is a len(rows)-way equality select — for a
        # single-envelope program, one broadcast row, zero MXU work
        e_i = jnp.broadcast_to(t_ref[0, 0, 0][None, :], (tb, ck))
        e_q = jnp.broadcast_to(t_ref[0, 1, 0][None, :], (tb, ck))
        # minor-dim insertion must happen on the i32 vector, not the i1
        # compare result (mosaic: "Insertion of minor dim that is not a
        # no-op only supported for 32-bit types")
        addr_col = addr[:, None]                              # [TB, 1] i32
        for ridx in range(1, len(rows)):
            selr = addr_col == rows[ridx]
            e_i = jnp.where(selr, t_ref[0, 0, ridx][None, :], e_i)
            e_q = jnp.where(selr, t_ref[0, 1, ridx][None, :], e_q)
    else:
        # ---- envelope: one-hot(addr) @ Toeplitz on the MXU -------------
        r_rows = t_ref.shape[2]
        oh = (addr[:, None]
              == jax.lax.broadcasted_iota(jnp.int32, (tb, r_rows), 1)
              ).astype(jnp.float32)
        # HIGHEST: bf16 operand rounding would quantize env samples past
        # the synthesize_element parity tolerance (the one-hot side is
        # exact)
        e_i = jax.lax.dot_general(
            oh, t_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)              # [TB, CK]
        e_q = jax.lax.dot_general(
            oh, t_ref[0, 1], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)

    # ---- carrier: basis row select (F is tiny), scalar rotation --------
    f_idx = fidx_ref[0, 0, :]                                 # [TB]
    bc = jnp.broadcast_to(bas_ref[0, 0, 0][None, :], (tb, ck))
    bs = jnp.broadcast_to(bas_ref[0, 1, 0][None, :], (tb, ck))
    f_col = f_idx[:, None]             # i32 reshape BEFORE the compare
    for f in range(1, n_f):
        sel = f_col == f
        bc = jnp.where(sel, bas_ref[0, 0, f][None, :], bc)
        bs = jnp.where(sel, bas_ref[0, 1, f][None, :], bs)
    cosa = cosa_ref[0, 0, :][:, None]
    sina = sina_ref[0, 0, :][:, None]
    cth = cosa * bc - sina * bs
    sth = sina * bc + cosa * bs

    # ---- window assembly ----------------------------------------------
    lane = jax.lax.broadcasted_iota(jnp.int32, (tb, ck), 1)
    s_abs = s0_ref[0] + lane
    in_win = (s_abs < nsamp_ref[0, 0, :][:, None]).astype(jnp.float32)
    amp = amp_ref[0, 0, :][:, None]
    y_i = in_win * amp * (e_i * cth - e_q * sth)
    y_q = in_win * amp * (e_i * sth + e_q * cth)

    # ---- channel response + streamed ADC noise + matched filter -------
    # resonator ring-up w(s) = 1 - exp(-(s+1)/ring_tau) scales the
    # signal path only (same contract as physics._resolve); ring_ref
    # holds 1/ring_tau in SMEM.  `ring` is static: the flat model
    # compiles the factor out, and when active, w is one [1, ck] row
    # (s is constant along the shot axis) broadcast into the products
    gs_i = gsi_ref[0, 0, :][:, None]
    gs_q = gsq_ref[0, 0, :][:, None]
    if ring:
        s_row = s0_ref[0] + jax.lax.broadcasted_iota(jnp.int32, (1, ck), 1)
        w = 1.0 - jnp.exp(-(s_row + 1).astype(jnp.float32) * ring_ref[0])
    else:
        w = jnp.float32(1.0)
    if native_rng:
        # in-VMEM ADC noise: counter-based bits seeded per (run key,
        # grid cell, chunk) -> Box-Muller pair.  The noise never
        # touches HBM — the streamed path's ~2 GB/chunk of threefry
        # normals was the bulk of the resolve cost at bench shapes.
        # Mosaic accepts at most 2 seed words: mix the grid cell and
        # chunk offset into the key words (murmur3 finalizer constants;
        # int32 wrap is fine — this is statistical decorrelation, the
        # per-(cell, chunk) streams just must not coincide)
        s0v = s0_ref[0]
        seed0 = seed_ref[0] + pl.program_id(0) * jnp.int32(-1640531527) \
            + s0v * jnp.int32(-2048144789)
        seed1 = seed_ref[1] + pl.program_id(1) * jnp.int32(-1028477387) \
            + s0v
        pltpu.prng_seed(seed0, seed1)
        bits = pltpu.prng_random_bits((2, tb, ck))
        # 24-bit mantissa uniforms: u1 in (0,1] (log-safe), u2 in [0,1).
        # bits are SIGNED int32 — a plain >> would sign-extend and hand
        # log() negative arguments; shift logically
        top24 = jax.lax.shift_right_logical(bits, 8)
        u1 = (top24[0] + 1).astype(jnp.float32) * (2.0 ** -24)
        u2 = top24[1].astype(jnp.float32) * (2.0 ** -24)
        r_bm = jnp.sqrt(-2.0 * jnp.log(u1))
        ang = (2.0 * np.pi) * u2
        sigma = sig_ref[0]
        nz_i = sigma * r_bm * jnp.cos(ang)
        nz_q = sigma * r_bm * jnp.sin(ang)
    else:
        nz_i, nz_q = nz_ref[0, 0], nz_ref[1, 0]
    r_i = w * (gs_i * y_i - gs_q * y_q) + nz_i
    r_q = w * (gs_i * y_q + gs_q * y_i) + nz_q
    acc_i_ref[0, 0, :] = acc_i_in[0, 0, :] + jnp.sum(r_i * y_i + r_q * y_q,
                                                     axis=1)
    acc_q_ref[0, 0, :] = acc_q_in[0, 0, :] + jnp.sum(r_q * y_i - r_i * y_q,
                                                     axis=1)
    energy_ref[0, 0, :] = energy_in[0, 0, :] + jnp.sum(y_i * y_i + y_q * y_q,
                                                       axis=1)


@functools.partial(
    jax.jit, static_argnames=('tb', 'ck', 'w_pad', 'ring', 'native_rng',
                              'rows', 'interpret'))
def _resolve_call(amp, cosa, sina, gs_i, gs_q, f_idx, addr, nsamp,
                  key, sigma, inv_ring, t_dac, basis, tb, ck, w_pad,
                  ring, native_rng, rows, interpret):
    C, _, B = amp.shape
    n_chunks = w_pad // ck
    R = t_dac.shape[2]
    F = basis.shape[2]
    # True -> pltpu.InterpretParams() (see ops/_pallas_common.py); the
    # kernel itself is backend-pure
    interpret = normalize_interpret(interpret)
    lane_spec = pl.BlockSpec((1, 1, tb), lambda c, t: (c, 0, t))
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    call = pl.pallas_call(
        functools.partial(_kernel, tb=tb, ck=ck, n_f=F, ring=ring,
                          native_rng=native_rng, rows=rows),
        grid=(C, B // tb),
        in_specs=[lane_spec] * 8 + [smem] * 4 + [
            pl.BlockSpec((1, 2, R, ck), lambda c, t: (c, 0, 0, 0)),
            pl.BlockSpec((1, 2, F, ck), lambda c, t: (c, 0, 0, 0)),
        ] + ([] if native_rng else
             [pl.BlockSpec((2, 1, tb, ck), lambda c, t: (0, c, t, 0))])
        + [lane_spec] * 3,
        out_specs=[lane_spec] * 3,
        out_shape=[jax.ShapeDtypeStruct((C, 1, B), jnp.float32)] * 3,
        interpret=interpret,
    )
    # seed material for the in-kernel generator: the (epoch-folded) key's
    # raw words — grid position and chunk offset are folded in-kernel
    seed = jax.lax.bitcast_convert_type(
        jax.random.key_data(key).reshape(-1)[:2], jnp.int32)

    def chunk_body(carry, k):
        acc_i, acc_q, energy = carry
        s0 = k * ck
        t_k = jax.lax.dynamic_slice(t_dac, (0, 0, 0, s0), (C, 2, R, ck))
        b_k = jax.lax.dynamic_slice(basis, (0, 0, 0, s0), (C, 2, F, ck))
        nz = [] if native_rng else [sigma * jax.random.normal(
            jax.random.fold_in(key, k), (2, C, B, ck), jnp.float32)]
        acc_i, acc_q, energy = call(
            amp, cosa, sina, gs_i, gs_q, f_idx, addr, nsamp,
            s0.reshape((1,)), inv_ring.reshape((1,)),
            sigma.reshape((1,)), seed, t_k, b_k, *nz,
            acc_i, acc_q, energy)
        return (acc_i, acc_q, energy), None

    zeros = jnp.zeros((C, 1, B), jnp.float32)
    (acc_i, acc_q, energy), _ = jax.lax.scan(
        chunk_body, (zeros, zeros, zeros),
        jnp.arange(n_chunks, dtype=jnp.int32))
    return acc_i, acc_q, energy


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def fused_chunk(chunk, W: int) -> int:
    """Kernel chunk width for a requested ``resolve_chunk``: capped at W
    and rounded up to the 128-lane tile (every interp ratio divides it)."""
    return _round_up(min(chunk or W, W), 128)


def build_fused_tables(env_pads, basis, W: int, interps, ck: int,
                       rows: tuple = None):
    """Kernel constants for :func:`resolve_windows_fused` — build ONCE
    per run, outside the epoch while_loop (XLA does not hoist the
    gathers out of while bodies; rebuilding per epoch would re-pay the
    full table materialisation every resolve).

    Returns ``(t_dac, bas, w_pad)``: the DAC-resolution Toeplitz
    envelope tables ``[C, 2, R, Wp]`` with
    ``T[c, p, r, j] = env_p[c, r + j//interp]`` (hold-last-sample
    overrun via the clamped env index), the stacked carrier basis
    ``[C, 2, F, Wp]``, and the chunk-aligned window length.

    ``rows``: optional static envelope-address list
    (physics._static_meas_env_addrs) — the table then carries ONLY
    those start rows (``T[c, p, i, j] = env_p[c, rows[i] + j//interp]``,
    padded to the 8-sublane tile by repeating the last row) and the
    kernel selects by address equality instead of a [lanes, R] one-hot
    matmul.
    """
    env_i_pad, env_q_pad = env_pads
    C, Lp = env_i_pad.shape
    w_pad = _round_up(W, ck)
    if rows is not None:
        r_rows = _round_up(max(len(rows), 8), 8)
        starts = np.asarray(list(rows) + [rows[-1]]
                            * (r_rows - len(rows)))[:, None]
    else:
        r_rows = _round_up(Lp, 128)
        starts = np.arange(r_rows)[:, None]
    ts = []
    for c in range(C):
        interp = int(interps[c])
        j_env = np.arange(w_pad) // interp
        win = np.minimum(starts + j_env[None, :], Lp - 1)
        win_j = jnp.asarray(win)
        ts.append(jnp.stack([env_i_pad[c][win_j], env_q_pad[c][win_j]], 0))
    t_dac = jnp.stack(ts, 0)                        # [C, 2, R, Wp]

    bas_cos, bas_sin = basis
    pad_w = w_pad - bas_cos.shape[2]
    if pad_w > 0:
        bas_cos = jnp.pad(bas_cos, ((0, 0), (0, 0), (0, pad_w)))
        bas_sin = jnp.pad(bas_sin, ((0, 0), (0, 0), (0, pad_w)))
    bas = jnp.stack([bas_cos[:, :, :w_pad], bas_sin[:, :, :w_pad]], 1)
    return t_dac, bas, w_pad


def build_energy_tables(env_pads, addrs, W: int, interps, lane: int = 128):
    """Per-address DAC-resolution envelope ENERGY rows for the fused
    measure-in-megastep engine (``sim.interpreter`` engine ``'fused'``,
    docs/PERF.md "fused epoch") — the same clamped hold-last Toeplitz
    construction as :func:`build_fused_tables`, collapsed to |env|^2
    over the statically-enumerated envelope start addresses
    (``physics._static_meas_env_addrs``), since at sigma=0 the
    matched-filter accumulation needs only window energy (the
    carrier's unit magnitude drops out).

    Returns ``[C, R, Wp]`` float32 with
    ``E2[c, r, s] = |env[c, min(addrs[r] + s//interp_c, Lp-1)]|^2``,
    ``Wp`` = W rounded up to the ``lane`` tile; the kernel masks
    ``s < count`` and row-selects by address equality, so the whole
    demodulation is gather-free inside the span kernel body.
    """
    env_i_pad, env_q_pad = env_pads                     # [C, Lp]
    env2 = env_i_pad ** 2 + env_q_pad ** 2
    C, Lp = env2.shape
    w_pad = _round_up(W, lane)
    s = np.arange(w_pad, dtype=np.int64)
    rows = []
    for c in range(C):
        it = max(int(interps[c]), 1)
        idx = np.minimum(np.asarray(addrs, np.int64)[:, None]
                         + s[None, :] // it, Lp - 1)    # [R, Wp]
        rows.append(env2[c][jnp.asarray(idx)])
    return jnp.stack(rows, 0).astype(jnp.float32)


def resolve_windows_fused(sc: dict, fused_tables, gs_i, gs_q,
                          sigma, inv_ring, key, W: int, Lp: int,
                          *, tb: int = 256, ck: int = 256,
                          ring: bool = False, native_rng: bool = None,
                          rows: tuple = None, interpret: bool = False):
    """Matched-filter accumulators for one compacted window per (B, C).

    ``sc``: per-window scalars shaped ``[B, C, 1]`` (the compacted form
    from ``physics._window_scalars``).  ``fused_tables``: the
    :func:`build_fused_tables` output (built once per run).
    ``gs_i``/``gs_q``: ``[B, C]`` state-dependent channel response.
    ``key``: noise key for this resolve call (fold the epoch in before
    calling).  ``Lp``: the padded envelope-plane length (the addr clip
    domain).  Returns ``(acc_i, acc_q, energy)`` each ``[B, C]``.
    """
    if not _HAS_PALLAS:   # pragma: no cover - pallas ships with jax
        raise RuntimeError(
            'jax.experimental.pallas unavailable; use '
            "resolve_mode='persample'")
    t_dac, bas, w_pad = fused_tables
    B, C = sc['amp'].shape[:2]

    # lane arrays: [B, C, 1] -> [C, B], shot axis padded to the tile
    b_pad = _round_up(B, tb)
    def lanes(a, dtype):
        a = jnp.transpose(a[..., 0], (1, 0)).astype(dtype)[:, None, :]
        return jnp.pad(a, ((0, 0), (0, 0), (0, b_pad - B)))
    amp = lanes(sc['amp'], jnp.float32)
    cosa = lanes(sc['cosA'], jnp.float32)
    sina = lanes(sc['sinA'], jnp.float32)
    f_idx = lanes(sc['f_idx'], jnp.int32)
    # compact-rows mode compares raw addresses against the static row
    # values; the one-hot mode clips into the Toeplitz row range
    addr = lanes(sc['addr'] if rows is not None
                 else jnp.clip(sc['addr'], 0, Lp - 1), jnp.int32)
    nsamp = lanes(jnp.minimum(sc['n_samp'], W), jnp.int32)
    gsi = jnp.pad(jnp.transpose(gs_i, (1, 0))[:, None, :],
                  ((0, 0), (0, 0), (0, b_pad - B)))
    gsq = jnp.pad(jnp.transpose(gs_q, (1, 0))[:, None, :],
                  ((0, 0), (0, 0), (0, b_pad - B)))
    sigma = jnp.asarray(sigma, jnp.float32)
    inv_ring = jnp.asarray(inv_ring, jnp.float32)
    if native_rng is None:
        # the interpret shim stubs prng_random_bits to zeros — silent
        # no-noise; stream portable threefry noise there instead
        native_rng = not interpret
    elif native_rng and interpret:
        raise ValueError(
            'native_rng=True under interpret mode would silently disable '
            'ADC noise (the interpret shim stubs prng_random_bits to '
            'zeros) — use the streamed generator off-TPU')

    acc_i, acc_q, energy = _resolve_call(
        amp, cosa, sina, gsi, gsq, f_idx, addr, nsamp, key, sigma,
        inv_ring, t_dac, bas, tb, ck, w_pad, ring, native_rng, rows,
        interpret)
    back = lambda a: jnp.transpose(a[:, 0, :B], (1, 0))[..., None]
    return back(acc_i), back(acc_q), back(energy)
