"""Pallas TPU "megastep" execution kernel: one custom call per span.

docs/PERF.md's overhead decomposition (``t/step = a + b*B``) pins the
batched interpreter at a ≈ 5.3-5.8 ms of per-step FIXED cost — dozens
of small VPU kernels per instruction step, each round-tripping the
``[B, C]`` lane carry through HBM.  This module removes the
round-trips: the whole per-shot machine state (registers, clocks,
measurement slots, pulse params, fault word — ~1.6 KB/shot) is loaded
into VMEM ONCE, a straight-line span of K instructions is applied as an
in-kernel loop specialized on the trace-time instruction stream, and
the carry is stored once — K × (dozens of kernels + HBM traffic)
becomes one launch.  It is the TPU analogue of the reference's
``proc.sv`` stepping its instruction loop without ever leaving the
core (PAPER.md's north star).

Layering: this module owns NO instruction semantics.  The interpreter
(:mod:`..sim.interpreter`) passes its per-instruction apply functions
in as a traced ``body`` callable, so the kernel computes bit-for-bit
the same int32 arithmetic as the XLA engines by construction — and
``ops`` never imports ``sim`` (``sim.physics`` already imports
``ops``; the dependency must stay one-way).

The state keeps its host layout ``[tile_b, C, ...]`` inside the kernel
(shot tile on sublanes).  That is lane-inefficient for small core
counts on a real TPU — a lane-major ``[C, 1, B]`` relayout like
``resolve_pallas.py``'s is the obvious next lever — but it is correct
on every backend and already deletes the per-instruction fixed cost,
which is what the decomposition says dominates.

CPU fallback follows the idiom proven in ``resolve_pallas.py`` /
``waveform_pallas.py``: ``interpret=True`` runs the kernel under
``pltpu.InterpretParams()`` (see :mod:`._pallas_common`), which is how
tier-1 CPU tests exercise this code path.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ._pallas_common import HAS_PALLAS, pl, normalize_interpret

# VMEM budget for one resident state tile (input + output double-count
# is absorbed by the factor-2 headroom in _pick_tile's doubling test);
# v5e has 128 MB of VMEM per core, so 2 MB leaves the pipeliner room
_TILE_VMEM_BYTES = 2 << 20


def _per_shot_bytes(shapes) -> int:
    """Bytes one shot lane contributes across all ``[B, ...]`` leaves
    (every carry is a 4-byte int32/bool-as-int32)."""
    return sum(4 * int(np.prod(s[1:], dtype=np.int64)) for s in shapes)


def _pick_tile(B: int, per_shot: int) -> int:
    """Largest power-of-two shot tile within the VMEM budget; the whole
    batch rides one tile (grid of 1, no padding) when it fits."""
    if B * per_shot <= _TILE_VMEM_BYTES:
        return B
    tb = 1
    while 2 * tb * per_shot <= _TILE_VMEM_BYTES:
        tb *= 2
    return tb


def span_call(state: dict, consts: dict, shared: dict, body, *,
              interpret):
    """Run ``body(state, consts, shared) -> state`` as ONE pallas call
    over shot tiles of the leading batch axis.

    ``state``: the mutable machine-state dict — every leaf ``[B, ...]``
    int32 (or bool, converted to int32 at the kernel boundary and back).
    ``consts``: read-only int32 inputs tiled alongside the state (the
    injected ``meas_bits``, a block engine's lane-activity mask).
    ``shared``: small read-only arrays every tile loads whole (the
    per-core ``spc`` / ``interp`` element constants).  ``body`` must be
    a pure jnp function of those three dicts (the interpreter's
    specialized instruction loop).

    When ``B`` is not a tile multiple, the batch is padded by
    REPLICATING real shot rows (``arange(B_pad) % B`` — the same inert
    clone-lane trick the serving tier uses): execution is deterministic
    per lane, so replicas retire identically and slicing them back off
    is exact.
    """
    if not HAS_PALLAS:   # pragma: no cover - pallas ships with jax
        raise RuntimeError("jax.experimental.pallas unavailable; use "
                           "engine='generic'")
    skeys = sorted(state)
    ckeys = sorted(consts)
    hkeys = sorted(shared)
    bools = frozenset(k for k in skeys if state[k].dtype == jnp.bool_)
    B = state[skeys[0]].shape[0]
    tb = _pick_tile(B, _per_shot_bytes(
        [state[k].shape for k in skeys]
        + [consts[k].shape for k in ckeys]))
    b_pad = -(-B // tb) * tb
    if b_pad != B:
        rep = jnp.arange(b_pad, dtype=jnp.int32) % B
        pad = lambda a: jnp.take(a, rep, axis=0)
    else:
        pad = lambda a: a

    consts = {k: jnp.asarray(consts[k], jnp.int32) for k in ckeys}
    shared = {k: jnp.asarray(shared[k]) for k in hkeys}
    ins = [pad(state[k].astype(jnp.int32) if k in bools else state[k])
           for k in skeys]
    ins += [pad(consts[k]) for k in ckeys]
    ins += [shared[k] for k in hkeys]

    # the body closes over its instruction stream as numpy-derived
    # constants; pallas forbids non-scalar constants inside a kernel
    # jaxpr, so trace the body ONCE here, lift the jaxpr's consts into
    # explicit kernel inputs (bools and scalars re-packed as >=1-D
    # int32 at the boundary), and replay the jaxpr inside the kernel
    ex_args = (
        {k: jax.ShapeDtypeStruct((tb,) + tuple(state[k].shape[1:]),
                                 state[k].dtype) for k in skeys},
        {k: jax.ShapeDtypeStruct((tb,) + tuple(consts[k].shape[1:]),
                                 jnp.int32) for k in ckeys},
        {k: jax.ShapeDtypeStruct(shared[k].shape, shared[k].dtype)
         for k in hkeys})
    flat_ex, in_tree = jax.tree.flatten(ex_args)
    out_trees = []

    def body_flat(*flat):
        s, c, h = jax.tree.unflatten(in_tree, flat)
        leaves, tree = jax.tree.flatten(body(s, c, h))
        out_trees.append(tree)
        return leaves

    closed = jax.make_jaxpr(body_flat)(*flat_ex)
    out_tree = out_trees[0]
    hmeta = []
    for c in closed.consts:
        c = jnp.asarray(c)
        hb = c.dtype == jnp.bool_
        hmeta.append((hb, c.shape))
        a = c.astype(jnp.int32) if hb else c
        ins.append(a.reshape(1) if a.ndim == 0 else a)

    def tile_spec(shape):
        nz = len(shape) - 1
        return pl.BlockSpec((tb,) + tuple(shape[1:]),
                            lambda t, _nz=nz: (t,) + (0,) * _nz)

    def full_spec(shape):
        nd = len(shape)
        return pl.BlockSpec(tuple(shape),
                            lambda t, _nd=nd: (0,) * _nd)

    n_s, n_c, n_h = len(skeys), len(ckeys), len(hkeys)
    n_in = n_s + n_c + n_h + len(hmeta)

    def kernel(*refs):
        inr, outr = refs[:n_in], refs[n_in:]
        st = {k: ((r[...] != 0) if k in bools else r[...])
              for k, r in zip(skeys, inr[:n_s])}
        cc = {k: r[...] for k, r in zip(ckeys, inr[n_s:n_s + n_c])}
        hh = {k: r[...] for k, r in zip(hkeys, inr[n_s + n_c:
                                                   n_s + n_c + n_h])}
        extras = [r[...].reshape(sh).astype(jnp.bool_) if hb
                  else r[...].reshape(sh)
                  for (hb, sh), r in zip(hmeta, inr[n_s + n_c + n_h:])]
        res = jax.core.eval_jaxpr(closed.jaxpr, extras,
                                  *jax.tree.leaves((st, cc, hh)))
        st = jax.tree.unflatten(out_tree, res)
        for k, r in zip(skeys, outr):
            r[...] = st[k].astype(jnp.int32) if k in bools else st[k]

    outs = pl.pallas_call(
        kernel,
        grid=(b_pad // tb,),
        in_specs=[tile_spec(a.shape) for a in ins[:n_s + n_c]]
        + [full_spec(a.shape) for a in ins[n_s + n_c:]],
        out_specs=[tile_spec(state[k].shape) for k in skeys],
        out_shape=[jax.ShapeDtypeStruct(
            (b_pad,) + tuple(state[k].shape[1:]), jnp.int32)
            for k in skeys],
        interpret=normalize_interpret(interpret),
    )(*ins)
    return {k: ((v[:B] != 0) if k in bools else v[:B])
            for k, v in zip(skeys, outs)}
