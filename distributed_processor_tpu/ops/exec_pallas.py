"""Pallas TPU "megastep" execution kernel: one custom call per span.

docs/PERF.md's overhead decomposition (``t/step = a + b*B``) pins the
batched interpreter at a ≈ 5.3-5.8 ms of per-step FIXED cost — dozens
of small VPU kernels per instruction step, each round-tripping the
``[B, C]`` lane carry through HBM.  This module removes the
round-trips: the whole per-shot machine state (registers, clocks,
measurement slots, pulse params, fault word — ~1.6 KB/shot) is loaded
into VMEM ONCE, a straight-line span of K instructions is applied as an
in-kernel loop specialized on the trace-time instruction stream, and
the carry is stored once — K × (dozens of kernels + HBM traffic)
becomes one launch.  It is the TPU analogue of the reference's
``proc.sv`` stepping its instruction loop without ever leaving the
core (PAPER.md's north star).

Layering: this module owns NO instruction semantics.  The interpreter
(:mod:`..sim.interpreter`) passes its per-instruction apply functions
in as a traced ``body`` callable, so the kernel computes bit-for-bit
the same int32 arithmetic as the XLA engines by construction — and
``ops`` never imports ``sim`` (``sim.physics`` already imports
``ops``; the dependency must stay one-way).

The state keeps its host layout ``[tile_b, C, ...]`` inside the kernel
(shot tile on sublanes).  That is lane-inefficient for small core
counts on a real TPU — a lane-major ``[C, 1, B]`` relayout like
``resolve_pallas.py``'s is the obvious next lever — but it is correct
on every backend and already deletes the per-instruction fixed cost,
which is what the decomposition says dominates.

CPU fallback follows the idiom proven in ``resolve_pallas.py`` /
``waveform_pallas.py``: ``interpret=True`` runs the kernel under
``pltpu.InterpretParams()`` (see :mod:`._pallas_common`), which is how
tier-1 CPU tests exercise this code path.
"""

from __future__ import annotations

import collections

import numpy as np
import jax
import jax.numpy as jnp

from ._pallas_common import (HAS_PALLAS, pl, normalize_interpret,
                             BitPackPlan)

# VMEM budget for one resident state tile (input + output double-count
# is absorbed by the factor-2 headroom in _pick_tile's doubling test);
# v5e has 128 MB of VMEM per core, so 2 MB leaves the pipeliner room
_TILE_VMEM_BYTES = 2 << 20

# One carry leaf's static packing directive (see span_call's packspec):
#   trim     — kept last-axis column indices (tuple) or None; trimmed
#              columns must enter the kernel holding ``fill`` and never
#              be written by the body, so dropping them round-trips
#   fill     — the constant trimmed columns are rebuilt from in-kernel
#   widths   — per-element bit widths after trim (scalar = uniform, or
#              a flat array over the trimmed tail), None to trim only
#   sentinel — optional out-of-band value (e.g. the INT32_MAX "slot
#              never fired" marker) mapped to the width's all-ones
#              code; requires a uniform width with every REAL value
#              strictly below ``2**width - 1``
PackLeaf = collections.namedtuple('PackLeaf',
                                  ('trim', 'fill', 'widths', 'sentinel'))
PackLeaf.__new__.__defaults__ = (None,)


def _per_shot_bytes(shapes) -> int:
    """Bytes one shot lane contributes across all ``[B, ...]`` leaves
    (every carry is a 4-byte int32/bool-as-int32)."""
    return sum(4 * int(np.prod(s[1:], dtype=np.int64)) for s in shapes)


def _pick_tile(B: int, per_shot: int, reserve: int = 0) -> int:
    """Largest power-of-two shot tile within the VMEM budget (less
    ``reserve`` bytes of whole-tile shared inputs); the whole batch
    rides one tile (grid of 1, no padding) when it fits."""
    budget = max(_TILE_VMEM_BYTES - reserve, per_shot)
    if B * per_shot <= budget:
        return B
    tb = 1
    while 2 * tb * per_shot <= budget:
        tb *= 2
    return tb


def _take_cols(a, cols):
    """Static last-axis column select (stack of static slices — no
    gather, so the same code lowers inside a Pallas kernel body)."""
    if list(cols) == list(range(a.shape[-1])):
        return a
    return jnp.stack([a[..., c] for c in cols], axis=-1)


def _untrim_cols(a, cols, n, fill):
    """Inverse of :func:`_take_cols`: rebuild the full last axis,
    dropped columns refilled with their invariant constant."""
    pos = {c: j for j, c in enumerate(cols)}
    full = [a[..., pos[c]] if c in pos
            else jnp.full(a.shape[:-1], fill, jnp.int32)
            for c in range(n)]
    return jnp.stack(full, axis=-1)


class _CarryCodec:
    """Applies one side's packspec: trim invariant slots, then bit-pack
    small-width fields into shared 32-bit words (``'_pk'``).

    encode/decode are pure jnp shift/mask/stack ops, so the SAME codec
    runs on the XLA side of the kernel boundary (shrinking the
    HBM-crossing stream) and inside the kernel body (rebuilding the
    full state in VMEM).  Decode(encode(x)) == x for every value the
    spec's widths admit — the builder (`sim/interpreter.py`
    ``_carry_packspec``) derives widths from the static program and ISA
    field masks so that holds for every reachable state.
    """

    def __init__(self, specs, template, restore_bool):
        self.specs = {k: sp for k, sp in (specs or {}).items()
                      if k in template
                      and (sp.trim is not None or sp.widths is not None)}
        self.active = bool(self.specs)
        self.bools = frozenset(
            k for k in self.specs if template[k].dtype == jnp.bool_
        ) if restore_bool else frozenset()
        self.pass_keys = [k for k in template if k not in self.specs]
        self.meta = {}
        self.sent = {}
        plan_leaves = []
        self.packed = []
        for k in sorted(self.specs):
            sp = self.specs[k]
            shape = tuple(template[k].shape)
            tail = shape[1:]
            if sp.trim is not None:
                tail = tail[:-1] + (len(sp.trim),)
            self.meta[k] = (tuple(sp.trim) if sp.trim is not None
                            else None, int(sp.fill or 0),
                            shape[-1] if len(shape) > 1 else 0, tail)
            if sp.widths is not None:
                plan_leaves.append((k, tail, sp.widths))
                self.packed.append(k)
                if sp.sentinel is not None:
                    if not isinstance(sp.widths, int):
                        raise ValueError(
                            f'sentinel on {k!r} needs a uniform width')
                    self.sent[k] = (jnp.int32(sp.sentinel),
                                    jnp.int32((1 << sp.widths) - 1))
        self.trim_only = [k for k in sorted(self.specs)
                          if k not in set(self.packed)]
        self.plan = BitPackPlan(plan_leaves) if plan_leaves else None

    def encode(self, d):
        out = {k: d[k] for k in self.pass_keys}
        vals = {}
        for k in self.specs:
            a = d[k]
            if a.dtype != jnp.int32:
                a = a.astype(jnp.int32)
            cols = self.meta[k][0]
            if cols is not None:
                a = _take_cols(a, cols)
            if k in self.sent:
                val, code = self.sent[k]
                a = jnp.where(a == val, code, a)
            vals[k] = a
        for k in self.trim_only:
            out[k] = vals[k]
        if self.plan is not None:
            out['_pk'] = self.plan.pack({k: vals[k] for k in self.packed})
        return out

    def decode(self, d):
        out = {k: d[k] for k in self.pass_keys}
        vals = self.plan.unpack(d['_pk']) if self.plan is not None else {}
        for k in self.trim_only:
            vals[k] = d[k]
        for k in self.specs:
            a = vals[k]
            if k in self.sent:
                val, code = self.sent[k]
                a = jnp.where(a == code, val, a)
            cols, fill, n, _ = self.meta[k]
            if cols is not None:
                a = _untrim_cols(a, cols, n, fill)
            if k in self.bools:
                a = a != 0
            out[k] = a
        return out

    def stream_shot_bytes(self, template) -> int:
        """Modeled bytes one shot lane contributes to the packed
        stream (the packed analogue of :func:`_per_shot_bytes`)."""
        total = sum(4 * int(np.prod(template[k].shape[1:],
                                    dtype=np.int64))
                    for k in self.pass_keys)
        total += sum(4 * int(np.prod(self.meta[k][3], dtype=np.int64))
                     for k in self.trim_only)
        if self.plan is not None:
            total += 4 * self.plan.n_words
        return total


def span_stream_bytes(state, consts, packspec=None):
    """Per-shot bytes of the (state, consts) kernel streams under
    ``packspec`` (None = unpacked).  Template dicts need only
    ``.shape``/``.dtype`` leaves (``jax.ShapeDtypeStruct`` works), so
    the perf model (`tools/exec_profile.py`, bench utilization rows)
    prices the packed carry without tracing a kernel."""
    spec = packspec or {}
    sc = _CarryCodec(spec.get('state'), state, True)
    cc = _CarryCodec(spec.get('consts'), consts, False)
    return sc.stream_shot_bytes(state), cc.stream_shot_bytes(consts)


def span_call(state: dict, consts: dict, shared: dict, body, *,
              interpret, packspec=None, shot_slack: int = 0):
    """Run ``body(state, consts, shared) -> state`` as ONE pallas call
    over shot tiles, optionally with the HBM-crossing state/const
    streams bit-packed (``packspec``: ``{'state': {key: PackLeaf},
    'consts': {...}}``).  The pack/unpack shims trace INTO the kernel
    jaxpr, so the full-width state exists only in VMEM; XLA packs once
    before the call and unpacks once after.  ``shot_slack`` reserves
    extra per-shot VMEM for body scratch (the fused-measure window
    accumulators) when picking the shot tile."""
    spec = packspec or {}
    sc = _CarryCodec(spec.get('state'), state, True)
    cc = _CarryCodec(spec.get('consts'), consts, False)
    if not (sc.active or cc.active):
        return _span_call_raw(state, consts, shared, body,
                              interpret=interpret, shot_slack=shot_slack)

    def wrapped(stt, c, h):
        return sc.encode(body(sc.decode(stt), cc.decode(c), h))

    out = _span_call_raw(sc.encode(state), cc.encode(consts), shared,
                         wrapped, interpret=interpret,
                         shot_slack=shot_slack)
    return sc.decode(out)


def _span_call_raw(state: dict, consts: dict, shared: dict, body, *,
                   interpret, shot_slack: int = 0):
    """Run ``body(state, consts, shared) -> state`` as ONE pallas call
    over shot tiles of the leading batch axis.

    ``state``: the mutable machine-state dict — every leaf ``[B, ...]``
    int32 (or bool, converted to int32 at the kernel boundary and back).
    ``consts``: read-only int32 inputs tiled alongside the state (the
    injected ``meas_bits``, a block engine's lane-activity mask).
    ``shared``: small read-only arrays every tile loads whole (the
    per-core ``spc`` / ``interp`` element constants).  ``body`` must be
    a pure jnp function of those three dicts (the interpreter's
    specialized instruction loop).

    When ``B`` is not a tile multiple, the batch is padded by
    REPLICATING real shot rows (``arange(B_pad) % B`` — the same inert
    clone-lane trick the serving tier uses): execution is deterministic
    per lane, so replicas retire identically and slicing them back off
    is exact.
    """
    if not HAS_PALLAS:   # pragma: no cover - pallas ships with jax
        raise RuntimeError("jax.experimental.pallas unavailable; use "
                           "engine='generic'")
    skeys = sorted(state)
    ckeys = sorted(consts)
    hkeys = sorted(shared)
    bools = frozenset(k for k in skeys if state[k].dtype == jnp.bool_)
    B = state[skeys[0]].shape[0]
    reserve = sum(4 * int(np.prod(np.shape(shared[k]), dtype=np.int64))
                  for k in hkeys)
    tb = _pick_tile(B, shot_slack + _per_shot_bytes(
        [state[k].shape for k in skeys]
        + [consts[k].shape for k in ckeys]), reserve)
    b_pad = -(-B // tb) * tb
    if b_pad != B:
        rep = jnp.arange(b_pad, dtype=jnp.int32) % B
        pad = lambda a: jnp.take(a, rep, axis=0)
    else:
        pad = lambda a: a

    consts = {k: jnp.asarray(consts[k], jnp.int32) for k in ckeys}
    shared = {k: jnp.asarray(shared[k]) for k in hkeys}
    ins = [pad(state[k].astype(jnp.int32) if k in bools else state[k])
           for k in skeys]
    ins += [pad(consts[k]) for k in ckeys]
    ins += [shared[k] for k in hkeys]

    # the body closes over its instruction stream as numpy-derived
    # constants; pallas forbids non-scalar constants inside a kernel
    # jaxpr, so trace the body ONCE here, lift the jaxpr's consts into
    # explicit kernel inputs (bools and scalars re-packed as >=1-D
    # int32 at the boundary), and replay the jaxpr inside the kernel
    ex_args = (
        {k: jax.ShapeDtypeStruct((tb,) + tuple(state[k].shape[1:]),
                                 state[k].dtype) for k in skeys},
        {k: jax.ShapeDtypeStruct((tb,) + tuple(consts[k].shape[1:]),
                                 jnp.int32) for k in ckeys},
        {k: jax.ShapeDtypeStruct(shared[k].shape, shared[k].dtype)
         for k in hkeys})
    flat_ex, in_tree = jax.tree.flatten(ex_args)
    out_trees = []

    def body_flat(*flat):
        s, c, h = jax.tree.unflatten(in_tree, flat)
        leaves, tree = jax.tree.flatten(body(s, c, h))
        out_trees.append(tree)
        return leaves

    closed = jax.make_jaxpr(body_flat)(*flat_ex)
    out_tree = out_trees[0]
    hmeta = []
    for c in closed.consts:
        c = jnp.asarray(c)
        hb = c.dtype == jnp.bool_
        hmeta.append((hb, c.shape))
        a = c.astype(jnp.int32) if hb else c
        ins.append(a.reshape(1) if a.ndim == 0 else a)

    def tile_spec(shape):
        nz = len(shape) - 1
        return pl.BlockSpec((tb,) + tuple(shape[1:]),
                            lambda t, _nz=nz: (t,) + (0,) * _nz)

    def full_spec(shape):
        nd = len(shape)
        return pl.BlockSpec(tuple(shape),
                            lambda t, _nd=nd: (0,) * _nd)

    n_s, n_c, n_h = len(skeys), len(ckeys), len(hkeys)
    n_in = n_s + n_c + n_h + len(hmeta)

    def kernel(*refs):
        inr, outr = refs[:n_in], refs[n_in:]
        st = {k: ((r[...] != 0) if k in bools else r[...])
              for k, r in zip(skeys, inr[:n_s])}
        cc = {k: r[...] for k, r in zip(ckeys, inr[n_s:n_s + n_c])}
        hh = {k: r[...] for k, r in zip(hkeys, inr[n_s + n_c:
                                                   n_s + n_c + n_h])}
        extras = [r[...].reshape(sh).astype(jnp.bool_) if hb
                  else r[...].reshape(sh)
                  for (hb, sh), r in zip(hmeta, inr[n_s + n_c + n_h:])]
        res = jax.core.eval_jaxpr(closed.jaxpr, extras,
                                  *jax.tree.leaves((st, cc, hh)))
        st = jax.tree.unflatten(out_tree, res)
        for k, r in zip(skeys, outr):
            r[...] = st[k].astype(jnp.int32) if k in bools else st[k]

    outs = pl.pallas_call(
        kernel,
        grid=(b_pad // tb,),
        in_specs=[tile_spec(a.shape) for a in ins[:n_s + n_c]]
        + [full_spec(a.shape) for a in ins[n_s + n_c:]],
        out_specs=[tile_spec(state[k].shape) for k in skeys],
        out_shape=[jax.ShapeDtypeStruct(
            (b_pad,) + tuple(state[k].shape[1:]), jnp.int32)
            for k in skeys],
        interpret=normalize_interpret(interpret),
    )(*ins)
    return {k: ((v[:B] != 0) if k in bools else v[:B])
            for k, v in zip(skeys, outs)}
