"""Signal-generator element: DAC waveform synthesis from pulse records.

The reference keeps the DDS/envelope element out-of-repo (the separate
LBL-QubiC/gateware project; this repo only fixes its interface — reference:
hdl/pulse_iface.sv:1-6 — and its buffer word formats — reference:
python/distproc/asmparse.py:46-86).  This module implements the element
numerically so the simulation loop closes: given the interpreter's pulse
records and the assembler's envelope/frequency tables, produce the
baseband output of one element.

I/Q values are carried as a trailing axis of size 2 (``[..., 0]`` = I,
``[..., 1]`` = Q) in float32 — complex dtypes are avoided on the device
compute path (TPU backends vectorise real pairs; complex views are a
host-side convenience via :func:`iq_to_complex`).

Numeric contract (defined here, consistent with
:mod:`distributed_processor_tpu.elements`):

* carrier is phase-coherent: phase at DAC sample ``n`` (counted from the
  last phase reset) is ``2*pi*freq*n/fsamp + phase_offset`` — this is the
  invariant the compiler's virtual-z accumulation relies on;
* envelope memory holds ``interp_ratio``-decimated samples; sample ``n``
  of a pulse starting at DAC sample ``s`` reads envelope index
  ``env_start + (n - s) // interp_ratio``;
* a continuous-wave pulse (length sentinel 0xfff) holds the envelope
  sample at its start address until the next pulse on the element or the
  end of the trace;
* output = ``amp_frac * env_iq * exp(i*phase)`` with
  ``amp_frac = amp_word / (2^16 - 1)`` and envelope scaled to [-1, 1].

Everything is static-shape and vmappable over shots; the per-sample
formulation is a sum over pulse windows, which XLA fuses into a single
elementwise pipeline over the trace.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..elements import ENV_CW_SENTINEL

PHASE_BITS = 17
AMP_SCALE = float(2 ** 16 - 1)


def iq_to_complex(x):
    """Host-side view: ``[..., 2]`` I/Q pairs -> complex array."""
    x = np.asarray(x)
    return x[..., 0] + 1j * x[..., 1]


def complex_to_iq(z) -> np.ndarray:
    z = np.asarray(z)
    return np.stack([np.real(z), np.imag(z)], axis=-1).astype(np.float32)


def carrier_phase(freq_rel, n, phase0=0.0):
    """Phase-coherent carrier phase ``2*pi*freq_rel*n + phase0`` via a
    split-precision NCO: the frequency's 16-bit-exact head accumulates
    in wrapping integer arithmetic (exact mod-1, like the hardware NCO
    and the Pallas kernel), and only the tiny residual (< 2^-17
    cycles/sample) multiplies ``n`` in float32.  The naive f32
    ``2*pi*f*n`` loses ~1e-4 rad by a few hundred carrier cycles, which
    shows up as window-synthesis mismatches on long traces.
    ``n`` must be int32; broadcasting applies.
    """
    freq_rel = jnp.asarray(freq_rel, jnp.float32)
    inc_hi = jnp.round(freq_rel * 65536.0).astype(jnp.int32)
    resid = freq_rel - inc_hi.astype(jnp.float32) / 65536.0
    frac = ((inc_hi * n) & 0xffff).astype(jnp.float32) / 65536.0
    return 2 * jnp.pi * (frac + resid * n.astype(jnp.float32)) + phase0


def synthesize_element(rec: dict, env_table, spc: int, interp: int,
                       n_clks: int, elem: int = 0):
    """Render one element's baseband trace from pulse records.

    ``rec``: dict with 1-D arrays ``gtime, env, phase, freq_rel, amp, elem``
    (one entry per emitted pulse; ``freq_rel = freq/fsamp`` already
    resolved from the frequency table) and scalar ``n_pulses``.
    ``env_table``: envelope memory for this element — complex array or
    ``[n, 2]`` I/Q array (fractional, i.e. raw int15 / IQ_SCALE).
    Returns ``float32[n_clks * spc, 2]`` I/Q samples.
    """
    n_samples = n_clks * spc
    n = jnp.arange(n_samples)
    env_table = np.asarray(env_table)
    if env_table.ndim == 1:          # complex -> I/Q pairs
        env_table = complex_to_iq(env_table)
    env_len_mem = max(len(env_table), 1)
    env_table = jnp.asarray(
        np.pad(env_table.astype(np.float32), ((0, 1), (0, 0))))  # zero slot

    P = rec['gtime'].shape[0]
    valid = (jnp.arange(P) < rec['n_pulses']) & (rec['elem'] == elem)
    start = rec['gtime'] * spc                        # [P] DAC start sample
    env_word = rec['env']
    env_addr = (env_word & 0xfff) * 4
    env_nw = (env_word >> 12) & 0xfff
    is_cw = env_nw == ENV_CW_SENTINEL
    length = jnp.where(is_cw, n_samples, env_nw * 4 * interp)  # in DAC samples

    # CW pulses end at the next valid pulse on this element
    big = jnp.int32(2 ** 30)
    starts_sorted = jnp.where(valid, start, big)
    next_start = jnp.min(
        jnp.where(starts_sorted[None, :] > start[:, None],
                  starts_sorted[None, :], big), axis=1)
    end = jnp.where(is_cw, jnp.minimum(next_start, n_samples), start + length)

    amp = rec['amp'].astype(jnp.float32) / AMP_SCALE
    phase0 = 2 * jnp.pi * (rec['phase'].astype(jnp.float32)
                           / (1 << PHASE_BITS))
    freq_rel = rec['freq_rel'].astype(jnp.float32)    # freq / fsamp

    # [P, N] windowed contributions; pulses on one element never overlap
    # (the Schedule pass serialises them per dest channel), so a sum is an
    # exclusive select.
    in_win = valid[:, None] & (n[None, :] >= start[:, None]) \
        & (n[None, :] < end[:, None])
    k = (n[None, :] - start[:, None]) // interp
    env_idx = jnp.where(is_cw[:, None], env_addr[:, None],
                        env_addr[:, None] + k)
    env_idx = jnp.where(in_win, jnp.clip(env_idx, 0, env_len_mem - 1),
                        env_len_mem)                  # padded zero slot
    env_i = env_table[env_idx, 0]                     # [P, N]
    env_q = env_table[env_idx, 1]
    theta = carrier_phase(freq_rel[:, None], n[None, :].astype(jnp.int32),
                          phase0[:, None])
    c, s = jnp.cos(theta), jnp.sin(theta)
    out_i = amp[:, None] * (env_i * c - env_q * s)
    out_q = amp[:, None] * (env_i * s + env_q * c)
    zero = jnp.float32(0)
    out_i = jnp.sum(jnp.where(in_win, out_i, zero), axis=0)
    out_q = jnp.sum(jnp.where(in_win, out_q, zero), axis=0)
    return jnp.stack([out_i, out_q], axis=-1)


def resolve_pulse_freqs(rec_freq, freq_table_hz, fsamp: float):
    """Map 9-bit frequency-buffer addresses to freq/fsamp ratios."""
    table = jnp.asarray(np.asarray(freq_table_hz, np.float32) / fsamp)
    table = jnp.pad(table, (0, 1))
    idx = jnp.clip(rec_freq, 0, len(table) - 1)
    return table[idx]


def pulse_window_weights(start_clk: int, n_clks: int, spc: int,
                         freq_hz: float, fsamp: float,
                         env=None) -> np.ndarray:
    """Demodulation weights for a readout window: conj reference carrier
    (optionally envelope-weighted) over ``[start, start + n)`` clocks.

    Host-side helper producing the ``[n_samples, 2]`` (I, Q) weight matrix
    consumed by :func:`..ops.demod.demod_iq` — the numeric equivalent of
    the accumulator the reference's out-of-repo readout chain implements.
    """
    n = np.arange(start_clk * spc, (start_clk + n_clks) * spc)
    ref = np.exp(-2j * np.pi * freq_hz * n / fsamp)
    if env is not None:
        ref = ref * np.conj(np.asarray(env))
    return np.stack([np.real(ref), np.imag(ref)], axis=1).astype(np.float32)
